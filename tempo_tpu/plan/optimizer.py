"""Optimizer passes over a recorded plan.

Since round 11 the decisions below are **cost-based**
(``tempo_tpu/plan/cost.py``, ``TEMPO_TPU_COST_MODEL``): fusion,
engine hoisting and reshard placement are argmins over estimated cost
with the legacy thresholds demoted to feasibility priors.  Every
cost-decided plan stays bitwise-identical to its rule-based twin —
the argmin only runs over bitwise-equal alternatives (all join
engines; fused vs op-by-op; placed vs declarative resharding), and
the range-engine candidate set is the round-5 revalidation singleton.
Under the default priors every decision reproduces the old rules.

Four passes, in order:

1. **Fusion** — rewrite adjacent nodes onto the already-shipped fused
   kernels: ``resample(freq, 'floor')`` followed by
   ``EMA(col, exact=True)`` over that single metric column becomes one
   ``resampleEMA`` node (the PR-2 floor-resample+EMA VMEM kernel: the
   column is read once); a mesh ``asofJoin -> withRangeStats [-> EMA]``
   chain becomes one ``fused_asof_stats_ema`` node executed as a
   SINGLE jitted program (plan/fused.py) instead of one dispatch per
   op.  The resampleEMA rewrite produces exactly ``TSDF.resampleEMA``'s
   output (bit-identical to calling the fused entry point by hand; the
   unfused chain differs from it in float rounding, see MIGRATION.md).
2. **Engine hoisting** — ``pick_join_engine`` / ``pick_range_engine``
   run once at plan time; the decisions are annotated on the nodes
   (rendered by ``explain()``) and installed as hints
   (plan/hints.py) while the executor replays the node, so knob reads
   and size probes happen once per plan instead of once per call.
3. **Dead-column pruning** — when a downstream ``select`` (or a
   ``count`` terminal) bounds the live column set, source frames are
   pruned BEFORE packing: columns no op consumes and no output needs
   never reach the device.
4. **Barrier marking** — ops that force a device->host materialisation
   (``collect``, ``withLookbackFeatures``, ``fourier_transform`` on a
   resampled mesh view) are annotated explicitly so ``explain()``
   shows where a chain leaves the device.
"""

from __future__ import annotations

import logging
from typing import Dict, FrozenSet, Optional, Union

from tempo_tpu.plan import ir

logger = logging.getLogger(__name__)

#: sentinel: "every column may be needed"
ALL = None


def optimize(root: ir.Node) -> ir.Node:
    """A new, annotated (possibly rewritten) plan DAG; the logical plan
    is left untouched."""
    root = _copy(root)
    root = _fuse_sql_filters(root)
    root = _fuse_resample_ema(root)
    root = _fuse_mesh_chain(root)
    _hoist_engines(root)
    _annotate_sql_backends(root)
    root = _place_reshards(root)
    _prune_columns(root)
    _mark_barriers(root)
    root = _place_checkpoints(root)
    # stitching runs LAST so reshard and checkpoint nodes (placed
    # above) are natural stitch boundaries: a resumed chain re-runs
    # only whole post-barrier stitch groups, zero recompiles
    root = _stitch_chains(root)
    return root


def reshard_mode() -> str:
    """``TEMPO_TPU_RESHARD_PLACEMENT`` — how the planner places layout
    switches on time-sharded mesh chains: ``auto`` (default) inserts
    explicit reshard nodes around maximal series-local-preferring op
    runs, sinking/eliminating redundant switches; ``explicit`` reshards
    around every such op individually (never eliminates — the
    debugging view); ``declarative`` places no plan nodes and keeps
    each op's internal all_to_all pair (XLA plans the collectives).
    Part of the executable-cache key (executor.py): flipping the knob
    never replays a plan placed under the other mode."""
    from tempo_tpu import config

    mode = (config.get("TEMPO_TPU_RESHARD_PLACEMENT") or "auto")
    mode = mode.strip().lower()
    return mode if mode in ("auto", "declarative", "explicit") else "auto"


def _copy(root: ir.Node) -> ir.Node:
    memo: Dict[int, ir.Node] = {}

    def rec(n: ir.Node) -> ir.Node:
        if id(n) in memo:
            return memo[id(n)]
        c = ir.Node.__new__(ir.Node)
        c.op = n.op
        c.params = n.params
        c.inputs = tuple(rec(i) for i in n.inputs)
        c.payload = n.payload
        c.objs = dict(n.objs)
        c.ann = dict(n.ann)
        memo[id(n)] = c
        return c

    return rec(root)


def _rewrite(root: ir.Node, fn) -> ir.Node:
    """Bottom-up node rewriter (``fn(node) -> node``)."""
    memo: Dict[int, ir.Node] = {}

    def rec(n: ir.Node) -> ir.Node:
        if id(n) in memo:
            return memo[id(n)]
        n.inputs = tuple(rec(i) for i in n.inputs)
        out = fn(n)
        memo[id(n)] = out
        return out

    return rec(root)


def _mesh_side(node: ir.Node) -> bool:
    cur = node
    while True:
        if cur.op in ("on_mesh", "dist_source"):
            return True
        if not cur.inputs:
            return False
        cur = cur.inputs[0]


# ----------------------------------------------------------------------
# Pass 0: adjacent sql_filter fusion + backend annotation
# ----------------------------------------------------------------------

def _fuse_sql_filters(root: ir.Node) -> ir.Node:
    """``filter(p).filter(q)`` recorded as two ``sql_filter`` nodes
    collapses into ONE with the Kleene-AND predicate — bitwise-equal
    (both keep exactly the rows where p AND q is TRUE; row-wise pandas
    evaluation is pure, so evaluating q before p's row drop changes no
    surviving value) and one plane program instead of two."""
    from tempo_tpu import sql

    def fn(n: ir.Node) -> ir.Node:
        if n.op != "sql_filter" or not n.inputs:
            return n
        inner = n.inputs[0]
        if inner.op != "sql_filter":
            return n
        a, b = inner.objs.get("ast"), n.objs.get("ast")
        if a is None or b is None:
            return n
        combined = sql.And(a, b)
        fused = ir.Node("sql_filter", params=dict(
            condition=sql.unparse(combined), ast=combined.canon(),
            cols=tuple(sorted(set(inner.param("cols", ()))
                              | set(n.param("cols", ())))),
            strict=bool(inner.param("strict")) or bool(n.param("strict"))),
            inputs=inner.inputs, objs=dict(ast=combined))
        fused.ann["rewrite"] = (
            "adjacent sql_filter predicates AND-fused into one node "
            "(one mask program instead of two)")
        return fused

    return _rewrite(root, fn)


def _derived_dtypes(node: ir.Node):
    """Static column->dtype map of a node's result, walked through the
    schema-preserving ops; None when not derivable at plan time."""
    if node.op == "source":
        df = node.payload.df
        return {c: df[c].dtype for c in df.columns}
    if not node.inputs:
        return None
    if node.op in ("sql_filter", "checkpoint"):
        return _derived_dtypes(node.inputs[0])
    if node.op == "select":
        base = _derived_dtypes(node.inputs[0])
        if base is None:
            return None
        sel = node.param("cols", ())
        if "*" in sel:
            return base
        return {c: base[c] for c in sel if c in base}
    return None


def _annotate_sql_backends(root: ir.Node) -> None:
    """Annotate each ``sql_filter`` with the execution backend its
    predicate lands on (``jit-plane`` / ``host-vector``) when the input
    schema is statically derivable — rendered by ``explain()`` as
    ``eval[sql]=...`` so a predicate silently outside the plane subset
    is visible before anything runs."""
    from tempo_tpu.plan import sql_compile

    for n in root.walk():
        if n.op != "sql_filter" or "sql_eval" in n.ann:
            continue
        ast = n.objs.get("ast")
        if ast is None or not n.inputs:
            continue
        dtypes = _derived_dtypes(n.inputs[0])
        if dtypes is None:
            continue
        try:
            n.ann["sql_eval"] = sql_compile.filter_backend(ast, dtypes)
        except Exception as e:  # pragma: no cover - annotation only
            logger.debug("plan: sql backend annotation skipped (%s)", e)


# ----------------------------------------------------------------------
# Pass 1a: floor-resample + exact EMA -> the fused resampleEMA kernel
# ----------------------------------------------------------------------

def _fuse_resample_ema(root: ir.Node) -> ir.Node:
    def fn(n: ir.Node) -> ir.Node:
        if n.op != "ema" or not n.inputs:
            return n
        rs = n.inputs[0]
        if rs.op != "resample" or _mesh_side(rs):
            return n
        col = n.param("colName")
        metric = rs.param("metricCols")
        if (n.param("exact") is True
                and rs.param("func") in ("floor", "closest_lead")
                and rs.param("prefix") in (None, "")
                and not rs.param("fill")
                and metric == (col,)):
            fused = ir.Node("resample_ema", params=dict(
                freq=rs.param("freq"), colName=col,
                exp_factor=n.param("exp_factor")), inputs=rs.inputs)
            fused.ann["rewrite"] = (
                "floor-resample + exact EMA -> resampleEMA fused kernel "
                "(single column read)")
            return fused
        return n

    return _rewrite(root, fn)


# ----------------------------------------------------------------------
# Pass 1b: mesh asofJoin -> withRangeStats [-> EMA] as ONE program
# ----------------------------------------------------------------------

def _plain_numeric_mesh_source(node: ir.Node) -> bool:
    """True when the node is an on_mesh(source)/dist_source whose value
    columns all ride plain numeric device planes (the fused program has
    no host-gather / seq / resampled path)."""
    import pandas as pd

    if node.op == "dist_source":
        p = node.payload
        return (not p.resampled and p.seq is None and not p.host_cols
                and p.time_axis is None
                and all(c.ts_chunk is None and c.host_gather is None
                        for c in p.cols.values()))
    if node.op == "on_mesh" and node.inputs and node.inputs[0].op == "source":
        if node.param("time_axis") is not None:
            return False
        t = node.inputs[0].payload
        if t.sequence_col:
            return False
        structural = {t.ts_col, *t.partitionCols}
        for c in t.df.columns:
            if c in structural:
                continue
            dtype = t.df[c].dtype
            if not (pd.api.types.is_numeric_dtype(dtype)
                    and not pd.api.types.is_bool_dtype(dtype)):
                return False
        return True
    return False


def _host_value_cols(t) -> list:
    """Plane-backed value columns of a host TSDF — everything except
    ts, partitions, and the sequence column.  THE one column filter
    behind every host plane count: ``_device_plane_count``'s
    on_mesh(source) branch, ``_est_frame_bytes``'s fusion byte input,
    and the query service's runtime admission projection
    (``service/admission.py``) all call it, so the three models cannot
    drift column-accounting again."""
    return [c for c in t.df.columns
            if c not in {t.ts_col, *t.partitionCols,
                         t.sequence_col or ""}]


def _est_frame_bytes(node: ir.Node) -> int:
    """Best-effort device byte estimate of a source-adjacent node's
    packed planes (ts + value/validity per column) — the byte input of
    the fusion cost decision; 0 when not derivable at plan time."""
    try:
        frame = _source_frame(node)
        if frame is None:
            return 0
        lay = getattr(frame, "layout", None)
        if lay is not None:                     # host TSDF
            import numpy as np

            from tempo_tpu import packing

            K = lay.n_series
            L = packing.pad_length(int(np.max(lay.lengths, initial=0)))
            n_cols = max(1, len(_host_value_cols(frame)))
            return K * L * (8 + 5 * n_cols)
        return int(frame.K_dev) * int(frame.L) * (
            8 + 5 * max(1, len(frame.cols)))    # DistributedTSDF
    except Exception:  # pragma: no cover - estimate must never kill a plan
        return 0


def _fuse_mesh_chain(root: ir.Node) -> ir.Node:
    def fn(n: ir.Node) -> ir.Node:
        # the rewriter runs bottom-up: range_stats(asof_join) fuses
        # first; an ema over a fused node then folds into it
        if (n.op == "ema" and n.inputs
                and n.inputs[0].op == "fused_asof_stats_ema"
                and not n.inputs[0].param("has_ema")):
            base = n.inputs[0]
            params = dict(base.params)
            params.update(
                has_ema=True,
                e_col=n.param("colName"), e_window=n.param("window"),
                e_exp_factor=n.param("exp_factor"),
                e_exact=n.param("exact"),
                e_inclusive=n.param("inclusive_window"))
            fused = ir.Node("fused_asof_stats_ema", params=params,
                            inputs=base.inputs)
            fused.ann.update(base.ann)
            fused.ann["rewrite"] = (
                "asofJoin + withRangeStats + EMA chained into ONE "
                "jitted program (plan/fused.py)")
            if "fusion_cost" in fused.ann:
                # re-cost at the TRUE op count: the folded EMA adds a
                # dispatch + an HBM re-read to the op-by-op side while
                # the fused side stays one program, so a 2-op verdict
                # of "fuse" only strengthens — no re-gate needed (a
                # 2-op decline already stopped the base rewrite; that
                # conservatively misses chains only a 3-op costing
                # would fuse, which is bitwise-safe either way)
                from tempo_tpu.plan import cost as plan_cost

                est = sum(_est_frame_bytes(c) for c in base.inputs)
                _, costs3 = plan_cost.fusion_worthwhile(3, est)
                fused.ann["fusion_cost"] = dict(costs3,
                                                decision="fused")
            return fused
        if n.op != "range_stats" or not _mesh_side(n) or not n.inputs:
            return n
        if n.param("strategy", "exact") != "exact":
            return n
        jn = n.inputs[0]
        if jn.op != "asof_join" or len(jn.inputs) != 2:
            return n
        if not (jn.param("skipNulls") is True
                and not jn.param("maxLookback")
                and jn.param("tsPartitionVal") is None):
            return n
        left, right = jn.inputs
        if not (_plain_numeric_mesh_source(left)
                and _plain_numeric_mesh_source(right)):
            return n
        from tempo_tpu.plan import cost as plan_cost

        fusion_costs = None
        if plan_cost.enabled():
            # cost-decided fusion: one program vs the op-by-op chain —
            # both bitwise-identical (plan/fused.py pins the op
            # boundaries), so the decision is free to flip with the
            # cost inputs; the priors make fusion win (today's rule)
            est = _est_frame_bytes(left) + _est_frame_bytes(right)
            worthwhile, fusion_costs = plan_cost.fusion_worthwhile(2, est)
            if not worthwhile:
                n.ann["fusion_cost"] = dict(fusion_costs,
                                            decision="op-by-op")
                return n
        fused = ir.Node("fused_asof_stats_ema", params=dict(
            j_left_prefix=jn.param("left_prefix"),
            j_right_prefix=jn.param("right_prefix") or "right",
            s_cols=n.param("colsToSummarize"),
            s_window=n.param("rangeBackWindowSecs"),
            has_ema=False,
        ), inputs=(left, right))
        fused.ann["rewrite"] = (
            "asofJoin + withRangeStats chained into ONE jitted "
            "program (plan/fused.py)")
        if fusion_costs is not None:
            fused.ann["fusion_cost"] = dict(fusion_costs,
                                            decision="fused")
        return fused

    return _rewrite(root, fn)


# ----------------------------------------------------------------------
# Pass 2: hoist engine selection to plan time
# ----------------------------------------------------------------------

def _source_frame(node: ir.Node):
    """The concrete frame a source-adjacent node will execute over, if
    it is directly available at plan time (payload of a source, or of
    an on_mesh over a source)."""
    if node.is_source():
        return node.payload
    if node.op == "on_mesh" and node.inputs and node.inputs[0].is_source():
        return node.inputs[0].payload
    return None


def _hoist_engines(root: ir.Node) -> None:
    from tempo_tpu import resilience

    for n in root.walk():
        if n.op in ("range_stats", "fused_asof_stats_ema"):
            w = n.param("s_window" if n.op == "fused_asof_stats_ema"
                        else "rangeBackWindowSecs", 1000)
            engine, rcosts = _plan_range_engine(n, float(w))
            if engine is not None:
                n.ann["range_engine"] = engine
                n.ann.setdefault("hints", {})["range_engine"] = engine
                if rcosts is not None:
                    n.ann["cost"] = rcosts
        if n.op in ("asof_join", "fused_asof_stats_ema"):
            sides = [(_source_frame(c)) for c in n.inputs[:2]]
            if all(s is not None for s in sides):
                import numpy as np

                from tempo_tpu import packing

                lens = []
                for s in sides:
                    lay = getattr(s, "layout", None)
                    if lay is None:
                        lens = None
                        break
                    lens.append(packing.pad_length(
                        int(np.max(lay.lengths, initial=0))))
                if lens:
                    limit = resilience.max_merged_lanes()
                    est = sum(lens)
                    from tempo_tpu import profiling

                    engine = profiling.pick_join_engine(
                        est, limit, chunked_ok=True)
                    n.ann["join_engine"] = engine
                    n.ann["merged_lanes_est"] = est
                    n.ann.setdefault("hints", {})["join_engine"] = engine
                    from tempo_tpu.plan import cost as plan_cost

                    if plan_cost.enabled():
                        n.ann["cost"] = {
                            k: v for k, v in plan_cost.join_costs(
                                est, limit, True).items()
                            if v is not None}


def _plan_range_engine(node: ir.Node, w: float):
    """``(engine, costs)`` the stats op will pick over this node's
    input chain, computed once at plan time — the SAME decision
    function the eager paths run per call (rolling.plan_range_engine
    for host frames, dist's shared shard pick for mesh frames), so
    replaying the hint can never change which kernel a planned chain
    runs.  ``costs`` is the per-engine estimate dict explain() renders
    next to the choice (host chains with derivable rowbounds, cost
    model on; None otherwise — the mesh picks are per-shard and
    annotate the engine only).  ``(None, None)`` when the shard shape
    is not derivable at plan time (e.g. stats after an op that
    reshapes) — the executor then picks at run time, exactly like
    eager."""
    if not node.inputs:
        return None, None
    child = node.inputs[0]
    try:
        if _mesh_side(child):
            from tempo_tpu import dist

            if child.op == "dist_source":
                engine, _, _ = child.payload._range_engine_choice(w)
                return engine, None
            # mesh chains pick on the LEFT frame's packed geometry; a
            # join keeps it, so walk past source-preserving ops to an
            # on_mesh(source) whose geometry is derivable pre-packing
            cur = child
            while cur.op in ("asof_join", "ema"):
                cur = cur.inputs[0]
            if cur.op == "on_mesh" and cur.inputs \
                    and cur.inputs[0].op == "source":
                t = cur.inputs[0].payload
                mesh = cur.objs.get("mesh")
                if mesh is None:
                    from tempo_tpu.parallel.mesh import make_mesh

                    mesh = make_mesh()
                engine, _, _ = dist.plan_range_engine_choice(
                    t.layout, mesh, cur.param("series_axis", "series"),
                    cur.param("time_axis"), w)
                return engine, None
            return None, None
        src = _source_frame(child)
        if src is None:
            return None, None
        from tempo_tpu import rolling as frame_rolling

        # the column count enters the host pick (C*K shard elements),
        # so mirror the eager default exactly
        pick = node.param("colsToSummarize")
        cols = list(pick) if pick else src.summarizable_columns()
        if not cols:
            return None, None
        engine, rb, ts_long, _ = frame_rolling.plan_range_engine(
            src, cols, w)
        costs = None
        if rb is not None and ts_long is not None:
            from tempo_tpu.plan import cost as plan_cost

            if plan_cost.enabled():
                K, L = ts_long.shape
                costs = plan_cost.range_costs(
                    int(rb[0]) + int(rb[1]), K * L)
        return engine, costs
    except Exception as e:  # pragma: no cover - probe must never kill a plan
        logger.debug("plan: range-engine hoist skipped (%s)", e)
        return None, None


# ----------------------------------------------------------------------
# Pass 2b: plan-placed resharding on time-sharded mesh chains
# ----------------------------------------------------------------------

#: ops whose shard-local kernels want series-local FULL rows — on a
#: time-sharded mesh the eager methods bound each one with an explicit
#: ``dist.reshard_frame`` switch pair (the join keeps its in-program
#: ``_asof_a2a`` collectives: its math is float-accumulation-free and
#: therefore layout-robust bitwise).  Their
#: series-local twins are bitwise-identical (the kernels are batched
#: over the lead axis and never couple rows), so the planner may run
#: any RUN of them inside one series-local region bounded by two
#: explicit ``reshard`` nodes: the interior all_to_all pairs are
#: ELIMINATED (producer and consumer shardings already agree), and a
#: pending reshard-back SINKS through further members of the set.
_SERIES_LOCAL_OPS = ("asof_join", "range_stats", "resample", "fourier",
                     "interpolate", "calc_bars")

#: ops a pending reshard-back may NOT sink past: their time-sharded
#: and series-local executions differ in f32 association — EMA's
#: cross-shard carry stitch (parallel/halo.py) vs the plain local scan
#: bracket the same recurrence differently — so moving the layout
#: boundary across them would break the bitwise planned==eager
#: contract.  The reshard-back is placed immediately above them.
_RESHARD_SINK_BLOCKERS = ("ema",)


def _device_plane_count(node: ir.Node) -> Optional[int]:
    """Best-effort device value-plane count of a node's result frame
    (feeds the reshard nodes' modeled comm bytes in ``explain()``);
    None when not statically derivable."""
    if node.op == "dist_source":
        return len(node.payload.cols)
    if node.op == "source":
        # bare host frame (pre-mesh): the same value planes it packs —
        # a derivable LEAF, so downstream op nodes of pure host chains
        # derive their counts too (runtime admission projects whole
        # host chains through this model, not just mesh chains)
        return len(_host_value_cols(node.payload))
    if node.op == "on_mesh" and node.inputs \
            and node.inputs[0].op == "source":
        return len(_host_value_cols(node.inputs[0].payload))
    if not node.inputs:
        return None
    base = _device_plane_count(node.inputs[0])
    if base is None:
        return None
    if node.op in ("reshard", "checkpoint"):
        return base
    if node.op == "asof_join":
        right = _device_plane_count(node.inputs[1])
        if right is None:
            return None
        return base + right + 3          # + the joined-ts chunk planes
    if node.op == "range_stats":
        pick = node.param("colsToSummarize")
        import tempo_tpu.packing as packing

        n_sum = len(pick) if pick else base
        return base + len(packing.RANGE_STATS) * n_sum
    if node.op == "ema":
        return base + 1
    if node.op in ("resample",):
        pick = node.param("metricCols")
        return len(pick) if pick else base
    if node.op == "calc_bars":
        # four prefixed planes per metric (open/low/high/close); the
        # optional zero-fill interpolate adds no columns
        pick = node.param("metricCols")
        return 4 * (len(pick) if pick else base)
    return None


def _reshard_node(child: ir.Node, target: str) -> ir.Node:
    node = ir.Node("reshard", params=dict(target=target), inputs=(child,))
    node.ann["reshard"] = "placed"
    planes = _device_plane_count(child)
    src = next(iter(child.sources()), None)
    if planes is not None and src is not None \
            and src.op == "dist_source":
        from tempo_tpu import dist

        p = src.payload
        node.ann["comm_bytes_model"] = dist.relayout_comm_bytes(
            p.K_dev, p.L, planes,
            p.n_series_shards * max(p.n_time, 1),
            has_seq=p.seq is not None)
    elif planes is not None and src is not None and src.op == "source":
        mesh_node = child
        while mesh_node.op != "on_mesh" and mesh_node.inputs:
            mesh_node = mesh_node.inputs[0]
        mesh = mesh_node.objs.get("mesh") if mesh_node.op == "on_mesh" \
            else None
        if mesh is not None:
            from tempo_tpu import dist

            K_dev, L, n_s, n_t = dist._mesh_packed_geometry(
                src.payload.layout, mesh,
                mesh_node.param("series_axis", "series"),
                mesh_node.param("time_axis"))
            node.ann["comm_bytes_model"] = dist.relayout_comm_bytes(
                K_dev, L, planes, n_s * n_t,
                has_seq=bool(src.payload.sequence_col))
    return node


def _place_reshards(root: ir.Node) -> ir.Node:
    """Insert explicit ``reshard`` plan nodes on time-sharded mesh
    chains (see :data:`_SERIES_LOCAL_OPS`): one switch to the
    series-local layout at the head of each maximal series-local run,
    one switch back where a sink-blocked op (or ``explicit`` mode)
    requires the time-sharded layout again; the trailing switch is
    eliminated outright when the consumer is ``collect``/``count``
    (materialisation reads any layout).  ``declarative`` mode is a
    no-op: every op keeps its internal all_to_all pair.

    In ``auto`` mode the placement is **cost-decided** (round 11):
    the placed shape's modeled comm bytes + per-node dispatch cost is
    compared against the internal all_to_all pairs the ops would run
    declaratively, and the whole plan keeps whichever is cheaper —
    both shapes are bitwise-identical (the round-10 elimination
    contract), so the decision is free to flip with the cost inputs.
    Under the default priors placement wins whenever it eliminates a
    switch, which is today's rule."""
    mode = reshard_mode()
    if mode == "declarative":
        return root
    from tempo_tpu.plan import cost as plan_cost

    if mode == "auto" and plan_cost.enabled():
        trial = _place_reshards_impl(_copy(root), mode)
        stats = _reshard_stats(trial)
        if stats["n_placed"] == 0:
            return trial               # no time-sharded chain: nothing
        #                                to decide, no annotation noise
        place, costs = plan_cost.reshard_decision(
            stats["n_placed"], stats["placed_bytes"],
            stats["n_internal"], stats["internal_bytes"])
        if not place:
            root.ann["reshard_cost"] = dict(costs,
                                            decision="declarative")
            return root
        trial.ann["reshard_cost"] = dict(costs, decision="placed")
        return trial
    return _place_reshards_impl(root, mode)


def _reshard_stats(placed: ir.Node) -> Dict[str, object]:
    """Switch counts and modeled bytes of a placed plan, feeding the
    cost decision above.  Internal pairs are modeled as 2 switches of
    the same frame geometry per series-local member (the eager
    time-sharded ops bracket themselves with ``dist.reshard_frame``);
    bytes fall back to None (count-only decision) when any placed node
    lacks a comm model."""
    n_placed = 0
    placed_bytes: Optional[int] = 0
    members = 0
    for n in placed.walk():
        if n.op == "reshard" and n.ann.get("reshard") == "placed":
            n_placed += 1
            b = n.ann.get("comm_bytes_model")
            if b is None or placed_bytes is None:
                placed_bytes = None
            else:
                placed_bytes += int(b)
        elif n.op in _SERIES_LOCAL_OPS and (
                "reshard_eliminated" in n.ann
                or (n.inputs and n.inputs[0].op == "reshard")):
            members += 1
    n_internal = 2 * members
    internal_bytes = None
    if placed_bytes is not None and n_placed:
        internal_bytes = n_internal * (placed_bytes // n_placed)
    return {"n_placed": n_placed, "placed_bytes": placed_bytes,
            "n_internal": n_internal, "internal_bytes": internal_bytes}


def _place_reshards_impl(root: ir.Node, mode: str) -> ir.Node:
    layout: Dict[int, str] = {}        # id(node) -> "time" | "joint"

    def fn(n: ir.Node) -> ir.Node:
        if n.op == "dist_source":
            p = n.payload
            if p.time_axis is not None:
                layout[id(n)] = "time"
            elif isinstance(p.series_axis, tuple):
                layout[id(n)] = "joint"
            return n
        if n.op == "on_mesh":
            if n.param("time_axis") is not None:
                layout[id(n)] = "time"
            return n
        if not n.inputs:
            return n
        in_layout = layout.get(id(n.inputs[0]))
        if in_layout is None:
            return n
        series_local = n.op in _SERIES_LOCAL_OPS
        if n.op == "range_stats" \
                and n.param("strategy", "exact") != "exact":
            # halo-strategy stats are DEFINED by the time-sharded
            # layout (windows truncate at the halo, with an audit):
            # resharding them series-local would silently compute the
            # exact form instead — treat them as a boundary so the
            # reshard-back lands above and eager/planned run the same
            # halo program
            series_local = False
        if series_local:
            if in_layout == "time":
                r = _reshard_node(n.inputs[0], "series_local")
                layout[id(r)] = "joint"
                n.inputs = (r,) + n.inputs[1:]
            else:
                n.ann["reshard_eliminated"] = (
                    "producer already series-local — shardings agree, "
                    "the op's all_to_all pair is elided")
            if n.op == "interpolate":
                # interpolate's result is a NEW dense series-local
                # frame in eager too (dist.py): nothing downstream
                # ever reshards it back
                return n
            out = n
            layout[id(out)] = "joint"
            if mode == "explicit":
                out = _reshard_node(n, "time_sharded")
                layout[id(out)] = "time"
            return out
        if in_layout == "joint":
            if n.op in ("collect", "count"):
                n.ann["reshard_eliminated"] = (
                    "trailing reshard elided — collect() materialises "
                    "from any layout")
                layout[id(n)] = "joint"
                return n
            r = _reshard_node(n.inputs[0], "time_sharded")
            layout[id(r)] = "time"
            n.inputs = (r,) + n.inputs[1:]
            if n.op in _RESHARD_SINK_BLOCKERS:
                n.ann["reshard_note"] = (
                    "reshard-back not sunk past EMA: the time-sharded "
                    "carry stitch and the series-local scan differ in "
                    "f32 association (bitwise contract)")
            layout[id(n)] = "time"
            return n
        layout[id(n)] = in_layout
        return n

    return _rewrite(root, fn)


# ----------------------------------------------------------------------
# Pass 3: dead-column pruning before packing
# ----------------------------------------------------------------------

Wanted = Union[None, FrozenSet[str]]  # None == ALL


def _required_inputs(node: ir.Node, wanted: Wanted):
    """Per-input wanted column sets for this node, given what its own
    output must provide."""
    n_in = len(node.inputs)
    if node.op == "count":
        return [frozenset()] * n_in
    if node.op in ("collect", "on_mesh", "source", "dist_source",
                   "reshard", "checkpoint"):
        return [wanted] * n_in
    if node.op == "select":
        sel = node.param("cols", ())
        if "*" in sel:
            return [ALL]
        return [frozenset(sel)]
    if node.op == "sql_project":
        # the node evaluates EVERY projection (its aliases are its
        # output schema), so its input always needs the full resolved
        # ref set — already a strict subset of upstream for any
        # projection that drops columns
        return [frozenset(node.param("cols", ()))]
    if node.op == "sql_filter":
        refs = frozenset(node.param("cols", ()))
        return [ALL if wanted is ALL else frozenset(wanted) | refs]
    if node.op == "ema":
        if wanted is ALL:
            return [ALL]
        return [frozenset(wanted - {f"EMA_{node.param('colName')}"})
                | {node.param("colName")}]
    if node.op == "range_stats":
        pick = node.param("colsToSummarize")
        if wanted is ALL or pick is None:
            return [ALL]
        stats_out = {f"{s}_{c}" for c in pick
                     for s in ir._range_stats_names()}
        return [frozenset(wanted - stats_out) | set(pick)]
    if node.op == "resample":
        pick = node.param("metricCols")
        return [frozenset(pick) if pick else ALL]
    if node.op == "resample_ema":
        return [frozenset({node.param("colName")})]
    if node.op in ("interpolate", "interpolate_resampled"):
        pick = node.param("target_cols")
        return [frozenset(pick) if pick else ALL]
    if node.op == "fourier":
        return [frozenset({node.param("valueCol")})]
    if node.op in ("asof_join", "fused_asof_stats_ema"):
        if node.op == "fused_asof_stats_ema":
            pick = node.param("s_cols")
            extra = set(pick or ())
            if node.param("has_ema"):
                extra.add(node.param("e_col"))
            if wanted is not ALL:
                wanted = frozenset(wanted) | extra
            elif pick is None:
                wanted = ALL
            lp, rp = node.param("j_left_prefix"), node.param("j_right_prefix")
        else:
            lp = node.param("left_prefix")
            rp = node.param("right_prefix") or "right"
        if wanted is ALL:
            return [ALL, ALL]
        l_cols = ir.output_columns(node.inputs[0])
        r_cols = ir.output_columns(node.inputs[1])
        if l_cols is None or r_cols is None:
            return [ALL, ALL]
        ren = (lambda c: f"{lp}_{c}") if lp else (lambda c: c)
        lw = {c for c in l_cols if ren(c) in wanted}
        rw = {c for c in r_cols if f"{rp}_{c}" in wanted}
        return [frozenset(lw), frozenset(rw)]
    # unknown op (with_column, lookback_features, ...): conservative
    return [ALL] * n_in


def _prune_columns(root: ir.Node) -> None:
    wanted: Dict[int, Wanted] = {id(root): ALL}
    order = list(root.walk())
    for n in reversed(order):          # root first (reverse post-order)
        w = wanted.get(id(n), ALL)
        reqs = _required_inputs(n, w)
        for child, req in zip(n.inputs, reqs):
            prev = wanted.get(id(child), "unset")
            if prev == "unset":
                wanted[id(child)] = req
            elif prev is ALL or req is ALL:
                wanted[id(child)] = ALL
            else:
                wanted[id(child)] = frozenset(prev) | frozenset(req)
    for n in order:
        if n.op != "source":
            continue
        w = wanted.get(id(n), ALL)
        if w is ALL:
            continue
        t = n.payload
        structural = {t.ts_col, *t.partitionCols}
        if t.sequence_col:
            structural.add(t.sequence_col)
        keep = [c for c in t.df.columns if c in structural or c in w]
        if len(keep) < len(t.df.columns):
            n.ann["prune_to"] = tuple(keep)
            n.ann["pruned"] = tuple(c for c in t.df.columns
                                    if c not in keep)


# ----------------------------------------------------------------------
# Pass 5: plan-integrated checkpoint barriers (TEMPO_TPU_CKPT_PLACEMENT)
# ----------------------------------------------------------------------

#: frame-producing ops after which a checkpoint barrier may be placed —
#: each materialises a new device/host frame, so the boundary above it
#: is a legal resume point (the saved frame IS the subtree's value)
_CKPT_BOUNDARY_OPS = ("asof_join", "range_stats", "ema", "resample",
                      "resample_ema", "interpolate", "fourier",
                      "fused_asof_stats_ema", "calc_bars")


def _est_ckpt_bytes(node: ir.Node) -> Optional[int]:
    """Estimated on-disk bytes of checkpointing this node's result
    frame (ts plane + mask + value/validity per plane), rendered by
    ``explain()`` next to each placed barrier; None when the geometry
    is not derivable at plan time."""
    try:
        src = next(iter(node.sources()), None)
        if src is None:
            return None
        planes = _device_plane_count(node)
        if planes is None:
            planes = 1
        if src.op == "dist_source":
            K, L = int(src.payload.K_dev), int(src.payload.L)
        else:
            import numpy as np

            from tempo_tpu import packing

            lay = src.payload.layout
            K = lay.n_series
            L = packing.pad_length(int(np.max(lay.lengths, initial=0)))
        return int(K * L * (8 + 1 + planes * 5))
    except Exception:  # pragma: no cover - estimate must never kill a plan
        return None


def _place_checkpoints(root: ir.Node) -> ir.Node:
    """Insert first-class ``checkpoint`` plan nodes when a
    :func:`tempo_tpu.plan.checkpoints.checkpointed` context is active
    (and ``TEMPO_TPU_CKPT_PLACEMENT`` is not ``off``): one barrier
    after every ``every``-th materialization boundary
    (:data:`_CKPT_BOUNDARY_OPS`), one before each placed reshard's
    layout switch (the canonical-layout frame is what gets saved), and
    always one under the terminal materialisation (``collect`` /
    ``count`` / host barriers) so a completed chain's final frame is a
    resume point.  Interiors of series-local reshard regions are never
    checkpointed — their joint layout is not restorable through
    ``checkpoint.load``'s canonical re-placement path.  Uncacheable
    plans (opaque params) are left barrier-free: their signatures are
    not stable across submissions, so stamped barriers could never be
    matched on resume."""
    from tempo_tpu.plan import checkpoints as plan_ckpt

    spec = plan_ckpt.active()
    if spec is None or plan_ckpt.placement_mode() == "off" \
            or root.uncacheable():
        return root
    every = max(1, int(spec.every))
    layout: Dict[int, Optional[str]] = {}
    state = {"ops": 0, "steps": 0}

    def wrap(child: ir.Node) -> ir.Node:
        state["steps"] += 1
        node = ir.Node("checkpoint", params=dict(step=state["steps"]),
                       inputs=(child,))
        node.ann["ckpt"] = (
            "plan barrier: signed step manifest (plan signature + "
            "predecessor CRC), resume point")
        est = _est_ckpt_bytes(child)
        if est:
            node.ann["ckpt_bytes_est"] = est
        layout[id(node)] = layout.get(id(child))
        return node

    def fn(n: ir.Node) -> ir.Node:
        # layout tracking mirrors _place_reshards_impl: barriers must
        # only land on canonically-laid frames
        if n.op == "dist_source":
            p = n.payload
            layout[id(n)] = ("time" if p.time_axis is not None else
                             "joint" if isinstance(p.series_axis, tuple)
                             else None)
            return n
        if n.op == "on_mesh":
            layout[id(n)] = ("time" if n.param("time_axis") is not None
                             else None)
            return n
        if not n.inputs:
            return n
        if n.op == "reshard":
            child = n.inputs[0]
            if n.param("target") == "series_local" \
                    and child.op in _CKPT_BOUNDARY_OPS \
                    and layout.get(id(child)) != "joint":
                n.inputs = (wrap(child),) + n.inputs[1:]
            layout[id(n)] = ("joint" if n.param("target") == "series_local"
                             else "time")
            return n
        layout[id(n)] = layout.get(id(n.inputs[0]))
        if n.op in _CKPT_BOUNDARY_OPS and layout.get(id(n)) != "joint":
            state["ops"] += 1
            if state["ops"] % every == 0:
                return wrap(n)
            return n
        if n.op in ("collect", "count", "lookback_features"):
            child = n.inputs[0]
            if child.op in _CKPT_BOUNDARY_OPS \
                    and layout.get(id(child)) != "joint":
                n.inputs = (wrap(child),) + n.inputs[1:]
            return n
        return n

    return _rewrite(root, fn)


# ----------------------------------------------------------------------
# Pass 4: explicit materialisation barriers
# ----------------------------------------------------------------------

def _mark_barriers(root: ir.Node) -> None:
    for n in root.walk():
        if n.op == "collect":
            n.ann["barrier"] = "device->host materialisation"
        elif n.op == "lookback_features":
            n.ann["barrier"] = ("host materialisation: collect_list "
                                "semantics run on host (dist.py fallback)")
        elif n.op == "fourier" and any(
                c.op in ("resample", "interpolate") for c in n.walk()):
            n.ann["barrier"] = ("host materialisation: fourier on a "
                                "resampled (bucket-head) view collects "
                                "to host (dist.py fallback)")


# ----------------------------------------------------------------------
# Pass 6: whole-chain program stitching (TEMPO_TPU_STITCH_MAX_OPS)
# ----------------------------------------------------------------------

def _stitch_max_ops() -> int:
    """``TEMPO_TPU_STITCH_MAX_OPS`` — longest run of adjacent
    series-local planned ops collapsed into one ``stitched`` node
    (plan/stitch.py); < 2 disables the pass.  Env knob wins, then the
    tuned profile's winner (tune/space.py ``stitched_chain`` class),
    then the built-in 8."""
    from tempo_tpu import config, tune

    n = config.get_int("TEMPO_TPU_STITCH_MAX_OPS")
    if n is None:
        tuned = tune.knob_value("TEMPO_TPU_STITCH_MAX_OPS",
                                "stitched_chain")
        n = 8 if tuned is None else int(tuned)
    return n


def _stitch_chains(root: ir.Node) -> ir.Node:
    """Collapse maximal single-consumer runs of adjacent stitchable
    mesh ops into ONE ``stitched`` node executed as a single jitted
    program (plan/stitch.py).  Runs after every other pass, so fused
    nodes, placed reshards and checkpoint barriers all act as stitch
    boundaries — a mid-chain barrier splits the chain into two stitch
    groups and resume replays only the downstream one.  Top-down so a
    chain is grouped from its TOPMOST member; interior nodes are
    consumed by the group and never visited."""
    from tempo_tpu.plan import cost as plan_cost
    from tempo_tpu.plan.stitch import STITCHABLE_OPS

    max_ops = _stitch_max_ops()
    if max_ops < 2:
        return root
    counts: Dict[int, int] = {}
    for n in root.walk():
        for c in n.inputs:
            counts[id(c)] = counts.get(id(c), 0) + 1
    memo: Dict[int, ir.Node] = {}

    def rec(n: ir.Node) -> ir.Node:
        if id(n) in memo:
            return memo[id(n)]
        out = n
        if n.op in STITCHABLE_OPS and _mesh_side(n):
            chain = [n]
            cur = n
            while (cur.inputs and cur.inputs[0].op in STITCHABLE_OPS
                   and counts.get(id(cur.inputs[0]), 0) == 1
                   and len(chain) < max_ops):
                cur = cur.inputs[0]
                chain.append(cur)
            if len(chain) >= 2:
                bottom = chain[-1]
                stitch_costs = None
                worthwhile = True
                if plan_cost.enabled():
                    # cost-decided stitching: one program vs the
                    # op-by-op chain — both bitwise-identical
                    # (plan/stitch.py pins every op boundary with
                    # optimization_barrier), so the decision is free
                    est = (_est_frame_bytes(bottom.inputs[0])
                           if bottom.inputs else 0)
                    worthwhile, stitch_costs = \
                        plan_cost.stitch_worthwhile(len(chain), est)
                if worthwhile:
                    stitched = ir.Node("stitched", params=dict(
                        stages=tuple((c.op, c.params)
                                     for c in reversed(chain)),
                        n_ops=len(chain)), inputs=bottom.inputs)
                    stitched.ann["rewrite"] = (
                        f"{len(chain)} adjacent series-local ops "
                        f"stitched into ONE jitted program "
                        f"(plan/stitch.py)")
                    # reshard decisions recorded on swallowed members
                    # (pass 2b ran first) must stay visible in the
                    # walked plan and in explain()
                    for c in reversed(chain):
                        for key in ("reshard_eliminated",
                                    "reshard_note"):
                            if key in c.ann:
                                note = f"{c.op}: {c.ann[key]}"
                                prev = stitched.ann.get(key)
                                stitched.ann[key] = (
                                    note if prev is None
                                    else f"{prev}; {note}")
                    if stitch_costs is not None:
                        stitched.ann["stitch_cost"] = dict(
                            stitch_costs, decision="stitched")
                    out = stitched
                else:
                    n.ann["stitch_cost"] = dict(stitch_costs,
                                                decision="op-by-op")
        out.inputs = tuple(rec(c) for c in out.inputs)
        memo[id(n)] = out
        return out

    return rec(root)
