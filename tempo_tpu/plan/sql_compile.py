"""Compile the SQL surface into plan IR (the query service's front
door).

The reference exposes text queries through ``selectExpr`` / string
predicates (TSDF.scala:226-238) and, in Spark proper, full statements;
until this module, tempo-tpu evaluated all of it on the host pandas
engine — a materialization barrier that dropped text queries off the
device path entirely while the whole backend (cost-based optimizer,
whole-chain stitching, executable cache, admission control) sat behind
the Python method-chain API.

Lowering contract (BUILDING.md "The SQL lowering contract"):

* :func:`lower_select_exprs` / :func:`lower_filter` turn the parsed
  ``tempo_tpu.sql`` expression ASTs into the node parts of the
  ``sql_project`` / ``sql_filter`` IR ops.  Column references are
  resolved at compile time through :func:`sql.resolve_column` — the
  SAME ladder host evaluation uses — so pruning and execution can never
  disagree about which column an expression reads.  The canonical AST
  (``Expr.canon()``) rides in the node params: it IS the plan
  signature, so two spellings of the same query share one cached
  executable while ``x + 2`` and ``x + 2.0`` never do.
* :func:`compile_statement` parses a full ``SELECT`` statement
  (projections, ``ASOF JOIN``, ``WHERE``, ``GROUP BY time_bucket``)
  and lowers it onto the SAME planned ops method chains record —
  ``asof_join`` onto the join planner, time buckets onto the
  bucket-stats ``resample`` kernels — plus ``sql_project`` /
  ``sql_filter`` for projection arithmetic and predicates.  The plan
  root carries ``_origin='sql'`` so SQL-born plans get distinct cache
  signatures from their method-chain twins (MIGRATION v0.18).
* Predicate execution prefers the jitted *plane* backend
  (:func:`plane_program`): numeric/timestamp predicates evaluate as one
  XLA program over (values, validity) planes with SQL three-valued
  logic encoded in the validity lane.  Anything outside that subset
  (string ops, CASE, casts, nullable extension dtypes) evaluates
  through the shared vectorized AST — still inside the plan, still
  bitwise-identical to the host oracle.  ``explain()`` shows which
  backend a filter landed on (``eval[sql]=...``).

The host pandas engine remains the bitwise oracle and the fallback for
the genuinely unsupported tail (pandas-eval/query syntax in
``selectExpr``/``filter``); strict mode (``strict=True`` /
``TEMPO_TPU_SQL_STRICT=1``) turns that tail into a named
:class:`sql.StrictSqlFallback` error instead of a silent engine switch.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from tempo_tpu import sql
from tempo_tpu.plan import ir

logger = logging.getLogger(__name__)

__all__ = ["lower_select_exprs", "lower_filter", "compile_statement",
           "run_statement", "run_project", "run_filter",
           "filter_backend"]


# ----------------------------------------------------------------------
# Expression lowering: selectExpr / filter -> sql_project / sql_filter
# ----------------------------------------------------------------------

def _resolve(ast: sql.Expr, columns) -> sql.Expr:
    """Compile-time column resolution through the shared ladder; names
    with no match stay as written (evaluation raises the same 'column
    not found' the eager path would)."""
    if columns is None:
        return ast
    return sql.map_columns(
        ast, lambda n: sql.resolve_column(n, list(columns)) or n)


def lower_select_exprs(exprs, columns=None) -> Tuple[Dict, Dict]:
    """Parse + lower ``selectExpr`` strings; returns the ``(params,
    objs)`` of a ``sql_project`` node.  Raises :class:`sql.SqlError`
    when any expression is outside the SQL grammar (the caller decides
    fallback vs strict)."""
    raws, aliases, canons, projs = [], [], [], []
    refs = set()
    for raw in exprs:
        alias, body = sql.split_projection(raw)
        ast = _resolve(sql.parse(body), columns)
        raws.append(raw)
        aliases.append(alias)
        canons.append(ast.canon())
        projs.append((alias, ast))
        refs |= sql.column_refs(ast)
    params = dict(exprs=tuple(raws), aliases=tuple(aliases),
                  asts=tuple(canons), cols=tuple(sorted(refs)))
    return params, dict(projs=tuple(projs))


def lower_filter(condition: str, columns=None) -> Tuple[Dict, Dict]:
    """Parse + lower a string predicate; returns the ``(params, objs)``
    of a ``sql_filter`` node.  Raises :class:`sql.SqlError` for
    non-SQL predicates (pandas ``query`` syntax)."""
    ast = _resolve(sql.parse(condition), columns)
    params = dict(condition=condition, ast=ast.canon(),
                  cols=tuple(sorted(sql.column_refs(ast))))
    return params, dict(ast=ast)


# ----------------------------------------------------------------------
# Execution: the two sql ops' evaluators (called by plan/executor.py)
# ----------------------------------------------------------------------

def run_project(frame, node: ir.Node):
    """Evaluate a ``sql_project`` node over a host TSDF — the pre-parsed
    Exprs evaluate through the SAME ``Expr.__call__`` bodies as
    ``sql.select_exprs``, so planned output is bitwise the eager
    output with zero re-parsing per run."""
    df = frame.df
    env = {c: df[c] for c in df.columns}
    out = {}
    for alias, ast in node.objs["projs"]:
        val = ast(env)
        if isinstance(val, pd.Series):
            val = val.reset_index(drop=True)
            val.index = df.index
        else:
            val = pd.Series([val] * len(df), index=df.index)
        out[alias] = val
    return frame._with_df(pd.DataFrame(out, index=df.index))


def run_filter(frame, node: ir.Node):
    """Evaluate a ``sql_filter`` node over a host TSDF: the jitted
    plane backend when the predicate compiles to it, else the shared
    vectorized AST — both produce the exact ``filter_mask`` row set
    (TRUE rows only)."""
    df = frame.df
    ast = node.objs["ast"]
    mask = _plane_mask(ast, df)
    if mask is not None:
        node.ann["sql_eval"] = "jit-plane"
    else:
        node.ann["sql_eval"] = "host-vector"
        v = sql.evaluate(ast, df)
        if not isinstance(v, pd.Series):
            v = pd.Series([v] * len(df), index=df.index)
        mask = v.astype("boolean").fillna(False).astype(bool)
    return frame._with_df(df[mask])


# ----------------------------------------------------------------------
# The jitted plane backend: numeric/timestamp predicates as one XLA
# program over (values, validity) planes
# ----------------------------------------------------------------------
#
# SQL three-valued logic is encoded in a validity lane: every
# sub-expression evaluates to (value, valid) with the invariant that
# boolean values are False wherever invalid (canonical NULL), which
# makes Kleene AND/OR plain bitwise ops plus a validity formula.  The
# final mask is value & valid — exactly filter_mask's "TRUE rows only".

class _Unsupported(Exception):
    pass


_AGG_FUNCS = {"mean": "mean", "avg": "mean", "min": "min", "max": "max",
              "first": "floor", "last": "ceil"}

_PLANE_CACHE: Dict[tuple, tuple] = {}


def _col_kinds(ast: sql.Expr, dtypes) -> Dict[str, str]:
    """dtype-kind map for the predicate's column refs; raises
    _Unsupported for extension dtypes / unsupported kinds."""
    kinds = {}
    for name in sql.column_refs(ast):
        if name not in dtypes:
            raise _Unsupported(name)
        dt = dtypes[name]
        if not isinstance(dt, np.dtype) or dt.kind not in "iufMb":
            raise _Unsupported(str(dt))
        kinds[name] = dt.kind
    return kinds


def _emit(e: sql.Expr, kinds: Dict[str, str]):
    """Build one plane evaluator: returns (tag, fn) where tag is
    'num:<kind>' / 'bool' / 'null' and fn(cols) -> (value, valid) jnp
    arrays (or scalars for literals)."""
    import jax.numpy as jnp

    if isinstance(e, sql.Col):
        k = kinds[e.name]
        name = e.name
        if k == "b":
            return "bool", lambda cols: cols[name]
        tag = "num:M" if k == "M" else ("num:f" if k == "f" else "num:i")
        return tag, lambda cols: cols[name]
    if isinstance(e, sql.Lit):
        v = e.value
        if v is None:
            return "null", lambda cols: (0.0, False)
        if isinstance(v, bool):
            return "bool", lambda cols: (v, True)
        if isinstance(v, int):
            return "num:i", lambda cols: (np.int64(v), True)
        if isinstance(v, float):
            return "num:f", lambda cols: (np.float64(v), True)
        # string literals only survive next to a timestamp operand
        # (_promote_ts rewrites them); bare ones are unsupported here
        raise _Unsupported("string literal")
    if isinstance(e, sql.Neg):
        tag, f = _emit(e.inner, kinds)
        if not tag.startswith("num:") or tag == "num:M":
            raise _Unsupported("negate non-numeric")

        def neg(cols, f=f):
            v, ok = f(cols)
            return -v, ok
        return tag, neg
    if isinstance(e, sql.Arith):
        if e.op == "%":
            # truncated-remainder corner cases (int zero divisors)
            # diverge between numpy and XLA — host-vector handles them
            raise _Unsupported("% stays on the host vector path")
        lt, lf = _emit(e.left, kinds)
        rt, rf = _emit(e.right, kinds)
        for t in (lt, rt):
            if t == "num:M" or t == "bool":
                raise _Unsupported("arith on non-numeric")
            if t == "null":
                pass
            elif not t.startswith("num:"):
                raise _Unsupported(t)
        int_out = lt == "num:i" and rt == "num:i" and e.op != "/"
        op = e.op

        def arith(cols, lf=lf, rf=rf, op=op, int_out=int_out):
            a, av = lf(cols)
            b, bv = rf(cols)
            if op == "/":
                a = jnp.asarray(a, jnp.float64)
                b = jnp.asarray(b, jnp.float64)
            r = {"+": lambda: a + b, "-": lambda: a - b,
                 "*": lambda: a * b, "/": lambda: a / b}[op]()
            ok = jnp.logical_and(av, bv)
            if not int_out:
                ok = jnp.logical_and(ok, ~jnp.isnan(
                    jnp.asarray(r, jnp.float64)))
            return r, ok
        return ("num:i" if int_out else "num:f"), arith
    if isinstance(e, sql.Cmp):
        return "bool", _emit_cmp(e.op, e.left, e.right, kinds)
    if isinstance(e, sql.Between):
        lo = _emit_cmp(">=", e.inner, e.lo, kinds)
        hi = _emit_cmp("<=", e.inner, e.hi, kinds)
        return "bool", _kleene_and(lo, hi)
    if isinstance(e, sql.And):
        return "bool", _kleene_and(_emit_bool(e.left, kinds),
                                   _emit_bool(e.right, kinds))
    if isinstance(e, sql.Or):
        lf, rf = _emit_bool(e.left, kinds), _emit_bool(e.right, kinds)

        def f_or(cols, lf=lf, rf=rf):
            a, av = lf(cols)
            b, bv = rf(cols)
            val = jnp.logical_or(a, b)
            ok = jnp.logical_or(jnp.logical_and(av, bv),
                                jnp.logical_or(a, b))
            return val, ok
        return "bool", f_or
    if isinstance(e, sql.Not):
        f = _emit_bool(e.inner, kinds)

        def f_not(cols, f=f):
            v, ok = f(cols)
            return jnp.logical_and(~v, ok), ok
        return "bool", f_not
    if isinstance(e, sql.IsNull):
        tag, f = _emit(e.inner, kinds)
        if tag == "null":
            return "bool", lambda cols: (True, True)

        def f_isnull(cols, f=f):
            _, ok = f(cols)
            return ~jnp.asarray(ok, bool), True
        return "bool", f_isnull
    if isinstance(e, sql.Flip):
        f = _emit(e.inner, kinds)[1]

        def f_flip(cols, f=f):
            v, _ = f(cols)
            return ~jnp.asarray(v, bool), True
        return "bool", f_flip
    if isinstance(e, sql.IsTrue):
        f = _emit_bool(e.inner, kinds)

        def f_istrue(cols, f=f):
            v, ok = f(cols)
            return jnp.logical_and(v, ok), True
        return "bool", f_istrue
    if isinstance(e, sql.IsFalse):
        f = _emit_bool(e.inner, kinds)

        def f_isfalse(cols, f=f):
            v, ok = f(cols)
            return jnp.logical_and(~v, ok), True
        return "bool", f_isfalse
    if isinstance(e, sql.InList):
        # numeric non-null literals only: pandas isin treats NaN/None
        # literals specially (NaN matches NaN), host-vector keeps those
        if not all(isinstance(i, sql.Lit)
                   and isinstance(i.value, (int, float))
                   and not isinstance(i.value, bool)
                   and not pd.isna(i.value) for i in e.items):
            raise _Unsupported("IN over non-numeric-literal list")
        fns = [_emit_cmp("=", e.inner, i, kinds) for i in e.items]
        out = fns[0]
        for nxt in fns[1:]:
            lf, rf = out, nxt

            def f_or(cols, lf=lf, rf=rf):
                a, av = lf(cols)
                b, bv = rf(cols)
                return (jnp.logical_or(a, b),
                        jnp.logical_and(av, bv))
            out = f_or
        return "bool", out
    raise _Unsupported(type(e).__name__)


def _emit_bool(e: sql.Expr, kinds):
    tag, f = _emit(e, kinds)
    if tag == "bool":
        return f
    if tag == "null":
        return lambda cols: (False, False)
    raise _Unsupported(f"non-boolean operand ({tag})")


def _promote_ts(other: sql.Expr, other_tag: str):
    """A string literal next to a timestamp operand compares as its
    parsed timestamp (pandas' coercion rule), lowered to int64 ns."""
    if other_tag == "null":
        return lambda cols: (np.int64(0), False)
    if isinstance(other, sql.Lit) and isinstance(other.value, str):
        ns = pd.Timestamp(other.value).value
        return lambda cols: (np.int64(ns), True)
    return None


def _emit_cmp(op: str, left: sql.Expr, right: sql.Expr, kinds):
    import jax.numpy as jnp

    lt = rt = None
    try:
        lt, lf = _emit(left, kinds)
    except _Unsupported:
        lt = None
    try:
        rt, rf = _emit(right, kinds)
    except _Unsupported:
        rt = None
    # timestamp vs string-literal promotion (either side)
    if lt == "num:M" and rt is None:
        pf = _promote_ts(right, "lit")
        if pf is None:
            raise _Unsupported("timestamp vs non-literal")
        rt, rf = "num:M", pf
    elif rt == "num:M" and lt is None:
        pf = _promote_ts(left, "lit")
        if pf is None:
            raise _Unsupported("timestamp vs non-literal")
        lt, lf = "num:M", pf
    if lt is None or rt is None:
        raise _Unsupported("comparison operand")
    if lt == "null":
        lf = lambda cols: (np.int64(0), False)  # noqa: E731
    if rt == "null":
        rf = lambda cols: (np.int64(0), False)  # noqa: E731
    num_tags = ("num:i", "num:f", "num:M", "null")
    if lt not in num_tags or rt not in num_tags:
        raise _Unsupported("non-numeric comparison")
    # datetime compares only against datetime (pandas raises otherwise
    # — that path must go through the vector engine to raise alike)
    if ("num:M" in (lt, rt)) and not (
            lt in ("num:M", "null") and rt in ("num:M", "null")):
        raise _Unsupported("timestamp vs number")

    def cmp(cols, lf=lf, rf=rf, op=op):
        a, av = lf(cols)
        b, bv = rf(cols)
        ok = jnp.logical_and(av, bv)
        if op in ("=", "=="):
            r = a == b
        elif op in ("!=", "<>"):
            r = a != b
        elif op == "<":
            r = a < b
        elif op == "<=":
            r = a <= b
        elif op == ">":
            r = a > b
        elif op == ">=":
            r = a >= b
        else:  # <=> null-safe equal: never NULL
            both_null = jnp.logical_and(~jnp.asarray(av, bool),
                                        ~jnp.asarray(bv, bool))
            r = jnp.logical_or(jnp.logical_and(a == b, ok), both_null)
            return r, True
        return jnp.logical_and(r, ok), ok
    return cmp


def _kleene_and(lf, rf):
    import jax.numpy as jnp

    def f_and(cols, lf=lf, rf=rf):
        a, av = lf(cols)
        b, bv = rf(cols)
        val = jnp.logical_and(a, b)
        # NULL AND FALSE = FALSE; NULL AND TRUE = NULL
        ok = jnp.logical_or(
            jnp.logical_and(av, bv),
            jnp.logical_or(jnp.logical_and(av, ~jnp.asarray(a, bool)),
                           jnp.logical_and(bv, ~jnp.asarray(b, bool))))
        return val, ok
    return f_and


def plane_program(ast: sql.Expr, dtypes: Dict[str, np.dtype]):
    """Compile a predicate AST to a jitted (values, valid)-plane mask
    program for the given column dtypes; ``None`` when the predicate is
    outside the plane subset (strings, CASE, casts, extension
    dtypes)."""
    try:
        import jax

        kinds = _col_kinds(ast, dtypes)
        key = (ast.canon(), tuple(sorted(kinds.items())))
        hit = _PLANE_CACHE.get(key)
        if hit is not None:
            return hit
        tag, f = _emit(ast, kinds)
        if tag != "bool":
            raise _Unsupported("non-boolean predicate")
        names = sorted(kinds)

        def fn(*flat):
            cols = {n: (flat[2 * i], flat[2 * i + 1])
                    for i, n in enumerate(names)}
            import jax.numpy as jnp

            val, ok = f(cols)
            return jnp.logical_and(jnp.asarray(val, bool),
                                   jnp.asarray(ok, bool))
        prog = (names, jax.jit(fn))
        _PLANE_CACHE[key] = prog
        return prog
    except (_Unsupported, ImportError):
        return None


def filter_backend(ast: sql.Expr, dtypes) -> str:
    """Which backend a predicate lands on for a given schema — used by
    the optimizer's explain annotation and the bench seam check."""
    return ("jit-plane" if plane_program(ast, dict(dtypes)) is not None
            else "host-vector")


def _series_planes(s: pd.Series):
    k = s.dtype.kind
    if k == "M":
        vals = s.to_numpy("datetime64[ns]").view("int64")
        return vals, s.notna().to_numpy()
    vals = s.to_numpy()
    if k == "f":
        return vals, ~np.isnan(vals)
    return vals, np.ones(len(vals), bool)


def _plane_mask(ast: sql.Expr, df: pd.DataFrame) -> Optional[np.ndarray]:
    prog = plane_program(ast, {c: df[c].dtype for c in df.columns
                               if isinstance(df[c].dtype, np.dtype)})
    if prog is None:
        return None
    names, fn = prog
    flat = []
    for n in names:
        v, ok = _series_planes(df[n])
        flat += [v, ok]
    return np.asarray(fn(*flat), bool)


# ----------------------------------------------------------------------
# Statement compiler: SELECT ... FROM ... [ASOF JOIN ...] [WHERE ...]
#                     [GROUP BY time_bucket('<freq>')]
# ----------------------------------------------------------------------

class _Statement:
    __slots__ = ("projs", "star", "table", "join_table", "join_params",
                 "where", "bucket")

    def __init__(self):
        self.projs = []         # ("expr", ast, alias, raw) |
        #                         ("agg", func, col, alias)
        self.star = False
        self.table = None
        self.join_table = None
        self.join_params = {}
        self.where = None       # sql.Expr
        self.bucket = None      # freq string


def _ident(p: "sql._Parser", what: str) -> str:
    t = p.next()
    if t.kind != "ident":
        raise sql.SqlError(f"expected {what}, found {t.text!r}")
    return t.text[1:-1] if t.text.startswith("`") else t.text


def _str_lit(p: "sql._Parser", what: str) -> str:
    t = p.next()
    if t.kind != "str":
        raise sql.SqlError(f"expected a string literal for {what}, "
                           f"found {t.text!r}")
    return t.text[1:-1]


def _parse_projection(p: "sql._Parser"):
    t = p.peek()
    # aggregate call: <agg>(<col>) — agg names are not expression
    # functions, so they are recognised structurally here
    if (t.kind == "ident" and t.text.lower() in _AGG_FUNCS
            and p.toks[p.pos + 1].kind == "op"
            and p.toks[p.pos + 1].text == "("):
        func = _AGG_FUNCS[t.text.lower()]
        p.pos += 2
        col = _ident(p, "an aggregated column")
        p.expect_op(")")
        alias = _ident(p, "an alias") if p.kw("as") else col
        return ("agg", func, col, alias)
    ast = p.parse_expr()
    if p.kw("as"):
        alias = _ident(p, "an alias")
    elif isinstance(ast, sql.Col):
        alias = ast.name.split(".")[-1]
    else:
        raise sql.SqlError(
            "statement projections other than bare columns require an "
            "AS alias")
    return ("expr", ast, alias, None)


def parse_statement(text: str) -> _Statement:
    """Parse the supported statement grammar::

        SELECT <proj> [, <proj>]* | *
        FROM <table>
        [ASOF JOIN <table> [PREFIX '<p>'] [LEFT PREFIX '<p>']
                           [LOOKBACK <seconds>]]
        [WHERE <predicate>]
        [GROUP BY time_bucket('<freq>')]

    Aggregate projections (``mean``/``avg``/``min``/``max``/``first``/
    ``last``) require GROUP BY and lower onto the bucket-stats resample
    kernels; everything else is an expression projection."""
    p = sql._Parser(sql._tokenize(text))
    if not p.kw("select"):
        raise sql.SqlError("statement must start with SELECT")
    st = _Statement()
    if p.op("*"):
        st.star = True
    else:
        st.projs.append(_parse_projection(p))
        while p.op(","):
            st.projs.append(_parse_projection(p))
    if not p.kw("from"):
        raise sql.SqlError("statement requires FROM <table>")
    st.table = _ident(p, "a table name")
    if p.kw("asof"):
        if not p.kw("join"):
            raise sql.SqlError("ASOF must be followed by JOIN")
        st.join_table = _ident(p, "a join table name")
        while True:
            if p.kw("prefix"):
                st.join_params["right_prefix"] = _str_lit(p, "PREFIX")
            elif p.kw("left"):
                if not p.kw("prefix"):
                    raise sql.SqlError("LEFT must be followed by PREFIX")
                st.join_params["left_prefix"] = _str_lit(p, "LEFT PREFIX")
            elif p.kw("lookback"):
                t = p.next()
                if t.kind != "num":
                    raise sql.SqlError("LOOKBACK requires a number")
                st.join_params["maxLookback"] = int(float(t.text))
            else:
                break
    if p.kw("where"):
        st.where = p.parse_expr()
    if p.kw("group"):
        if not p.kw("by"):
            raise sql.SqlError("GROUP must be followed by BY")
        t = p.next()
        if not (t.kind == "ident" and t.text.lower() == "time_bucket"):
            raise sql.SqlError(
                "only GROUP BY time_bucket('<freq>') is compiled")
        p.expect_op("(")
        st.bucket = _str_lit(p, "time_bucket")
        p.expect_op(")")
    if p.peek().kind != "end":
        raise sql.SqlError(
            f"trailing tokens at {p.peek().text!r} in statement")
    return st


def _table_node(name: str, tables) -> ir.Node:
    from tempo_tpu.plan import lazy as plan_lazy

    key = sql.resolve_column(name, tables)
    if key is None:
        raise sql.SqlError(
            f"unknown table {name!r}; registered: "
            + ", ".join(sorted(tables)))
    return plan_lazy._as_node(tables[key])


def _structural(node: ir.Node) -> List[str]:
    """ts + partition (+ sequence) columns of the frame under a plan
    chain — the spine every statement result retains."""
    src = node.sources()[0]
    f = src.payload
    seq = getattr(f, "sequence_col", "") or getattr(f, "seq_col", "")
    return ([f.ts_col] + list(f.partitionCols) + ([seq] if seq else []))


def compile_statement(text: str, tables) -> ir.Node:
    """Compile one SELECT statement into a plan-IR root over the given
    ``{name: TSDF|DistributedTSDF|lazy}`` tables.  The root carries
    ``_origin='sql'`` (a distinct cache signature from the equivalent
    method chain — MIGRATION v0.18)."""
    from tempo_tpu import freq as freq_mod

    st = parse_statement(text)
    cur = _table_node(st.table, tables)
    if st.join_table is not None:
        right = _table_node(st.join_table, tables)
        jp = dict(left_prefix=None, right_prefix="right",
                  tsPartitionVal=None, fraction=0.5, skipNulls=True,
                  sql_join_opt=False, suppress_null_warning=False,
                  maxLookback=0)
        jp.update(st.join_params)
        cur = ir.Node("asof_join", params=jp, inputs=(cur, right))
    if st.where is not None:
        cols = ir.output_columns(cur)
        ast = _resolve(st.where, cols)
        params = dict(condition=sql.unparse(ast), ast=ast.canon(),
                      cols=tuple(sorted(sql.column_refs(ast))))
        cur = ir.Node("sql_filter", params=params, inputs=(cur,),
                      objs=dict(ast=ast))
    aggs = [pr for pr in st.projs if pr[0] == "agg"]
    exprs = [pr for pr in st.projs if pr[0] == "expr"]
    if st.bucket is not None:
        if not aggs:
            raise sql.SqlError(
                "GROUP BY time_bucket requires aggregate projections")
        freq_mod.checkAllowableFreq(st.bucket)
        funcs = {f for _, f, _, _ in aggs}
        if len(funcs) > 1:
            raise sql.SqlError(
                "one aggregate function per statement (the bucket-stats "
                f"kernels aggregate uniformly); got {sorted(funcs)}")
        structural = _structural(cur)
        cols = ir.output_columns(cur)
        metric = []
        for _, _, col, _ in aggs:
            rc = (sql.resolve_column(col, cols) if cols else col) or col
            metric.append(rc)
        for pr in exprs:
            if not (isinstance(pr[1], sql.Col)
                    and (sql.resolve_column(pr[1].name, structural)
                         or pr[1].name in structural)):
                raise sql.SqlError(
                    "non-aggregate projections in a GROUP BY statement "
                    "must be the frame's time/partition columns")
        cur = ir.Node("resample", params=dict(
            freq=st.bucket, func=next(iter(funcs)),
            metricCols=tuple(metric), prefix=None, fill=None),
            inputs=(cur,))
        # post-resample aliasing only when some alias differs from its
        # source column (the bucket kernels keep metric column names)
        if any(alias != col for _, _, col, alias in aggs):
            projs = [(c, sql.Col(c)) for c in structural]
            projs += [(alias, sql.Col(col)) for _, _, col, alias in aggs]
            params = dict(
                exprs=tuple(f"{e.name} AS {a}" if a != e.name else a
                            for a, e in projs),
                aliases=tuple(a for a, _ in projs),
                asts=tuple(e.canon() for _, e in projs),
                cols=tuple(sorted({e.name for _, e in projs})))
            cur = ir.Node("sql_project", params=params, inputs=(cur,),
                          objs=dict(projs=tuple(projs)))
    elif aggs:
        raise sql.SqlError(
            "aggregate projections require GROUP BY time_bucket")
    elif not st.star:
        structural = _structural(cur)
        out_cols = ir.output_columns(cur)
        projs, aliases = [], []
        for _, ast, alias, _ in exprs:
            projs.append((alias, _resolve(ast, out_cols)))
            aliases.append(alias)
        # auto-inject the structural spine (a time-series SELECT always
        # keeps its time/partition columns; explicit projections win)
        inject = [c for c in structural if c not in aliases]
        projs = [(c, sql.Col(c)) for c in inject] + projs
        refs = set()
        for _, ast in projs:
            refs |= sql.column_refs(ast)
        params = dict(
            exprs=tuple(f"<{a}>" for a, _ in projs),
            aliases=tuple(a for a, _ in projs),
            asts=tuple(e.canon() for _, e in projs),
            cols=tuple(sorted(refs)))
        cur = ir.Node("sql_project", params=params, inputs=(cur,),
                      objs=dict(projs=tuple(projs)))
    # the origin marker: SQL-born plans never share a cache signature
    # (and therefore never a cached executable) with method-chain twins
    root_params = dict(cur.params)
    root_params["_origin"] = "sql"
    root = ir.Node(cur.op, params=root_params, inputs=cur.inputs,
                   payload=cur.payload, objs=cur.objs)
    return root


def run_statement(text: str, tables):
    """One-shot compile + plan-execute (the non-service entry point the
    parity gate and tests use)."""
    from tempo_tpu.plan import executor, optimizer

    root = compile_statement(text, tables)
    if optimizer._mesh_side(root):
        root = ir.Node("collect", inputs=(root,))
    return executor.execute(root)
