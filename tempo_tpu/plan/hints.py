"""Plan-time engine hints.

The optimizer hoists engine selection (``pick_join_engine``,
``pick_range_engine``) to plan time; while the executor replays a node
whose annotations carry a hoisted decision, the hint is installed here
and the pick functions return it without re-reading knobs or
re-probing sizes.  Import-light on purpose: consulted from
``tempo_tpu.profiling`` and ``tempo_tpu.ops.rolling`` without creating
an import cycle.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import Dict, Optional

_HINTS: contextvars.ContextVar[Dict[str, object]] = contextvars.ContextVar(
    "tempo_tpu_plan_hints", default={})


def get(name: str) -> Optional[object]:
    """The active hint value (``join_engine`` / ``range_engine``), or
    None when no planned node is executing."""
    return _HINTS.get().get(name)


@contextlib.contextmanager
def installed(hints: Dict[str, object]):
    token = _HINTS.set(dict(hints))
    try:
        yield
    finally:
        _HINTS.reset(token)
