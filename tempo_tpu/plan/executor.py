"""Plan executor: replay an optimized plan through the eager API.

``execute(root)`` is the single entry point the lazy terminals call:
it looks the plan up in the executable cache
(:mod:`tempo_tpu.plan.cache`), builds an :class:`Executable` on a miss
(optimizer passes run exactly once per cached plan), and runs it over
the plan's source payloads.  Re-running a structurally identical chain
over same-shape frames is a cache hit: no re-optimization, no engine
re-pick — and no new XLA compiles, because every program builder
underneath (dist.py's ``lru_cache``\\ d shard_map factories, the fused
chain builder, jax's jit cache) is keyed by the same shapes.

Recording is suspended for the whole run, so replaying through the
eager methods never re-records.
"""

from __future__ import annotations

import logging
import os
import time
from typing import Dict, List, Optional

from tempo_tpu.plan import cache, hints, ir, optimizer
from tempo_tpu.plan import checkpoints as plan_ckpt

logger = logging.getLogger(__name__)


def execute(root: ir.Node):
    from tempo_tpu.plan import cost

    # snapshot the cost inputs ONCE: the key's fingerprint and the
    # decisions optimize() bakes into the executable must come from
    # the same inputs even if a concurrent set_measured() lands
    # mid-build (cost.pinned below)
    snap = cost.snapshot()
    key = ir.state_key(root)
    if key is not None:
        # the reshard-placement mode, the active cost-model inputs and
        # the checkpoint-barrier spec all change the OPTIMIZED plan
        # without touching the logical signature — fold them into the
        # cache key so flipping TEMPO_TPU_RESHARD_PLACEMENT, a measured
        # cost input, or a checkpointed() context never replays a plan
        # decided under the other configuration
        key = key + (optimizer.reshard_mode(), cost.fingerprint(snap),
                     plan_ckpt.fingerprint())

    def build():
        t0 = time.perf_counter()
        with cost.pinned(snap):
            exe = Executable(optimizer.optimize(root))
        exe.build_seconds = time.perf_counter() - t0
        # run() binds the caller's payloads positionally, so the
        # build-time frames on the optimized copy are dead weight —
        # drop them or the process-global cache pins up to max_size()
        # full DataFrames/device buffers until eviction
        for s in exe.plan.sources():
            s.payload = None
        return exe

    # single-flight under the shared cache: concurrent tenants missing
    # on the same signature build once (plan/cache.py)
    exe = cache.CACHE.get_or_build(key, build)
    return exe.run([n.payload for n in root.sources()])


class Executable:
    """One optimized plan bound to nothing: ``run(payloads)`` supplies
    the source frames (positionally, in plan DFS order), so the same
    executable serves every same-shape instance of the query."""

    def __init__(self, plan: ir.Node):
        self.plan = plan
        self.build_seconds = 0.0
        self.runs = 0

    def run(self, payloads: List):
        from tempo_tpu import plan as plan_mod

        sources = self.plan.sources()
        if len(sources) != len(payloads):
            raise ValueError(
                f"plan expects {len(sources)} source frame(s); "
                f"got {len(payloads)}")
        self.runs += 1
        env: Dict[int, object] = {}
        spec = plan_ckpt.active()
        # barrier nodes only exist in plans optimized under an active
        # context (the spec is in the cache key), so the hot path —
        # every query-service dispatch — skips the plan walk entirely
        ckpt_nodes = ([n for n in self.plan.walk()
                       if n.op == "checkpoint"]
                      if spec is not None else [])
        sig = None
        resume_id, resume_frame, prev0 = None, None, None
        skip = frozenset()
        if spec is not None and ckpt_nodes:
            from tempo_tpu import checkpoint as ckpt_mod
            from tempo_tpu.resilience import CheckpointError

            os.makedirs(spec.ckpt_dir, exist_ok=True)
            sig = _stamped_signature(self.plan, payloads)
            below = None
            while True:
                # manifest-only resolve; load verifies the arrays ONCE
                # — an unloadable barrier falls back to an older one
                hit = ckpt_mod.resolve_step(
                    spec.ckpt_dir, signature=sig,
                    max_step=len(ckpt_nodes), verify=False,
                    below_step=below)
                if hit is None:
                    break
                step_no, path, _man = hit
                target = next((n for n in ckpt_nodes
                               if n.param("step") == step_no), None)
                if target is None:
                    break
                try:
                    resume_frame = _load_barrier(target, path, payloads,
                                                 sources)
                except (CheckpointError, ValueError) as e:
                    logger.warning(
                        "plan: barrier %s unusable (%s); falling back "
                        "to an older one", path, e)
                    below = step_no
                    continue
                resume_id = id(target)
                prev0 = (step_no, ckpt_mod.manifest_crc(path))
                # skip the resumed subtree — EXCEPT nodes a consumer
                # outside the subtree still needs (a DAG may share a
                # source across the barrier: it must stay live)
                live = set()

                def _mark(n):
                    if id(n) in live or id(n) == resume_id:
                        return
                    live.add(id(n))
                    for c in n.inputs:
                        _mark(c)

                _mark(self.plan)
                skip = (frozenset(id(c) for c in target.walk())
                        - live - {resume_id})
                logger.info(
                    "plan: resuming from barrier step %d (%s); "
                    "%d upstream plan node(s) skipped",
                    step_no, path, len(skip))
                break
        prev: Optional[tuple] = prev0   # (step, manifest CRC) chain link
        with plan_mod.suspended():
            for node in self.plan.walk():
                if id(node) in skip:
                    # everything under the resumed barrier: its value IS
                    # the restored checkpoint — never re-executed
                    env[id(node)] = None
                    continue
                if node.op == "checkpoint":
                    if id(node) == resume_id:
                        env[id(node)] = resume_frame
                    else:
                        env[id(node)], prev = _save_barrier(
                            node, env[id(node.inputs[0])], spec, sig,
                            prev)
                    continue
                if node.is_source():
                    env[id(node)] = _bind_source(
                        node, payloads[sources.index(node)])
                else:
                    with hints.installed(node.ann.get("hints", {})):
                        env[id(node)] = _eval_op(node, [
                            env[id(c)] for c in node.inputs
                        ])
        return env[id(self.plan)]


def _stamped_signature(plan: ir.Node, payloads: List) -> str:
    """What a barrier manifest is stamped with: the optimized-plan
    signature (structure + params + annotations) PLUS each source
    frame's content fingerprint.  Structure alone would let the same
    chain over different same-shape data restore the previous data's
    barriers — the stale-restore variant of the foreign-resume
    hazard."""
    import hashlib

    fps = "|".join(plan_ckpt.source_fingerprint(p) for p in payloads)
    return hashlib.sha1(
        f"{ir.signature(plan)}|{fps}".encode()).hexdigest()[:16]


def _save_barrier(node: ir.Node, frame, spec, sig: str,
                  prev: Optional[tuple]):
    """Write one plan barrier: a ``step_NNNNN`` checkpoint whose
    manifest is stamped with the optimized-plan signature and the
    predecessor barrier's manifest CRC (the chained-manifest scheme);
    the frame passes through unchanged.  A barrier node run OUTSIDE a
    checkpointed context (same cached executable, context since
    exited) is a transparent no-op."""
    if spec is None:
        return frame, prev
    from tempo_tpu import checkpoint as ckpt_mod

    step = int(node.param("step"))
    path = os.path.join(spec.ckpt_dir, f"step_{step:05d}")
    meta = {"pipeline_signature": sig, "step": step,
            "plan_op": node.inputs[0].op}
    if prev is not None:
        meta["prev_step"], meta["prev_manifest_crc"] = prev
    ckpt_mod.save(frame, path, sharded=spec.sharded, meta=meta)
    logger.info("plan: barrier step %d (%s) checkpointed to %s",
                step, node.inputs[0].op, path)
    ckpt_mod.prune(spec.ckpt_dir, keep_last=spec.keep_last)
    return frame, (step, ckpt_mod.manifest_crc(path))


def _load_barrier(node: ir.Node, path: str, payloads: List,
                  sources: List[ir.Node]):
    """Restore the frame a barrier checkpoint holds, re-placed onto the
    mesh the CURRENT submission's source frames live on (cached
    executables drop build-time payloads, so the mesh comes from the
    caller's live frames / the recorded on_mesh node)."""
    from tempo_tpu import checkpoint as ckpt_mod

    mesh, s_ax, t_ax, on_mesh_seen = None, "series", None, False
    for n in node.walk():
        if n.op == "on_mesh":
            on_mesh_seen = True
            mesh = n.objs.get("mesh") or mesh
            s_ax = n.param("series_axis", "series")
            t_ax = n.param("time_axis")
        elif n.op == "dist_source":
            p = payloads[sources.index(n)]
            mesh, s_ax, t_ax = p.mesh, p.series_axis, p.time_axis
    if mesh is None and on_mesh_seen:
        from tempo_tpu.parallel.mesh import make_mesh

        mesh = make_mesh()
    return ckpt_mod.load(path, mesh=mesh, series_axis=s_ax,
                         time_axis=t_ax)


def _bind_source(node: ir.Node, payload):
    if node.op == "unified_scan":
        # the unified history+live source: one TSDF over everything
        # ever written — Parquet store history plus the live tail —
        # snapshotted at this version under the table's watermark
        return payload.materialize()
    keep = node.ann.get("prune_to")
    if keep is None or node.op != "source":
        return payload
    logger.debug("plan: pruning %s before packing (dead columns: %s)",
                 type(payload).__name__, node.ann.get("pruned"))
    return payload.select(list(keep))


def _eval_op(node: ir.Node, ins: List):
    from tempo_tpu.dist import DistributedTSDF

    op = node.op
    p = node.param
    if op == "reshard":
        # the optimizer's first-class layout switch (plan-placed
        # resharding): one explicit all_to_all program over the whole
        # frame instead of per-op pairs inside every downstream stage
        from tempo_tpu import dist as dist_mod

        return dist_mod.reshard_frame(ins[0], p("target"))
    if op == "on_mesh":
        return ins[0].on_mesh(
            node.objs.get("mesh"), time_axis=p("time_axis"),
            series_axis=p("series_axis", "series"),
            halo_fraction=p("halo_fraction", 0.5))
    if op == "select":
        return ins[0].select(list(p("cols", ())))
    if op in ("sql_project", "sql_filter"):
        from tempo_tpu.plan import sql_compile

        if op == "sql_project":
            return sql_compile.run_project(ins[0], node)
        return sql_compile.run_filter(ins[0], node)
    if op == "with_column":
        return ins[0].withColumn(p("colName"), node.objs["values"])
    if op == "asof_join":
        return ins[0].asofJoin(
            ins[1], left_prefix=p("left_prefix"),
            right_prefix=p("right_prefix") or "right",
            tsPartitionVal=p("tsPartitionVal"),
            fraction=p("fraction", 0.5),
            skipNulls=bool(p("skipNulls", True)),
            sql_join_opt=bool(p("sql_join_opt", False)),
            suppress_null_warning=bool(p("suppress_null_warning", False)),
            maxLookback=int(p("maxLookback", 0) or 0))
    if op == "range_stats":
        cols = p("colsToSummarize")
        cols = list(cols) if cols else None
        if isinstance(ins[0], DistributedTSDF):
            return ins[0].withRangeStats(
                colsToSummarize=cols,
                rangeBackWindowSecs=p("rangeBackWindowSecs", 1000),
                strategy=p("strategy", "exact"))
        return ins[0].withRangeStats(
            type=p("type", "range"), colsToSummarize=cols,
            rangeBackWindowSecs=p("rangeBackWindowSecs", 1000))
    if op == "ema":
        return ins[0].EMA(
            p("colName"), window=int(p("window", 30)),
            exp_factor=p("exp_factor", 0.2), exact=bool(p("exact", False)),
            inclusive_window=bool(p("inclusive_window", False)))
    if op == "ema_stream":
        # the standing-query canonical form of EMA(exact=True): the
        # sequential split-invariant kernel (ops/rolling.ema_scan) the
        # serving carries resume bitwise (query/split.py)
        from tempo_tpu.query import split as standing_split

        return standing_split.eval_ema_stream(
            ins[0], p("colName"), float(p("exp_factor", 0.2)))
    if op == "resample":
        cols = p("metricCols")
        cols = list(cols) if cols else None
        if isinstance(ins[0], DistributedTSDF):
            return ins[0].resample(p("freq"), p("func"), metricCols=cols)
        return ins[0].resample(p("freq"), p("func"), metricCols=cols,
                               prefix=p("prefix"), fill=p("fill"))
    if op == "resample_ema":
        return ins[0].resampleEMA(p("freq"), p("colName"),
                                  exp_factor=p("exp_factor", 0.2))
    if op == "interpolate":
        cols = p("target_cols")
        cols = list(cols) if cols else None
        if isinstance(ins[0], DistributedTSDF):
            return ins[0].interpolate(
                freq=p("freq"), func=p("func"), method=p("method"),
                target_cols=cols,
                show_interpolated=bool(p("show_interpolated", False)))
        pcols = p("partition_cols")
        return ins[0].interpolate(
            freq=p("freq"), func=p("func"), method=p("method"),
            target_cols=cols, ts_col=p("ts_col"),
            partition_cols=list(pcols) if pcols else None,
            show_interpolated=bool(p("show_interpolated", False)))
    if op == "interpolate_resampled":
        cols = p("target_cols")
        return ins[0].interpolate(
            p("method"), target_cols=list(cols) if cols else None,
            show_interpolated=bool(p("show_interpolated", False)))
    if op == "fourier":
        return ins[0].fourier_transform(p("timestep"), p("valueCol"))
    if op == "lookback_features":
        return ins[0].withLookbackFeatures(
            list(p("featureCols", ())), int(p("lookbackWindowSize")),
            exactSize=bool(p("exactSize", True)),
            featureColName=p("featureColName", "features"))
    if op == "collect":
        return ins[0].collect()
    if op == "count":
        return ins[0].count()
    if op == "calc_bars":
        mc = p("metricCols")
        return ins[0].calc_bars(
            p("freq"), func=p("func"),
            metricCols=list(mc) if mc else None, fill=p("fill"))
    if op == "fused_asof_stats_ema":
        from tempo_tpu.plan import fused

        out = fused.run(ins[0], ins[1], node)
        if out is not None:
            return out
        logger.debug("plan: fused chain guard failed at run time — "
                     "executing the chain op-by-op")
        return _sequential_chain(node, ins)
    if op == "stitched":
        from tempo_tpu.plan import stitch

        out = stitch.run(ins[0], node)
        if out is not None:
            return out
        logger.debug("plan: stitched chain guard failed at run time — "
                     "executing the chain op-by-op")
        return stitch.run_sequential(ins[0], node)
    raise ValueError(f"plan executor: unknown op {op!r}")


def _sequential_chain(node: ir.Node, ins: List):
    """Op-by-op fallback for a fused node whose run-time guards failed
    (e.g. a frame grew a sequence column since planning)."""
    p = node.param
    cols = p("s_cols")
    out = ins[0].asofJoin(
        ins[1], left_prefix=p("j_left_prefix"),
        right_prefix=p("j_right_prefix") or "right",
    ).withRangeStats(
        colsToSummarize=list(cols) if cols else None,
        rangeBackWindowSecs=p("s_window", 1000))
    if p("has_ema"):
        out = out.EMA(
            p("e_col"), window=int(p("e_window", 30)),
            exp_factor=p("e_exp_factor", 0.2),
            exact=bool(p("e_exact", False)),
            inclusive_window=bool(p("e_inclusive", False)))
    return out
