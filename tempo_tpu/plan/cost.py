"""Cost-based plan decisions: engine picks, fusion, reshard placement.

Until round 11 every planner decision was *rule-based*: hand-set
thresholds (``TEMPO_TPU_STREAM_MAX_ROWS``, ``TEMPO_TPU_JOIN_CHUNK_LANES``,
the ~205K merged-lane ceiling) decided which engine ran, fusion always
fired when its guards held, and reshard placement always placed.  This
module is the Catalyst-style cost layer over the same decisions: every
choice is an argmin over *estimated seconds* computed from

* **byte models** — the same per-plane accounting the compiled tier
  audits (``profiling.comm_bytes_from_compiled`` byte-exact on the CPU
  mesh, padding headroom from ``profiling.COLLECTIVE_TOLERANCE``) and
  the roofline bytes-minimal math (``profiling.window_roofline``);
* **measured rates** — the single-chip stream rate the bench measures
  (BENCH r5: ~675 GB/s achieved on the streaming kernels) as the prior,
  overridable per-process by :func:`set_measured` (the bench and the
  round-12 autotuner feed re-measured rates back in);
* **demoted thresholds** — the old knob values survive as *priors*
  (feasibility bounds and default chunk widths), not laws: they gate
  which engines are candidates, the cost decides among candidates.

**The bitwise contract bounds what cost may decide.**  A cost-decided
plan must stay bitwise-identical to its rule-based twin, so the argmin
runs over the *bitwise-equal candidate set* only:

* AS-OF join engines (single / chunked / bracket) are all bit-identical
  to each other (round 3), so the join argmin is free within resource
  feasibility — this is the pick that genuinely flips when the cost
  inputs change.
* The range-stats engines (shifted / stream / windowed) differ in f32
  rounding order, so the revalidation lattice from round 5
  (``ops/rolling.pick_range_engine``: shifted iff it fits, else stream
  iff it fits, else windowed) admits exactly ONE bitwise-safe engine
  per shape — the cost numbers are computed and rendered
  (``explain()``), but the argmin is over that singleton by
  construction.
* Fusing the mesh chain into one program and plan-placed resharding
  are both bitwise-identical to their unfused/declarative twins
  (rounds 5 and 10 pin this), so both decisions are free to flip.

``TEMPO_TPU_COST_MODEL=0`` switches every consumer back to the pure
rule-based decisions.  :func:`fingerprint` folds the active cost inputs
into the executable-cache key, so flipping an input re-plans instead of
replaying a stale decision.
"""

from __future__ import annotations

import contextlib
import contextvars
import math
import threading
from typing import Dict, Optional, Tuple

#: Per-merged-lane traffic of an AS-OF join engine pass: i64 key read,
#: f32 payload read + bool validity, f32 result write.  One shared
#: constant — the engines move the same compulsory bytes, they differ
#: in rate and per-chunk overhead.
JOIN_LANE_BYTES = 17

#: Per-row traffic of a range-stats pass (i64 key + f32 value + bool
#: validity in, 7 f32 stat planes out) — window_roofline's
#: bytes-minimal accounting at one summarized column.
STATS_ROW_BYTES = 8 + 4 + 1 + 7 * 4

#: Cost priors.  Rates are bytes/sec, overheads are seconds.  The
#: stream rate is the measured single-chip figure (BENCH r5 streaming
#: kernels); the host rate is the measured pandas-bracket order of
#: magnitude; the windowed penalty is the measured shifted/windowed
#: ratio from the rolling_crossover record (175M vs 8M rows/s).
#: :func:`set_measured` overlays any of these with fresher numbers.
PRIORS: Dict[str, float] = {
    "hbm_stream_rate": 675e9,
    "join_single_rate": 675e9,
    "join_chunked_rate": 675e9,
    "host_bracket_rate": 0.5e9,
    "ici_rate": 45e9,
    "dispatch_overhead_s": 50e-6,
    "chunk_overhead_s": 15e-6,
    "fused_overhead_s": 0.0,
    # prior 0: the mesh-scaling bench measured no per-dispatch penalty
    # for a placed reshard program vs in-op pairs, so under the priors
    # placement wins whenever it moves no MORE bytes than the internal
    # pairs it eliminates (ties place — today's rule); a measured
    # override charges the dispatch and can flip whole-plan placement
    "reshard_dispatch_s": 0.0,
    "windowed_gather_penalty": 20.0,
    # VMEM-resident shifted/stream passes re-touch their slab once per
    # window row at roughly this multiple of the HBM stream rate — the
    # term that makes wide windows expensive for the pass-based
    # engines (and reproduces the measured crossover where the
    # W-independent windowed RMQ form eventually wins)
    "vmem_pass_rate_multiple": 50.0,
}

_lock = threading.Lock()
_measured: Dict[str, float] = {}  # guarded-by: _lock

#: build-time pin: the executor snapshots the active inputs ONCE when
#: it computes the cache key and installs them here for the whole
#: optimize/build, so a concurrent ``set_measured`` (a live autotuner
#: feeding rates while the query service builds) can never bake
#: decisions into an executable cached under the OLD fingerprint.
_PINNED: contextvars.ContextVar[Optional[Dict[str, float]]] = \
    contextvars.ContextVar("tempo_tpu_cost_pinned", default=None)


@contextlib.contextmanager
def pinned(snapshot: Optional[Dict[str, float]]):
    """Run a block with the cost inputs pinned to ``snapshot`` (a
    :func:`params` result; None = no-op, for the cost-model-off
    path).  Every ``params()`` read inside the block — the optimizer
    passes, the engine picks they call — sees the snapshot."""
    if snapshot is None:
        yield
        return
    token = _PINNED.set(dict(snapshot))
    try:
        yield
    finally:
        _PINNED.reset(token)


def enabled() -> bool:
    """``TEMPO_TPU_COST_MODEL`` (default on).  Off = every consumer
    (``pick_join_engine``, the optimizer's fusion and reshard passes)
    returns to the pure rule-based decision."""
    from tempo_tpu import config

    return config.get_bool("TEMPO_TPU_COST_MODEL", True)


def set_measured(**inputs: float) -> None:
    """Overlay measured cost inputs over the priors (process-wide).
    Unknown names raise — the input space is the documented
    :data:`PRIORS` set plus the ``join_chunk_lanes`` demoted
    threshold.  ``TEMPO_TPU_STREAM_MAX_ROWS`` is deliberately NOT a
    cost input: it gates which range engine is *bitwise-legal* (the
    engines differ in f32 rounding), so overriding it here could flip
    result bits — widen the knob itself instead."""
    known = set(PRIORS) | {"join_chunk_lanes"}
    for name in inputs:
        if name not in known:
            raise KeyError(
                f"unknown cost input {name!r}: known inputs are "
                f"{sorted(known)}")
    with _lock:
        _measured.update({k: float(v) for k, v in inputs.items()})


def clear_measured() -> None:
    with _lock:
        _measured.clear()


def params() -> Dict[str, float]:
    """The active cost inputs: priors, then the tuned-profile overlay
    (tempo_tpu/tune — the autotuner's MEASURED rates for this image,
    e.g. the real saxpy stream rate instead of the BENCH r5 TPU
    figure), the demoted thresholds (read from their knobs — they are
    priors now, not laws), and any :func:`set_measured` overlay on
    top.  A loaded profile also contributes ``tune_profile_crc`` — an
    inert-to-the-arithmetic stamp that rides :func:`fingerprint` into
    the executable-cache key, so swapping profiles (which can change
    the kernel-structure knobs the rates don't cover) re-plans instead
    of replaying.  Inside a :func:`pinned` block the snapshot wins
    outright (build-time consistency)."""
    pin = _PINNED.get()
    if pin is not None:
        return dict(pin)
    from tempo_tpu import config, tune

    out = dict(PRIORS)
    out.update(tune.measured())
    crc = tune.stamp()
    if crc is not None:
        out["tune_profile_crc"] = crc
    # 32768 is the auto chunk-width CEILING of the streaming join's
    # VMEM plan (pallas_merge._plan_chunk_lanes doubles while
    # Cm <= 1 << 15) — a wider prior would undercount the per-chunk
    # overhead of chunk plans the engine can never actually run
    lanes = config.get_int("TEMPO_TPU_JOIN_CHUNK_LANES")
    if lanes is None:
        lanes = tune.knob_value("TEMPO_TPU_JOIN_CHUNK_LANES")
    out["join_chunk_lanes"] = float(lanes or 32768)
    with _lock:
        out.update(_measured)
    return out


def snapshot() -> Optional[Dict[str, float]]:
    """The active inputs as a build-time pin (None when the model is
    off): the executor keys the cache with
    ``fingerprint(snapshot)`` and optimizes under ``pinned(snapshot)``
    so key and decisions can never diverge mid-build."""
    return params() if enabled() else None


def fingerprint(snap: Optional[Dict[str, float]] = None) -> tuple:
    """Hashable digest of the cost inputs (``snap`` when given, else
    the live ones), folded into the executable-cache key
    (plan/executor.py): flipping an input must re-plan, never replay a
    decision made under the other inputs."""
    if snap is None:
        if not enabled():
            from tempo_tpu import tune

            crc = tune.stamp()
            # the tuned profile changes kernel-structure knobs (DMA
            # depth, pack width) even with the cost model off — its
            # stamp must still key the cache so a swap re-plans
            return ("cost-off",) if crc is None else ("cost-off", crc)
        snap = params()
    return tuple(sorted(snap.items()))


# ----------------------------------------------------------------------
# AS-OF join engines — the bitwise-free argmin
# ----------------------------------------------------------------------

def join_costs(est_lanes: int, limit: int,
               chunked_ok: bool) -> Dict[str, Optional[float]]:
    """Estimated seconds per join engine at ``est_lanes`` merged lanes;
    ``None`` marks an engine outside its resource feasibility (the old
    thresholds, now acting as candidate gates): ``single`` past the
    compiler ceiling, ``chunked`` where the Mosaic kernel cannot run."""
    p = params()
    nbytes = float(est_lanes) * JOIN_LANE_BYTES
    out: Dict[str, Optional[float]] = {
        "single": None, "chunked": None, "bracket": None}
    if limit <= 0 or est_lanes <= limit:
        out["single"] = nbytes / p["join_single_rate"] \
            + p["dispatch_overhead_s"]
    if chunked_ok:
        n_chunks = max(1, math.ceil(est_lanes / p["join_chunk_lanes"]))
        out["chunked"] = nbytes / p["join_chunked_rate"] \
            + p["dispatch_overhead_s"] + n_chunks * p["chunk_overhead_s"]
    out["bracket"] = nbytes / p["host_bracket_rate"] \
        + p["dispatch_overhead_s"]
    return out


def decide_join_engine(est_lanes: int, limit: int, chunked_ok: bool) -> str:
    """Cheapest feasible join engine.  All three engines are
    bit-identical (round 3), so the argmin is unconstrained within
    feasibility; under the default priors it reproduces the rule-based
    pick exactly (single under the ceiling, chunked past it, bracket
    last), and a measured rate/overhead override flips it — the
    flip-under-cost-inputs the round-11 acceptance demonstrates."""
    costs = join_costs(est_lanes, limit, chunked_ok)
    order = ("single", "chunked", "bracket")   # rule-order tie-break
    best = min((e for e in order if costs[e] is not None),
               key=lambda e: costs[e])
    return best


# ----------------------------------------------------------------------
# Range-stats engines — argmin over the bitwise-safe singleton
# ----------------------------------------------------------------------

def range_costs(W: int, n_elems: int) -> Dict[str, float]:
    """Estimated seconds per range-stats engine over ``n_elems`` rows
    with a (max_behind + max_ahead) row extent of ``W`` — the numbers
    the plan-time hoist (``optimizer._hoist_engines``) attaches to
    range_stats nodes for ``explain()`` to render next to the engine
    choice (host chains with derivable rowbounds).  Models:
    shifted/stream cross HBM once (roofline-minimal) but re-touch the
    VMEM-resident slab once per window row at
    ``vmem_pass_rate_multiple`` × the stream rate (stream pays one
    extra dispatch for its scalar prologue); windowed pays the
    measured RMQ gather penalty but is W-independent (prefix scans +
    log-doubling RMQ) — so the estimates reproduce the measured
    crossover where wide windows eventually favour the windowed
    form."""
    p = params()
    base = float(n_elems) * STATS_ROW_BYTES / p["hbm_stream_rate"]
    per_pass = (float(n_elems) * 4.0
                / (p["hbm_stream_rate"] * p["vmem_pass_rate_multiple"]))
    passes = max(1, int(W)) * per_pass
    return {
        "shifted": base + passes + p["dispatch_overhead_s"],
        "stream": base + passes + 2 * p["dispatch_overhead_s"],
        "windowed": base * p["windowed_gather_penalty"]
        + p["dispatch_overhead_s"],
    }


def decide_range_engine(W: int, n_elems: int, fits_shifted: bool,
                        fits_stream: bool) -> str:
    """Cheapest *bitwise-safe* range engine.  The three engines differ
    in f32 rounding order (MIGRATION v0.7), so the candidate set is the
    revalidation lattice's singleton — shifted iff it fits, else stream
    iff it fits, else windowed — and a cost argmin over one candidate
    can never flip the engine away from the rule-based pick (the
    bitwise contract wins over the cost model by design).  The
    :func:`range_costs` estimates are therefore NOT computed on this
    per-call path; they surface once per plan via the optimizer's
    engine hoist, which annotates the node for ``explain()``.  ``W``
    and ``n_elems`` stay in the signature as the decision's cost-model
    inputs — a future bitwise-equal engine pair would argmin over
    them."""
    del W, n_elems                       # singleton candidate set
    if fits_shifted:
        return "shifted"
    if fits_stream:
        return "stream"
    return "windowed"


# ----------------------------------------------------------------------
# Fusion and reshard placement — bitwise-equal program shapes
# ----------------------------------------------------------------------

def fusion_worthwhile(n_ops: int, est_bytes: int) -> Tuple[bool, dict]:
    """Should a mesh ``asofJoin -> withRangeStats [-> EMA]`` run fuse
    into ONE jitted program (plan/fused.py)?  Both shapes are
    bitwise-identical (the fused program pins its op boundaries with
    optimization_barriers), so the decision is free: fused saves
    ``n_ops - 1`` dispatches and the between-op HBM re-reads; the
    ``fused_overhead_s`` input charges whatever a measured profile says
    one-program execution costs extra (0 under the priors — fusion
    always wins, today's rule)."""
    p = params()
    re_read = float(est_bytes) / p["hbm_stream_rate"]
    cost_chain = n_ops * p["dispatch_overhead_s"] + (n_ops - 1) * re_read
    cost_fused = p["dispatch_overhead_s"] + p["fused_overhead_s"]
    return cost_fused <= cost_chain, {
        "fused_s": cost_fused, "chain_s": cost_chain, "n_ops": n_ops}


def stitch_worthwhile(n_ops: int, est_bytes: int) -> Tuple[bool, dict]:
    """Should a maximal run of ``n_ops`` adjacent series-local planned
    ops (resample / interpolate / EMA / range stats / calc_bars) stitch
    into ONE jitted program (plan/stitch.py)?  Same shape as
    :func:`fusion_worthwhile` — both forms are bitwise-identical (the
    stitched program pins every op boundary with
    ``jax.lax.optimization_barrier``), so the decision is free: the
    op-by-op chain pays ``n_ops`` dispatches plus the between-op HBM
    re-reads of the intermediate frame; the stitched program pays one
    dispatch plus ``fused_overhead_s`` (0 under the priors — stitching
    always wins, and a measured profile can charge it)."""
    p = params()
    re_read = float(est_bytes) / p["hbm_stream_rate"]
    cost_chain = n_ops * p["dispatch_overhead_s"] + (n_ops - 1) * re_read
    cost_stitched = p["dispatch_overhead_s"] + p["fused_overhead_s"]
    return cost_stitched <= cost_chain, {
        "stitched_s": cost_stitched, "chain_s": cost_chain,
        "n_ops": n_ops}


def reshard_decision(n_placed: int, placed_bytes: Optional[int],
                     n_internal: int,
                     internal_bytes: Optional[int]) -> Tuple[bool, dict]:
    """Should the optimizer place explicit ``reshard`` plan nodes
    around this plan's series-local runs (vs leaving each op its
    internal all_to_all pair — ``declarative`` execution)?  Both
    placements are bitwise-identical (round 10's elimination contract),
    so the decision is free: per-switch comm seconds from the relayout
    byte model over the ICI rate, plus ``reshard_dispatch_s`` for each
    placed node (a separate program dispatch; internal pairs ride
    inside the op's own program).  Byte models unavailable (geometry
    not derivable at plan time) fall back to switch counts.  Under the
    priors placement wins whenever it eliminates at least one switch —
    today's rule."""
    p = params()
    if placed_bytes is not None and internal_bytes is not None:
        placed_s = placed_bytes / p["ici_rate"] \
            + n_placed * p["reshard_dispatch_s"]
        internal_s = internal_bytes / p["ici_rate"]
    else:
        # count-only fallback: a nominal 1 MiB per switch (the byte
        # model is unavailable, the *ratio* of switch counts decides)
        per_switch = float(1 << 20) / p["ici_rate"]
        placed_s = n_placed * (per_switch + p["reshard_dispatch_s"])
        internal_s = n_internal * per_switch
    return placed_s <= internal_s, {
        "placed_s": placed_s, "declarative_s": internal_s,
        "n_placed": n_placed, "n_internal_switches": n_internal}
