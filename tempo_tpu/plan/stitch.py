"""Whole-chain program stitching: a maximal run of adjacent
series-local planned ops as ONE jitted executable.

``plan/fused.py`` covers exactly one chain shape (asofJoin ->
withRangeStats [-> EMA]).  This module covers the general case the
optimizer's ``_stitch_chains`` pass collapses: any single-consumer run
of resample / interpolate / EMA / withRangeStats / calc_bars over a
mesh frame, executed as one dispatch instead of one per op.  Stage-N
out_shardings equal stage-N+1 in_shardings by contract (every op here
is series-local under the run-time guards), so the stitched program is
just the composition of the SAME ``lru_cache``'d kernel factories the
eager methods call (``dist._resample_fn`` / ``dist._interp_fn`` /
``dist._ema_local`` / ``dist._range_stats_local_packed``) — nested
jits inline under the outer trace — with
``jax.lax.optimization_barrier`` over the live plane set at every op
boundary.  The barriers pin each op's outputs to the same
fusion-cluster roots the op-by-op chain has (the eager chain
materialises them between dispatches), so stitched == op-by-op is
BITWISE: XLA cannot re-fuse producer arithmetic into a consumer stage
and flip an FMA-contraction decision in the last ulp.

Execution is two phases:

* **Plan** (host, per ``run`` call): a tiny metadata interpreter
  (:class:`_Sim`) replays each stage's host-side decisions EXACTLY as
  the eager method makes them — column selection, bucket step, fkey /
  mkey lookup, engine choice, the layout-vouched static grid bound G —
  and records a pure-data *recipe*: program inputs (frame planes
  promoted on first consumption), one emit descriptor per device
  dispatch the eager chain would make (calc_bars contributes its four
  resamples; a non-resampled interpolate contributes its internal
  resample), the per-boundary live key sets, and the output planes.
  Any decision that is not host-static under the guards — a
  device-fetched grid bound, audited rowbounds (device scalars the
  eager path fetches at collect), a consumed host-gather/ts-chunk
  plane — raises :class:`_Refuse`, ``run`` returns None, and the
  executor replays the chain op-by-op through the eager methods
  instead (still planned + cached, just not single-program — and any
  real argument error surfaces with the eager message).
* **Emit** (device, one dispatch): :func:`_stitched_program` builds
  the jitted program from the recipe.  Recipes are hashable and the
  builder is ``lru_cache``'d, so re-running a cached plan executable
  re-uses the compiled program — zero recompiles at steady state, the
  same property the per-op factories have.

The untouched-column discipline mirrors the eager methods exactly:
a column the chain never rewrites rides through BY REFERENCE (eager's
``new_cols = dict(self.cols)`` keeps the DistCol object), never
through the program.
"""

from __future__ import annotations

import functools
import logging
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from tempo_tpu import packing
from tempo_tpu.plan import ir

logger = logging.getLogger(__name__)

#: ops the stitcher may collapse (all single-input, all series-local
#: under the run-time guards; calc_bars is a macro over resample +
#: interpolate)
STITCHABLE_OPS = ("resample", "interpolate", "ema", "range_stats",
                  "calc_bars")


class _Refuse(Exception):
    """A stage decision is not host-static under the stitched-program
    guards — fall back to the op-by-op replay."""


class _Plane:
    """One [K, L] device plane threaded through the stitched program.
    Concrete (``ref`` = the frame's array, promoted to a program input
    on first consumption) or traced (``key`` only, produced by an
    emit)."""

    __slots__ = ("key", "ref")

    def __init__(self, key=None, ref=None):
        self.key = key
        self.ref = ref


class _Col:
    """A column's value/validity planes plus the original DistCol when
    it is still carried by reference (never rewritten by the chain)."""

    __slots__ = ("v", "g", "int64", "src")

    def __init__(self, v, g, int64=False, src=None):
        self.v = v
        self.g = g
        self.int64 = int64
        self.src = src


class _Sim:
    """Plan-time frame-metadata simulator.  Replays the host-side half
    of each eager op over plane handles instead of arrays and records
    the emit descriptors the device half becomes."""

    def __init__(self, frame, sort_kernels: bool):
        self.frame = frame
        self.sort_kernels = bool(sort_kernels)
        self.K_dev = int(frame.K_dev)
        self.L = int(frame.L)
        self.n_series_shards = int(frame.n_series_shards)
        self.resampled = bool(frame.resampled)
        self.resample_freq = frame._resample_freq
        self.grid_replaced = False
        self.ts = _Plane(ref=frame.ts)
        self.mask = _Plane(ref=frame.mask)
        self.cols: Dict[str, _Col] = {
            name: _Col(_Plane(ref=c.values), _Plane(ref=c.valid),
                       int64=c.int64, src=c)
            for name, c in frame.cols.items()
        }
        self._next = 0
        self.in_keys: List[int] = []
        self.in_arrays: List[object] = []
        #: (descriptor, read keys, written keys) per device dispatch
        self.emits: List[Tuple[tuple, Tuple[int, ...], Tuple[int, ...]]] = []

    # -- plane bookkeeping ---------------------------------------------

    def _key(self) -> int:
        self._next += 1
        return self._next

    def _promote(self, plane: _Plane) -> int:
        if plane.key is None:
            plane.key = self._key()
            self.in_keys.append(plane.key)
            self.in_arrays.append(plane.ref)
        return plane.key

    def _consume_col(self, name: str) -> Tuple[int, int]:
        col = self.cols.get(name)
        if col is None:
            raise _Refuse(f"column {name!r} not on the frame")
        if col.src is not None and (col.src.ts_chunk is not None
                                    or col.src.host_gather is not None):
            # eager CAN stack these planes, but the result frame's
            # metadata handling is not worth simulating — replay
            raise _Refuse(f"column {name!r} rides a non-plain plane")
        return self._promote(col.v), self._promote(col.g)

    def numeric_names(self) -> List[str]:
        # dist.numeric_columns: plain device planes only.  Traced
        # (chain-produced) columns are always plain.
        return [n for n, c in self.cols.items()
                if c.src is None or (c.src.ts_chunk is None
                                     and c.src.host_gather is None)]

    def _emit(self, desc: tuple, reads, writes) -> None:
        self.emits.append((desc, tuple(reads), tuple(writes)))

    # -- per-op planners (each replicates its eager method's host half)

    def sim_resample(self, freq, func, metricCols) -> None:
        from tempo_tpu import dist
        from tempo_tpu.freq import (freq_to_seconds, average, ceiling,
                                    floor, max_func, min_func)

        try:
            step = int(freq_to_seconds(freq) * packing.NS_PER_S)
            fkey = {floor: 0, ceiling: 1, average: 2, min_func: 3,
                    max_func: 4}[dist._canon_func(func)]
        except Exception as e:
            raise _Refuse(f"resample args: {e}")
        cols = list(metricCols) if metricCols else self.numeric_names()
        if not cols:
            raise _Refuse("resample over zero columns")
        ts_k = self._promote(self.ts)
        mask_k = self._promote(self.mask)
        in_cols = tuple(self._consume_col(c) for c in cols)
        o_ts, o_mask = self._key(), self._key()
        o_cols = tuple((self._key(), self._key()) for _ in cols)
        self._emit(
            ("resample", step, fkey, self.sort_kernels, ts_k, mask_k,
             in_cols, o_ts, o_mask, o_cols),
            reads=[ts_k, mask_k] + [k for vg in in_cols for k in vg],
            writes=[o_ts, o_mask] + [k for vg in o_cols for k in vg])
        self.ts = _Plane(key=o_ts)
        self.mask = _Plane(key=o_mask)
        self.cols = {c: _Col(_Plane(key=vk), _Plane(key=gk))
                     for c, (vk, gk) in zip(cols, o_cols)}
        self.resampled = True
        self.resample_freq = freq
        self.grid_replaced = True

    def sim_ema(self, colName, window, exp_factor, exact,
                inclusive_window) -> None:
        vk, gk = self._consume_col(colName)
        n_taps = int(window) + (1 if inclusive_window else 0)
        out = self._key()
        self._emit(("ema", float(exp_factor), bool(exact), n_taps,
                    vk, gk, out),
                   reads=[vk, gk], writes=[out])
        # eager: new_cols["EMA_" + colName] = DistCol(y, self.mask) —
        # the validity IS the current mask plane (shared)
        self.cols["EMA_" + colName] = _Col(_Plane(key=out), self.mask)

    def sim_range_stats(self, colsToSummarize, rangeBackWindowSecs,
                        strategy) -> None:
        from tempo_tpu import dist

        if strategy not in ("exact", "halo"):
            raise _Refuse(f"strategy {strategy!r}")
        cols = (list(colsToSummarize) if colsToSummarize
                else self.numeric_names())
        w = float(rangeBackWindowSecs)
        if not cols:
            # eager no-ops (dict copy, no kernel)
            return
        if strategy == "exact" and self.sort_kernels:
            # dist._range_engine_choice: host-layout rowbounds feed the
            # three-way engine pick; the shifted-window form's audits
            # are device scalars the eager path defers to collect —
            # keep those out of stitched programs
            lay = self.frame.layout
            rb = None
            if (not self.resampled and lay.n_rows > 0
                    and int(lay.starts[-1]) == lay.n_rows):
                rb = packing.layout_rowbounds(lay, w)
            shard_k = self.K_dev // max(self.n_series_shards, 1)
            engine, rowbounds = dist._pick_range_engine_for_shard(
                shard_k, self.L, rb)
            if rowbounds is not None:
                raise _Refuse("row-bounded stats window carries a "
                              "deferred clip audit")
        else:
            engine = "shifted"
        ts_k = self._promote(self.ts)
        in_cols = tuple(self._consume_col(c) for c in cols)
        outs = tuple(tuple(self._key() for _ in packing.RANGE_STATS)
                     for _ in cols)
        self._emit(
            ("stats", w, self.sort_kernels, engine, ts_k, in_cols, outs),
            reads=[ts_k] + [k for vg in in_cols for k in vg],
            writes=[k for per_col in outs for k in per_col])
        for ci, c in enumerate(cols):
            for si, stat in enumerate(packing.RANGE_STATS):
                self.cols[f"{stat}_{c}"] = _Col(
                    _Plane(key=outs[ci][si]), self.mask,
                    int64=(stat == "count"))

    def sim_interpolate(self, freq, func, method, target_cols,
                        show_interpolated) -> None:
        from tempo_tpu.freq import freq_to_seconds, validateFuncExists

        if method not in ("zero", "null", "ffill", "bfill", "linear"):
            raise _Refuse(f"method {method!r}")
        if self.resampled:
            freq = freq or self.resample_freq
            if freq != self.resample_freq:
                raise _Refuse("freq mismatch on a resampled frame")
        if freq is None:
            raise _Refuse("interpolate requires freq")
        cols = (list(target_cols) if target_cols
                else self.numeric_names())
        if not cols:
            raise _Refuse("interpolate over zero columns")
        if not self.resampled:
            try:
                validateFuncExists(func)
            except Exception as e:
                raise _Refuse(f"interpolate func: {e}")
            # eager: res = self.resample(freq, func, metricCols=cols) —
            # a separate device dispatch, so a separate emit here
            self.sim_resample(freq, func, tuple(cols))
        step = int(freq_to_seconds(freq) * packing.NS_PER_S)
        # static grid bound: ONLY the layout-vouched host path is
        # stitchable; the eager fallback fetches [K] device scalars
        lay = self.frame.layout
        if not (lay.n_rows > 0 and int(lay.starts[-1]) == lay.n_rows):
            raise _Refuse("grid bound needs a device fetch")
        spans = []
        for k in range(lay.n_series):
            s = lay.ts_ns[lay.starts[k]: lay.starts[k + 1]]
            if len(s):
                spans.append(int(s[-1] - s[0]))
        span = max(spans, default=0)
        G = span // step + 2
        G = max(8, -(-G // 8) * 8)
        mkey = ("zero", "null", "ffill", "bfill", "linear").index(method)
        flags = bool(show_interpolated)
        ts_k = self._promote(self.ts)
        mask_k = self._promote(self.mask)
        in_cols = tuple(self._consume_col(c) for c in cols)
        o_ts, o_mask = self._key(), self._key()
        o_cols = tuple((self._key(), self._key()) for _ in cols)
        o_fts = self._key() if flags else None
        o_fcols = tuple(self._key() for _ in cols) if flags else ()
        writes = [o_ts, o_mask] + [k for vg in o_cols for k in vg]
        if flags:
            writes += [o_fts] + list(o_fcols)
        self._emit(
            ("interp", step, G, mkey, flags, ts_k, mask_k, in_cols,
             o_ts, o_mask, o_cols, o_fts, o_fcols),
            reads=[ts_k, mask_k] + [k for vg in in_cols for k in vg],
            writes=writes)
        self.ts = _Plane(key=o_ts)
        self.mask = _Plane(key=o_mask)
        new_cols = {c: _Col(_Plane(key=vk), _Plane(key=gk))
                    for c, (vk, gk) in zip(cols, o_cols)}
        if flags:
            new_cols["is_ts_interpolated"] = _Col(
                _Plane(key=o_fts), self.mask, int64=True)
            for c, fk in zip(cols, o_fcols):
                new_cols[f"is_interpolated_{c}"] = _Col(
                    _Plane(key=fk), self.mask, int64=True)
        self.cols = new_cols
        self.L = G
        self.resampled = True
        self.resample_freq = freq
        self.grid_replaced = True

    def sim_calc_bars(self, freq, func, metricCols, fill) -> None:
        mc = list(metricCols) if metricCols else self.numeric_names()
        if not mc:
            raise _Refuse("calc_bars over zero columns")
        # four resamples over the SAME input planes (eager loops
        # self.resample four times), merged by name, sorted — the close
        # (ceil) grid is the one the merged frame physically keeps
        pre_ts, pre_mask, pre_cols = self.ts, self.mask, self.cols
        merged: Dict[str, _Col] = {}
        last = None
        for prefix, f in (("open", "floor"), ("low", "min"),
                          ("high", "max"), ("close", "ceil")):
            self.ts, self.mask, self.cols = pre_ts, pre_mask, dict(pre_cols)
            self.sim_resample(freq, f, tuple(mc))
            for c in mc:
                merged[f"{prefix}_{c}"] = self.cols[c]
            last = (self.ts, self.mask)
        self.ts, self.mask = last
        self.cols = {c: merged[c] for c in sorted(merged)}
        if fill:
            self.sim_interpolate(None, None, "zero", None, False)

    # -- recipe ---------------------------------------------------------

    def recipe(self) -> tuple:
        out_keys: Dict[int, None] = {}

        def want(plane: Optional[_Plane]):
            if plane is not None and plane.ref is None:
                out_keys.setdefault(plane.key)

        want(self.ts)
        want(self.mask)
        for col in self.cols.values():
            if col.src is None:
                want(col.v)
                want(col.g)
        out = tuple(out_keys)
        # per-boundary live sets: keys any later emit reads (or the
        # program returns), restricted to keys defined by then
        n = len(self.emits)
        defined = set(self.in_keys)
        defined_after = []
        for _, _, writes in self.emits:
            defined |= set(writes)
            defined_after.append(set(defined))
        suffix = set(out)
        barriers: List[Tuple[int, ...]] = [()] * max(n - 1, 0)
        for j in range(n - 1, 0, -1):
            suffix |= set(self.emits[j][1])
            barriers[j - 1] = tuple(sorted(suffix & defined_after[j - 1]))
        return (tuple(self.in_keys),
                tuple(d for d, _, _ in self.emits),
                tuple(barriers), out)


def _plan(frame, stages, sort_kernels: bool) -> _Sim:
    sim = _Sim(frame, sort_kernels)
    for op, params in stages:
        p = dict(params)
        if op == "resample":
            sim.sim_resample(p.get("freq"), p.get("func"),
                             p.get("metricCols"))
        elif op == "ema":
            sim.sim_ema(p.get("colName"), p.get("window", 30),
                        p.get("exp_factor", 0.2), p.get("exact", False),
                        p.get("inclusive_window", False))
        elif op == "range_stats":
            sim.sim_range_stats(p.get("colsToSummarize"),
                                p.get("rangeBackWindowSecs", 1000),
                                p.get("strategy", "exact"))
        elif op == "interpolate":
            sim.sim_interpolate(p.get("freq"), p.get("func"),
                                p.get("method"), p.get("target_cols"),
                                p.get("show_interpolated", False))
        elif op == "calc_bars":
            sim.sim_calc_bars(p.get("freq"), p.get("func"),
                              p.get("metricCols"), p.get("fill"))
        else:
            raise _Refuse(f"op {op!r} is not stitchable")
    return sim


# ----------------------------------------------------------------------
# Device half: the stitched program
# ----------------------------------------------------------------------

def _run_emit(env: dict, em: tuple, mesh, series_axis) -> None:
    from tempo_tpu import dist

    kind = em[0]
    if kind == "resample":
        _, step, fkey, sk, ts_k, mask_k, cols, o_ts, o_mask, o_cols = em
        kernel = dist._resample_fn(mesh, series_axis, None, step, fkey,
                                   len(cols), sk)
        vals = jnp.stack([env[vk] for vk, _ in cols])
        valids = jnp.stack([env[gk] for _, gk in cols])
        new_ts, head, ov, og = kernel(env[ts_k], env[mask_k], vals,
                                      valids)
        env[o_ts], env[o_mask] = new_ts, head
        for i, (vk, gk) in enumerate(o_cols):
            env[vk], env[gk] = ov[i], og[i]
    elif kind == "ema":
        _, alpha, exact, n_taps, vk, gk, out = em
        env[out] = dist._ema_local(mesh, series_axis, alpha, exact,
                                   n_taps)(env[vk], env[gk])
    elif kind == "stats":
        _, w, sk, engine, ts_k, cols, outs = em
        kernel = dist._range_stats_local_packed(mesh, series_axis, w,
                                                None, sk, engine)
        xs = jnp.stack([env[vk] for vk, _ in cols])
        vs = jnp.stack([env[gk] for _, gk in cols])
        stats, _clipped = kernel(env[ts_k], xs, vs)
        for ci in range(len(cols)):
            for si, stat in enumerate(packing.RANGE_STATS):
                env[outs[ci][si]] = stats[stat][ci]
    elif kind == "interp":
        (_, step, G, mkey, flags, ts_k, mask_k, cols, o_ts, o_mask,
         o_cols, o_fts, o_fcols) = em
        kernel = dist._interp_fn(mesh, series_axis, None, step, G, mkey,
                                 len(cols), flags)
        vals = jnp.stack([env[vk] for vk, _ in cols])
        valids = jnp.stack([env[gk] for _, gk in cols])
        out = kernel(env[ts_k], env[mask_k], vals, valids)
        grid_ts, grid_mask, ov, og = out[:4]
        env[o_ts], env[o_mask] = grid_ts, grid_mask
        for i, (vk, gk) in enumerate(o_cols):
            env[vk], env[gk] = ov[i], og[i]
        if flags:
            # eager: DistCol(flag.astype(vals.dtype), ...) — exact
            # bool->float cast, traced here instead of post-dispatch
            env[o_fts] = out[4].astype(vals.dtype)
            for i, fk in enumerate(o_fcols):
                env[fk] = out[5][i].astype(vals.dtype)
    else:  # pragma: no cover - descriptors come from _Sim only
        raise ValueError(f"unknown emit {kind!r}")


@functools.lru_cache(maxsize=64)
def _stitched_program(mesh, series_axis, recipe: tuple):
    """ONE jitted program for the whole recipe.  Between consecutive
    emits the live plane set crosses an ``optimization_barrier`` — the
    op boundaries stay exactly where the op-by-op chain materialises
    its frames, so the stitched result is bitwise-identical while XLA
    still sees one dispatch."""
    in_keys, emits, barriers, out_keys = recipe

    def fn(*inputs):
        env = dict(zip(in_keys, inputs))
        for j, em in enumerate(emits):
            if j and barriers[j - 1]:
                live = barriers[j - 1]
                pinned = jax.lax.optimization_barrier(
                    tuple(env[k] for k in live))
                env.update(zip(live, pinned))
            _run_emit(env, em, mesh, series_axis)
        return tuple(env[k] for k in out_keys)

    return jax.jit(fn)


# ----------------------------------------------------------------------
# Executor entry points
# ----------------------------------------------------------------------

def run(frame, node: ir.Node):
    """Execute a ``stitched`` plan node over one DistributedTSDF, or
    None when a run-time guard fails (the executor then replays the
    chain op-by-op via :func:`run_sequential`)."""
    from tempo_tpu import dist
    from tempo_tpu.dist import DistCol, DistributedTSDF

    if not isinstance(frame, DistributedTSDF):
        return None
    if frame.time_axis is not None:
        # the series-local kernels assert n_time == 1; time-sharded
        # chains reach here only if the reshard pass did not bracket
        # them — replay op-by-op (each eager op reshards itself)
        return None
    stages = node.param("stages") or ()
    try:
        sim = _plan(frame, stages, dist._use_sort_kernels())
    except _Refuse as e:
        logger.debug("plan: stitched chain refused at run time (%s)", e)
        return None
    except (KeyError, ValueError, TypeError) as e:
        logger.debug("plan: stitched chain planning failed (%s)", e)
        return None
    in_keys, emits, barriers, out_keys = recipe = sim.recipe()
    if emits:
        prog = _stitched_program(frame.mesh, frame.series_axis, recipe)
        outs = prog(*sim.in_arrays)
    else:
        outs = ()
    env = dict(zip(out_keys, outs))

    def val(plane: _Plane):
        return plane.ref if plane.ref is not None else env[plane.key]

    new_cols = {}
    for name, col in sim.cols.items():
        if col.src is not None:
            new_cols[name] = col.src      # by-ref ride-through
        else:
            new_cols[name] = DistCol(val(col.v), val(col.g),
                                     int64=col.int64)
    kw: Dict[str, object] = dict(cols=new_cols)
    if sim.ts.ref is None:
        kw["ts"] = env[sim.ts.key]
    if sim.mask.ref is None:
        kw["mask"] = env[sim.mask.key]
    if sim.grid_replaced:
        kw.update(resampled=True, resample_freq=sim.resample_freq,
                  seq=None, seq_col="")
    return frame._with(**kw)


def run_sequential(frame, node: ir.Node):
    """Op-by-op fallback: replay the recorded stages through the eager
    methods (one dispatch per op, same results — and the eager error
    messages — as an unstitched plan)."""
    from tempo_tpu.plan import executor

    cur = frame
    for op, params in node.param("stages") or ():
        cur = executor._eval_op(ir.Node(op, params=dict(params)), [cur])
    return cur
