"""Lazy frame wrappers: record ops as plan nodes, execute on demand.

``TEMPO_TPU_PLAN=1`` makes the recorded op methods of TSDF /
DistributedTSDF return these wrappers instead of executing.  Recorded
ops extend the plan; terminal ops (``collect``, ``.df``,
``to_pandas``, ``count``, ``show``) optimize + execute through the
executable cache.  Any *other* attribute access materialises the chain
recorded so far and delegates to the eager result (logged at debug
level), so the full eager API keeps working under planning — ops
outside the IR simply act as plan boundaries.
"""

from __future__ import annotations

import logging
from typing import Optional

from tempo_tpu.plan import ir

logger = logging.getLogger(__name__)


def _frame_strict(strict) -> bool:
    """The frame layer's strict-SQL resolution (explicit arg >
    TEMPO_TPU_SQL_STRICT > legacy TEMPO_TPU_STRICT_SQL)."""
    from tempo_tpu.frame import _strict_sql

    return _strict_sql(strict)


def _as_node(frame) -> ir.Node:
    """Plan node for an op input: lazy wrappers contribute their
    recorded node; eager frames become fresh source nodes."""
    if isinstance(frame, _LazyBase):
        return frame._node
    from tempo_tpu.dist import DistributedTSDF

    if isinstance(frame, DistributedTSDF):
        return ir.Node("dist_source", payload=frame)
    return ir.Node("source", payload=frame)


def record(frame, op: str, others=(), params=None, objs=None):
    """Entry point for the ``_plan_record`` preambles in frame.py /
    dist.py: build the op node over ``frame`` (+ any other frame
    operands) and wrap it."""
    node = ir.Node(op, params=params, objs=objs,
                   inputs=(_as_node(frame),)
                   + tuple(_as_node(o) for o in others))
    return wrap(node)


def wrap(node: ir.Node):
    """The lazy wrapper class a node's result belongs to: ``on_mesh``
    moves a chain onto the mesh; ops over a mesh chain stay there."""
    mesh_side = node.op == "on_mesh"
    cur = node
    while not mesh_side and cur.inputs:
        cur = cur.inputs[0]
        mesh_side = cur.op in ("on_mesh", "dist_source")
    return (LazyDistributedTSDF if mesh_side else LazyTSDF)(node)


class _LazyBase:
    """Shared recording/terminal machinery."""

    def __init__(self, node: ir.Node):
        self._node = node

    # -- plan access ----------------------------------------------------

    @property
    def plan(self) -> ir.Node:
        return self._node

    def explain(self, cost: bool = False) -> str:
        """Render (and return) the logical + optimized plans, per-node
        engine choices and barriers; ``cost=True`` adds XLA's compiled
        cost analysis for the plan's device segments."""
        from tempo_tpu.plan import render

        text = render.explain_text(self._node, cost=cost)
        print(text)
        return text

    # -- recording helpers ---------------------------------------------

    def _rec(self, op, others=(), params=None, objs=None):
        node = ir.Node(op, params=params, objs=objs,
                       inputs=(self._node,)
                       + tuple(_as_node(o) for o in others))
        return wrap(node)

    def _execute(self, terminal: Optional[str] = None):
        from tempo_tpu.plan import executor

        node = self._node if terminal is None else \
            ir.Node(terminal, inputs=(self._node,))
        return executor.execute(node)

    def __getattr__(self, name):
        # not a recorded op: materialise the chain and delegate — the
        # plan boundary is explicit in the log
        if name.startswith("_"):
            raise AttributeError(name)
        logger.debug(
            "plan: %r is not a recorded op — materialising the lazy "
            "chain and continuing eagerly", name)
        from tempo_tpu import plan as plan_mod

        result = self._execute()
        with plan_mod.suspended():
            return getattr(result, name)

    def __repr__(self):
        chain = " <- ".join(n.op for n in self._node.walk()
                            if not n.is_source())
        return f"{type(self).__name__}({chain or 'source'})"


class LazyTSDF(_LazyBase):
    """Deferred host-frame chain."""

    # -- recorded ops ---------------------------------------------------

    def select(self, *cols):
        if len(cols) == 1 and isinstance(cols[0], (list, tuple)):
            cols = tuple(cols[0])
        return self._rec("select", params=dict(cols=tuple(cols)))

    def withColumn(self, colName: str, values):
        # the value rides in objs for execution; its canonical form (an
        # opaque token for callables/arrays) keys the signature
        return self._rec("with_column",
                         params=dict(colName=colName, values=values),
                         objs=dict(values=values))

    def selectExpr(self, *exprs, strict: Optional[bool] = None):
        from tempo_tpu import sql
        from tempo_tpu.plan import sql_compile

        try:
            lowered, objs = sql_compile.lower_select_exprs(
                exprs, columns=ir.output_columns(self._node))
        except sql.SqlError as e:
            return self._sql_boundary("selectExpr", e, strict,
                                      lambda f: f.selectExpr(*exprs))
        lowered["strict"] = _frame_strict(strict)
        return self._rec("sql_project", params=lowered, objs=objs)

    def filter(self, condition, strict: Optional[bool] = None):
        if not isinstance(condition, str):
            # callable / mask filters are eager-only: plan boundary
            from tempo_tpu import plan as plan_mod

            result = self._execute()
            with plan_mod.suspended():
                return result.filter(condition, strict=strict)
        from tempo_tpu import sql
        from tempo_tpu.plan import sql_compile

        try:
            lowered, objs = sql_compile.lower_filter(
                condition, columns=ir.output_columns(self._node))
        except sql.SqlError as e:
            return self._sql_boundary(
                "filter", e, strict,
                lambda f: f.filter(condition, strict=strict))
        lowered["strict"] = _frame_strict(strict)
        return self._rec("sql_filter", params=lowered, objs=objs)

    where = filter

    def _sql_boundary(self, what, err, strict, cont):
        """An expression outside the SQL grammar under planning: strict
        raises by name; otherwise the chain materialises here and the
        eager fallback engine continues (the logged plan boundary)."""
        from tempo_tpu import plan as plan_mod
        from tempo_tpu import sql

        if _frame_strict(strict):
            raise sql.StrictSqlFallback(
                f"{what} left the compiled SQL surface ({err}); strict "
                f"mode forbids the host-pandas fallback")
        logger.debug(
            "plan: %s is outside the SQL grammar (%s) — materialising "
            "the lazy chain and continuing eagerly", what, err)
        result = self._execute()
        with plan_mod.suspended():
            return cont(result)

    def asofJoin(self, right_tsdf, left_prefix=None, right_prefix="right",
                 tsPartitionVal=None, fraction=0.5, skipNulls=True,
                 sql_join_opt=False, suppress_null_warning=False,
                 maxLookback=0):
        return self._rec("asof_join", (right_tsdf,), params=dict(
            left_prefix=left_prefix, right_prefix=right_prefix,
            tsPartitionVal=tsPartitionVal, fraction=fraction,
            skipNulls=skipNulls, sql_join_opt=sql_join_opt,
            suppress_null_warning=suppress_null_warning,
            maxLookback=maxLookback))

    def withRangeStats(self, type: str = "range", colsToSummarize=None,
                       rangeBackWindowSecs: int = 1000):
        return self._rec("range_stats", params=dict(
            type=type,
            colsToSummarize=tuple(colsToSummarize) if colsToSummarize
            else None,
            rangeBackWindowSecs=rangeBackWindowSecs))

    def EMA(self, colName: str, window: int = 30, exp_factor: float = 0.2,
            exact: bool = False, inclusive_window: bool = False):
        return self._rec("ema", params=dict(
            colName=colName, window=window, exp_factor=exp_factor,
            exact=exact, inclusive_window=inclusive_window))

    def resample(self, freq: str, func=None, metricCols=None, prefix=None,
                 fill=None):
        return self._rec("resample", params=dict(
            freq=freq, func=func,
            metricCols=tuple(metricCols) if metricCols else None,
            prefix=prefix, fill=fill))

    def resampleEMA(self, freq: str, colName: str,
                    exp_factor: float = 0.2):
        return self._rec("resample_ema", params=dict(
            freq=freq, colName=colName, exp_factor=exp_factor))

    def interpolate(self, *args, **kw):
        if self._node.op == "resample":
            # chained _ResampledTSDF signature: (method, target_cols,
            # show_interpolated)
            names = ("method", "target_cols", "show_interpolated")
            p = dict(zip(names, args))
            p.update(kw)
            p.setdefault("target_cols", None)
            p.setdefault("show_interpolated", False)
            if p.get("target_cols"):
                p["target_cols"] = tuple(p["target_cols"])
            return self._rec("interpolate_resampled", params=p)
        names = ("freq", "func", "method", "target_cols", "ts_col",
                 "partition_cols", "show_interpolated")
        p = dict(zip(names, args))
        p.update(kw)
        for n in names:
            p.setdefault(n, False if n == "show_interpolated" else None)
        for key in ("target_cols", "partition_cols"):
            if p.get(key):
                p[key] = tuple(p[key])
        return self._rec("interpolate", params=p)

    def on_mesh(self, mesh=None, time_axis=None, series_axis="series",
                halo_fraction: float = 0.5):
        return self._rec("on_mesh", params=dict(
            time_axis=time_axis, series_axis=series_axis,
            halo_fraction=halo_fraction,
            mesh=ir._mesh_state(mesh)), objs=dict(mesh=mesh))

    # -- terminals ------------------------------------------------------

    @property
    def df(self):
        return self._execute().df

    def to_pandas(self):
        return self._execute().df

    def count(self) -> int:
        return int(self._execute("count"))

    def show(self, n: int = 20, truncate: bool = True,
             vertical: bool = False):
        return self._execute().show(n, truncate, vertical)


class LazyDistributedTSDF(_LazyBase):
    """Deferred mesh chain; ``collect()`` is the explicit
    materialisation barrier that optimizes + executes."""

    def asofJoin(self, right, left_prefix=None, right_prefix="right",
                 tsPartitionVal=None, fraction=0.5, skipNulls=True,
                 sql_join_opt=False, suppress_null_warning=False,
                 maxLookback=0):
        return self._rec("asof_join", (right,), params=dict(
            left_prefix=left_prefix, right_prefix=right_prefix,
            tsPartitionVal=tsPartitionVal, fraction=fraction,
            skipNulls=skipNulls, sql_join_opt=sql_join_opt,
            suppress_null_warning=suppress_null_warning,
            maxLookback=maxLookback))

    def withRangeStats(self, colsToSummarize=None,
                       rangeBackWindowSecs: int = 1000,
                       strategy: str = "exact"):
        return self._rec("range_stats", params=dict(
            colsToSummarize=tuple(colsToSummarize) if colsToSummarize
            else None,
            rangeBackWindowSecs=rangeBackWindowSecs, strategy=strategy))

    rangeStats = withRangeStats

    def EMA(self, colName: str, window: int = 30, exp_factor: float = 0.2,
            exact: bool = False, inclusive_window: bool = False):
        return self._rec("ema", params=dict(
            colName=colName, window=window, exp_factor=exp_factor,
            exact=exact, inclusive_window=inclusive_window))

    def resample(self, freq: str, func: str, metricCols=None):
        return self._rec("resample", params=dict(
            freq=freq, func=func,
            metricCols=tuple(metricCols) if metricCols else None))

    def interpolate(self, freq=None, func=None, method=None,
                    target_cols=None, show_interpolated=False):
        return self._rec("interpolate", params=dict(
            freq=freq, func=func, method=method,
            target_cols=tuple(target_cols) if target_cols else None,
            show_interpolated=show_interpolated))

    def calc_bars(self, freq: str, func=None, metricCols=None,
                  fill=None):
        return self._rec("calc_bars", params=dict(
            freq=freq, func=func,
            metricCols=tuple(metricCols) if metricCols else None,
            fill=fill))

    def fourier_transform(self, timestep: float, valueCol: str):
        return self._rec("fourier", params=dict(
            timestep=timestep, valueCol=valueCol))

    def withLookbackFeatures(self, featureCols, lookbackWindowSize: int,
                             exactSize: bool = True,
                             featureColName: str = "features"):
        # host-materialisation barrier (collect_list semantics) — the
        # optimizer marks it; execution collects like the eager path
        return self._rec("lookback_features", params=dict(
            featureCols=tuple(featureCols),
            lookbackWindowSize=lookbackWindowSize, exactSize=exactSize,
            featureColName=featureColName))

    # -- terminals ------------------------------------------------------

    def collect(self):
        return self._execute("collect")

    def to_pandas(self):
        return self._execute("collect").df

    def count(self) -> int:
        return int(self._execute("count"))

    def show(self, n: int = 20, truncate: bool = True):
        return self._execute("collect").show(n, truncate)
