"""Single-program execution of a mesh ``asofJoin -> withRangeStats
[-> EMA]`` chain.

The eager mesh chain runs one jitted program per op (join, stats, EMA)
plus the alignment programs between them — every dispatch pays the
launch/tunnel latency and re-reads its inputs from HBM.  The optimizer
rewrites the chain onto this module (``fused_asof_stats_ema`` node),
which traces the SAME shard-local kernels the eager ops use
(``dist._asof_planes``, ``dist._range_stats_block_packed``,
``pallas_kernels.ema_scan`` / ``ops.rolling.ema_compat``) into ONE
jitted program: one dispatch, results bitwise-identical to the
op-by-op chain (identical kernel functions over identical inputs),
XLA free to fuse across the op boundaries.

Guards: the fused program covers the plain fast path — series-only
mesh, ``skipNulls=True``, no sequence tie-break, no ``maxLookback``,
no host-resident / resampled / join-derived planes.  ``run`` returns
None when a run-time guard fails and the executor replays the chain
op-by-op instead (still planned + cached, just not single-program).
"""

from __future__ import annotations

import functools
import logging
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from tempo_tpu import packing
from tempo_tpu.plan import ir

logger = logging.getLogger(__name__)

_STATS = packing.RANGE_STATS


def _fusible_frames(dl, dr) -> bool:
    from tempo_tpu.dist import DistributedTSDF

    if not (isinstance(dl, DistributedTSDF)
            and isinstance(dr, DistributedTSDF)):
        return False
    if dl.mesh is not dr.mesh and dl.mesh != dr.mesh:
        return False
    if any(size != 1 for name, size in dl.mesh.shape.items()
           if name != dl.series_axis):
        return False
    if dl.time_axis is not None or dr.time_axis is not None:
        return False
    if dl.partitionCols != dr.partitionCols:
        return False
    if dr.seq is not None or dl.resampled or dr.resampled:
        return False
    if dr.host_cols:
        return False
    plain = lambda cols: all(c.ts_chunk is None and c.host_gather is None
                             for c in cols.values())
    return (plain(dl.cols) and plain(dr.cols)
            and len(dl.cols) > 0 and len(dr.cols) > 0)


def run(dl, dr, node: ir.Node):
    """Execute the fused node over two DistributedTSDFs, or None when a
    run-time guard fails (executor falls back to op-by-op)."""
    if not _fusible_frames(dl, dr):
        return None
    from tempo_tpu import dist
    from tempo_tpu.dist import DistCol

    p = node.param
    lp = p("j_left_prefix")
    rp = p("j_right_prefix") or "right"
    rename = (lambda c: f"{lp}_{c}") if lp else (lambda c: c)

    l_names = list(dl.cols)
    r_names = list(dr.cols)
    joined = {rename(c): ("l", i) for i, c in enumerate(l_names)}
    joined.update({f"{rp}_{c}": ("r", i) for i, c in enumerate(r_names)})

    s_cols = list(p("s_cols") or joined)   # default: all numeric planes
    srcs = []
    for c in s_cols:
        if c not in joined:
            return None
        srcs.append(joined[c])
    ema_src = None
    if p("has_ema"):
        e_col = p("e_col")
        if e_col not in joined:
            return None
        ema_src = joined[e_col]

    w = float(p("s_window", 1000))
    engine, rowbounds, sort_kernels = dl._range_engine_choice(w)
    perm, ok = dist._key_perm(dl.layout.key_frame, dr.layout.key_frame,
                              dl.partitionCols, dl.K_dev)

    from tempo_tpu import resilience

    merged = int(dl.L) + int(dr.L)
    limit = resilience.max_merged_lanes()
    if 0 < limit < merged:
        logger.info(
            "asofJoin(plan-fused): merged width %d exceeds the "
            "single-program limit %d — shard-local joins use the XLA "
            "bitonic oversize engine", merged, limit)

    n_taps = int(p("e_window", 30) or 0) + (1 if p("e_inclusive") else 0)
    program = _fused_program(
        dl.mesh, dl.series_axis, tuple(srcs), w, rowbounds, engine,
        sort_kernels, ema_src, float(p("e_exp_factor", 0.2) or 0.2),
        bool(p("e_exact", False)), n_taps)

    lvals = jnp.stack([dl.cols[c].values for c in l_names])
    lvalids = jnp.stack([dl.cols[c].valid for c in l_names])
    rvals = jnp.stack([dr.cols[c].values for c in r_names])
    rvalids = jnp.stack([dr.cols[c].valid for c in r_names])
    planes, vstack = _right_stacks(dr.ts, dr.mask, rvals, rvalids)
    out = program(dl.ts, lvals, lvalids, dr.ts, planes, vstack,
                  jnp.asarray(perm), jnp.asarray(ok))
    vals, found, stats, clips, ema_y = out

    n = len(r_names)
    new_cols = {rename(c): col for c, col in dl.cols.items()}
    new_host = {rename(c): src for c, src in dl.host_cols.items()}
    for i, c in enumerate(r_names):
        # the null mask is applied OUTSIDE the program, exactly like
        # the eager join does on its program's outputs
        new_cols[f"{rp}_{c}"] = DistCol(
            jnp.where(found[i], vals[i], jnp.nan), found[i],
            int64=dr.cols[c].int64)
    rts_name = f"{rp}_{dr.ts_col}"
    for j, shift in enumerate((42, 21, 0)):
        new_cols[f"__{rts_name}__c{j}"] = DistCol(
            vals[n + j], found[n + j], ts_chunk=(rts_name, shift))
    audits = list(dl.audits)
    for si, c in enumerate(s_cols):
        if rowbounds is not None:
            audits.append((
                f"withRangeStats({c}): %d rows had window frames "
                f"extending past the static row bounds {rowbounds}; "
                f"this is a tempo-tpu bug — please report it",
                clips[si],
            ))
        for ki, stat in enumerate(_STATS):
            new_cols[f"{stat}_{c}"] = DistCol(
                stats[si, ki], dl.mask, int64=(stat == "count"))
    if ema_src is not None:
        new_cols["EMA_" + p("e_col")] = DistCol(ema_y, dl.mask)
    return dl._with(cols=new_cols, audits=audits, host_cols=new_host,
                    ts_col=rename(dl.ts_col), seq=None, seq_col="")


#: ``donate_argnums`` of the fused program — the right-side payload
#: plane stack and its validity stack, freshly built per call by
#: :func:`_right_stacks` (never frame-owned), whose HBM buffers XLA
#: reuses for the equal-shaped ``raw``/``found`` outputs.  A single
#: source of truth: the jit declaration below AND the donation-applied
#: compiled contract (tempo_tpu/plan/contracts.py) both read it.
DONATE_ARGNUMS = (4, 5)


def _right_stacks(r_ts, r_mask, rvals, rvalids):
    """The right side's [n+3, K, L] payload-plane stack (values + the
    three 21-bit ts-chunk planes) and its validity stack.  Built
    OUTSIDE the fused program so both can be donated: each is exactly
    the shape/dtype of a program output (``raw``/``found``), so the
    two biggest input buffers of the chain are reused for the two
    biggest outputs instead of doubling the working set.  Integer
    shift/concat ops only — bitwise identical to the former in-program
    construction."""
    dt = rvals.dtype
    chunk_mask = jnp.int64((1 << 21) - 1)
    ts_chunks = jnp.stack([
        ((r_ts >> shift) & chunk_mask).astype(dt)
        for shift in (42, 21, 0)
    ])
    planes = jnp.concatenate([rvals, ts_chunks])
    vstack = jnp.concatenate(
        [rvalids, jnp.broadcast_to(r_mask[None], (3,) + r_mask.shape)])
    return planes, vstack


@functools.lru_cache(maxsize=64)
def _fused_program(mesh, series_axis: str, stats_srcs: Tuple,
                   w: float, rowbounds, engine: str, sort_kernels: bool,
                   ema_src, alpha: float, exact: bool, n_taps: int):
    """One jitted program for the whole chain.  The global section
    (key-space alignment) and the shard_map'd local section (join
    fill, range stats, EMA scan) compile together; on a series mesh
    the collective-free kernels partition trivially.  The right-side
    stacks arrive pre-built (:func:`_right_stacks`) and DONATED
    (:data:`DONATE_ARGNUMS`): their buffers alias the ``raw``/``found``
    outputs in the compiled executable — verified against the compiled
    HLO by the donation-applied contract rule."""
    from tempo_tpu import dist
    from tempo_tpu.ops import pallas_kernels as pk
    from tempo_tpu.ops import rolling as rk
    from tempo_tpu.parallel.halo import shard_map

    sp2 = dist._spec(mesh, series_axis, None)
    sp3 = dist._spec(mesh, series_axis, None, ndim=3)
    sp4 = dist._spec(mesh, series_axis, None, ndim=4)
    n_stats = len(stats_srcs)

    def local(l_ts, lvals, lvalids, r_ts_al, vstack, pstack):
        raw, found = dist._asof_planes(l_ts, r_ts_al, vstack, pstack,
                                       sort_kernels, 0)
        n = raw.shape[0] - 3
        # op-boundary pinning — the planned==eager contract is BITWISE:
        # the eager chain materialises the join program's outputs
        # between dispatches, and ``raw``/``found`` must leave THIS
        # program in that same raw form (returned below) or XLA re-fuses
        # the join into the downstream stats arithmetic and the
        # FMA-contraction decisions drift in the last ulp at
        # cancellation-sensitive windows.  The barriers pin the stats
        # inputs/outputs to the same cluster roots the op-by-op chain
        # has.  (The fused program still saves the per-op dispatches
        # and the alignment round trips.)
        right_vals, found_b = jax.lax.optimization_barrier(
            (jnp.where(found[:n], raw[:n], jnp.nan), found[:n]))

        def plane(src):
            side, i = src
            if side == "l":
                return lvals[i], lvalids[i]
            return right_vals[i], found_b[i]

        # multi-column payload packing: ONE packed range-stats pass
        # over the [S, K, L] source stack — the timestamp planes cross
        # HBM once per kernel pack instead of once per summarized
        # column.  The packed block fn is the SAME function the eager
        # mesh chain now runs (dist.withRangeStats — per-column math
        # bitwise-identical to the unpacked kernels), so the
        # planned==eager bit-identity contract is preserved by
        # construction.
        planes_sv = [plane(src) for src in stats_srcs]
        xs = jnp.stack([x for x, _ in planes_sv])
        vs = jnp.stack([v for _, v in planes_sv])
        # pin the stats INPUTS too: in the eager chain (ts, xs, vs)
        # are program inputs of the packed stats program — their own
        # cluster roots.  Without this barrier the input-output
        # aliasing that donation declares (DONATE_ARGNUMS) reshapes
        # the stats fusion clusters and the var/stddev FMA-contraction
        # decisions drift in the last ulp, breaking the bitwise
        # planned==eager contract.
        s_ts, xs, vs = jax.lax.optimization_barrier((l_ts, xs, vs))
        st, clipped = dist._range_stats_block_packed(s_ts, xs, vs, w,
                                                     rowbounds, engine)
        # pin the op boundary: in the eager chain the packed stats
        # dict is a program OUTPUT (its own fusion-cluster root); the
        # [S, 7, K, L] stack below would otherwise reshape the
        # clusters and flip FMA-contraction decisions in the
        # var/stddev math — visible as last-ulp drift exactly at the
        # cancellation-sensitive windows
        st = jax.lax.optimization_barrier(st)
        stats = jnp.stack([jnp.stack([st[k][si] for k in _STATS])
                           for si in range(n_stats)])  # [S, 7, K, L]
        clips = jax.lax.psum(clipped, series_axis)     # [S]
        if ema_src is not None:
            x, v = plane(ema_src)
            ema_y = (pk.ema_scan(x, v, alpha) if exact
                     else rk.ema_compat(x, v, n_taps, alpha))
            ema_y = jax.lax.optimization_barrier(ema_y)
        else:
            ema_y = jnp.zeros_like(l_ts, dtype=lvals.dtype)
        return raw, found, stats, clips, ema_y

    sharded = shard_map(
        local, mesh=mesh,
        in_specs=(sp2, sp3, sp3, sp2, sp3, sp3),
        out_specs=(sp3, sp3, sp4, jax.sharding.PartitionSpec(None),
                   sp2))

    def fn(l_ts, lvals, lvalids, r_ts, planes, vstack, perm, ok):
        # key-space alignment (dist._align_fn / _align3_fn bodies)
        r_ts_al = jnp.where(
            ok[:, None],
            jnp.take(r_ts, jnp.clip(perm, 0, r_ts.shape[0] - 1), axis=0),
            jnp.asarray(packing.TS_PAD, r_ts.dtype))
        clip2 = jnp.clip(perm, 0, planes.shape[1] - 1)
        pstack = jnp.where(
            ok[None, :, None], jnp.take(planes, clip2, axis=1),
            jnp.asarray(np.nan, planes.dtype))
        vstack = jnp.where(
            ok[None, :, None], jnp.take(vstack, clip2, axis=1), False)
        return sharded(l_ts, lvals, lvalids, r_ts_al, vstack, pstack)

    # explicit stage shardings: operands arrive exactly as the frames
    # hold them (series-sharded planes, replicated K-sized alignment
    # metadata) and outputs leave pinned to the frame layout — a
    # mis-laid operand raises instead of compiling an implicit reshard
    ns = lambda s: jax.sharding.NamedSharding(mesh, s)
    repl = ns(jax.sharding.PartitionSpec())
    return jax.jit(
        fn,
        in_shardings=(ns(sp2), ns(sp3), ns(sp3), ns(sp2), ns(sp3),
                      ns(sp3), repl, repl),
        out_shardings=(ns(sp3), ns(sp3), ns(sp4), repl, ns(sp2)),
        donate_argnums=DONATE_ARGNUMS)


def compiled_cost(dl, dr, node: ir.Node):
    """XLA cost/memory analysis of the fused program over these frames
    (the ``explain(cost=True)`` numbers)."""
    if not _fusible_frames(dl, dr):
        return None
    from tempo_tpu import dist, profiling

    p = node.param
    lp = p("j_left_prefix")
    rp = p("j_right_prefix") or "right"
    rename = (lambda c: f"{lp}_{c}") if lp else (lambda c: c)
    joined = {rename(c): ("l", i) for i, c in enumerate(dl.cols)}
    joined.update({f"{rp}_{c}": ("r", i) for i, c in enumerate(dr.cols)})
    s_cols = list(p("s_cols") or joined)
    if any(c not in joined for c in s_cols):
        return None
    srcs = tuple(joined[c] for c in s_cols)
    ema_src = joined.get(p("e_col")) if p("has_ema") else None
    if p("has_ema") and ema_src is None:
        return None
    w = float(p("s_window", 1000))
    engine, rowbounds, sort_kernels = dl._range_engine_choice(w)
    perm, ok = dist._key_perm(dl.layout.key_frame, dr.layout.key_frame,
                              dl.partitionCols, dl.K_dev)
    n_taps = int(p("e_window", 30) or 0) + (1 if p("e_inclusive") else 0)
    program = _fused_program(
        dl.mesh, dl.series_axis, srcs, w, rowbounds, engine,
        sort_kernels, ema_src, float(p("e_exp_factor", 0.2) or 0.2),
        bool(p("e_exact", False)), n_taps)
    lvals = jnp.stack([c.values for c in dl.cols.values()])
    lvalids = jnp.stack([c.valid for c in dl.cols.values()])
    rvals = jnp.stack([c.values for c in dr.cols.values()])
    rvalids = jnp.stack([c.valid for c in dr.cols.values()])
    planes, vstack = _right_stacks(dr.ts, dr.mask, rvals, rvalids)
    return profiling.compiled_cost(
        program, dl.ts, lvals, lvalids, dr.ts, planes, vstack,
        jnp.asarray(perm), jnp.asarray(ok))
