"""Executable cache: compiled plan programs keyed by
(optimized-plan signature, source shapes/dtypes, mesh).

Serving millions of repeated queries needs plan-signature caching of
compiled executables, not per-call retrace (ROADMAP north star): the
second invocation of a structurally identical chain over same-shape
frames reuses the cached executable — no re-optimization, no engine
re-pick, and (because the underlying program builders are themselves
keyed caches) zero new XLA compiles.  Counters are surfaced through
:func:`tempo_tpu.profiling.plan_cache_stats`.

The LRU bound is ``TEMPO_TPU_PLAN_CACHE_SIZE`` (default 64; 0 disables
caching entirely).  A shape or dtype change on any source frame is a
different key — a miss by design, since the compiled programs are
shape-specialised.
"""

from __future__ import annotations

import collections
import threading
from typing import Dict, Optional

_DEFAULT_SIZE = 64


def max_size() -> int:
    from tempo_tpu import config

    return config.get_int("TEMPO_TPU_PLAN_CACHE_SIZE", _DEFAULT_SIZE)


class PlanCache:
    """Thread-safe LRU of built executables + hit/miss/evict/build
    counters."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = collections.OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.builds = 0          # executables constructed (cache misses
        #                          + uncacheable plans)
        self.uncacheable = 0     # runs that bypassed the cache entirely

    def lookup(self, key: Optional[tuple]):
        with self._lock:
            if key is None:
                self.uncacheable += 1
                return None
            exe = self._entries.get(key)
            if exe is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return exe

    def insert(self, key: Optional[tuple], exe) -> None:
        with self._lock:
            self.builds += 1
            if key is None:
                return
            bound = max_size()
            if bound <= 0:
                return
            self._entries[key] = exe
            self._entries.move_to_end(key)
            while len(self._entries) > bound:
                self._entries.popitem(last=False)
                self.evictions += 1

    def get_or_build(self, key: Optional[tuple], build):
        """Cached executable for ``key``, invoking ``build()`` (and
        recording the build) on a miss.  The lookup/insert pair every
        steady-state consumer wants — the serving engine's per-bucket
        step programs go through here, so its zero-recompile claim is
        checkable from the same counters as the planner's
        (``profiling.plan_cache_stats``)."""
        exe = self.lookup(key)
        if exe is None:
            exe = build()
            self.insert(key, exe)
        return exe

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "size": len(self._entries),
                "max_size": max_size(),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "builds": self.builds,
                "uncacheable": self.uncacheable,
            }

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.hits = self.misses = self.evictions = 0
            self.builds = self.uncacheable = 0


#: Process-wide executable cache.
CACHE = PlanCache()
