"""Executable cache: compiled plan programs keyed by
(optimized-plan signature, source shapes/dtypes, mesh).

Serving millions of repeated queries needs plan-signature caching of
compiled executables, not per-call retrace (ROADMAP north star): the
second invocation of a structurally identical chain over same-shape
frames reuses the cached executable — no re-optimization, no engine
re-pick, and (because the underlying program builders are themselves
keyed caches) zero new XLA compiles.  Counters are surfaced through
:func:`tempo_tpu.profiling.plan_cache_stats`.

Round 11 made the cache a genuinely shared, multi-tenant structure:

* **single-flight builds** — two tenants missing on the same key
  build ONCE: the first miss claims the key and builds outside the
  lock, later misses wait on its event and then hit the inserted
  entry (a failed build releases the claim so a waiter retries as the
  builder — a poisoned query must not wedge every tenant behind it);
* **per-signature and per-tenant counters** — ``stats()`` breaks the
  totals down by plan signature (``key[0]``) and by the tenant the
  query service installs via :func:`tenant_scope`, so a steady-state
  audit can pin WHICH query shape or client is recompiling.

The LRU bound is ``TEMPO_TPU_PLAN_CACHE_SIZE`` (default 64; 0 disables
caching entirely).  A shape or dtype change on any source frame is a
different key — a miss by design, since the compiled programs are
shape-specialised.
"""

from __future__ import annotations

import collections
import contextlib
import contextvars
import threading
from typing import Dict, Optional

_DEFAULT_SIZE = 64

_TENANT: contextvars.ContextVar[Optional[str]] = contextvars.ContextVar(
    "tempo_tpu_plan_cache_tenant", default=None)


def max_size() -> int:
    from tempo_tpu import config

    return config.get_int("TEMPO_TPU_PLAN_CACHE_SIZE", _DEFAULT_SIZE)


def device_key(mesh=None) -> tuple:
    """Hashable device-placement component of an executable cache key.

    Compiled executables are pinned to concrete devices: the same
    program lowered for a different backend — or sharded over a
    different mesh — is a DIFFERENT executable, and replaying a cached
    one would either crash or silently run with stale placement.  Every
    serving-engine key (per-stream step programs, cohort step programs)
    folds this in; ``mesh=None`` is the single-device form."""
    import jax

    if mesh is None:
        return (jax.default_backend(), None)
    return (jax.default_backend(),
            tuple(sorted(mesh.shape.items())),
            tuple(d.id for d in mesh.devices.flat))


@contextlib.contextmanager
def tenant_scope(tenant: Optional[str]):
    """Attribute cache traffic inside the block to ``tenant`` (the
    query service wraps each query execution; contextvars make the
    attribution per-thread, so concurrent tenants never mix)."""
    token = _TENANT.set(tenant)
    try:
        yield
    finally:
        _TENANT.reset(token)


def _signature_of(key: Optional[tuple]) -> str:
    if isinstance(key, tuple) and key:
        return str(key[0])
    return "uncacheable"


class PlanCache:  # thread-shared
    """Thread-safe LRU of built executables + hit/miss/evict/build
    counters (totals, per-signature, per-tenant) and single-flight
    ``get_or_build``."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries = collections.OrderedDict()  # guarded-by: self._lock
        self._building: Dict[tuple, threading.Event] = {}  # guarded-by: self._lock
        self.hits = 0  # guarded-by: self._lock
        self.misses = 0  # guarded-by: self._lock
        self.evictions = 0  # guarded-by: self._lock
        # builds: executables constructed (cache misses + uncacheable)
        self.builds = 0  # guarded-by: self._lock
        # uncacheable: runs that bypassed the cache entirely
        self.uncacheable = 0  # guarded-by: self._lock
        self.by_signature: Dict[str, Dict[str, int]] = {}  # guarded-by: self._lock
        self.by_tenant: Dict[str, Dict[str, int]] = {}  # guarded-by: self._lock

    # -- counter plumbing (callers hold self._lock) ---------------------

    def _bump(self, key: Optional[tuple], field: str) -> None:  # guarded-by: self._lock
        sig = _signature_of(key)
        self.by_signature.setdefault(
            sig, {"hits": 0, "misses": 0, "builds": 0, "evictions": 0})
        self.by_signature[sig][field] += 1
        tenant = _TENANT.get()
        if tenant is not None and field != "evictions":
            self.by_tenant.setdefault(
                tenant, {"hits": 0, "misses": 0, "builds": 0})
            self.by_tenant[tenant][field] += 1

    def _hit_locked(self, key: tuple):  # guarded-by: self._lock
        """LRU-touch + hit bookkeeping for a present entry (caller
        holds the lock) — the ONE hit path shared by :meth:`lookup`
        and :meth:`get_or_build`, so the counters the zero-recompile
        audits read cannot diverge between them."""
        exe = self._entries.get(key)
        if exe is None:
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        self._bump(key, "hits")
        return exe

    def lookup(self, key: Optional[tuple]):
        with self._lock:
            if key is None:
                self.uncacheable += 1
                return None
            exe = self._hit_locked(key)
            if exe is None:
                self.misses += 1
                self._bump(key, "misses")
            return exe

    def insert(self, key: Optional[tuple], exe) -> None:
        with self._lock:
            self.builds += 1
            self._bump(key, "builds")
            if key is None:
                return
            bound = max_size()
            if bound <= 0:
                return
            self._entries[key] = exe
            self._entries.move_to_end(key)
            while len(self._entries) > bound:
                evicted, _ = self._entries.popitem(last=False)
                self.evictions += 1
                self._bump(evicted, "evictions")

    def get_or_build(self, key: Optional[tuple], build):
        """Cached executable for ``key``, invoking ``build()`` (and
        recording the build) on a miss.  The lookup/insert pair every
        steady-state consumer wants — the serving engine's per-bucket
        step programs and the query service's per-signature executables
        both go through here, so their zero-recompile claims are
        checkable from the same counters
        (``profiling.plan_cache_stats``).

        SINGLE-FLIGHT: concurrent misses on one key serialize on a
        per-key event — exactly one caller builds, the rest wait and
        take the inserted entry as a (late) hit.  A build that raises
        releases the claim before re-raising, so one waiter retries as
        the new builder instead of every tenant inheriting the
        failure."""
        if key is None:
            self.lookup(key)         # counts the uncacheable bypass
            exe = build()
            self.insert(key, exe)
            return exe
        while True:
            claimed: Optional[threading.Event] = None
            with self._lock:
                exe = self._hit_locked(key)
                if exe is not None:
                    return exe
                waiting = self._building.get(key)
                if waiting is None:
                    claimed = self._building[key] = threading.Event()
                    self.misses += 1
                    self._bump(key, "misses")
            if claimed is None:
                waiting.wait()
                continue
            try:
                # insert() stays INSIDE the claim window: if it raises
                # (e.g. a malformed cache-size env var), the claim must
                # still release or every waiter on this key hangs
                # forever in wait()
                exe = build()
                self.insert(key, exe)
                return exe
            finally:
                with self._lock:
                    self._building.pop(key, None)
                claimed.set()

    def stats(self) -> Dict[str, object]:
        with self._lock:
            return {
                "size": len(self._entries),
                "max_size": max_size(),
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "builds": self.builds,
                "uncacheable": self.uncacheable,
                "by_signature": {s: dict(c)
                                 for s, c in self.by_signature.items()},
                "by_tenant": {t: dict(c)
                              for t, c in self.by_tenant.items()},
            }

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self.hits = self.misses = self.evictions = 0
            self.builds = self.uncacheable = 0
            self.by_signature = {}
            self.by_tenant = {}


#: Process-wide executable cache.
CACHE = PlanCache()
