"""``explain()`` rendering: logical plan, optimized plan, per-node
engine choices, barriers, and (``cost=True``) XLA's compiled cost
analysis — the analog of the reference's ``explain cost`` display path
(python/tempo/tsdf.py).
"""

from __future__ import annotations

from typing import List

from tempo_tpu.plan import ir, optimizer


def _param_str(node: ir.Node) -> str:
    parts = []
    for k, v in node.params:
        if v is None or k == "mesh":
            continue
        if ir.is_opaque(v):
            v = "<opaque>"
        parts.append(f"{k}={v!r}")
    return ", ".join(parts)


def _node_line(node: ir.Node) -> str:
    if node.op == "source":
        t = node.payload
        cols = node.ann.get("prune_to") or tuple(t.df.columns)
        line = (f"source[host] rows={len(t.df)} ts={t.ts_col!r} "
                f"keys={t.partitionCols} cols={list(cols)}")
        if node.ann.get("pruned"):
            line += f"  ! pruned before packing: {list(node.ann['pruned'])}"
        return line
    if node.op == "dist_source":
        p = node.payload
        axes = dict(p.mesh.shape)
        return (f"source[mesh {axes}] packed=[{p.K_dev}, {p.L}] "
                f"cols={list(p.cols)}")
    if node.op == "unified_scan":
        p = node.payload
        return (f"unified_scan[{p.table.name!r} v{p.table.version}] "
                f"history+live under one watermark "
                f"ts={p.ts_col!r} keys={list(p.partitionCols)} "
                f"cols={list(p.columns)}")
    if node.op == "ema_stream":
        return (f"ema_stream[{node.param('colName')!r} "
                f"alpha={node.param('exp_factor')}]  <- CANONICALIZED: "
                f"sequential split-invariant EMA kernel (resumable "
                f"bitwise by the serving carry)")
    if node.op == "reshard":
        line = f"reshard[{node.param('target')}]"
        model = node.ann.get("comm_bytes_model")
        line += ("  <- PLACED: explicit all_to_all layout switch"
                 + (f", ~{model} B/shard modeled comm" if model else ""))
        return line
    if node.op == "checkpoint":
        line = f"checkpoint[step {node.param('step')}]"
        est = node.ann.get("ckpt_bytes_est")
        line += ("  <- PLACED: plan barrier (signed step manifest, "
                 "resume point)"
                 + (f", ~{est} B est" if est else ""))
        return line
    if node.op == "stitched":
        ops = [op for op, _ in (node.param("stages") or ())]
        line = (f"stitched[{' -> '.join(ops)}]  <- STITCHED: "
                f"{len(ops)} ops -> 1 dispatch "
                f"(optimization_barrier-pinned boundaries)")
        sc = node.ann.get("stitch_cost")
        if sc:
            line += (f"; cost-decided: {sc['decision']} "
                     f"(stitched~{sc['stitched_s'] * 1e6:.1f}us vs "
                     f"chain~{sc['chain_s'] * 1e6:.1f}us)")
        return line
    if node.op == "sql_project":
        aliases = node.param("aliases", ())
        line = f"sql_project[{', '.join(aliases)}]"
    elif node.op == "sql_filter":
        line = f"sql_filter[{node.param('condition')}]"
    else:
        line = f"{node.op}({_param_str(node)})"
    notes = []
    if "sql_eval" in node.ann:
        notes.append(f"eval[sql]={node.ann['sql_eval']}")
    if "reshard_eliminated" in node.ann:
        notes.append(f"reshard ELIMINATED: {node.ann['reshard_eliminated']}")
    if "reshard_note" in node.ann:
        notes.append(node.ann["reshard_note"])
    if "join_engine" in node.ann:
        est = node.ann.get("merged_lanes_est")
        notes.append(f"engine[join]={node.ann['join_engine']}"
                     + (f" (~{est} merged lanes)" if est else ""))
    if "range_engine" in node.ann:
        notes.append(f"engine[stats]={node.ann['range_engine']}")
    if "cost" in node.ann:
        notes.append("est cost: " + ", ".join(
            f"{k}~{v * 1e6:.1f}us" for k, v in node.ann["cost"].items()))
    if "fusion_cost" in node.ann:
        fc = node.ann["fusion_cost"]
        notes.append(
            f"cost-decided fusion: {fc['decision']} "
            f"(fused~{fc['fused_s'] * 1e6:.1f}us vs "
            f"chain~{fc['chain_s'] * 1e6:.1f}us)")
    if "stitch_cost" in node.ann:
        sc = node.ann["stitch_cost"]
        notes.append(
            f"cost-decided stitch: {sc['decision']} "
            f"(stitched~{sc['stitched_s'] * 1e6:.1f}us vs "
            f"chain~{sc['chain_s'] * 1e6:.1f}us)")
    if "rewrite" in node.ann:
        notes.append(f"rewrite: {node.ann['rewrite']}")
    if "barrier" in node.ann:
        notes.append(f"BARRIER: {node.ann['barrier']}")
    if notes:
        line += "  <- " + "; ".join(notes)
    return line


def _tree(node: ir.Node, depth: int = 0, out: List[str] = None) -> List[str]:
    out = [] if out is None else out
    prefix = "" if depth == 0 else "   " * (depth - 1) + "+- "
    out.append(prefix + _node_line(node))
    for child in node.inputs:
        _tree(child, depth + 1, out)
    return out


def explain_text(root: ir.Node, cost: bool = False) -> str:
    opt = optimizer.optimize(root)
    lines = ["== Logical plan =="]
    lines += _tree(root)
    lines += ["", "== Optimized plan =="]
    lines += _tree(opt)
    barriers = [n.op for n in opt.walk() if "barrier" in n.ann]
    lines += ["", "barriers: " + (", ".join(barriers) if barriers
                                  else "none (chain stays on device)")]
    rc = opt.ann.get("reshard_cost")
    if rc:
        lines += [f"reshard placement: cost-decided -> {rc['decision']} "
                  f"(placed~{rc['placed_s'] * 1e6:.1f}us vs "
                  f"declarative~{rc['declarative_s'] * 1e6:.1f}us, "
                  f"{rc['n_placed']} placed vs "
                  f"{rc['n_internal_switches']} internal switches)"]
    if cost:
        lines += ["", "== Compiled cost (XLA) =="]
        lines += _cost_lines(opt)
    from tempo_tpu.plan import cache

    st = cache.CACHE.stats()
    lines += ["plan cache: %d/%s entries, %d hits, %d misses, "
              "%d evictions" % (st["size"], st["max_size"], st["hits"],
                                st["misses"], st["evictions"])]
    return "\n".join(lines)


def _cost_lines(opt: ir.Node) -> List[str]:
    """profiling.compiled_cost numbers for the plan's fused device
    segment (host ops have no XLA program to cost)."""
    from tempo_tpu import profiling
    from tempo_tpu.plan import executor, fused

    out = []
    for n in opt.walk():
        if n.op != "fused_asof_stats_ema":
            continue
        # evaluate the two (source-side) inputs to concrete frames so
        # the program can be lowered at the real shapes
        try:
            frames = []
            for child in n.inputs:
                child_exe = executor.Executable(child)
                frames.append(child_exe.run(
                    [s.payload for s in child.sources()]))
            c = fused.compiled_cost(frames[0], frames[1], n)
        except Exception as e:  # pragma: no cover - backend-specific
            out.append(f"fused_asof_stats_ema: cost unavailable ({e})")
            continue
        if c is None:
            out.append("fused_asof_stats_ema: cost unavailable "
                       "(run-time guard failed)")
            continue
        out.append("fused_asof_stats_ema: "
                   + ", ".join(f"{k}={v}" for k, v in c.items()
                               if v is not None))
    if not out:
        out.append("no fused device segment in this plan — per-op "
                   "programs are costed by profiling.compiled_cost at "
                   "execution time")
    for n in opt.walk():
        if n.op == "source":
            out.append(f"source[host]: host_bytes="
                       f"{profiling.host_bytes(n.payload.df)}")
    return out
