"""Compiled-artifact contracts: the guarantees a production program
makes about what XLA *actually compiled*, declared next to the
programs and machine-checked by the compiled-contract analyzer tier
(``python tools/analyze.py --compiled``, ``tools/analysis/compiled``).

The AST tier (``tools/analysis``) reasons about source; everything the
rebuild promises *about executables* — bitwise identity across
backends (no f64 creep under the f32 policy), comm-bytes models,
donation, stage-chained shardings, zero host round-trips — lives in
the lowered/compiled artifact and can drift without any source-level
symptom.  ``profiling.comm_bytes_from_compiled`` proved compiled-HLO
introspection works (the dryrun's comm audit); this module promotes it
to a first-class tier:

* :class:`Contract` — the declared guarantees of one program:
  collective inventory (modeled bytes per kind, checked within
  :data:`tempo_tpu.profiling.COLLECTIVE_TOLERANCE`), ``donate_argnums``
  that must appear as input-output aliases, f64/host-transfer
  allowances.
* :func:`register` — a builder per production program, compiling it at
  small representative shapes (``TEMPO_TPU_CONTRACT_LANES`` is the
  compile-shape budget) on the current backend — on CPU that is the
  dryrun-style virtual mesh, with the TPU kernel forms
  (``sort_kernels=True``, f32 planes) so the checked artifact is the
  production shape of the program, not the golden-parity shape.
* :class:`Chain` — declared stage wiring of multi-program pipelines:
  stage N's out-sharding must equal stage N+1's in-sharding (the
  static precondition of sharding-matched program chaining, ROADMAP
  item 2).

Registry coverage map (program -> production user):

==============================  =======================================
``fused.asof_stats_ema``        the planner's ONE-program chain
                                (plan/fused.py; executor.py replays the
                                rest through the dist factories below)
``dist.align3`` /               the eager + executor-replayed mesh
``dist.asof_local`` /           asofJoin -> withRangeStats -> EMA chain
``dist.range_stats_local`` /    (dist.py shard_map factories; also the
``dist.ema_local``              ``plan.mesh_chain`` sharding chain —
                                join/stats now DONATE their consumed
                                stage-N-1 stacks, round 10)
``dist.range_stats_windowed``   the data-independent windowed fallback
``halo.range_stats`` /          the time-sharded halo kernels
``halo.asof`` / ``halo.ema``    (parallel/halo.py; dryrun audit twin)
``reshard.series_to_time`` /    the explicit all_to_all layout
``reshard.time_to_series``      switches (parallel/reshard.py)
``reshard.plan_node``           the planner's first-class reshard node
                                executor (dist.reshard_frame: the
                                whole-frame series-local switch the
                                eager time-sharded stats/resample/
                                fourier/interpolate paths now share)
``engine.join_single`` /        the ``pick_join_engine`` /
``engine.join_bitonic`` /       ``pick_range_engine`` XLA engine forms
``engine.range_shifted`` /      (ops/sortmerge.py, ops/pallas_merge.py
``engine.range_windowed``       bitonic network, ops/rolling.py RMQ)
``serve.step``                  the online serving engine's
                                steady-state push step
                                (tempo_tpu/serve/state.py: AS-OF +
                                EMA + window carries, donated)
``serve.cohort_push`` /         the fleet-serving cohort engine's
``serve.cohort_query``          mesh-sharded step programs
                                (serve/cohort.py: [S, ...] stream-axis
                                state, whole-state donation, ZERO
                                collectives — stream-parallel by
                                construction) + the ``serve.cohort_
                                loop`` chain pinning that the step's
                                out-shardings ARE its own (and the
                                query's) in-shardings.  The tiered
                                member-state spill (spill_dir +
                                resident_budget) adds NO device
                                program: spill/fault-in are host-side
                                slot copies around the same
                                ``serve.cohort_push`` step, so its
                                contracts cover the spilling cohort
                                unchanged
``service.dispatch_stats`` /    the query service's steady-state
``service.dispatch_ema``        dispatch programs: the cached planner
                                executables (plan/fused.py) at the
                                service bench's two canonical mesh
                                query shapes (stats-only and
                                stats+EMA), donation + alignment
                                collectives pinned
``standing.step``               the standing-query engine's
                                incremental EMA step: the serving push
                                program at the canonical standing
                                config (EMA carry, no window/lookback
                                planes — query/standing.py's shared
                                subscription plane), donated retired
                                state, zero per-push collectives
``standing.unified_scan``       the ``ema_stream`` batch kernel
                                (query/split.py:eval_ema_stream):
                                the sequential split-invariant EMA
                                scan the unified history+live path and
                                every catch-up replay verify against,
                                f32 pinned (the serving carry's
                                precision), packed input donated
==============================  =======================================

The Mosaic-lowered engines (lane-chunked join, streaming window
kernels) cannot produce a TPU artifact on a CPU-only image; their
registry entries are gated ``requires_tpu`` and their carry/identity
behaviour stays pinned by the interpret-mode suites
(tests/test_chunked_join.py, test_pallas_window.py).

Suppression reuses the AST tier's convention: a
``# lint-ok: <rule>: <reason>`` comment on (or next to) the builder's
``@register`` line silences that rule for that program.
"""

from __future__ import annotations

import dataclasses
import inspect
from typing import Callable, Dict, List, Optional, Sequence, Tuple

#: series count of every representative shape: one series per device
#: of the 8-way dryrun-style mesh (divides smaller meshes too).
CONTRACT_SERIES = 8

#: static row bounds used by the shifted-engine artifacts (the graft
#: entry's bench-shaped bounds: ticks every 1-2s, 10s window).
CONTRACT_ROWBOUNDS = (20, 8)

_WINDOW_SECS = 10.0


def contract_lanes() -> int:
    """``TEMPO_TPU_CONTRACT_LANES`` — the compile-shape budget: padded
    per-series row count L of every representative shape (default 32,
    clamped [16, 4096]; larger shapes compile slower but sit closer to
    production extents)."""
    from tempo_tpu import config

    n = config.get_int("TEMPO_TPU_CONTRACT_LANES", 32) or 32
    return max(16, min(int(n), 4096))


@dataclasses.dataclass(frozen=True)
class Contract:
    """Declared compiled-artifact guarantees of one program.

    * ``collectives`` — REQUIRED collective kinds with their modeled
      per-shard bytes: the compiled HLO must contain each kind with
      ``model <= measured <= tol * model`` (tol from
      ``profiling.COLLECTIVE_TOLERANCE``, overridable per kind via
      ``tolerances``); a declared kind that vanished compiled away
      real comm the model says must exist, and fails too.
    * ``incidental`` — kinds allowed up to a byte ceiling without a
      model (scalar audit reductions: the clipped-count psum).
      Any kind in the HLO that is neither modeled nor incidental is an
      UNMODELED collective — the class the dryrun audit can only see
      at whole-program grain.
    * ``donate_argnums`` — parameters that must appear as input-output
      aliases in the compiled executable (declared donation that XLA
      silently dropped is exactly the HBM-doubling drift this catches).
      Indices are into the COMPILED executable's flat parameter list —
      the same convention as :class:`Link` — which diverges from the
      python signature when jit drops unused/static args; declare the
      compiled index when the spaces differ.
    * ``allow_f64`` — f64 ops tolerated (golden/f64-policy programs
      only; production TPU-shaped artifacts must stay f64-free).
    * ``host_transfer_ok`` — a declared materialization-barrier reason
      string; None bans infeed/outfeed/callback custom-calls outright.
    """

    collectives: Dict[str, int] = dataclasses.field(default_factory=dict)
    incidental: Dict[str, int] = dataclasses.field(default_factory=dict)
    tolerances: Dict[str, float] = dataclasses.field(default_factory=dict)
    donate_argnums: Tuple[int, ...] = ()
    allow_f64: bool = False
    host_transfer_ok: Optional[str] = None


@dataclasses.dataclass
class CompiledProgram:
    """One built registry entry: the compiled artifact + its contract
    (+ the builder's source location, for ``# lint-ok`` suppression
    lookup)."""

    name: str
    compiled: object                  # jax.stages.Compiled
    contract: Contract
    source_file: str = ""
    source_line: int = 0
    _hlo_text: Optional[str] = dataclasses.field(default=None, repr=False)

    def hlo_text(self) -> str:
        """The optimized-HLO dump, serialized ONCE and shared by every
        rule (``as_text()`` is the dominant per-program cost after the
        compile itself — four rules re-dumping it quadrupled the
        tier's runtime)."""
        if self._hlo_text is None:
            self._hlo_text = self.compiled.as_text()
        return self._hlo_text


@dataclasses.dataclass(frozen=True)
class Link:
    """One declared stage boundary: flat output ``out_idx`` of
    ``producer`` feeds flat input ``in_idx`` of ``consumer`` (flat =
    ``jax.tree_util`` leaf order).  ``drop_leading`` leading axes of
    the producer value are consumed by host-side slicing before the
    next stage (they must be unsharded — a sharded dropped axis would
    change ownership in flight)."""

    producer: str
    out_idx: int
    consumer: str
    in_idx: int
    drop_leading: int = 0


@dataclasses.dataclass
class Chain:
    """Declared stage wiring; ``source_file``/``source_line`` are
    stamped by the registry (the declaring builder's ``@register``
    site) so chain-level findings honour the same ``# lint-ok``
    suppression as program-level ones."""

    name: str
    links: Tuple[Link, ...]
    source_file: str = ""
    source_line: int = 0


# ----------------------------------------------------------------------
# Registry machinery
# ----------------------------------------------------------------------

_BUILDERS: Dict[str, Callable] = {}
_BUILDER_META: Dict[str, dict] = {}


def register(name: str, requires_devices: int = 1,
             requires_tpu: bool = False):
    """Declare a compiled-contract builder.  The builder returns
    ``(programs, chains)`` (lists; a bare CompiledProgram also works)
    and is invoked lazily by :func:`build_all`."""

    def deco(fn):
        _BUILDERS[name] = fn
        _BUILDER_META[name] = dict(requires_devices=requires_devices,
                                   requires_tpu=requires_tpu)
        return fn

    return deco


def names() -> List[str]:
    return list(_BUILDERS)


def _normalize(name: str, result) -> Tuple[List[CompiledProgram],
                                           List[Chain]]:
    if isinstance(result, CompiledProgram):
        programs, chains = [result], []
    else:
        programs, chains = result
    fn = _BUILDERS[name]
    try:
        src = inspect.getsourcefile(fn) or ""
        line = inspect.getsourcelines(fn)[1]
    except (OSError, TypeError):  # builders defined in a REPL/exec
        src, line = "", 0
    for p in programs:
        p.source_file, p.source_line = src, line
    for c in chains:
        c.source_file, c.source_line = src, line
    return list(programs), list(chains)


def build_all(only: Optional[Sequence[str]] = None):
    """Build the registry (or the named subset).  Returns
    ``(programs, chains, skipped, errors)`` where ``skipped`` maps
    name -> reason (unmet backend requirement) and ``errors`` maps
    name -> exception string (a build failure is a finding, not a
    crash — the runner turns it into the build-error exit bit).

    Preconditions the driver must arrange BEFORE jax initialises:
    ``TEMPO_TPU_COMPUTE_DTYPE=float32`` and
    ``TEMPO_TPU_SORT_KERNELS=1`` (the artifacts must be the TPU
    production forms — checking the f64 golden forms for f64 would be
    vacuous), plus >= ``CONTRACT_SERIES`` devices (real or
    ``--xla_force_host_platform_device_count``)."""
    import jax

    from tempo_tpu import packing
    from tempo_tpu.ops.sortmerge import use_sort_kernels

    import numpy as np

    if packing.compute_dtype() != np.float32:
        raise RuntimeError(
            "compiled contracts check the TPU production artifacts: "
            "set TEMPO_TPU_COMPUTE_DTYPE=float32 (the driver "
            "tools/analyze.py --compiled does) before building")
    if not use_sort_kernels():
        raise RuntimeError(
            "compiled contracts check the TPU production artifacts: "
            "set TEMPO_TPU_SORT_KERNELS=1 (the driver "
            "tools/analyze.py --compiled does) before building")

    n_dev = len(jax.devices())
    backend = jax.default_backend()
    wanted = list(only) if only else names()
    unknown = [n for n in wanted if n not in _BUILDERS]
    if unknown:
        raise KeyError(f"unknown contract program(s): {unknown} "
                       f"(known: {sorted(_BUILDERS)})")

    programs: List[CompiledProgram] = []
    chains: List[Chain] = []
    skipped: Dict[str, str] = {}
    errors: Dict[str, str] = {}
    for name in wanted:
        meta = _BUILDER_META[name]
        if meta["requires_tpu"] and backend != "tpu":
            skipped[name] = ("Mosaic-lowered engine: no TPU artifact on "
                            f"backend {backend!r} (pinned by the "
                            "interpret-mode suites)")
            continue
        if n_dev < meta["requires_devices"]:
            skipped[name] = (f"needs {meta['requires_devices']} devices, "
                             f"have {n_dev} (set --xla_force_host_"
                             f"platform_device_count)")
            continue
        try:
            ps, cs = _normalize(name, _BUILDERS[name]())
        except Exception as e:  # noqa: BLE001 - reported as build-error
            errors[name] = f"{type(e).__name__}: {e}"
            continue
        programs.extend(ps)
        chains.extend(cs)
    return programs, chains, skipped, errors


# ----------------------------------------------------------------------
# Shared builder plumbing
# ----------------------------------------------------------------------

def _nbytes(*arrays) -> int:
    return int(sum(a.size * a.dtype.itemsize for a in arrays))


def _series_mesh():
    from tempo_tpu.parallel import make_mesh

    return make_mesh({"series": CONTRACT_SERIES})


def _grid_mesh():
    from tempo_tpu.parallel import make_mesh

    return make_mesh({"series": CONTRACT_SERIES // 2, "time": 2})


def _mesh_arrays(mesh, series_axis="series", time_axis=None, n_cols=2,
                 seed=0):
    """The representative sharded operand set of the mesh chain:
    [K, L] int64 ts (1-2s ticks — CONTRACT_ROWBOUNDS-compatible),
    f32 value planes + bool validity, [C, K, L] right stacks."""
    import numpy as np

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    K, L = CONTRACT_SERIES, contract_lanes()
    rng = np.random.default_rng(seed)
    secs = np.cumsum(rng.integers(1, 3, size=(K, L)), axis=-1)
    ts = secs.astype(np.int64) * np.int64(1_000_000_000)
    x = rng.standard_normal((K, L)).astype(np.float32)
    valid = np.ones((K, L), dtype=bool)
    rv = rng.standard_normal((n_cols, K, L)).astype(np.float32)
    rvd = rng.random((n_cols, K, L)) > 0.1
    s2 = NamedSharding(mesh, P(series_axis, time_axis))
    s3 = NamedSharding(mesh, P(None, series_axis, time_axis))
    put2 = lambda a: jax.device_put(jnp.asarray(a), s2)
    put3 = lambda a: jax.device_put(jnp.asarray(a), s3)
    return dict(ts=put2(ts), x=put2(x), valid=put2(valid),
                rvals=put3(rv), rvalids=put3(rvd),
                perm=jnp.arange(K), ok=jnp.ones((K,), bool))


# ----------------------------------------------------------------------
# The production-program registry
# ----------------------------------------------------------------------

@register("fused.asof_stats_ema", requires_devices=CONTRACT_SERIES)
def _build_fused():
    """The planner's ONE-program chain (plan/fused.py), with its
    donation (DONATE_ARGNUMS) and its key-alignment all-gathers
    modeled: the gathers move the full right stacks once."""
    import jax.numpy as jnp

    from tempo_tpu.plan import fused

    mesh = _series_mesh()
    a = _mesh_arrays(mesh)
    program = fused._fused_program(
        mesh, "series", (("l", 0), ("r", 0), ("r", 1)), _WINDOW_SECS,
        CONTRACT_ROWBOUNDS, "shifted", True, ("l", 0), 0.2, True, 31)
    lvals = a["x"][None]
    lvalids = a["valid"][None]
    planes, vstack = fused._right_stacks(a["ts"], a["valid"],
                                         a["rvals"], a["rvalids"])
    compiled = program.lower(a["ts"], lvals, lvalids, a["ts"], planes,
                             vstack, a["perm"], a["ok"]).compile()
    n_stats = 3
    contract = Contract(
        collectives={
            # key-space alignment: r_ts + the two right stacks are
            # gathered to full rows once each (per-shard result bytes)
            "all-gather": _nbytes(a["ts"], planes, vstack),
        },
        incidental={
            # clipped-count psum: [S] s64 audit scalars
            "all-reduce": n_stats * 8 * 4,
        },
        donate_argnums=fused.DONATE_ARGNUMS,
    )
    return CompiledProgram("fused.asof_stats_ema", compiled, contract)


@register("plan.mesh_chain", requires_devices=CONTRACT_SERIES)
def _build_mesh_chain():
    """The eager/executor-replayed mesh chain as FOUR compiled stages
    (align3 -> asof_local -> range_stats_local_packed -> ema_local)
    plus the declared stage-boundary sharding links — the static
    precondition for chaining them without implicit resharding."""
    import jax.numpy as jnp

    from tempo_tpu import dist
    from tempo_tpu.plan import fused

    mesh = _series_mesh()
    a = _mesh_arrays(mesh)
    planes, vstack = fused._right_stacks(a["ts"], a["valid"],
                                         a["rvals"], a["rvalids"])

    align3 = dist._align3_fn(mesh, "series", None, donate=True)
    align_c = align3.lower(planes, a["perm"], a["ok"], float("nan")) \
        .compile()
    align_contract = Contract(
        collectives={"all-gather": _nbytes(planes)},
        donate_argnums=(0,),
    )

    join = dist._asof_local(mesh, "series", sort_kernels=True)
    join_c = join.lower(a["ts"], a["valid"], a["ts"], a["valid"],
                        vstack, planes).compile()
    # whole-chain donation (round 10): the join donates its consumed
    # aligned stacks (python args 4/5; the unused l/r masks are
    # dropped by jit, so the COMPILED parameter indices are 2/3) onto
    # its equal-shaped found/vals outputs, and the packed stats donate
    # the per-call [C, K, L] value stack (compiled index 1) onto a
    # stats plane — each stage of the chain reuses the buffers of the
    # stage it consumed.
    join_contract = Contract(donate_argnums=(2, 3))

    stats = dist._range_stats_local_packed(
        mesh, "series", _WINDOW_SECS, CONTRACT_ROWBOUNDS, True,
        "shifted")
    xs = a["rvals"]
    stats_c = stats.lower(a["ts"], xs, a["rvalids"]).compile()
    stats_contract = Contract(
        incidental={"all-reduce": xs.shape[0] * 8 * 4},
        donate_argnums=(1,),
    )

    ema = dist._ema_local(mesh, "series", 0.2, True, 31)
    ema_c = ema.lower(a["x"], a["valid"]).compile()

    programs = [
        CompiledProgram("dist.align3", align_c, align_contract),
        CompiledProgram("dist.asof_local", join_c, join_contract),
        CompiledProgram("dist.range_stats_local", stats_c,
                        stats_contract),
        CompiledProgram("dist.ema_local", ema_c, Contract()),
    ]
    chain = Chain("plan.mesh_chain", (
        # aligned plane stack -> the join's r_values operand.  Flat
        # indices refer to the COMPILED executable's parameters: jit
        # drops unused args (the l/r masks under compact=False), so
        # the join's 6 python operands compile to 4 inputs.
        Link("dist.align3", 0, "dist.asof_local", 3),
        # join vals/found -> the packed stats' xs/vs operands
        Link("dist.asof_local", 0, "dist.range_stats_local", 1),
        Link("dist.asof_local", 1, "dist.range_stats_local", 2),
        # a [K, L] stats plane (leading C axis sliced host-side,
        # unsharded) -> the EMA's value operand
        Link("dist.range_stats_local", 0, "dist.ema_local", 0,
             drop_leading=1),
    ))
    return programs, [chain]


@register("serve.step")
def _build_serve_step():
    """The steady-state serving push step (serve/state.py): ONE jitted
    program advancing the AS-OF join carry, the EMA carry and the
    ring-buffer window state per micro-batch.  Contract: every retired
    state buffer is donated (the steady state must update in place —
    a dropped donation doubles serving HBM per tick), no f64 creep
    (f32 value planes, integer timestamp/position math), no host
    transfers (the executor loop may never bounce through python
    mid-tick)."""
    from tempo_tpu.serve import state as serve_state

    cfg = serve_state.StreamConfig(
        n_series=CONTRACT_SERIES, n_cols=2, skip_nulls=True,
        max_lookback=16, window_ns=serve_state.window_ns(_WINDOW_SECS),
        rows_bound=8, ema_alpha=0.2)
    Lb = 8
    fn, n_state = serve_state.push_jitted(cfg, Lb)
    compiled = fn.lower(*serve_state.push_avals(cfg, Lb)).compile()
    # donation is backend-gated (serve_state.donate_serve_steps: off on
    # XLA:CPU where the virtual-device host platform corrupts donated
    # serve buffers); the contract pins whatever the builder declared
    donate = (tuple(range(n_state))
              if serve_state.donate_serve_steps() else ())
    contract = Contract(donate_argnums=donate)
    return CompiledProgram("serve.step", compiled, contract)


@register("serve.cohort_step", requires_devices=CONTRACT_SERIES)
def _build_cohort_step():
    """The fleet-serving cohort engine's mesh-sharded step programs
    (serve/cohort.py): ONE push and ONE query program for S streams
    sharing a shape bucket, the [S, ...] stream axis sharded across the
    mesh.  Contracts: every retired cohort state buffer donated (a
    dropped donation doubles FLEET HBM per tick), no f64 creep, no
    host transfers, and — the fleet-scaling claim itself — ZERO
    collectives: nothing in the step mixes streams, so an empty
    collective inventory is the declared model and ANY collective in
    the compiled HLO fails as unmodeled.  The ``serve.cohort_loop``
    chain declares the steady-state wiring: the push step's state
    out-shardings are its own in-shardings (the pre-partitioned pjit
    handoff) and feed the query step's carry inputs — jit drops the
    query's two unused lock planes under skipNulls, so the query-side
    indices are COMPILED parameter positions."""
    import jax

    from tempo_tpu import dist
    from tempo_tpu.serve import state as serve_state

    S = 2 * CONTRACT_SERIES
    cfg = serve_state.StreamConfig(
        n_series=4, n_cols=2, skip_nulls=True, max_lookback=16,
        window_ns=serve_state.window_ns(_WINDOW_SECS), rows_bound=8,
        ema_alpha=0.2)
    Lb = 8
    mesh = dist.stream_mesh(CONTRACT_SERIES)
    push_fn, n_state = serve_state.cohort_push_jitted(cfg, S, Lb, mesh)
    push_c = push_fn.lower(
        *serve_state.cohort_push_avals(cfg, S, Lb)).compile()
    query_fn = serve_state.cohort_query_jitted(cfg, S, Lb, mesh)
    query_c = query_fn.lower(
        *serve_state.cohort_query_avals(cfg, S, Lb)).compile()
    # the query reads 7 of its 9 python operands (skipNulls drops
    # lock_val/lock_valid), so python arg 7 (the donated n_merged
    # carry) lands at COMPILED parameter index 5.  Donation is
    # backend-gated (serve_state.donate_serve_steps: off on XLA:CPU)
    donating = serve_state.donate_serve_steps()
    programs = [
        CompiledProgram("serve.cohort_push", push_c,
                        Contract(donate_argnums=(
                            tuple(range(n_state)) if donating else ()))),
        CompiledProgram("serve.cohort_query", query_c,
                        Contract(donate_argnums=(
                            (5,) if donating else ()))),
    ]
    # flat output order of the push step: the state tuple's n_state
    # leaves precede the emission dict, so state i is out_idx i; the
    # query's compiled inputs are the 7 used operands in python order
    links = [Link("serve.cohort_push", i, "serve.cohort_push", i)
             for i in range(n_state)]
    links += [
        Link("serve.cohort_push", out_i, "serve.cohort_query", in_i)
        for out_i, in_i in
        # last_val, last_src, lock_src, last_ridx, r_count, n_merged
        ((0, 0), (1, 1), (4, 2), (5, 3), (6, 4), (7, 5))
    ]
    chain = Chain("serve.cohort_loop", tuple(links))
    return programs, [chain]


@register("service.dispatch", requires_devices=CONTRACT_SERIES)
def _build_service_dispatch():
    """The query service's steady-state dispatch programs
    (tempo_tpu/service/): a cached query IS a planner executable, and
    the service's canonical mesh queries dispatch the fused chain
    program (plan/fused.py) at two shapes the existing
    ``fused.asof_stats_ema`` entry does not pin — stats over ONE right
    column without EMA, and stats over both right columns with EMA
    riding a right plane.  Contracts: the right stacks stay donated
    (the service's shared cache would otherwise double every
    concurrent query's HBM), alignment all-gathers within the byte
    model, no f64 creep, no host transfer mid-dispatch."""
    from tempo_tpu.plan import fused

    mesh = _series_mesh()
    a = _mesh_arrays(mesh)
    lvals = a["x"][None]
    lvalids = a["valid"][None]
    planes, vstack = fused._right_stacks(a["ts"], a["valid"],
                                         a["rvals"], a["rvalids"])
    shapes = (
        ("service.dispatch_stats", (("r", 0),), None),
        ("service.dispatch_ema", (("r", 0), ("r", 1)), ("r", 0)),
    )
    programs = []
    for name, srcs, ema_src in shapes:
        program = fused._fused_program(
            mesh, "series", srcs, _WINDOW_SECS, CONTRACT_ROWBOUNDS,
            "shifted", True, ema_src, 0.2, True, 31)
        compiled = program.lower(a["ts"], lvals, lvalids, a["ts"],
                                 planes, vstack, a["perm"],
                                 a["ok"]).compile()
        # both canonical shapes summarize RIGHT columns only, so jit
        # drops the unused left value/validity stacks (python args
        # 1/2) and the donated right stacks (fused.DONATE_ARGNUMS =
        # python 4/5) land at COMPILED parameter indices 2/3 — the
        # Link convention (see Contract.donate_argnums)
        contract = Contract(
            collectives={
                "all-gather": _nbytes(a["ts"], planes, vstack),
            },
            incidental={"all-reduce": len(srcs) * 8 * 4},
            donate_argnums=(2, 3),
        )
        programs.append(CompiledProgram(name, compiled, contract))
    return programs, []


@register("dist.range_stats_windowed", requires_devices=CONTRACT_SERIES)
def _build_stats_windowed():
    """The data-independent windowed fallback (rowbounds unknowable:
    resampled/ingest-assembled frames) — the artifact that leaked
    weak-f64 window-bound arithmetic before round 8."""
    from tempo_tpu import dist

    mesh = _series_mesh()
    a = _mesh_arrays(mesh)
    fn = dist._range_stats_local_packed(mesh, "series", _WINDOW_SECS,
                                        None, True, "windowed")
    compiled = fn.lower(a["ts"], a["rvals"], a["rvalids"]).compile()
    contract = Contract(
        incidental={"all-reduce": a["rvals"].shape[0] * 8 * 4},
        donate_argnums=(1,),
    )
    return CompiledProgram("dist.range_stats_windowed", compiled,
                           contract)


def _halo_params():
    halo = 4
    return halo


@register("halo.range_stats", requires_devices=CONTRACT_SERIES)
def _build_halo_range_stats():
    """Time-sharded halo range stats (parallel/halo.py) on the
    series x time grid mesh — the dryrun audit's program, with the
    same ppermute model (left+right halos of ts/x/valid)."""
    from tempo_tpu.parallel import halo as ph

    mesh = _grid_mesh()
    a = _mesh_arrays(mesh, time_axis="time")
    halo = _halo_params()
    K_loc = CONTRACT_SERIES // mesh.shape["series"]
    fn = ph._build_range_stats(mesh, 8.0, halo, "time", "series")
    secs = (a["ts"] // 1_000_000_000)
    compiled = fn.lower(secs, a["x"], a["valid"]).compile()
    model = 2 * K_loc * halo * (8 + 4 + 1)   # s64 secs + f32 x + bool
    contract = Contract(
        collectives={"collective-permute": model},
        incidental={"all-reduce": 16},       # clipped-count psum
    )
    return CompiledProgram("halo.range_stats", compiled, contract)


@register("halo.asof", requires_devices=CONTRACT_SERIES)
def _build_halo_asof():
    """Time-sharded halo AS-OF join: right-halo ppermutes + the
    cross-shard carry all_gathers (the dryrun audit's second
    program)."""
    from tempo_tpu.parallel import halo as ph

    mesh = _grid_mesh()
    a = _mesh_arrays(mesh, time_axis="time")
    halo = _halo_params()
    n_time = mesh.shape["time"]
    K_loc = CONTRACT_SERIES // mesh.shape["series"]
    C = a["rvals"].shape[0]
    fn = ph._build_asof(mesh, halo, "time", "series", sort_kernels=False)
    compiled = fn.lower(a["ts"], a["ts"], a["rvalids"],
                        a["rvals"]).compile()
    model_cp = K_loc * halo * (8 + C * (1 + 4))
    model_ag = n_time * C * K_loc * (1 + 4)
    contract = Contract(
        collectives={"collective-permute": model_cp,
                     "all-gather": model_ag},
        incidental={"all-reduce": 16},
    )
    return CompiledProgram("halo.asof", compiled, contract)


@register("halo.ema", requires_devices=CONTRACT_SERIES)
def _build_halo_ema():
    """Time-sharded EMA: the associative carry stitch's collectives."""
    from tempo_tpu.parallel import halo as ph

    mesh = _grid_mesh()
    a = _mesh_arrays(mesh, time_axis="time")
    n_time = mesh.shape["time"]
    K_loc = CONTRACT_SERIES // mesh.shape["series"]
    fn = ph._build_ema(mesh, 0.2, "time", "series")
    compiled = fn.lower(a["x"], a["valid"]).compile()
    # carry stitch: the per-shard (scale, offset) f32 carry pair is
    # all-gathered across the time axis
    model_ag = n_time * K_loc * 2 * 4
    contract = Contract(collectives={"all-gather": model_ag})
    return CompiledProgram("halo.ema", compiled, contract)


@register("reshard.series_to_time", requires_devices=CONTRACT_SERIES)
def _build_reshard_s2t():
    """The explicit all_to_all layout switch
    (reshard.all_to_all_series_to_time's kernel and specs verbatim —
    the eager wrapper jits internally, so the contract rebuilds the
    same shard_map to get a lowerable handle), modeled at its
    per-shard result bytes."""
    import jax
    from jax.sharding import PartitionSpec as P

    from tempo_tpu.parallel import halo as ph

    mesh = _grid_mesh()
    a = _mesh_arrays(mesh, time_axis="time")
    x = a["x"]
    n_s, n_t = mesh.shape["series"], mesh.shape["time"]

    def kernel(block):
        return jax.lax.all_to_all(block, "time", split_axis=0,
                                  concat_axis=1, tiled=True)

    fn = jax.jit(ph.shard_map(kernel, mesh=mesh,
                              in_specs=(P("series", "time"),),
                              out_specs=P(("series", "time"), None)))
    compiled = fn.lower(x).compile()
    shard_bytes = (x.shape[0] // (n_s * n_t)) * x.shape[1] * 4
    contract = Contract(collectives={"all-to-all": shard_bytes})
    return CompiledProgram("reshard.series_to_time", compiled, contract)


@register("reshard.time_to_series", requires_devices=CONTRACT_SERIES)
def _build_reshard_t2s():
    """The inverse layout switch
    (reshard.all_to_all_time_to_series): full-row joint-sharded blocks
    back to P(series, time) — same per-shard element count as the
    forward switch."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from tempo_tpu.parallel import halo as ph

    mesh = _grid_mesh()
    a = _mesh_arrays(mesh, time_axis="time")
    n_s, n_t = mesh.shape["series"], mesh.shape["time"]
    x = jax.device_put(a["x"],
                       NamedSharding(mesh, P(("series", "time"), None)))

    def kernel(block):
        return jax.lax.all_to_all(block, "time", split_axis=1,
                                  concat_axis=0, tiled=True)

    fn = jax.jit(ph.shard_map(kernel, mesh=mesh,
                              in_specs=(P(("series", "time"), None),),
                              out_specs=P("series", "time")))
    compiled = fn.lower(x).compile()
    shard_bytes = (x.shape[0] // (n_s * n_t)) * x.shape[1] * 4
    contract = Contract(collectives={"all-to-all": shard_bytes})
    return CompiledProgram("reshard.time_to_series", compiled, contract)


@register("reshard.plan_node", requires_devices=CONTRACT_SERIES)
def _build_reshard_plan_node():
    """The planner's first-class ``reshard`` node executor
    (dist.reshard_frame / dist._relayout_fn): the whole-frame
    series-local layout switch as ONE program — ts + mask + the
    [C, K, L] value/validity stacks each ride one ``lax.all_to_all``
    — modeled byte-exactly by ``dist.relayout_comm_bytes`` (the same
    model explain() renders on placed reshard nodes and the
    --only-mesh-scaling bench asserts).  No donation by construction:
    a layout switch changes every per-device buffer shape, so no
    input/output alias can exist."""
    from tempo_tpu import dist

    mesh = _grid_mesh()
    a = _mesh_arrays(mesh, time_axis="time")
    fn = dist._relayout_fn(mesh, "series", "time", forward=True,
                           with_cols=True, has_seq=False)
    compiled = fn.lower(a["ts"], a["valid"], a["rvals"],
                        a["rvalids"]).compile()
    K, L = a["ts"].shape
    model = dist.relayout_comm_bytes(K, L, a["rvals"].shape[0],
                                     CONTRACT_SERIES, has_seq=False)
    contract = Contract(collectives={"all-to-all": model})
    return CompiledProgram("reshard.plan_node", compiled, contract)


@register("engine.join_single")
def _build_engine_join_single():
    """pick_join_engine's 'single' engine: the sort-and-scan AS-OF
    merge (ops/sortmerge.py) jitted at a representative [K, L]."""
    import jax

    from tempo_tpu.ops import sortmerge as sm

    mesh = _series_mesh()
    a = _mesh_arrays(mesh)
    fn = jax.jit(lambda lts, rts, rvd, rv: sm.asof_merge_values(
        lts, rts, rvd, rv))
    compiled = fn.lower(a["ts"], a["ts"], a["rvalids"],
                        a["rvals"]).compile()
    return CompiledProgram("engine.join_single", compiled, Contract())


@register("engine.join_bitonic")
def _build_engine_join_bitonic():
    """The XLA bitonic oversize engine (asof_merge_values_bitonic) —
    the in-shard_map route past the single-program lane ceiling."""
    import jax

    from tempo_tpu.ops import pallas_merge as pm

    mesh = _series_mesh()
    a = _mesh_arrays(mesh)
    fn = jax.jit(lambda lts, rts, rvd, rv: pm.asof_merge_values_bitonic(
        lts, rts, rvd, rv))
    compiled = fn.lower(a["ts"], a["ts"], a["rvalids"],
                        a["rvals"]).compile()
    return CompiledProgram("engine.join_bitonic", compiled, Contract())


@register("engine.range_shifted")
def _build_engine_range_shifted():
    """pick_range_engine's 'shifted' engine: statically-unrolled masked
    shifted passes over int32 rebased seconds (the graft entry's
    flagship form)."""
    import jax
    import jax.numpy as jnp

    from tempo_tpu.ops import sortmerge as sm

    mesh = _series_mesh()
    a = _mesh_arrays(mesh)
    secs32 = (a["ts"] // 1_000_000_000).astype(jnp.int32)
    fn = jax.jit(lambda s, x, v: sm.range_stats_shifted(
        s, x, v, jnp.asarray(int(_WINDOW_SECS)).astype(jnp.int32),
        max_behind=CONTRACT_ROWBOUNDS[0], max_ahead=CONTRACT_ROWBOUNDS[1]))
    compiled = fn.lower(secs32, a["x"], a["valid"]).compile()
    return CompiledProgram("engine.range_shifted", compiled, Contract())


@register("engine.range_windowed")
def _build_engine_range_windowed():
    """pick_range_engine's 'windowed' (prefix+RMQ) engine — the
    unbounded-window fallback."""
    import jax
    import jax.numpy as jnp

    from tempo_tpu.ops import rolling as rk

    mesh = _series_mesh()
    a = _mesh_arrays(mesh)
    secs = a["ts"] // 1_000_000_000

    def fn(s, x, v):
        start, end = rk.range_window_bounds(
            s, rk.range_window_width(s, _WINDOW_SECS))
        return rk.windowed_stats(x, v, start, end)

    compiled = jax.jit(fn).lower(secs, a["x"], a["valid"]).compile()
    return CompiledProgram("engine.range_windowed", compiled, Contract())


@register("standing.step")
def _build_standing_step():
    """The standing-query engine's incremental EMA step
    (query/standing.py): subscriptions in delta-EMA mode share a
    serving-plane cohort whose push program IS serve/state.py's step at
    the canonical standing config — EMA carry only, no window or
    lookback planes (max_lookback=0, window off), one value column.
    Contracts: retired state donated (input_output_aliases — the
    standing fleet's steady state must update in place), no f64 creep
    (the standing==batch bitwise contract is an f32 contract), no host
    transfers, and zero per-push collectives (nothing in the step
    mixes subscriptions)."""
    from tempo_tpu.serve import state as serve_state

    cfg = serve_state.StreamConfig(
        n_series=CONTRACT_SERIES, n_cols=1, skip_nulls=True,
        max_lookback=0, window_ns=None, rows_bound=8, ema_alpha=0.3)
    Lb = 8
    fn, n_state = serve_state.push_jitted(cfg, Lb)
    compiled = fn.lower(*serve_state.push_avals(cfg, Lb)).compile()
    donate = (tuple(range(n_state))
              if serve_state.donate_serve_steps() else ())
    return CompiledProgram("standing.step", compiled,
                           Contract(donate_argnums=donate))


@register("standing.unified_scan")
def _build_standing_unified_scan():
    """The ``ema_stream`` batch kernel (query/split.py:
    eval_ema_stream): the sequential split-invariant EMA scan over the
    packed unified history+live layout — the program every standing
    catch-up replay, resume rebuild and batch twin run through.
    Contracts: f32 end to end (the serving carry's precision — an f64
    creep here would break the standing==batch bitwise identity, not
    just the no-f64 policy), packed value plane donated (the scan's
    output has the input's shape; the replay never needs the raw plane
    back), no collectives, no host transfers."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tempo_tpu.ops import rolling as ops_rolling

    L = contract_lanes()
    x = jax.ShapeDtypeStruct((CONTRACT_SERIES, L), jnp.float32)
    valid = jax.ShapeDtypeStruct((CONTRACT_SERIES, L), jnp.bool_)
    fn = jax.jit(
        lambda v, m: ops_rolling.ema_scan(v, m, np.float32(0.3)),
        donate_argnums=(0,))
    compiled = fn.lower(x, valid).compile()
    donate = (0,) if _donate_landed(compiled) else ()
    return CompiledProgram("standing.unified_scan", compiled,
                           Contract(donate_argnums=donate))


def _donate_landed(compiled) -> bool:
    """XLA:CPU sometimes declines a requested donation (no
    input_output_alias in the artifact); the contract pins what the
    backend actually honoured, mirroring serve_state.donate_serve_steps
    gating."""
    try:
        return "input_output_alias" in compiled.as_text()
    except Exception:  # pragma: no cover - backend-specific
        return False


@register("engine.join_chunked", requires_tpu=True)
def _build_engine_join_chunked():  # pragma: no cover - TPU image only
    """The lane-chunked streaming merge (Mosaic): TPU artifact only."""
    import jax
    import numpy as np

    from tempo_tpu.ops import pallas_merge as pm

    mesh = _series_mesh()
    a = _mesh_arrays(mesh)
    fn = jax.jit(lambda lts, rts, rvd, rv: pm.asof_merge_values_chunked(
        lts, rts, rvd, rv))
    compiled = fn.lower(np.asarray(a["ts"]), np.asarray(a["ts"]),
                        np.asarray(a["rvalids"]),
                        np.asarray(a["rvals"])).compile()
    return CompiledProgram("engine.join_chunked", compiled, Contract())
