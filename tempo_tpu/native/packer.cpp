// Native host-runtime packing engine for tempo-tpu.
//
// Role: the ragged->padded layout transform that feeds the TPU — the
// equivalent of what the reference delegates to Spark's JVM/Tungsten
// shuffle machinery (hash-partition rows by key, sort each partition by
// (ts, seq); /root/reference/python/tempo/tsdf.py:121,563-580).  The
// Python fallback is numpy lexsort + fancy-indexing scatter; this C++
// path does a bucket place + per-key stable sort + contiguous memcpy
// pack, multithreaded over series buckets.
//
// Exposed via a plain C ABI, loaded from Python with ctypes
// (pybind11 is not available in this image).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <functional>
#include <thread>
#include <vector>

namespace {

// Comparator matching numpy lexsort((seq, ts, key)) within one key
// bucket: primary ts, secondary seq with NaN sorted last (numpy sorts
// NaN to the end), stable on full ties.  The sequence column comes in
// either float64 (seq_f) or exact int64 (seq_i) flavors — int64
// sequence ids above 2^53 must not round through a double.
struct TsSeqLess {
  const int64_t* ts;
  const double* seq_f;   // may be null
  const int64_t* seq_i;  // may be null (mutually exclusive with seq_f)
  bool operator()(int64_t a, int64_t b) const {
    if (ts[a] != ts[b]) return ts[a] < ts[b];
    if (seq_i != nullptr) return seq_i[a] < seq_i[b];
    if (seq_f == nullptr) return false;
    const double sa = seq_f[a], sb = seq_f[b];
    const bool na = std::isnan(sa), nb = std::isnan(sb);
    if (na || nb) return !na && nb;  // non-NaN < NaN; NaN==NaN keeps order
    return sa < sb;
  }
};

void parallel_over_keys(int64_t n_keys, const int64_t* starts, int nthreads,
                        const std::function<void(int64_t)>& body) {
  if (nthreads <= 1 || n_keys <= 1) {
    for (int64_t k = 0; k < n_keys; ++k) body(k);
    return;
  }
  std::atomic<int64_t> next(0);
  auto worker = [&]() {
    for (;;) {
      int64_t k = next.fetch_add(1);
      if (k >= n_keys) return;
      body(k);
    }
  };
  std::vector<std::thread> pool;
  int nt = std::min<int64_t>(nthreads, n_keys);
  pool.reserve(nt);
  for (int t = 0; t < nt; ++t) pool.emplace_back(worker);
  for (auto& th : pool) th.join();
  (void)starts;
}

}  // namespace

extern "C" {

// Compute the sorted flat layout: order[i] = position into the original
// arrays of the i-th row in (key, ts, seq) order; starts[k] = row offset
// of key k in the sorted stream (length n_keys+1).
// key_ids must be dense in [0, n_keys).  seq may be null.
void tempo_sort_layout(const int64_t* key_ids, const int64_t* ts,
                       const double* seq_f, const int64_t* seq_i, int64_t n,
                       int64_t n_keys, int64_t* order, int64_t* starts,
                       int nthreads) {
  // pass 1: counts -> starts
  std::vector<int64_t> counts(n_keys, 0);
  for (int64_t i = 0; i < n; ++i) counts[key_ids[i]]++;
  starts[0] = 0;
  for (int64_t k = 0; k < n_keys; ++k) starts[k + 1] = starts[k] + counts[k];
  // pass 2: stable bucket placement by key (original order within bucket)
  std::vector<int64_t> cursor(starts, starts + n_keys);
  for (int64_t i = 0; i < n; ++i) order[cursor[key_ids[i]]++] = i;
  // pass 3: per-key stable sort by (ts, seq)
  TsSeqLess less{ts, seq_f, seq_i};
  parallel_over_keys(n_keys, starts, nthreads, [&](int64_t k) {
    std::stable_sort(order + starts[k], order + starts[k + 1], less);
  });
}

// Gather a column through `order` (itemsize-generic):
// out[i*itemsize..] = vals[order[i]*itemsize..].
void tempo_take(const char* vals, const int64_t* order, int64_t n,
                int64_t itemsize, char* out, int nthreads) {
  int nt = std::max(1, nthreads);
  int64_t chunk = (n + nt - 1) / nt;
  std::vector<std::thread> pool;
  for (int t = 0; t < nt; ++t) {
    int64_t lo = t * chunk, hi = std::min<int64_t>(n, lo + chunk);
    if (lo >= hi) break;
    pool.emplace_back([=]() {
      for (int64_t i = lo; i < hi; ++i)
        std::memcpy(out + i * itemsize, vals + order[i] * itemsize, itemsize);
    });
  }
  for (auto& th : pool) th.join();
}

// Pack an already key/ts-sorted flat column into dense [K, L] padded
// rows: row k = vals[starts[k]:starts[k+1]] then fill_elem repeated.
// Contiguous memcpy per series + pattern fill — the scatter the numpy
// path does with fancy indexing.
void tempo_pack(const char* vals, const int64_t* starts, int64_t n_keys,
                int64_t padded_len, int64_t itemsize, const char* fill_elem,
                char* out, int nthreads) {
  parallel_over_keys(n_keys, starts, nthreads, [&](int64_t k) {
    const int64_t len = std::min(starts[k + 1] - starts[k], padded_len);
    char* row = out + k * padded_len * itemsize;
    std::memcpy(row, vals + starts[k] * itemsize, len * itemsize);
    for (int64_t j = len; j < padded_len; ++j)
      std::memcpy(row + j * itemsize, fill_elem, itemsize);
  });
}

// Inverse of tempo_pack: flatten [K, L] padded rows back to the sorted
// flat stream of real rows.
void tempo_unpack(const char* packed, const int64_t* starts, int64_t n_keys,
                  int64_t padded_len, int64_t itemsize, char* out,
                  int nthreads) {
  parallel_over_keys(n_keys, starts, nthreads, [&](int64_t k) {
    const int64_t len = starts[k + 1] - starts[k];
    std::memcpy(out + starts[k] * itemsize,
                packed + k * padded_len * itemsize, len * itemsize);
  });
}

}  // extern "C"
