"""ctypes loader for the native C++ packing engine.

Compiles ``packer.cpp`` on first use with the system ``g++`` (pybind11
is not in this image; the C ABI + ctypes keeps the binding dependency-
free) and exposes numpy-typed wrappers.  Falls back silently when the
toolchain or the build is unavailable — ``available()`` gates every call
site in :mod:`tempo_tpu.packing`.  Set ``TEMPO_TPU_NATIVE=0`` to force
the pure-numpy path.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import tempfile
from typing import Optional, Tuple

import numpy as np

from tempo_tpu import config

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "packer.cpp")
_SO = os.path.join(_HERE, "_packer.so")

_lib = None
_tried = False

N_THREADS = config.get_int("TEMPO_TPU_NATIVE_THREADS", os.cpu_count() or 1)


def _build() -> bool:
    # compile to a per-process temp name, then atomically rename:
    # concurrent first-use builds (pytest workers, multiple interpreters)
    # must never install each other's half-written output
    tmp = None
    try:
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=_HERE)
        os.close(fd)
        cmd = [
            "g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
            _SRC, "-o", tmp,
        ]
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO)
        return True
    except (subprocess.SubprocessError, OSError) as e:  # pragma: no cover
        logger.info("native packer build failed, using numpy path: %s", e)
        if tmp is not None:
            try:
                os.unlink(tmp)
            except OSError:
                pass
        return False


def _load():
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    if config.get("TEMPO_TPU_NATIVE", "1") == "0":
        return None
    try:
        # binary-only installs (no .cpp) load whatever .so is shipped;
        # a read-only package dir falls through to the numpy path
        have_src = os.path.exists(_SRC)
        stale = not os.path.exists(_SO) or (
            have_src and os.path.getmtime(_SO) < os.path.getmtime(_SRC)
        )
        if stale and (not have_src or not _build()):
            return None
        lib = ctypes.CDLL(_SO)
    except OSError as e:  # pragma: no cover
        logger.info("native packer load failed: %s", e)
        return None
    i64p = ctypes.POINTER(ctypes.c_int64)
    f64p = ctypes.POINTER(ctypes.c_double)
    cp = ctypes.c_char_p
    lib.tempo_sort_layout.argtypes = [
        i64p, i64p, f64p, i64p, ctypes.c_int64, ctypes.c_int64, i64p, i64p,
        ctypes.c_int,
    ]
    lib.tempo_take.argtypes = [
        cp, i64p, ctypes.c_int64, ctypes.c_int64, cp, ctypes.c_int,
    ]
    lib.tempo_pack.argtypes = [
        cp, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, cp, cp,
        ctypes.c_int,
    ]
    lib.tempo_unpack.argtypes = [
        cp, i64p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64, cp,
        ctypes.c_int,
    ]
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def _f64p(a: Optional[np.ndarray]):
    if a is None:
        return ctypes.cast(None, ctypes.POINTER(ctypes.c_double))
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_double))


def _bytes_ptr(a: np.ndarray):
    return ctypes.cast(a.ctypes.data, ctypes.c_char_p)


def sort_layout(
    key_ids: np.ndarray, ts_ns: np.ndarray, seq: Optional[np.ndarray],
    n_series: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """(order, starts) for the (key, ts, seq) total order — the native
    equivalent of ``np.lexsort((seq, ts_ns, key_ids))`` + bincount.
    Integer sequence columns take the exact int64 comparator (values
    above 2^53 must not round through float64)."""
    lib = _load()
    n = key_ids.shape[0]
    key_ids = np.ascontiguousarray(key_ids, dtype=np.int64)
    ts_ns = np.ascontiguousarray(ts_ns, dtype=np.int64)
    if n and (int(key_ids.min()) < 0 or int(key_ids.max()) >= n_series):
        # the C++ writes are unchecked; fault here like bincount would
        raise IndexError(
            f"key_ids out of range [0, {n_series}) for native sort_layout"
        )
    seq_f = seq_i = None
    if seq is not None:
        dt = np.asarray(seq).dtype
        if np.issubdtype(dt, np.unsignedinteger):
            # uint64 above 2^63 would wrap negative through int64; the
            # dispatcher (packing._sort_layout) keeps those on numpy
            seq_i = np.ascontiguousarray(seq.astype(np.int64))
        elif np.issubdtype(dt, np.integer):
            seq_i = np.ascontiguousarray(seq, dtype=np.int64)
        else:
            seq_f = np.ascontiguousarray(seq, dtype=np.float64)
    order = np.empty(n, dtype=np.int64)
    starts = np.empty(n_series + 1, dtype=np.int64)
    lib.tempo_sort_layout(
        _i64p(key_ids), _i64p(ts_ns), _f64p(seq_f),
        _i64p(seq_i) if seq_i is not None else ctypes.cast(None, ctypes.POINTER(ctypes.c_int64)),
        ctypes.c_int64(n), ctypes.c_int64(n_series),
        _i64p(order), _i64p(starts), N_THREADS,
    )
    return order, starts


def take(values: np.ndarray, order: np.ndarray) -> np.ndarray:
    """``values[order]`` along axis 0; rows of an N-D array are gathered
    whole (the per-item stride is itemsize x trailing dims)."""
    lib = _load()
    values = np.ascontiguousarray(values)
    order = np.ascontiguousarray(order, dtype=np.int64)
    if order.size and (
        int(order.min()) < 0 or int(order.max()) >= values.shape[0]
    ):
        raise IndexError("order out of range for native take")
    row_bytes = values.dtype.itemsize * int(np.prod(values.shape[1:], dtype=np.int64))
    out = np.empty((order.shape[0],) + values.shape[1:], dtype=values.dtype)
    lib.tempo_take(
        _bytes_ptr(values), _i64p(order), ctypes.c_int64(order.shape[0]),
        ctypes.c_int64(row_bytes), _bytes_ptr(out), N_THREADS,
    )
    return out


def pack(
    values_sorted: np.ndarray, starts: np.ndarray, padded_len: int, fill,
) -> np.ndarray:
    lib = _load()
    values_sorted = np.ascontiguousarray(values_sorted)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    K = starts.shape[0] - 1
    lengths = np.diff(starts)
    if lengths.size and (int(lengths.min()) < 0 or int(lengths.max()) > padded_len):
        # match the numpy scatter path, which faults on overflow rather
        # than silently truncating rows
        raise IndexError(
            f"series lengths {int(lengths.min())}..{int(lengths.max())} "
            f"invalid for padded_len {padded_len}"
        )
    if int(starts[-1]) > values_sorted.shape[0] or int(starts[0]) < 0:
        raise ValueError(
            f"starts[-1]={int(starts[-1])} exceeds values length "
            f"{values_sorted.shape[0]}"
        )
    out = np.empty((K, padded_len), dtype=values_sorted.dtype)
    fill_elem = np.asarray(fill, dtype=values_sorted.dtype).tobytes()
    lib.tempo_pack(
        _bytes_ptr(values_sorted), _i64p(starts), ctypes.c_int64(K),
        ctypes.c_int64(padded_len), ctypes.c_int64(values_sorted.dtype.itemsize),
        ctypes.c_char_p(fill_elem), _bytes_ptr(out), N_THREADS,
    )
    return out


def unpack(packed: np.ndarray, starts: np.ndarray) -> np.ndarray:
    lib = _load()
    packed = np.ascontiguousarray(packed)
    starts = np.ascontiguousarray(starts, dtype=np.int64)
    K = starts.shape[0] - 1
    lengths = np.diff(starts)
    if lengths.size and (
        int(lengths.min()) < 0 or int(lengths.max()) > packed.shape[1]
    ):
        raise IndexError("starts inconsistent with packed shape in native unpack")
    n = int(starts[-1])
    out = np.empty(n, dtype=packed.dtype)
    lib.tempo_unpack(
        _bytes_ptr(packed), _i64p(starts), ctypes.c_int64(K),
        ctypes.c_int64(packed.shape[1]), ctypes.c_int64(packed.dtype.itemsize),
        _bytes_ptr(out), N_THREADS,
    )
    return out
