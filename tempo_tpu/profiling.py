"""Profiling, cost probes, and strategy picking.

The reference's only introspection hook is a driver-side size probe: it
parses ``explain cost`` output to read the optimizer's ``sizeInBytes``
estimate and uses it to pick the broadcast join strategy
(python/tempo/tsdf.py:433-461, consumed at :482-509).  Observability
beyond that is delegated to the Spark UI.

The TPU-native equivalents:

* :func:`trace` — a context manager around ``jax.profiler`` producing
  TensorBoard-loadable traces (the Spark-UI analog).
* :func:`compiled_cost` — XLA's own post-compilation cost/memory
  analysis for a jitted function, the compiler-backed version of the
  ``sizeInBytes`` scrape.
* :func:`host_bytes` — cheap driver-side size estimate of a frame
  (used by the join planner, tempo_tpu/join.py).
* :func:`pick_asof_strategy` — the size-probe -> algorithm decision in
  one audited place.
"""

from __future__ import annotations

import contextlib
import logging
from typing import Dict, Optional

import jax
import pandas as pd

logger = logging.getLogger(__name__)

# tsdf.py:491 uses 30MiB as the broadcast cutoff
BROADCAST_BYTES_THRESHOLD = 30 * 1024 * 1024


@contextlib.contextmanager
def trace(log_dir: str, create_perfetto_link: bool = False):
    """Profile everything inside the block to ``log_dir``.

    Usage::

        with profiling.trace("/tmp/tempo-trace"):
            tsdf.asofJoin(other).df
    """
    jax.profiler.start_trace(log_dir, create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named sub-span inside a :func:`trace` block (shows up on the TPU
    timeline): ``with profiling.annotate("asof-kernel"): ...``"""
    return jax.profiler.TraceAnnotation(name)


def compiled_cost(fn, *args, **kwargs) -> Dict[str, Optional[float]]:
    """Compile ``fn`` for the current backend and return XLA's cost and
    memory analysis: flops, transcendentals, bytes accessed, and
    per-space buffer sizes.  Values are ``None`` where a backend does
    not report them."""
    compiled = jax.jit(fn).lower(*args, **kwargs).compile()
    out: Dict[str, Optional[float]] = {
        "flops": None,
        "bytes_accessed": None,
        "output_bytes": None,
        "temp_bytes": None,
        "argument_bytes": None,
        "generated_code_bytes": None,
    }
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if cost:
            out["flops"] = cost.get("flops")
            out["bytes_accessed"] = cost.get("bytes accessed")
    except Exception as e:  # pragma: no cover - backend-specific
        logger.debug("cost_analysis unavailable: %s", e)
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            out["output_bytes"] = getattr(mem, "output_size_in_bytes", None)
            out["temp_bytes"] = getattr(mem, "temp_size_in_bytes", None)
            out["argument_bytes"] = getattr(mem, "argument_size_in_bytes", None)
            out["generated_code_bytes"] = getattr(
                mem, "generated_code_size_in_bytes", None
            )
    except Exception as e:  # pragma: no cover - backend-specific
        logger.debug("memory_analysis unavailable: %s", e)
    return out


def window_roofline(
    n_rows: int,
    read_bytes_per_row: float,
    write_bytes_per_row: float,
    restream_bytes_per_row: float = 0.0,
    t_iter: Optional[float] = None,
    stream_bytes_per_sec: Optional[float] = None,
    n_cols: int = 1,
    key_bytes_per_row: float = 0.0,
) -> Dict[str, float]:
    """Roofline accounting for a windowed/streaming config: bytes-moved
    vs bytes-minimal, and their fractions of a *measured* stream rate.

    * ``bytes_minimal`` — the compulsory traffic of an ideal
      implementation: every input column read ONCE, every output plane
      written ONCE.  ``minimal_frac`` answers "how close is this config
      to the fastest any implementation could possibly be".
    * ``bytes_moved`` — what the current implementation actually
      streams, including re-streamed intermediates (e.g. a cast or
      scale pass that writes a converted copy the kernel then re-reads:
      ``restream_bytes_per_row``).  ``achieved_frac`` answers "what
      fraction of the machine's stream capability is this config
      driving" — the utilization number the hbm-stream bound compares.
    * ``stream_efficiency`` = minimal/moved — 1.0 means no byte is
      moved twice; below 1.0 quantifies exactly the re-streaming that
      kernel fusion (scale/jitter scalars riding SMEM,
      ops/pallas_window.py / ops/pallas_bucket.py) removes.

    **Column packing** (``n_cols`` > 1): the shared key planes
    (``key_bytes_per_row`` — timestamps/bucket ids) are compulsory
    traffic ONCE per pass, while ``read_bytes_per_row`` /
    ``write_bytes_per_row`` count one *column's* payload and scale by
    ``n_cols``.  An unpacked implementation re-streams the keys per
    column — model that by putting the extra (n_cols-1) x key bytes
    into ``restream_bytes_per_row``; the packed kernels
    (ops/pallas_window.py ``range_stats_*_packed``) reclaim exactly
    that term.  ``n_rows`` stays the per-column row count; the
    per-row figures below are per base row.
    """
    per_row_min = key_bytes_per_row + n_cols * (
        read_bytes_per_row + write_bytes_per_row)
    bytes_min = float(n_rows) * per_row_min
    bytes_moved = bytes_min + float(n_rows) * restream_bytes_per_row
    out: Dict[str, float] = {
        "bytes_minimal_per_row": per_row_min,
        "bytes_moved_per_row": bytes_moved / max(n_rows, 1),
        "stream_efficiency": round(bytes_min / max(bytes_moved, 1.0), 3),
    }
    if n_cols > 1:
        out["packed_cols"] = n_cols
    if t_iter and stream_bytes_per_sec:
        out["achieved_frac"] = round(
            bytes_moved / t_iter / stream_bytes_per_sec, 3)
        out["minimal_frac"] = round(
            bytes_min / t_iter / stream_bytes_per_sec, 3)
    return out


def join_engine_override() -> Optional[str]:
    """``TEMPO_TPU_JOIN_ENGINE``: force one AS-OF merge engine —
    ``single`` (the one-shot VMEM plan; expert, may exceed the
    compiler ceiling), ``chunked`` (the lane-chunked streaming VMEM
    kernel), ``bracket`` (legacy host time-bracketing), or ``bitonic``
    (the XLA log-stage network, the tracer-context oversize engine).
    Unset/unknown = auto."""
    from tempo_tpu import config

    env = (config.get("TEMPO_TPU_JOIN_ENGINE") or "").strip().lower()
    if env == "vmem":
        env = "single"
    return env if env in ("single", "chunked", "bracket", "bitonic") \
        else None


def pick_join_engine(est_lanes: int, limit: int,
                     chunked_ok: bool) -> str:
    """'single' | 'chunked' | 'bracket' — the three-way oversize
    decision of the host AS-OF join (join.py):

    * ``single``: the estimated merged-lane width fits one device
      program (the single-shot VMEM merge plan, or the XLA ladders
      under the measured ~205K-lane compiler ceiling,
      resilience.max_merged_lanes);
    * ``chunked``: past the ceiling, the lane-chunked streaming VMEM
      kernel (ops/pallas_merge.py) joins on-chip at any length — the
      default oversize engine since round 6;
    * ``bracket``: host time-bracketing with exact carries — the last
      resort when the streaming engine cannot run (non-TPU backend,
      >= 2^24 merged rows).

    ``TEMPO_TPU_JOIN_ENGINE`` forces a specific engine (the
    ``bitonic`` value is a device-dispatch knob — the host path treats
    it as ``single`` and the sortmerge layer routes to the XLA bitonic
    network).  A plan-time hoisted decision (tempo_tpu/plan/hints.py)
    wins while the planner replays the node — skipping the knob read —
    but only when the caller's freshly-probed bounds still admit it
    (a cached 'single' plan replayed past the compiler ceiling, or
    'chunked' on a backend where the streaming kernel is unavailable,
    falls through and re-picks).

    With the cost model on (``TEMPO_TPU_COST_MODEL``, default on —
    tempo_tpu/plan/cost.py) the unforced decision is an argmin over
    estimated engine cost with the thresholds above demoted to
    feasibility priors; all three engines are bit-identical, so a
    measured cost input flipping the pick never changes a result bit.
    Under the default priors the argmin reproduces the rule exactly."""
    from tempo_tpu.plan import hints as plan_hints

    hinted = plan_hints.get("join_engine")
    if hinted == "single" and (limit <= 0 or est_lanes <= limit):
        return "single"
    if hinted == "chunked" and chunked_ok:
        return "chunked"
    if hinted == "bracket":
        return "bracket"
    forced = join_engine_override()
    if forced == "bitonic":
        return "single"
    if forced is not None:
        return forced
    from tempo_tpu.plan import cost as plan_cost

    if plan_cost.enabled():
        return plan_cost.decide_join_engine(est_lanes, limit, chunked_ok)
    if limit <= 0 or est_lanes <= limit:
        return "single"
    return "chunked" if chunked_ok else "bracket"


_COLLECTIVE_OPS = ("collective-permute", "all-to-all", "all-gather",
                   "all-reduce")

#: Per-collective tolerance of a modeled-vs-compiled comm-bytes audit:
#: ``model <= measured <= tol * model``.  ONE table shared by the
#: dryrun multichip audit (__graft_entry__.py) and the
#: collective-inventory compiled-contract rule
#: (tools/analysis/compiled/), so "how much XLA padding is
#: acceptable" is decided once.  The CPU-mesh measurements are
#: byte-exact (ratio 1.0, MULTICHIP_r05 + the round-8 contract
#: baselines); the headroom covers XLA padding/fusion round-up on
#: real ICI, and all-reduce gets extra slack because scalar audit
#: reductions ride tuple-combined all-reduces whose shapes XLA may
#: widen.
COLLECTIVE_TOLERANCE: Dict[str, float] = {
    "collective-permute": 1.25,
    "all-to-all": 1.25,
    "all-gather": 1.25,
    "all-reduce": 2.0,
}
_DTYPE_BYTES = {"f64": 8, "f32": 4, "f16": 2, "bf16": 2, "s64": 8,
                "s32": 4, "u64": 8, "u32": 4, "s16": 2, "u16": 2,
                "s8": 1, "u8": 1, "pred": 1}


def _collective_instructions(text: str):
    """The collective instructions of an optimized-HLO dump, yielded as
    ``(kind, op, rhs)`` per instruction line — the ONE parser behind
    both :func:`comm_bytes_from_compiled` and
    :func:`collective_counts_from_compiled` (a second copy of the
    which-line-is-a-collective logic would silently skew one audit
    when the other is taught a new op kind).

    e.g.  ``%all-to-all.1 = f32[4,16]{1,0} all-to-all(...)``
          ``ROOT %cp = (f32[2,4]{...}, u32[]) collective-permute(...)``
    Async decompositions count at the '-done' op (its result IS the
    received data; the '-start' result is a bundle whose tuple would
    double-count the operand)."""
    import re

    for line in text.splitlines():
        stripped = line.strip()
        m = re.match(r"^(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(.*)$", stripped)
        if not m:
            continue
        rhs = m.group(1)
        for k in _COLLECTIVE_OPS:
            for suffix in ("", "-done"):
                if re.search(rf"\b{k}{suffix}\(", rhs):
                    yield k, k + suffix, rhs
                    break
            else:
                continue
            break


def comm_bytes_from_compiled(compiled,
                             text: Optional[str] = None) -> Dict[str, int]:
    """Per-kind ICI/DCN communication bytes of a compiled program, read
    from its optimized HLO: every collective instruction's result shape
    (per-shard, SPMD) summed by op kind.  The measured side of the
    dryrun's ``comm_bytes=model:measured`` audit — XLA's
    ``cost_analysis`` does not break out collective traffic, the HLO
    does."""
    import re

    if text is None:
        text = compiled.as_text()
    out: Dict[str, int] = {}
    shape_re = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
    for kind, op, rhs in _collective_instructions(text):
        # result type is everything before the op name: one shape, or a
        # tuple of shapes
        type_part = rhs.split(op + "(")[0]
        nbytes = 0
        for dt, dims in shape_re.findall(type_part):
            if dt not in _DTYPE_BYTES:
                continue
            n = 1
            for d in dims.split(","):
                if d:
                    n *= int(d)
            nbytes += n * _DTYPE_BYTES[dt]
        out[kind] = out.get(kind, 0) + nbytes
    return out


def collective_counts_from_compiled(compiled,
                                    text: Optional[str] = None
                                    ) -> Dict[str, int]:
    """Per-kind collective INSTRUCTION counts of a compiled program
    (same :func:`_collective_instructions` parser as
    :func:`comm_bytes_from_compiled`, counting ops instead of result
    bytes).  The dryrun's per-stage reshard report reads the
    ``all-to-all`` entry: each layout switch is one all_to_all
    instruction per plane group."""
    if text is None:
        text = compiled.as_text()
    out: Dict[str, int] = {}
    for kind, _, _ in _collective_instructions(text):
        out[kind] = out.get(kind, 0) + 1
    return out


def donated_params_from_compiled(compiled,
                                 text: Optional[str] = None) -> set:
    """Parameter indices the compiled executable aliases to outputs —
    the *applied* side of ``donate_argnums``, read from the
    ``input_output_alias={ {out}: (param, {}, may-alias) }`` header of
    the optimized HLO.  A declared donation XLA could not match (shape/
    dtype mismatch with every output) does NOT appear here — exactly
    the drift the donation-applied compiled contract exists to catch."""
    import re

    if text is None:
        text = compiled.as_text()
    start = text.find("input_output_alias={")
    if start < 0:
        return set()
    # scan to the matching close brace (entries nest one level:
    # ``{ {out_idx}: (param, {}, may-alias), ... }``) — no length cap:
    # a truncated window would silently drop aliases and mint false
    # 'declared donation NOT applied' findings
    i = text.index("{", start)
    depth = 0
    close = None
    for j in range(i, len(text)):
        if text[j] == "{":
            depth += 1
        elif text[j] == "}":
            depth -= 1
            if depth == 0:
                close = j
                break
    if close is None:  # malformed header: no aliases rather than
        return set()   # scanning arbitrary HLO for ': (N,' matches
    body = text[i:close + 1]
    return {int(p) for p in re.findall(r":\s*\((\d+),", body)}


#: HLO markers of a device->host (or host->device) transfer inside a
#: compiled program: infeed/outfeed, send/recv pairs, and the python
#: callback custom-calls (io_callback / pure_callback / debug prints).
_HOST_TRANSFER_MARKERS = (
    " infeed(", " outfeed(", " send(", " recv(", " send-done(",
    " recv-done(", "xla_python_cpu_callback", "xla_ffi_python_cpu_callback",
    "xla_python_gpu_callback", "CustomCallWithHostTransfer",
)


def host_transfers_from_compiled(compiled,
                                 text: Optional[str] = None) -> list:
    """The host-transfer instructions of a compiled program (op line
    snippets), empty for a clean device-resident program.  The
    no-host-transfer compiled contract asserts this is empty outside
    declared materialization barriers."""
    out = []
    if text is None:
        text = compiled.as_text()
    for line in text.splitlines():
        stripped = line.strip()
        if any(m in stripped for m in _HOST_TRANSFER_MARKERS):
            out.append(stripped[:160])
    return out


def plan_cache_stats() -> Dict[str, object]:
    """Hit/miss/evict/build counters of the lazy planner's executable
    cache (tempo_tpu/plan/cache.py; LRU bound
    ``TEMPO_TPU_PLAN_CACHE_SIZE``), including the ``by_signature`` and
    ``by_tenant`` breakdowns (round 11: the query service attributes
    traffic per tenant via ``cache.tenant_scope``).  The serving-loop
    health metric: a steady-state query mix should be all hits — every
    miss re-runs the optimizer and may compile, and the breakdowns pin
    WHICH query shape or client caused it."""
    from tempo_tpu.plan.cache import CACHE

    return CACHE.stats()


def host_bytes(df: pd.DataFrame) -> int:
    """Driver-side in-memory size of a frame — the packed-columnar analog
    of the reference's ``explain cost`` sizeInBytes scrape."""
    return int(df.memory_usage(deep=True).sum())


def pick_asof_strategy(
    left_df: pd.DataFrame,
    right_df: pd.DataFrame,
    sql_join_opt: bool,
    has_sequence: bool,
    max_lookback: int,
) -> str:
    """'broadcast' | 'merge' | 'searchsorted' — mirrors the reference's
    decision tree (tsdf.py:482-509 fast path; the union/sort algorithm
    otherwise, with the merge variant when a sequence tie-break or row
    cap forces merged-stream coordinates).

    ``maxLookback`` wins over the broadcast fast path: the broadcast
    kernel has no row cap, and Scala — the source of maxLookback
    (asofJoin.scala:64-88) — has no broadcast path to mirror, so
    honouring the cap is the only semantics-preserving choice
    (ADVICE r3: the old order silently dropped the cap).

    This picks the *algorithm*; the orthogonal oversize *engine*
    decision (single-plan VMEM / lane-chunked streaming / host
    brackets) is :func:`pick_join_engine`, consulted by join.py once
    the merged-lane estimate is known."""
    if max_lookback and max_lookback > 0:
        if sql_join_opt:
            logger.warning(
                "asofJoin: sql_join_opt is ignored when maxLookback is "
                "set — the broadcast fast path cannot bound lookback"
            )
        return "merge"
    if sql_join_opt and (
        host_bytes(left_df) < BROADCAST_BYTES_THRESHOLD
        or host_bytes(right_df) < BROADCAST_BYTES_THRESHOLD
    ):
        return "broadcast"
    if has_sequence:
        return "merge"
    return "searchsorted"
