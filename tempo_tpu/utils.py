"""Display / environment adapters.

Parity with python/tempo/utils.py:11-98: detect the runtime environment
(Databricks vs notebook vs terminal) and bind a ``display`` function that
renders a TSDF appropriately.  The HTML path degrades gracefully when
IPython is absent.
"""

from __future__ import annotations

import logging

import pandas as pd

from tempo_tpu import config

logger = logging.getLogger(__name__)

PLATFORM = (
    "DATABRICKS"
    if config.env_external("DATABRICKS_RUNTIME_VERSION") is not None
    else "NON_DATABRICKS"
)


def __isnotebookenv() -> bool:
    try:
        from IPython import get_ipython  # type: ignore

        shell = get_ipython().__class__.__name__
        return shell == "ZMQInteractiveShell"
    except Exception:
        return False


def display_html(df) -> None:
    """Render a frame as HTML in notebook environments."""
    try:
        from IPython.core.display import HTML  # type: ignore
        from IPython.display import display as ipydisplay  # type: ignore

        ipydisplay(HTML("<style>pre { white-space: pre !important; }</style>"))
    except Exception as e:
        # cosmetic only — but never swallowed silently (bare-except ban,
        # tools/check_no_bare_except.py)
        logger.debug("notebook HTML styling unavailable: %s", e)
    if isinstance(df, pd.DataFrame):
        print(df.head(20).to_string(index=False))
    else:
        logger.error("'display' method not available for this object")


def display_unavailable(df) -> None:
    logger.error(
        "'display' method not available in this environment. Use 'show' method instead."
    )


ENV_BOOLEAN = __isnotebookenv()


def _frame_of(obj):
    return obj.df if type(obj).__name__ == "TSDF" else obj


def _databricks_native_display():
    """The Databricks notebook's own ``display`` from the IPython user
    namespace (reference utils.py:57-60) — the rich-table binding users
    expect on that platform; None when unavailable."""
    try:
        from IPython import get_ipython  # type: ignore

        return get_ipython().user_ns["display"]
    except Exception:
        return None


if PLATFORM == "DATABRICKS" and _databricks_native_display() is not None:
    method = _databricks_native_display()

    def display_improvised(obj):
        """Parity: reference utils.py:61-66 — route through the
        notebook's native display, unwrapping TSDFs."""
        method(_frame_of(obj))

    display = display_improvised
elif ENV_BOOLEAN:

    def display_html_improvised(obj):
        display_html(_frame_of(obj))

    display = display_html_improvised
else:

    def display_terminal(obj):
        df = _frame_of(obj)
        if isinstance(df, pd.DataFrame):
            print(df.head(20).to_string(index=False))
        else:
            display_unavailable(df)

    display = display_terminal
