"""Explicit DMA pipelining for the HBM-stream-bound kernels.

The streaming kernels (``ops/pallas_window.py``, ``ops/pallas_bucket.py``)
ran at 0.18-0.28 of the *measured* 675 GB/s stream rate (BENCH_r05
``roofline``): every grid step's HBM->VMEM block copy rode Mosaic's
implicit BlockSpec pipeline, which is fixed at double buffering and
couples the copy granularity to the compute granularity.  This module
provides the two mechanisms BlockSpecs cannot express:

* :func:`ring_call` — an **N-deep input ring**: the operands stay in
  HBM (``memory_space=ANY``) and the kernel streams row slabs through
  ``pltpu.make_async_copy`` into a ``TEMPO_TPU_DMA_BUFFERS``-slot VMEM
  ring, so the copy of slab *i+N-1* overlaps the compute of slab *i*
  (depth-2 is exactly the implicit pipeline's overlap; deeper rings
  smooth slabs whose compute time varies).  Outputs stage through a
  double-buffered VMEM slab pair and DMA out asynchronously, so the
  write of slab *i* overlaps the compute of slab *i+1* — the implicit
  pipeline serialises the final writeback of each step.  The slab loop
  is a *python* loop (static trip count, static ring slots): no
  dynamic-slot indexing for Mosaic to spill, at the cost of a
  per-slab-count compile (bounded by :data:`MAX_RING_SLABS`).
* :func:`grid_semantics` — megacore grid partitioning: carry-free grid
  axes are declared ``"parallel"`` so Mosaic splits them across both
  TensorCores on megacore parts (v4/v5p; a no-op on single-core v5e).
  Axes with cross-step carry state (the chunked merge's fill scratch,
  any manual ring) MUST stay ``"arbitrary"`` — a parallel split would
  hand half the sequential carry chain to each core.  Callers name
  their carry axes; this function never guesses.

Both knobs are registered in ``tempo_tpu/config.py`` and documented in
BUILDING.md ("Roofline methodology"); bitwise identity of the ring
path against the BlockSpec path is pinned in
tests/test_pallas_window.py / test_pallas_bucket.py.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tempo_tpu.ops import pallas_kernels as pk

#: Ring slab-loop ceiling: the loop is python-unrolled (static slots —
#: Mosaic never sees a dynamic ring index), so the trace grows linearly
#: with the slab count; past this the BlockSpec pipeline path wins on
#: compile time and callers must fall back.
MAX_RING_SLABS = 256


def dma_buffers() -> int:
    """``TEMPO_TPU_DMA_BUFFERS`` — HBM->VMEM buffer depth.  2 (the
    default) keeps the implicit double-buffered BlockSpec pipeline;
    3..8 engage the explicit ring.  Clamped to [2, 8]: one buffer
    cannot overlap anything, and past 8 the ring's VMEM share starves
    the compute planes.  Env unset falls back to the tuned-profile
    prior (tempo_tpu/tune — the autotuner's measured winner for this
    device kind), then to the built-in 2."""
    from tempo_tpu import config, tune

    n = config.get_int("TEMPO_TPU_DMA_BUFFERS")
    if n is None:
        n = tune.knob_value("TEMPO_TPU_DMA_BUFFERS") or 2
    return max(2, min(int(n), 8))


def megacore_enabled() -> bool:
    """``TEMPO_TPU_MEGACORE`` — declare carry-free grid axes
    ``"parallel"`` (default on; harmless on single-core chips).  Env
    unset falls back to the tuned-profile prior, then on."""
    from tempo_tpu import config, tune

    val = config.get("TEMPO_TPU_MEGACORE")
    if val is None:
        tuned = tune.knob_value("TEMPO_TPU_MEGACORE")
        return True if tuned is None else bool(int(tuned))
    return config.get_bool("TEMPO_TPU_MEGACORE", True)


def grid_semantics(n_axes: int, carry_axes: Sequence[int] = ()):
    """``dimension_semantics`` for an ``n_axes`` grid whose
    ``carry_axes`` hold cross-step state (VMEM scratch carries, manual
    DMA rings).  Carry axes are always ``"arbitrary"`` — that is a
    legality rule, not a preference: Mosaic's megacore split hands each
    TensorCore a contiguous sub-range of a ``"parallel"`` axis, and a
    carry chain cut in half computes garbage on the second core.  The
    knob only widens/narrows the *remaining* axes."""
    if n_axes <= 0:
        return None
    on = megacore_enabled()
    return tuple(
        "arbitrary" if (i in carry_axes or not on) else "parallel"
        for i in range(n_axes)
    )


def ring_plan(K_pad: int, bk: int, depth: int):
    """(n_slabs, depth) of a feasible ring over ``K_pad`` padded rows in
    ``bk``-row slabs, or None when the ring cannot help (fewer than two
    slabs: nothing to overlap) or cannot compile cheaply (slab count
    past :data:`MAX_RING_SLABS` — the loop is python-unrolled)."""
    n_slabs = K_pad // bk
    if n_slabs < 2 or n_slabs > MAX_RING_SLABS:
        return None
    return n_slabs, max(2, min(depth, n_slabs))


def plan_with_ring(K: int, L: int, arrays_fn, depth: int,
                   bk_max: int = 32, budget: int = 90 * 2**20):
    """(grid, bk, K_pad, use_ring): block plan at the requested DMA
    depth, falling back to the implicit depth-2 BlockSpec pipeline
    when the N-deep ring's larger plane budget — ``arrays_fn(depth)``
    in [bk, L] f32 units — or the slab ring itself is infeasible.  The
    feasibility gates (``stream_supported`` & co) budget for depth 2,
    so a gated call must never crash merely because the
    ``TEMPO_TPU_DMA_BUFFERS`` knob is set high for a near-boundary
    shape.  Returns None only when even the depth-2 plan fails."""
    if depth > 2:
        p = pk._plan(K, L, arrays=arrays_fn(depth), bk_max=bk_max,
                     budget=budget)
        if p is not None and ring_plan(p[2], p[1], depth) is not None:
            return (*p, True)
    p = pk._plan(K, L, arrays=arrays_fn(2), bk_max=bk_max,
                 budget=budget)
    return None if p is None else (*p, False)


def pack_cols_cap() -> int:
    """``TEMPO_TPU_PACK_COLS`` — cap on the payload pack width; unset
    = the tuned-profile prior (tempo_tpu/tune), then the VMEM folding
    alone (bounded at 8: past that the per-step block shrinks below a
    sublane and the grid overhead eats the saved key reads)."""
    from tempo_tpu import config, tune

    n = config.get_int("TEMPO_TPU_PACK_COLS")
    if n is None:
        n = tune.knob_value("TEMPO_TPU_PACK_COLS")
    return max(1, min(int(n), 8)) if n else 8


def pack_budget(K: int, L: int, n_cols: int, arrays_fn,
                bk_max: int = 32, budget: int = 90 * 2**20) -> int:
    """Largest pack width c <= min(``n_cols``, :func:`pack_cols_cap`)
    whose [c, bk, L] block plan — ``arrays_fn(c)`` in [bk, L] f32
    plane units — fits the VMEM budget: the dynamic twin of the static
    analyzer's vmem-budget folding, shared by the window and bucket
    packers so their cap/clamp semantics cannot diverge.  Returns at
    least 1 (a single column either fits or the caller's per-column
    gate already rejected the shape)."""
    c = min(int(n_cols), pack_cols_cap())
    while c > 1:
        if pk._plan(int(K), int(L), arrays=arrays_fn(c), bk_max=bk_max,
                    budget=budget) is not None:
            return c
        c -= 1
    return 1


def _slab(ref, i: int, bk: int):
    """HBM slice of row slab ``i``: rank-2 planes block over rows,
    rank-3 (column-packed) planes over the middle axis."""
    if len(ref.shape) == 2:
        return ref.at[pl.ds(i * bk, bk)]
    return ref.at[:, pl.ds(i * bk, bk)]


def _make_ring_kernel(math, n_scalar: int, n_in: int, n_out: int,
                      bk: int, n_slabs: int, depth: int):
    """Kernel closure running ``math`` over every row slab with the
    N-deep input ring and double-buffered output staging.  ``math``
    takes (scalar_refs_tuple, slab_arrays_list) and returns ``n_out``
    f32 arrays shaped like the out-template slab."""

    def kernel(*refs):
        scalar_refs = refs[:n_scalar]
        in_refs = refs[n_scalar:n_scalar + n_in]
        out_refs = refs[n_scalar + n_in:n_scalar + n_in + n_out]
        sc = n_scalar + n_in + n_out
        rings = refs[sc:sc + n_in]
        stages = refs[sc + n_in:sc + n_in + n_out]
        in_sem = refs[sc + n_in + n_out]
        out_sem = refs[sc + n_in + n_out + 1]

        def in_dma(i: int, j: int):
            return pltpu.make_async_copy(
                _slab(in_refs[j], i, bk),
                rings[j].at[i % depth],
                in_sem.at[i % depth, j],
            )

        def out_dma(i: int, t: int):
            return pltpu.make_async_copy(
                stages[t].at[i % 2],
                _slab(out_refs[t], i, bk),
                out_sem.at[i % 2, t],
            )

        # warm-up: keep depth-1 slab copies in flight ahead of compute
        for i in range(min(depth - 1, n_slabs)):
            for j in range(n_in):
                in_dma(i, j).start()
        for i in range(n_slabs):
            slot = i % depth
            nxt = i + depth - 1
            if nxt < n_slabs:
                for j in range(n_in):
                    in_dma(nxt, j).start()
            for j in range(n_in):
                in_dma(i, j).wait()
            outs = math(scalar_refs, [rings[j][slot]
                                      for j in range(n_in)])
            # the stage pair is reused every other slab: the write of
            # slab i-2 must have landed before slab i overwrites it
            if i >= 2:
                for t in range(n_out):
                    out_dma(i - 2, t).wait()
            for t in range(n_out):
                stages[t][i % 2] = outs[t]
                out_dma(i, t).start()
        for i in range(max(n_slabs - 2, 0), n_slabs):
            for t in range(n_out):
                out_dma(i, t).wait()

    return kernel


def ring_call(math, scalars: Sequence, planes: Sequence, n_out: int,
              out_like: int, bk: int, depth: int,
              interpret: bool = False) -> Tuple:
    """Run ``math`` over row slabs of ``planes`` through the explicit
    DMA ring.  ``scalars`` ride SMEM; ``planes`` ([K_pad, L] or
    column-packed [C, K_pad, L], K_pad a multiple of ``bk``) stay in
    HBM and stream slab-by-slab; the ``n_out`` outputs are f32 arrays
    shaped like ``planes[out_like]``.  Callers are responsible for the
    VMEM plan (ring + stage + math temporaries must fit — the static
    analyzer's vmem-budget rule folds the declared ring/stage scratch
    at its full N-deep shape) and for checking :func:`ring_plan`."""
    planes = [jnp.asarray(p) for p in planes]
    K_pad = planes[0].shape[-2]
    plan = ring_plan(K_pad, bk, depth)
    if plan is None:
        raise ValueError(
            f"no feasible DMA ring at K_pad={K_pad}, bk={bk}: "
            f"ring_plan returned None — use the BlockSpec path")
    n_slabs, depth = plan
    n_scalar = len(scalars)
    n_in = len(planes)
    slab_shape = lambda p: p.shape[:-2] + (bk, p.shape[-1])
    out_tpl = planes[out_like]
    scratch = (
        [pltpu.VMEM((depth,) + slab_shape(p), p.dtype) for p in planes]
        + [pltpu.VMEM((2,) + slab_shape(out_tpl), jnp.float32)
           for _ in range(n_out)]
        + [pltpu.SemaphoreType.DMA((depth, n_in)),
           pltpu.SemaphoreType.DMA((2, n_out))]
    )
    kernel = _make_ring_kernel(math, n_scalar, n_in, n_out, bk,
                               n_slabs, depth)
    with pk.x64_off():
        out = pl.pallas_call(
            kernel,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * n_scalar
            + [pl.BlockSpec(memory_space=pltpu.ANY)] * n_in,
            out_specs=[pl.BlockSpec(memory_space=pltpu.ANY)] * n_out,
            out_shape=[jax.ShapeDtypeStruct(out_tpl.shape, jnp.float32)]
            * n_out,
            scratch_shapes=scratch,
            compiler_params=pk.tpu_compiler_params(
                vmem_limit_bytes=100 * 1024 * 1024,
            ),
            interpret=interpret,
        )(*scalars, *planes)
    return tuple(out)
