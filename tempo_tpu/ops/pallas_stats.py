"""Pallas VMEM kernel for the shifted-window range stats (legacy).

Since the streaming window engine landed (ops/pallas_window.py — same
semantics, leaner per-pass math, runtime window widths), the shifted
dispatcher prefers that module's unrolled form; this kernel stays as
the TEMPO_TPU_WINDOW_ENGINE=legacy fallback and the parity baseline
its tests pin.

``ops/sortmerge.py:range_stats_shifted`` computes Spark's
rangeBetween(-window, 0) aggregates as W static shifted masked
accumulations.  XLA fuses the passes, but the operand still crosses HBM
several times per aggregate; here the whole pass structure runs on a
[bk, L] block resident in VMEM — one HBM read of (secs, x, valid), one
write of the eight outputs, with every shift a ``pltpu.roll``.

Engages for f32 values with an int32-expressible seconds axis (the
frame layer already rebases per series, packing.py:rebase_seconds; the
wrapper rebases otherwise) on lane-aligned blocks; the XLA form remains
for CPU/f64 and infeasible shapes.  Semantics identical to
``range_stats_shifted`` including the ``clipped`` truncation audit —
parity pinned in tests/test_pallas_stats.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tempo_tpu.ops import pallas_kernels as pk

_I32_BIG = 2**31 - 1  # python int: jnp scalars capture as consts in kernels


def _shift(p, j: int, fill, shape):
    """out[:, i] = p[:, i-j] (j<0 looks ahead); rolled lanes become
    ``fill`` (negative roll shifts SIGABRT Mosaic — use L-|j|)."""
    if j == 0:
        return p
    L = shape[1]
    lane = jax.lax.broadcasted_iota(jnp.int32, shape, dimension=1)
    if j > 0:
        rolled = pltpu.roll(p, shift=jnp.int32(j), axis=1)
        return jnp.where(lane >= j, rolled, fill)
    rolled = pltpu.roll(p, shift=jnp.int32(L + j), axis=1)
    return jnp.where(lane < L + j, rolled, fill)


def _make_kernel(max_behind: int, max_ahead: int):
    def kernel(w_ref, secs_ref, x_ref, valid_ref,
               mean_ref, cnt_ref, mn_ref, mx_ref, sum_ref, std_ref,
               z_ref, clip_ref):
        w = w_ref[0]
        secs = secs_ref[:]
        x = x_ref[:]
        valid = valid_ref[:]
        shape = secs.shape

        # bool planes cannot ride pltpu.roll: shift an f32 image
        f0 = jnp.float32(0.0)
        f1 = jnp.float32(1.0)
        validf = valid.astype(jnp.float32)
        xz = jnp.where(valid, x, f0)
        nv = jnp.sum(validf, axis=1, keepdims=True)
        center = jnp.sum(xz, axis=1, keepdims=True) / jnp.maximum(nv, f1)
        xc = jnp.where(valid, x - center, f0)

        lo = secs - w
        pinf = jnp.float32(jnp.inf)
        cnt = jnp.zeros(shape, jnp.float32)
        s1 = jnp.zeros(shape, jnp.float32)
        s2 = jnp.zeros(shape, jnp.float32)
        mn = jnp.full(shape, pinf)
        mx = jnp.full(shape, -pinf)
        for j in range(-max_ahead, max_behind + 1):
            sj = _shift(secs, j, _I32_BIG, shape)
            inw = (sj >= lo) & (sj <= secs) & (
                _shift(validf, j, f0, shape) > f0
            )
            xj = _shift(xc, j, f0, shape)
            xr = _shift(x, j, f0, shape)
            cnt = cnt + inw.astype(jnp.float32)
            s1 = s1 + jnp.where(inw, xj, f0)
            s2 = s2 + jnp.where(inw, xj * xj, f0)
            mn = jnp.minimum(mn, jnp.where(inw, xr, pinf))
            mx = jnp.maximum(mx, jnp.where(inw, xr, -pinf))

        nan = jnp.float32(jnp.nan)
        mean = jnp.where(cnt > 0, s1 / jnp.maximum(cnt, f1) + center, nan)
        total = s1 + cnt * center
        var = jnp.where(
            cnt > 1,
            (s2 - s1 * s1 / jnp.maximum(cnt, f1))
            / jnp.maximum(cnt - f1, f1),
            nan,
        )
        std = jnp.where(cnt > 1, jnp.sqrt(jnp.maximum(var, f0)), nan)

        # truncation audit: mirrors range_stats_shifted exactly
        L = shape[1]
        clipped = jnp.zeros(shape, jnp.bool_)
        for j in (min(max_behind + 1, L), -min(max_ahead + 1, L)):
            sj = _shift(secs, j, _I32_BIG, shape)
            clipped = clipped | (
                (sj >= lo) & (sj <= secs)
                & (valid | (_shift(validf, j, f0, shape) > f0))
            )

        mean_ref[:] = mean
        cnt_ref[:] = cnt
        mn_ref[:] = jnp.where(cnt > 0, mn, nan)
        mx_ref[:] = jnp.where(cnt > 0, mx, nan)
        sum_ref[:] = jnp.where(cnt > 0, total, nan)
        std_ref[:] = std
        z_ref[:] = jnp.where(valid, (x - mean) / std, nan)
        clip_ref[:] = clipped.astype(jnp.float32)

    return kernel


# Largest unrolled window the kernel may take.  Probed on v5e: W=64
# compiles and runs (43s, bk=16); W≈150 fits standalone at bk=8 but
# overflows VMEM by 7M once the bench's fori-loop wraps it, and W≈266
# exceeds by 20M even at the minimum block — Mosaic's live temporaries
# grow superlinearly in W, so the bound sits at the largest probed
# size with comfortable margin.  Beyond this the XLA shifted form
# (which can spill) takes over, up to the frame layer's
# SHIFTED_MAX_ROWS; past that, the prefix-scan+RMQ windowed form.
_PALLAS_STATS_MAX_W = 64


def _plan_arrays(max_behind: int, max_ahead: int) -> int:
    """Live-plane budget for the block plan.  The base term covers
    I/O double buffers + accumulators (calibrated at the r3 window,
    W≈28, bk=32); the per-shift term covers the temporaries Mosaic's
    scheduler keeps live across the unrolled shift passes — measured:
    W=64 at bk=32 overflowed VMEM by 29M (157M used), so the window
    length must shrink the block."""
    return 32 + max_behind + max_ahead


@functools.partial(
    jax.jit, static_argnames=("max_behind", "max_ahead", "interpret")
)
def _stats_call(secs, x, valid, window, max_behind, max_ahead,
                interpret=False):
    K, L = x.shape
    plan = pk._plan(K, L, arrays=_plan_arrays(max_behind, max_ahead),
                    bk_max=32, budget=90 * 2**20)
    if plan is None:
        # callers consult range_stats_supported first; a whole-array
        # block here would be strictly larger than the one the planner
        # just rejected
        raise ValueError(
            f"range-stats kernel infeasible at L={L}: even an [8, {L}] "
            f"block exceeds the VMEM budget; use the XLA shifted form"
        )
    grid, bk, K_pad = plan
    secs = pk._pad_rows(secs, K_pad)
    x, valid = pk._pad_rows(x, K_pad), pk._pad_rows(valid, K_pad)
    with pk.x64_off():
        spec = pl.BlockSpec((bk, L), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
        out = pl.pallas_call(
            _make_kernel(max_behind, max_ahead),
            grid=grid,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)]
            + [spec] * 3,
            out_specs=[spec] * 8,
            out_shape=[jax.ShapeDtypeStruct((K_pad, L), jnp.float32)] * 8,
            # measured 18.9M at [8, 8192] blocks: over the 16M default
            # scoped cap; v5e has 128M physical VMEM (same treatment as
            # the merge kernel)
            compiler_params=pk.tpu_compiler_params(
                vmem_limit_bytes=100 * 1024 * 1024,
            ),
            interpret=interpret,
        )(jnp.asarray([window], jnp.int32), secs, x, valid)
    return tuple(o[:K] for o in out)


def pallas_block_feasible(K: int, L: int) -> bool:
    """Whether THIS kernel could take a [K, L] f32 shard at its window
    ceiling — the shard-shape part of :func:`range_stats_supported`,
    used by the auto-pick budget (ops/rolling.py:shifted_row_budget):
    the VMEM form's exemption from the XLA form's HBM bound only
    applies when the VMEM form is actually reachable."""
    return (
        int(L) % 128 == 0
        and jax.default_backend() == "tpu"
        and pk._plan(int(K), int(L),
                     arrays=_plan_arrays(_PALLAS_STATS_MAX_W, 0),
                     bk_max=32, budget=90 * 2**20) is not None
    )


def range_stats_supported(secs, x, valid, max_behind: int = 28,
                          max_ahead: int = 0) -> bool:
    return (
        x.dtype == jnp.float32
        and x.ndim == 2
        and x.shape[1] % 128 == 0
        and int(max_behind) + int(max_ahead) <= _PALLAS_STATS_MAX_W
        and jax.default_backend() == "tpu"
        and pk._plan(int(x.shape[0]), int(x.shape[1]),
                     arrays=_plan_arrays(int(max_behind), int(max_ahead)),
                     bk_max=32, budget=90 * 2**20) is not None
    )


def range_stats_pallas(secs, x, valid, window, max_behind: int,
                       max_ahead: int = 0, interpret: bool = False):
    """Drop-in VMEM form of ``range_stats_shifted``; same output dict.
    ``secs`` must fit int32 after the caller's per-series rebase (the
    wrapper in sortmerge casts and falls back when it cannot)."""
    with pk.interpret_scope(interpret):
        outs = _stats_call(
            secs.astype(jnp.int32), x, valid,
            jnp.asarray(window).astype(jnp.int32),
            max_behind=int(max_behind), max_ahead=int(max_ahead),
            interpret=interpret,
        )
    mean, cnt, mn, mx, total, std, z, clip = outs
    return {
        "mean": mean, "count": cnt, "min": mn, "max": mx, "sum": total,
        "stddev": std, "zscore": z,
        "clipped": jnp.sum(clip, axis=-1, keepdims=True),
    }
