"""Rolling / windowed statistics kernels on packed [K, L] series.

Replaces the reference's Spark Window scans:

* ``withRangeStats`` (tsdf.py:673-721): rangeBetween(-secs, 0) over the
  timestamp cast to long seconds, six aggregates per metric column plus
  a derived zscore.  Here: per-row window bounds from two vmapped
  ``searchsorted`` calls, sums/counts from exclusive prefix sums
  (mean-centred for f32-safe accumulation), min/max from an O(L log L)
  log-doubling sparse table - all fused by XLA into one pass over HBM.
* EMA (tsdf.py:615-635): the reference builds ``window`` lag-column
  expressions (plan blowup); here it is a single causal depthwise
  convolution with weights e(1-e)^i - MXU-friendly - plus an *exact*
  infinite-horizon variant via ``lax.associative_scan`` that the
  reference cannot express.
* grouped stats (tsdf.py:723-759): epoch-aligned tumbling windows as
  flat segment reductions (jax.ops.segment_*), num_segments static per
  call via host-computed bucket boundaries.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from tempo_tpu.ops import window_utils as wu

# Three-way auto-pick between the range-stats engines (the measured
# evidence is bench.py's ``rolling_crossover`` record):
#
# 1. **shifted** — W static masked shifted passes
#    (ops/sortmerge.py:range_stats_shifted; VMEM-resident via the
#    unrolled ops/pallas_window.py kernel on TPU).  Wins every extent
#    it can legally reach (shifted 175M rows/s vs windowed 8.0M on
#    identical ~140-row windows, BENCH_r05) but is bounded by
#    resources: compile-time growth on small shards (SHIFTED_MAX_ROWS)
#    and HBM shifted-copy materialisation on large ones
#    (:func:`shifted_row_budget`).
# 2. **stream** — the streaming VMEM sweep
#    (ops/pallas_window.py:range_stats_stream): same O(W) work but the
#    width is a runtime scalar, O(1) live planes, one HBM read — it
#    serves every extent the unrolled forms cannot, up to
#    TEMPO_TPU_STREAM_MAX_ROWS.
# 3. **windowed** — the general prefix-scan + RMQ form
#    (:func:`windowed_stats`).  Gather-bound on TPU (~96 ms per RMQ
#    take_along_axis at [1024, 8192]) — the last resort there, the
#    default off-TPU.
#
# TEMPO_TPU_WINDOW_ENGINE forces a choice (auto | shifted | stream |
# windowed | legacy — legacy keeps the pre-streaming pallas_stats
# kernel on the shifted path).
SHIFTED_MAX_ROWS = 512


def window_engine_override() -> str:
    from tempo_tpu import config

    return (config.get("TEMPO_TPU_WINDOW_ENGINE") or "auto").lower()


def pick_range_engine(n_elems: int, max_behind: int, max_ahead: int,
                      pallas_small_ok: bool = False,
                      stream_ok: bool = False) -> str:
    """'shifted' | 'stream' | 'windowed' for a frame whose row extent
    is (max_behind, max_ahead) on a shard of ``n_elems`` values.
    ``pallas_small_ok``/``stream_ok``: the caller verified the
    respective VMEM kernels can take this shard shape/dtype.

    When the lazy planner replays a node whose engine was hoisted to
    plan time (tempo_tpu/plan/optimizer.py), the decision arrives as a
    hint and wins — skipping the knob read — but only while it still
    matches what the current shard's bounds would pick.  The three
    engines differ in FMA/rounding order, so a cached plan replayed
    over different data (same shapes, different row bounds) must
    re-pick rather than force an engine eager execution would not
    choose — that would break the planned==eager bit-identity contract
    (MIGRATION.md v0.7).  Join hints have no such guard because every
    join engine is bit-identical to the others."""
    from tempo_tpu.ops import pallas_window as pw
    from tempo_tpu.plan import hints as plan_hints

    W = int(max_behind) + int(max_ahead)
    hinted = plan_hints.get("range_engine")
    if hinted in ("shifted", "stream", "windowed"):
        fits_shifted = W <= shifted_row_budget(n_elems, pallas_small_ok)
        fits_stream = stream_ok and W <= pw._stream_max_rows()
        if hinted == "shifted" and fits_shifted:
            return "shifted"
        if hinted == "stream" and not fits_shifted and fits_stream:
            return "stream"
        if hinted == "windowed" and not fits_shifted and not fits_stream:
            return "windowed"
        # the data moved out from under the hoisted decision: fall
        # through and re-pick (knob read included)
    forced = window_engine_override()
    if forced in ("shifted", "stream", "windowed"):
        return forced
    fits_shifted = W <= shifted_row_budget(n_elems, pallas_small_ok)
    fits_stream = stream_ok and W <= pw._stream_max_rows()
    from tempo_tpu.plan import cost as plan_cost

    if plan_cost.enabled():
        # cost-decided, but over the BITWISE-SAFE candidate set only:
        # the three engines differ in f32 rounding order, so the
        # revalidation lattice above admits exactly one engine per
        # shape and a cost argmin cannot drift from the rule pick —
        # the cost numbers surface in explain() via the plan-time
        # hoist, not on this per-call path
        # (plan/cost.py:decide_range_engine documents the contract)
        return plan_cost.decide_range_engine(W, n_elems, fits_shifted,
                                             fits_stream)
    if fits_shifted:
        return "shifted"
    if fits_stream:
        return "stream"
    return "windowed"


def range_stats_streaming(secs, x, valid, window, max_behind, max_ahead,
                          scale=None):
    """Streaming-engine entry: the VMEM sweep on TPU/f32/int32 keys,
    the exact windowed (prefix-scan + RMQ) form elsewhere.  Returns the
    ``range_stats_shifted`` output dict including ``clipped`` (always
    zero on the fallback — the windowed form has no truncation)."""
    from tempo_tpu.ops import pallas_window as pw

    secs = jnp.asarray(secs)
    x = jnp.asarray(x)
    valid = jnp.asarray(valid)
    if (secs.dtype == jnp.int32 and pw.stream_supported(x)
            and window_engine_override() != "windowed"):
        return pw.range_stats_stream(secs, x, valid, window,
                                     max_behind, max_ahead, scale=scale)
    if scale is not None:
        x = x * jnp.asarray(scale, x.dtype)
    start, end = range_window_bounds(secs, range_window_width(secs, window))
    try:
        max_w = 1 << (int(max_behind) + int(max_ahead) + 1).bit_length()
    except TypeError:
        # traced bounds (the streaming kernel takes them as runtime
        # scalars): build every sparse-table level instead
        max_w = 0
    stats = dict(windowed_stats(x, valid, start, end, max_window=max_w))
    stats["clipped"] = jnp.zeros((x.shape[0], 1), x.dtype)
    return stats


def packed_column_dispatch(n_cols, scales, gate, packed_group,
                           single_col):
    """Shared group/fallback/concat loop of the ``*_packed``
    multi-column entry points (here and
    ``sortmerge.range_stats_shifted_packed``).  Walks the column axis:
    where ``gate(c0)`` holds, ``packed_group(c0, scales_vec)`` reduces
    a kernel-pack-sized group in one pass and returns ``(width,
    stats-dict of [width, ...] planes)``; elsewhere ``single_col(c0,
    scale)`` runs the single-column dispatcher (results bitwise-equal
    to unpacked calls either way — the packed kernels trace the
    identical per-column op sequence).  Returns [C, ...] planes."""
    scv = None if scales is None else \
        jnp.broadcast_to(jnp.asarray(scales, jnp.float32).reshape(-1),
                         (n_cols,))
    parts = []
    c0 = 0
    while c0 < n_cols:
        if gate(c0):
            width, part = packed_group(c0, scv)
        else:
            width = 1
            single = single_col(c0, None if scv is None else scv[c0])
            part = {k: v[None] for k, v in single.items()}
        parts.append(part)
        c0 += width
    if len(parts) == 1:
        return parts[0]
    return {k: jnp.concatenate([p[k] for p in parts]) for k in parts[0]}


def range_stats_streaming_packed(secs, xs, valids, window, max_behind,
                                 max_ahead, scales=None):
    """Multi-column :func:`range_stats_streaming`: ``xs``/``valids``
    are [C, K, L] stacks over one [K, L] key plane.  On TPU the
    columns run as packed kernel passes (``pack_cols_budget``-sized
    groups — the key planes cross HBM once per group instead of once
    per column); elsewhere, and for any residual infeasible group, a
    per-column loop of :func:`range_stats_streaming` whose results are
    bitwise-identical to the unpacked calls.  Output planes are
    [C, K, L] ([C, K, 1] for ``clipped``)."""
    from tempo_tpu.ops import pallas_window as pw

    secs = jnp.asarray(secs)
    xs = jnp.asarray(xs)
    valids = jnp.asarray(valids)
    C, K, L = xs.shape

    def gate(c0):
        return (secs.dtype == jnp.int32 and pw.stream_supported(xs[c0])
                and window_engine_override() != "windowed")

    def packed_group(c0, scv):
        width = pw.pack_cols_budget(K, L, C - c0)
        return width, pw.range_stats_stream_packed(
            secs, xs[c0:c0 + width], valids[c0:c0 + width], window,
            max_behind, max_ahead,
            scales=None if scv is None else scv[c0:c0 + width])

    def single_col(c0, scale):
        return range_stats_streaming(secs, xs[c0], valids[c0], window,
                                     max_behind, max_ahead, scale=scale)

    return packed_column_dispatch(C, scales, gate, packed_group,
                                  single_col)


def shifted_row_budget(n_elems: int, pallas_ok: bool = False) -> int:
    """Largest row extent the shifted form may take for a shard of
    ``n_elems`` values.  The XLA form materialises ~2.4 shifted operand
    copies per unrolled pass (measured on v5e at [1024, 8192]: W=512
    demanded 40.9G of the 15.75G HBM; W=139 fit), so the memory bound
    scales inversely with the shard's element count; 12G of the 15.75G
    is budgeted, with a 3x-per-pass margin over the measured 2.4.

    ``pallas_ok`` (the caller verified the VMEM kernel can take this
    shard shape/dtype — pallas_stats.pallas_block_feasible) floors the
    budget at that kernel's window ceiling: extents IT accepts never
    materialise shifted copies in HBM.  The floor must not apply
    otherwise — a shard the Pallas gate rejects for shape reasons
    falls to the XLA form, where the memory bound is real (code-review
    r4 finding)."""
    from tempo_tpu.ops.pallas_stats import _PALLAS_STATS_MAX_W
    from tempo_tpu.ops.pallas_window import UNROLL_MAX_W

    mem_rows = int(12e9 // max(n_elems * 4 * 3, 1))
    if pallas_ok:
        mem_rows = max(mem_rows, _PALLAS_STATS_MAX_W, UNROLL_MAX_W)
    return min(SHIFTED_MAX_ROWS, mem_rows)


def _sparse_table(arr: jnp.ndarray, fill, reducer, nlev: int = 0) -> jnp.ndarray:
    """Log-doubling table [K, L, nlev]: level k reduces the trailing 2^k
    elements ending at each position.  ``nlev`` caps the levels when the
    caller knows the maximum window length (levels beyond
    floor(log2(max_len)) are never queried)."""
    L = arr.shape[-1]
    full = max(1, (L - 1).bit_length() + 1)
    nlev = full if nlev <= 0 else min(nlev, full)
    levels = [arr]
    span = 1
    for _ in range(nlev - 1):
        prev = levels[-1]
        levels.append(reducer(prev, wu._shift_right(prev, span, fill)))
        span *= 2
    return jnp.stack(levels, axis=-1)  # [K, L, nlev]


def _range_query(table: jnp.ndarray, start: jnp.ndarray, end: jnp.ndarray, reducer):
    """Reduce table's base array over [start, end) per row; end > start.

    Classic two-overlapping-spans RMQ: with k = floor(log2(end-start)),
    combine the 2^k-span ending at end-1 and the one ending at
    start+2^k-1.  The (position, level) lookup is a single gather into
    the level-flattened table: the chained two-gather form sent XLA's
    compiler into a 2-minute pathological optimisation (136s vs 2.6s
    compile, measured on v5e).
    """
    K, L, nlev = table.shape
    flat = table.reshape(K, L * nlev)
    # f32 log2 avoids f64 emulation on TPU but can round UP for lengths
    # just below a large power of two (e.g. 2^21-1 -> 21); a level whose
    # span exceeds the window would read out-of-window elements, so
    # decrement k when that happens (the true floor is then exactly k-1)
    length = jnp.maximum(end - start, 1)
    k = jnp.floor(jnp.log2(length.astype(jnp.float32))).astype(jnp.int32)
    k = jnp.where((1 << k) > length, k - 1, k)
    k = jnp.minimum(k, nlev - 1)
    span = (1 << k).astype(start.dtype)
    p1 = (end - 1).astype(jnp.int32) * nlev + k
    p2 = (start + span - 1).astype(jnp.int32) * nlev + k
    return reducer(
        jnp.take_along_axis(flat, p1, axis=1),
        jnp.take_along_axis(flat, p2, axis=1),
    )


def range_window_width(ts_long: jnp.ndarray, window_secs) -> jnp.ndarray:
    """Exact window-width operand for :func:`range_window_bounds` over
    an INTEGER seconds axis.  Membership ``ts >= t - w`` with integer
    keys equals ``ts >= t - floor(w)`` (a fractional remainder can
    never be met exactly by integer timestamps), so every width folds
    to the axis dtype: no float compare — neither the weak-f64 bound
    arithmetic a bare ``jnp.asarray(w)`` mints under the library's
    global x64 mode (the compiled no-f64-leak contract class) nor the
    epoch-scale rounding a float32 cast would inflict (~128 s
    resolution at 1.7e9).  The ONE way dist.py / parallel/halo.py /
    rolling.py build the operand; fractional widths keep exact Spark
    ``rangeBetween`` semantics.  A traced (jit-operand) width floors
    in its own dtype before the integer cast."""
    import math

    if isinstance(window_secs, jax.core.Tracer):
        w = jnp.asarray(window_secs)
        if jnp.issubdtype(w.dtype, jnp.integer):
            return w.astype(ts_long.dtype)
        return jnp.floor(w).astype(ts_long.dtype)
    return jnp.asarray(ts_long.dtype.type(math.floor(float(window_secs))))


@jax.jit
def range_window_bounds(
    ts_long: jnp.ndarray, window_secs: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-row [start, end) bounds of rangeBetween(-window_secs, 0) over a
    sorted long-seconds timestamp axis.  Note Spark range windows include
    *following* rows that share the current row's order-key value, hence
    end = upper_bound(ts[i]) not i+1."""
    start = wu.searchsorted_batched(ts_long, ts_long - window_secs, side="left")
    end = wu.searchsorted_batched(ts_long, ts_long, side="right")
    return start.astype(jnp.int32), end.astype(jnp.int32)


@functools.partial(jax.jit, static_argnames=("max_window",))
def windowed_stats(
    x: jnp.ndarray,        # [K, L] float values
    valid: jnp.ndarray,    # [K, L] bool
    start: jnp.ndarray,    # [K, L] int32 window start (inclusive)
    end: jnp.ndarray,      # [K, L] int32 window end (exclusive)
    max_window: int = 0,   # static upper bound on end-start rows (0 = L)
) -> Dict[str, jnp.ndarray]:
    """mean/count/min/max/sum/stddev(sample)/zscore over per-row windows.

    Accumulations are mean-centred per series before the prefix sums so
    the sum-of-squares cancellation stays benign even in float32.  When
    the caller can bound the window length in rows (``max_window``), the
    min/max sparse tables only build the levels that bound can query —
    at a 10s window over ~1Hz data that is 4 levels instead of 14.
    Passing a bound smaller than a real window silently degrades min/max
    coverage, so callers must compute it from the actual bounds.
    """
    xz = jnp.where(valid, x, 0.0)
    n_valid = jnp.sum(valid, axis=-1, keepdims=True)
    center = jnp.sum(xz, axis=-1, keepdims=True) / jnp.maximum(n_valid, 1)
    xc = jnp.where(valid, x - center, 0.0)

    # inclusive prefix sums (one fused Pallas pass on TPU/f32); the
    # window query uses C[e-1] - C[s-1] with C[-1] = 0
    from tempo_tpu.ops import pallas_kernels as pk

    P1, P2, Pc = pk.cumsum3(xc, valid)

    def win(P):
        P = P.astype(x.dtype)
        hi = jnp.take_along_axis(P, jnp.maximum(end - 1, 0), axis=-1)
        hi = jnp.where(end > 0, hi, 0.0)
        lo = jnp.take_along_axis(P, jnp.maximum(start - 1, 0), axis=-1)
        lo = jnp.where(start > 0, lo, 0.0)
        return hi - lo

    s1, s2, cnt = win(P1), win(P2), win(Pc)
    mean = jnp.where(cnt > 0, s1 / jnp.maximum(cnt, 1) + center, jnp.nan)
    total = s1 + cnt * center
    var = jnp.where(
        cnt > 1, (s2 - s1 * s1 / jnp.maximum(cnt, 1)) / jnp.maximum(cnt - 1, 1), jnp.nan
    )
    std = jnp.sqrt(jnp.maximum(var, 0.0))
    std = jnp.where(cnt > 1, std, jnp.nan)

    nlev = (max(1, int(max_window)) - 1).bit_length() + 1 if max_window else 0
    pinf = jnp.array(jnp.inf, x.dtype)
    tmin = _sparse_table(jnp.where(valid, x, pinf), pinf, jnp.minimum, nlev)
    tmax = _sparse_table(jnp.where(valid, x, -pinf), -pinf, jnp.maximum, nlev)
    wmin = _range_query(tmin, start, end, jnp.minimum)
    wmax = _range_query(tmax, start, end, jnp.maximum)
    wmin = jnp.where(cnt > 0, wmin, jnp.nan)
    wmax = jnp.where(cnt > 0, wmax, jnp.nan)

    zscore = (x - mean) / std
    return {
        "mean": mean,
        "count": cnt,
        "min": wmin,
        "max": wmax,
        "sum": jnp.where(cnt > 0, total, jnp.nan),
        "stddev": std,
        "zscore": jnp.where(valid, zscore, jnp.nan),
    }


def bucket_stats(bid, x, valid, start, end):
    """Tumbling-bucket aggregates broadcast to every row of the bucket
    (the resample/groupedStats reduction, reference resample.py:38-117
    / tsdf.py:723-759).  On TPU/f32 the whole reduction runs as ONE
    VMEM segmented-scan kernel (ops/pallas_bucket.py — no
    searchsorteds, no prefix-sum gathers, no RMQ tables); elsewhere the
    ``windowed_stats`` form over the precomputed [start, end) bucket
    bounds.  ``bid`` is the per-row int32 bucket id (non-decreasing;
    pad rows share a clamped id and form their own bucket — callers
    mask their outputs)."""
    from tempo_tpu.ops import pallas_bucket as pb

    if pb.bucket_stats_supported(x):
        return pb.bucket_stats_pallas(bid, x, valid)
    return windowed_stats(x, valid, start, end)


def bucket_stats_multi(bid, xs, valids, start, end):
    """Multi-column :func:`bucket_stats`: ``xs``/``valids`` are
    [C, K, L] stacks over one [K, L] bucket-id plane.  On TPU the
    columns run as packed kernel passes
    (``pallas_bucket.bucket_pack_budget``-sized groups — the id plane
    and its head/tail flag ladders cross HBM and the VPU once per group
    instead of once per column); elsewhere, and for any infeasible
    column, the single-column dispatch.  Returns [C, K, L] planes,
    bitwise-identical to C :func:`bucket_stats` calls."""
    from tempo_tpu.ops import pallas_bucket as pb

    xs = jnp.asarray(xs)
    valids = jnp.asarray(valids)
    C, K, L = xs.shape

    def gate(c0):
        return pb.bucket_stats_supported(xs[c0])

    def packed_group(c0, scv):
        width = pb.bucket_pack_budget(K, L, C - c0)
        return width, pb.bucket_stats_packed(
            bid, xs[c0:c0 + width], valids[c0:c0 + width])

    def single_col(c0, scale):
        return bucket_stats(bid, xs[c0], valids[c0], start, end)

    return packed_column_dispatch(C, None, gate, packed_group,
                                  single_col)


@functools.partial(jax.jit, static_argnames=("num_segments",))
def segment_stats(
    x: jnp.ndarray,        # [n] flat values
    valid: jnp.ndarray,    # [n] bool
    seg_ids: jnp.ndarray,  # [n] int32 sorted segment ids
    num_segments: int,
) -> Dict[str, jnp.ndarray]:
    """Six grouped aggregates per segment (withGroupedStats tsdf.py:750-754)."""
    xz = jnp.where(valid, x, 0.0)
    cnt = jax.ops.segment_sum(valid.astype(x.dtype), seg_ids, num_segments)
    s1 = jax.ops.segment_sum(xz, seg_ids, num_segments)
    s2 = jax.ops.segment_sum(xz * xz, seg_ids, num_segments)
    pinf = jnp.array(jnp.inf, x.dtype)
    mn = jax.ops.segment_min(jnp.where(valid, x, pinf), seg_ids, num_segments)
    mx = jax.ops.segment_max(jnp.where(valid, x, -pinf), seg_ids, num_segments)
    mean = jnp.where(cnt > 0, s1 / jnp.maximum(cnt, 1), jnp.nan)
    var = jnp.where(
        cnt > 1, (s2 - s1 * s1 / jnp.maximum(cnt, 1)) / jnp.maximum(cnt - 1, 1), jnp.nan
    )
    std = jnp.where(cnt > 1, jnp.sqrt(jnp.maximum(var, 0.0)), jnp.nan)
    return {
        "mean": mean,
        "count": cnt,
        "min": jnp.where(cnt > 0, mn, jnp.nan),
        "max": jnp.where(cnt > 0, mx, jnp.nan),
        "sum": jnp.where(cnt > 0, s1, jnp.nan),
        "stddev": std,
    }


@functools.partial(jax.jit, static_argnames=("window",))
def ema_compat(x: jnp.ndarray, valid: jnp.ndarray, window: int, exp_factor: float) -> jnp.ndarray:
    """Reference-parity truncated EMA (tsdf.py:615-635):
    EMA_t = sum_{i=0}^{window-1} e(1-e)^i * x_{t-i}, null lags contribute 0.

    One causal depthwise convolution instead of `window` stacked Spark
    window expressions.
    """
    w = exp_factor * (1.0 - exp_factor) ** jnp.arange(window, dtype=x.dtype)
    xz = jnp.where(valid, x, 0.0)[:, None, :]                  # [K, 1, L]
    filt = w[::-1][None, None, :]                              # [1, 1, W]
    y = jax.lax.conv_general_dilated(
        xz, filt, window_strides=(1,), padding=[(window - 1, 0)],
        dimension_numbers=("NCH", "IOH", "NCH"),
    )
    return y[:, 0, :]


def ema_scan(x: jnp.ndarray, valid: jnp.ndarray, alpha,
             y0: jnp.ndarray = None):
    """Sequential (``lax.scan``) twin of :func:`ema_exact` with an
    explicit carry: ``(ys, y_end)`` where ``ys`` is the EMA at every
    position and ``y_end`` the carry after the last one.

    Same recurrence — ``y_t = decay_t * y_{t-1} + inp_t`` with
    ``decay = 1-a`` / ``inp = a*x`` at valid rows and ``1`` / ``0`` at
    null rows — but evaluated strictly left-to-right, ONE multiply-add
    per element.  That makes it **split-invariant bitwise**: feeding
    ``y_end`` back as ``y0`` across any batch boundary reproduces the
    unsplit run bit-for-bit, which is the contract the online serving
    engine is built on (``tempo_tpu/serve/state.py``).
    :func:`ema_exact`'s ``associative_scan`` computes the same values
    through a combine tree whose bracketing — and therefore f32
    rounding — depends on the total length, so it cannot be resumed
    mid-stream exactly.  ``y0=None`` starts from the zero carry, which
    matches the scan's implicit start exactly (``0*d + i == i``)."""
    a = jnp.asarray(alpha, x.dtype)
    one = jnp.asarray(1.0, x.dtype)
    zero = jnp.asarray(0.0, x.dtype)
    decay = jnp.where(valid, one - a, one)
    inp = jnp.where(valid, a * x, zero)
    if y0 is None:
        y0 = jnp.zeros(x.shape[:-1], x.dtype)

    def step(y, di):
        d, i = di
        y2 = d * y + i
        return y2, y2

    y_end, ys = jax.lax.scan(
        step, y0, (jnp.moveaxis(decay, -1, 0), jnp.moveaxis(inp, -1, 0)))
    return jnp.moveaxis(ys, 0, -1), y_end


@jax.jit
def ema_exact(x: jnp.ndarray, valid: jnp.ndarray, alpha: float) -> jnp.ndarray:
    """Exact infinite-horizon EMA y_t = (1-a) y_{t-1} + a x_t via an
    associative scan (the full story of the reference's truncated-lag
    approximation and this stack's exact forms:
    resample.py:resample_ema, "Truncated-lag EMA — the canonical
    note").  Null inputs carry the previous EMA forward."""
    a = jnp.asarray(alpha, x.dtype)
    decay = jnp.where(valid, 1.0 - a, 1.0)
    inp = jnp.where(valid, a * x, 0.0)

    def combine(c1, c2):
        d1, v1 = c1
        d2, v2 = c2
        return d1 * d2, v2 + d2 * v1

    d, y = jax.lax.associative_scan(combine, (decay, inp), axis=1)
    return y
