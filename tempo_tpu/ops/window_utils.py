"""Shared window primitives for packed [K, L] series kernels.

These replace Spark's Window-expression machinery (reference
python/tempo/tsdf.py:563-580 window builders): instead of a sorted
shuffle + streaming window scan per key, we use O(L log L) data-parallel
primitives (prefix scans, log-doubling range queries, searchsorted) that
map onto the TPU VPU and keep everything inside one fused XLA program.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


# NOTE: jnp.cumsum / lax.cummax lower to reduce-window on the CPU/axon
# backends with catastrophic compile times (100s+ at L~2000, measured);
# associative_scan lowers to the log-depth scan XLA compiles in ~1s.
# All cumulative ops in tempo-tpu go through these wrappers.


def cumsum(x: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    return jax.lax.associative_scan(jnp.add, x, axis=axis % x.ndim)


def cummax(x: jnp.ndarray, axis: int = -1, reverse: bool = False) -> jnp.ndarray:
    return jax.lax.associative_scan(
        jnp.maximum, x, axis=axis % x.ndim, reverse=reverse
    )


def cummin(x: jnp.ndarray, axis: int = -1, reverse: bool = False) -> jnp.ndarray:
    return jax.lax.associative_scan(
        jnp.minimum, x, axis=axis % x.ndim, reverse=reverse
    )


def last_valid_index_xla(valid: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    n = valid.shape[axis]
    idx = jnp.arange(n, dtype=jnp.int32)
    idx = jnp.broadcast_to(idx, valid.shape)
    cand = jnp.where(valid, idx, -1)
    return cummax(cand, axis=axis)


def last_valid_index(valid: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Running index of the last True up to and including each position.

    -1 where no valid element has been seen yet.  This is the vectorised
    equivalent of Spark's ``last(col, ignoreNulls=True)`` over an
    unbounded-preceding window (reference tsdf.py:139).  On TPU the
    [K, L] lane-aligned case runs as a fused Pallas VMEM scan.
    """
    if valid.ndim == 2 and axis in (-1, 1):
        from tempo_tpu.ops import pallas_kernels as pk

        if pk._index_supported(jnp.asarray(valid)):
            return pk.last_valid_index_scan(valid)
    return last_valid_index_xla(valid, axis)


def first_valid_index_xla(valid: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    n = valid.shape[axis]
    idx = jnp.arange(n, dtype=jnp.int32)
    idx = jnp.broadcast_to(idx, valid.shape)
    cand = jnp.where(valid, idx, n)
    return cummin(cand, axis=axis, reverse=True)


def first_valid_index(valid: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """Index of the first True at or after each position; n where none.

    Equivalent of ``first(col, ignoreNulls=True)`` over a current-row-to-
    unbounded-following window (reference interpol.py:216-222).  On TPU
    the [K, L] lane-aligned case runs as a fused Pallas VMEM scan.
    """
    if valid.ndim == 2 and axis in (-1, 1):
        from tempo_tpu.ops import pallas_kernels as pk

        if pk._index_supported(jnp.asarray(valid)):
            return pk.first_valid_index_scan(valid)
    return first_valid_index_xla(valid, axis)


def _shift_right(x: jnp.ndarray, k: int, fill) -> jnp.ndarray:
    """Shift along last axis: out[..., i] = x[..., i-k] (fill for i<k)."""
    if k == 0:
        return x
    pad = jnp.full(x.shape[:-1] + (k,), fill, dtype=x.dtype)
    return jnp.concatenate([pad, x[..., :-k]], axis=-1)


def windowed_max_last(x: jnp.ndarray, window: int) -> jnp.ndarray:
    """max over the trailing ``window`` elements (inclusive) per position.

    Log-doubling sparse-table construction: O(L log W) work, fully
    vectorised - the TPU-friendly replacement for Spark's
    ``rowsBetween(-W+1, 0)`` max scan (scala asofJoin.scala:64-88
    maxLookback window).
    """
    if window <= 0:
        raise ValueError("window must be >= 1")
    # a window covering the whole axis equals the axis length (and
    # _shift_right cannot represent longer shifts): callers may pass
    # caps larger than the data (asofJoin maxLookback)
    window = min(int(window), int(x.shape[-1]))
    neg = jnp.finfo(x.dtype).min if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    # doubling table: level k covers 2^k trailing elements
    levels = [x]
    span = 1
    while span < window:
        prev = levels[-1]
        levels.append(jnp.maximum(prev, _shift_right(prev, span, neg)))
        span *= 2
    if span == window:
        return levels[-1]
    # combine two overlapping power-of-two spans covering exactly `window`
    k = len(levels) - 1
    half = 1 << (k - 1)
    lo = levels[k - 1]
    return jnp.maximum(lo, _shift_right(lo, window - half, neg))


def windowed_last_valid(has: jnp.ndarray, val: jnp.ndarray, window: int,
                        min_pos: jnp.ndarray = None):
    """(value at the last ``has``-True position within the trailing
    ``window`` elements inclusive, found flag) per position.

    The bounded-lookback sibling of the unbounded forward-fill scan:
    the same log-doubling construction as :func:`windowed_max_last`
    (argmax is idempotent, so two overlapping power-of-two spans
    combine exactly) carrying the value as an argmax payload.  This is
    the engine of Scala's ``maxLookback`` rowsBetween(-W+1, 0) merged-
    stream cap (scala asofJoin.scala:64-88) in packed form.

    ``min_pos`` (broadcastable int32, the per-position segment-head
    lane) fences the window at segment boundaries for bin-packed rows:
    the found flag additionally requires the winning position to sit
    at-or-after it.  The fence is exact post-hoc because segments are
    contiguous and the ladder takes the *largest* has-position — a
    cross-segment candidate (strictly before the head, so a strictly
    smaller position) can only win when no same-segment candidate
    exists in the window.
    """
    if window <= 0:
        raise ValueError("window must be >= 1")
    # a window covering the whole axis is equivalent to the axis length
    # (and _shift_right cannot represent longer shifts)
    window = min(int(window), int(has.shape[-1]))
    lane = jnp.broadcast_to(
        jnp.arange(has.shape[-1], dtype=jnp.int32), has.shape
    )
    pos = jnp.where(has, lane, -1)

    def combine(p, v, ps, vs):
        take = ps > p
        return jnp.where(take, ps, p), jnp.where(take, vs, v)

    levels = [(pos, val)]
    span = 1
    while span < window:
        p, v = levels[-1]
        levels.append(combine(p, v, _shift_right(p, span, -1),
                              _shift_right(v, span, jnp.zeros((), v.dtype))))
        span *= 2
    p, v = levels[-1]
    if span != window:
        k = len(levels) - 1
        half = 1 << (k - 1)
        p, v = levels[k - 1]
        p, v = combine(p, v, _shift_right(p, window - half, -1),
                       _shift_right(v, window - half,
                                    jnp.zeros((), v.dtype)))
    floor = 0 if min_pos is None else jnp.maximum(min_pos, 0)
    return v, p >= floor


def searchsorted_batched(sorted_keys: jnp.ndarray, queries: jnp.ndarray, side: str = "left") -> jnp.ndarray:
    """Batched searchsorted over the leading (series) axis.

    API CONTRACT: ``queries`` MUST be ascending along the last axis (per
    row), in addition to ``sorted_keys``.  On TPU this dispatches to the
    sort-and-scan merge (:func:`tempo_tpu.ops.sortmerge.merge_rank`) —
    measured ~25x faster than binary search there, which lowers to a
    per-step dynamic gather — and the merge returns ranks in
    sorted-query order: unsorted queries get silently wrong ranks for
    the whole row, not an error.  Every tempo-tpu caller passes
    shifted/bucketed versions of an already-sorted time axis.  CPU keeps
    the vmapped binary search (fast native searchsorted, no sort cost),
    which happens to tolerate unsorted queries — do not rely on that.
    """
    from tempo_tpu.ops import sortmerge as sm

    if sorted_keys.ndim == 2 and queries.ndim == 2 and sm.use_sort_kernels():
        from tempo_tpu.ops import pallas_merge as pm

        if pm.merge_rank_supported(sorted_keys, queries):
            # one VMEM pass (merge + count + unmerge) instead of
            # merge_rank's two lax.sort ladders
            return pm.merge_rank_pallas(sorted_keys, queries, side=side)
        return sm.merge_rank(sorted_keys, queries, side=side)
    fn = lambda a, v: jnp.searchsorted(a, v, side=side)
    return jax.vmap(fn)(sorted_keys, queries)


def segment_bounds_from_sorted(ids: np.ndarray, n_segments: int) -> np.ndarray:
    """Host helper: start offsets [n_segments+1] of each id-run in a sorted
    id array (ids must be non-decreasing)."""
    counts = np.bincount(ids, minlength=n_segments)
    starts = np.zeros(n_segments + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    return starts
