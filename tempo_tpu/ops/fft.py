"""MXU-native batched DFTs of arbitrary length.

The reference computes per-series FFTs by shipping each group to a
Python worker (scipy via ``applyInPandas``, tsdf.py:828-902).  The axon
TPU backend cannot materialise complex dtypes, so complex arithmetic is
carried as (real, imag) float pairs and every transform is built from
*real matmuls* that run on the systolic array:

* ``dft_batched`` — direct [F, F] DFT matmul up to ``_DIRECT_MAX``
  points; above that, the **four-step Cooley-Tukey** factorisation
  F = N1*N2: reshape, DFT_N2 matmul, twiddle, DFT_N1 matmul — O(F*(N1+
  N2)) flops with O(N1^2 + N2^2) matrix memory instead of O(F^2), which
  is what lifts the old 2048-point ceiling (VERDICT r1 weak #5).
* ``bluestein_dft`` — exact DFTs of *arbitrary* (non-pow2, per-series
  varying) lengths via the chirp-z transform: a length-n DFT becomes a
  linear convolution evaluated with fixed-size-F circular FFTs, with
  the per-series chirp phases built from exact integer ``j^2 mod 2n``
  arithmetic (large-n phase accuracy).  Because F depends only on the
  *bucket* (next pow2), every series in a bucket shares one compiled
  program — compilations are O(log max_len) even for Zipfian length
  distributions, not O(#distinct lengths).
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

_DIRECT_MAX = 2048     # [2048, 2048] f32 DFT matrix = 16MB: fine in HBM


@functools.lru_cache(maxsize=32)
def _dft_mats_np(F: int, dtype_name: str):
    """(cos, sin) of the F-point DFT matrix W^{jk} = e^{-2pi i jk/F}.
    Angles reduced with exact integer mod before the float cast so
    large F keeps full phase accuracy.  Cached as HOST arrays — caching
    jnp constants would capture tracers when first built inside a jit
    trace."""
    j = np.arange(F, dtype=np.int64)
    jk = (j[:, None] * j[None, :]) % F
    ang = (2.0 * np.pi / F) * jk
    dt = np.dtype(dtype_name)
    return np.cos(ang).astype(dt), np.sin(ang).astype(dt)


def _dft_mats(F: int, dtype_name: str):
    c, s = _dft_mats_np(F, dtype_name)
    return jnp.asarray(c), jnp.asarray(s)


@functools.lru_cache(maxsize=32)
def _twiddle_np(N1: int, N2: int, dtype_name: str):
    F = N1 * N2
    ang = (2.0 * np.pi / F) * (np.arange(N1)[:, None] * np.arange(N2)[None, :])
    dt = np.dtype(dtype_name)
    return np.cos(ang).astype(dt), np.sin(ang).astype(dt)


def _twiddle(N1: int, N2: int, dtype_name: str):
    c, s = _twiddle_np(N1, N2, dtype_name)
    return jnp.asarray(c), jnp.asarray(s)


def _cmul(ar, ai, br, bi):
    return ar * br - ai * bi, ar * bi + ai * br


def _cmatmul(ar, ai, br, bi):
    """(ar + i ai) @ (br + i bi) as four real MXU matmuls."""
    p = jax.lax.Precision.HIGHEST
    rr = jnp.matmul(ar, br, precision=p) - jnp.matmul(ai, bi, precision=p)
    ri = jnp.matmul(ar, bi, precision=p) + jnp.matmul(ai, br, precision=p)
    return rr, ri


def _split_factor(F: int):
    """F = N1 * N2 with both factors pow2 and as square as possible."""
    log = F.bit_length() - 1
    n1 = 1 << (log // 2)
    return n1, F // n1


def dft_batched(xr: jnp.ndarray, xi: jnp.ndarray, inverse: bool = False):
    """Batched complex DFT along the last axis; length must be a power
    of two (direct matmul or four-step).  Returns (re, im); the inverse
    is unscaled (caller divides by F)."""
    F = int(xr.shape[-1])
    if F & (F - 1):
        raise ValueError(f"dft_batched needs a pow2 length, got {F}")
    dtn = str(xr.dtype)
    if F <= _DIRECT_MAX:
        c, s = _dft_mats(F, dtn)
        if inverse:
            s = -s
        # X = x @ (C - iS):   (xr + i xi)(C - i S)
        return _cmatmul(xr, xi, c, -s)

    N1, N2 = _split_factor(F)
    c1, s1 = _dft_mats(N1, dtn)
    c2, s2 = _dft_mats(N2, dtn)
    tc, ts = _twiddle(N1, N2, dtn)
    if inverse:
        s1, s2, ts = -s1, -s2, -ts

    batch = xr.shape[:-1]
    # x[j], j = j1 + N1*j2  ->  A[j1, j2]
    ar = xr.reshape(batch + (N2, N1)).swapaxes(-1, -2)
    ai = xi.reshape(batch + (N2, N1)).swapaxes(-1, -2)
    # inner DFT over j2
    br, bi = _cmatmul(ar, ai, c2, -s2)
    # twiddle W_F^{j1 k2}
    br, bi = _cmul(br, bi, tc, -ts)
    # outer DFT over j1:  D[k1, k2] = sum_j1 C[j1, k2] W_N1^{j1 k1}
    dr, di = _cmatmul(br.swapaxes(-1, -2), bi.swapaxes(-1, -2), c1, -s1)
    # k = k2 + N2*k1  ->  flatten with k1 major
    dr = dr.swapaxes(-1, -2).reshape(batch + (F,))
    di = di.swapaxes(-1, -2).reshape(batch + (F,))
    return dr, di


@functools.partial(jax.jit, static_argnames=("bucket",))
def bluestein_dft(x: jnp.ndarray, n: jnp.ndarray, bucket: int):
    """Exact n-point DFTs of zero-padded real rows, batched.

    ``x``: [B, bucket] real, row b holding n[b] values then zeros.
    ``n``: [B] int32/int64 true lengths (1 <= n <= bucket).
    Returns (re, im) [B, bucket]; entries at k >= n[b] are meaningless.
    One compiled program per ``bucket`` regardless of the mix of n.
    """
    dt = x.dtype
    B = int(x.shape[-1])
    F = 2 * B                    # pow2 >= 2n-1 for every n <= B
    j = jnp.arange(B, dtype=jnp.int64)
    n64 = n.astype(jnp.int64)[:, None]
    # chirp w_j = e^{-i pi j^2 / n}; j^2 mod 2n in exact ints first
    q = (j[None, :] * j[None, :]) % (2 * n64)
    ang = (jnp.pi * q.astype(dt)) / n64.astype(dt)
    cw, sw = jnp.cos(ang), jnp.sin(ang)          # w = cw - i sw
    in_row = j[None, :] < n64
    ar = jnp.where(in_row, x * cw, 0.0)
    ai = jnp.where(in_row, -x * sw, 0.0)
    ar = jnp.pad(ar, ((0, 0), (0, F - B)))
    ai = jnp.pad(ai, ((0, 0), (0, F - B)))

    # b_m = conj(w_m) = cw + i sw for |m| < n, wrapped to length F
    m = jnp.arange(F, dtype=jnp.int64)
    mm = jnp.minimum(m, F - m)                   # |m| under wrap
    qb = (mm[None, :] * mm[None, :]) % (2 * n64)
    angb = (jnp.pi * qb.astype(dt)) / n64.astype(dt)
    keep = mm[None, :] < n64
    br = jnp.where(keep, jnp.cos(angb), 0.0)
    bi = jnp.where(keep, jnp.sin(angb), 0.0)

    fr_a, fi_a = dft_batched(ar, ai)
    fr_b, fi_b = dft_batched(br, bi)
    pr, pi = _cmul(fr_a, fi_a, fr_b, fi_b)
    cr, ci = dft_batched(pr, pi, inverse=True)
    cr, ci = cr[:, :B] / F, ci[:, :B] / F
    # X_k = w_k * conv_k
    re, im = _cmul(cr, ci, cw, -sw)
    return re, im
