"""AS-OF join kernels on packed [K, L] series.

Reference semantics (python/tempo/tsdf.py:463-560 ``asofJoin`` and its
helper ``__getLastRightRow`` tsdf.py:111-162): for every left row, find
the *last* right row at-or-before it in the total order
(ts, sequence, side) - where, on a full tie, right rows sort before left
rows (rec_ind -1 < 1, tsdf.py:119,546) and a null sequence (left rows)
sorts before any non-null sequence (Spark NULLS FIRST ascending).  With
``skipNulls=True`` each right column independently takes its last
*non-null* value (tsdf.py:139); with ``skipNulls=False`` every column
comes from the single last right row, nulls included (struct-wrap trick,
tsdf.py:123-136).  Scala adds a ``maxLookback`` cap counted in rows of
the merged left+right stream (scala/.../asofJoin.scala:64-88).

TPU design: instead of union + shuffle + sorted window scan, we exploit
that both sides are packed time-sorted per key:

* fast path (no sequence col): a vmapped ``searchsorted`` of left
  timestamps into right timestamps plus a cumulative last-valid-index
  scan per column - O((Ll + Lr) log Lr), no materialised union;
* general path (sequence tie-break or maxLookback): a stable multi-key
  ``lax.sort`` merge of the two packed sides, then the same scans in
  merged coordinates - exactly the reference's union algorithm but as
  one fused XLA program per batch of series.

Kernels return *row indices* into the right side ([K, Ll] int32, -1 for
no match).  Value gathering happens in the frame layer, which keeps
device work dtype-agnostic and lets string columns ride the same path.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from tempo_tpu.ops import window_utils as wu


# ----------------------------------------------------------------------
# Fast path: no sequence column -> searchsorted
# ----------------------------------------------------------------------

def asof_indices_searchsorted(
    l_ts: jnp.ndarray,          # [K, Ll] int64, padded with TS_PAD
    r_ts: jnp.ndarray,          # [K, Lr] int64, padded with TS_PAD
    r_valids: jnp.ndarray,      # [C, K, Lr] bool per right column
    n_cols: Optional[int] = None,   # kept for API compat; C comes from
                                    # r_valids.shape (static under jit)
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Returns (last_row_idx [K, Ll], per_col_idx [C, K, Ll]).

    last_row_idx: index of the last right row with r_ts <= l_ts (-1 none)
    per_col_idx:  index of the last right row at-or-before l_ts whose
                  column value is non-null (-1 none) - skipNulls=True.

    On TPU (sort kernels active) this dispatches to
    :func:`tempo_tpu.ops.sortmerge.asof_merge_indices` — the binary
    search and the per-column last-valid gathers both lower to dynamic
    gathers there, each costing more than a full lane sort.  The merge
    form additionally REQUIRES ``l_ts`` ascending per row (every
    packed-layout caller guarantees it; the searchsorted form queries
    rows independently and does not care).
    """
    from tempo_tpu.ops import sortmerge as sm

    if sm.use_sort_kernels():
        return sm.asof_merge_indices(l_ts, r_ts, r_valids)
    return _asof_indices_search_form(l_ts, r_ts, r_valids)


@jax.jit
def _asof_indices_search_form(l_ts, r_ts, r_valids):
    pos = wu.searchsorted_batched(r_ts, l_ts, side="right")  # [K, Ll]
    last_row_idx = (pos - 1).astype(jnp.int32)               # -1 when none

    def per_col(valid):                                       # [K, Lr] -> [K, Ll]
        lv = wu.last_valid_index(valid)                       # [K, Lr]
        # gather lv at last_row_idx (clip then mask)
        g = jnp.take_along_axis(lv, jnp.maximum(last_row_idx, 0).astype(jnp.int32), axis=-1)
        return jnp.where(last_row_idx >= 0, g, -1)

    per_col_idx = (jax.vmap(per_col)(r_valids) if int(r_valids.shape[0])
                   else jnp.zeros((0,) + l_ts.shape, jnp.int32))
    return last_row_idx, per_col_idx


# ----------------------------------------------------------------------
# General path: merge by (ts, seq, side) with stable multi-key sort
# ----------------------------------------------------------------------

def asof_indices_merge(
    l_ts: jnp.ndarray,           # [K, Ll] int64 (TS_PAD padding)
    l_seq: Optional[jnp.ndarray],  # [K, Ll] float64 or None
    r_ts: jnp.ndarray,           # [K, Lr] int64
    r_seq: Optional[jnp.ndarray],  # [K, Lr] float64 or None
    r_valids: jnp.ndarray,       # [n_cols, K, Lr] bool
    n_cols: int,
    max_lookback: int = 0,       # 0 = unbounded (scala asofJoin.scala:68)
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Merge-scan AS-OF with sequence tie-break and optional maxLookback.

    Sort keys mirror the reference exactly: (combined_ts, sequence with
    NULLS FIRST, rec_ind) - tsdf.py:117-121.  Left rows carry seq=-inf
    when they have no sequence value (Spark nulls-first), rec=+1; right
    rows rec=-1.

    On TPU the unbounded form runs as the VMEM Pallas merge kernel
    with the sequence riding as extra order-preserving key planes
    (ops/pallas_merge.py, round 4) — the XLA form below pays a
    dynamic-gather per column, each costing more than a full lane sort
    on this hardware (ops/sortmerge.py module docstring timings).
    ``maxLookback`` keeps the XLA windowed-argmax ladder here; the
    host join reroutes oversize and maxLookback-capped joins to the
    lane-chunked streaming kernel instead
    (pallas_merge.asof_merge_indices_chunked, dispatched by join.py
    via profiling.pick_join_engine).
    """
    from tempo_tpu.ops import pallas_merge as pm

    if not max_lookback:
        l_seq_k = pm.seq_kernel_form(l_seq)
        r_seq_k = pm.seq_kernel_form(r_seq)
        expressible = (l_seq is None or l_seq_k is not None) and \
            (r_seq is None or r_seq_k is not None)
        if expressible and pm.merge_indices_supported(
                l_ts, r_ts, r_valids, l_seq_k, r_seq_k):
            return pm.asof_merge_indices_pallas(l_ts, r_ts, r_valids,
                                                l_seq_k, r_seq_k)
    return _asof_indices_merge_xla(l_ts, l_seq, r_ts, r_seq, r_valids,
                                   n_cols=n_cols,
                                   max_lookback=max_lookback)


@functools.partial(jax.jit, static_argnames=("n_cols", "max_lookback"))
def _asof_indices_merge_xla(
    l_ts: jnp.ndarray,
    l_seq: Optional[jnp.ndarray],
    r_ts: jnp.ndarray,
    r_seq: Optional[jnp.ndarray],
    r_valids: jnp.ndarray,
    n_cols: int,
    max_lookback: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    K, Ll = l_ts.shape
    Lr = r_ts.shape[1]
    Lc = Ll + Lr

    neg_inf = jnp.float64(-jnp.inf)
    l_seq_arr = l_seq if l_seq is not None else jnp.full((K, Ll), neg_inf, jnp.float64)
    r_seq_arr = r_seq if r_seq is not None else jnp.full((K, Lr), neg_inf, jnp.float64)

    ts = jnp.concatenate([l_ts, r_ts], axis=-1)
    seq = jnp.concatenate([l_seq_arr, r_seq_arr], axis=-1)
    rec = jnp.concatenate(
        [jnp.ones((K, Ll), jnp.int32), -jnp.ones((K, Lr), jnp.int32)], axis=-1
    )
    src = jnp.concatenate(
        [
            jnp.broadcast_to(jnp.arange(Ll, dtype=jnp.int32), (K, Ll)),
            jnp.broadcast_to(jnp.arange(Lr, dtype=jnp.int32), (K, Lr)),
        ],
        axis=-1,
    )

    ts_s, seq_s, rec_s, src_s = jax.lax.sort(
        (ts, seq, rec, src), dimension=-1, num_keys=3, is_stable=True
    )
    is_right = rec_s == -1
    right_idx_sorted = jnp.where(is_right, src_s, -1)  # [K, Lc]

    def running_last(cand):
        if max_lookback and max_lookback > 0:
            # rowsBetween(-maxLookback, 0) on the merged stream
            return wu.windowed_max_last(cand, max_lookback + 1)
        return wu.cummax(cand, axis=-1)

    # last right row regardless of column validity
    last_row_sorted = running_last(right_idx_sorted)

    # scatter back to left-row coordinates
    left_scatter = jnp.where(is_right, Ll, src_s)  # right rows -> dropped

    def to_left(vals_sorted):
        out = jnp.full((K, Ll), -1, jnp.int32)
        return out.at[
            jnp.arange(K)[:, None], left_scatter
        ].set(vals_sorted, mode="drop")

    last_row_idx = to_left(last_row_sorted)

    def per_col(valid):  # [K, Lr] -> [K, Ll]
        v = jnp.take_along_axis(
            valid, jnp.maximum(right_idx_sorted, 0).astype(jnp.int32), axis=-1
        )
        cand = jnp.where(is_right & v, right_idx_sorted, -1)
        return to_left(running_last(cand))

    per_col_idx = (
        jax.vmap(per_col)(r_valids)
        if n_cols
        else jnp.zeros((0, K, Ll), jnp.int32)
    )
    return last_row_idx, per_col_idx


# ----------------------------------------------------------------------
# Broadcast fast path (reference tsdf.py:482-509 sql_join_opt)
# ----------------------------------------------------------------------

@jax.jit
def asof_indices_inner(l_ts: jnp.ndarray, r_ts: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Range-join flavour: like the searchsorted path but flags rows with
    no preceding right row for *dropping* (the reference's SQL fast path
    is an inner ``between`` join, so unmatched left rows disappear)."""
    pos = wu.searchsorted_batched(r_ts, l_ts, side="right")
    idx = (pos - 1).astype(jnp.int32)
    return idx, idx >= 0
