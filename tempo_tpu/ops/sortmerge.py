"""Sort-and-scan kernels: the TPU-native form of search-and-gather.

Measured on v5e (axon), shapes [1024, 8192]: a single dynamic gather
(``take_along_axis``) costs ~96 ms and a vmapped ``jnp.searchsorted``
1.4 s (f32) to 4.0 s (i64) — while a full-width lane *sort* costs 14-17
ms and an associative scan 6-11 ms.  The reference leans on Spark's
sort-based shuffle for exactly this reason (tsdf.py:111-162: union,
sort, running ``last``); the TPU analog is ``lax.sort`` + scans, not
binary search.  This module provides the three hot primitives in that
form:

* :func:`merge_rank` — batched searchsorted of sorted queries into
  sorted keys via two stable sorts and a prefix count.  O((Lk+Lq) log)
  comparisons, zero gathers.
* :func:`asof_merge_values` — the AS-OF join producing joined *values*
  directly: one multi-operand merge sort, one batched forward-fill
  scan, one routing sort.  Replaces searchsorted + per-column index
  gathers + value gathers (the reference's whole
  ``__getLastRightRow`` contract, tsdf.py:111-162, including
  skipNulls and the sequence-number tie-break of tsdf.py:117-121).
* :func:`range_stats_shifted` — ``withRangeStats`` (tsdf.py:673-721)
  for row-bounded windows as W shifted masked accumulations: for a 10 s
  window over ~1 Hz data that is ~32 cheap elementwise passes (0.6 ms
  total) instead of prefix-sum boundary gathers and sparse-table RMQ
  lookups (~1 s).

All three are pure jittable functions usable inside shard_map blocks.
"""

from __future__ import annotations

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp


def _icumsum(x: jnp.ndarray) -> jnp.ndarray:
    """Inclusive prefix sum along the last axis (log-depth scan)."""
    return jax.lax.associative_scan(jnp.add, x, axis=x.ndim - 1)


@functools.partial(jax.jit, static_argnames=("side",))
def merge_rank(
    sorted_keys: jnp.ndarray,     # [K, Lk], ascending per row
    sorted_queries: jnp.ndarray,  # [K, Lq], ascending per row
    side: str = "left",
) -> jnp.ndarray:
    """``searchsorted`` of each query row into each key row, computed by
    merging rather than searching.

    REQUIRES both inputs ascending along the last axis (every packed-
    layout caller satisfies this: timestamps ascend and ``TS_PAD`` pads
    sort to the end with headroom, packing.py:33-41).  Matches
    ``np.searchsorted(keys[k], queries[k], side)`` exactly.
    """
    if side not in ("left", "right"):
        raise ValueError(f"side must be 'left' or 'right', got {side!r}")
    K, Lk = sorted_keys.shape
    Lq = sorted_queries.shape[-1]
    dt = jnp.promote_types(sorted_keys.dtype, sorted_queries.dtype)

    vals = jnp.concatenate(
        [sorted_keys.astype(dt), sorted_queries.astype(dt)], axis=-1
    )
    # tie order decides left/right bound: side='left' -> queries sort
    # before equal keys (rank counts strictly-smaller keys); 'right' ->
    # after (rank counts keys <= query)
    tq, tk = (0, 1) if side == "left" else (1, 0)
    tie = jnp.concatenate(
        [jnp.full((K, Lk), tk, jnp.int32), jnp.full((K, Lq), tq, jnp.int32)],
        axis=-1,
    )
    is_key = jnp.concatenate(
        [jnp.ones((K, Lk), jnp.int32), jnp.zeros((K, Lq), jnp.int32)],
        axis=-1,
    )
    _, _, is_key_s = jax.lax.sort(
        (vals, tie, is_key), dimension=-1, num_keys=2, is_stable=True
    )
    nkeys = _icumsum(is_key_s)  # at a query slot: #keys at-or-before it
    # route query results back to original query order: queries were
    # sorted, so a stable sort on (is_key) puts them first, in order
    _, rank = jax.lax.sort(
        (is_key_s, nkeys), dimension=-1, num_keys=1, is_stable=True
    )
    return rank[..., :Lq]


def _ffill_scan(has: jnp.ndarray, val: jnp.ndarray, axis: int = -1):
    """Batched last-valid carry: at each position, the most recent
    ``val`` where ``has`` was True (and whether any was seen)."""

    def combine(a, b):
        ha, va = a
        hb, vb = b
        return ha | hb, jnp.where(hb, vb, va)

    return jax.lax.associative_scan(
        combine, (has, val), axis=axis % has.ndim
    )


def asof_merge_values(
    l_ts: jnp.ndarray,            # [K, Ll] int64 ns (TS_PAD padded)
    r_ts: jnp.ndarray,            # [K, Lr] int64 ns
    r_valids: jnp.ndarray,        # [C, K, Lr] bool
    r_values: jnp.ndarray,        # [C, K, Lr] float
    l_seq: Optional[jnp.ndarray] = None,   # [K, Ll] sortable seq key
    r_seq: Optional[jnp.ndarray] = None,   # [K, Lr]
    skip_nulls: bool = True,
    max_lookback: int = 0,        # merged-stream row cap; 0 = unbounded
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """AS-OF join returning values directly: ``(vals [C, K, Ll],
    found [C, K, Ll], last_row_idx [K, Ll])``.

    Semantics mirror the reference's union-sort-scan
    (tsdf.py:111-162): per left row, the last right row at-or-before it
    in (ts [, seq], side) order, right rows winning full ties
    (rec_ind -1 < 1, tsdf.py:119,546); ``skip_nulls`` takes each
    column's last *non-null* value independently (tsdf.py:139), else
    all columns come from the single last right row, nulls included
    (tsdf.py:123-136).  Sequence keys, when given, order with Spark's
    NULLS FIRST via the caller mapping nulls to -inf.

    One merge sort (ts [, seq], side) carrying C value planes, one
    batched forward-fill scan, one routing sort.  No gathers.

    Dispatches OUTSIDE jit so the ``TEMPO_TPU_NAN_ASOF`` opt-in (a
    leaner NaN-encoded variant — the axon remote compiler hung >30 min
    on the fused pipeline built that way, measured 2026-07-30, so it is
    off by default) takes effect per call, not per first-trace.

    On TPU every f32 shape of the join — including the sequence
    tie-break (extra kernel key planes) and skipNulls=False (lockstep
    keyed fill) since round 4 — runs as ONE Pallas kernel: bitonic
    *merge* network + ffill ladder + routing sort, all VMEM-resident
    (``ops/pallas_merge.py``) — measured 7.5x this module's lax.sort
    form at [1024, 8192]: the sort ladders pay an HBM round-trip per
    compare-exchange stage, the kernel touches HBM twice total.
    """
    from tempo_tpu.ops import pallas_merge as pm

    if not max_lookback:
        # f64 seq planes re-encode (f32 / int64) before the kernel gate
        # — the TPU X64 rewriter has no 64-bit bitcast (seq_kernel_form)
        l_seq_k = pm.seq_kernel_form(l_seq)
        r_seq_k = pm.seq_kernel_form(r_seq)
        expressible = (l_seq is None or l_seq_k is not None) and \
            (r_seq is None or r_seq_k is not None)
        if expressible and not _forced_bitonic() \
                and pm.merge_join_supported(
                l_ts, r_ts, r_values, l_seq_k, r_seq_k, skip_nulls):
            return pm.asof_merge_values_pallas(
                l_ts, r_ts, r_valids, r_values, l_seq=l_seq_k,
                r_seq=r_seq_k, skip_nulls=skip_nulls,
            )
        if expressible and _oversize_bitonic(l_ts, r_ts, r_values,
                                             l_seq_k, r_seq_k):
            # past the lax.sort compiler ceiling (and the VMEM plan):
            # the XLA bitonic network joins at O(log Lc) full-array
            # stages instead of O(log^2), tracer-safe — the per-shard
            # oversize engine of the mesh paths (dist.py, parallel/halo)
            return pm.asof_merge_values_bitonic(
                l_ts, r_ts, r_valids, r_values, l_seq=l_seq_k,
                r_seq=r_seq_k, skip_nulls=skip_nulls,
            )
    if not max_lookback and skip_nulls \
            and jnp.issubdtype(r_values.dtype, jnp.floating) \
            and _nan_encoding_enabled():
        return _asof_merge_nan_encoded(l_ts, r_ts, r_valids, r_values,
                                       l_seq, r_seq)
    return _asof_merge_explicit(l_ts, r_ts, r_valids, r_values,
                                l_seq, r_seq, skip_nulls=skip_nulls,
                                max_lookback=int(max_lookback))


def _forced_bitonic() -> bool:
    from tempo_tpu import profiling

    return profiling.join_engine_override() == "bitonic"


def _oversize_bitonic(l_ts, r_ts, r_values, l_seq, r_seq) -> bool:
    """Whether the merged width sits in the regime where the lax.sort
    ladders OOM-kill the XLA compiler (~205K merged lanes, BASELINE.md
    r3) and the f32 bitonic network should run instead.  Forced on/off
    by TEMPO_TPU_JOIN_ENGINE=bitonic / single|bracket (the forced form
    also suppresses the single-plan Pallas branch at the call sites —
    the knob must measure the engine it names)."""
    from tempo_tpu import profiling, resilience
    from tempo_tpu.ops import pallas_merge as pm

    if not pm.merge_join_bitonic_supported(l_ts, r_ts, r_values,
                                           l_seq, r_seq):
        return False
    forced = profiling.join_engine_override()
    if forced == "bitonic":
        return True
    if forced in ("single", "bracket"):
        return False
    limit = resilience.max_merged_lanes()
    return 0 < limit < int(l_ts.shape[-1]) + int(r_ts.shape[-1])


def _merge_sides(l_ts, r_ts, l_seq, r_seq):
    """Shared merged sort-key construction: (ts [, seq], side), right
    rows sorting before left rows on full ties (rec_ind -1 < 1), null
    seq sides riding the dtype minimum (NULLS FIRST)."""
    K, Ll = l_ts.shape
    Lr = r_ts.shape[-1]
    ts = jnp.concatenate([l_ts, r_ts], axis=-1)
    is_left = jnp.concatenate(
        [jnp.ones((K, Ll), jnp.int32), jnp.zeros((K, Lr), jnp.int32)],
        axis=-1,
    )
    keys = [ts]
    if l_seq is not None or r_seq is not None:
        sdt = (l_seq if l_seq is not None else r_seq).dtype
        neg = (
            jnp.finfo(sdt).min
            if jnp.issubdtype(sdt, jnp.floating)
            else jnp.iinfo(sdt).min
        )
        ls = l_seq if l_seq is not None else jnp.full((K, Ll), neg, sdt)
        rs = r_seq if r_seq is not None else jnp.full((K, Lr), neg, sdt)
        keys.append(jnp.concatenate([ls, rs], axis=-1))
    keys.append(is_left)
    return keys, is_left


@functools.partial(jax.jit,
                   static_argnames=("skip_nulls", "max_lookback"))
def _asof_merge_explicit(l_ts, r_ts, r_valids, r_values, l_seq=None,
                         r_seq=None, skip_nulls=True,
                         l_sid=None, r_sid=None, max_lookback=0):
    """Default form: validity rides as explicit bool planes.  With
    ``l_sid``/``r_sid`` (bin-packed rows) the series id leads the sort
    keys and the fill is fenced at series boundaries — for every fill
    flavour: the unbounded scan turns segmented, and the
    ``max_lookback`` windowed argmax ladder (Scala's
    rowsBetween(-maxLookback, 0) on the union stream,
    asofJoin.scala:64-88) rejects candidates before the series' own
    segment head (contiguous series + positional argmax make the
    post-hoc fence exact: a cross-segment candidate only wins when no
    same-segment one exists, window_utils.windowed_last_valid).
    """
    C = int(r_values.shape[0])
    K, Ll = l_ts.shape
    Lr = r_ts.shape[-1]
    Lc = Ll + Lr
    vdt = r_values.dtype

    keys, is_left = _merge_sides(l_ts, r_ts, l_seq, r_seq)
    if l_sid is not None:
        sid = jnp.concatenate(
            [l_sid.astype(jnp.int32), r_sid.astype(jnp.int32)], axis=-1
        )
        keys = [sid] + keys

    ridx = jnp.concatenate(
        [
            jnp.full((K, Ll), -1, jnp.int32),
            jnp.broadcast_to(jnp.arange(Lr, dtype=jnp.int32), (K, Lr)),
        ],
        axis=-1,
    )

    # value/valid planes: left slots carry zeros (never read — the scan
    # only consumes right-tagged slots)
    zeros_l = jnp.zeros((C, K, Ll), vdt)
    planes = jnp.concatenate([zeros_l, r_values], axis=-1)      # [C, K, Lc]
    falses_l = jnp.zeros((C, K, Ll), jnp.bool_)
    vplanes = jnp.concatenate([falses_l, r_valids], axis=-1)    # [C, K, Lc]

    ops = tuple(keys) + (ridx,) + tuple(planes[c] for c in range(C)) \
        + tuple(vplanes[c] for c in range(C))
    sorted_ops = jax.lax.sort(
        ops, dimension=-1, num_keys=len(keys), is_stable=True
    )
    nk = len(keys)
    is_left_s = sorted_ops[nk - 1]
    ridx_s = sorted_ops[nk]
    planes_s = jnp.stack(sorted_ops[nk + 1: nk + 1 + C]) if C else \
        jnp.zeros((0, K, Lc), vdt)
    vplanes_s = jnp.stack(sorted_ops[nk + 1 + C:]) if C else \
        jnp.zeros((0, K, Lc), jnp.bool_)
    is_right_s = is_left_s == 0

    if l_sid is not None:
        sid_s = sorted_ops[0]
        head = jnp.concatenate(
            [jnp.ones((K, 1), jnp.bool_),
             sid_s[:, 1:] != sid_s[:, :-1]], axis=-1
        )
    else:
        head = None

    def fill(has, val):
        """Unbounded ffill (segmented over bin-packed series), or the
        windowed argmax ladder when the merged-stream row cap is active
        (fenced at the series' segment head for bin-packed rows)."""
        if max_lookback:
            from tempo_tpu.ops import window_utils as wu

            min_pos = None
            if head is not None:
                lane = jnp.broadcast_to(
                    jnp.arange(Lc, dtype=jnp.int32), (K, Lc)
                )
                min_pos = _ffill_scan(head, jnp.where(head, lane, 0))[1]
            val_f, has_f = wu.windowed_last_valid(
                has, val, max_lookback + 1, min_pos=min_pos
            )
            return has_f, val_f
        if head is not None:
            _, has_f, val_f = _ffill_scan_seg(
                jnp.broadcast_to(head, has.shape), has, val
            )
            return has_f, val_f
        return _ffill_scan(has, val)

    # batched forward fill: stack [C+1] problems and scan once.
    # channel C is the last-right-row index (validity = any right row)
    if skip_nulls:
        has = jnp.concatenate(
            [is_right_s[None] & vplanes_s,
             jnp.broadcast_to(is_right_s, (1, K, Lc))], axis=0
        )
        val = jnp.concatenate(
            [jnp.where(vplanes_s, planes_s, 0.0),
             ridx_s[None].astype(vdt)], axis=0
        )
        has_f, val_f = fill(has, val)
        vals_sorted = val_f[:C]
        found_sorted = has_f[:C]
        idx_sorted = jnp.where(has_f[C], val_f[C].astype(jnp.int32), -1)
    else:
        # all columns ride the single last right row: fill (value,
        # validity) pairs keyed on is_right only
        has = jnp.broadcast_to(is_right_s, (2 * C + 1, K, Lc))
        val = jnp.concatenate(
            [planes_s, vplanes_s.astype(vdt), ridx_s[None].astype(vdt)],
            axis=0,
        )
        has_f, val_f = fill(has, val)
        vals_sorted = val_f[:C]
        found_sorted = has_f[:C] & (val_f[C: 2 * C] > 0.5)
        idx_sorted = jnp.where(has_f[2 * C], val_f[2 * C].astype(jnp.int32),
                               -1)

    # route left rows back to original order: stable sort on is_left
    # descending (left first).  Left rows were originally ascending in
    # the same total order, so their merged relative order IS the
    # original order.
    route = tuple([1 - is_left_s, idx_sorted]
                  + [vals_sorted[c] for c in range(C)]
                  + [found_sorted[c] for c in range(C)])
    routed = jax.lax.sort(route, dimension=-1, num_keys=1, is_stable=True)
    idx_l = routed[1][..., :Ll]
    vals_l = jnp.stack([routed[2 + c][..., :Ll] for c in range(C)]) if C \
        else jnp.zeros((0, K, Ll), vdt)
    found_l = jnp.stack([routed[2 + C + c][..., :Ll] for c in range(C)]) \
        if C else jnp.zeros((0, K, Ll), jnp.bool_)
    vals_l = jnp.where(found_l, vals_l, jnp.nan)
    return vals_l, found_l, idx_l


def asof_merge_values_binpacked(l_ts, r_ts, r_valids, r_values,
                                l_sid, r_sid, skip_nulls: bool = True,
                                max_lookback: int = 0,
                                l_seq=None, r_seq=None):
    """AS-OF join over *bin-packed* rows: each [K, L] lane row holds
    several series back-to-back, identified by the non-decreasing
    ``sid`` planes (packing.py:bin_pack_series).  Right rows win full
    ties — the same contract as :func:`asof_merge_values` including
    ``skip_nulls``, the ``max_lookback`` merged-row cap (both fenced
    at series boundaries) and, since round 6, the sequence tie-break
    (REQUIRES the packed runs sorted by (ts, seq) per series — what
    join.py's layouts guarantee when a seq plane is packed), with
    ``last_row_idx`` a within-lane-row position.  The TPU answer to
    Zipf-skewed key distributions (the reference's tsPartitionVal
    machinery, tsdf.py:164-190): instead of padding every series to
    the longest (96% padding on NBBO-shaped data, round-2 verdict),
    short series share lane rows at ~full occupancy and one compiled
    program serves every skew shape.
    """
    from tempo_tpu.ops import pallas_merge as pm

    l_seq_k = pm.seq_kernel_form(l_seq)
    r_seq_k = pm.seq_kernel_form(r_seq)
    expressible = (l_seq is None or l_seq_k is not None) and \
        (r_seq is None or r_seq_k is not None)
    if not max_lookback and expressible and not _forced_bitonic() \
            and pm.merge_join_supported(
            l_ts, r_ts, r_values, l_seq_k, r_seq_k, skip_nulls,
            segmented=True):
        return pm.asof_merge_values_pallas(l_ts, r_ts, r_valids,
                                           r_values, l_sid, r_sid,
                                           l_seq=l_seq_k, r_seq=r_seq_k,
                                           skip_nulls=skip_nulls)
    if not max_lookback and expressible and _oversize_bitonic(
            l_ts, r_ts, r_values, l_seq_k, r_seq_k):
        return pm.asof_merge_values_bitonic(
            l_ts, r_ts, r_valids, r_values, l_sid, r_sid,
            l_seq=l_seq_k, r_seq=r_seq_k, skip_nulls=skip_nulls)
    return _asof_merge_explicit(l_ts, r_ts, r_valids, r_values,
                                l_seq=l_seq, r_seq=r_seq,
                                l_sid=l_sid, r_sid=r_sid,
                                skip_nulls=skip_nulls,
                                max_lookback=int(max_lookback))


def asof_indices_binpacked(l_ts, r_ts, r_valids, l_sid, r_sid,
                           max_lookback: int = 0, r_seq=None):
    """Index-returning bin-packed join: same layout contract as
    :func:`asof_merge_values_binpacked`, position-encoded payloads.
    Returns ``(last_row_idx, per_col_idx)`` as WITHIN-LANE-ROW
    positions (-1 none); callers convert to per-series indices with
    the offsets they packed with (join.py does)."""
    C, K, Lr = r_valids.shape
    vdt = jnp.float32 if use_sort_kernels() else jnp.float64
    pos = jnp.broadcast_to(jnp.arange(Lr, dtype=vdt), (K, Lr))
    planes = jnp.broadcast_to(pos[None], (C, K, Lr))
    vals, found, last_idx = asof_merge_values_binpacked(
        l_ts, r_ts, r_valids, planes, l_sid, r_sid,
        max_lookback=max_lookback, r_seq=r_seq,
    )
    per_col = jnp.where(found, vals, -1).astype(jnp.int32)
    return last_idx, per_col


def _ffill_scan_seg(f, has, val, axis: int = -1):
    """Segmented last-valid carry (Blelloch segmented-scan monoid):
    ``f`` flags segment heads; fills never cross a head."""

    def combine(a, b):
        fa, ha, va = a
        fb, hb, vb = b
        h = jnp.where(fb, hb, ha | hb)
        v = jnp.where(fb, vb, jnp.where(hb, vb, va))
        return fa | fb, h, v

    return jax.lax.associative_scan(combine, (f, has, val),
                                    axis=axis % has.ndim)


def asof_merge_indices(l_ts, r_ts, r_valids):
    """Index-returning sibling of :func:`asof_merge_values` (same
    skipNulls semantics): returns ``(last_row_idx [K, Ll],
    per_col_idx [C, K, Ll])``, -1 for no match.  On TPU this runs as
    the Pallas merge kernel with position-encoded payloads
    (ops/pallas_merge.py); the XLA form below merges with 3+C operands
    and forward-fills the row-index channel per column.  REQUIRES
    ``l_ts`` ascending per row (the packed-layout invariant)."""
    from tempo_tpu.ops import pallas_merge as pm

    if not _forced_bitonic() and pm.merge_indices_supported(
            l_ts, r_ts, r_valids):
        return pm.asof_merge_indices_pallas(l_ts, r_ts, r_valids)
    if _oversize_bitonic(l_ts, r_ts,
                         jnp.zeros((0,), jnp.float32), None, None):
        return pm.asof_merge_indices_bitonic(l_ts, r_ts, r_valids)
    return _asof_merge_indices_xla(l_ts, r_ts, r_valids)


@jax.jit
def _asof_merge_indices_xla(l_ts, r_ts, r_valids):
    C, K, Lr = r_valids.shape
    Ll = l_ts.shape[-1]
    Lc = Ll + Lr

    keys, is_left = _merge_sides(l_ts, r_ts, None, None)
    ridx = jnp.concatenate(
        [jnp.full((K, Ll), -1, jnp.int32),
         jnp.broadcast_to(jnp.arange(Lr, dtype=jnp.int32), (K, Lr))],
        axis=-1,
    )
    vplanes = jnp.concatenate(
        [jnp.zeros((C, K, Ll), jnp.bool_), r_valids], axis=-1
    )
    ops = tuple(keys) + (ridx,) + tuple(vplanes[c] for c in range(C))
    sorted_ops = jax.lax.sort(
        ops, dimension=-1, num_keys=len(keys), is_stable=True
    )
    nk = len(keys)
    is_right_s = sorted_ops[nk - 1] == 0
    ridx_s = sorted_ops[nk]
    vplanes_s = jnp.stack(sorted_ops[nk + 1:]) if C else \
        jnp.zeros((0, K, Lc), jnp.bool_)

    has = jnp.concatenate(
        [is_right_s[None] & vplanes_s,
         jnp.broadcast_to(is_right_s, (1, K, Lc))], axis=0
    )
    val = jnp.broadcast_to(ridx_s, (C + 1, K, Lc))
    has_f, val_f = _ffill_scan(has, jnp.where(has, val, 0))
    idx_sorted = jnp.where(has_f, val_f, -1)

    route = (1 - sorted_ops[nk - 1],) + tuple(idx_sorted[i]
                                              for i in range(C + 1))
    routed = jax.lax.sort(route, dimension=-1, num_keys=1, is_stable=True)
    per_col = jnp.stack([routed[1 + c][..., :Ll] for c in range(C)]) if C \
        else jnp.zeros((0, K, Ll), jnp.int32)
    last_idx = routed[1 + C][..., :Ll]
    return last_idx, per_col


def _nan_encoding_enabled() -> bool:
    from tempo_tpu import config

    return (config.get("TEMPO_TPU_NAN_ASOF") or "0") not in ("0", "false",
                                                             "no")


@jax.jit
def _asof_merge_nan_encoded(l_ts, r_ts, r_valids, r_values, l_seq=None,
                            r_seq=None):
    """skipNulls float fast path of :func:`asof_merge_values`: null and
    not-found states are NaN inside the value planes themselves, so the
    merge and routing sorts move C+1 payload operands instead of 2C+2.
    Requires valid slots to hold finite values (the packing invariant:
    NaN source values are null by definition)."""
    C = int(r_values.shape[0])
    K, Ll = l_ts.shape
    Lr = r_ts.shape[-1]
    vdt = r_values.dtype

    keys, is_left = _merge_sides(l_ts, r_ts, l_seq, r_seq)

    planes = jnp.concatenate(
        [jnp.full((C, K, Ll), jnp.nan, vdt),
         jnp.where(r_valids, r_values, jnp.nan)], axis=-1,
    )
    ridx_f = jnp.concatenate(
        [jnp.full((K, Ll), jnp.nan, vdt),
         jnp.broadcast_to(jnp.arange(Lr, dtype=vdt), (K, Lr))],
        axis=-1,
    )

    ops = tuple(keys) + tuple(planes[c] for c in range(C)) + (ridx_f,)
    sorted_ops = jax.lax.sort(
        ops, dimension=-1, num_keys=len(keys), is_stable=True
    )
    nk = len(keys)
    is_left_s = sorted_ops[nk - 1]
    payload = jnp.stack(sorted_ops[nk:])          # [C+1, K, Lc]

    has = ~jnp.isnan(payload)
    has_f, val_f = _ffill_scan(has, jnp.where(has, payload, 0.0))
    filled = jnp.where(has_f, val_f, jnp.nan)     # NaN == never found

    route = (1 - is_left_s,) + tuple(filled[i] for i in range(C + 1))
    routed = jax.lax.sort(route, dimension=-1, num_keys=1, is_stable=True)
    vals_l = jnp.stack([routed[1 + c][..., :Ll] for c in range(C)]) if C \
        else jnp.zeros((0, K, Ll), vdt)
    idx_f = routed[1 + C][..., :Ll]
    found_l = ~jnp.isnan(vals_l)
    idx_l = jnp.where(jnp.isnan(idx_f), -1, idx_f).astype(jnp.int32)
    return vals_l, found_l, idx_l


def _shift_back(x: jnp.ndarray, j: int, fill) -> jnp.ndarray:
    """out[..., i] = x[..., i - j] (j may be negative = look ahead)."""
    if j == 0:
        return x
    if j > 0:
        pad = jnp.full(x.shape[:-1] + (j,), fill, dtype=x.dtype)
        return jnp.concatenate([pad, x[..., :-j]], axis=-1)
    pad = jnp.full(x.shape[:-1] + (-j,), fill, dtype=x.dtype)
    return jnp.concatenate([x[..., -j:], pad], axis=-1)


def range_stats_shifted(
    secs: jnp.ndarray,       # [K, L] sorted window-order key (int)
    x: jnp.ndarray,          # [K, L] float values
    valid: jnp.ndarray,      # [K, L] bool
    window: jnp.ndarray,     # scalar window size in key units
    max_behind: int,         # static bound: rows any window reaches back
    max_ahead: int = 0,      # static bound: longest tie run ahead
    scale=None,              # optional scalar folded onto x in-kernel
) -> Dict[str, jnp.ndarray]:
    """Dispatcher: on TPU with int32 keys and f32 values the whole
    shifted-pass structure runs VMEM-resident as one Pallas kernel —
    the streamlined ops/pallas_window.py unrolled form by default
    (fewer rotate/mask ops per pass; TEMPO_TPU_WINDOW_ENGINE=legacy
    keeps the original ops/pallas_stats.py kernel) — an int32 ``secs``
    dtype is the caller's assertion that per-series key spans fit
    (rebase_seconds or equivalent); int64 keys keep the XLA form
    below.  ``scale``, when given, multiplies ``x`` inside the kernel
    (consumers fold the elementwise pre-pass they would otherwise
    re-stream the column for)."""
    from tempo_tpu.ops import pallas_stats as ps
    from tempo_tpu.ops import pallas_window as pw
    from tempo_tpu.ops.rolling import window_engine_override

    if secs.dtype == jnp.int32:
        if window_engine_override() != "legacy" and pw.unrolled_supported(
                x, max_behind, max_ahead):
            return pw.range_stats_unrolled(
                secs, x, valid, window, max_behind, max_ahead,
                scale=scale)
        if ps.range_stats_supported(secs, x, valid, max_behind,
                                    max_ahead):
            if scale is not None:
                x = x * jnp.asarray(scale, x.dtype)
            return ps.range_stats_pallas(secs, x, valid, window,
                                         max_behind, max_ahead)
    if scale is not None:
        x = x * jnp.asarray(scale, x.dtype)
    return _range_stats_shifted_xla(secs, x, valid, window,
                                    max_behind=max_behind,
                                    max_ahead=max_ahead)


def range_stats_shifted_packed(secs, xs, valids, window, max_behind,
                               max_ahead, scales=None):
    """Multi-column :func:`range_stats_shifted`: ``xs``/``valids`` are
    [C, K, L] stacks over one [K, L] key plane.  On TPU, packable
    groups run through the unrolled pallas_window kernel in single
    passes that read the key planes once
    (``pallas_window.range_stats_unrolled_packed``, group width from
    ``pack_cols_budget``); every other configuration (legacy kernel,
    XLA form, int64 keys) loops the single-column dispatcher, so the
    per-column results are bitwise-identical to unpacked calls either
    way.  Output planes are [C, K, L] ([C, K, 1] for ``clipped``)."""
    from tempo_tpu.ops import pallas_window as pw
    from tempo_tpu.ops.rolling import (packed_column_dispatch,
                                       window_engine_override)

    secs = jnp.asarray(secs)
    xs = jnp.asarray(xs)
    valids = jnp.asarray(valids)
    C, K, L = xs.shape

    def gate(c0):
        return (secs.dtype == jnp.int32
                and window_engine_override() != "legacy"
                and pw.unrolled_supported(xs[c0], max_behind,
                                          max_ahead))

    def packed_group(c0, scv):
        width = pw.pack_cols_budget(K, L, C - c0,
                                    max_behind=int(max_behind),
                                    max_ahead=int(max_ahead),
                                    unroll=True)
        return width, pw.range_stats_unrolled_packed(
            secs, xs[c0:c0 + width], valids[c0:c0 + width], window,
            max_behind, max_ahead,
            scales=None if scv is None else scv[c0:c0 + width])

    def single_col(c0, scale):
        return dict(range_stats_shifted(
            secs, xs[c0], valids[c0], window, max_behind, max_ahead,
            scale=scale))

    return packed_column_dispatch(C, scales, gate, packed_group,
                                  single_col)


@functools.partial(jax.jit, static_argnames=("max_behind", "max_ahead"))
def _range_stats_shifted_xla(
    secs: jnp.ndarray,
    x: jnp.ndarray,
    valid: jnp.ndarray,
    window: jnp.ndarray,
    max_behind: int,
    max_ahead: int = 0,
) -> Dict[str, jnp.ndarray]:
    """``withRangeStats`` for row-bounded windows, gather-free.

    Spark's rangeBetween(-window, 0) frame at row i contains exactly the
    rows j with ``secs[j] in [secs[i]-window, secs[i]]`` — preceding
    rows within the window plus following rows tied with secs[i]
    (tsdf.py:575-576 via the long cast).  When the caller can bound the
    frame extent in *rows* (``max_behind`` back, ``max_ahead`` ties
    ahead — compute both from the data as the frame layer does), the
    frame is a union of static shifts, and each aggregate is a masked
    accumulation over those shifts: O(W·KL) elementwise work, no
    searchsorted, no prefix-sum boundary gathers, no RMQ tables.  Sums
    accumulate mean-centred per series (f32-safe).

    Bounds too small TRUNCATE frames; the returned ``clipped`` entry
    ([K, 1] per-series count of rows whose true frame extends past
    ``max_behind``/``max_ahead``) audits exactly that — the same
    contract as the halo layer's clipped counts (parallel/halo.py).
    Callers derive bounds from real data and assert the audit is zero
    (frame layer: deferred collect-time audit; bench.py: hard assert).
    """
    dt = x.dtype
    xz = jnp.where(valid, x, 0.0)
    n_valid = jnp.sum(valid, axis=-1, keepdims=True)
    center = jnp.sum(xz, axis=-1, keepdims=True) / jnp.maximum(n_valid, 1)
    xc = jnp.where(valid, x - center, 0.0).astype(dt)

    big = jnp.iinfo(secs.dtype).max
    lo = secs - window.astype(secs.dtype)
    pinf = jnp.array(jnp.inf, dt)

    cnt = jnp.zeros_like(x, dt)
    s1 = jnp.zeros_like(x, dt)
    s2 = jnp.zeros_like(x, dt)
    mn = jnp.full_like(x, pinf)
    mx = jnp.full_like(x, -pinf)
    for j in range(-max_ahead, max_behind + 1):
        sj = _shift_back(secs, j, big)
        inw = (sj >= lo) & (sj <= secs) & _shift_back(valid, j, False)
        xj = _shift_back(xc, j, jnp.array(0.0, dt))
        xr = _shift_back(x, j, jnp.array(0.0, dt))
        cnt = cnt + inw.astype(dt)
        s1 = s1 + jnp.where(inw, xj, 0.0)
        s2 = s2 + jnp.where(inw, xj * xj, 0.0)
        mn = jnp.minimum(mn, jnp.where(inw, xr, pinf))
        mx = jnp.maximum(mx, jnp.where(inw, xr, -pinf))

    mean = jnp.where(cnt > 0, s1 / jnp.maximum(cnt, 1) + center, jnp.nan)
    total = s1 + cnt * center
    var = jnp.where(
        cnt > 1,
        (s2 - s1 * s1 / jnp.maximum(cnt, 1)) / jnp.maximum(cnt - 1, 1),
        jnp.nan,
    )
    std = jnp.where(cnt > 1, jnp.sqrt(jnp.maximum(var, 0.0)), jnp.nan)
    zscore = (x - mean) / std

    # truncation audit: a row is clipped when the first row beyond
    # either static bound still falls inside its frame's key range and
    # either end of that extension is a valid row.  Requiring only the
    # *beyond* row valid would undercount when a null row sits exactly
    # at the boundary with valid rows behind it; requiring neither
    # would count all-pad tie runs (pads share one clamped key, so a
    # pad "extends ahead" into its neighbour pad).  Real-row false
    # positives from pads are impossible: pad keys sit >= window above
    # any real key (TS_PAD / INT32_MAX headroom), so real rows fail
    # ``sj >= lo`` against them and pads ahead fail ``sj <= secs``.
    # Shifts are clamped to the row length (a bound >= L has nothing
    # beyond it — shifting further is all-fill, and _shift_back cannot
    # represent |j| > L).
    L = secs.shape[-1]
    clipped = jnp.zeros_like(x, jnp.bool_)
    for j in (min(max_behind + 1, L), -min(max_ahead + 1, L)):
        sj = _shift_back(secs, j, big)
        clipped = clipped | (
            (sj >= lo) & (sj <= secs)
            & (valid | _shift_back(valid, j, False))
        )
    return {
        "mean": mean,
        "count": cnt,
        "min": jnp.where(cnt > 0, mn, jnp.nan),
        "max": jnp.where(cnt > 0, mx, jnp.nan),
        "sum": jnp.where(cnt > 0, total, jnp.nan),
        "stddev": std,
        "zscore": jnp.where(valid, zscore, jnp.nan),
        "clipped": jnp.sum(clipped, axis=-1, keepdims=True).astype(dt),
    }


def use_sort_kernels() -> bool:
    """Whether the sort-and-scan forms should replace search-and-gather
    on the current backend (TPU: yes — see module docstring timings;
    override with TEMPO_TPU_SORT_KERNELS=0/1)."""
    from tempo_tpu import config

    env = config.get("TEMPO_TPU_SORT_KERNELS")
    if env is not None:
        return env not in ("0", "false", "no")
    return jax.default_backend() == "tpu"
