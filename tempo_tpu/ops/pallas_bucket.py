"""Pallas VMEM kernels for tumbling-bucket (resample) reductions.

The reference's resample/groupBy aggregation is a Spark shuffle +
groupBy (python/tempo/resample.py:38-117, tsdf.py:723-759).  The XLA
forms here were bucket row-bounds (two batched searchsorteds) feeding
``windowed_stats`` prefix sums and RMQ tables — several HBM round
trips per aggregate, which left the resample+EMA bench config flat at
~20 GB/s for two rounds (VERDICT r3 weak #3).  A tumbling bucket is a
*segmented* reduction over the lane axis, and a segmented reduction is
two log-depth ladders entirely in VMEM:

1. **forward segmented inclusive scan** (head-flag doubling monoid,
   the in-kernel form of ``sortmerge._ffill_scan_seg``): after the
   ladder, each bucket's LAST row holds the full-bucket aggregate;
2. **reverse next-fill broadcast**: every row takes the value at the
   first bucket-tail at-or-after it — which is always its own bucket's
   tail, so no segment fence is needed.

Five aggregate planes (count, centred sum, centred sum-of-squares,
min, max) ride the two ladders lockstep, sharing the flag ladder.
HBM traffic: one read of (bucket-id, x, valid), one write of the
outputs — independent of L.

Kernels:

* ``bucket_stats_pallas``   — mean/count/min/max/sum/stddev/zscore per
  bucket, broadcast to every row: a drop-in for ``windowed_stats``
  when the window bounds are tumbling buckets (resample func variants,
  grouped stats, vwap — dist.py:_resample_fn/_bucket_stats_fn).
* ``resample_ema_pallas``   — the fused bench config-3 pipeline:
  floor-resample head pick + exact EMA ladder in ONE kernel (the
  separate XLA bucket/head pass + Pallas EMA pass each paid their own
  HBM round trip).

Reference semantics: resample.py:38-117 (aggregation), tsdf.py:615-635
(EMA).  Engage for f32 on lane-aligned TPU blocks; XLA forms remain
for CPU/f64/infeasible shapes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tempo_tpu.ops import pallas_kernels as pk


def _lane(shape):
    return jax.lax.broadcasted_iota(jnp.int32, shape, dimension=1)


def _roll_back(p, span: int):
    """p[:, i - span] with wraparound (callers mask lane < span)."""
    return pltpu.roll(p, shift=jnp.int32(span), axis=1)


def _roll_fwd(p, span: int, L: int):
    """p[:, i + span] with wraparound (callers mask lane >= L - span).
    Negative roll shifts SIGABRT Mosaic — ride the circular L - span."""
    return pltpu.roll(p, shift=jnp.int32(L - span), axis=1)


def _seg_scan(planes, ops, head_f, shape):
    """Forward segmented inclusive scan: planes[p][i] reduces plane p
    over [segment_start(i), i].  ``ops`` is a per-plane (combine,
    identity) list; the head-flag ladder is shared."""
    L = shape[1]
    f = head_f
    span = 1
    while span < L:
        ok = _lane(shape) >= span
        f_prev = jnp.where(ok, _roll_back(f, span), 1.0)
        new = []
        for p, (combine, ident) in zip(planes, ops):
            prev = jnp.where(ok, _roll_back(p, span), ident)
            new.append(jnp.where(f > 0, p, combine(p, prev)))
        planes = new
        f = jnp.maximum(f, f_prev)
        span *= 2
    return planes


def _tail_broadcast(planes, tail_f, shape):
    """Reverse next-fill: planes[p][i] <- plane value at the first
    tail-flagged slot at-or-after i (always i's own bucket tail)."""
    L = shape[1]
    g = tail_f
    span = 1
    while span < L:
        ok = _lane(shape) < L - span
        g_next = jnp.where(ok, _roll_fwd(g, span, L), 0.0)
        new = []
        for p in planes:
            nxt = jnp.where(ok, _roll_fwd(p, span, L), 0.0)
            new.append(jnp.where(g > 0, p, nxt))
        planes = new
        g = jnp.maximum(g, g_next)
        span *= 2
    return planes


def _head_tail(bid, shape):
    """(head, tail) f32 flags of each bucket run along the lanes."""
    L = shape[1]
    lane = _lane(shape)
    head = (lane == 0) | (bid != _roll_back(bid, 1))
    tail = (lane == L - 1) | (bid != _roll_fwd(bid, 1, L))
    return head.astype(jnp.float32), tail.astype(jnp.float32)


def _bucket_stats_kernel(bid_ref, x_ref, valid_ref,
                         mean_ref, cnt_ref, mn_ref, mx_ref, sum_ref,
                         std_ref, z_ref):
    bid = bid_ref[:]
    x = x_ref[:]
    valid = valid_ref[:]
    shape = bid.shape

    head_f, tail_f = _head_tail(bid, shape)

    f0 = jnp.float32(0.0)
    f1 = jnp.float32(1.0)
    validf = valid.astype(jnp.float32)
    xz = jnp.where(valid, x, f0)
    nv = jnp.sum(validf, axis=1, keepdims=True)
    center = jnp.sum(xz, axis=1, keepdims=True) / jnp.maximum(nv, f1)
    xc = jnp.where(valid, x - center, f0)

    pinf = jnp.float32(jnp.inf)
    planes = [
        validf,                                  # count
        xc,                                      # centred sum
        xc * xc,                                 # centred sum of squares
        jnp.where(valid, x, pinf),               # min
        jnp.where(valid, x, -pinf),              # max
    ]
    add = (jnp.add, f0)
    ops = [add, add, add, (jnp.minimum, pinf), (jnp.maximum, -pinf)]
    planes = _seg_scan(planes, ops, head_f, shape)
    cnt, s1, s2, mn, mx = _tail_broadcast(planes, tail_f, shape)

    nan = jnp.float32(jnp.nan)
    mean = jnp.where(cnt > 0, s1 / jnp.maximum(cnt, f1) + center, nan)
    total = s1 + cnt * center
    var = jnp.where(
        cnt > 1,
        (s2 - s1 * s1 / jnp.maximum(cnt, f1))
        / jnp.maximum(cnt - f1, f1),
        nan,
    )
    std = jnp.where(cnt > 1, jnp.sqrt(jnp.maximum(var, f0)), nan)

    mean_ref[:] = mean
    cnt_ref[:] = cnt
    mn_ref[:] = jnp.where(cnt > 0, mn, nan)
    mx_ref[:] = jnp.where(cnt > 0, mx, nan)
    sum_ref[:] = jnp.where(cnt > 0, total, nan)
    std_ref[:] = std
    z_ref[:] = jnp.where(valid, (x - mean) / std, nan)


_ARRAYS = 40  # 3 in + 7 out double-buffered + 5 scan planes + flags/temps


@functools.partial(jax.jit, static_argnames=("interpret",))
def _bucket_stats_call(bid, x, valid, interpret=False):
    K, L = x.shape
    plan = pk._plan(K, L, arrays=_ARRAYS, bk_max=32, budget=90 * 2**20)
    if plan is None:
        raise ValueError(
            f"bucket-stats kernel infeasible at L={L}; use the XLA "
            f"windowed form"
        )
    grid, bk, K_pad = plan
    bid = pk._pad_rows(bid, K_pad)
    x, valid = pk._pad_rows(x, K_pad), pk._pad_rows(valid, K_pad)
    with pk.x64_off():
        spec = pl.BlockSpec((bk, L), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
        out = pl.pallas_call(
            _bucket_stats_kernel,
            grid=grid,
            in_specs=[spec] * 3,
            out_specs=[spec] * 7,
            out_shape=[jax.ShapeDtypeStruct((K_pad, L), jnp.float32)] * 7,
            compiler_params=pk.tpu_compiler_params(
                vmem_limit_bytes=100 * 1024 * 1024,
            ),
            interpret=interpret,
        )(bid, x, valid)
    return tuple(o[:K] for o in out)


def bucket_stats_supported(x) -> bool:
    return (
        x.dtype == jnp.float32
        and x.ndim == 2
        and x.shape[1] % 128 == 0
        and jax.default_backend() == "tpu"
        and pk._plan(int(x.shape[0]), int(x.shape[1]), arrays=_ARRAYS,
                     bk_max=32, budget=90 * 2**20) is not None
    )


def bucket_stats_pallas(bid, x, valid, interpret: bool = False):
    """Tumbling-bucket aggregates broadcast to every row of the bucket
    — the same output contract as ``windowed_stats`` called with
    bucket [start, end) bounds (dist.py:_bucket_heads), minus the
    searchsorteds and gathers.  ``bid`` is an int32 bucket id,
    non-decreasing per row (pad rows carry a distinct id so they form
    their own bucket; their outputs are masked by callers)."""
    with pk.interpret_scope(interpret):
        outs = _bucket_stats_call(bid.astype(jnp.int32), x, valid,
                                  interpret=interpret)
    mean, cnt, mn, mx, total, std, z = outs
    return {
        "mean": mean, "count": cnt, "min": mn, "max": mx, "sum": total,
        "stddev": std, "zscore": z,
    }


# ----------------------------------------------------------------------
# Fused floor-resample + EMA (bench config 3)
# ----------------------------------------------------------------------

def _resample_ema_kernel(step_ref, alpha_ref, scale_ref, secs_ref,
                         x_ref, valid_ref, res_ref, ema_ref):
    step = step_ref[0]
    alpha = alpha_ref[0]
    secs = secs_ref[:]
    # the scale scalar folds the caller's elementwise pre-pass into
    # this kernel's single read of x (the pre-pass re-streamed the
    # column through HBM: 8B/row of pure overhead at bench scale)
    x = x_ref[:] * scale_ref[0]
    valid = valid_ref[:]
    shape = secs.shape

    # exact integer bucketing: i32 floor-divide lowers natively in
    # Mosaic (probed on v5e).  The first kernel revision multiplied by
    # a rounded f32 reciprocal, which misassigns rows one second below
    # a bucket boundary from secs ≈ 10.2M up (code-review r4 finding,
    # verified numerically) — reciprocal multiply is NOT division.
    bucket = secs // step
    lane = _lane(shape)
    head = ((lane == 0) | (bucket != _roll_back(bucket, 1))) & valid

    nan = jnp.float32(jnp.nan)
    res_ref[:] = jnp.where(head, x, nan)

    # exact EMA ladder over head-masked samples (pallas_kernels._ema)
    f0 = jnp.float32(0.0)
    f1 = jnp.float32(1.0)
    d = jnp.where(head, f1 - alpha, f1)
    v = jnp.where(head, alpha * x, f0)
    L = shape[1]
    span = 1
    while span < L:
        ok = lane >= span
        d_prev = jnp.where(ok, _roll_back(d, span), f1)
        v_prev = jnp.where(ok, _roll_back(v, span), f0)
        v = v + d * v_prev
        d = d * d_prev
        span *= 2
    ema_ref[:] = v


@functools.partial(jax.jit, static_argnames=("interpret",))
def _resample_ema_call(secs, x, valid, step, alpha, scale,
                       interpret=False):
    K, L = x.shape
    plan = pk._plan(K, L, arrays=24, bk_max=32, budget=90 * 2**20)
    if plan is None:
        raise ValueError(
            f"resample-ema kernel infeasible at L={L}; use the XLA form"
        )
    grid, bk, K_pad = plan
    secs = pk._pad_rows(secs, K_pad)
    x, valid = pk._pad_rows(x, K_pad), pk._pad_rows(valid, K_pad)
    with pk.x64_off():
        spec = pl.BlockSpec((bk, L), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
        out = pl.pallas_call(
            _resample_ema_kernel,
            grid=grid,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 3
            + [spec] * 3,
            out_specs=[spec] * 2,
            out_shape=[jax.ShapeDtypeStruct((K_pad, L), jnp.float32)] * 2,
            compiler_params=pk.tpu_compiler_params(
                vmem_limit_bytes=100 * 1024 * 1024,
            ),
            interpret=interpret,
        )(jnp.asarray([step], jnp.int32),
          jnp.asarray([alpha], jnp.float32),
          jnp.asarray(scale, jnp.float32).reshape(1), secs, x, valid)
    return out[0][:K], out[1][:K]


def resample_ema_supported(secs, x) -> bool:
    """Gate: f32 lane-aligned TPU blocks with an int32-expressible
    seconds axis (the in-kernel bucketing is exact i32 division)."""
    return (
        x.dtype == jnp.float32
        and x.ndim == 2
        and x.shape[1] % 128 == 0
        and jax.default_backend() == "tpu"
        and pk._plan(int(x.shape[0]), int(x.shape[1]), arrays=24,
                     bk_max=32, budget=90 * 2**20) is not None
    )


def resample_ema_pallas(secs, x, valid, step: float, alpha: float,
                        scale=None, interpret: bool = False):
    """Fused floor-resample + exact EMA: ``res`` is x at each bucket's
    first valid head row (NaN elsewhere — the packed-in-place
    downsample view), ``ema`` the exact EMA over the head-masked
    samples.  ``secs`` and ``step`` must be integral (the in-kernel
    bucketing is exact i32 division; a fractional step would silently
    truncate and a sub-1 step would divide by zero) and fit int32.
    ``scale`` (scalar) multiplies x inside the kernel -- callers
    fold the elementwise pre-pass they would otherwise re-stream
    the column for."""
    step_i = int(step)
    if step_i != step or step_i < 1:
        raise ValueError(
            f"resample_ema_pallas needs an integral step >= 1 in the "
            f"seconds unit of `secs`, got {step!r}; rescale secs (e.g. "
            f"to ms) for sub-second buckets"
        )
    with pk.interpret_scope(interpret):
        res, ema = _resample_ema_call(
            secs.astype(jnp.int32), x, valid,
            jnp.asarray(step_i, jnp.int32),
            jnp.asarray(alpha, jnp.float32),
            jnp.float32(1.0) if scale is None else scale,
            interpret=interpret,
        )
    return res, ema
