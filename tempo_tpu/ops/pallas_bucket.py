"""Pallas VMEM kernels for tumbling-bucket (resample) reductions.

The reference's resample/groupBy aggregation is a Spark shuffle +
groupBy (python/tempo/resample.py:38-117, tsdf.py:723-759).  The XLA
forms here were bucket row-bounds (two batched searchsorteds) feeding
``windowed_stats`` prefix sums and RMQ tables — several HBM round
trips per aggregate, which left the resample+EMA bench config flat at
~20 GB/s for two rounds (VERDICT r3 weak #3).  A tumbling bucket is a
*segmented* reduction over the lane axis, and a segmented reduction is
two log-depth ladders entirely in VMEM:

1. **forward segmented inclusive scan** (head-flag doubling monoid,
   the in-kernel form of ``sortmerge._ffill_scan_seg``): after the
   ladder, each bucket's LAST row holds the full-bucket aggregate;
2. **reverse next-fill broadcast**: every row takes the value at the
   first bucket-tail at-or-after it — which is always its own bucket's
   tail, so no segment fence is needed.

Five aggregate planes (count, centred sum, centred sum-of-squares,
min, max) ride the two ladders lockstep, sharing the flag ladder.
HBM traffic: one read of (bucket-id, x, valid), one write of the
outputs — independent of L.

Kernels:

* ``bucket_stats_pallas``   — mean/count/min/max/sum/stddev/zscore per
  bucket, broadcast to every row: a drop-in for ``windowed_stats``
  when the window bounds are tumbling buckets (resample func variants,
  grouped stats, vwap — dist.py:_resample_fn/_bucket_stats_fn).
* ``resample_ema_pallas``   — the fused bench config-3 pipeline:
  floor-resample head pick + exact EMA ladder in ONE kernel (the
  separate XLA bucket/head pass + Pallas EMA pass each paid their own
  HBM round trip).

Reference semantics: resample.py:38-117 (aggregation), tsdf.py:615-635
(EMA).  Engage for f32 on lane-aligned TPU blocks; XLA forms remain
for CPU/f64/infeasible shapes.

HBM-roofline mechanisms (PR 6, cf. ops/pallas_window.py):
``bucket_stats_packed`` reduces a [C, K, L] column stack sharing ONE
bucket-id plane and flag ladder per block (engaged through
``rolling.bucket_stats_multi`` — the grouped-stats/resample mesh
reductions in dist.py); ``TEMPO_TPU_DMA_BUFFERS``
> 2 streams both kernels' slabs through the explicit DMA ring
(ops/pallas_stream.py); carry-free row grids are declared
megacore-parallel.  Bitwise identity across all forms is pinned in
tests/test_pallas_bucket.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tempo_tpu.ops import pallas_kernels as pk
from tempo_tpu.ops import pallas_stream as psr


def _lane(shape):
    return jax.lax.broadcasted_iota(jnp.int32, shape, dimension=1)


def _roll_back(p, span: int):
    """p[:, i - span] with wraparound (callers mask lane < span)."""
    return pltpu.roll(p, shift=jnp.int32(span), axis=1)


def _roll_fwd(p, span: int, L: int):
    """p[:, i + span] with wraparound (callers mask lane >= L - span).
    Negative roll shifts SIGABRT Mosaic — ride the circular L - span."""
    return pltpu.roll(p, shift=jnp.int32(L - span), axis=1)


def _seg_scan(planes, ops, head_f, shape):
    """Forward segmented inclusive scan: planes[p][i] reduces plane p
    over [segment_start(i), i].  ``ops`` is a per-plane (combine,
    identity) list; the head-flag ladder is shared."""
    L = shape[1]
    f = head_f
    span = 1
    while span < L:
        ok = _lane(shape) >= span
        f_prev = jnp.where(ok, _roll_back(f, span), 1.0)
        new = []
        for p, (combine, ident) in zip(planes, ops):
            prev = jnp.where(ok, _roll_back(p, span), ident)
            new.append(jnp.where(f > 0, p, combine(p, prev)))
        planes = new
        f = jnp.maximum(f, f_prev)
        span *= 2
    return planes


def _tail_broadcast(planes, tail_f, shape):
    """Reverse next-fill: planes[p][i] <- plane value at the first
    tail-flagged slot at-or-after i (always i's own bucket tail)."""
    L = shape[1]
    g = tail_f
    span = 1
    while span < L:
        ok = _lane(shape) < L - span
        g_next = jnp.where(ok, _roll_fwd(g, span, L), 0.0)
        new = []
        for p in planes:
            nxt = jnp.where(ok, _roll_fwd(p, span, L), 0.0)
            new.append(jnp.where(g > 0, p, nxt))
        planes = new
        g = jnp.maximum(g, g_next)
        span *= 2
    return planes


def _head_tail(bid, shape):
    """(head, tail) f32 flags of each bucket run along the lanes."""
    L = shape[1]
    lane = _lane(shape)
    head = (lane == 0) | (bid != _roll_back(bid, 1))
    tail = (lane == L - 1) | (bid != _roll_fwd(bid, 1, L))
    return head.astype(jnp.float32), tail.astype(jnp.float32)


def _bucket_math(bid, x, valid, head_f, tail_f):
    """One column's full segmented reduction over a [bk, L] block — the
    shared op sequence of the single-column, packed and DMA-ring kernel
    forms (bitwise identity across the forms holds by construction).
    The head/tail flag ladders depend only on ``bid`` and are computed
    once per block by the callers."""
    shape = bid.shape
    f0 = jnp.float32(0.0)
    f1 = jnp.float32(1.0)
    validf = valid.astype(jnp.float32)
    xz = jnp.where(valid, x, f0)
    nv = jnp.sum(validf, axis=1, keepdims=True)
    center = jnp.sum(xz, axis=1, keepdims=True) / jnp.maximum(nv, f1)
    xc = jnp.where(valid, x - center, f0)

    pinf = jnp.float32(jnp.inf)
    planes = [
        validf,                                  # count
        xc,                                      # centred sum
        xc * xc,                                 # centred sum of squares
        jnp.where(valid, x, pinf),               # min
        jnp.where(valid, x, -pinf),              # max
    ]
    add = (jnp.add, f0)
    ops = [add, add, add, (jnp.minimum, pinf), (jnp.maximum, -pinf)]
    planes = _seg_scan(planes, ops, head_f, shape)
    cnt, s1, s2, mn, mx = _tail_broadcast(planes, tail_f, shape)

    nan = jnp.float32(jnp.nan)
    mean = jnp.where(cnt > 0, s1 / jnp.maximum(cnt, f1) + center, nan)
    total = s1 + cnt * center
    var = jnp.where(
        cnt > 1,
        (s2 - s1 * s1 / jnp.maximum(cnt, f1))
        / jnp.maximum(cnt - f1, f1),
        nan,
    )
    std = jnp.where(cnt > 1, jnp.sqrt(jnp.maximum(var, f0)), nan)

    return (mean, cnt,
            jnp.where(cnt > 0, mn, nan),
            jnp.where(cnt > 0, mx, nan),
            jnp.where(cnt > 0, total, nan),
            std,
            jnp.where(valid, (x - mean) / std, nan))


def _make_bucket_kernel(n_cols: int):
    """BlockSpec kernel over :func:`_bucket_math`.  With ``n_cols > 1``
    the payload refs are [C, bk, L] stacks: the bucket-id plane and its
    head/tail flag ladders are computed ONCE per block and shared by
    every column — the multi-column packing that removes the per-column
    re-stream of the segment keys."""

    def kernel(bid_ref, x_ref, valid_ref, *out_refs):
        bid = bid_ref[:]
        head_f, tail_f = _head_tail(bid, bid.shape)
        if n_cols == 1:
            outs = _bucket_math(bid, x_ref[:], valid_ref[:], head_f,
                                tail_f)
            for r, o in zip(out_refs, outs):
                r[:] = o
            return
        for c in range(n_cols):
            outs = _bucket_math(bid, x_ref[c], valid_ref[c], head_f,
                                tail_f)
            for r, o in zip(out_refs, outs):
                r[c] = o

    return kernel


def _bucket_arrays(n_cols: int, depth: int = 2) -> int:
    """[bk, L] f32 plane budget: 5 scan planes + flags/temps live per
    column (columns run sequentially), I/O per the pipeline depth."""
    base = 22                       # scan planes + flag ladders + temps
    if depth <= 2:
        return base + 18 * n_cols   # (x + valid) in + 7 out, 2x each
    return base + depth * (1 + 2 * n_cols) + 14 * n_cols


_ARRAYS = _bucket_arrays(1)  # == 40: the seed single-column budget


def _ring_bucket_math(n_cols: int):
    def ring_math(scalar_refs, slabs):
        del scalar_refs
        bid, x, valid = slabs
        head_f, tail_f = _head_tail(bid, bid.shape)
        if n_cols == 1:
            return _bucket_math(bid, x, valid, head_f, tail_f)
        per = [_bucket_math(bid, x[c], valid[c], head_f, tail_f)
               for c in range(n_cols)]
        return tuple(jnp.stack([per[c][t] for c in range(n_cols)])
                     for t in range(7))

    return ring_math


@functools.partial(jax.jit, static_argnames=("depth", "interpret"))
def _bucket_stats_call(bid, x, valid, depth=2, interpret=False):
    if x.ndim == 3 and x.shape[0] == 1:
        # width-1 stack (bucket_pack_budget returns 1 for infeasible /
        # single-column cases): run the rank-2 single-column form — the
        # identical op sequence — and restack; the rank-2 spec paths
        # below would otherwise trace rank-2 BlockSpecs over the rank-3
        # operands
        outs = _bucket_stats_call(bid, x[0], valid[0], depth=depth,
                                  interpret=interpret)
        return tuple(o[None] for o in outs)
    n_cols = 1 if x.ndim == 2 else x.shape[0]
    K, L = x.shape[-2], x.shape[-1]
    plan = psr.plan_with_ring(
        K, L, lambda d: _bucket_arrays(n_cols, d), depth)
    if plan is None:
        raise ValueError(
            f"bucket-stats kernel infeasible at L={L}, n_cols={n_cols};"
            f" use the XLA windowed form (or narrow the pack)"
        )
    grid, bk, K_pad, use_ring = plan
    bid = pk._pad_rows(bid, K_pad)
    x, valid = pk._pad_rows(x, K_pad), pk._pad_rows(valid, K_pad)

    if use_ring:
        out = psr.ring_call(
            _ring_bucket_math(n_cols), [], [bid, x, valid], n_out=7,
            out_like=1, bk=bk, depth=depth, interpret=interpret)
        return tuple(o[..., :K, :] for o in out)

    with pk.x64_off():
        spec2 = pl.BlockSpec((bk, L), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
        if n_cols == 1:
            spec3, out_shape = spec2, (K_pad, L)
        else:
            spec3 = pl.BlockSpec((n_cols, bk, L), lambda i: (0, i, 0),
                                 memory_space=pltpu.VMEM)
            out_shape = (n_cols, K_pad, L)
        out = pl.pallas_call(
            _make_bucket_kernel(n_cols),
            grid=grid,
            in_specs=[spec2, spec3, spec3],
            out_specs=[spec3] * 7,
            out_shape=[jax.ShapeDtypeStruct(out_shape, jnp.float32)] * 7,
            compiler_params=pk.tpu_compiler_params(
                vmem_limit_bytes=100 * 1024 * 1024,
                dimension_semantics=psr.grid_semantics(len(grid)),
            ),
            interpret=interpret,
        )(bid, x, valid)
    return tuple(o[..., :K, :] for o in out)


def bucket_stats_supported(x) -> bool:
    return (
        x.dtype == jnp.float32
        and x.ndim == 2
        and x.shape[1] % 128 == 0
        and jax.default_backend() == "tpu"
        and pk._plan(int(x.shape[0]), int(x.shape[1]), arrays=_ARRAYS,
                     bk_max=32, budget=90 * 2**20) is not None
    )


def bucket_stats_pallas(bid, x, valid, interpret: bool = False):
    """Tumbling-bucket aggregates broadcast to every row of the bucket
    — the same output contract as ``windowed_stats`` called with
    bucket [start, end) bounds (dist.py:_bucket_heads), minus the
    searchsorteds and gathers.  ``bid`` is an int32 bucket id,
    non-decreasing per row (pad rows carry a distinct id so they form
    their own bucket; their outputs are masked by callers)."""
    with pk.interpret_scope(interpret):
        outs = _bucket_stats_call(bid.astype(jnp.int32), x, valid,
                                  depth=psr.dma_buffers(),
                                  interpret=interpret)
    mean, cnt, mn, mx, total, std, z = outs
    return {
        "mean": mean, "count": cnt, "min": mn, "max": mx, "sum": total,
        "stddev": std, "zscore": z,
    }


def bucket_stats_packed(bid, xs, valids, interpret: bool = False):
    """Multi-column :func:`bucket_stats_pallas`: ``xs``/``valids`` are
    [C, K, L] stacks sharing one [K, L] bucket-id plane, reduced in ONE
    kernel pass — the id plane and its head/tail flag ladders cross HBM
    (and the VPU) once instead of once per column.  Outputs are
    [C, K, L]; per-column results are bitwise-equal to C single-column
    calls (identical op sequence).  Size C against the VMEM budget with
    :func:`bucket_pack_budget`."""
    with pk.interpret_scope(interpret):
        outs = _bucket_stats_call(bid.astype(jnp.int32), xs, valids,
                                  depth=psr.dma_buffers(),
                                  interpret=interpret)
    mean, cnt, mn, mx, total, std, z = outs
    return {
        "mean": mean, "count": cnt, "min": mn, "max": mx, "sum": total,
        "stddev": std, "zscore": z,
    }


def bucket_pack_budget(K: int, L: int, n_cols: int) -> int:
    """Largest bucket-stats pack width (<= ``n_cols``) whose block plan
    fits the VMEM budget (``pallas_stream.pack_budget`` over this
    module's plane counts; cf. ``pallas_window.pack_cols_budget``)."""
    depth = psr.dma_buffers()
    return psr.pack_budget(K, L, n_cols,
                           lambda c: _bucket_arrays(c, depth))


# ----------------------------------------------------------------------
# Fused floor-resample + EMA (bench config 3)
# ----------------------------------------------------------------------

def _resample_ema_math(step, alpha, scale, secs, x, valid):
    """The fused floor-resample + EMA op sequence over one [bk, L]
    block, shared by the BlockSpec and DMA-ring kernel forms."""
    shape = secs.shape
    # the scale scalar folds the caller's elementwise pre-pass into
    # this kernel's single read of x (the pre-pass re-streamed the
    # column through HBM: 8B/row of pure overhead at bench scale)
    x = x * scale

    # exact integer bucketing: i32 floor-divide lowers natively in
    # Mosaic (probed on v5e).  The first kernel revision multiplied by
    # a rounded f32 reciprocal, which misassigns rows one second below
    # a bucket boundary from secs ≈ 10.2M up (code-review r4 finding,
    # verified numerically) — reciprocal multiply is NOT division.
    bucket = secs // step
    lane = _lane(shape)
    head = ((lane == 0) | (bucket != _roll_back(bucket, 1))) & valid

    nan = jnp.float32(jnp.nan)
    res = jnp.where(head, x, nan)

    # exact EMA ladder over head-masked samples (pallas_kernels._ema)
    f0 = jnp.float32(0.0)
    f1 = jnp.float32(1.0)
    d = jnp.where(head, f1 - alpha, f1)
    v = jnp.where(head, alpha * x, f0)
    L = shape[1]
    span = 1
    while span < L:
        ok = lane >= span
        d_prev = jnp.where(ok, _roll_back(d, span), f1)
        v_prev = jnp.where(ok, _roll_back(v, span), f0)
        v = v + d * v_prev
        d = d * d_prev
        span *= 2
    return res, v


def _resample_ema_kernel(step_ref, alpha_ref, scale_ref, secs_ref,
                         x_ref, valid_ref, res_ref, ema_ref):
    res, ema = _resample_ema_math(step_ref[0], alpha_ref[0],
                                  scale_ref[0], secs_ref[:], x_ref[:],
                                  valid_ref[:])
    res_ref[:] = res
    ema_ref[:] = ema


def _ring_resample_math(scalar_refs, slabs):
    step_ref, alpha_ref, scale_ref = scalar_refs
    secs, x, valid = slabs
    return _resample_ema_math(step_ref[0], alpha_ref[0], scale_ref[0],
                              secs, x, valid)


def _resample_arrays(depth: int = 2) -> int:
    return 24 if depth <= 2 else 14 + depth * 3


@functools.partial(jax.jit, static_argnames=("depth", "interpret"))
def _resample_ema_call(secs, x, valid, step, alpha, scale, depth=2,
                       interpret=False):
    K, L = x.shape
    plan = psr.plan_with_ring(K, L, _resample_arrays, depth)
    if plan is None:
        raise ValueError(
            f"resample-ema kernel infeasible at L={L}; use the XLA form"
        )
    grid, bk, K_pad, use_ring = plan
    secs = pk._pad_rows(secs, K_pad)
    x, valid = pk._pad_rows(x, K_pad), pk._pad_rows(valid, K_pad)
    scalars = (jnp.asarray([step], jnp.int32),
               jnp.asarray([alpha], jnp.float32),
               jnp.asarray(scale, jnp.float32).reshape(1))

    if use_ring:
        out = psr.ring_call(
            _ring_resample_math, list(scalars), [secs, x, valid],
            n_out=2, out_like=1, bk=bk, depth=depth,
            interpret=interpret)
        return out[0][:K], out[1][:K]

    with pk.x64_off():
        spec = pl.BlockSpec((bk, L), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
        out = pl.pallas_call(
            _resample_ema_kernel,
            grid=grid,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 3
            + [spec] * 3,
            out_specs=[spec] * 2,
            out_shape=[jax.ShapeDtypeStruct((K_pad, L), jnp.float32)] * 2,
            compiler_params=pk.tpu_compiler_params(
                vmem_limit_bytes=100 * 1024 * 1024,
                dimension_semantics=psr.grid_semantics(len(grid)),
            ),
            interpret=interpret,
        )(*scalars, secs, x, valid)
    return out[0][:K], out[1][:K]


def resample_ema_supported(secs, x) -> bool:
    """Gate: f32 lane-aligned TPU blocks with an int32-expressible
    seconds axis (the in-kernel bucketing is exact i32 division)."""
    return (
        x.dtype == jnp.float32
        and x.ndim == 2
        and x.shape[1] % 128 == 0
        and jax.default_backend() == "tpu"
        and pk._plan(int(x.shape[0]), int(x.shape[1]), arrays=24,
                     bk_max=32, budget=90 * 2**20) is not None
    )


def resample_ema_pallas(secs, x, valid, step: float, alpha: float,
                        scale=None, interpret: bool = False):
    """Fused floor-resample + exact EMA: ``res`` is x at each bucket's
    first valid head row (NaN elsewhere — the packed-in-place
    downsample view), ``ema`` the exact EMA over the head-masked
    samples.  ``secs`` and ``step`` must be integral (the in-kernel
    bucketing is exact i32 division; a fractional step would silently
    truncate and a sub-1 step would divide by zero) and fit int32.
    ``scale`` (scalar) multiplies x inside the kernel -- callers
    fold the elementwise pre-pass they would otherwise re-stream
    the column for."""
    step_i = int(step)
    if step_i != step or step_i < 1:
        raise ValueError(
            f"resample_ema_pallas needs an integral step >= 1 in the "
            f"seconds unit of `secs`, got {step!r}; rescale secs (e.g. "
            f"to ms) for sub-second buckets"
        )
    with pk.interpret_scope(interpret):
        res, ema = _resample_ema_call(
            secs.astype(jnp.int32), x, valid,
            jnp.asarray(step_i, jnp.int32),
            jnp.asarray(alpha, jnp.float32),
            jnp.float32(1.0) if scale is None else scale,
            depth=psr.dma_buffers(), interpret=interpret,
        )
    return res, ema
