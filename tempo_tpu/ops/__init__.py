"""Device kernels on packed [K, L] series — the public window-builder
surface (the packed-array equivalent of the reference's WindowSpec
builders, scala TSDF.scala:127-159; mapping table in MIGRATION.md).

Kernel-choice note: the scan-shaped ops (EMA, last/first-valid, prefix
sums) run as Pallas VMEM ladders on TPU (``pallas_kernels``), range
windows with a boundable row extent run as the VMEM shifted kernel
(``pallas_stats``, auto-picked through ``rolling.shifted_row_budget``),
and tumbling-bucket reductions as the VMEM segmented-scan kernel
(``pallas_bucket``).  Only UNBOUNDED-extent range windows stay on XLA:
their queries need per-element dynamic gathers, which Mosaic cannot
lower (probed on v5e).
"""

from tempo_tpu.ops.rolling import (
    range_window_bounds,
    windowed_stats,
    bucket_stats,
    bucket_stats_multi,
    segment_stats,
    shifted_row_budget,
    ema_compat,
    ema_exact,
)
from tempo_tpu.ops.pallas_bucket import (
    bucket_stats_pallas,
    resample_ema_pallas,
)
from tempo_tpu.ops.window_utils import (
    last_valid_index,
    first_valid_index,
    windowed_max_last,
    searchsorted_batched,
)
from tempo_tpu.ops.pallas_kernels import (
    ema_scan,
    cumsum3,
    last_valid_scan,
    last_valid_index_scan,
    first_valid_index_scan,
)

__all__ = [
    "range_window_bounds",
    "windowed_stats",
    "bucket_stats",
    "bucket_stats_multi",
    "segment_stats",
    "shifted_row_budget",
    "bucket_stats_pallas",
    "resample_ema_pallas",
    "ema_compat",
    "ema_exact",
    "last_valid_index",
    "first_valid_index",
    "windowed_max_last",
    "searchsorted_batched",
    "ema_scan",
    "cumsum3",
    "last_valid_scan",
    "last_valid_index_scan",
    "first_valid_index_scan",
]
