"""Device kernels on packed [K, L] series — the public window-builder
surface (the packed-array equivalent of the reference's WindowSpec
builders, scala TSDF.scala:127-159; mapping table in MIGRATION.md).

Kernel-choice note: the scan-shaped ops (EMA, last/first-valid, prefix
sums) run as Pallas VMEM ladders on TPU (see ``pallas_kernels``);
variable-width *range* windows stay on XLA because their queries need
per-element dynamic gathers, which Mosaic cannot lower (probed on v5e)
— and XLA's cumsum+gather formulation is already near the HBM bound.
"""

from tempo_tpu.ops.rolling import (
    range_window_bounds,
    windowed_stats,
    segment_stats,
    ema_compat,
    ema_exact,
)
from tempo_tpu.ops.window_utils import (
    last_valid_index,
    first_valid_index,
    windowed_max_last,
    searchsorted_batched,
)
from tempo_tpu.ops.pallas_kernels import (
    ema_scan,
    cumsum3,
    last_valid_scan,
    last_valid_index_scan,
    first_valid_index_scan,
)

__all__ = [
    "range_window_bounds",
    "windowed_stats",
    "segment_stats",
    "ema_compat",
    "ema_exact",
    "last_valid_index",
    "first_valid_index",
    "windowed_max_last",
    "searchsorted_batched",
    "ema_scan",
    "cumsum3",
    "last_valid_scan",
    "last_valid_index_scan",
    "first_valid_index_scan",
]
