"""Pallas VMEM merge-join kernel: the AS-OF join in one HBM pass.

The XLA form of the join (``ops/sortmerge.py:asof_merge_values``) runs
three full ``lax.sort`` ladders over the concatenated streams.  Each
ladder is a bitonic *sort* network — O(log^2 Lc) compare-exchange
stages — and every stage is an HBM round-trip of every operand plane,
which is why the flagship op measured ~0.2% of the chip's HBM bandwidth
(round-2 verdict).  But the two sides are *already sorted per row* (the
packed-layout invariant, packing.py:33-41): merging them needs only a
bitonic *merge* network — O(log Lc) stages — and none of the stages
needs to leave VMEM.

This kernel runs the whole join on a [bk, Lc] block resident in VMEM:

1. **Bitonic merge** of ``[left ascending, reversed(right)]`` (a bitonic
   sequence) under the total order (ts, side, pos): log2(Lc) stages of
   ``pltpu.roll`` + compare-exchange.  Timestamps are int64 ns split
   into two i32 planes (hi, bias-corrected lo) because lane arithmetic
   is i32-native on TPU; ``pos`` (the within-side lane index) makes the
   order total, which both emulates the reference's stable sort and
   lets the compare-exchange ignore ties.  Right rows carry side-keys
   below left rows, reproducing the reference's rec_ind tie-break
   (right wins full ties — tsdf.py:119,546).
2. **Forward-fill ladder** over the merged stream, NaN-encoded per
   column (skipNulls=True semantics: each right column independently
   takes its last non-null value, tsdf.py:139), plus a row-index plane
   giving the last right row regardless of validity.
3. **Unmerge**: the merge stages are involutions over disjoint lane
   pairs, so replaying their recorded swap masks in reverse order
   inverts the merge permutation exactly — every filled slot returns
   to its input lane (left rows at [0, Llp)) in log2(Lc) stages.  The
   first kernel revision sorted a destination-key permutation instead
   (log^2 stages, ~105 at Lc=16K); the recorded-mask unmerge replaced
   ~80% of the kernel's stage work.

HBM traffic: one read of the input planes, one write of the output —
independent of the number of network stages.

Engages for float32 values on any combination of the reference's join
flags (round 4; rounds 2-3 covered only the default configuration):

* **sequence tie-break** (tsdf.py:117-121): the seq plane joins the
  kernel's total order between the ts planes and the side key, as one
  or two extra i32 key planes via an order-preserving bit map
  (IEEE-float sign-fold, int64 hi/lo split — `_seq_key_planes`).  The
  packed layout already sorts each side by (ts, seq)
  (packing.py:228-245), so the bitonic-merge precondition holds.
* **skipNulls=False** (tsdf.py:123-136 struct-wrap): the ffill ladder
  switches from per-plane NaN fill to a *lockstep* fill keyed on the
  last-right-row channel — every payload plane takes the same source
  slot, so all columns come from the single last right row, nulls
  included (`_ffill_stage_keyed`).

The XLA forms remain for maxLookback, float64 golden runs, CPU, and
VMEM-infeasible shapes.  Reference semantics: tsdf.py:111-162.

Round 6 adds two engines past the single-shot VMEM plan (which capped
the join at the ~205K merged-lane compiler-OOM cliff, VERDICT r5
missing #1):

* **Lane-chunked streaming merge** (``asof_merge_values_chunked``): the
  FlashAttention idiom applied to the join — grid over the merged-lane
  axis in VMEM-sized chunks (host merge-path split,
  packing.asof_chunk_plan), each chunk a full merge+ffill+unmerge
  network, with the cross-chunk forward-fill state (last-valid value
  per payload plane, the live series id, and the maxLookback horizon
  via global merged positions) carried in VMEM scratch across
  sequential grid steps.  Bit-identical to the single-plan kernel
  (fills select, never compute) at any length under 2^24 merged rows,
  and it covers maxLookback — which the single-plan kernel never did.
* **XLA bitonic merge** (``asof_merge_values_bitonic``): the same
  network in plain jnp rolls — O(log Lc) full-array passes instead of
  ``lax.sort``'s O(log^2) ladder whose unrolled network OOM-killed the
  XLA compiler at ~205K lanes.  Tracer-safe, so it is the oversize
  engine *inside* shard_map (dist.py / parallel/halo.py per-shard
  joins), where the host-built chunk layout cannot go.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tempo_tpu.ops import pallas_kernels as pk
from tempo_tpu.ops import pallas_stream as psr

# left/right side marker added to the within-side position to form the
# tie-break key: right rows (sec = pos) sort before left rows
# (sec = _SIDE + pos) on full ts ties, like rec_ind -1 < 1
_SIDE = 1 << 24


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _pad_plan(Ll: int, Lr: int):
    """(Lrp, Lc2, Llp): lane-align the right side, then pad the left so
    the merged length is a power of two (the network requirement).
    Shared by the kernel wrapper and the feasibility gate — they must
    agree or the gate admits shapes the kernel plans differently."""
    Lrp = -(-Lr // 128) * 128
    Lc2 = _next_pow2(max(Ll + Lrp, 256))
    return Lrp, Lc2, Lc2 - Lrp


def _lane(shape):
    return jax.lax.broadcasted_iota(jnp.int32, shape, dimension=1)


_I32_MAX = 2**31 - 1


def _pad_lanes(p, n: int, fill):
    """Append ``n`` fill lanes (the shared pad convention of the join
    and rank wrappers: i32-max keys sort after every real row)."""
    return jnp.pad(p, ((0, 0), (0, n)), constant_values=fill)


def _rev(p):
    return jnp.flip(p, axis=-1)


def _roll_tpu(p, span: int):
    """Lane rotate so out[i] = p[(i - span) mod L] (pltpu form)."""
    return pltpu.roll(p, shift=jnp.int32(span), axis=1)


def _roll_jnp(p, span: int):
    """Same rotation in plain jnp — the XLA bitonic engine's roll, one
    HBM pass per stage instead of VMEM-resident, but tracer-safe at any
    width (usable inside shard_map, no VMEM plan, no lax.sort)."""
    return jnp.roll(p, span, axis=1)


def _partner(p, span: int, in_lower, roll=_roll_tpu):
    """Value at lane ^ span (the compare-exchange partner).  The rolls
    wrap, but a lane only reads the direction that stays in range.
    Negative roll shifts SIGABRT the Mosaic compiler (probed on v5e) —
    the forward roll rides the circular equivalent L - span."""
    L = p.shape[1]
    fwd = roll(p, L - span)   # lane + span
    bwd = roll(p, span)       # lane - span
    return jnp.where(in_lower, fwd, bwd)


def _gtn(a_keys, b_keys):
    """Strict lexicographic compare over an arbitrary key-plane list.
    The running-equality plane is not materialised for the final key
    (its eq is never consumed): with a seq tie-break that saves one
    compare+and per merge stage — the only reducible part of the seq
    path's extra stage work (the extra key plane itself is not
    foldable: ns timestamps already fill 64 bits across (hi, lo), and
    the seq is arbitrary 32-bit user data — see BUILDING.md)."""
    gt = None
    eq = None
    last = len(a_keys) - 1
    for i, (a, b) in enumerate(zip(a_keys, b_keys)):
        term = (a > b) if eq is None else eq & (a > b)
        gt = term if gt is None else gt | term
        if i < last:
            eq = (a == b) if eq is None else eq & (a == b)
    return gt


def _exchange(planes, take):
    return [jnp.where(take, pp, p) for p, pp in planes]


def _merge_stage(keys, payload, span: int, shape, roll=_roll_tpu):
    """One ascending bitonic-merge stage over all planes; the
    lexicographic key-plane list decides the swap.  Returns the swap
    mask too: each stage exchanges disjoint lane pairs, so it is an
    involution — replaying the recorded masks in reverse order inverts
    the whole merge permutation (the O(log) unmerge that replaces an
    O(log^2) routing sort)."""
    in_lower = (_lane(shape) & span) == 0
    pkeys = [_partner(k, span, in_lower, roll) for k in keys]
    gt = _gtn(keys, pkeys)
    # lower lane keeps the min, upper the max (ascending network).
    # take is symmetric across each pair (strict total order): both
    # lanes of a swapped pair have take=True
    take = jnp.logical_xor(gt, ~in_lower)
    keys = _exchange(list(zip(keys, pkeys)), take)
    payload = _exchange(
        [(p, _partner(p, span, in_lower, roll)) for p in payload], take
    )
    return keys, payload, take


def _unmerge_stage(payload, take, span: int, shape, roll=_roll_tpu):
    """Apply one recorded merge exchange to the payload planes (its own
    inverse): lanes with take=True swap with their span-partner."""
    in_lower = (_lane(shape) & span) == 0
    return _exchange(
        [(p, _partner(p, span, in_lower, roll)) for p in payload], take
    )


def _ffill_stage_keyed(planes, span: int, shape, sid=None, roll=_roll_tpu):
    """Lockstep fill: the LAST plane (the last-right-row index channel,
    NaN at left/pad slots) keys the fill, and every plane moves with
    it — so each slot always holds the fields of ONE source row.  This
    realises skipNulls=False (all columns from the single last right
    row, nulls included, tsdf.py:123-136): value planes are NaN-encoded
    per right row (NaN = that row's value is null), and a filled slot
    inherits the whole row, NaNs and all.  Pointer-doubling correctness
    is the per-plane argument applied to the key plane; the other
    planes ride its take mask, preserving the one-source invariant by
    induction."""
    ok = _lane(shape) >= span
    if sid is not None:
        ok = ok & (roll(sid, span) == sid)
    take = jnp.isnan(planes[-1]) & ok
    out = []
    for p in planes:
        prev = roll(p, span)
        out.append(jnp.where(take, prev, p))
    return out


def _ffill_stage(planes, span: int, shape, sid=None, roll=_roll_tpu):
    """planes[i] <- planes[i] if non-NaN else planes[i - span].  With
    ``sid`` (bin-packed rows: multiple series per lane row) the fill is
    *segmented* — a previous value is taken only when it belongs to the
    same series; series are contiguous runs, so a matching sid at
    distance ``span`` implies the whole gap is one series."""
    ok = _lane(shape) >= span
    if sid is not None:
        ok = ok & (roll(sid, span) == sid)
    out = []
    for p in planes:
        prev = roll(p, span)
        # strongly-typed f32 NaN: interpret mode re-traces kernel
        # jaxprs under the caller's (x64) config at lowering time, and
        # a weak python-float constant would come out f64 there
        prev = jnp.where(ok, prev, jnp.float32(jnp.nan))
        out.append(jnp.where(jnp.isnan(p), prev, p))
    return out


def _make_kernel(n_payload: int, Lc2: int, Llp: int, n_keys: int,
                 segmented: bool, keyed_fill: bool):
    """Kernel closure: merge + ffill + unmerge on [bk, Lc2] blocks.
    ``n_keys`` counts the key planes (sid? + ts hi/lo + seq planes? +
    side); with ``segmented``, the leading series-id key plane both
    orders the merge (so bin-packed series never interleave) and fences
    the fill.  ``keyed_fill`` switches the ladder to the lockstep
    skipNulls=False form (`_ffill_stage_keyed`).

    Routing back to input lanes replays the merge's recorded swap masks
    in reverse (each stage is an involution over disjoint pairs), which
    lands every filled slot exactly where it started — the left rows at
    lanes [0, Llp).  log2(Lc2) stages instead of the log^2 bitonic sort
    a destination-keyed route would need."""

    def kernel(*refs):
        key_refs = refs[:n_keys]
        payload_refs = refs[n_keys: n_keys + n_payload]
        out_refs = refs[n_keys + n_payload:]
        shape = key_refs[0].shape
        keys = [r[:] for r in key_refs]
        payload = [r[:] for r in payload_refs]

        takes = []
        span = Lc2 // 2
        while span >= 1:
            keys, payload, take = _merge_stage(keys, payload, span, shape)
            takes.append((span, take))
            span //= 2

        sid = keys[0] if segmented else None
        stage = _ffill_stage_keyed if keyed_fill else _ffill_stage
        span = 1
        while span < Lc2:
            payload = stage(payload, span, shape, sid=sid)
            span *= 2

        for span, take in reversed(takes):
            payload = _unmerge_stage(payload, take, span, shape)

        for r, p in zip(out_refs, payload):
            r[:] = p[:, :Llp]

    return kernel


_VMEM_CAP = 90 * 2**20  # headroom under the raised 100M scoped limit


def _plan_merge(K: int, Lc2: int, n_payload: int, n_keys: int):
    """(grid, bk=8, K_pad) or None.  Footprint model: ~6x the resident
    (payload + key) planes — calibrated against the compiler's own
    accounting of the first kernel revision (21.6M peak at [8, 16384]
    with 3+3 planes ≈ pipelined I/O double buffers + network
    temporaries) — PLUS one plane-slot per recorded unmerge swap mask
    (log2(Lc2) of them stay live across the ffill and unmerge ladders;
    bools, but budgeted at vreg width).  The segmented path adds a 4th
    (sid) key plane; every term must be counted or the gate admits
    shapes Mosaic then rejects."""
    bk = 8
    n_masks = max(Lc2.bit_length() - 1, 0)
    planes = 6 * (n_payload + n_keys) + n_masks
    if bk * Lc2 * 4 * planes > _VMEM_CAP:
        return None
    K_pad = -(-K // bk) * bk
    return (K_pad // bk,), bk, K_pad


@functools.partial(
    jax.jit, static_argnames=("n_payload", "Lc2", "Llp", "segmented",
                              "keyed_fill", "interpret")
)
def _merge_call(keys, payload, n_payload, Lc2, Llp, segmented=False,
                keyed_fill=False, interpret=False):
    K = keys[0].shape[0]
    n_keys = len(keys)
    plan = _plan_merge(K, Lc2, n_payload, n_keys)
    if plan is None:
        # callers are expected to consult merge_join_supported first; a
        # silent whole-array block here would be strictly larger than
        # the block the planner just rejected
        raise ValueError(
            f"asof merge kernel infeasible: [8, {Lc2}] blocks with "
            f"~{6 * (n_payload + n_keys)} buffered plane-slots plus "
            f"{max(Lc2.bit_length() - 1, 0)} unmerge masks exceed the "
            f"VMEM budget; use the XLA sortmerge forms for this shape"
        )
    grid, bk, K_pad = plan
    args = [pk._pad_rows(a, K_pad) for a in (*keys, *payload)]
    with pk.x64_off():
        spec = pl.BlockSpec((bk, Lc2), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
        ospec = pl.BlockSpec((bk, Llp), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
        out = pl.pallas_call(
            _make_kernel(n_payload, Lc2, Llp, n_keys=n_keys,
                         segmented=segmented, keyed_fill=keyed_fill),
            grid=grid,
            in_specs=[spec] * (n_keys + n_payload),
            out_specs=[ospec] * n_payload,
            out_shape=[jax.ShapeDtypeStruct((K_pad, Llp), jnp.float32)]
            * n_payload,
            # the network temporaries + pipelined I/O buffers exceed the
            # 16M default scoped-vmem cap at [8, 16384] blocks; v5e has
            # 128M physical VMEM per core — raise the cap instead of
            # shrinking blocks below Mosaic's 8-sublane minimum
            compiler_params=pk.tpu_compiler_params(
                vmem_limit_bytes=100 * 1024 * 1024,
            ),
            interpret=interpret,
        )(*args)
    return tuple(o[:K] for o in out)


def _split_ts(ts):
    """int64 ns -> (hi, lo) i32 planes preserving order under
    lexicographic signed compare (lo bias-corrected)."""
    ts = ts.astype(jnp.int64)
    hi = (ts >> 32).astype(jnp.int32)
    lo = ((ts & 0xFFFFFFFF) - (1 << 31)).astype(jnp.int32)
    return hi, lo


def _seq_key_planes(seq):
    """Order-preserving i32 key planes for a sequence plane (the sort
    key of the reference's tie-break, tsdf.py:117-121).  Floats ride
    the IEEE sign-fold (monotone int of the bit pattern: non-negative
    keeps its bits, negative maps to int_min - bits — exact for every
    value including the caller's ±inf null/pad encodings; NaN is
    excluded by the packing contract, which maps null seq to -inf
    before any kernel).  64-bit keys split (hi, bias-corrected lo)
    like the ts planes."""
    if seq.dtype == jnp.int32:
        return [seq]
    if seq.dtype == jnp.int64:
        return list(_split_ts(seq))
    if seq.dtype == jnp.float32:
        b = jax.lax.bitcast_convert_type(seq, jnp.int32)
        return [jnp.where(b >= 0, b, jnp.int32(-(2**31)) - b)]
    # float64 never reaches the kernel: a 64-bit bitcast-convert is
    # unimplemented in the TPU backend's X64-rewrite pass (probed on
    # v5e, 2026-07-30) — dispatchers re-encode concrete f64 planes via
    # seq_kernel_form() first
    raise TypeError(f"unsupported sequence dtype {seq.dtype}")


def seq_kernel_form(seq):
    """Concrete float64 sequence plane -> a kernel-expressible dtype,
    or None when it must stay on the XLA path.

    The TPU X64 rewriter cannot lower ``bitcast_convert(f64 -> s64)``
    (probed), so f64 seq keys cannot ride the IEEE sign-fold on
    device.  Instead, outside jit: cast to f32 when every value
    round-trips exactly (±inf sentinels included); else, integral
    values re-encode as int64 (shift/mask splitting IS supported — the
    ts planes prove it) with ±inf mapped to the int64 extremes.  The
    -inf -> int64-min collapse merges the null-seq key with the
    synthesized left key — semantically invisible: they tie on seq and
    the side key orders right-before-left, the same visible set as the
    strict float order (tsdf.py:117-121 NULLS FIRST + rec_ind).

    f32/int planes pass through; tracers (in-jit callers, e.g. the
    dist shard_map kernels, which use the f32 compute dtype anyway)
    and inexpressible f64 return None."""
    if seq is None:
        return seq
    if isinstance(seq, jax.core.Tracer):
        return None if seq.dtype == jnp.float64 else seq
    if seq.dtype != jnp.float64:
        return seq
    a = np.asarray(seq)
    f32 = a.astype(np.float32)
    if np.array_equal(f32.astype(np.float64), a):
        return jnp.asarray(f32)
    finite = np.isfinite(a)
    af = a[finite]
    if np.array_equal(af, np.floor(af)) and (
            af.size == 0 or np.abs(af).max() < 2.0**62):
        i = np.where(finite, a, 0.0).astype(np.int64)
        i = np.where(a == np.inf, np.iinfo(np.int64).max, i)
        i = np.where(a == -np.inf, np.iinfo(np.int64).min, i)
        return jnp.asarray(i)
    return None


def _n_seq_planes(l_seq, r_seq):
    """Key-plane count the sequence pair will need, or None when the
    (promoted) dtype has no order-preserving i32 mapping here (f64:
    see seq_kernel_form — dispatchers re-encode before the gate)."""
    if l_seq is None and r_seq is None:
        return 0
    dts = [s.dtype for s in (l_seq, r_seq) if s is not None]
    pdt = dts[0] if len(dts) == 1 else jnp.promote_types(*dts)
    if pdt in (jnp.int32, jnp.float32):
        return 1
    if pdt == jnp.int64:
        return 2
    return None


def _seq_sides(l_seq, r_seq, K, Ll, Lr):
    """(l_seq, r_seq) with the None side synthesized at the dtype
    minimum and both cast to the promoted dtype — exactly the XLA
    ``_merge_sides`` construction (sortmerge.py): the synthesized side
    sits above the -inf null encoding and below any real value, giving
    right-null < left < right-non-null on ts ties (Spark ASC NULLS
    FIRST + rec_ind, tsdf.py:117-121)."""
    sdt = (l_seq if l_seq is not None else r_seq).dtype
    neg = (
        jnp.finfo(sdt).min
        if jnp.issubdtype(sdt, jnp.floating)
        else jnp.iinfo(sdt).min
    )
    ls = l_seq if l_seq is not None else jnp.full((K, Ll), neg, sdt)
    rs = r_seq if r_seq is not None else jnp.full((K, Lr), neg, sdt)
    pdt = jnp.promote_types(ls.dtype, rs.dtype)
    return ls.astype(pdt), rs.astype(pdt)


def _build_join_planes(l_ts, r_ts, r_valids, r_values, l_sid, r_sid,
                       l_seq, r_seq):
    """Key/payload plane construction shared by the single-plan kernel
    and the XLA bitonic engine: i32 key planes (sid? + ts hi/lo + seq
    planes? + side) and NaN-encoded f32 payload planes (C values + the
    last-right-row index channel) in the ``[left asc | reversed right]``
    bitonic concat layout.  Pad keys are i32-max so pads sort after
    every real row.  Returns ``(keys, payload, Lc2, Llp)``."""
    C = int(r_values.shape[0])
    K, Ll = l_ts.shape
    Lr = r_ts.shape[-1]
    segmented = l_sid is not None
    Lrp, Lc2, Llp = _pad_plan(Ll, Lr)

    hi_l, lo_l = _split_ts(l_ts)
    hi_r, lo_r = _split_ts(r_ts)
    imax = jnp.int32(_I32_MAX)
    padl = _pad_lanes

    hi_l = padl(hi_l, Llp - Ll, imax)
    lo_l = padl(lo_l, Llp - Ll, imax)
    hi_r = padl(hi_r, Lrp - Lr, imax)
    lo_r = padl(lo_r, Lrp - Lr, imax)
    sec_l = _SIDE + _lane((K, Llp))
    sec_r = _lane((K, Lrp))

    rev = _rev
    keys = []
    if segmented:
        sid_l = padl(l_sid.astype(jnp.int32), Llp - Ll, imax)
        sid_r = padl(r_sid.astype(jnp.int32), Lrp - Lr, imax)
        keys.append(jnp.concatenate([sid_l, rev(sid_r)], axis=-1))
    keys.append(jnp.concatenate([hi_l, rev(hi_r)], axis=-1))
    keys.append(jnp.concatenate([lo_l, rev(lo_r)], axis=-1))
    if l_seq is not None or r_seq is not None:
        ls, rs = _seq_sides(l_seq, r_seq, K, Ll, Lr)
        for pl_, pr_ in zip(_seq_key_planes(ls), _seq_key_planes(rs)):
            keys.append(jnp.concatenate(
                [padl(pl_, Llp - Ll, imax), rev(padl(pr_, Lrp - Lr, imax))],
                axis=-1,
            ))
    keys.append(jnp.concatenate([sec_l, rev(sec_r)], axis=-1))

    nanl = jnp.full((K, Llp), jnp.nan, jnp.float32)
    payload = []
    for c in range(C):
        v = jnp.where(r_valids[c], r_values[c].astype(jnp.float32),
                      jnp.nan)
        payload.append(
            jnp.concatenate([nanl, rev(padl(v, Lrp - Lr, jnp.nan))],
                            axis=-1)
        )
    ridx = jnp.broadcast_to(
        jnp.arange(Lr, dtype=jnp.float32), (K, Lr)
    )
    payload.append(
        jnp.concatenate([nanl, rev(padl(ridx, Lrp - Lr, jnp.nan))],
                        axis=-1)
    )
    return keys, payload, Lc2, Llp


def _join_outputs(out, C, K, Ll):
    """(vals, found, last_row_idx) from filled payload planes."""
    vals = (jnp.stack([o[:, :Ll] for o in out[:C]]) if C
            else jnp.zeros((0, K, Ll), jnp.float32))
    found = ~jnp.isnan(vals)
    idx_f = out[C][:, :Ll]
    idx = jnp.where(jnp.isnan(idx_f), -1, idx_f).astype(jnp.int32)
    return vals, found, idx


@functools.partial(jax.jit,
                   static_argnames=("skip_nulls", "interpret"))
def asof_merge_values_pallas(l_ts, r_ts, r_valids, r_values,
                             l_sid=None, r_sid=None,
                             l_seq=None, r_seq=None,
                             skip_nulls: bool = True,
                             interpret: bool = False):
    """float path of ``asof_merge_values`` as one Pallas kernel; same
    contract: ``(vals [C, K, Ll], found, last_row_idx)``.  REQUIRES
    both ts arrays ascending per row (packed-layout invariant) — with
    ``l_seq``/``r_seq``, ascending in (ts, seq), which the layout sort
    guarantees (packing.py:228-245).

    ``skip_nulls=False`` switches the ffill ladder to the lockstep
    keyed form: every output column comes from the single last right
    row, nulls included (tsdf.py:123-136) — the payload encoding is
    identical (NaN = null), only the fill rule changes.

    ``l_sid``/``r_sid`` ([K, L] int32, non-decreasing per row) engage
    the *bin-packed* form: each lane row holds several series
    back-to-back (the skew/NBBO layout, packing.py:bin_pack_series —
    the TPU answer to the reference's tsPartitionVal skew machinery,
    tsdf.py:164-190).  The series id becomes the leading merge key and
    fences the forward fill, so co-packed series join independently;
    ``last_row_idx`` stays a within-lane-row position (callers convert
    with the per-series offsets they packed with).  REQUIRES the same
    series to occupy the same lane row on both sides.  Since round 6
    the segmented form combines with a sequence tie-break: the
    bin-packed layouts sort (ts, seq) per series when a seq plane is
    packed (join.py), so the (sid, ts, seq, side) merge precondition
    holds and seq planes slot between the ts and side keys as usual.
    """
    C = int(r_values.shape[0])
    K, Ll = l_ts.shape
    keys, payload, Lc2, Llp = _build_join_planes(
        l_ts, r_ts, r_valids, r_values, l_sid, r_sid, l_seq, r_seq)
    out = _merge_call(tuple(keys), tuple(payload), n_payload=C + 1,
                      Lc2=Lc2, Llp=Llp, segmented=l_sid is not None,
                      keyed_fill=not skip_nulls, interpret=interpret)
    return _join_outputs(out, C, K, Ll)


def _merge_network_xla(keys, payload, Lc2, Llp, segmented, keyed_fill):
    """The kernel's merge + ffill + unmerge network in plain jnp rolls.

    Identical stage functions, two differences from the VMEM form:
    every stage is an HBM round trip (XLA fuses the elementwise work
    but not the rotates), and the recorded unmerge swap masks pack as
    bits of ONE int32 plane (log2(Lc2) <= 24 stages) instead of
    log2(Lc2) live bool planes — O(1) extra memory at any width.

    ~3*log2(Lc2) simple stages compile where ``lax.sort``'s O(log^2)
    unrolled network OOM-killed the compiler at ~205K lanes
    (BASELINE.md r3), which is the point: this is the oversize engine
    for tracer contexts (shard_map in dist.py / parallel/halo.py)."""
    shape = keys[0].shape
    roll = _roll_jnp
    bits = jnp.zeros(shape, jnp.int32)
    span = Lc2 // 2
    b = 0
    while span >= 1:
        keys, payload, take = _merge_stage(keys, payload, span, shape,
                                           roll=roll)
        bits = bits | (take.astype(jnp.int32) << b)
        b += 1
        span //= 2

    sid = keys[0] if segmented else None
    stage = _ffill_stage_keyed if keyed_fill else _ffill_stage
    span = 1
    while span < Lc2:
        payload = stage(payload, span, shape, sid=sid, roll=roll)
        span *= 2

    for i in range(b - 1, -1, -1):
        take = ((bits >> i) & 1) == 1
        payload = _unmerge_stage(payload, take, Lc2 >> (i + 1), shape,
                                 roll=roll)
    return [p[:, :Llp] for p in payload]


@functools.partial(jax.jit, static_argnames=("skip_nulls",))
def asof_merge_values_bitonic(l_ts, r_ts, r_valids, r_values,
                              l_sid=None, r_sid=None,
                              l_seq=None, r_seq=None,
                              skip_nulls: bool = True):
    """XLA twin of :func:`asof_merge_values_pallas` — same contract,
    same plane construction, same network, executed as jnp rolls (see
    ``_merge_network_xla``).  Runs on any backend at any width under
    the 2^24 position-exactness bound, inside jit/shard_map."""
    C = int(r_values.shape[0])
    K, Ll = l_ts.shape
    keys, payload, Lc2, Llp = _build_join_planes(
        l_ts, r_ts, r_valids, r_values, l_sid, r_sid, l_seq, r_seq)
    out = _merge_network_xla(keys, payload, Lc2, Llp,
                             segmented=l_sid is not None,
                             keyed_fill=not skip_nulls)
    return _join_outputs(out, C, K, Ll)


@jax.jit
def asof_merge_indices_bitonic(l_ts, r_ts, r_valids, l_seq=None,
                               r_seq=None):
    """Index-returning sibling of :func:`asof_merge_values_bitonic`
    (position-encoded payloads, like the pallas indices wrapper)."""
    C = int(r_valids.shape[0])
    K, Ll = l_ts.shape
    Lr = r_ts.shape[-1]
    pos = jnp.broadcast_to(jnp.arange(Lr, dtype=jnp.float32), (K, Lr))
    planes = jnp.where(r_valids, pos[None], jnp.nan)
    out, _, last_idx = asof_merge_values_bitonic(
        l_ts, r_ts, r_valids, planes, l_seq=l_seq, r_seq=r_seq)
    per_col = jnp.where(jnp.isnan(out), -1, out).astype(jnp.int32)
    return last_idx, per_col


def merge_join_bitonic_supported(l_ts, r_ts, r_values, l_seq,
                                 r_seq) -> bool:
    """Gate for the XLA bitonic engine: f32 values, an i32-mappable
    sequence dtype, and positions exact in f32 (< 2^24 right rows /
    merged lanes).  No VMEM plan — the network streams from HBM — and
    no segmented/keyed distinction: those only change plane counts."""
    if r_values.dtype != jnp.float32:
        return False
    if _n_seq_planes(l_seq, r_seq) is None:
        return False
    K, Ll = l_ts.shape
    Lr = int(r_ts.shape[-1])
    if Lr >= (1 << 24):
        return False
    _, Lc2, _ = _pad_plan(Ll, Lr)
    return Lc2 < (1 << 24)


@functools.partial(jax.jit, static_argnames=("interpret",))
def asof_merge_indices_pallas(l_ts, r_ts, r_valids, l_seq=None,
                              r_seq=None, interpret=False):
    """Index-returning sibling of :func:`asof_merge_values_pallas` —
    the engine of the host frame path's ``asof_indices_merge`` (value
    gathering happens host-side so string columns ride the same join,
    ops/asof.py), including the sequence-tie-break form the host join
    dispatches with (join.py -> asof.py).  Same kernel, position-
    encoded payloads: plane c is ``where(valid_c, lane, NaN)``, so the
    ffill produces each column's last-valid right row index directly;
    the value wrapper's own ridx channel doubles as the unconditional
    last-row index.  Returns ``(last_row_idx [K, Ll],
    per_col_idx [C, K, Ll])``, -1 for none.  Positions are exact in
    f32 up to 2^24 rows/series."""
    C = int(r_valids.shape[0])
    K, Ll = l_ts.shape
    Lr = r_ts.shape[-1]
    pos = jnp.broadcast_to(jnp.arange(Lr, dtype=jnp.float32), (K, Lr))
    planes = jnp.where(r_valids, pos[None], jnp.nan)
    out, _, last_idx = asof_merge_values_pallas(
        l_ts, r_ts, r_valids, planes, l_seq=l_seq, r_seq=r_seq,
        interpret=interpret,
    )
    per_col = jnp.where(jnp.isnan(out), -1, out).astype(jnp.int32)
    return last_idx, per_col


def _make_rank_kernel(n_keys: int, Lc2: int, Lqp: int):
    """Searchsorted as merge + count + unmerge: merge the key and
    query streams, prefix-count the key-indicator in VMEM, unmerge via
    the recorded swap masks, and read the counts at the query lanes.
    Replaces merge_rank's two lax.sort ladders with one HBM pass."""

    def kernel(*refs):
        key_refs = refs[:n_keys]
        isk_ref, out_ref = refs[n_keys], refs[n_keys + 1]
        shape = key_refs[0].shape
        keys = [r[:] for r in key_refs]
        isk = isk_ref[:]

        takes = []
        span = Lc2 // 2
        while span >= 1:
            keys, (isk,), take = _merge_stage(keys, [isk], span, shape)
            takes.append((span, take))
            span //= 2

        # inclusive prefix count of keys along the merged stream: at a
        # query slot this IS its searchsorted rank (tie order encoded
        # in the sec key decides left/right bound)
        cnt = isk
        span = 1
        while span < Lc2:
            rolled = pltpu.roll(cnt, shift=jnp.int32(span), axis=1)
            lane = _lane(shape)
            cnt = cnt + jnp.where(lane >= span, rolled, jnp.float32(0.0))
            span *= 2

        for span, take in reversed(takes):
            (cnt,) = _unmerge_stage([cnt], take, span, shape)

        # query lanes sit reversed at the tail of the concat layout
        out_ref[:] = cnt[:, Lc2 - Lqp:]

    return kernel


@functools.partial(
    jax.jit, static_argnames=("n_keys", "Lc2", "Lqp", "interpret")
)
def _rank_call(keys, isk, n_keys, Lc2, Lqp, interpret=False):
    K = keys[0].shape[0]
    plan = _plan_merge(K, Lc2, 1, n_keys)
    if plan is None:
        raise ValueError("merge_rank kernel infeasible for this shape")
    grid, bk, K_pad = plan
    args = [pk._pad_rows(a, K_pad) for a in (*keys, isk)]
    with pk.x64_off():
        spec = pl.BlockSpec((bk, Lc2), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
        ospec = pl.BlockSpec((bk, Lqp), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
        out = pl.pallas_call(
            _make_rank_kernel(n_keys, Lc2, Lqp),
            grid=grid,
            in_specs=[spec] * (n_keys + 1),
            out_specs=ospec,
            out_shape=jax.ShapeDtypeStruct((K_pad, Lqp), jnp.float32),
            compiler_params=pk.tpu_compiler_params(
                vmem_limit_bytes=100 * 1024 * 1024,
            ),
            interpret=interpret,
        )(*args)
    return out[:K]


def _rank_key_planes(vals):
    """Order-preserving i32 plane list for a sorted operand row."""
    if vals.dtype == jnp.int64:
        hi, lo = _split_ts(vals)
        return [hi, lo]
    if vals.dtype == jnp.int32:
        return [vals]
    raise TypeError(f"unsupported rank key dtype {vals.dtype}")


@functools.partial(jax.jit, static_argnames=("side", "interpret"))
def merge_rank_pallas(sorted_keys, sorted_queries, side: str = "left",
                      interpret: bool = False):
    """Pallas form of :func:`tempo_tpu.ops.sortmerge.merge_rank` (same
    contract: np.searchsorted of each query row into each key row; both
    ascending).  int32/int64 keys; counts exact in f32 (gated to
    Lk < 2^24 by the caller)."""
    if side not in ("left", "right"):
        raise ValueError(f"side must be 'left' or 'right', got {side!r}")
    K, Lk = sorted_keys.shape
    Lq = sorted_queries.shape[-1]
    dt = jnp.promote_types(sorted_keys.dtype, sorted_queries.dtype)
    keys_k = sorted_keys.astype(dt)
    keys_q = sorted_queries.astype(dt)

    # roles swap vs the join: keys take the "left" (ascending) slot,
    # queries ride reversed; pad both with i32-max planes
    Lqp, Lc2, Lkp = _pad_plan(Lk, Lq)
    imax = jnp.int32(_I32_MAX)

    kp = _rank_key_planes(keys_k)
    qp = _rank_key_planes(keys_q)
    kp = [_pad_lanes(p, Lkp - Lk, imax) for p in kp]
    qp = [_pad_lanes(p, Lqp - Lq, imax) for p in qp]
    # tie key: side='left' -> queries sort before equal keys (rank
    # counts strictly-smaller keys); 'right' -> after.  pos keeps the
    # order strictly total (and the swap masks symmetric).
    if side == "left":
        sec_k = _SIDE + _lane((K, Lkp))
        sec_q = _lane((K, Lqp))
    else:
        sec_k = _lane((K, Lkp))
        sec_q = _SIDE + _lane((K, Lqp))

    rev = _rev
    planes = [jnp.concatenate([a, rev(b)], axis=-1)
              for a, b in zip(kp, qp)]
    planes.append(jnp.concatenate([sec_k, rev(sec_q)], axis=-1))
    isk = jnp.concatenate(
        [
            jnp.ones((K, Lkp), jnp.float32)
            * (_lane((K, Lkp)) < Lk),
            jnp.zeros((K, Lqp), jnp.float32),
        ],
        axis=-1,
    )
    out = _rank_call(tuple(planes), isk, n_keys=len(planes), Lc2=Lc2,
                     Lqp=Lqp, interpret=interpret)
    ranks = jnp.flip(out, axis=-1)[:, :Lq]
    return ranks.astype(jnp.int32)


def merge_rank_supported(sorted_keys, sorted_queries) -> bool:
    if not _pallas_enabled():
        return False
    if sorted_keys.dtype not in (jnp.int32, jnp.int64):
        return False
    if jnp.promote_types(sorted_keys.dtype, sorted_queries.dtype) \
            not in (jnp.int32, jnp.int64):
        return False
    K, Lk = sorted_keys.shape
    if Lk >= (1 << 24):
        return False
    Lq = int(sorted_queries.shape[-1])
    # MUST mirror merge_rank_pallas's call exactly (keys first)
    _, Lc2, _ = _pad_plan(Lk, Lq)
    n_keys = 3 if jnp.promote_types(
        sorted_keys.dtype, sorted_queries.dtype) == jnp.int64 else 2
    return _plan_merge(K, Lc2, 1, n_keys) is not None


def _pallas_enabled() -> bool:
    """Shared kill-switch + backend gate for every Pallas join path."""
    from tempo_tpu import config

    env = config.get("TEMPO_TPU_PALLAS_ASOF")
    if env is not None and env in ("0", "false", "no"):
        return False
    return jax.default_backend() == "tpu"


def merge_indices_supported(l_ts, r_ts, r_valids, l_seq=None,
                            r_seq=None) -> bool:
    """Gate for the index kernel: the value-kernel conditions with C
    position payloads (+ the wrapper's ridx channel)."""
    if not _pallas_enabled():
        return False
    if int(r_ts.shape[-1]) >= (1 << 24):
        return False
    nsq = _n_seq_planes(l_seq, r_seq)
    if nsq is None:
        return False
    K, Ll = l_ts.shape
    _, Lc2, _ = _pad_plan(Ll, int(r_ts.shape[-1]))
    C = int(r_valids.shape[0])
    return _plan_merge(K, Lc2, C + 1, 3 + nsq) is not None


def merge_join_supported(l_ts, r_ts, r_values, l_seq, r_seq,
                         skip_nulls: bool,
                         segmented: bool = False) -> bool:
    """Gate for the Pallas path: f32 values, TPU backend, a seq dtype
    with an i32 key mapping (or none), and a feasible VMEM plan.
    skipNulls=False rides the keyed lockstep fill; the sequence
    tie-break adds 1-2 key planes.  Since round 6, bin-packed
    (segmented) rows combine with a sequence column too: the bin-pack
    layouts are built from (ts, seq)-sorted per-series runs when a seq
    plane is packed (join.py / packing.build_layout_from_codes), so
    the (sid, ts, seq, side) merge precondition holds and the seq
    planes slot in as usual.

    NaN semantics: the kernel NaN-encodes validity, so a slot that is
    marked valid but holds NaN is treated as null.  That is the
    framework's packing invariant (pandas ingest maps float NaN to
    null before values reach any kernel — frame.py:numeric_flat,
    dist.py packing), so no public-API caller can observe the
    difference; direct kernel callers must honour it.
    """
    if not _pallas_enabled():
        return False
    if r_values.dtype != jnp.float32:
        return False
    nsq = _n_seq_planes(l_seq, r_seq)
    if nsq is None:
        return False
    K, Ll = l_ts.shape
    Lr = r_ts.shape[-1]
    _, Lc2, _ = _pad_plan(Ll, Lr)
    C = int(r_values.shape[0])
    n_keys = 3 + nsq + (1 if segmented else 0)
    return _plan_merge(K, Lc2, C + 1, n_keys) is not None


# ----------------------------------------------------------------------
# Lane-chunked streaming merge: the join past the single-shot VMEM plan
# ----------------------------------------------------------------------

def join_chunk_lanes_override():
    """``TEMPO_TPU_JOIN_CHUNK_LANES`` — explicit merged-lane chunk width
    (power of two >= 256) for the streaming engine; env unset falls
    back to the tuned-profile prior (tempo_tpu/tune), then to the
    largest width the VMEM plan admits."""
    from tempo_tpu import config, tune

    n = config.get_int("TEMPO_TPU_JOIN_CHUNK_LANES")
    if n is None:
        n = tune.knob_value("TEMPO_TPU_JOIN_CHUNK_LANES")
    return None if n is None else int(n)


def _chunk_plane_counts(C: int, nsq: int, segmented: bool, keyed: bool,
                       max_lookback: int):
    """(n_keys, n_payload, n_out) of one chunk program.  maxLookback
    adds source-position (psrc) planes: one per channel for the
    independent per-column fill (each channel's last-valid source has
    its own merged position), a single lockstep plane for the keyed
    skipNulls=False fill."""
    n_keys = (1 if segmented else 0) + 2 + nsq + 1
    n_out = C + 1
    n_payload = n_out + ((1 if keyed else n_out) if max_lookback else 0)
    return n_keys, n_payload, n_out


def _plan_chunk_lanes(n_payload: int, n_keys: int, override=None,
                      depth=None):
    """Largest power-of-two chunk width whose program fits the VMEM
    budget — the single-plan footprint model plus the recorded unmerge
    masks and ~2 plane-slots of carry scratch.  None when even a
    256-lane chunk does not fit (absurd column counts).  ``depth``
    (``TEMPO_TPU_DMA_BUFFERS`` when unset) folds the payload prefetch
    ring at its full N-deep size — exactly the accounting the static
    analyzer's vmem-budget rule applies to the declared scratch."""
    if depth is None:
        depth = psr.dma_buffers()
    if override:
        Cm = int(override)
        if Cm < 256 or Cm & (Cm - 1):
            raise ValueError(
                f"TEMPO_TPU_JOIN_CHUNK_LANES must be a power of two "
                f">= 256, got {Cm}")
        return Cm
    best = None
    Cm = 256
    while Cm <= (1 << 15):
        n_masks = Cm.bit_length() - 1
        planes = (6 * n_keys + (4 + max(depth, 2)) * n_payload
                  + n_masks + 2)
        if 8 * Cm * 4 * planes > _VMEM_CAP:
            break
        best = Cm
        Cm *= 2
    return best


def _make_chunked_kernel(n_payload: int, n_out: int, Cm: int, n_keys: int,
                         segmented: bool, keyed_fill: bool,
                         chunk_rows: int, windowed: bool,
                         depth: int = 2, bk: int = 8, nc: int = 1):
    """Streaming kernel closure: one full merge + ffill + unmerge
    network per [bk, Cm] chunk block, with the cross-chunk fill state
    carried in VMEM scratch across the (sequential) chunk grid axis —
    the FlashAttention tiling idiom applied to the forward fill.

    Carry-in: after the in-chunk ladder, slots with no in-chunk source
    take the previous chunks' last fill state (per plane, or lockstep
    for the keyed skipNulls=False fill); with series-segmented rows
    only lanes of the series live at the previous chunk's tail are
    eligible (the host gives chunk-tail pads that series' id —
    packing.AsofChunkPlan — so the state is readable at the last lane).
    Carry-out: every payload plane's last lane, recorded BEFORE the
    maxLookback nulling (staleness is a property of the consuming
    slot's merged position, not of the state itself).

    maxLookback (``windowed``): payload carries the source's global
    merged position (chunk * chunk_rows + lane — exact because greedy
    chunking keeps every chunk before a non-empty one full); a filled
    slot whose source sits more than the horizon (a runtime SMEM
    scalar — one compile per shape for any cap) merged rows back nulls
    out, which is exact for last-valid fills: any earlier candidate is
    further away still.

    ``depth > 2``: the payload planes (the bulk of the chunk traffic)
    arrive through an explicit ``depth``-slot DMA ring instead of the
    implicit double-buffered BlockSpec pipeline — chunk ``c+depth-1``'s
    copy is in flight while chunk ``c``'s merge network computes, which
    smooths the network's long, chunk-count-independent compute tail.
    The ring rides the SEQUENTIAL chunk axis (it is itself a cross-step
    carry, like the fill scratch), so the megacore split stays on the
    row axis only — the grid-carry legality rule in BUILDING.md."""
    CL = Cm // 2

    def kernel(*refs):
        n_sc = 1 if windowed else 0
        ml_ref = refs[0] if windowed else None
        key_refs = refs[n_sc: n_sc + n_keys]
        payload_refs = refs[n_sc + n_keys: n_sc + n_keys + n_payload]
        out_refs = refs[n_sc + n_keys + n_payload:
                        n_sc + n_keys + n_payload + n_out]
        carry_ref = refs[n_sc + n_keys + n_payload + n_out]
        sid_carry = (refs[n_sc + n_keys + n_payload + n_out + 1]
                     if segmented else None)
        shape = key_refs[0].shape
        c = pl.program_id(1)

        @pl.when(c == 0)
        def _reset():
            carry_ref[...] = jnp.full(carry_ref.shape, jnp.nan,
                                      jnp.float32)
            if segmented:
                sid_carry[...] = jnp.full(sid_carry.shape, -1, jnp.int32)

        keys = [r[:] for r in key_refs]
        if depth > 2:
            # payload refs live in HBM (memory_space=ANY): stream chunk
            # slabs through the prefetch ring.  Ring + semaphores are
            # the last two scratch operands.
            ring, psem = refs[-2], refs[-1]
            i = pl.program_id(0)

            def pdma(cc, p, slot):
                return pltpu.make_async_copy(
                    payload_refs[p].at[pl.ds(i * bk, bk),
                                       pl.ds(cc * Cm, Cm)],
                    ring.at[slot, p],
                    psem.at[slot, p],
                )

            @pl.when(c == 0)
            def _warm():
                # the chunk axis restarts at every row block, so the
                # warm-up refills the ring per block (megacore-safe:
                # each core owns whole row blocks)
                for q in range(min(depth - 1, nc)):
                    for p in range(n_payload):
                        pdma(q, p, q).start()

            nxt = c + depth - 1

            @pl.when(nxt < nc)
            def _prefetch():
                for p in range(n_payload):
                    pdma(nxt, p, nxt % depth).start()

            slot = c % depth
            for p in range(n_payload):
                pdma(c, p, slot).wait()
            payload = [ring[slot, p] for p in range(n_payload)]
        else:
            payload = [r[:] for r in payload_refs]

        takes = []
        span = Cm // 2
        while span >= 1:
            keys, payload, take = _merge_stage(keys, payload, span, shape)
            takes.append((span, take))
            span //= 2

        sid = keys[0] if segmented else None
        stage = _ffill_stage_keyed if keyed_fill else _ffill_stage
        span = 1
        while span < Cm:
            payload = stage(payload, span, shape, sid=sid)
            span *= 2

        carry = [carry_ref[i, :, :1] for i in range(n_payload)]
        elig = (sid == sid_carry[:, :1]) if segmented else None
        if keyed_fill:
            take_c = jnp.isnan(payload[-1])
            if elig is not None:
                take_c = take_c & elig
            payload = [jnp.where(take_c, cp, p)
                       for p, cp in zip(payload, carry)]
        else:
            for i in range(n_payload):
                t = jnp.isnan(payload[i])
                if elig is not None:
                    t = t & elig
                payload[i] = jnp.where(t, carry[i], payload[i])

        for i in range(n_payload):
            carry_ref[i] = jnp.broadcast_to(
                payload[i][:, Cm - 1:Cm], (shape[0], 128))
        if segmented:
            sid_carry[...] = jnp.broadcast_to(
                sid[:, Cm - 1:Cm], (shape[0], 128))

        if windowed:
            ml = ml_ref[0]
            pos_self = (_lane(shape) + c * chunk_rows).astype(jnp.float32)
            if keyed_fill:
                stale = pos_self - payload[-1] > ml
                payload = [jnp.where(stale, jnp.float32(jnp.nan), p)
                           for p in payload]
            else:
                for i in range(n_out):
                    stale = pos_self - payload[n_out + i] > ml
                    payload[i] = jnp.where(stale, jnp.float32(jnp.nan),
                                           payload[i])

        outp = payload[:n_out]
        for span, take in reversed(takes):
            outp = _unmerge_stage(outp, take, span, shape)
        for r, p in zip(out_refs, outp):
            r[:] = p[:, :CL]

    return kernel


@functools.partial(
    jax.jit,
    static_argnames=("n_payload", "n_out", "Cm", "segmented",
                     "keyed_fill", "chunk_rows", "windowed", "depth",
                     "interpret"),
)
def _chunked_call(keys, payload, n_payload, n_out, Cm, segmented,
                  keyed_fill, chunk_rows, windowed=False, ml=None,
                  depth=2, interpret=False):
    K = keys[0].shape[0]
    nc = keys[0].shape[1] // Cm
    n_keys = len(keys)
    CL = Cm // 2
    bk = 8
    K_pad = -(-K // bk) * bk
    # the payload ring needs at least two chunks to overlap anything
    use_ring = depth > 2 and nc >= 2
    args = [pk._pad_rows(a, K_pad) for a in (*keys, *payload)]
    if windowed:
        # the horizon is a runtime SMEM scalar: one compiled program
        # per shape serves every maxLookback value
        args = [jnp.asarray(ml, jnp.float32).reshape(1)] + args
    with pk.x64_off():
        spec = pl.BlockSpec((bk, Cm), lambda i, c: (i, c),
                            memory_space=pltpu.VMEM)
        ospec = pl.BlockSpec((bk, CL), lambda i, c: (i, c),
                             memory_space=pltpu.VMEM)
        # ring mode keeps the payload planes in HBM and streams them
        # through the explicit prefetch ring (scratch below)
        pspec = (pl.BlockSpec(memory_space=pltpu.ANY) if use_ring
                 else spec)
        sspec = [pl.BlockSpec(memory_space=pltpu.SMEM)] if windowed \
            else []
        scratch = [pltpu.VMEM((n_payload, bk, 128), jnp.float32)]
        if segmented:
            scratch.append(pltpu.VMEM((bk, 128), jnp.int32))
        if use_ring:
            scratch.append(pltpu.VMEM((depth, n_payload, bk, Cm),
                                      jnp.float32))
            scratch.append(pltpu.SemaphoreType.DMA((depth, n_payload)))
        out = pl.pallas_call(  # lint-ok: vmem-budget: Cm (and the ring depth) is sized by _plan_chunk_lanes in every caller (asof_merge_*_chunked)
            _make_chunked_kernel(n_payload, n_out, Cm, n_keys,
                                 segmented, keyed_fill, chunk_rows,
                                 windowed,
                                 depth=depth if use_ring else 2,
                                 bk=bk, nc=nc),
            # row blocks are independent (parallel); the chunk axis
            # carries the fill state AND the prefetch ring and MUST
            # run sequentially (pallas_stream.grid_semantics)
            grid=(K_pad // bk, nc),
            in_specs=sspec + [spec] * n_keys + [pspec] * n_payload,
            out_specs=[ospec] * n_out,
            out_shape=[jax.ShapeDtypeStruct((K_pad, nc * CL),
                                            jnp.float32)] * n_out,
            scratch_shapes=scratch,
            compiler_params=pk.tpu_compiler_params(
                vmem_limit_bytes=100 * 1024 * 1024,
                dimension_semantics=psr.grid_semantics(
                    2, carry_axes=(1,)),
            ),
            interpret=interpret,
        )(*args)
    return tuple(o[:K] for o in out)


def _split_ts_np(ts):
    """Numpy mirror of ``_split_ts``."""
    ts = ts.astype(np.int64)
    hi = (ts >> 32).astype(np.int32)
    lo = ((ts & 0xFFFFFFFF) - (1 << 31)).astype(np.int32)
    return hi, lo


def _seq_key_planes_np(seq):
    """Numpy mirror of ``_seq_key_planes`` (same bit-exact order maps,
    applied host-side while the chunked layout is built)."""
    if seq.dtype == np.int32:
        return [seq]
    if seq.dtype == np.int64:
        return list(_split_ts_np(seq))
    if seq.dtype == np.float32:
        b = seq.view(np.int32)
        return [np.where(b >= 0, b.astype(np.int64),
                         np.int64(-(2**31)) - b.astype(np.int64)
                         ).astype(np.int32)]
    raise TypeError(f"unsupported sequence dtype {seq.dtype}")


def _scatter_into(base, src, dest):
    """In-place scatter of real lanes into an already-filled chunked
    plane (``dest`` from packing.asof_chunk_plan; -1 entries dropped)."""
    rows = np.broadcast_to(np.arange(base.shape[0])[:, None], dest.shape)
    m = dest >= 0
    base[rows[m], dest[m]] = src[m]
    return base


def _require_concrete(name, a):
    if isinstance(a, jax.core.Tracer):
        raise TypeError(
            f"the chunked asof engine builds its lane layout host-side "
            f"and requires concrete arrays ({name} is a tracer); inside "
            f"jit/shard_map use asof_merge_values_bitonic instead")
    return np.asarray(a)


def asof_merge_values_chunked(l_ts, r_ts, r_valids, r_values,
                              l_sid=None, r_sid=None,
                              l_seq=None, r_seq=None,
                              skip_nulls: bool = True,
                              max_lookback: int = 0,
                              chunk_lanes=None,
                              interpret: bool = False):
    """Lane-chunked streaming form of :func:`asof_merge_values_pallas`
    — same contract and flag surface PLUS ``max_lookback`` (which the
    single-plan kernel never supported), at any length under 2^24
    merged rows per lane row.

    Host-orchestrated: the merge-path chunk split and the chunk-major
    scatter/unscatter are numpy (packing.asof_chunk_plan — the same
    cost class as the packing every join already pays), the join itself
    is ONE pallas_call gridded (row blocks × chunks) with the fill
    state carried across chunks in VMEM scratch.  HBM traffic stays
    one read + one write of the (≤2x padded) chunk layout regardless
    of length — the property the single-plan kernel had and the XLA
    ladders lose.  Outputs are bit-identical to the single-plan kernel
    and the XLA oracle: fills select values, they never compute."""
    keys, planes, plan, meta = build_chunked_planes(
        l_ts, r_ts, r_valids, r_values, l_sid=l_sid, r_sid=r_sid,
        l_seq=l_seq, r_seq=r_seq, skip_nulls=skip_nulls,
        max_lookback=max_lookback, chunk_lanes=chunk_lanes)
    # every operand is 32-bit by construction, so the whole call can
    # run in the 32-bit scope interpret mode needs (pk.interpret_scope)
    ml = int(max_lookback or 0)
    with pk.interpret_scope(interpret):
        out = _chunked_call(
            tuple(jnp.asarray(k) for k in keys),
            tuple(jnp.asarray(x) for x in planes),
            n_payload=meta["n_payload"], n_out=meta["n_out"],
            Cm=plan.merged_lanes, segmented=l_sid is not None,
            keyed_fill=not skip_nulls, chunk_rows=plan.chunk_rows,
            windowed=ml > 0, ml=float(ml), depth=psr.dma_buffers(),
            interpret=interpret,
        )
    return chunked_outputs(out, plan, meta["C"], int(np.asarray(l_ts).shape[1]))


def chunked_outputs(out, plan, C, Ll):
    """Unscatter kernel outputs back to the packed [*, K, Ll] form."""
    from tempo_tpu.packing import chunk_gather

    K = plan.l_out.shape[0]
    outs = [chunk_gather(np.asarray(o), plan.l_out, np.nan, np.float32)
            for o in out]
    vals = (np.stack(outs[:C]) if C
            else np.zeros((0, K, Ll), np.float32))
    found = ~np.isnan(vals)
    idx = np.where(np.isnan(outs[C]), -1, outs[C]).astype(np.int32)
    return jnp.asarray(vals), jnp.asarray(found), jnp.asarray(idx)


def build_chunked_planes(l_ts, r_ts, r_valids, r_values,
                         l_sid=None, r_sid=None,
                         l_seq=None, r_seq=None,
                         skip_nulls: bool = True,
                         max_lookback: int = 0,
                         chunk_lanes=None):
    """Host side of the chunked engine: chunk plan + key/payload plane
    construction.  Split out so bench.py can time the device program
    on prebuilt planes.  Returns ``(keys, planes, plan, meta)``."""
    from tempo_tpu import packing

    l_ts = _require_concrete("l_ts", l_ts)
    r_ts = _require_concrete("r_ts", r_ts)
    r_valids = np.asarray(r_valids)
    r_values = np.asarray(r_values)
    C = int(r_values.shape[0])
    K, Ll = l_ts.shape
    Lr = r_ts.shape[-1]
    if Ll + Lr >= (1 << 24):
        # the payload position channels (ridx, merged psrc) ride f32 —
        # exact only below 2^24.  Enforced here, not just in the
        # availability gate, so a forced TEMPO_TPU_JOIN_ENGINE=chunked
        # cannot silently round positions past the bound
        raise ValueError(
            f"chunked asof merge infeasible: {Ll} + {Lr} lanes exceed "
            f"the 2^24 f32 position-exactness bound; use the host "
            f"bracketing engine for this shape")
    segmented = l_sid is not None
    keyed = not skip_nulls
    ml = int(max_lookback or 0)
    if l_sid is not None:
        l_sid = np.asarray(l_sid)
        r_sid = np.asarray(r_sid)

    ls = rs = None
    nsq = 0
    if l_seq is not None or r_seq is not None:
        l_seq_k = seq_kernel_form(jnp.asarray(l_seq)) \
            if l_seq is not None else None
        r_seq_k = seq_kernel_form(jnp.asarray(r_seq)) \
            if r_seq is not None else None
        if (l_seq is not None and l_seq_k is None) or \
                (r_seq is not None and r_seq_k is None):
            raise ValueError(
                "sequence dtype has no order-preserving i32 mapping "
                "(seq_kernel_form): use the XLA forms for this join")
        ls, rs = packing._seq_merge_sides_np(
            np.asarray(l_seq_k) if l_seq_k is not None else None,
            np.asarray(r_seq_k) if r_seq_k is not None else None,
            K, Ll, Lr)
        nsq = len(_seq_key_planes_np(ls))

    n_keys, n_payload, n_out = _chunk_plane_counts(
        C, nsq, segmented, keyed, ml)
    Cm = _plan_chunk_lanes(n_payload, n_keys,
                           chunk_lanes or join_chunk_lanes_override())
    if Cm is None:
        raise ValueError(
            f"chunked asof merge infeasible: no chunk width fits "
            f"{n_payload} payload + {n_keys} key planes in VMEM")
    plan = packing.asof_chunk_plan(l_ts, r_ts, Cm, l_sid, r_sid, ls, rs)
    nc, S, W = plan.n_chunks, plan.chunk_rows, plan.n_chunks * Cm
    imax = np.int32(_I32_MAX)

    keys = []
    if segmented:
        sid_pl = np.repeat(plan.chunk_pad_sid, Cm,
                           axis=1).astype(np.int32)
        _scatter_into(sid_pl, l_sid.astype(np.int32), plan.l_dest)
        _scatter_into(sid_pl, r_sid.astype(np.int32), plan.r_dest)
        keys.append(sid_pl)
    for (a, b) in zip(_split_ts_np(l_ts), _split_ts_np(r_ts)):
        p = np.full((K, W), imax, np.int32)
        _scatter_into(p, a, plan.l_dest)
        _scatter_into(p, b, plan.r_dest)
        keys.append(p)
    if nsq:
        for pa, pb in zip(_seq_key_planes_np(ls), _seq_key_planes_np(rs)):
            p = np.full((K, W), imax, np.int32)
            _scatter_into(p, pa, plan.l_dest)
            _scatter_into(p, pb, plan.r_dest)
            keys.append(p)
    # the side/pos plane is a pure function of the chunk layout: left
    # half ascending above _SIDE, right half the pre-reversal iota
    w = np.tile(np.arange(Cm, dtype=np.int32), nc)
    sec = np.where(w < Cm // 2, _SIDE + w, Cm - 1 - w).astype(np.int32)
    keys.append(np.ascontiguousarray(np.broadcast_to(sec, (K, W))))

    val_srcs = [
        np.where(r_valids[c], r_values[c].astype(np.float32),
                 np.float32(np.nan)).astype(np.float32)
        for c in range(C)
    ]
    rscat = lambda src: packing.chunk_scatter(
        src.astype(np.float32), plan.r_dest, W, np.nan, np.float32)
    planes = [rscat(src) for src in val_srcs]
    planes.append(rscat(np.ascontiguousarray(np.broadcast_to(
        np.arange(Lr, dtype=np.float32), (K, Lr)))))
    if ml:
        rpos = plan.r_pos.astype(np.float32)
        if keyed:
            planes.append(rscat(rpos))
        else:
            # each channel's psrc shares its value plane's NaN pattern
            # exactly, so the independent fills stay in lockstep pairs
            planes.extend(
                rscat(np.where(np.isnan(src), np.float32(np.nan), rpos))
                for src in val_srcs)
            planes.append(rscat(rpos))

    meta = {"C": C, "n_keys": n_keys, "n_payload": n_payload,
            "n_out": n_out}
    return keys, planes, plan, meta


def asof_carry_init(n_cols: int, n_series: int):
    """Explicit-array form of the chunked kernel's cross-chunk carry
    scratch, for callers that thread the AS-OF fill state through
    jitted programs instead of a VMEM grid (the online serving engine,
    ``tempo_tpu/serve/state.py``).

    The kernel carries, per series row: the last filled value of every
    payload plane (NaN = nothing yet), the live series id, and — for
    maxLookback — the source's global merged position.  Lifted out of
    scratch that is exactly, per series ``k``:

    * ``last_val [C, K] f32``  — last *valid* right value per column
      (NaN-encoded, the per-column skipNulls=True fill state);
    * ``last_src [C, K] i64``  — merged-stream position of that source
      (the psrc plane; init far-negative so any horizon is expired);
    * ``lock_val [C, K] f32`` / ``lock_valid [C, K] bool`` /
      ``lock_src [K] i64`` — the single last right row (values, raw;
      validity flags; merged position): the lockstep skipNulls=False
      fill state AND the unconditional last-right-row channel;
    * ``last_ridx [K] i64`` — that row's within-side index (-1 none);
    * ``n_merged [K] i64`` — merged positions consumed so far (both
      sides count, exactly like lanes of the merged stream).

    Fills select values, they never compute, so a carry threaded across
    any batch split reproduces the batch join bit-for-bit — the same
    argument that makes the chunked kernel bit-identical to the
    single-plan form at any chunk width."""
    C, K = int(n_cols), int(n_series)
    far = np.int64(-(1 << 62))
    return {
        "last_val": np.full((C, K), np.nan, np.float32),
        "last_src": np.full((C, K), far, np.int64),
        "lock_val": np.full((C, K), np.nan, np.float32),
        "lock_valid": np.zeros((C, K), bool),
        "lock_src": np.full((K,), far, np.int64),
        "last_ridx": np.full((K,), -1, np.int64),
        "n_merged": np.zeros((K,), np.int64),
    }


def asof_merge_indices_chunked(l_ts, r_ts, r_valids,
                               l_sid=None, r_sid=None,
                               l_seq=None, r_seq=None,
                               max_lookback: int = 0,
                               chunk_lanes=None,
                               interpret: bool = False):
    """Index-returning chunked sibling (position-encoded payloads, like
    :func:`asof_merge_indices_pallas`): ``(last_row_idx [K, Ll],
    per_col_idx [C, K, Ll])``, -1 for none; within-lane-row positions
    under bin-packing."""
    r_valids = np.asarray(r_valids)
    C, K, Lr = r_valids.shape
    pos = np.ascontiguousarray(np.broadcast_to(
        np.arange(Lr, dtype=np.float32), (K, Lr)))
    planes = np.ascontiguousarray(np.broadcast_to(pos, (C, K, Lr)))
    vals, found, last_idx = asof_merge_values_chunked(
        l_ts, r_ts, r_valids, planes, l_sid=l_sid, r_sid=r_sid,
        l_seq=l_seq, r_seq=r_seq, max_lookback=max_lookback,
        chunk_lanes=chunk_lanes, interpret=interpret,
    )
    per_col = np.where(np.asarray(found), np.asarray(vals),
                       -1).astype(np.int32)
    return last_idx, jnp.asarray(per_col)


def chunked_join_available(est_lanes: int, n_cols: int, r_seq=None,
                           segmented: bool = False,
                           skip_nulls: bool = True,
                           max_lookback: int = 0) -> bool:
    """Host-planner gate for the streaming engine: TPU backend (or the
    forced-engine knob, join.py), positions exact in f32, a mappable
    seq dtype, and a feasible chunk plan."""
    if not _pallas_enabled():
        return False
    if est_lanes >= (1 << 24):
        return False
    nsq = 0
    if r_seq is not None:
        sk = seq_kernel_form(jnp.asarray(r_seq))
        if sk is None:
            return False
        nsq = _n_seq_planes(None, sk)
    n_keys, n_payload, _ = _chunk_plane_counts(
        int(n_cols), nsq, segmented, not skip_nulls, int(max_lookback))
    return _plan_chunk_lanes(n_payload, n_keys,
                             join_chunk_lanes_override()) is not None
