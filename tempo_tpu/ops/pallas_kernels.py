"""Pallas TPU kernels for the scan-shaped hot ops.

The reference's rolling ops are Spark Window scans (tsdf.py:615-635 EMA;
interpol.py:197-222 ffill/bfill via ``last/first ignorenulls`` over
unbounded windows).  On TPU these are first-order recurrences along the
time axis; XLA's ``lax.associative_scan`` computes them in O(log L)
*separate fused loops*, each a full HBM read+write of the operand.  The
kernels here run the whole Hillis-Steele ladder inside one
``pallas_call`` with the operand resident in VMEM, so HBM is touched
exactly twice (one read, one write) regardless of L.

Mosaic cannot lower ``cumsum`` / dynamic gathers (probed on v5e), so the
ladder is built from the primitives it does support: ``pltpu.roll``
(static lane shift) + ``broadcasted_iota`` masks.

Kernels:

* ``ema_scan``  - y_t = (1-a) * y_{t-1} + a * x_t, invalid rows carry
  the previous EMA forward (exact infinite-horizon EMA; why the
  reference truncates and this stack never has to:
  resample.py:resample_ema, "Truncated-lag EMA — the canonical
  note").  Wired into the flagship fused pipeline (__graft_entry__).
* ``last_valid_index_scan`` / ``first_valid_index_scan`` - running
  index of the last/next valid element, the engine under
  ``window_utils.last_valid_index``/``first_valid_index`` (which back
  ffill/bfill/linear interpolation scaffolds and skipNulls AS-OF);
  those wrappers dispatch here on TPU.
* ``last_valid_scan`` - forward-fill of the last valid *value* in one
  pass, for f32 packed-array pipelines that need filled values rather
  than indices.

Kernels engage for [K, L] blocks with L a multiple of 128 on TPU
(float32 for the value kernels; the index kernels are dtype-agnostic -
they only read the validity mask) and fall back to the XLA
implementations elsewhere (CPU-mesh tests, float64 golden runs).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANE = 128
_BK = 32  # series rows per grid step; carries + roll temps + I/O double
          # buffers for a [32, 8192] f32 block stay under the 16M VMEM cap


def x64_off():
    """Context manager forcing 32-bit tracing around a pallas_call
    (index maps must trace as i32: under the library's global x64 mode
    they come out i64, which Mosaic's func.return rejects).  Newer jax
    exposes this as ``jax.enable_x64``; older builds (this image's
    0.4.37) only have the experimental context manager — same object,
    different home."""
    if hasattr(jax, "enable_x64"):
        return jax.enable_x64(False)
    from jax.experimental import enable_x64 as _enable_x64

    return _enable_x64(False)


def interpret_scope(interpret: bool):
    """Scope for CALLING an interpret-capable kernel wrapper: interpret
    mode inlines the pallas machinery into the caller's jaxpr and
    lowers it in the caller's config scope, so the whole call must run
    32-bit or the grid-loop constants come out i64 against the
    kernel's i32 jaxpr (verifier mismatch under the library's global
    x64).  Compiled mode needs no extra scope."""
    import contextlib

    return x64_off() if interpret else contextlib.nullcontext()


def tpu_compiler_params(**kwargs):
    """``pltpu.CompilerParams`` across jax versions (older builds spell
    it ``TPUCompilerParams``)."""
    cls = getattr(pltpu, "CompilerParams", None) \
        or pltpu.TPUCompilerParams
    return cls(**kwargs)


def _ladder_levels(L: int):
    spans = []
    s = 1
    while s < L:
        spans.append(s)
        s *= 2
    return spans


def _shift_with_identity(arr, span: int, identity):
    """arr shifted right by ``span`` along the lane axis; the first
    ``span`` lanes (which pltpu.roll wraps) become ``identity``."""
    # under jax_enable_x64 a python-int shift traces as i64, which
    # tpu.dynamic_rotate rejects
    rolled = pltpu.roll(arr, shift=jnp.int32(span), axis=1)
    lane = jax.lax.broadcasted_iota(jnp.int32, arr.shape, dimension=1)
    return jnp.where(lane >= span, rolled, identity)


def _ema_kernel(alpha_ref, x_ref, valid_ref, out_ref):
    a = alpha_ref[0]
    valid = valid_ref[:]
    f0 = jnp.float32(0.0)
    f1 = jnp.float32(1.0)
    # linear recurrence y_i = d_i * y_{i-1} + v_i
    d = jnp.where(valid, f1 - a, f1)
    v = jnp.where(valid, a * x_ref[:], f0)
    for span in _ladder_levels(d.shape[1]):
        d_prev = _shift_with_identity(d, span, f1)
        v_prev = _shift_with_identity(v, span, f0)
        v = v + d * v_prev
        d = d * d_prev
    out_ref[:] = v


def _last_valid_kernel(x_ref, valid_ref, out_ref, outv_ref):
    f0 = jnp.float32(0.0)
    has = valid_ref[:].astype(jnp.float32)
    val = jnp.where(valid_ref[:], x_ref[:], f0)
    for span in _ladder_levels(has.shape[1]):
        has_prev = _shift_with_identity(has, span, f0)
        val_prev = _shift_with_identity(val, span, f0)
        val = jnp.where(has > 0, val, val_prev)
        has = jnp.maximum(has, has_prev)
    out_ref[:] = val
    outv_ref[:] = has > 0


def _shift_left_with_identity(arr, span: int, identity):
    """arr shifted left by ``span`` along the lane axis (for reverse
    scans); the last ``span`` lanes become ``identity``."""
    L = arr.shape[1]
    rolled = pltpu.roll(arr, shift=jnp.int32(L - span), axis=1)
    lane = jax.lax.broadcasted_iota(jnp.int32, arr.shape, dimension=1)
    return jnp.where(lane < L - span, rolled, identity)


def _last_valid_index_kernel(valid_ref, out_ref):
    L = valid_ref.shape[1]
    lane = jax.lax.broadcasted_iota(jnp.int32, valid_ref.shape, dimension=1)
    cand = jnp.where(valid_ref[:], lane, -1)
    for span in _ladder_levels(L):
        cand = jnp.maximum(cand, _shift_with_identity(cand, span, -1))
    out_ref[:] = cand


def _first_valid_index_kernel(valid_ref, out_ref):
    L = valid_ref.shape[1]
    lane = jax.lax.broadcasted_iota(jnp.int32, valid_ref.shape, dimension=1)
    cand = jnp.where(valid_ref[:], lane, L)
    for span in _ladder_levels(L):
        cand = jnp.minimum(cand, _shift_left_with_identity(cand, span, L))
    out_ref[:] = cand


def _cumsum3_kernel(x_ref, valid_ref, s1_ref, s2_ref, c_ref):
    """Inclusive prefix sums of (masked x, masked x^2, valid count) in
    one VMEM pass — the three scans behind windowed range stats."""
    valid = valid_ref[:]
    f0 = jnp.float32(0.0)
    xz = jnp.where(valid, x_ref[:], f0)
    s1 = xz
    s2 = xz * xz
    c = valid.astype(jnp.float32)
    for span in _ladder_levels(s1.shape[1]):
        s1 = s1 + _shift_with_identity(s1, span, f0)
        s2 = s2 + _shift_with_identity(s2, span, f0)
        c = c + _shift_with_identity(c, span, f0)
    s1_ref[:] = s1
    s2_ref[:] = s2
    c_ref[:] = c


@functools.partial(jax.jit, static_argnames=("interpret",))
def _cumsum3_call(x, valid, interpret=False):
    K, L = x.shape
    # three carries + three outputs live at once: a larger array budget
    grid, bk, K_pad = _plan(K, L, arrays=16, bk_max=16) or ((1,), K, K)
    x, valid = _pad_rows(x, K_pad), _pad_rows(valid, K_pad)
    with x64_off():
        spec = pl.BlockSpec((bk, L), lambda i: (i, 0), memory_space=pltpu.VMEM)
        out = pl.pallas_call(
            _cumsum3_kernel,
            grid=grid,
            in_specs=[spec, spec],
            out_specs=[spec, spec, spec],
            out_shape=[jax.ShapeDtypeStruct((K_pad, L), jnp.float32)] * 3,
            interpret=interpret,
        )(x, valid)
    return tuple(o[:K] for o in out)


def cumsum3(x, valid, interpret: bool = False):
    """(cumsum(xz), cumsum(xz^2), cumsum(valid)) inclusive along lanes;
    Pallas on TPU/f32, XLA associative scans elsewhere."""
    x = jnp.asarray(x)
    valid = jnp.asarray(valid)
    if interpret or _supported(x, arrays=16, bk_max=16):
        with interpret_scope(interpret):
            return _cumsum3_call(x, valid, interpret=interpret)
    from tempo_tpu.ops import window_utils as wu

    xz = jnp.where(valid, x, 0.0)
    return (
        wu.cumsum(xz, axis=-1),
        wu.cumsum(xz * xz, axis=-1),
        wu.cumsum(valid.astype(x.dtype), axis=-1),
    )


def _supported(x: jax.Array, arrays: int = 12, bk_max: int = _BK) -> bool:
    return x.dtype == jnp.float32 and _index_supported(x, arrays, bk_max)


_VMEM_BUDGET = 14 * 2**20  # headroom under the 16M scoped-vmem limit


def _plan(K: int, L: int, arrays: int = 12, bk_max: int = _BK,
          budget: int = _VMEM_BUDGET):
    """(grid, bk, K_padded) row-blocking plan fitting the scoped-VMEM
    cap, or None when no legal block fits.  ``arrays`` is a conservative
    count of simultaneously-live [bk, L] f32 buffers (carries + roll
    temps + pipelined I/O).  A fixed block OOMs once L grows — [32,
    16384] f32 blew the 16M cap at 23.5M, measured.

    Mosaic requires the sublane block be a multiple of 8 or the whole
    array, so K that no power-of-two >= 8 divides is *padded up* to the
    chosen block (callers pad inputs / slice outputs); when even an
    8-row block exceeds the budget (huge L) there is no feasible plan
    and callers must stay on the XLA path.
    """
    if K * L * 4 * arrays <= budget:
        return (1,), K, K          # whole array in one block
    cap = budget // (L * 4 * arrays)
    if cap < 8:
        return None                # not even [8, L] fits: infeasible
    bk = 1 << min(bk_max, cap).bit_length() - 1
    K_pad = -(-K // bk) * bk
    return (K_pad // bk,), bk, K_pad


def _feasible(shape, arrays: int, bk_max: int) -> bool:
    return _plan(int(shape[0]), int(shape[1]), arrays, bk_max) is not None


def _pad_rows(arr, K_pad: int):
    """Pad the row axis (axis -2: [..., K, L] -> [..., K_pad, L])."""
    K = arr.shape[-2]
    if K_pad == K:
        return arr
    pad = [(0, 0)] * arr.ndim
    pad[-2] = (0, K_pad - K)
    return jnp.pad(arr, pad)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _ema_call(x, valid, alpha, interpret=False):
    K, L = x.shape
    grid, bk, K_pad = _plan(K, L) or ((1,), K, K)
    x, valid = _pad_rows(x, K_pad), _pad_rows(valid, K_pad)
    # index maps must trace as i32: under the library's global x64 mode
    # they come out i64, which Mosaic's func.return rejects
    with x64_off():
        spec = pl.BlockSpec((bk, L), lambda i: (i, 0), memory_space=pltpu.VMEM)
        out = pl.pallas_call(
            _ema_kernel,
            grid=grid,
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                spec,
                spec,
            ],
            out_specs=spec,
            out_shape=jax.ShapeDtypeStruct((K_pad, L), jnp.float32),
            interpret=interpret,
        )(jnp.asarray([alpha], jnp.float32), x, valid)
    return out[:K]


@functools.partial(jax.jit, static_argnames=("interpret",))
def _last_valid_call(x, valid, interpret=False):
    K, L = x.shape
    grid, bk, K_pad = _plan(K, L) or ((1,), K, K)
    x, valid = _pad_rows(x, K_pad), _pad_rows(valid, K_pad)
    with x64_off():
        spec = pl.BlockSpec((bk, L), lambda i: (i, 0), memory_space=pltpu.VMEM)
        out = pl.pallas_call(
            _last_valid_kernel,
            grid=grid,
            in_specs=[spec, spec],
            out_specs=[spec, spec],
            out_shape=[
                jax.ShapeDtypeStruct((K_pad, L), jnp.float32),
                jax.ShapeDtypeStruct((K_pad, L), jnp.bool_),
            ],
            interpret=interpret,
        )(x, valid)
    return out[0][:K], out[1][:K]


@functools.partial(jax.jit, static_argnames=("kernel", "interpret"))
def _index_scan_call(valid, kernel, interpret=False):
    K, L = valid.shape
    grid, bk, K_pad = _plan(K, L, arrays=8) or ((1,), K, K)
    valid = _pad_rows(valid, K_pad)
    with x64_off():
        spec = pl.BlockSpec((bk, L), lambda i: (i, 0), memory_space=pltpu.VMEM)
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=[spec],
            out_specs=spec,
            out_shape=jax.ShapeDtypeStruct((K_pad, L), jnp.int32),
            interpret=interpret,
        )(valid)
    return out[:K]


def _index_supported(valid: jax.Array, arrays: int = 8,
                     bk_max: int = _BK) -> bool:
    return (
        valid.ndim == 2
        and valid.shape[1] % LANE == 0
        and jax.default_backend() == "tpu"
        and _feasible(valid.shape, arrays, bk_max)
    )


def last_valid_index_scan(valid, interpret: bool = False):
    """Running index of the last True at-or-before each lane; -1 before
    the first.  Pallas on TPU, XLA cummax elsewhere."""
    valid = jnp.asarray(valid)
    if interpret or _index_supported(valid):
        with interpret_scope(interpret):
            return _index_scan_call(valid, _last_valid_index_kernel,
                                    interpret=interpret)
    from tempo_tpu.ops import window_utils as wu

    return wu.last_valid_index_xla(valid)


def first_valid_index_scan(valid, interpret: bool = False):
    """Index of the first True at-or-after each lane; L where none."""
    valid = jnp.asarray(valid)
    if interpret or _index_supported(valid):
        with interpret_scope(interpret):
            return _index_scan_call(valid, _first_valid_index_kernel,
                                    interpret=interpret)
    from tempo_tpu.ops import window_utils as wu

    return wu.first_valid_index_xla(valid)


def ema_scan(x, valid, alpha: float, interpret: bool = False):
    """Exact EMA over [K, L]; Pallas on TPU/f32, XLA scan otherwise."""
    x = jnp.asarray(x)
    valid = jnp.asarray(valid)
    if interpret or _supported(x):
        with interpret_scope(interpret):
            return _ema_call(x, valid, float(alpha), interpret=interpret)
    from tempo_tpu.ops import rolling as rk

    return rk.ema_exact(x, valid, alpha)


def last_valid_scan(x, valid, interpret: bool = False):
    """(ffilled values, any-valid-so-far mask) over [K, L]."""
    x = jnp.asarray(x)
    valid = jnp.asarray(valid)
    if interpret or _supported(x):
        with interpret_scope(interpret):
            return _last_valid_call(x, valid, interpret=interpret)
    # XLA fallback: the same scan via associative_scan
    def combine(c1, c2):
        h1, v1 = c1
        h2, v2 = c2
        return jnp.logical_or(h2, h1), jnp.where(h2, v2, v1)

    has, val = jax.lax.associative_scan(
        combine, (valid, jnp.where(valid, x, 0)), axis=1
    )
    return val, has
