"""Interpolation kernels over dense per-series time grids.

Reference semantics (python/tempo/interpol.py): after resampling, the
reference explodes ``sequence(ts, next_ts - freq, freq)`` to generate
missing timestamps (interpol.py:330-347), builds prev/next scaffold
columns with last/first-ignorenulls windows and surrogate per-column
timestamps (interpol.py:182-258), then applies one of five fills
(zero / null / ffill / bfill / linear, interpol.py:96-180).

TPU design: the exploded row set is exactly the *dense grid* from the
first to the last bucket of each series.  We scatter the resampled rows
onto that grid ([K, G] packed form) and express every scaffold as an
index scan (last/first-valid) - no row explosion, no window shuffles,
one fused XLA program for all columns.  Semantics preserved exactly,
including the subtle cases encoded in the reference goldens:

* an existing-but-null row is flagged interpolated but NOT
  ts-interpolated (interpol.py:114-119);
* exploded rows inherit their *source* row's scaffolds, so ``next``
  means "next real row after the source", not "next grid slot";
* bfill falls back to ``next_null`` (first non-null at-or-after the
  source) only when the next real value is null AND the source value is
  null (interpol.py:153-170);
* linear uses unix-seconds arithmetic and two distinct formulas for the
  null-source and non-null-source branches (interpol.py:66-94), with
  the tail edge ``next_timestamp = ts + freq`` (interpol.py:315-321).
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from tempo_tpu.ops import window_utils as wu


def _gather(x: jnp.ndarray, idx: jnp.ndarray, ok: jnp.ndarray, fill):
    g = jnp.take_along_axis(x, jnp.clip(idx, 0, x.shape[-1] - 1), axis=-1)
    return jnp.where(ok, g, fill)


@functools.partial(jax.jit, static_argnames=("method",))
def interpolate_columns(
    real: jnp.ndarray,      # [K, G] bool: slot holds a resampled row
    glen: jnp.ndarray,      # [K] int32 grid length per series
    ts_sec: jnp.ndarray,    # [K, G] float64 grid timestamps (unix seconds)
    freq_sec: jnp.ndarray,  # scalar seconds between slots
    values: jnp.ndarray,    # [C, K, G] float64 (NaN where null/absent)
    valid: jnp.ndarray,     # [C, K, G] bool (non-null real value)
    method: str,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Returns (out_values [C,K,G], out_valid [C,K,G],
    is_ts_interpolated [K,G], is_interpolated [C,K,G])."""
    K, G = real.shape
    slot = jnp.broadcast_to(jnp.arange(G, dtype=jnp.int32), (K, G))
    in_grid = slot < glen[:, None]

    src = wu.last_valid_index(real)                      # [K, G] source row slot
    # src always >= 0 inside the grid (grid starts at a real row)
    is_ts_interp = in_grid & (slot != jnp.maximum(src, 0))

    # next real slot strictly after the source (== strictly after g,
    # since there is no real slot in (src, g])
    fr = wu.first_valid_index(real)                      # [K, G] first real >= g
    nxt = jnp.concatenate(
        [fr[:, 1:], jnp.full((K, 1), G, jnp.int32)], axis=-1
    )                                                    # first real >= g+1
    nxt_ok = nxt < glen[:, None]

    src_ts = _gather(ts_sec, src, src >= 0, jnp.nan)
    nxt_ts = jnp.where(nxt_ok, _gather(ts_sec, nxt, nxt_ok, 0.0),
                       src_ts + freq_sec)                # tail edge rule

    def per_col(v, ok):
        v_src = _gather(v, src, src >= 0, jnp.nan)
        ok_src = _gather(ok, src, src >= 0, False)
        flag = in_grid & (is_ts_interp | ~ok_src)

        prev_i = wu.last_valid_index(ok)                 # last non-null <= g
        prev_ok = prev_i >= 0
        prev_v = _gather(v, prev_i, prev_ok, jnp.nan)
        prev_t = _gather(ts_sec, prev_i, prev_ok, jnp.nan)

        nn_i = wu.first_valid_index(ok)                  # first non-null >= g
        nn_ok = nn_i < glen[:, None]
        nn_v = _gather(v, nn_i, nn_ok, jnp.nan)
        nn_t = _gather(ts_sec, nn_i, nn_ok, jnp.nan)

        next_v = _gather(v, nxt, nxt_ok, jnp.nan)        # may be null
        next_value_ok = nxt_ok & _gather(ok, nxt, nxt_ok, False)

        if method == "zero":
            out = jnp.where(flag, 0.0, v_src)
            out_ok = in_grid
        elif method == "null":
            out = jnp.where(flag, jnp.nan, v_src)
            out_ok = in_grid & ~flag
        elif method == "ffill":
            out = jnp.where(flag, prev_v, v_src)
            out_ok = in_grid & jnp.where(flag, prev_ok, True)
        elif method == "bfill":
            use_nn = ~next_value_ok & ~ok_src
            filled = jnp.where(use_nn, nn_v, next_v)
            filled_ok = jnp.where(use_nn, nn_ok, next_value_ok)
            out = jnp.where(flag, filled, v_src)
            out_ok = in_grid & jnp.where(flag, filled_ok, True)
        elif method == "linear":
            # null-source branch: between prev non-null and next non-null
            lin_null = prev_v + (nn_v - prev_v) * (ts_sec - prev_t) / (nn_t - prev_t)
            lin_null_ok = prev_ok & nn_ok
            # non-null-source branch: between source value and next real value
            lin_src = v_src + (next_v - v_src) * (ts_sec - src_ts) / (nxt_ts - src_ts)
            lin_src_ok = next_value_ok
            filled = jnp.where(ok_src, lin_src, lin_null)
            filled_ok = jnp.where(ok_src, lin_src_ok, lin_null_ok)
            out = jnp.where(flag, filled, v_src)
            out_ok = in_grid & jnp.where(flag, filled_ok, True)
        else:
            raise ValueError(f"unknown method {method}")
        return jnp.where(out_ok, out, jnp.nan), out_ok, flag

    outs, oks, flags = jax.vmap(per_col)(values, valid)
    return outs, oks, is_ts_interp, flags
