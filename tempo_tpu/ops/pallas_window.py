"""Streaming sliding-window engine: VMEM window sweeps at any width.

``ops/sortmerge.py:range_stats_shifted`` computes Spark's
rangeBetween(-window, 0) aggregates as W statically-unrolled shifted
passes; ``ops/pallas_stats.py`` runs that structure VMEM-resident but
inherits the unroll, so Mosaic's live-temporary growth caps it at
W<=64 rows (measured: W~150 overflowed VMEM by 7M, W~266 by 20M).
Wider frames used to fall back to the prefix-scan + RMQ form
(``ops/rolling.py:windowed_stats``), which is gather-bound on this
hardware (~96 ms per ``take_along_axis`` level at [1024, 8192]) — the
one regime where a TPU chip lost to a single CPU core (BENCH_r05
``2b_range_stats_dense_50hz``: 8.0M rows/s vs 9.6M numpy).

This module replaces that regime with a *streaming* kernel: the block
tiles through VMEM once (one HBM read of (secs, x, valid), one write
of the eight output planes — each element crosses HBM O(1) times) and
the window sweep runs as a ``fori_loop`` of dynamic-rotate passes with
O(1) live planes, so

* the window width is a **runtime scalar** (SMEM), not a compile-time
  unroll: one compiled program serves every window size at a given
  [K, L] — no recompiles across datasets, no Mosaic live-range blowup;
* per-pass work is cut vs the legacy kernel: validity is folded into
  the key planes once (single compare per pass instead of three
  compare/mask ops), and min/max accumulate on the mean-centred values
  (recovered exactly by adding the per-series center back), so a pass
  rolls 3 planes instead of 4;
* row- and range-based windows share one kernel: Spark's
  rangeBetween(-wb, +wa) is the generic form, and rowsBetween is the
  same sweep over an iota key (``rows_stats_stream``).

The in-window test per pass IS the monotone two-pointer sweep in
vectorised form: because keys ascend along lanes, ``secs[i-j] >=
secs[i] - w`` is exactly "j is before the back pointer", and the
folded key planes carry the inter-pass boundary state.

An ``unroll=True`` twin (static trip count, python-int rotate
amounts) exists for small windows where the legacy kernel used to
engage; the three-way auto-pick (``ops/rolling.pick_range_engine``)
chooses between shifted/VMEM-unrolled and streaming forms from the
measured crossovers (bench.py ``rolling_crossover``).

Both forms take an optional ``scale`` scalar that multiplies ``x``
inside the kernel — downstream consumers that previously re-streamed
the column through a separate elementwise pass (bench bodies, fused
pipelines) fold it here for free.

HBM-roofline mechanisms (PR 6 — BENCH_r05 put these kernels at
0.18-0.28 of the measured stream rate):

* **multi-column payload packing** (``range_stats_stream_packed`` /
  ``range_stats_unrolled_packed``): one kernel pass reduces a stacked
  [C, K, L] payload, reading the key planes (secs + per-column valids
  ride the payload) ONCE instead of streaming a tiled timestamp copy
  per metric column — the frame/mesh ``withRangeStats`` callers used
  to materialise C broadcast copies of ``secs``.  The pack width is
  sized by the same VMEM-budget folding the static analyzer applies
  (:func:`pack_cols_budget`, capped by ``TEMPO_TPU_PACK_COLS``);
  per-column math is the identical op sequence, so packed outputs are
  bitwise-equal to C single-column calls (tests pin this).
* **explicit DMA pipelining** (``TEMPO_TPU_DMA_BUFFERS`` > 2): the
  slab loop moves into the kernel and inputs stream through the
  N-deep ``pltpu.make_async_copy`` ring of ``ops/pallas_stream.py``,
  overlapping the copy of slab i+N-1 and the writeback of slab i-1
  with the compute of slab i.  Depth 2 (default) keeps Mosaic's
  implicit BlockSpec pipeline.
* **megacore partitioning**: the row-block grid axis is carry-free, so
  it is declared ``"parallel"`` (``pallas_stream.grid_semantics``)
  and Mosaic may split it across TensorCores on megacore parts.

Semantics are identical to ``range_stats_shifted`` including the
``clipped`` truncation audit; parity is pinned in
tests/test_pallas_window.py against both the XLA shifted form and a
brute-force numpy oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from tempo_tpu.ops import pallas_kernels as pk
from tempo_tpu.ops import pallas_stream as psr

_I32_BIG = 2**31 - 1     # python ints: capture as consts inside kernels
_I32_MIN = -(2**31)

# Live-plane budgets for the block plan, in [bk, L] f32 plane units.
# The streaming form keeps O(1) temporaries per column whatever the
# window (folded keys + centred values + 5 accumulators + rotate
# temps); the unrolled form inherits the per-shift live-temporary
# growth measured on the legacy kernel (ops/pallas_stats._plan_arrays).
# Columns are processed sequentially inside the kernel, so only ONE
# column's temporaries are live at a time — the per-column cost is the
# pipelined I/O (x + valid in, 8 planes out), not the sweep state.
_COL_TEMPS = 20          # one column's live sweep temporaries
_COL_IO = 20             # (x + valid) in + 8 out, double-buffered


def _plan_arrays(n_cols: int, max_behind: int, max_ahead: int,
                 unroll: bool, depth: int) -> int:
    """Conservative count of simultaneously-live [bk, L] f32 planes for
    the block plan (``pallas_kernels._plan``).  The explicit DMA ring
    trades the BlockSpec pipeline's 2x I/O for ``depth`` input slots
    plus a double-buffered output stage — same formula, depth-scaled
    input term."""
    base = _COL_TEMPS + (max_behind + max_ahead if unroll else 4)
    if depth <= 2:
        return base + _COL_IO * n_cols
    in_planes = 1 + 2 * n_cols            # secs + (x, valid) per column
    return base + depth * in_planes + 16 * n_cols


_STREAM_ARRAYS = _plan_arrays(1, 0, 0, unroll=False, depth=2)   # == 44


def _unroll_arrays(max_behind: int, max_ahead: int) -> int:
    return _plan_arrays(1, max_behind, max_ahead, unroll=True, depth=2)


# Largest window the *unrolled* twin may take: beyond this the
# streaming form is the only VMEM path (the legacy kernel's probed
# ceiling — Mosaic live temporaries grow superlinearly in the unroll).
UNROLL_MAX_W = 64


def _stream_max_rows() -> int:
    """Row-extent ceiling for the streaming form.  The sweep is O(W)
    dynamic-rotate passes, so at SOME width the O(L log L) sort-based
    windowed form must win again; extrapolating the measured pass rate
    (~15us per [1024, 8192] rotate) against the measured RMQ-path
    floor (~1.05 s/iteration at that shape, BENCH_r05) puts the
    crossover above 20k rows.  Re-measure with bench.py
    --only-stream-stats and override here.  Env unset falls back to
    the tuned-profile prior (tempo_tpu/tune — the autotuner's
    audit-gated winner: a candidate ceiling that flipped the engine
    pick changed result bits and was rejected at sweep time), then to
    the built-in 16384."""
    from tempo_tpu import config, tune

    n = config.get_int("TEMPO_TPU_STREAM_MAX_ROWS")
    if n is None:
        n = tune.knob_value("TEMPO_TPU_STREAM_MAX_ROWS")
    return 16384 if n is None else int(n)


def pack_cols_budget(K: int, L: int, n_cols: int,
                     max_behind: int = 0, max_ahead: int = 0,
                     unroll: bool = False) -> int:
    """Largest payload pack width (<= ``n_cols``, capped by
    ``TEMPO_TPU_PACK_COLS``) whose [C, bk, L] block plan still fits
    the VMEM budget (``pallas_stream.pack_budget`` over this module's
    plane counts) — consulted by the frame/mesh ``withRangeStats``
    packers before stacking metric columns."""
    depth = psr.dma_buffers()
    return psr.pack_budget(
        K, L, n_cols,
        lambda c: _plan_arrays(c, max_behind, max_ahead, unroll, depth))


def _window_math(max_behind: int, max_ahead: int, unroll: bool,
                 interpret: bool = False):
    """The window sweep as a function of *arrays*: one metric column's
    full pass, shared verbatim by every kernel form (single-column
    BlockSpec, multi-column packed, explicit DMA ring) — bitwise
    identity across the forms holds by construction because they trace
    this exact op sequence.  ``unroll=True`` bakes the trip counts
    (python-int rotate amounts, fully unrolled passes); otherwise the
    bounds ride in as runtime scalars and the sweep is a ``fori_loop``
    whose rotate amount is the loop index."""

    def _roll(p, shift):
        # interpret mode avoids roll_p: its fallback lowering re-derives
        # shape constants OUTSIDE the kernel's 32-bit scope and trips
        # the global-x64 i32/i64 verifier; jnp.roll traced here is
        # equivalent and stays in-scope
        if interpret:
            return jnp.roll(p, shift, axis=1)
        return pltpu.roll(p, shift=shift, axis=1)

    def math(w, wa, mb_r, ma_r, scale, secs, x, valid):
        x = x * scale
        shape = secs.shape
        L = shape[1]
        lane = jax.lax.broadcasted_iota(jnp.int32, shape, dimension=1)

        big = jnp.int32(_I32_BIG)
        lo = secs - w
        # forward bound, saturated one below the pad sentinel: clamped
        # pads carry key INT32_MAX, so an unsaturated `secs + wa` both
        # wraps at pad centers and lets the BIG-folded invalids below
        # tie `sj <= hi` — capping at BIG-1 closes both without a
        # per-pass validity compare (real keys sit >= window below the
        # pads by the rebase headroom contract, packing.rebase_seconds)
        hi = jnp.minimum(secs + jnp.minimum(wa, big - secs), big - 1)
        # validity folded into the key planes once: an invalid row's
        # key can never pass the single in-window compare of its
        # direction (MIN fails `sj >= lo`, BIG fails `sj <= hi`)
        s_lo = jnp.where(valid, secs, jnp.int32(_I32_MIN))
        s_hi = jnp.where(valid, secs, big)

        f0 = jnp.float32(0.0)
        f1 = jnp.float32(1.0)
        validf = valid.astype(jnp.float32)
        xz = jnp.where(valid, x, f0)
        nv = jnp.sum(validf, axis=1, keepdims=True)
        center = jnp.sum(xz, axis=1, keepdims=True) / jnp.maximum(nv, f1)
        xc = jnp.where(valid, x - center, f0)
        xc2 = xc * xc
        pinf = jnp.float32(jnp.inf)

        def accumulate(carry, inw, xj, xj2):
            cnt, s1, s2, mn, mx = carry
            return (cnt + inw.astype(jnp.float32),
                    s1 + jnp.where(inw, xj, f0),
                    s2 + jnp.where(inw, xj2, f0),
                    # min/max ride the centred values too (argmin is
                    # shift-invariant); the epilogue adds center back
                    jnp.minimum(mn, jnp.where(inw, xj, pinf)),
                    jnp.maximum(mx, jnp.where(inw, xj, -pinf)))

        def behind_step(j, carry):
            # keys ascend, so a row j back is in-window iff it is at or
            # after the back pointer: ONE compare (`<= hi` holds by
            # sortedness; wrapped lanes are masked by the iota)
            sj = _roll(s_lo, j)
            inw = (sj >= lo) & (lane >= j)
            return accumulate(carry,
                              inw,
                              _roll(xc, j),
                              _roll(xc2, j))

        def ahead_step(j, carry):
            # rows ahead are in-window iff within the forward bound
            # (`>= lo` holds by sortedness); rotate by L-j looks ahead
            # (negative rotate amounts SIGABRT Mosaic)
            sj = _roll(s_hi, L - j)
            inw = (sj <= hi) & (lane < L - j)
            return accumulate(carry,
                              inw,
                              _roll(xc, L - j),
                              _roll(xc2, L - j))

        # j = 0: the row itself (always inside its own frame)
        carry = (validf, xc, xc2,
                 jnp.where(valid, xc, pinf), jnp.where(valid, xc, -pinf))
        if unroll:
            for j in range(1, max_behind + 1):
                carry = behind_step(j, carry)
            for j in range(1, max_ahead + 1):
                carry = ahead_step(j, carry)
            mb = jnp.int32(max_behind)
            ma = jnp.int32(max_ahead)
        else:
            mb = mb_r
            ma = ma_r
            # a bound >= L has no row beyond it; clamping also keeps
            # the rotate amounts inside [0, L)
            carry = jax.lax.fori_loop(
                jnp.int32(1), jnp.minimum(mb, L - 1) + 1,
                behind_step, carry)
            carry = jax.lax.fori_loop(
                jnp.int32(1), jnp.minimum(ma, L - 1) + 1,
                ahead_step, carry)
        cnt, s1, s2, mn, mx = carry

        nan = jnp.float32(jnp.nan)
        mean = jnp.where(cnt > 0, s1 / jnp.maximum(cnt, f1) + center, nan)
        total = s1 + cnt * center
        var = jnp.where(
            cnt > 1,
            (s2 - s1 * s1 / jnp.maximum(cnt, f1))
            / jnp.maximum(cnt - f1, f1),
            nan,
        )
        std = jnp.where(cnt > 1, jnp.sqrt(jnp.maximum(var, f0)), nan)

        # truncation audit (same contract as range_stats_shifted): a
        # row is clipped when the first row beyond either bound still
        # falls inside its frame's key range and either end is valid
        clipped = jnp.zeros(shape, jnp.bool_)
        for behind in (True, False):
            jb = jnp.minimum((mb if behind else ma) + 1, L)
            # jb == L rotates by 0 / L-jb == 0, but the lane mask is
            # then all-False (no row lies beyond the axis), so the
            # wrapped values never contribute
            shift = (jb % L) if behind else (L - jb)
            sj = _roll(secs, shift)
            vj = _roll(validf, shift)
            ok = (lane >= jb) if behind else (lane < L - jb)
            sj = jnp.where(ok, sj, jnp.int32(_I32_BIG))
            vj = jnp.where(ok, vj, f0)
            clipped = clipped | (
                (sj >= lo) & (sj <= hi) & (valid | (vj > f0))
            )

        return (mean, cnt,
                jnp.where(cnt > 0, mn + center, nan),
                jnp.where(cnt > 0, mx + center, nan),
                jnp.where(cnt > 0, total, nan),
                std,
                jnp.where(valid, (x - mean) / std, nan),
                clipped.astype(jnp.float32))

    return math


def _make_kernel(max_behind: int, max_ahead: int, unroll: bool,
                 interpret: bool = False, n_cols: int = 1):
    """BlockSpec-kernel factory over :func:`_window_math`.  With
    ``n_cols > 1`` the payload refs are [C, bk, L] stacks and the key
    planes are read once per block — columns run sequentially through
    the identical per-column op sequence."""
    math = _window_math(max_behind, max_ahead, unroll, interpret)

    def kernel(p_ref, scale_ref, secs_ref, x_ref, valid_ref,
               *out_refs):
        secs = secs_ref[:]
        if n_cols == 1:
            outs = math(p_ref[0], p_ref[1], p_ref[2], p_ref[3],
                        scale_ref[0], secs, x_ref[:], valid_ref[:])
            for r, o in zip(out_refs, outs):
                r[:] = o
            return
        for c in range(n_cols):
            outs = math(p_ref[0], p_ref[1], p_ref[2], p_ref[3],
                        scale_ref[c], secs, x_ref[c], valid_ref[c])
            for r, o in zip(out_refs, outs):
                r[c] = o

    return kernel


def _ring_math(max_behind: int, max_ahead: int, unroll: bool,
               interpret: bool, n_cols: int):
    """Per-slab math adapter for the explicit DMA ring
    (``pallas_stream.ring_call``): same :func:`_window_math` sequence,
    outputs restacked to the packed [C, bk, L] template."""
    math = _window_math(max_behind, max_ahead, unroll, interpret)

    def ring_math(scalar_refs, slabs):
        p_ref, scale_ref = scalar_refs
        secs, x, valid = slabs
        if n_cols == 1:
            return math(p_ref[0], p_ref[1], p_ref[2], p_ref[3],
                        scale_ref[0], secs, x, valid)
        per = [math(p_ref[0], p_ref[1], p_ref[2], p_ref[3],
                    scale_ref[c], secs, x[c], valid[c])
               for c in range(n_cols)]
        return tuple(jnp.stack([per[c][t] for c in range(n_cols)])
                     for t in range(8))

    return ring_math


def _call(secs, x, valid, params, scale, max_behind, max_ahead,
          unroll, depth, interpret):
    """Shared dispatch for every kernel form.  ``x``/``valid`` are
    [K, L] (single column) or [C, K, L] (packed); ``secs`` is always
    [K, L].  ``depth > 2`` streams the slabs through the explicit DMA
    ring where its plan is feasible, else the standard double-buffered
    BlockSpec pipeline with the row grid declared megacore-parallel."""
    if x.ndim == 3 and x.shape[0] == 1:
        # width-1 pack (a single summarized column, or the leftover of
        # a C % pack_cols_budget split): run the rank-2 single-column
        # form — the identical op sequence — and restack; the rank-2
        # spec paths below would otherwise trace rank-2 BlockSpecs over
        # the rank-3 operands
        outs = _call(secs, x[0], valid[0], params, scale, max_behind,
                     max_ahead, unroll, depth, interpret)
        return tuple(o[None] for o in outs)
    n_cols = 1 if x.ndim == 2 else x.shape[0]
    K, L = x.shape[-2], x.shape[-1]
    plan = psr.plan_with_ring(
        K, L, lambda d: _plan_arrays(n_cols, max_behind, max_ahead,
                                     unroll, d), depth)
    if plan is None:
        raise ValueError(
            f"streaming window kernel infeasible at L={L}, "
            f"n_cols={n_cols}: even an [8, {L}] block exceeds the VMEM "
            f"budget; use the XLA forms (or narrow the pack — "
            f"pack_cols_budget)"
        )
    grid, bk, K_pad, use_ring = plan
    secs = pk._pad_rows(secs, K_pad)
    x, valid = pk._pad_rows(x, K_pad), pk._pad_rows(valid, K_pad)

    if use_ring:
        out = psr.ring_call(
            _ring_math(max_behind, max_ahead, unroll, interpret,
                       n_cols),
            [params, scale], [secs, x, valid], n_out=8, out_like=1,
            bk=bk, depth=depth, interpret=interpret)
        return tuple(o[..., :K, :] for o in out)

    with pk.x64_off():
        spec2 = pl.BlockSpec((bk, L), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
        if n_cols == 1:
            spec3 = spec2
            out_shape = (K_pad, L)
        else:
            spec3 = pl.BlockSpec((n_cols, bk, L), lambda i: (0, i, 0),
                                 memory_space=pltpu.VMEM)
            out_shape = (n_cols, K_pad, L)
        out = pl.pallas_call(
            _make_kernel(max_behind, max_ahead, unroll, interpret,
                         n_cols),
            grid=grid,
            in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM)] * 2
            + [spec2, spec3, spec3],
            out_specs=[spec3] * 8,
            out_shape=[jax.ShapeDtypeStruct(out_shape, jnp.float32)] * 8,
            compiler_params=pk.tpu_compiler_params(
                vmem_limit_bytes=100 * 1024 * 1024,
                dimension_semantics=psr.grid_semantics(len(grid)),
            ),
            interpret=interpret,
        )(params, scale, secs, x, valid)
    return tuple(o[..., :K, :] for o in out)


@functools.partial(jax.jit, static_argnames=("depth", "interpret"))
def _stream_call(secs, x, valid, params, scale, depth=2,
                 interpret=False):
    """ONE compiled program per [K, L] shape (and pack width): window
    size and row bounds are runtime scalars."""
    return _call(secs, x, valid, params, scale, 0, 0, unroll=False,
                 depth=depth, interpret=interpret)


@functools.partial(
    jax.jit,
    static_argnames=("max_behind", "max_ahead", "depth", "interpret"),
)
def _unrolled_call(secs, x, valid, params, scale, max_behind, max_ahead,
                   depth=2, interpret=False):
    return _call(secs, x, valid, params, scale, max_behind, max_ahead,
                 unroll=True, depth=depth, interpret=interpret)


def _as_dict(outs):
    mean, cnt, mn, mx, total, std, z, clip = outs
    return {
        "mean": mean, "count": cnt, "min": mn, "max": mx, "sum": total,
        "stddev": std, "zscore": z,
        "clipped": jnp.sum(clip, axis=-1, keepdims=True),
    }


def _params(window, window_ahead, max_behind, max_ahead):
    # clamp the key windows so `secs - w` / `secs + wa` cannot wrap
    # int32 for rebased (non-negative) keys
    cap = jnp.int32(_I32_BIG // 2)
    w = jnp.minimum(jnp.asarray(window).astype(jnp.int32), cap)
    wa = jnp.minimum(jnp.asarray(window_ahead).astype(jnp.int32), cap)
    return jnp.stack([
        w, wa,
        jnp.asarray(max_behind).astype(jnp.int32),
        jnp.asarray(max_ahead).astype(jnp.int32),
    ])


def _scale(scale, n_cols: int = 1):
    if scale is None:
        return jnp.ones((n_cols,), jnp.float32)
    s = jnp.asarray(scale, jnp.float32).reshape(-1)
    if s.shape[0] == n_cols:
        return s
    return jnp.broadcast_to(s, (n_cols,))


def stream_supported(x, L_mult: int = 128) -> bool:
    """Gate for the streaming (runtime-width) form: f32 lane-aligned
    TPU blocks; feasibility is window-independent."""
    return (
        x.dtype == jnp.float32
        and x.ndim == 2
        and x.shape[1] % L_mult == 0
        and jax.default_backend() == "tpu"
        and pk._plan(int(x.shape[0]), int(x.shape[1]),
                     arrays=_STREAM_ARRAYS, bk_max=32,
                     budget=90 * 2**20) is not None
    )


def stream_block_feasible(K: int, L: int) -> bool:
    """Shape-only variant of :func:`stream_supported` for pickers that
    run before the arrays exist (frame/mesh auto-pick)."""
    return (
        int(L) % 128 == 0
        and jax.default_backend() == "tpu"
        and pk._plan(int(K), int(L), arrays=_STREAM_ARRAYS, bk_max=32,
                     budget=90 * 2**20) is not None
    )


def unrolled_supported(x, max_behind: int, max_ahead: int) -> bool:
    return (
        x.dtype == jnp.float32
        and x.ndim == 2
        and x.shape[1] % 128 == 0
        and int(max_behind) + int(max_ahead) <= UNROLL_MAX_W
        and jax.default_backend() == "tpu"
        and pk._plan(int(x.shape[0]), int(x.shape[1]),
                     arrays=_unroll_arrays(int(max_behind),
                                           int(max_ahead)),
                     bk_max=32, budget=90 * 2**20) is not None
    )


def range_stats_stream(secs, x, valid, window, max_behind, max_ahead,
                       window_ahead=0, scale=None,
                       interpret: bool = False):
    """Streaming rangeBetween(-window, +window_ahead) aggregates.

    Same output dict as ``range_stats_shifted`` (mean/count/min/max/
    sum/stddev/zscore + the [K, 1] ``clipped`` truncation audit).
    ``max_behind``/``max_ahead`` are *runtime* row bounds — derive them
    from the data exactly as for the shifted form; bounds too small
    truncate frames and the audit counts the affected rows.  ``secs``
    must be int32 (rebased, non-negative) and ascending per row;
    ``scale`` multiplies x inside the kernel (fold the elementwise
    pre-pass a caller would otherwise re-stream the column for)."""
    with pk.interpret_scope(interpret):
        outs = _stream_call(
            secs.astype(jnp.int32), x, valid,
            _params(window, window_ahead, max_behind, max_ahead),
            _scale(scale), depth=psr.dma_buffers(), interpret=interpret,
        )
    return _as_dict(outs)


def range_stats_unrolled(secs, x, valid, window, max_behind, max_ahead,
                         window_ahead=0, scale=None,
                         interpret: bool = False):
    """Statically-unrolled twin of :func:`range_stats_stream` for
    small windows (W <= UNROLL_MAX_W): same semantics, trip counts
    baked at compile time."""
    with pk.interpret_scope(interpret):
        outs = _unrolled_call(
            secs.astype(jnp.int32), x, valid,
            _params(window, window_ahead, max_behind, max_ahead),
            _scale(scale), max_behind=int(max_behind),
            max_ahead=int(max_ahead), depth=psr.dma_buffers(),
            interpret=interpret,
        )
    return _as_dict(outs)


def range_stats_stream_packed(secs, xs, valids, window, max_behind,
                              max_ahead, window_ahead=0, scales=None,
                              interpret: bool = False):
    """Multi-column :func:`range_stats_stream`: ``xs``/``valids`` are
    [C, K, L] stacks sharing one [K, L] key plane, reduced in ONE
    kernel pass — the key planes cross HBM once instead of once per
    column.  Outputs are [C, K, L] ([C, K, 1] for ``clipped``);
    per-column results are bitwise-equal to C single-column calls
    (identical op sequence — tests/test_pallas_window.py pins the
    matrix).  ``scales`` is None, a scalar, or a [C] vector.  Callers
    size C with :func:`pack_cols_budget`."""
    C = xs.shape[0]
    with pk.interpret_scope(interpret):
        outs = _stream_call(
            secs.astype(jnp.int32), xs, valids,
            _params(window, window_ahead, max_behind, max_ahead),
            _scale(scales, C), depth=psr.dma_buffers(),
            interpret=interpret,
        )
    return _as_dict(outs)


def range_stats_unrolled_packed(secs, xs, valids, window, max_behind,
                                max_ahead, window_ahead=0, scales=None,
                                interpret: bool = False):
    """Multi-column :func:`range_stats_unrolled` (see
    :func:`range_stats_stream_packed`)."""
    C = xs.shape[0]
    with pk.interpret_scope(interpret):
        outs = _unrolled_call(
            secs.astype(jnp.int32), xs, valids,
            _params(window, window_ahead, max_behind, max_ahead),
            _scale(scales, C), max_behind=int(max_behind),
            max_ahead=int(max_ahead), depth=psr.dma_buffers(),
            interpret=interpret,
        )
    return _as_dict(outs)


def rows_stats_stream(x, valid, rows_behind, rows_ahead=0, scale=None,
                      interpret: bool = False):
    """Row-based windows (Spark rowsBetween(-rows_behind, +rows_ahead))
    as the same streaming sweep over an iota key: key distance == row
    distance, so the range kernel computes exactly the row frame."""
    K, L = x.shape
    iota = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32), (K, L))
    return range_stats_stream(
        iota, x, valid, window=rows_behind, max_behind=rows_behind,
        max_ahead=rows_ahead, window_ahead=rows_ahead, scale=scale,
        interpret=interpret,
    )
