"""Runtime admission control: project a query's device footprint and
reject/queue it before it compiles or runs.

The static analyzer's ``vmem-budget`` rule folds every kernel call
site's worst-case per-step VMEM bytes at lint time
(``tools/analysis/rules/vmem.py``); its sanctioned *runtime* twin is
the kernel planners' own block folding
(``ops/pallas_kernels._plan`` — the function behind
``pallas_stream.pack_budget``).  This module applies that same folding
per submitted plan:

* **VMEM** — the worst-case per-step block bytes any kernel of the
  plan would hold live (the scoped-VMEM working set).  A query whose
  projection exceeds ``TEMPO_TPU_SERVICE_VMEM_BUDGET`` could NEVER
  run on the declared budget and is **rejected** with
  :class:`AdmissionError` — named, immediate, not queued forever.
* **HBM** — the packed source planes plus the widest intermediate the
  chain materialises (input + output live together).  A query over
  the whole ``TEMPO_TPU_SERVICE_HBM_BUDGET`` is rejected; one that
  merely exceeds the *currently free* share is **queued** until
  running queries release theirs (the scheduler re-checks on every
  release).

The numbers are projections, not accounting: they bound the working
set from the packed geometry the plan declares, which is exactly what
an admission decision needs to be made *before* anything compiles.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from tempo_tpu.plan import ir

#: default total-HBM admission budget (bytes) when the knob is unset.
_DEFAULT_HBM_BUDGET = 2 << 30


class AdmissionError(RuntimeError):
    """A query's projected footprint exceeds the service budget — the
    named rejection the admission controller raises instead of queueing
    a query that could never run."""

    def __init__(self, message: str, hbm_bytes: int = 0,
                 vmem_bytes: int = 0):
        super().__init__(message)
        self.hbm_bytes = hbm_bytes
        self.vmem_bytes = vmem_bytes


@dataclasses.dataclass(frozen=True)
class Footprint:
    """Projected device working set of one query."""

    hbm_bytes: int
    vmem_bytes: int


def vmem_budget_bytes() -> int:
    """``TEMPO_TPU_SERVICE_VMEM_BUDGET``; unset = the kernel planners'
    scoped budget (``pallas_kernels._VMEM_BUDGET`` — headroom under
    the 16 MiB scoped-vmem cap), so by default admission rejects
    exactly the shapes the kernels themselves could not block-plan.
    An explicit 0 means 0 (admit nothing) — only *unset* defaults."""
    from tempo_tpu import config
    from tempo_tpu.ops import pallas_kernels as pk

    val = config.get_int("TEMPO_TPU_SERVICE_VMEM_BUDGET")
    return pk._VMEM_BUDGET if val is None else val


def hbm_budget_bytes() -> int:
    """``TEMPO_TPU_SERVICE_HBM_BUDGET``; unset = 2 GiB.  An explicit 0
    means 0 (admit nothing) — only *unset* defaults."""
    from tempo_tpu import config

    val = config.get_int("TEMPO_TPU_SERVICE_HBM_BUDGET")
    return _DEFAULT_HBM_BUDGET if val is None else val


def _geometry(node: ir.Node) -> Optional[tuple]:
    """(K, L) packed geometry of the frame feeding ``node``, walked
    down the primary input chain to a source; None when no source
    geometry is derivable."""
    import numpy as np

    from tempo_tpu import packing

    cur = node
    while True:
        if cur.op == "dist_source":
            p = cur.payload
            return int(p.K_dev), int(p.L)
        if cur.op == "source":
            lay = cur.payload.layout
            L = packing.pad_length(int(np.max(lay.lengths, initial=0)))
            return int(lay.n_series), L
        if not cur.inputs:
            return None
        cur = cur.inputs[0]


def _node_hbm_bytes(node: ir.Node) -> int:
    """Packed plane bytes this node's result holds live (ts i64 + one
    f32 value + bool validity plane per column), from the optimizer's
    plane-count model; conservative fallback doubles the input."""
    from tempo_tpu.plan import optimizer

    geom = _geometry(node)
    if geom is None:
        return 0
    K, L = geom
    planes = optimizer._device_plane_count(node)
    if planes is None:
        planes = 2 * max(1, len(node.inputs))
    return K * L * (8 + 5 * int(planes))


#: conservative live-plane counts of the kernel block plans, mirroring
#: the static rule's per-site folding: the window engines hold carries
#: + roll temps + pipelined I/O (~16 [bk, L] f32 planes), the merge
#: network ~12 over the merged lane axis.
_OP_VMEM_ARRAYS = {
    "range_stats": 16,
    "fused_asof_stats_ema": 16,
    "asof_join": 12,
}


def _node_vmem_bytes(node: ir.Node) -> int:
    """Worst-case per-step VMEM block bytes of the kernel this op would
    run, via the kernel planners' own folding
    (``pallas_kernels._plan`` — the runtime twin of the analyzer's
    vmem-budget rule).  When even the smallest legal block is over the
    planners' scoped budget, the minimal [8, L] block's bytes are
    reported — the true requirement the admission budget is compared
    against."""
    from tempo_tpu.ops import pallas_kernels as pk

    arrays = _OP_VMEM_ARRAYS.get(node.op)
    if arrays is None:
        return 0
    geom = _geometry(node)
    if geom is None:
        return 0
    K, L = geom
    if node.op == "asof_join":
        right = _geometry(node.inputs[1]) if len(node.inputs) > 1 else None
        L = L + (right[1] if right else L)      # merged lane width
    plan = pk._plan(int(K), int(L), arrays=arrays)
    if plan is None:
        return 8 * L * 4 * arrays               # minimal legal block
    _, bk, _ = plan
    return bk * L * 4 * arrays


def project_footprint(root: ir.Node) -> Footprint:
    """Project one plan's working set: all source planes resident plus
    the two widest op results (an op's input and output are live
    together), and the largest kernel block any op folds."""
    hbm = 0
    op_bytes = []
    vmem = 0
    for n in root.walk():
        if n.is_source():
            hbm += _node_hbm_bytes(n)
        else:
            op_bytes.append(_node_hbm_bytes(n))
            vmem = max(vmem, _node_vmem_bytes(n))
    op_bytes.sort(reverse=True)
    hbm += sum(op_bytes[:2])
    return Footprint(hbm_bytes=int(hbm), vmem_bytes=int(vmem))


class AdmissionController:
    """Budget bookkeeping for the query service.  NOT itself locked —
    the service serializes calls under its scheduler condition, so
    check/acquire/release are plain arithmetic here."""

    def __init__(self, hbm_budget: Optional[int] = None,
                 vmem_budget: Optional[int] = None):
        # None = defaults; an explicit 0 is honoured (admit nothing)
        self.hbm_budget = int(
            hbm_budget_bytes() if hbm_budget is None else hbm_budget)
        self.vmem_budget = int(
            vmem_budget_bytes() if vmem_budget is None else vmem_budget)
        self.hbm_in_use = 0

    def check(self, fp: Footprint) -> None:
        """Raise :class:`AdmissionError` when the query could NEVER run
        under the declared budgets (reject-at-submit, not
        queued-forever)."""
        if fp.vmem_bytes > self.vmem_budget:
            raise AdmissionError(
                f"query rejected: projected worst-case VMEM block "
                f"{fp.vmem_bytes} B exceeds the admission budget "
                f"{self.vmem_budget} B (TEMPO_TPU_SERVICE_VMEM_BUDGET) "
                f"— no block plan fits; the shape cannot run",
                hbm_bytes=fp.hbm_bytes, vmem_bytes=fp.vmem_bytes)
        if fp.hbm_bytes > self.hbm_budget:
            raise AdmissionError(
                f"query rejected: projected HBM footprint "
                f"{fp.hbm_bytes} B exceeds the TOTAL admission budget "
                f"{self.hbm_budget} B (TEMPO_TPU_SERVICE_HBM_BUDGET) — "
                f"it could never be scheduled",
                hbm_bytes=fp.hbm_bytes, vmem_bytes=fp.vmem_bytes)

    def fits_now(self, fp: Footprint) -> bool:
        return self.hbm_in_use + fp.hbm_bytes <= self.hbm_budget

    def acquire(self, fp: Footprint) -> None:
        self.hbm_in_use += fp.hbm_bytes

    def release(self, fp: Footprint) -> None:
        self.hbm_in_use = max(0, self.hbm_in_use - fp.hbm_bytes)
