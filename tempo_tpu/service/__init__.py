"""Multi-tenant query service over the cost-based planner.

The reference library's deployment story is "many analysts fire
time-series queries at one shared Spark engine"; this package is the
rebuild's equivalent front door (ROADMAP item 1):

* ``service/service.py`` — :class:`QueryService`: plan-signature-keyed
  queries from N concurrent tenants against the SHARED executable
  cache (single-flight builds, per-tenant counters), a fair scheduler
  (per-tenant token accounting + per-tenant submit backpressure), and
  graceful drain.
* ``service/admission.py`` — admission control: the static analyzer's
  VMEM folding applied at runtime projects each query's device
  footprint; over-budget queries are rejected with the named
  :class:`AdmissionError` (never queued forever), over-the-free-share
  queries queue until running work releases budget.

Plan decisions underneath (engine picks, fusion, reshard placement)
are cost-based since round 11 (``tempo_tpu/plan/cost.py``): estimated
cost decides, the legacy thresholds are demoted to feasibility priors,
and every cost-decided plan stays bitwise-identical to its rule-based
twin.
"""

from tempo_tpu.resilience import (Cancelled, Deadline, DeadlineExceeded,
                                  QuarantinedError, ShutdownError)
from tempo_tpu.service.admission import (AdmissionController,
                                         AdmissionError, Footprint,
                                         project_footprint)
from tempo_tpu.service.service import QueryService, QueryTicket, lazy_frame

__all__ = [
    "QueryService", "QueryTicket", "lazy_frame",
    "AdmissionController", "AdmissionError", "Footprint",
    "project_footprint",
    # fault-domain vocabulary (tempo_tpu.resilience), re-exported:
    # service callers meet these on submit() and tickets
    "Deadline", "DeadlineExceeded", "Cancelled", "ShutdownError",
    "QuarantinedError",
]
