"""Multi-tenant query service: N concurrent clients, one shared
planner.

``QueryService`` is the front door the ROADMAP's "many analysts, one
engine" cohort needs: clients submit plan-signature-keyed queries
(lazy chains — :func:`lazy_frame` wraps any eager frame without the
``TEMPO_TPU_PLAN`` knob), a bounded worker pool executes them through
the shared executable cache (``plan/cache.py`` — single-flight, so two
tenants compiling the same signature build once), and two policies sit
between submit and dispatch:

* **admission control** (``service/admission.py``) — the static
  analyzer's VMEM folding applied at runtime: a query whose projected
  footprint could never fit the declared budgets is REJECTED with
  :class:`~tempo_tpu.service.admission.AdmissionError` at submit; one
  that merely exceeds the currently-free HBM share stays QUEUED and
  dispatches when running queries release theirs.
* **fair scheduling** — per-tenant token accounting over the
  bounded-queue backpressure pattern of ``serve/executor.py``: each
  dispatch charges the tenant a token, the scheduler always offers the
  lowest-token tenant first, and a tenant at
  ``TEMPO_TPU_SERVICE_TENANT_QUOTA`` pending queries blocks in
  ``submit()`` instead of flooding the shared queue — no client can
  starve the others by volume.

A poisoned query (its execution raises) fails its own ticket and
releases its budget; the workers live on.  ``stats()`` reports
per-tenant submitted/completed/failed/rejected counts, p50/p99
latency, the cache's per-tenant traffic, and the max/min
completed-query ratio — the starvation audit the bench asserts.

**The fault domain** (resilience.py primitives):

* *deadlines* — ``submit(..., deadline_s=...)`` (default
  ``TEMPO_TPU_SERVICE_DEADLINE_S``) carries ONE
  :class:`~tempo_tpu.resilience.Deadline` through the tenant-quota
  wait, the admission queue and dispatch; whichever stage the budget
  dies at raises/fails with a stage-named ``DeadlineExceeded``.
* *cancellation* — ``QueryTicket.cancel()`` removes a still-queued
  query, frees its quota slot, and resolves the ticket with
  :class:`~tempo_tpu.resilience.Cancelled`; it never reaches a worker
  and never acquires budget.
* *quarantine* — a per-plan-signature
  :class:`~tempo_tpu.resilience.CircuitBreaker`: a signature failing
  ``TEMPO_TPU_BREAKER_THRESHOLD`` consecutive times is refused at
  submit with ``QuarantinedError`` until a half-open probe (after
  ``TEMPO_TPU_BREAKER_COOLDOWN_S``) succeeds — a poison-pill query
  cannot burn every worker's time forever.
* *supervision* — worker threads run under a supervisor: an exception
  escaping the scheduler loop (not a query's own failure — those are
  already per-ticket) logs, counts on ``restarts`` and restarts the
  worker, so the plane survives its own bugs and injected faults.
"""

from __future__ import annotations

import collections
import logging
import queue as queue_mod
import threading
import time
from typing import Dict, Optional

from tempo_tpu.plan import cache as plan_cache
from tempo_tpu.plan import ir
from tempo_tpu.resilience import (Cancelled, CircuitBreaker, Deadline,
                                  DeadlineExceeded)
from tempo_tpu.serve.executor import LATENCY_WINDOW
from tempo_tpu.service.admission import (AdmissionController,
                                         Footprint, project_footprint)

logger = logging.getLogger(__name__)


def lazy_frame(frame):
    """Wrap an eager ``TSDF`` / ``DistributedTSDF`` into its lazy
    recording wrapper WITHOUT the ``TEMPO_TPU_PLAN`` knob: service
    clients chain ops on the result and submit it — the service is
    always plan-driven, whatever the process-wide planning mode."""
    from tempo_tpu.plan import lazy

    return lazy.wrap(lazy._as_node(frame))


class QueryTicket:
    """One submitted query: a waitable handle for its result."""

    __slots__ = ("tenant", "signature", "footprint", "deadline",
                 "_service", "t_submit", "t_blocked", "t_start",
                 "t_done", "_root", "_event", "_result", "_exc")

    def __init__(self, tenant: str, root: ir.Node, signature: str,
                 footprint: Footprint,
                 deadline: Optional[Deadline] = None, service=None):
        self.tenant = tenant
        self.signature = signature
        self.footprint = footprint
        self.deadline = deadline
        self._service = service
        self.t_submit = time.perf_counter()
        #: when this query, AT THE HEAD of its tenant's queue, first
        #: failed ``fits_now()`` — the budget-reservation clock (time
        #: spent behind the tenant's own earlier queries is not
        #: starvation and must not trigger a service-wide reserve)
        self.t_blocked: Optional[float] = None
        self.t_start: Optional[float] = None
        self.t_done: Optional[float] = None
        self._root = root
        self._event = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None

    def _finish(self, result=None, exc: Optional[BaseException] = None):
        self._result, self._exc = result, exc
        self.t_done = time.perf_counter()
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def cancel(self) -> bool:
        """Cancel this query if it is still queued: it is removed from
        its tenant's queue (freeing the quota slot), never reaches a
        worker, never acquires budget, and ``result()`` raises
        :class:`~tempo_tpu.resilience.Cancelled`.  Returns ``False``
        once the query has been dispatched or resolved."""
        if self._service is None:
            return False
        return self._service._cancel(self)

    def result(self, timeout: Optional[float] = None):
        """The query's result frame (blocks until dispatched and
        executed); re-raises the query's own failure."""
        if not self._event.wait(timeout):
            raise TimeoutError("query not executed yet")
        if self._exc is not None:
            raise self._exc
        return self._result

    @property
    def latency_s(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return self.t_done - self.t_submit


class QueryService:
    """See module docstring."""

    #: per-tenant latency samples kept for the percentile report (a
    #: sliding window, not a lifetime log) — the serving executors'
    #: shared bound (serve/executor.py:LATENCY_WINDOW), so every
    #: queue-side percentile in the system is over the same window
    _LATENCY_WINDOW = LATENCY_WINDOW

    def __init__(self, workers: Optional[int] = None,
                 tenant_quota: Optional[int] = None,
                 hbm_budget: Optional[int] = None,
                 vmem_budget: Optional[int] = None,
                 reserve_after_s: float = 5.0,
                 deadline_s: Optional[float] = None,
                 breaker: Optional[CircuitBreaker] = None):
        from tempo_tpu import config

        if workers is None:
            workers = config.get_int("TEMPO_TPU_SERVICE_WORKERS", 4)
        if tenant_quota is None:
            tenant_quota = config.get_int(
                "TEMPO_TPU_SERVICE_TENANT_QUOTA", 64)
        if deadline_s is None:
            deadline_s = config.get_float("TEMPO_TPU_SERVICE_DEADLINE_S")
        #: default end-to-end budget for submitted queries (None = no
        #: deadline unless the submit passes one)
        self.deadline_s = deadline_s
        #: per-plan-signature circuit breaker: repeat-failing
        #: signatures are refused at submit with QuarantinedError
        self.breaker = breaker if breaker is not None else CircuitBreaker()
        #: supervised worker restarts (an exception escaping the
        #: scheduler loop, NOT a query's own failure)
        self.restarts = 0  # guarded-by: self._cond
        self.tenant_quota = max(1, int(tenant_quota))
        #: budget reservation threshold: once a head-of-queue query has
        #: sat unfitting this long, the scheduler stops handing the
        #: freed HBM share to smaller queries until the starved one
        #: fits — without it, a sustained small-query stream could keep
        #: ``hbm_in_use`` high forever and a large admitted query would
        #: never dispatch (admission only rejects what can NEVER fit)
        self.reserve_after_s = float(reserve_after_s)
        self.admission = AdmissionController(hbm_budget, vmem_budget)
        #: per-worker-thread picked-but-unaccounted ticket (supervisor
        #: fails + releases it if the loop dies mid-query)
        self._running: Dict[int, QueryTicket] = {}
        self._cond = threading.Condition()
        self._queues: Dict[str, collections.deque] = {}  # guarded-by: self._cond
        self._tokens: Dict[str, int] = {}  # guarded-by: self._cond
        self._counts: Dict[str, Dict[str, int]] = {}  # guarded-by: self._cond
        self._latencies: Dict[str, "collections.deque"] = {}  # guarded-by: self._cond
        self._closed = False  # guarded-by: self._cond
        self._standing_engine = None  # guarded-by: self._cond
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"tempo-query-service-{i}")
            for i in range(max(1, int(workers)))
        ]
        for t in self._threads:
            t.start()

    # -- client side ---------------------------------------------------

    def _count(self, tenant: str, field: str, by: int = 1) -> None:  # guarded-by: self._cond
        c = self._counts.setdefault(tenant, {
            "submitted": 0, "completed": 0, "failed": 0, "rejected": 0,
            "cancelled": 0, "quarantined": 0})
        c[field] += by

    @staticmethod
    def _as_root(query) -> ir.Node:
        from tempo_tpu.plan import lazy

        if isinstance(query, ir.Node):
            return query
        if isinstance(query, lazy.LazyDistributedTSDF):
            # mesh chains materialise through their collect barrier,
            # exactly like the lazy terminal does
            return ir.Node("collect", inputs=(query.plan,))
        if isinstance(query, lazy._LazyBase):
            return query.plan
        raise TypeError(
            f"submit() takes a lazy chain (service.lazy_frame(frame)"
            f".op()...) or a plan node, got {type(query).__name__}")

    def submit(self, tenant: str, query,
               timeout: Optional[float] = None,
               deadline_s=None) -> QueryTicket:
        """Enqueue one query for ``tenant``.  Raises
        :class:`AdmissionError` when the projected footprint could
        never fit the budgets, and
        :class:`~tempo_tpu.resilience.QuarantinedError` when the plan
        signature's circuit breaker is open (repeat poison pill —
        fail-fast until a half-open probe succeeds); blocks while the
        tenant is at quota (per-tenant backpressure — ``queue.Full``
        after ``timeout``).  ``deadline_s`` (seconds or a
        :class:`Deadline`; default ``TEMPO_TPU_SERVICE_DEADLINE_S``)
        is carried end to end: expiry during the quota wait raises —
        and later, in the admission queue or at dispatch, fails the
        ticket — with a stage-named ``DeadlineExceeded``."""
        root = self._as_root(query)
        footprint = project_footprint(root)
        sig = ir.signature(root)
        dl = Deadline.after(self.deadline_s if deadline_s is None
                            else deadline_s)
        deadline = None if timeout is None else \
            time.perf_counter() + timeout
        with self._cond:
            if self._closed:
                raise RuntimeError("query service is closed")
            try:
                self.admission.check(footprint)
            except Exception:
                self._count(tenant, "submitted")
                self._count(tenant, "rejected")
                raise
            try:
                self.breaker.allow(sig, label="plan signature")
            except Exception:
                self._count(tenant, "submitted")
                self._count(tenant, "quarantined")
                raise
            try:
                ticket = self._enqueue_locked(tenant, root, sig,
                                              footprint, dl, deadline)
            except BaseException:
                # this admission may have been the signature's
                # half-open probe; a failed ENQUEUE (quota Full,
                # deadline, close) reports no outcome — free the probe
                # slot or the signature quarantines forever
                self.breaker.abandon(sig)
                raise
        return ticket

    def submit_sql(self, tenant: str, text: str, tables,
                   timeout: Optional[float] = None,
                   deadline_s=None) -> QueryTicket:
        """Submit one SQL statement: ``text`` compiles through the plan
        IR (plan/sql_compile.py — projections, ``ASOF JOIN``,
        ``WHERE``, ``GROUP BY time_bucket``) over the registered
        ``tables`` ({name: TSDF | DistributedTSDF | lazy}), then flows
        through the SAME admission / fairness / dispatch path as a
        lazy-chain submission — so text queries hit the executable
        cache and the sharded dispatch tiers exactly like method
        chains.  The compiled root carries ``_origin='sql'``: its plan
        signature (the quota, breaker and cache identity) is distinct
        from the equivalent method chain's (MIGRATION v0.18).
        ``sql.SqlError`` raises here, before anything is enqueued."""
        from tempo_tpu.plan import optimizer, sql_compile

        root = sql_compile.compile_statement(text, tables)
        if optimizer._mesh_side(root):
            root = ir.Node("collect", inputs=(root,))
        return self.submit(tenant, root, timeout=timeout,
                           deadline_s=deadline_s)

    # -- standing queries ----------------------------------------------

    def _standing(self):
        """The service's standing-query engine, created on first
        ``register`` (one engine shared by every tenant — subscriptions
        on the same serving config share one AOT-warmed cohort
        plane)."""
        from tempo_tpu.query.standing import StandingQueryEngine

        with self._cond:
            if self._closed:
                raise RuntimeError("query service is closed")
            if self._standing_engine is None:
                self._standing_engine = StandingQueryEngine()
            return self._standing_engine

    def register(self, tenant: str, query):
        """Register a planned method chain over
        :class:`~tempo_tpu.query.unified.StreamTable` frames as a
        **standing query**: where :meth:`submit` answers once,
        ``register`` answers forever — every
        :meth:`~tempo_tpu.query.standing.StandingQueryEngine.push`
        fans out to the returned
        :class:`~tempo_tpu.query.standing.Subscription` as an
        incremental delta, bitwise what re-running the batch query over
        the concatenated history produces.  Counted under the tenant
        like a submission."""
        eng = self._standing()
        sub = eng.register(query)
        with self._cond:
            self._count(tenant, "submitted")
            self._count(tenant, "completed")
        return sub

    def register_sql(self, tenant: str, text: str, tables):
        """Standing twin of :meth:`submit_sql`: compile one SQL
        statement over ``tables`` ({name: StreamTable | TSDF | lazy})
        and register it as a standing query — StreamTable entries enter
        the plan as ``unified_scan`` sources, so the statement answers
        over history + live under one watermark."""
        eng = self._standing()
        sub = eng.register_sql(text, tables)
        with self._cond:
            self._count(tenant, "submitted")
            self._count(tenant, "completed")
        return sub

    def push(self, table, df, *, deadline_s=None):
        """Admit one batch of events for ``table`` and fan it out to
        every standing subscription registered through this service
        (see :meth:`~tempo_tpu.query.standing.StandingQueryEngine.push`)."""
        return self._standing().push(table, df, deadline=deadline_s)

    def _enqueue_locked(self, tenant, root, sig, footprint, dl,
                        deadline) -> QueryTicket:  # guarded-by: self._cond
        """The quota-wait + append half of submit (under the
        scheduler condition)."""
        q = self._queues.setdefault(tenant, collections.deque())
        if tenant not in self._tokens:
            # new (or returning) tenants join at the FLOOR of the
            # live token counts, not 0: starting from zero would
            # hand a newcomer absolute priority until it caught up
            # with tenants that have been served for hours —
            # starving them, the inverse of the fairness contract
            self._tokens[tenant] = min(self._tokens.values(),
                                       default=0)
        # standard condition-variable shape: re-check the predicate
        # after EVERY wake (a timed-out wait may still have had the
        # queue drained just before the deadline — Full only when
        # the quota is genuinely still exhausted past it)
        while len(q) >= self.tenant_quota:
            if dl is not None:
                # the end-to-end budget dies HERE by name, not as
                # an anonymous queue.Full
                dl.check("tenant quota")
            remaining = None if deadline is None else \
                deadline - time.perf_counter()
            if dl is not None:
                rem_dl = dl.remaining()
                remaining = rem_dl if remaining is None \
                    else min(remaining, rem_dl)
            if remaining is not None and remaining <= 0:
                raise queue_mod.Full(
                    f"tenant {tenant!r} is at its pending-query "
                    f"quota ({self.tenant_quota})")
            self._cond.wait(remaining)
            if self._closed:
                raise RuntimeError("query service is closed")
            # the scheduler PRUNES a deque it drains
            # (_dispatch_locked), so the reference captured above
            # may be orphaned by now — re-resolve the live deque
            # before re-checking the predicate, or the append below
            # would land in a deque _pick never scans and silently
            # lose the query
            q = self._queues.setdefault(tenant, q)
        ticket = QueryTicket(tenant, root, sig, footprint,
                             deadline=dl, service=self)
        q.append(ticket)
        self._count(tenant, "submitted")
        self._cond.notify_all()
        return ticket

    def _cancel(self, ticket: QueryTicket) -> bool:
        """Remove a still-queued ticket (QueryTicket.cancel's body):
        frees its quota slot, resolves it with :class:`Cancelled`; a
        dispatched/resolved ticket is not cancellable."""
        with self._cond:
            q = self._queues.get(ticket.tenant)
            if ticket.done() or q is None or ticket not in q:
                return False
            q.remove(ticket)
            if not q:
                del self._queues[ticket.tenant]
            ticket._finish(exc=Cancelled(
                f"query {ticket.signature[:16]}... for tenant "
                f"{ticket.tenant!r} cancelled before dispatch"))
            self._count(ticket.tenant, "cancelled")
            self._cond.notify_all()     # a quota slot freed
        # a cancelled query reports no outcome: free a possible
        # half-open probe slot for its signature
        self.breaker.abandon(ticket.signature)
        return True

    # -- scheduler/worker side ------------------------------------------

    def _dispatch_locked(self, tenant: str) -> QueryTicket:  # guarded-by: self._cond
        ticket = self._queues[tenant].popleft()
        if not self._queues[tenant]:
            # prune drained queues so _pick's sort scans tenants with
            # PENDING work, not every tenant ever seen (tokens/counts
            # persist — they are per-tenant-cardinality, not per-query).
            # Safe against submitters blocked at quota: they re-resolve
            # the live deque after every wake (see submit()), so a
            # pruned reference is never appended into
            del self._queues[tenant]
        self._tokens[tenant] = self._tokens.get(tenant, 0) + 1
        self.admission.acquire(ticket.footprint)
        return ticket

    def _pick(self) -> Optional[QueryTicket]:  # guarded-by: self._cond
        """Next dispatchable ticket under the scheduler lock: tenants
        offered in token order (fewest dispatches first — the fairness
        accounting), first whose head query fits the free HBM share.
        None = nothing dispatchable right now.

        **Budget reservation**: a head that does not fit is only
        *transiently* blocked (admission rejected everything that can
        NEVER fit), but a sustained stream of smaller queries could
        re-consume every freed byte and block it forever.  Once the
        oldest unfitting head has waited ``reserve_after_s``, nothing
        else dispatches until it fits — running queries drain,
        ``hbm_in_use`` falls, and at worst an empty budget admits it.
        The clock starts when the query FIRST fails ``fits_now()`` as
        its tenant's head (``t_blocked``), not at submit: time queued
        behind the same tenant's earlier queries is ordinary waiting,
        and triggering off it would stall the whole service for a query
        that was never budget-starved."""
        self._expire_locked()
        now = time.perf_counter()
        tenants = sorted(
            (t for t, q in self._queues.items() if q),
            key=lambda t: (self._tokens.get(t, 0), t))
        starved: Optional[tuple] = None
        for t in tenants:
            head = self._queues[t][0]
            if not self.admission.fits_now(head.footprint):
                if head.t_blocked is None:
                    head.t_blocked = now
                if starved is None \
                        or head.t_blocked < starved[1].t_blocked:
                    starved = (t, head)
        if starved is not None and (
                now - starved[1].t_blocked >= self.reserve_after_s):
            if self.admission.fits_now(starved[1].footprint):
                return self._dispatch_locked(starved[0])
            return None                      # budget reserved: drain
        for t in tenants:
            if self.admission.fits_now(self._queues[t][0].footprint):
                return self._dispatch_locked(t)
        return None

    def _expire_locked(self) -> None:  # guarded-by: self._cond
        """Fail every queued ticket whose deadline died waiting for
        admission (stage-named) — under the scheduler lock.  Expired
        work must resolve NOW, not when it happens to reach its
        tenant's head."""
        for tenant in list(self._queues):
            q = self._queues[tenant]
            dead = [t for t in q
                    if t.deadline is not None and t.deadline.expired()]
            if not dead:
                continue
            for t in dead:
                q.remove(t)
                t._finish(exc=DeadlineExceeded(
                    f"deadline exceeded at stage 'admission queue': "
                    f"query for tenant {tenant!r} spent its "
                    f"{t.deadline.budget_s:.3f}s budget waiting for "
                    f"budget/workers", stage="admission queue"))
                self._count(tenant, "failed")
                self.breaker.abandon(t.signature)   # vanished probe
            if not q:
                del self._queues[tenant]
            self._cond.notify_all()     # quota slots freed

    def _worker(self) -> None:  # owns-tickets: _finish
        """Supervised scheduler/executor loop: a query's own failure is
        delivered on its ticket (the inner try); an exception escaping
        the LOOP itself (scheduler bug, injected plane fault) restarts
        the worker — the plane outlives it.  A ticket this worker had
        already PICKED when the loop died is failed and its budget
        released here (it would otherwise hang its caller and leak
        admission capacity forever)."""
        tid = threading.get_ident()
        while True:
            try:
                self._worker_loop(tid)
                return                       # clean close
            except Exception as e:  # noqa: BLE001 - supervised restart
                # _running is keyed by thread ident: each worker only
                # ever touches its OWN slot, and dict item ops are
                # atomic under the GIL — taking the scheduler condition
                # here would drag it into the dispatch hot path
                ticket = self._running.pop(tid, None)  # lint-ok: guarded-attr: per-thread-ident slot, GIL-atomic dict item ops
                if ticket is not None and not ticket.done():
                    ticket._finish(exc=e)
                    self.breaker.abandon(ticket.signature)
                    with self._cond:
                        self.admission.release(ticket.footprint)
                        self._count(ticket.tenant, "failed")
                with self._cond:
                    self.restarts += 1
                    n = self.restarts
                    self._cond.notify_all()
                logger.warning(
                    "query-service worker died (%s: %s); supervisor "
                    "restart #%d", type(e).__name__, e, n)

    def _worker_loop(self, tid) -> None:
        from tempo_tpu.plan import executor as plan_executor

        while True:
            with self._cond:
                ticket = self._pick()
                while ticket is None:
                    if self._closed and not any(self._queues.values()):
                        return
                    # reservation is age-triggered: wake periodically
                    # while queries are PENDING so a starved head's
                    # clock is re-read (and deadlines expire by name);
                    # an idle service sleeps until a submit/close
                    # notifies instead of spinning
                    self._cond.wait(
                        timeout=0.25 if any(self._queues.values())
                        else None)
                    ticket = self._pick()
                # a dispatch frees a quota slot: wake blocked
                # submitters (completions notify elsewhere)
                self._cond.notify_all()
            # visible to the supervisor: if this loop dies before the
            # ticket is accounted, the restart fails it and releases
            # its acquired budget instead of hanging its caller
            self._running[tid] = ticket
            if ticket.deadline is not None and ticket.deadline.expired():
                # budget died between pick and dispatch: the budget IS
                # acquired at pick — release it with the failure
                ticket._finish(exc=DeadlineExceeded(
                    f"deadline exceeded at stage 'dispatch': query for "
                    f"tenant {ticket.tenant!r} ran out of its "
                    f"{ticket.deadline.budget_s:.3f}s budget before "
                    f"execution", stage="dispatch"))
                with self._cond:
                    self.admission.release(ticket.footprint)
                    self._count(ticket.tenant, "failed")
                    self._cond.notify_all()
                self.breaker.abandon(ticket.signature)
                self._running.pop(tid, None)
                continue
            ticket.t_start = time.perf_counter()
            try:
                with plan_cache.tenant_scope(ticket.tenant):
                    result = plan_executor.execute(ticket._root)
            except BaseException as e:  # noqa: BLE001 - delivered on the
                ticket._finish(exc=e)   # ticket; the worker lives on
                self.breaker.record(ticket.signature, ok=False)
                with self._cond:
                    self.admission.release(ticket.footprint)
                    self._count(ticket.tenant, "failed")
                    self._cond.notify_all()
                self._running.pop(tid, None)
                continue
            ticket._finish(result=result)
            self.breaker.record(ticket.signature, ok=True)
            with self._cond:
                self.admission.release(ticket.footprint)
                self._count(ticket.tenant, "completed")
                # bounded sample: percentiles are over the most recent
                # window, and a long-lived service does not grow a
                # float per query served forever
                self._latencies.setdefault(
                    ticket.tenant,
                    collections.deque(maxlen=self._LATENCY_WINDOW),
                ).append(ticket.latency_s)
                self._cond.notify_all()
            self._running.pop(tid, None)

    # -- lifecycle / metrics --------------------------------------------

    def close(self, timeout: Optional[float] = None) -> None:
        """Graceful drain: stop accepting, execute everything already
        queued, stop the workers.  ``timeout`` bounds the WHOLE drain —
        one shared deadline across the worker joins, not per worker.
        Queries still pending when it expires are failed with
        :class:`~tempo_tpu.resilience.ShutdownError` — a ticket never
        hangs its caller."""
        from tempo_tpu.resilience import ShutdownError

        with self._cond:
            if self._closed:
                return
            self._closed = True
            standing = self._standing_engine
            self._standing_engine = None
            self._cond.notify_all()
        if standing is not None:
            standing.close()
        deadline = None if timeout is None else \
            time.perf_counter() + timeout
        for t in self._threads:
            t.join(None if deadline is None else
                   max(0.0, deadline - time.perf_counter()))
        with self._cond:
            for tenant in list(self._queues):
                for ticket in self._queues.pop(tenant):
                    ticket._finish(exc=ShutdownError(
                        f"query service closed with this query "
                        f"(tenant {tenant!r}) still pending"))
                    self._count(tenant, "failed")
                    self.breaker.abandon(ticket.signature)
            self._cond.notify_all()

    def __enter__(self) -> "QueryService":
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    def stats(self) -> dict:
        """Per-tenant counts + latency percentiles, the shared cache's
        per-tenant traffic, budget occupancy, and the starvation audit
        (max/min completed-query ratio across tenants that submitted)."""
        from tempo_tpu import profiling
        from tempo_tpu.serve.executor import latency_percentiles

        with self._cond:
            tenants = {
                t: dict(c, **latency_percentiles(
                    list(self._latencies.get(t, ()))))
                for t, c in self._counts.items()
            }
            completed = [c["completed"] for c in self._counts.values()
                         if c["submitted"] > 0]
            ratio = None
            if completed and min(completed) > 0:
                ratio = round(max(completed) / min(completed), 3)
            return {
                "tenants": tenants,
                "starvation_ratio": ratio,
                "hbm_in_use": self.admission.hbm_in_use,
                "hbm_budget": self.admission.hbm_budget,
                "vmem_budget": self.admission.vmem_budget,
                "plan_cache": profiling.plan_cache_stats(),
                "breaker": self.breaker.stats(),
                "restarts": self.restarts,
            }
