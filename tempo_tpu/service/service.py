"""Multi-tenant query service: N concurrent clients, one shared
planner.

``QueryService`` is the front door the ROADMAP's "many analysts, one
engine" cohort needs: clients submit plan-signature-keyed queries
(lazy chains — :func:`lazy_frame` wraps any eager frame without the
``TEMPO_TPU_PLAN`` knob), a bounded worker pool executes them through
the shared executable cache (``plan/cache.py`` — single-flight, so two
tenants compiling the same signature build once), and two policies sit
between submit and dispatch:

* **admission control** (``service/admission.py``) — the static
  analyzer's VMEM folding applied at runtime: a query whose projected
  footprint could never fit the declared budgets is REJECTED with
  :class:`~tempo_tpu.service.admission.AdmissionError` at submit; one
  that merely exceeds the currently-free HBM share stays QUEUED and
  dispatches when running queries release theirs.
* **fair scheduling** — per-tenant token accounting over the
  bounded-queue backpressure pattern of ``serve/executor.py``: each
  dispatch charges the tenant a token, the scheduler always offers the
  lowest-token tenant first, and a tenant at
  ``TEMPO_TPU_SERVICE_TENANT_QUOTA`` pending queries blocks in
  ``submit()`` instead of flooding the shared queue — no client can
  starve the others by volume.

A poisoned query (its execution raises) fails its own ticket and
releases its budget; the workers live on.  ``stats()`` reports
per-tenant submitted/completed/failed/rejected counts, p50/p99
latency, the cache's per-tenant traffic, and the max/min
completed-query ratio — the starvation audit the bench asserts.
"""

from __future__ import annotations

import collections
import queue as queue_mod
import threading
import time
from typing import Dict, Optional

from tempo_tpu.plan import cache as plan_cache
from tempo_tpu.plan import ir
from tempo_tpu.serve.executor import LATENCY_WINDOW
from tempo_tpu.service.admission import (AdmissionController,
                                         Footprint, project_footprint)


def lazy_frame(frame):
    """Wrap an eager ``TSDF`` / ``DistributedTSDF`` into its lazy
    recording wrapper WITHOUT the ``TEMPO_TPU_PLAN`` knob: service
    clients chain ops on the result and submit it — the service is
    always plan-driven, whatever the process-wide planning mode."""
    from tempo_tpu.plan import lazy

    return lazy.wrap(lazy._as_node(frame))


class QueryTicket:
    """One submitted query: a waitable handle for its result."""

    __slots__ = ("tenant", "signature", "footprint", "t_submit",
                 "t_blocked", "t_start", "t_done", "_root", "_event",
                 "_result", "_exc")

    def __init__(self, tenant: str, root: ir.Node, signature: str,
                 footprint: Footprint):
        self.tenant = tenant
        self.signature = signature
        self.footprint = footprint
        self.t_submit = time.perf_counter()
        #: when this query, AT THE HEAD of its tenant's queue, first
        #: failed ``fits_now()`` — the budget-reservation clock (time
        #: spent behind the tenant's own earlier queries is not
        #: starvation and must not trigger a service-wide reserve)
        self.t_blocked: Optional[float] = None
        self.t_start: Optional[float] = None
        self.t_done: Optional[float] = None
        self._root = root
        self._event = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None

    def _finish(self, result=None, exc: Optional[BaseException] = None):
        self._result, self._exc = result, exc
        self.t_done = time.perf_counter()
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        """The query's result frame (blocks until dispatched and
        executed); re-raises the query's own failure."""
        if not self._event.wait(timeout):
            raise TimeoutError("query not executed yet")
        if self._exc is not None:
            raise self._exc
        return self._result

    @property
    def latency_s(self) -> Optional[float]:
        if self.t_done is None:
            return None
        return self.t_done - self.t_submit


class QueryService:
    """See module docstring."""

    #: per-tenant latency samples kept for the percentile report (a
    #: sliding window, not a lifetime log) — the serving executors'
    #: shared bound (serve/executor.py:LATENCY_WINDOW), so every
    #: queue-side percentile in the system is over the same window
    _LATENCY_WINDOW = LATENCY_WINDOW

    def __init__(self, workers: Optional[int] = None,
                 tenant_quota: Optional[int] = None,
                 hbm_budget: Optional[int] = None,
                 vmem_budget: Optional[int] = None,
                 reserve_after_s: float = 5.0):
        from tempo_tpu import config

        if workers is None:
            workers = config.get_int("TEMPO_TPU_SERVICE_WORKERS", 4)
        if tenant_quota is None:
            tenant_quota = config.get_int(
                "TEMPO_TPU_SERVICE_TENANT_QUOTA", 64)
        self.tenant_quota = max(1, int(tenant_quota))
        #: budget reservation threshold: once a head-of-queue query has
        #: sat unfitting this long, the scheduler stops handing the
        #: freed HBM share to smaller queries until the starved one
        #: fits — without it, a sustained small-query stream could keep
        #: ``hbm_in_use`` high forever and a large admitted query would
        #: never dispatch (admission only rejects what can NEVER fit)
        self.reserve_after_s = float(reserve_after_s)
        self.admission = AdmissionController(hbm_budget, vmem_budget)
        self._cond = threading.Condition()
        self._queues: Dict[str, collections.deque] = {}
        self._tokens: Dict[str, int] = {}       # dispatches charged
        self._counts: Dict[str, Dict[str, int]] = {}
        self._latencies: Dict[str, "collections.deque"] = {}
        self._closed = False
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"tempo-query-service-{i}")
            for i in range(max(1, int(workers)))
        ]
        for t in self._threads:
            t.start()

    # -- client side ---------------------------------------------------

    def _count(self, tenant: str, field: str, by: int = 1) -> None:
        c = self._counts.setdefault(tenant, {
            "submitted": 0, "completed": 0, "failed": 0, "rejected": 0})
        c[field] += by

    @staticmethod
    def _as_root(query) -> ir.Node:
        from tempo_tpu.plan import lazy

        if isinstance(query, ir.Node):
            return query
        if isinstance(query, lazy.LazyDistributedTSDF):
            # mesh chains materialise through their collect barrier,
            # exactly like the lazy terminal does
            return ir.Node("collect", inputs=(query.plan,))
        if isinstance(query, lazy._LazyBase):
            return query.plan
        raise TypeError(
            f"submit() takes a lazy chain (service.lazy_frame(frame)"
            f".op()...) or a plan node, got {type(query).__name__}")

    def submit(self, tenant: str, query,
               timeout: Optional[float] = None) -> QueryTicket:
        """Enqueue one query for ``tenant``.  Raises
        :class:`AdmissionError` when the projected footprint could
        never fit the budgets; blocks while the tenant is at quota
        (per-tenant backpressure — ``queue.Full`` after ``timeout``)."""
        root = self._as_root(query)
        footprint = project_footprint(root)
        sig = ir.signature(root)
        deadline = None if timeout is None else \
            time.perf_counter() + timeout
        with self._cond:
            if self._closed:
                raise RuntimeError("query service is closed")
            try:
                self.admission.check(footprint)
            except Exception:
                self._count(tenant, "submitted")
                self._count(tenant, "rejected")
                raise
            q = self._queues.setdefault(tenant, collections.deque())
            if tenant not in self._tokens:
                # new (or returning) tenants join at the FLOOR of the
                # live token counts, not 0: starting from zero would
                # hand a newcomer absolute priority until it caught up
                # with tenants that have been served for hours —
                # starving them, the inverse of the fairness contract
                self._tokens[tenant] = min(self._tokens.values(),
                                           default=0)
            # standard condition-variable shape: re-check the predicate
            # after EVERY wake (a timed-out wait may still have had the
            # queue drained just before the deadline — Full only when
            # the quota is genuinely still exhausted past it)
            while len(q) >= self.tenant_quota:
                remaining = None if deadline is None else \
                    deadline - time.perf_counter()
                if remaining is not None and remaining <= 0:
                    raise queue_mod.Full(
                        f"tenant {tenant!r} is at its pending-query "
                        f"quota ({self.tenant_quota})")
                self._cond.wait(remaining)
                if self._closed:
                    raise RuntimeError("query service is closed")
                # the scheduler PRUNES a deque it drains
                # (_dispatch_locked), so the reference captured above
                # may be orphaned by now — re-resolve the live deque
                # before re-checking the predicate, or the append below
                # would land in a deque _pick never scans and silently
                # lose the query
                q = self._queues.setdefault(tenant, q)
            ticket = QueryTicket(tenant, root, sig, footprint)
            q.append(ticket)
            self._count(tenant, "submitted")
            self._cond.notify_all()
        return ticket

    # -- scheduler/worker side ------------------------------------------

    def _dispatch_locked(self, tenant: str) -> QueryTicket:
        ticket = self._queues[tenant].popleft()
        if not self._queues[tenant]:
            # prune drained queues so _pick's sort scans tenants with
            # PENDING work, not every tenant ever seen (tokens/counts
            # persist — they are per-tenant-cardinality, not per-query).
            # Safe against submitters blocked at quota: they re-resolve
            # the live deque after every wake (see submit()), so a
            # pruned reference is never appended into
            del self._queues[tenant]
        self._tokens[tenant] = self._tokens.get(tenant, 0) + 1
        self.admission.acquire(ticket.footprint)
        return ticket

    def _pick(self) -> Optional[QueryTicket]:
        """Next dispatchable ticket under the scheduler lock: tenants
        offered in token order (fewest dispatches first — the fairness
        accounting), first whose head query fits the free HBM share.
        None = nothing dispatchable right now.

        **Budget reservation**: a head that does not fit is only
        *transiently* blocked (admission rejected everything that can
        NEVER fit), but a sustained stream of smaller queries could
        re-consume every freed byte and block it forever.  Once the
        oldest unfitting head has waited ``reserve_after_s``, nothing
        else dispatches until it fits — running queries drain,
        ``hbm_in_use`` falls, and at worst an empty budget admits it.
        The clock starts when the query FIRST fails ``fits_now()`` as
        its tenant's head (``t_blocked``), not at submit: time queued
        behind the same tenant's earlier queries is ordinary waiting,
        and triggering off it would stall the whole service for a query
        that was never budget-starved."""
        now = time.perf_counter()
        tenants = sorted(
            (t for t, q in self._queues.items() if q),
            key=lambda t: (self._tokens.get(t, 0), t))
        starved: Optional[tuple] = None
        for t in tenants:
            head = self._queues[t][0]
            if not self.admission.fits_now(head.footprint):
                if head.t_blocked is None:
                    head.t_blocked = now
                if starved is None \
                        or head.t_blocked < starved[1].t_blocked:
                    starved = (t, head)
        if starved is not None and (
                now - starved[1].t_blocked >= self.reserve_after_s):
            if self.admission.fits_now(starved[1].footprint):
                return self._dispatch_locked(starved[0])
            return None                      # budget reserved: drain
        for t in tenants:
            if self.admission.fits_now(self._queues[t][0].footprint):
                return self._dispatch_locked(t)
        return None

    def _worker(self) -> None:
        from tempo_tpu.plan import executor as plan_executor

        while True:
            with self._cond:
                ticket = self._pick()
                while ticket is None:
                    if self._closed and not any(self._queues.values()):
                        return
                    # reservation is age-triggered: wake periodically
                    # while queries are PENDING so a starved head's
                    # clock is re-read; an idle service sleeps until a
                    # submit/close notifies instead of spinning
                    self._cond.wait(
                        timeout=0.25 if any(self._queues.values())
                        else None)
                    ticket = self._pick()
                # a dispatch frees a quota slot: wake blocked
                # submitters (completions notify elsewhere)
                self._cond.notify_all()
            ticket.t_start = time.perf_counter()
            try:
                with plan_cache.tenant_scope(ticket.tenant):
                    result = plan_executor.execute(ticket._root)
            except BaseException as e:  # noqa: BLE001 - delivered on the
                ticket._finish(exc=e)   # ticket; the worker lives on
                with self._cond:
                    self.admission.release(ticket.footprint)
                    self._count(ticket.tenant, "failed")
                    self._cond.notify_all()
                continue
            ticket._finish(result=result)
            with self._cond:
                self.admission.release(ticket.footprint)
                self._count(ticket.tenant, "completed")
                # bounded sample: percentiles are over the most recent
                # window, and a long-lived service does not grow a
                # float per query served forever
                self._latencies.setdefault(
                    ticket.tenant,
                    collections.deque(maxlen=self._LATENCY_WINDOW),
                ).append(ticket.latency_s)
                self._cond.notify_all()

    # -- lifecycle / metrics --------------------------------------------

    def close(self, timeout: Optional[float] = None) -> None:
        """Graceful drain: stop accepting, execute everything already
        queued, stop the workers.  ``timeout`` bounds the WHOLE drain —
        one shared deadline across the worker joins, not per worker."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        deadline = None if timeout is None else \
            time.perf_counter() + timeout
        for t in self._threads:
            t.join(None if deadline is None else
                   max(0.0, deadline - time.perf_counter()))

    def __enter__(self) -> "QueryService":
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False

    def stats(self) -> dict:
        """Per-tenant counts + latency percentiles, the shared cache's
        per-tenant traffic, budget occupancy, and the starvation audit
        (max/min completed-query ratio across tenants that submitted)."""
        from tempo_tpu import profiling
        from tempo_tpu.serve.executor import latency_percentiles

        with self._cond:
            tenants = {
                t: dict(c, **latency_percentiles(
                    list(self._latencies.get(t, ()))))
                for t, c in self._counts.items()
            }
            completed = [c["completed"] for c in self._counts.values()
                         if c["submitted"] > 0]
            ratio = None
            if completed and min(completed) > 0:
                ratio = round(max(completed) / min(completed), 3)
            return {
                "tenants": tenants,
                "starvation_ratio": ratio,
                "hbm_in_use": self.admission.hbm_in_use,
                "hbm_budget": self.admission.hbm_budget,
                "vmem_budget": self.admission.vmem_budget,
                "plan_cache": profiling.plan_cache_stats(),
            }
