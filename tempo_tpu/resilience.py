"""Failure detection, classification, retry/backoff, and resumable
pipelines.

The driver spec for this rebuild names "failure detection,
checkpoint/resume" as first-class (quoted in checkpoint.py:10).  The
save/load half lives in :mod:`tempo_tpu.checkpoint`; this module adds
the other half — the part Spark gives the reference for free through
task re-run recovery (SURVEY.md §5) and that a JAX-native stack must
supply itself:

* **Failure taxonomy** — :class:`FailureKind` plus :func:`classify`,
  mapping an arbitrary exception to the recovery action it admits.  A
  flaky NFS read (transient-io) is retryable; a checksum mismatch
  (corrupted-artifact) is not — it needs an older checkpoint; an XLA
  RESOURCE_EXHAUSTED (compile-oom) needs a smaller program, which the
  join planner arranges (join.py oversize bracketing).
* **Bounded retry** — :class:`RetryPolicy` (exponential backoff,
  jitter, attempt cap, wall-clock deadline) and :func:`retrying`, the
  wrapper the fallible host-side paths ride: Parquet ingest
  (io/ingest.py), checkpoint IO (checkpoint.py), multi-host init
  (parallel/multihost.py).
* **Resumable pipelines** — :func:`run_resumable` chains device ops
  with periodic checkpoints and, on restart, resumes from the newest
  *intact* checkpoint (corrupt ones are detected by checksum and
  skipped), recomputing only the steps after it.
* **Fault-domain primitives** — the serving executors (``serve/``) and
  the query service (``service/``) build their availability story from
  the pieces here: :class:`Deadline` (one wall-clock budget carried
  submit -> queue -> admission -> dispatch, dying with a *stage-named*
  :class:`DeadlineExceeded`), :class:`Cancelled` /
  :class:`ShutdownError` (a ticket always resolves — cancelled work
  never reaches a worker, a closed/dead plane fails its backlog by
  name instead of hanging callers), and :class:`CircuitBreaker` /
  :class:`QuarantinedError` (per-key quarantine of repeat offenders
  with half-open probes, so one poison pill cannot burn every retry
  budget).

Fault-injection coverage for all three lives in
:mod:`tempo_tpu.testing.faults` and the ``chaos``-marked test suite.
"""

from __future__ import annotations

import dataclasses
import enum
import errno
import functools
import logging
import os
import random
import re
import threading
import time
import zipfile
from typing import Callable, FrozenSet, Optional, Sequence

logger = logging.getLogger(__name__)


# ----------------------------------------------------------------------
# Failure taxonomy
# ----------------------------------------------------------------------

class FailureKind(enum.Enum):
    """What an exception *means* for recovery, independent of which
    library raised it."""

    TRANSIENT_IO = "transient-io"            # retry with backoff
    CORRUPTED_ARTIFACT = "corrupted-artifact"  # fall back to older data
    COMPILE_OOM = "compile-oom"              # shrink the program
    DEVICE_LOSS = "device-loss"              # re-init runtime / new mesh
    DEADLINE = "deadline"                    # give up, surface diagnostics
    PERMANENT = "permanent"                  # a bug or bad input: raise


class CheckpointError(ValueError):
    """A checkpoint could not be used: missing, corrupt (checksum or
    container failure), or written by a newer format version.  Carries
    the :class:`FailureKind` so retry wrappers know not to retry
    corruption (an older checkpoint is the recovery, not a re-read)."""

    def __init__(self, message: str,
                 kind: FailureKind = FailureKind.CORRUPTED_ARTIFACT):
        super().__init__(message)
        self.failure_kind = kind


class DeadlineExceeded(TimeoutError):
    """A wall-clock budget died: a retry loop ran past
    ``RetryPolicy.deadline_s``, or a serving/query ticket's
    :class:`Deadline` expired at a named plane stage (``stage`` says
    which one — queue wait, admission, dispatch...)."""

    failure_kind = FailureKind.DEADLINE

    def __init__(self, message: str, stage: Optional[str] = None):
        super().__init__(message)
        self.stage = stage


class Cancelled(RuntimeError):
    """A ticket was cancelled before a worker processed it.  Cancelled
    work releases its quota/queue slot and never reaches a worker; the
    caller's ``result()`` re-raises this by name.  Deliberate — never
    retried."""

    failure_kind = FailureKind.PERMANENT


class ShutdownError(RuntimeError):
    """The plane (executor / query service) shut down — or died — with
    this ticket still outstanding.  Every pending ticket is failed with
    this named error instead of hanging its caller forever on
    ``result()``."""

    failure_kind = FailureKind.PERMANENT


class QuarantinedError(RuntimeError):
    """Work was refused because its circuit breaker is OPEN: the same
    key (plan signature / stream member) failed
    ``TEMPO_TPU_BREAKER_THRESHOLD`` consecutive times and is
    quarantined until a half-open probe (one admission after
    ``TEMPO_TPU_BREAKER_COOLDOWN_S``) succeeds.  Fail-fast by design:
    a poison pill must not burn every retry budget in the plane."""

    failure_kind = FailureKind.PERMANENT

    def __init__(self, message: str, key=None,
                 retry_after_s: Optional[float] = None):
        super().__init__(message)
        self.key = key
        self.retry_after_s = retry_after_s


# ----------------------------------------------------------------------
# End-to-end deadlines
# ----------------------------------------------------------------------

class Deadline:
    """A wall-clock budget carried end to end through the serving and
    query planes: created at ``submit``, checked by name at every stage
    the ticket crosses (queue wait, admission wait, build, dispatch) so
    the caller learns *where* the budget died, not just that it did.

    Monotonic-clock based; ``None`` budgets are represented by the
    absence of a Deadline (``Deadline.after(None) is None``), so hot
    paths pay nothing when deadlines are off."""

    __slots__ = ("budget_s", "expires_at", "_clock")

    def __init__(self, budget_s: float,
                 clock: Callable[[], float] = time.monotonic):
        self.budget_s = float(budget_s)
        self._clock = clock
        self.expires_at = clock() + self.budget_s

    @classmethod
    def after(cls, budget_s, clock: Callable[[], float] = time.monotonic
              ) -> "Optional[Deadline]":
        """``None``/non-positive = no deadline; a :class:`Deadline`
        passes through unchanged (so call sites can take either)."""
        if budget_s is None:
            return None
        if isinstance(budget_s, Deadline):
            return budget_s
        if budget_s <= 0:
            return None
        return cls(budget_s, clock=clock)

    def remaining(self) -> float:
        return self.expires_at - self._clock()

    def expired(self) -> bool:
        return self._clock() >= self.expires_at

    def check(self, stage: str) -> None:
        """Raise :class:`DeadlineExceeded` naming ``stage`` when the
        budget is gone."""
        rem = self.remaining()
        if rem <= 0:
            raise DeadlineExceeded(
                f"deadline exceeded at stage {stage!r}: the "
                f"{self.budget_s:.3f}s budget ran out "
                f"{-rem:.3f}s ago", stage=stage)

    def __repr__(self) -> str:
        return (f"Deadline(budget_s={self.budget_s:.3f}, "
                f"remaining={self.remaining():.3f})")


# ----------------------------------------------------------------------
# Circuit breaker (per-key quarantine with half-open probes)
# ----------------------------------------------------------------------

class CircuitBreaker:  # thread-shared
    """Per-key failure quarantine for the serving/query planes.

    Keys are whatever identifies a repeat offender — a plan signature
    in the query service, a stream-member name in the cohort executor.
    ``threshold`` consecutive failures OPEN the circuit for that key:
    :meth:`allow` then raises :class:`QuarantinedError` immediately
    (fail-fast — the poison pill stops burning worker time and retry
    budgets).  After ``cooldown_s`` the circuit goes HALF-OPEN: exactly
    one probe is admitted; its success closes the circuit (counters
    reset), its failure re-opens it for another cooldown.  Thread-safe;
    the planes call it from submit paths and worker threads."""

    def __init__(self, threshold: Optional[int] = None,
                 cooldown_s: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        from tempo_tpu import config

        if threshold is None:
            threshold = config.get_int("TEMPO_TPU_BREAKER_THRESHOLD", 3)
        if cooldown_s is None:
            cooldown_s = config.get_float(
                "TEMPO_TPU_BREAKER_COOLDOWN_S", 5.0)
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        self._clock = clock
        self._lock = threading.Lock()
        # key -> [consecutive_failures, opened_at | None, probing]
        self._st = {}  # guarded-by: self._lock
        self.quarantined_total = 0  # guarded-by: self._lock
        self.trips = 0  # guarded-by: self._lock

    def state(self, key) -> str:
        """``"closed"`` / ``"open"`` / ``"half-open"`` for ``key``."""
        with self._lock:
            st = self._st.get(key)
            if st is None or st[1] is None:
                return "closed"
            if st[2] or self._clock() - st[1] >= self.cooldown_s:
                return "half-open"
            return "open"

    def allow(self, key, label: str = "work") -> None:
        """Admit or refuse ``key``.  Raises :class:`QuarantinedError`
        while the circuit is open (and while a half-open probe is
        already in flight); admits the single probe once the cooldown
        has elapsed."""
        with self._lock:
            st = self._st.get(key)
            if st is None or st[1] is None:
                return
            elapsed = self._clock() - st[1]
            if not st[2] and elapsed >= self.cooldown_s:
                st[2] = True        # this caller IS the half-open probe
                return
            self.quarantined_total += 1
            wait = max(0.0, self.cooldown_s - elapsed)
            raise QuarantinedError(
                f"{label} {key!r} is quarantined: {st[0]} consecutive "
                f"failures opened its circuit breaker"
                + (f"; half-open probe already in flight" if st[2]
                   else f"; next half-open probe in {wait:.2f}s"),
                key=key, retry_after_s=wait)

    def record(self, key, ok: bool) -> None:
        """Record one outcome for ``key`` (success closes a half-open
        circuit and resets counters; failure counts toward the
        threshold / re-opens a probing circuit)."""
        with self._lock:
            st = self._st.setdefault(key, [0, None, False])
            if ok:
                if st[0] or st[1] is not None:
                    self._st[key] = [0, None, False]
                return
            st[0] += 1
            if st[1] is not None or st[0] >= self.threshold:
                if st[1] is None:
                    self.trips += 1
                st[1] = self._clock()   # (re)open; probe slot resets
                st[2] = False

    def abandon(self, key) -> None:
        """The in-flight half-open probe for ``key`` will never report
        an outcome (cancelled / deadline-dead before dispatch): free
        the probe slot so the next :meth:`allow` can probe again —
        without this a vanished probe would quarantine the key
        forever.  No-op when ``key`` is not probing."""
        with self._lock:
            st = self._st.get(key)
            if st is not None and st[1] is not None and st[2]:
                st[2] = False

    def stats(self) -> dict:
        with self._lock:
            open_keys = [k for k, st in self._st.items()
                         if st[1] is not None]
            return {"open": sorted(map(str, open_keys)),
                    "trips": self.trips,
                    "quarantined_total": self.quarantined_total}


# errnos that indicate a transient environment problem, not a bug
_TRANSIENT_ERRNOS = frozenset(
    getattr(errno, name)
    for name in (
        "EAGAIN", "EINTR", "EBUSY", "ETIMEDOUT", "ECONNRESET",
        "ECONNABORTED", "ECONNREFUSED", "ENETRESET", "ENETUNREACH",
        "EHOSTUNREACH", "EPIPE", "EIO", "ESTALE",
    )
    if hasattr(errno, name)
)

# message heuristics for exceptions that arrive as bare RuntimeError /
# XlaRuntimeError strings (XLA does not export a typed hierarchy)
_OOM_PAT = re.compile(
    r"resource[ _]exhausted|out of memory|\boom\b|cannot allocate memory"
    r"|allocation .* (failed|exceeds)|exceeds the limit in memory",
    re.IGNORECASE,
)
_DEVICE_PAT = re.compile(
    r"device (?:lost|halted|failure|unavailable)|DEVICE_LOST"
    r"|data[ _]loss|chip (?:reboot|halt)|\bnccl\b|ici (?:link|failure)",
    re.IGNORECASE,
)
_DEADLINE_PAT = re.compile(
    r"deadline[ _]exceeded|timed[ _]?out|timeout", re.IGNORECASE
)
_TRANSIENT_PAT = re.compile(
    r"\bunavailable\b|connection (?:reset|refused|aborted)"
    r"|temporarily|try again|broken pipe",
    re.IGNORECASE,
)


def classify(exc: BaseException) -> FailureKind:
    """Map an exception to its :class:`FailureKind`.

    Precedence: an explicit ``failure_kind`` attribute on the exception
    wins (our own errors and injected faults self-describe); then typed
    checks (OSError errno, TimeoutError, zip/EOF container failures);
    then message heuristics for the string-typed XLA/runtime errors;
    then ``PERMANENT`` — unknown failures must surface, not retry."""
    kind = getattr(exc, "failure_kind", None)
    if isinstance(kind, FailureKind):
        return kind
    # errno before the TimeoutError type check: Python surfaces
    # OSError(ETIMEDOUT) AS TimeoutError, and a socket/NFS timeout is
    # transient weather (retry), unlike a logical deadline (give up)
    if isinstance(exc, OSError) and exc.errno in _TRANSIENT_ERRNOS:
        return FailureKind.TRANSIENT_IO
    if isinstance(exc, TimeoutError):
        return FailureKind.DEADLINE
    if isinstance(exc, (zipfile.BadZipFile, EOFError)):
        return FailureKind.CORRUPTED_ARTIFACT
    if isinstance(exc, MemoryError):
        return FailureKind.COMPILE_OOM
    if isinstance(exc, ConnectionError):
        return FailureKind.TRANSIENT_IO
    if isinstance(exc, OSError) and exc.errno == errno.ENOENT:
        return FailureKind.PERMANENT
    msg = str(exc)
    if _OOM_PAT.search(msg):
        return FailureKind.COMPILE_OOM
    if _DEVICE_PAT.search(msg):
        return FailureKind.DEVICE_LOSS
    if _DEADLINE_PAT.search(msg):
        return FailureKind.DEADLINE
    if _TRANSIENT_PAT.search(msg):
        return FailureKind.TRANSIENT_IO
    return FailureKind.PERMANENT


# ----------------------------------------------------------------------
# Retry / backoff
# ----------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Bounded exponential backoff with jitter and a wall-clock deadline.

    ``retry_on`` is the set of :class:`FailureKind` worth re-attempting;
    everything else re-raises immediately (retrying a checksum mismatch
    or a real bug only hides it).  ``deadline_s`` caps the *total* time
    the retry loop may consume — the loop never starts a sleep that
    would cross it."""

    max_attempts: int = 4
    base_delay_s: float = 0.1
    max_delay_s: float = 30.0
    multiplier: float = 2.0
    jitter: float = 0.5            # fraction of each delay randomized away
    deadline_s: Optional[float] = None
    retry_on: FrozenSet[FailureKind] = frozenset({FailureKind.TRANSIENT_IO})

    def delay_s(self, prior_failures: int, rng: random.Random) -> float:
        raw = min(self.max_delay_s,
                  self.base_delay_s * self.multiplier ** prior_failures)
        return raw * (1.0 - self.jitter * rng.random())


#: Default policy for host-side file IO (checkpoint + Parquet ingest).
DEFAULT_IO_POLICY = RetryPolicy(
    max_attempts=4, base_delay_s=0.05, max_delay_s=2.0, deadline_s=60.0,
)


def retrying(
    policy: Optional[RetryPolicy] = None,
    label: Optional[str] = None,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
    rng: Optional[random.Random] = None,
):
    """Decorator/wrapper giving a callable bounded retry semantics.

    Catches ``Exception`` only: simulated-kill faults
    (:class:`tempo_tpu.testing.faults.SimulatedKill`) and real signals
    derive from ``BaseException`` and always propagate.  Each retry is
    logged at WARNING with the classified kind; exhaustion logs at
    ERROR and re-raises the last failure (or raises
    :class:`DeadlineExceeded` when the wall clock, not the attempt
    count, ran out)."""
    pol = policy or DEFAULT_IO_POLICY
    _rng = rng or random.Random()

    def deco(fn):
        name = label or getattr(fn, "__qualname__", repr(fn))

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            start = clock()
            failures = 0
            while True:
                try:
                    return fn(*args, **kwargs)
                except Exception as exc:
                    kind = classify(exc)
                    failures += 1
                    if kind not in pol.retry_on:
                        raise
                    if failures >= pol.max_attempts:
                        logger.error(
                            "%s: giving up after %d attempt(s) (%s: %s)",
                            name, failures, kind.value, exc,
                        )
                        raise
                    delay = pol.delay_s(failures - 1, _rng)
                    elapsed = clock() - start
                    if pol.deadline_s is not None and \
                            elapsed + delay > pol.deadline_s:
                        logger.error(
                            "%s: retry deadline %.1fs exhausted after %d "
                            "attempt(s) (%s: %s)",
                            name, pol.deadline_s, failures, kind.value, exc,
                        )
                        raise DeadlineExceeded(
                            f"{name}: {elapsed:.1f}s elapsed of "
                            f"{pol.deadline_s:.1f}s retry deadline "
                            f"(last failure: {exc})"
                        ) from exc
                    logger.warning(
                        "%s: attempt %d/%d failed (%s: %s); retrying in "
                        "%.2fs", name, failures, pol.max_attempts,
                        kind.value, exc, delay,
                    )
                    sleep(delay)

        return wrapper

    return deco


def call_with_retry(fn, *args, policy: Optional[RetryPolicy] = None,
                    label: Optional[str] = None, **kwargs):
    """One-shot form of :func:`retrying` for call sites that don't want
    a decorated helper."""
    return retrying(policy, label=label)(fn)(*args, **kwargs)


# ----------------------------------------------------------------------
# Graceful degradation knobs (consumed by join.py)
# ----------------------------------------------------------------------

#: Merged-lane ceiling above which the AS-OF join degrades to the host
#: time-bracketing path instead of handing XLA a program it cannot
#: compile.  The measured failure: the lax.sort merge ladder OOM-killed
#: the compiler at ~205K merged lanes (BASELINE.md r3, VERDICT.md
#: missing #1); 192K leaves headroom below that cliff.
DEFAULT_MAX_MERGED_LANES = 196_608


def max_merged_lanes() -> int:
    """Merged-lane limit for a single AS-OF merge program.  Override
    with ``TEMPO_TPU_MAX_MERGED_LANES`` (ints only; smaller values force
    the bracketing fallback earlier, 0/negative disables the guard)."""
    from tempo_tpu import config

    env = config.get_int("TEMPO_TPU_MAX_MERGED_LANES")
    if env is not None:
        return env
    return DEFAULT_MAX_MERGED_LANES


# ----------------------------------------------------------------------
# Resumable pipelines
# ----------------------------------------------------------------------

def _apply_step(state, step):
    """A step is a callable ``frame -> frame``, a method name, or a
    ``(method_name, kwargs)`` tuple."""
    if callable(step):
        return step(state)
    if isinstance(step, str):
        return getattr(state, step)()
    name = step[0]
    kwargs = step[1] if len(step) > 1 else {}
    return getattr(state, name)(**kwargs)


def _step_label(step) -> str:
    if callable(step):
        return getattr(step, "__name__", repr(step))
    if isinstance(step, str):
        return step
    return str(step[0])


def _sig_canon(value) -> str:
    """Process-stable canonical string of one step kwarg: scalars by
    value (numpy scalars unwrapped — by type alone, two pipelines
    differing only in an np.int64 kwarg would collide and resume each
    other's state), containers recursively, everything else by TYPE
    only.  A bare ``repr`` would fold memory addresses into the
    signature for objects without a stable ``__repr__`` (a TSDF
    operand, say) — a restarted process would then refuse its OWN
    checkpoints."""
    import numpy as np

    if isinstance(value, np.generic) and value.shape == ():
        value = value.item()
    if value is None or isinstance(value, (bool, int, float, str)):
        return repr(value)
    if isinstance(value, (list, tuple)):
        return "[" + ",".join(_sig_canon(v) for v in value) + "]"
    if isinstance(value, dict):
        items = sorted(value.items(), key=lambda kv: str(kv[0]))
        return "{" + ",".join(f"{k}:{_sig_canon(v)}" for k, v in items) + "}"
    return f"<{type(value).__name__}>"


def pipeline_signature(steps: Sequence) -> str:
    """Stable signature of a ``run_resumable`` step chain, stamped into
    every step manifest so resume can refuse FOREIGN state by name
    (the silent-restore hazard: a stale ``ckpt_dir`` from a different
    pipeline restoring cleanly into this one).

    Covers step count, method names and canonical kwargs
    (:func:`_sig_canon` — stable across process restarts).  Callables
    canonicalize to their *position* only (two closures compiled from
    the same source are not provably the same step, and instrumented
    re-wraps of the same pipeline must keep resuming), so two
    all-callable chains of equal length collide — the hazard this
    guards is cross-pipeline shape drift, which always shows up in
    length or in the named steps."""
    import hashlib

    parts = []
    for step in steps:
        if callable(step):
            parts.append("<callable>")
        elif isinstance(step, str):
            parts.append(f"method:{step}")
        else:
            kwargs = step[1] if len(step) > 1 else {}
            parts.append(f"method:{step[0]}:{_sig_canon(dict(kwargs))}")
    h = hashlib.sha1(repr((len(parts), parts)).encode())
    return h.hexdigest()[:16]


def resume_signature(frame, steps: Sequence) -> str:
    """The signature :func:`run_resumable` stamps by default: the step
    chain (:func:`pipeline_signature`) PLUS the input frame's content
    fingerprint.  Steps alone would let a reused ``ckpt_dir`` restore
    a PREVIOUS run's retained final checkpoint when the same chain is
    re-run over new data — zero steps re-run, yesterday's output
    returned as today's.  The content fingerprint is the same one the
    plan barriers stamp (:func:`tempo_tpu.plan.checkpoints.
    source_fingerprint` — memoized, stable across restarts), so a
    crash-resumed pipeline re-fed the same bytes still matches its own
    checkpoints."""
    import hashlib

    from tempo_tpu.plan import checkpoints as plan_ckpt

    return hashlib.sha1(
        f"{pipeline_signature(steps)}|"
        f"{plan_ckpt.source_fingerprint(frame)}".encode()
    ).hexdigest()[:16]


def run_resumable(
    frame,
    steps: Sequence,
    ckpt_dir: str,
    every: int = 1,
    keep_last: int = 2,
    sharded: bool = False,
    signature: Optional[str] = None,
):
    """Run a chain of device ops with periodic checkpoints and
    crash-resume — the eager wrapper over the same signed-barrier
    machinery the plan executor's checkpoint nodes use
    (:mod:`tempo_tpu.plan.checkpoints`).

    ``steps`` is a sequence of callables ``frame -> frame`` (or
    ``(method_name, kwargs)`` tuples resolved against the frame).  After
    every ``every``-th step — and always after the last — the
    intermediate frame is checkpointed to ``ckpt_dir/step_NNNNN`` via
    :func:`tempo_tpu.checkpoint.save` (atomic, checksummed), its
    manifest stamped with the pipeline signature
    (:func:`resume_signature` — steps + input-frame content; or the
    caller's ``signature``) and the predecessor checkpoint's manifest
    CRC-32 (the chained-manifest scheme); older checkpoints beyond
    ``keep_last`` are pruned.

    On restart with the same ``ckpt_dir``, the newest intact,
    chain-consistent checkpoint STAMPED BY THIS PIPELINE is restored
    and only the steps after it re-run
    (:func:`tempo_tpu.checkpoint.resolve_step`): corrupt/truncated
    candidates and broken chain links fall back to older ones with a
    warning, but a checkpoint stamped by a *different* pipeline raises
    :class:`CheckpointError` by name instead of silently restoring
    foreign state.  Steps must be deterministic for the resumed result
    to be bit-identical to an uninterrupted run; all tempo-tpu device
    ops are.

    Checkpoint IO needs no extra wrapping here: every read/write
    primitive inside :mod:`tempo_tpu.checkpoint` already retries
    transient faults under :data:`DEFAULT_IO_POLICY` — one retry
    altitude, not nested loops."""
    from tempo_tpu import checkpoint

    if every < 1:
        raise ValueError(f"every must be >= 1, got {every}")
    os.makedirs(ckpt_dir, exist_ok=True)
    sig = signature or resume_signature(frame, steps)
    mesh = getattr(frame, "mesh", None)
    series_axis = getattr(frame, "series_axis", "series")
    time_axis = getattr(frame, "time_axis", None)

    state, done = frame, 0
    prev = None          # (step, manifest CRC) of the chain predecessor
    below = None
    while True:
        # resolve cheaply (manifest-only), verify the arrays ONCE in
        # load below; an intact-on-disk checkpoint this process cannot
        # load (corrupt arrays, a sharded save resumed single-process)
        # falls back to the next-older candidate
        hit = checkpoint.resolve_step(ckpt_dir, signature=sig,
                                      max_step=len(steps), verify=False,
                                      below_step=below)
        if hit is None:
            break
        step_no, path, _man = hit
        try:
            state = checkpoint.load(path, mesh=mesh,
                                    series_axis=series_axis,
                                    time_axis=time_axis)
        except (CheckpointError, ValueError) as e:
            logger.warning(
                "run_resumable: checkpoint %s unusable (%s); falling "
                "back to an older one", path, e)
            state, below = frame, step_no
            continue
        done = step_no
        prev = (step_no, checkpoint.manifest_crc(path))
        logger.info(
            "run_resumable: resumed after step %d/%d from %s",
            done, len(steps), path,
        )
        break

    for i in range(done, len(steps)):
        state = _apply_step(state, steps[i])
        if (i + 1) % every == 0 or i + 1 == len(steps):
            path = os.path.join(ckpt_dir, f"step_{i + 1:05d}")
            meta = {"pipeline_signature": sig, "step": i + 1,
                    "step_label": _step_label(steps[i])}
            if prev is not None:
                meta["prev_step"], meta["prev_manifest_crc"] = prev
            checkpoint.save(state, path, sharded=sharded, meta=meta)
            prev = (i + 1, checkpoint.manifest_crc(path))
            logger.info(
                "run_resumable: step %d/%d (%s) checkpointed to %s",
                i + 1, len(steps), _step_label(steps[i]), path,
            )
            checkpoint.prune(ckpt_dir, keep_last=keep_last)
    return state
