"""Transactional storage engine: crash-consistent clustered write-back.

The write-side fault domain (ROADMAP item 2): sharded Parquet
write-back of frames, distributed frames and query results as
*generations* of (series, time)-clustered segments, committed by
per-segment CRC'd manifests chained by predecessor CRC with a JSON
commit record written last, published by an atomic pointer swing — so
the previous table version survives ANY kill, a killed write resumes
with zero committed-segment re-writes, and torn/foreign/corrupt
staged state is refused by name.  ``compact`` merges small segments
into clustered large ones as a new transactional generation under
live readers.  See BUILDING.md "Storage engine".
"""

from tempo_tpu.store.compact import compact
from tempo_tpu.store.engine import (
    Store,
    StoreCommitError,
    StoreError,
    resolve_dataset_path,
    write_back,
)

__all__ = [
    "Store",
    "StoreError",
    "StoreCommitError",
    "compact",
    "resolve_dataset_path",
    "write_back",
]
