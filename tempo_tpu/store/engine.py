"""The transactional table engine behind ``tempo_tpu.store``.

On-disk layout of one table (all control files are ``_``-prefixed so
pyarrow dataset discovery ignores them; a generation directory IS a
plain Parquet dataset any engine can read)::

    <warehouse>/<table>/
      _CURRENT.json               # pointer: {"generation", "commit_crc"}
      gen_00000001/
        _staging.json             # write signature, stamped FIRST
        seg_00000.parquet         # clustered segment (sorted rows)
        _seg_00000.json           # segment commit sidecar, written LAST
        seg_00001.parquet
        _seg_00001.json           # chains _seg_00000.json by CRC-32
        _commit.json              # generation commit record, written LAST

Durability contract:

* a segment exists iff its ``_seg_NNNNN.json`` sidecar exists — the
  parquet file is staged ``.tmp`` → fsync → rename first, so the
  sidecar's presence is the commit record (the ingest shard-manifest
  discipline, io/ingest.py ``_ResumeLog``);
* sidecars are CHAINED: each records the CRC-32 of its predecessor
  sidecar, so a resume can prove the committed prefix is the one
  uninterrupted write, not an interleaving of two;
* ``_commit.json`` (written last, ``.tmp`` → fsync → rename) makes the
  generation readable; ``_CURRENT.json`` is then atomically replaced —
  the previous generation stays on disk (retention keeps
  ``TEMPO_TPU_STORE_KEEP_GENERATIONS``) so live readers holding its
  path stay bitwise-correct and any kill leaves the old table intact;
* a re-issued killed write verifies the staged signature (dataset
  path + schema + clustering spec + source-frame content fingerprint,
  via ``plan/checkpoints.source_fingerprint``), CRC-verifies the
  committed segment chain, and writes ONLY the segments after it —
  zero committed-segment re-writes;
* a foreign staging signature, a torn commit record, a broken chain
  link or a CRC-mismatched segment is REFUSED BY NAME
  (:class:`StoreError` / :class:`StoreCommitError` — both self-describe
  their :class:`~tempo_tpu.resilience.FailureKind` for
  ``resilience.classify``, and a torn commit is never transient);
  corruption is never silently rebuilt over.
"""

from __future__ import annotations

import glob
import hashlib
import json
import logging
import os
import re
import shutil
from typing import List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from tempo_tpu import checkpoint as ckpt
from tempo_tpu import config
from tempo_tpu.resilience import CheckpointError, FailureKind

logger = logging.getLogger(__name__)

FORMAT_VERSION = 1

CURRENT_NAME = "_CURRENT.json"
COMMIT_NAME = "_commit.json"
STAGING_NAME = "_staging.json"

_GEN_RE = re.compile(r"^gen_(\d{8})$")


class StoreError(CheckpointError):
    """The storage engine refused an operation: foreign staged state,
    a missing generation, or an ill-formed request.  Self-describes as
    ``PERMANENT`` by default — re-running the same call is never the
    recovery; the message names the explicit operator action that is."""

    def __init__(self, message: str,
                 kind: FailureKind = FailureKind.PERMANENT):
        super().__init__(message, kind=kind)


class StoreCommitError(StoreError):
    """Torn or corrupt commit state: an unparseable commit record or
    pointer, a broken segment-manifest chain link, or a CRC-mismatched
    segment.  Self-describes as ``CORRUPTED_ARTIFACT`` — a torn commit
    is NEVER transient (retrying the read re-reads the same bad bytes);
    the recovery is an older generation or a re-issued write."""

    def __init__(self, message: str):
        super().__init__(message, kind=FailureKind.CORRUPTED_ARTIFACT)


# ----------------------------------------------------------------------
# fsync'd atomic file primitives
# ----------------------------------------------------------------------

def _fsync_dir(d: str) -> None:
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:            # pragma: no cover - exotic filesystems
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _write_json_atomic(path: str, obj: dict) -> None:
    """``.tmp`` → fsync → rename: the file either holds the complete
    JSON document or does not exist; a kill can never leave a torn
    control file behind (so a torn one on disk is real corruption and
    is refused by name, not rebuilt over)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f, indent=2, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))


def _read_json(path: str, what: str) -> dict:
    try:
        with open(path) as f:
            obj = json.load(f)
    except FileNotFoundError:
        raise
    except (json.JSONDecodeError, UnicodeDecodeError, ValueError) as e:
        raise StoreCommitError(
            f"{what} {path!r} is torn/corrupt (does not parse as JSON: "
            f"{e}) — the file is written atomically, so this is real "
            f"corruption, not a crash artifact; restore from an older "
            f"generation or re-issue the write") from e
    if not isinstance(obj, dict):
        raise StoreCommitError(
            f"{what} {path!r} is not a JSON object — foreign file?")
    return obj


def _swing_pointer(tpath: str, gen_name: str, commit_crc: int) -> None:
    """Make a committed generation live: atomically replace the table
    pointer.  Module-level so the chaos campaign can kill exactly the
    window between the commit record and the swing."""
    _write_json_atomic(os.path.join(tpath, CURRENT_NAME), {
        "format_version": FORMAT_VERSION,
        "generation": gen_name,
        "commit_crc": commit_crc,
    })


def _write_segment(df: pd.DataFrame, path: str) -> int:
    """Stage one clustered segment: parquet to ``.tmp``, fsync, atomic
    rename.  Module-level so the chaos campaign can kill/count exactly
    the segment writes.  Returns the staged file's CRC-32."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    tmp = path + ".tmp"
    table = pa.Table.from_pandas(df, preserve_index=False)
    pq.write_table(table, tmp)
    with open(tmp, "rb") as f:
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_dir(os.path.dirname(path))
    return ckpt.file_crc(path)


def _write_seg_manifest(gen_dir: str, seq: int, man: dict) -> None:
    """Commit one segment: its sidecar appears (atomically) only after
    the parquet rename — module-level for the kill-between-files chaos
    phase."""
    _write_json_atomic(os.path.join(gen_dir, _seg_manifest_name(seq)),
                       man)


def _seg_name(seq: int) -> str:
    return f"seg_{seq:05d}.parquet"


def _seg_manifest_name(seq: int) -> str:
    return f"_seg_{seq:05d}.json"


def _json_scalar(v):
    """Key-range stats must ride JSON manifests: numpy scalars and
    timestamps to plain python."""
    if isinstance(v, (np.generic,)):
        v = v.item()
    if isinstance(v, (pd.Timestamp,)):
        return str(v)
    if isinstance(v, (int, float, str, bool)) or v is None:
        return v
    return str(v)


def _signature(table_path: str, schema: Sequence[Tuple[str, str]],
               sort_cols: Sequence[str], source_fp: str) -> str:
    """The write signature refusal keys on: dataset path + schema +
    clustering spec + source content fingerprint.  Any difference means
    a staged generation belongs to a DIFFERENT write."""
    blob = repr((os.path.abspath(table_path),
                 tuple((str(n), str(t)) for n, t in schema),
                 tuple(str(c) for c in sort_cols), str(source_fp)))
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def source_fingerprint(obj) -> str:
    """Content fingerprint of a write-back source: frames and
    distributed frames via the plan-checkpoint fingerprint
    (``plan/checkpoints.source_fingerprint``), bare DataFrames (query
    results) hashed the same way host frames are."""
    from tempo_tpu.dist import DistributedTSDF
    from tempo_tpu.frame import TSDF
    from tempo_tpu.plan import checkpoints as plan_ckpt

    if isinstance(obj, (TSDF, DistributedTSDF)):
        return plan_ckpt.source_fingerprint(obj)
    if isinstance(obj, pd.DataFrame):
        h = hashlib.sha1()
        h.update(repr(("df", tuple(obj.columns))).encode())
        h.update(np.ascontiguousarray(
            pd.util.hash_pandas_object(obj, index=False).to_numpy()
        ).tobytes())
        return h.hexdigest()[:16]
    raise TypeError(
        f"store.write_back accepts a TSDF, DistributedTSDF or pandas "
        f"DataFrame, got {type(obj).__name__}")


# ----------------------------------------------------------------------
# The engine
# ----------------------------------------------------------------------

class Store:
    """One warehouse directory of transactional generation tables.
    ``base_dir`` defaults to ``TEMPO_TPU_WAREHOUSE``."""

    def __init__(self, base_dir: Optional[str] = None):
        if base_dir is None:
            base_dir = config.get("TEMPO_TPU_WAREHOUSE",
                                  "tempo_tpu_warehouse")
        self.base_dir = str(base_dir)

    def table_path(self, table: str) -> str:
        return os.path.join(self.base_dir, str(table))

    # -- reading -------------------------------------------------------

    def current(self, table: str) -> Optional[Tuple[str, dict]]:
        """``(generation_name, commit_record)`` of the committed
        generation, or None for a table that has no pointer (never
        written / legacy layout).  A torn pointer, a pointer naming a
        generation without an intact commit record, or a commit CRC
        mismatch raises :class:`StoreCommitError` by name."""
        tpath = self.table_path(table)
        cur_path = os.path.join(tpath, CURRENT_NAME)
        if not os.path.exists(cur_path):
            return None
        cur = _read_json(cur_path, "store pointer")
        gen = cur.get("generation")
        want_crc = cur.get("commit_crc")
        if not isinstance(gen, str) or not _GEN_RE.match(gen) \
                or not isinstance(want_crc, int) \
                or isinstance(want_crc, bool):
            raise StoreCommitError(
                f"store pointer {cur_path!r} is malformed (generation="
                f"{gen!r}, commit_crc={want_crc!r}) — foreign or "
                f"corrupt pointer")
        commit = self._read_commit(os.path.join(tpath, gen), want_crc)
        return gen, commit

    def _read_commit(self, gen_dir: str, want_crc: Optional[int]) -> dict:
        cpath = os.path.join(gen_dir, COMMIT_NAME)
        if not os.path.isdir(gen_dir):
            raise StoreCommitError(
                f"store generation {gen_dir!r} named by the pointer "
                f"does not exist on disk")
        if not os.path.exists(cpath):
            raise StoreCommitError(
                f"store generation {gen_dir!r} has no commit record "
                f"({COMMIT_NAME}) — the generation never committed; "
                f"the pointer should not name it")
        if want_crc is not None:
            got = ckpt.file_crc(cpath)
            if got != int(want_crc):
                raise StoreCommitError(
                    f"torn commit: {cpath!r} has crc32 {got}, the "
                    f"pointer recorded {want_crc} — commit record and "
                    f"pointer disagree")
        commit = _read_json(cpath, "store commit record")
        fv = commit.get("format_version")
        if not isinstance(fv, int) or isinstance(fv, bool) \
                or "segments" not in commit:
            raise StoreCommitError(
                f"store commit record {cpath!r} is missing required "
                f"fields (integer format_version / segments) — "
                f"truncated or foreign file")
        if fv > FORMAT_VERSION:
            raise StoreError(
                f"store generation {gen_dir!r} has format_version {fv}, "
                f"newer than this library understands (expected <= "
                f"{FORMAT_VERSION}); upgrade tempo-tpu to read it")
        return commit

    def dataset_path(self, table: str) -> str:
        """The committed generation directory — a plain clustered
        Parquet dataset, the path ``io.ingest.from_parquet`` reads
        without a shuffle."""
        cur = self.current(table)
        if cur is None:
            raise StoreError(
                f"table {self.table_path(table)!r} has no committed "
                f"generation (no {CURRENT_NAME})")
        gen, _ = cur
        return os.path.join(self.table_path(table), gen)

    def verify(self, table: str) -> dict:
        """Strict integrity pass over the committed generation: every
        segment file CRC-32 against its commit record, every sidecar
        chain link.  Raises :class:`StoreCommitError` naming the first
        broken artifact; returns the commit record when intact."""
        gen, commit = self._require_current(table)
        gen_dir = os.path.join(self.table_path(table), gen)
        prev_crc = 0
        for seq, seg in enumerate(commit["segments"]):
            fp = os.path.join(gen_dir, seg["file"])
            if not os.path.exists(fp):
                raise StoreCommitError(
                    f"committed segment {seg['file']!r} is missing "
                    f"from {gen_dir!r}")
            got = ckpt.file_crc(fp)
            if got != int(seg["crc"]):
                raise StoreCommitError(
                    f"committed segment {fp!r} is corrupt: crc32 {got} "
                    f"!= recorded {seg['crc']}")
            man_path = os.path.join(gen_dir, _seg_manifest_name(seq))
            man = _read_json(man_path, "store segment manifest")
            if int(man.get("prev_manifest_crc", -1)) != prev_crc:
                raise StoreCommitError(
                    f"segment manifest chain broken at {man_path!r}: "
                    f"prev_manifest_crc {man.get('prev_manifest_crc')} "
                    f"!= predecessor crc32 {prev_crc}")
            prev_crc = ckpt.file_crc(man_path)
        if int(commit.get("chain_head_crc", -1)) != prev_crc:
            raise StoreCommitError(
                f"commit record of {gen_dir!r} records chain_head_crc "
                f"{commit.get('chain_head_crc')}, the sidecar chain "
                f"ends at {prev_crc}")
        return commit

    def _require_current(self, table: str) -> Tuple[str, dict]:
        cur = self.current(table)
        if cur is None:
            raise StoreError(
                f"table {self.table_path(table)!r} has no committed "
                f"generation (no {CURRENT_NAME})")
        return cur

    def read(self, table: str, columns: Optional[List[str]] = None,
             on_corrupt: str = "raise", batch_rows: int = 65536,
             verify: bool = False) -> pd.DataFrame:
        """Read the committed generation through the hardened ingest
        path: corrupt row groups surface
        :class:`~tempo_tpu.io.ingest.CorruptRowGroupError` with the
        exact ranges named (``on_corrupt="quarantine"`` reads around
        them), never an opaque pyarrow traceback.  ``verify=True``
        additionally CRC-checks every committed segment against the
        commit record first (:meth:`verify`)."""
        if verify:
            self.verify(table)
        return read_dataset_df(self.dataset_path(table),
                               columns=columns, on_corrupt=on_corrupt,
                               batch_rows=batch_rows)

    def generations(self, table: str) -> List[str]:
        """Generation directories on disk, oldest first (committed or
        staged)."""
        tpath = self.table_path(table)
        if not os.path.isdir(tpath):
            return []
        return sorted(d for d in os.listdir(tpath)
                      if _GEN_RE.match(d)
                      and os.path.isdir(os.path.join(tpath, d)))

    # -- writing -------------------------------------------------------

    def write_table(self, table: str, df: pd.DataFrame,
                    sort_cols: Sequence[str], *, source_fp: str,
                    segment_rows: Optional[int] = None,
                    keep_generations: Optional[int] = None) -> dict:
        """Write ``df`` as a new clustered generation of ``table`` and
        atomically swing the pointer to it.  Rows are stable-sorted by
        ``sort_cols`` (the ZORDER analogue: row-group statistics become
        selective for exactly those columns) and cut into segments of
        ``segment_rows`` (``TEMPO_TPU_STORE_SEGMENT_ROWS``), each
        committed by a chained CRC'd sidecar.

        Re-issuing a killed write (same frame, same table) resumes the
        staged generation: committed segments are CRC-verified and
        SKIPPED — the returned stats record ``segments_reused`` and the
        invariant ``segments_rewritten == 0``.  A staged generation
        with a different signature is refused by name (delete the
        staging directory, or call :meth:`discard_staging`, to
        overwrite with different data after a kill)."""
        tpath = self.table_path(table)
        os.makedirs(tpath, exist_ok=True)
        sort_cols = [c for c in sort_cols if c in df.columns]
        if sort_cols:
            df = df.sort_values(sort_cols, kind="stable")
        df = df.reset_index(drop=True)
        schema = [(c, str(df[c].dtype)) for c in df.columns]
        sig = _signature(tpath, schema, sort_cols, source_fp)
        if segment_rows is None:
            segment_rows = config.get_int("TEMPO_TPU_STORE_SEGMENT_ROWS",
                                          1_048_576)
        segment_rows = max(1, int(segment_rows))

        cur = self.current(table)
        if cur is not None and cur[1].get("signature") == sig:
            # this exact write (same content fingerprint, schema and
            # clustering spec) IS the committed generation already — a
            # re-issue after a kill that landed past the pointer swing,
            # or a verbatim retry.  Idempotent: zero writes.
            gen_name, commit = cur
            return {"path": os.path.join(tpath, gen_name),
                    "generation": gen_name,
                    "rows": int(commit["rows"]),
                    "segments": len(commit["segments"]),
                    "segments_reused": len(commit["segments"]),
                    "segments_rewritten": 0, "resumed": True,
                    "signature": sig}
        cur_id = int(_GEN_RE.match(cur[0]).group(1)) if cur else 0
        staged = self._find_staging(tpath, cur_id)
        reused = 0
        if staged is not None:
            gen_dir, st = staged
            if st is None:
                # killed before the signature stamp: nothing was
                # committed, the residue carries no promises — discard
                logger.warning("store: discarding unsigned staging "
                               "residue %s", gen_dir)
                shutil.rmtree(gen_dir)
                staged = None
            elif st.get("signature") != sig:
                raise StoreError(
                    f"staged generation {gen_dir!r} was written by a "
                    f"DIFFERENT write (staged signature "
                    f"{st.get('signature')!r} != {sig!r}: the "
                    f"signature folds dataset path, schema, clustering "
                    f"spec and source-frame content fingerprint) — "
                    f"refusing to resume onto foreign staged state; "
                    f"re-issue the original write, or discard the "
                    f"staging with Store.discard_staging({table!r})")
        if staged is not None:
            gen_dir, st = staged
            gen_name = os.path.basename(gen_dir)
            # resume continues the STAGED plan: its segment size, not
            # today's knob — chunk boundaries must line up exactly
            segment_rows = int(st["segment_rows"])
            resumed = True
        else:
            gen_name = f"gen_{cur_id + 1:08d}"
            gen_dir = os.path.join(tpath, gen_name)
            os.makedirs(gen_dir)
            st = {
                "format_version": FORMAT_VERSION,
                "signature": sig,
                "segment_rows": segment_rows,
                "sort_cols": list(sort_cols),
                "schema": [list(s) for s in schema],
                "source": str(source_fp),
                "rows": int(len(df)),
            }
            _write_json_atomic(os.path.join(gen_dir, STAGING_NAME), st)
            resumed = False

        n_segments = max(1, -(-len(df) // segment_rows))
        if os.path.exists(os.path.join(gen_dir, COMMIT_NAME)):
            # killed between commit and pointer swing: everything is
            # already durable — verify and swing, zero writes
            commit = self._read_commit(gen_dir, None)
            if commit.get("signature") != sig:
                raise StoreError(
                    f"committed staging {gen_dir!r} carries a foreign "
                    f"signature {commit.get('signature')!r} != {sig!r}")
            reused = len(commit["segments"])
        else:
            reused, prev_crc = self._verify_staged_segments(
                gen_dir, sig, n_segments)
            segments = self._staged_segment_records(gen_dir, reused)
            key_col = sort_cols[0] if sort_cols else None
            ts_col = sort_cols[-1] if sort_cols else None
            for seq in range(reused, n_segments):
                chunk = df.iloc[seq * segment_rows:
                                (seq + 1) * segment_rows]
                seg_file = _seg_name(seq)
                crc = _write_segment(chunk,
                                     os.path.join(gen_dir, seg_file))
                man = {
                    "format_version": FORMAT_VERSION,
                    "file": seg_file,
                    "seq": seq,
                    "rows": int(len(chunk)),
                    "crc": crc,
                    "signature": sig,
                    "prev_manifest_crc": prev_crc,
                    "key_min": _json_scalar(
                        chunk[key_col].iloc[0]) if key_col and len(chunk)
                    else None,
                    "key_max": _json_scalar(
                        chunk[key_col].iloc[-1]) if key_col and len(chunk)
                    else None,
                    "ts_min": _json_scalar(
                        chunk[ts_col].iloc[0]) if ts_col and len(chunk)
                    else None,
                    "ts_max": _json_scalar(
                        chunk[ts_col].iloc[-1]) if ts_col and len(chunk)
                    else None,
                }
                _write_seg_manifest(gen_dir, seq, man)
                prev_crc = ckpt.file_crc(
                    os.path.join(gen_dir, _seg_manifest_name(seq)))
                man["manifest_crc"] = prev_crc
                segments.append(man)
            commit = {
                "format_version": FORMAT_VERSION,
                "signature": sig,
                "rows": int(len(df)),
                "sort_cols": list(sort_cols),
                "schema": [list(s) for s in schema],
                "source": str(source_fp),
                "segments": [
                    {"file": s["file"], "rows": int(s["rows"]),
                     "crc": int(s["crc"]),
                     "manifest_crc": int(s["manifest_crc"]),
                     "key_min": s.get("key_min"),
                     "key_max": s.get("key_max")}
                    for s in segments],
                "chain_head_crc": prev_crc,
            }
            _write_json_atomic(os.path.join(gen_dir, COMMIT_NAME),
                               commit)
        commit_crc = ckpt.file_crc(os.path.join(gen_dir, COMMIT_NAME))
        _swing_pointer(tpath, gen_name, commit_crc)
        self._prune_generations(tpath, gen_name, keep_generations)
        logger.info(
            "store: committed %s/%s (%d rows, %d segments, %d reused%s)",
            table, gen_name, len(df), n_segments, reused,
            ", resumed" if resumed else "")
        return {
            "path": tpath, "generation": gen_name,
            "rows": int(len(df)), "segments": int(n_segments),
            "segments_reused": int(reused),
            "segments_rewritten": 0,
            "resumed": bool(resumed), "signature": sig,
        }

    def _find_staging(self, tpath: str, cur_id: int):
        """Newest staging generation (id > committed, no commit
        record): ``(dir, staging_record_or_None)``."""
        for name in reversed(sorted(os.listdir(tpath))
                             if os.path.isdir(tpath) else []):
            m = _GEN_RE.match(name)
            if not m or int(m.group(1)) <= cur_id:
                continue
            gen_dir = os.path.join(tpath, name)
            if not os.path.isdir(gen_dir):
                continue
            sp = os.path.join(gen_dir, STAGING_NAME)
            try:
                st = _read_json(sp, "store staging record")
            except FileNotFoundError:
                st = None
            return gen_dir, st
        return None

    def _verify_staged_segments(self, gen_dir: str, sig: str,
                                n_segments: int) -> Tuple[int, int]:
        """Walk the staged sidecar chain: ``(committed_count,
        chain_head_crc)``.  The committed prefix must verify exactly —
        a torn sidecar, broken chain link, foreign signature or
        CRC-mismatched segment file is refused by name (a kill cannot
        produce any of those states; rename-atomicity means they are
        corruption)."""
        reused = 0
        prev_crc = 0
        for seq in range(n_segments):
            man_path = os.path.join(gen_dir, _seg_manifest_name(seq))
            if not os.path.exists(man_path):
                break               # first uncommitted segment
            man = _read_json(man_path, "store segment manifest")
            if man.get("signature") != sig:
                raise StoreError(
                    f"staged segment manifest {man_path!r} carries a "
                    f"foreign signature {man.get('signature')!r} != "
                    f"{sig!r} — refusing to count it as committed")
            if int(man.get("prev_manifest_crc", -1)) != prev_crc:
                raise StoreCommitError(
                    f"staged segment chain broken at {man_path!r}: "
                    f"prev_manifest_crc {man.get('prev_manifest_crc')} "
                    f"!= predecessor sidecar crc32 {prev_crc}")
            seg_path = os.path.join(gen_dir, man["file"])
            if not os.path.exists(seg_path):
                raise StoreCommitError(
                    f"committed segment {seg_path!r} is missing though "
                    f"its sidecar {man_path!r} exists — the sidecar is "
                    f"written after the segment rename, so this is "
                    f"corruption, not a crash artifact")
            got = ckpt.file_crc(seg_path)
            if got != int(man["crc"]):
                raise StoreCommitError(
                    f"committed segment {seg_path!r} is corrupt: crc32 "
                    f"{got} != sidecar-recorded {man['crc']}")
            prev_crc = ckpt.file_crc(man_path)
            reused += 1
        # stray uncommitted residue past the verified prefix (partial
        # parquet, .tmp files): superseded by the re-write
        for p in glob.glob(os.path.join(gen_dir, "*.tmp")):
            os.remove(p)
        for seq in range(reused, n_segments + 1):
            stray = os.path.join(gen_dir, _seg_name(seq))
            if os.path.exists(stray):
                os.remove(stray)
        return reused, prev_crc

    def _staged_segment_records(self, gen_dir: str,
                                reused: int) -> List[dict]:
        out = []
        for seq in range(reused):
            man_path = os.path.join(gen_dir, _seg_manifest_name(seq))
            man = _read_json(man_path, "store segment manifest")
            man["manifest_crc"] = ckpt.file_crc(man_path)
            out.append(man)
        return out

    def _prune_generations(self, tpath: str, current_gen: str,
                           keep: Optional[int]) -> None:
        """Retention: keep the newest ``keep`` generations (default
        ``TEMPO_TPU_STORE_KEEP_GENERATIONS``, min 1 — the committed one
        is never pruned).  Keeping >= 2 is what lets readers opened on
        generation N stay bitwise-correct while N+1 commits."""
        if keep is None:
            keep = config.get_int("TEMPO_TPU_STORE_KEEP_GENERATIONS", 2)
        keep = max(1, int(keep))
        gens = sorted(d for d in os.listdir(tpath) if _GEN_RE.match(d))
        cur_id = int(_GEN_RE.match(current_gen).group(1))
        # stale staging above current cannot exist here (it just
        # committed); anything else beyond the keep window goes
        victims = [g for g in gens
                   if int(_GEN_RE.match(g).group(1)) <= cur_id][:-keep]
        for g in victims:
            logger.info("store: pruning old generation %s/%s (keep=%d)",
                        tpath, g, keep)
            shutil.rmtree(os.path.join(tpath, g), ignore_errors=True)

    def discard_staging(self, table: str) -> bool:
        """Explicitly drop a staged (uncommitted) generation — the
        named operator action the foreign-staging refusal points at."""
        tpath = self.table_path(table)
        cur = self.current(table)
        cur_id = int(_GEN_RE.match(cur[0]).group(1)) if cur else 0
        staged = self._find_staging(tpath, cur_id)
        if staged is None:
            return False
        shutil.rmtree(staged[0])
        return True


# ----------------------------------------------------------------------
# Module-level conveniences
# ----------------------------------------------------------------------

def write_back(source, table: str, *, base_dir: Optional[str] = None,
               ts_col: Optional[str] = None,
               partition_cols: Optional[Sequence[str]] = None,
               optimization_cols: Optional[Sequence[str]] = None,
               segment_rows: Optional[int] = None) -> dict:
    """Transactional clustered write-back of a frame, a distributed
    frame, or a query-result DataFrame.  Clustering is (series, time):
    partition cols + optimization cols + the derived ``event_time`` —
    the layout ``io.writer.write`` has always produced, now committed
    as a generation."""
    from tempo_tpu.dist import DistributedTSDF
    from tempo_tpu.frame import TSDF

    fp = source_fingerprint(source)
    if isinstance(source, DistributedTSDF):
        frame = source.collect()
    elif isinstance(source, TSDF):
        frame = source
    else:
        if ts_col is None:
            raise ValueError(
                "write_back of a bare DataFrame needs ts_col")
        frame = TSDF(source, ts_col=ts_col,
                     partition_cols=list(partition_cols or []))
    df, sort_cols = clustered_frame(frame, optimization_cols)
    return Store(base_dir).write_table(
        table, df, sort_cols, source_fp=fp, segment_rows=segment_rows)


def clustered_frame(tsdf, optimization_cols=None):
    """Derive the reference writer's columns (io.py:29-36 parity:
    ``event_dt`` date string + ``event_time`` HHMMSS.fff double,
    rotated to the front) and the clustering sort spec."""
    df = tsdf.df.copy()
    ts = pd.to_datetime(df[tsdf.ts_col])
    df["event_dt"] = ts.dt.date.astype(str)
    df["event_time"] = (
        ts.dt.hour * 10000 + ts.dt.minute * 100 + ts.dt.second
        + ts.dt.microsecond / 1e6
    ).astype(float)
    cols = list(df.columns)
    df = df[cols[-1:] + cols[:-1]]
    opt_cols = list(optimization_cols or []) + ["event_time"]
    sort_cols = [c for c in list(tsdf.partitionCols) + opt_cols
                 if c in df.columns]
    return df, sort_cols


def resolve_dataset_path(path: str) -> str:
    """Store-aware path resolution: a table directory holding a
    ``_CURRENT.json`` pointer resolves to its committed generation
    directory (verifying the pointer/commit pair, refusing torn state
    by name); any other path is returned unchanged.  ``from_parquet``
    and ``io.writer.read`` route through this, so a store table is
    ingestible by the exact path ``write`` returned."""
    cur_path = os.path.join(path, CURRENT_NAME)
    if not os.path.exists(cur_path):
        return path
    cur = _read_json(cur_path, "store pointer")
    gen = cur.get("generation")
    want_crc = cur.get("commit_crc")
    if not isinstance(gen, str) or not _GEN_RE.match(gen):
        raise StoreCommitError(
            f"store pointer {cur_path!r} is malformed "
            f"(generation={gen!r})")
    gen_dir = os.path.join(path, gen)
    cpath = os.path.join(gen_dir, COMMIT_NAME)
    if not os.path.exists(cpath):
        raise StoreCommitError(
            f"store pointer {cur_path!r} names generation {gen!r} "
            f"which has no commit record")
    if isinstance(want_crc, int) and not isinstance(want_crc, bool):
        got = ckpt.file_crc(cpath)
        if got != want_crc:
            raise StoreCommitError(
                f"torn commit: {cpath!r} has crc32 {got}, the pointer "
                f"recorded {want_crc}")
    return gen_dir


def read_dataset_df(path: str, columns: Optional[List[str]] = None,
                    on_corrupt: str = "raise",
                    batch_rows: int = 65536) -> pd.DataFrame:
    """Read a Parquet dataset directory through the hardened ingest
    machinery (``io/ingest._iter_batches``): deadline-free, but corrupt
    row groups surface :class:`~tempo_tpu.io.ingest.CorruptRowGroupError`
    with exact ranges (``on_corrupt="quarantine"`` reads around them)
    instead of an opaque pyarrow traceback."""
    import pyarrow as pa

    from tempo_tpu.io import ingest

    if on_corrupt not in ("raise", "quarantine"):
        raise ValueError(
            f"on_corrupt must be 'raise' or 'quarantine', got "
            f"{on_corrupt!r}")
    ctx = ingest._IngestCtx(on_corrupt=on_corrupt)
    ds = ingest._dataset(path, ctx)
    cols = list(columns) if columns is not None else None
    batches = list(ingest._iter_batches(ds, cols, None, batch_rows, ctx,
                                        stage="store-read"))
    ctx.raise_if_corrupt()
    schema = ds.schema if cols is None else pa.schema(
        [ds.schema.field(c) for c in cols])
    if not batches:
        return pa.Table.from_batches([], schema).to_pandas()
    return pa.Table.from_batches(batches, schema).to_pandas()
