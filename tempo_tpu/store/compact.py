"""Background compaction: small segments merge into clustered large
ones as a transactional NEW generation.

Compaction is just another :meth:`~tempo_tpu.store.engine.Store.
write_table` — the merged rows stage as generation N+1 with a
signature whose source fingerprint is ``compact:<gen N>:<chain head
CRC>`` (deterministic: re-running a killed compaction resumes the same
staged plan, committed merge segments reused), commit, then the
pointer swings.  Until the swing, readers resolve exactly generation
N; after it, exactly N+1 — never a blend.  Retention
(``TEMPO_TPU_STORE_KEEP_GENERATIONS`` >= 2) keeps N on disk, so a
reader that resolved its dataset path before the swing keeps reading
bitwise-identical files after it.
"""

from __future__ import annotations

import logging
from typing import Optional

from tempo_tpu import config
from tempo_tpu.store.engine import Store

logger = logging.getLogger(__name__)


def compact(table: str, *, base_dir: Optional[str] = None,
            target_rows: Optional[int] = None,
            min_segments: Optional[int] = None) -> Optional[dict]:
    """Merge the committed generation's segments into fewer, larger
    clustered ones.  Returns the new generation's write stats, or None
    when the table is already compact (fewer than ``min_segments``
    segments, default ``TEMPO_TPU_STORE_COMPACT_MIN_SEGMENTS``).

    Safe under live traffic and kills: the merge is a transactional
    new generation — a compactor killed mid-merge leaves the pointer
    (and every reader) on generation N; re-running it resumes the
    staged merge with zero committed-segment re-writes."""
    store = Store(base_dir)
    gen, commit = store._require_current(table)
    if min_segments is None:
        min_segments = config.get_int(
            "TEMPO_TPU_STORE_COMPACT_MIN_SEGMENTS", 2)
    if len(commit["segments"]) < max(2, int(min_segments)):
        return None
    if target_rows is None:
        target_rows = config.get_int("TEMPO_TPU_STORE_SEGMENT_ROWS",
                                     1_048_576) * 8
    # strict read: a compactor must never launder a corrupt segment
    # into a fresh-looking generation
    df = store.read(table, verify=True)
    stats = store.write_table(
        table, df, commit.get("sort_cols") or [],
        source_fp=f"compact:{gen}:{int(commit['chain_head_crc'])}",
        segment_rows=int(target_rows))
    logger.info("store: compacted %s %s (%d segments) -> %s (%d)",
                table, gen, len(commit["segments"]),
                stats["generation"], stats["segments"])
    stats["compacted_from"] = gen
    return stats
