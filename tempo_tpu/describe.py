"""TSDF.describe (parity: python/tempo/tsdf.py:384-431).

Produces the same 7-row summary table: a ``global`` row (unique series
count, min/max timestamp, granularity classification) followed by the
classic count/mean/stddev/min/max describe rows and a
``missing_vals_pct`` row.  Granularity uses the reference's modular
classifier over the double-seconds timestamp (tsdf.py:409-413).
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from tempo_tpu import packing


def _fmt(v):
    return None if v is None or (isinstance(v, float) and np.isnan(v)) else str(v)


def col_describe_series(s: pd.Series) -> dict:
    """count/mean/stddev/min/max of one column, Spark describe style
    (strings get count + lexicographic min/max)."""
    n = int(s.notna().sum())
    if pd.api.types.is_numeric_dtype(s.dtype) and not \
            pd.api.types.is_bool_dtype(s.dtype):
        vals = pd.to_numeric(s, errors="coerce")
        return {
            "count": str(n),
            "mean": _fmt(float(vals.mean())) if n else None,
            "stddev": _fmt(float(vals.std(ddof=1))) if n > 1 else None,
            "min": _fmt(vals.min()) if n else None,
            "max": _fmt(vals.max()) if n else None,
        }
    non_null = s.dropna()
    return {
        "count": str(n),
        "mean": None,
        "stddev": None,
        "min": _fmt(non_null.min()) if n else None,
        "max": _fmt(non_null.max()) if n else None,
    }


def classify_granularity(has_frac, sub_minute, sub_hour, sub_day) -> str:
    """The reference's finest-unit classifier (tsdf.py:409-413) from
    precomputed any() flags."""
    if has_frac:
        return "millis"
    if sub_minute:
        return "seconds"
    if sub_hour:
        return "minutes"
    if sub_day:
        return "hours"
    return "days"


def assemble_table(stat_cols, stats, missing, unique_ts, min_ts, max_ts,
                   granularity) -> pd.DataFrame:
    """The 7-row describe table from precomputed per-column stats —
    shared by the host path and the device-reduced distributed path."""
    rows = [{
        "summary": "global",
        "unique_ts_count": str(unique_ts),
        "min_ts": str(min_ts),
        "max_ts": str(max_ts),
        "granularity": granularity,
        **{c: " " for c in stat_cols},
    }]
    for stat in ("count", "mean", "stddev", "min", "max"):
        rows.append({
            "summary": stat,
            "unique_ts_count": " ",
            "min_ts": " ",
            "max_ts": " ",
            "granularity": " ",
            **{c: stats[c][stat] for c in stat_cols},
        })
    rows.append({
        "summary": "missing_vals_pct",
        "unique_ts_count": " ",
        "min_ts": " ",
        "max_ts": " ",
        "granularity": " ",
        **{c: str(round(missing[c], 2)) for c in stat_cols},
    })
    return pd.DataFrame(rows)


def describe(tsdf) -> pd.DataFrame:
    df = tsdf.df
    ts_col = tsdf.ts_col
    double_ts_col = ts_col + "_dbl"
    ts_sec = packing.series_to_ns(df[ts_col]) / packing.NS_PER_S

    # columns summarised: everything except the raw timestamp col, plus
    # the derived double view of it (tsdf.py:393-400)
    work = df.drop(columns=[ts_col]).copy()
    work[double_ts_col] = ts_sec
    stat_cols = list(work.columns)

    stats = {c: col_describe_series(work[c]) for c in stat_cols}
    missing = {
        c: 100.0 * float(work[c].isna().sum()) / max(len(work), 1) for c in stat_cols
    }

    # granularity classifier (tsdf.py:409-413): finest unit present
    frac = ts_sec - np.floor(ts_sec)
    gran = classify_granularity(
        (frac > 0).any(),
        (np.mod(ts_sec, 60) != 0).any(),
        (np.mod(ts_sec, 3600) != 0).any(),
        (np.mod(ts_sec, 86400) != 0).any(),
    )

    if tsdf.partitionCols:
        unique_ts = int(df[tsdf.partitionCols].drop_duplicates().shape[0])
    else:
        unique_ts = 1

    return assemble_table(stat_cols, stats, missing, unique_ts,
                          df[ts_col].min(), df[ts_col].max(), gran)
