"""Standing queries: one registered plan, answered forever.

``StandingQueryEngine.register`` takes a planned method chain or a
PR-18 SQL statement over :class:`~tempo_tpu.query.unified.StreamTable`
frames and turns it into a **standing query**: every admitted push
fans out to the subscription as an incremental *delta*, and the
accumulated standing result is **bitwise identical** to re-running the
registered plan over the concatenated history at every push boundary.
The split pass (:mod:`tempo_tpu.query.split`) decides how each
subscription is served:

* **stateless** — row-local suffix over the new rows, no device state;
* **delta** — the serving plane's carries: EMA subscriptions ride a
  shared :class:`~tempo_tpu.serve.cohort.StreamCohort` (one
  :class:`~tempo_tpu.serve.cohort.CohortMember` per subscription,
  dispatched through a :class:`~tempo_tpu.serve.executor.CohortExecutor`
  with AOT-compiled, shape-bucketed step programs — steady state is
  zero-recompile, observable in ``profiling.plan_cache_stats``);
  AS-OF join subscriptions dispatch the same plane machinery and
  additionally keep exact-dtype host index carries, because the batch
  join gathers right values in their SOURCE dtype (float64, datetimes,
  objects) while the serving plane's state is f32 — the carries are
  per-(series, column) last-valid right-row indices, O(1) per tick;
* **remainder** — the full canonical plan re-runs over the unified
  scan every ``TEMPO_TPU_STANDING_REMAINDER_EVERY`` boundaries
  (``StandingPlan.reason`` names what forced the fallback).

Delivery is asynchronous: ``push`` admits against the engine's
merged-stream feed watermarks (the ``serve.stream.admit_batch`` rule —
late ticks are rejected by name with
:class:`~tempo_tpu.serve.stream.LateTickError`, never reordered),
commits the table tail, and hands the batch to the delivery worker.
The worker submits every subscription's ticks FIRST and awaits them
after — concurrent subscriptions coalesce into batched cohort
dispatches — then pushes a :class:`Notification` into each
subscription's bounded queue.  Backpressure is per subscriber: a full
queue drops the OLDEST notification (counted on
``Subscription.dropped``) instead of stalling the fleet;
``Subscription.result()`` is always exact regardless of drops.
Deadlines (:class:`~tempo_tpu.resilience.Deadline`) ride the push end
to end; an expired delivery fails ONLY the affected subscription (a
missed delta would silently break the bitwise contract, so the
subscription fails loudly instead of drifting).

``snapshot_subscription`` / ``resume_subscription`` persist a standing
subscription as a ``kind="standing_state"`` artifact (per-table
cursors + the serving plane's slot carries, bit-for-bit) so a killed
engine resumes mid-stream with a byte-identical tail.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import queue
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from tempo_tpu import config
from tempo_tpu.plan import ir
from tempo_tpu.query import split as qsplit
from tempo_tpu.query.unified import StreamTable
from tempo_tpu.resilience import Deadline
from tempo_tpu.serve.stream import LateTickError, _SIDE_LEFT, _SIDE_RIGHT

__all__ = ["StandingQueryEngine", "Subscription", "Notification",
           "snapshot_subscription", "resume_subscription"]

_REPLAY_CHUNK = 4096


@dataclasses.dataclass
class Notification:
    """One delivery to a subscriber.  ``kind``: ``"catchup"`` (the
    register-time replay of everything already in the tables),
    ``"delta"`` (one push boundary's new result rows, suffix applied),
    ``"refresh"`` (a remainder subscription's periodic full re-run), or
    ``"error"`` (the subscription failed; ``error`` holds why)."""

    kind: str
    boundary: int
    frame: Optional[pd.DataFrame]
    error: Optional[BaseException] = None


def _suffix_df(plan: qsplit.StandingPlan, tsdf):
    """Apply the plan's row-local suffix to a TSDF and return the
    result DataFrame (row-local ops commute with every reordering the
    delta path performs, which is what makes per-delta application ==
    one application over the sorted concatenation)."""
    from tempo_tpu import plan as plan_mod
    from tempo_tpu.plan import executor as pexec

    with plan_mod.suspended():
        for n in plan.suffix:
            tsdf = pexec._eval_op(n, [tsdf])
    return tsdf.df if hasattr(tsdf, "df") else tsdf


def _run_batch(root: ir.Node, pinned: Dict[str, pd.DataFrame]):
    """Execute the canonical plan with every ``unified_scan`` replaced
    by a plain host source over a pinned snapshot — the batch twin /
    remainder program.  Returns the result TSDF."""
    from tempo_tpu.frame import TSDF
    from tempo_tpu.plan import executor as pexec

    memo: Dict[int, ir.Node] = {}

    def rec(n: ir.Node) -> ir.Node:
        got = memo.get(id(n))
        if got is not None:
            return got
        if n.op == "unified_scan":
            t = n.payload.table
            out = ir.Node("source", payload=TSDF(
                pinned[t.name], t.ts_col, t.partitionCols,
                t.sequence_col or None))
        else:
            ins = tuple(rec(c) for c in n.inputs)
            out = ir.Node(n.op, params=dict(n.params), inputs=ins,
                          payload=n.payload, objs=n.objs)
        memo[id(n)] = out
        return out

    clone = rec(root)
    exe = pexec.Executable(clone)
    return exe.run([s.payload for s in clone.sources()])


class _JoinSeries:
    """Exact-dtype AS-OF carries for one series of a join subscription:
    the last right row overall, the per-column last VALID right row
    (``skipNulls``), and — under ``maxLookback`` — the trailing window
    of merged-stream entries (``rowsBetween(-maxLookback, 0)`` on the
    merged stream, the batch kernel's rule)."""

    __slots__ = ("last", "col_last", "recent")

    def __init__(self, n_cols: int, max_lookback: int):
        self.last = -1
        self.col_last = [-1] * n_cols
        self.recent = (collections.deque(maxlen=max_lookback)
                       if max_lookback > 0 else None)

    def on_right(self, ridx: int, valid: Tuple[bool, ...]) -> None:
        if self.recent is not None:
            self.recent.append((ridx, valid))
            return
        self.last = ridx
        for ci, ok in enumerate(valid):
            if ok:
                self.col_last[ci] = ridx

    def on_left(self, n_cols: int):
        """Match indices for one left row: ``(row_idx, [col_idx])``."""
        if self.recent is None:
            return self.last, list(self.col_last)
        row, cols = -1, [-1] * n_cols
        need = n_cols
        for ridx, valid in reversed(self.recent):
            if ridx < 0:
                continue
            if row < 0:
                row = ridx
            for ci in range(n_cols):
                if cols[ci] < 0 and valid[ci]:
                    cols[ci] = ridx
                    need -= 1
            if need == 0 and row >= 0:
                break
        # the left row itself occupies a window slot for FUTURE lefts
        self.recent.append((-1, None))
        return row, cols


class Subscription:
    """One standing query's live handle.  ``get``/iteration consume
    notifications; ``result()`` assembles the full standing result —
    bitwise what re-running the registered plan over the concatenated
    history produces right now.  Mutable state is guarded by the
    owning engine's lock; the delivery worker is the only writer of
    the accumulators."""

    def __init__(self, engine: "StandingQueryEngine", sub_id: int,
                 plan: qsplit.StandingPlan, depth: int):
        self.engine = engine
        self.id = sub_id
        self.plan = plan
        self.mode = plan.mode
        self.reason = plan.reason
        self._q: "queue.Queue[Notification]" = queue.Queue(
            maxsize=max(1, depth))
        # the fields below are written only by the owning engine (and
        # the module-level resume helpers), always under engine._lock;
        # Subscription's own methods read them under the same lock
        self.dropped = 0
        self.boundaries = 0
        self._acc: List[dict] = []
        self._cursors: Dict[str, int] = {}
        self._err: Optional[BaseException] = None
        self._cancelled = False
        self._member = None
        self._plane = None
        self._jstate: Dict[tuple, _JoinSeries] = {}
        self._rrows = 0

    # -- consuming ------------------------------------------------------

    def get(self, timeout: Optional[float] = None) -> Notification:
        """Next notification (blocks; ``queue.Empty`` on timeout)."""
        return self._q.get(timeout=timeout)

    def drain(self) -> List[Notification]:
        """Every currently-queued notification, non-blocking."""
        out = []
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                return out

    def cancel(self) -> None:
        """Stop deliveries and release the subscription's serving-plane
        slot.  Idempotent."""
        self.engine._cancel(self)

    @property
    def live(self) -> bool:
        return self._err is None and not self._cancelled

    # -- the standing result -------------------------------------------

    def result(self, flush: bool = True):
        """The full standing result as a TSDF — bitwise equal to
        executing the registered (canonical) plan over the tables'
        unified snapshots at the current boundary.  ``flush`` waits for
        the delivery worker to drain first."""
        if flush:
            self.engine.flush()
        with self.engine._lock:
            if self._err is not None:
                raise self._err
            acc = list(self._acc)
            mode = self.mode
        if mode == "remainder":
            pinned = self.engine._pin_snapshots(self.plan.tables)
            return _run_batch(self.plan.root, pinned)
        if mode == "stateless":
            base = self._concat([r["base"] for r in acc],
                                self.plan.table)
            return self._finish(base)
        if self.plan.join is not None:
            return self._join_result(acc)
        return self._ema_result(acc)

    @staticmethod
    def _concat(frames: List[pd.DataFrame], table: StreamTable):
        if not frames:
            return pd.DataFrame({c: pd.Series([], dtype="float64")
                                 for c in table.columns})
        if len(frames) == 1:
            return frames[0].copy()
        return pd.concat(frames, ignore_index=True)

    def _finish(self, df: pd.DataFrame):
        from tempo_tpu.frame import TSDF

        t = self.plan.table
        out = TSDF(df, t.ts_col, t.partitionCols,
                   t.sequence_col or None)
        if self.plan.suffix:
            res = _suffix_df(self.plan, out)
            out = TSDF(res, t.ts_col, t.partitionCols,
                       t.sequence_col or None) \
                if t.ts_col in res.columns else res
        return out

    def _ema_result(self, acc):
        """Accumulated per-push EMA deltas -> the batch twin's frame:
        rows reordered by the SAME (key, ts, seq) stable layout the
        packed batch kernel uses, EMA columns already per-row (the
        serving carry emissions are bitwise the packed scan)."""
        from tempo_tpu.frame import TSDF

        t = self.plan.table
        raw = self._concat([r["base"] for r in acc], t)
        if not len(raw):
            return self._finish(raw)
        lay = TSDF(raw[t.columns], t.ts_col, t.partitionCols,
                   t.sequence_col or None).layout
        out = raw.iloc[lay.order].reset_index(drop=True)
        return self._finish(out)

    def _join_result(self, acc):
        """Accumulated left-row deltas + right index carries -> the
        batch ``asofJoin`` frame: left rows in (key, ts) stable layout
        order, right columns gathered from the right table's snapshot
        in their SOURCE dtype with the batch path's global null rules
        (``join._gather``)."""
        from tempo_tpu import packing
        from tempo_tpu.frame import TSDF
        from tempo_tpu.join import _gather

        js = self.plan.join
        left, right = js.left, js.right
        recs = [r for r in acc if r.get("left") is not None]
        lfs = [r["left"] for r in recs]
        lf = self._concat(lfs, left)
        pcols = left.partitionCols
        rvcols = [c for c in right.columns if c not in pcols]
        if len(lf):
            codes = pd.factorize(
                pd.MultiIndex.from_frame(lf[pcols]) if len(pcols) > 1
                else lf[pcols[0]], use_na_sentinel=False)[0] \
                if pcols else np.zeros(len(lf), np.int64)
            ts_ns = packing.series_to_ns(lf[left.ts_col])
            perm = np.lexsort((ts_ns, codes))
        else:
            perm = np.arange(0)
        left_sorted = lf.iloc[perm].reset_index(drop=True)
        rsnap = right.snapshot_df()
        out = {}
        for c in pcols:
            out[c] = left_sorted[c].to_numpy()
        for c in [c for c in left.columns if c not in pcols]:
            out[c] = left_sorted[c].to_numpy()
        n = len(left_sorted)
        for ci, c in enumerate(rvcols):
            if js.skip_nulls:
                flat = np.concatenate(
                    [r["col_idx"][ci] for r in recs]) if recs else \
                    np.zeros(0, np.int64)
            else:
                flat = np.concatenate(
                    [r["row_idx"] for r in recs]) if recs else \
                    np.zeros(0, np.int64)
            flat = flat[perm]
            ok = flat >= 0
            vals = rsnap[c].to_numpy()
            if not js.skip_nulls:
                valid = (~pd.isna(rsnap[c])).to_numpy()
                ok = ok & valid[np.where(ok, flat, 0)]
            col = _gather(vals, np.where(ok, flat, 0), ok)
            out[f"{js.right_prefix}_{c}"] = col
        res = pd.DataFrame(out, index=range(n))
        tsdf = TSDF(res, left.ts_col, pcols)
        if self.plan.suffix:
            resdf = _suffix_df(self.plan, tsdf)
            tsdf = TSDF(resdf, left.ts_col, pcols) \
                if left.ts_col in resdf.columns else resdf
        return tsdf


class _Plane:
    """One shared serving plane: a :class:`StreamCohort` +
    :class:`CohortExecutor` pair for every subscription with the same
    incremental-operator config (EMA columns + alpha, or join value
    columns + skipNulls + maxLookback).  Creation AOT-warms the
    smallest step bucket through the planner's executable cache, so
    ``profiling.plan_cache_stats()['builds']`` is the standing path's
    zero-recompile counter too."""

    def __init__(self, key: tuple, value_cols: List[str], *,
                 skip_nulls: bool = True, max_lookback: int = 0,
                 ema_alpha: Optional[float] = None):
        from tempo_tpu.serve.cohort import StreamCohort
        from tempo_tpu.serve.executor import CohortExecutor

        self.key = key
        self.cohort = StreamCohort(
            value_cols, skip_nulls=skip_nulls,
            max_lookback=max_lookback, ema_alpha=ema_alpha)
        self.executor = CohortExecutor(self.cohort)
        self.members = 0          # written by the engine under its lock

    def warm(self, member) -> None:
        """Pre-build every group's step-program ladder — the pow2
        tick-count buckets up to the executor's ``batch_rows`` cap,
        built once per (config, capacity, Lb) through
        ``plan/cache.py``, hit forever after.  The executor coalesces
        concurrent subscriptions into variable-width batches; warming
        the whole ladder (not one floor bucket) is what makes the
        steady state zero-recompile under ANY coalescing pattern."""
        if member._group is not None:
            self.cohort.warmup(self.executor.batch_rows)

    def close(self) -> None:
        self.executor.close()


class StandingQueryEngine:
    """See module docstring.  One engine owns a set of
    :class:`StreamTable` feeds, their merged-stream watermarks, the
    shared serving planes, and the delivery worker."""

    def __init__(self, *, queue_depth: Optional[int] = None,
                 remainder_every: Optional[int] = None,
                 push_period: Optional[float] = None):
        if queue_depth is None:
            queue_depth = config.get_int(
                "TEMPO_TPU_STANDING_QUEUE_DEPTH", 1024)
        self.queue_depth = max(1, int(queue_depth))
        if remainder_every is None:
            remainder_every = config.get_int(
                "TEMPO_TPU_STANDING_REMAINDER_EVERY", 64)
        self.remainder_every = max(1, int(remainder_every))
        if push_period is None:
            push_period = config.get_float(
                "TEMPO_TPU_STANDING_PUSH_PERIOD", 0.0)
        self.push_period = float(push_period or 0.0)
        self._lock = threading.RLock()
        self._ids = itertools.count(1)
        self._tables: Dict[str, StreamTable] = {}  # guarded-by: self._lock
        #: merged-stream watermark per feed group per series:
        #: group key -> {series: (ts, seq, side)}
        self._feeds: Dict[tuple, Dict[tuple, tuple]] = {}  # guarded-by: self._lock
        self._subs: Dict[int, Subscription] = {}   # guarded-by: self._lock
        self._by_table: Dict[str, List[Subscription]] = {}  # guarded-by: self._lock
        self._planes: Dict[tuple, _Plane] = {}     # guarded-by: self._lock
        self._closed = False      # guarded-by: self._lock
        self._work: "queue.Queue" = queue.Queue()
        self._enqueued = 0        # guarded-by: self._lock
        self._processed = 0       # guarded-by: self._lock
        self._drained = threading.Condition(self._lock)
        self._worker = threading.Thread(
            target=self._run, daemon=True, name="tempo-standing-delivery")
        self._worker.start()

    # -- registration ---------------------------------------------------

    @staticmethod
    def _as_root(query) -> ir.Node:
        from tempo_tpu.plan import lazy

        if isinstance(query, ir.Node):
            return query
        if isinstance(query, lazy.LazyDistributedTSDF):
            return ir.Node("collect", inputs=(query.plan,))
        if isinstance(query, lazy._LazyBase):
            return query.plan
        raise TypeError(
            f"register() takes a lazy chain over StreamTable.frame() "
            f"(or a plan node), got {type(query).__name__}")

    def register(self, query) -> Subscription:
        """Register a planned method chain as a standing query.
        Returns the live :class:`Subscription`; its first notification
        is the ``"catchup"`` replay of everything the tables already
        hold."""
        root = qsplit.canonicalize(self._as_root(query))
        plan = qsplit.split(root)
        with self._lock:
            if self._closed:
                raise RuntimeError("standing-query engine is closed")
            sub = Subscription(self, next(self._ids), plan,
                               self.queue_depth)
            for t in plan.tables:
                self._adopt(t)
            self._seed_feeds(plan)
            try:
                self._catchup(sub)
            except Exception as e:  # noqa: BLE001 - demote, by name
                if sub.mode != "remainder":
                    # the incremental catch-up could not be seeded
                    # (e.g. replay rejected): serve the subscription
                    # correctly from the batch remainder instead
                    sub.mode = "remainder"
                    sub.reason = (f"catch-up replay failed "
                                  f"({type(e).__name__}: {e}); demoted "
                                  f"to the batch remainder")
                    # the failed incremental catch-up may have claimed
                    # a plane member — release the cohort slot (the
                    # remainder path never uses it) and drop the
                    # half-seeded incremental state
                    self._release_member(sub)
                    sub._plane = None
                    sub._jstate = {}
                    sub._series_seen = set()
                    sub._rrows = 0
                    sub._acc = []
                    self._catchup(sub)
                else:
                    raise
            self._subs[sub.id] = sub
            for t in plan.tables:
                self._by_table.setdefault(t.name, []).append(sub)
        return sub

    def register_sql(self, text: str, tables: Dict[str, object]) -> Subscription:
        """Register one SQL statement (the PR-18 surface) as a standing
        query: ``tables`` maps names to :class:`StreamTable`\\ s (or
        plain frames for static sides); stream tables enter the plan as
        ``unified_scan`` sources, so the statement answers over history
        + live under one watermark."""
        from tempo_tpu.plan import sql_compile

        bound = {name: (t.frame() if isinstance(t, StreamTable) else t)
                 for name, t in tables.items()}
        root = sql_compile.compile_statement(text, bound)
        return self.register(root)

    def _adopt(self, table: StreamTable) -> None:  # guarded-by: self._lock
        have = self._tables.get(table.name)
        if have is None:
            # claim ownership: while adopted, direct table.append()
            # (and adoption by a second engine) is refused — both
            # would commit rows the engine's watermarks and per-push
            # base row counts never saw
            with table._lock:
                if table._engine is not None and table._engine is not self:
                    raise ValueError(
                        f"StreamTable {table.name!r} is already "
                        f"adopted by a different standing-query "
                        f"engine; close it first")
                table._engine = self
            self._tables[table.name] = table
        elif have is not table:
            raise ValueError(
                f"a DIFFERENT StreamTable named {table.name!r} is "
                f"already registered with this engine")

    # -- feed watermarks ------------------------------------------------

    def _groups_of(self, plan: qsplit.StandingPlan) -> List[tuple]:
        if plan.join is not None and plan.mode == "delta":
            return [("j", plan.join.left.name, plan.join.right.name)]
        return [("r", t.name) for t in plan.tables]

    def _seed_feeds(self, plan: qsplit.StandingPlan) -> None:  # guarded-by: self._lock
        """First subscription touching a feed seeds its merged-stream
        watermark from the data already in the tables (per-series max
        (ts, seq, side)) — later pushes admit strictly forward of
        everything the catch-up replay consumed."""
        for gk in self._groups_of(plan):
            wm = self._feeds.setdefault(gk, {})
            if gk[0] == "r":
                tabs = [(self._tables[gk[1]], _SIDE_RIGHT)]
            else:
                tabs = [(self._tables[gk[1]], _SIDE_LEFT),
                        (self._tables[gk[2]], _SIDE_RIGHT)]
            for t, side in tabs:
                df = t.snapshot_df()
                if not len(df):
                    continue
                _, keys, ts_ns, seq = t.prepare(df)
                for i, k in enumerate(keys):
                    key = (int(ts_ns[i]), float(seq[i]), side)
                    if key > wm.get(k, (-(1 << 62), -np.inf, 0)):
                        wm[k] = key

    # -- pushing --------------------------------------------------------

    def push(self, table: StreamTable, df: pd.DataFrame, *,
             deadline=None) -> dict:
        """Admit one batch of events for ``table``: validate against
        every feed watermark the table participates in (ALL groups
        accept before anything commits — a late tick raises
        :class:`LateTickError` and nothing changes), append to the live
        tail, and hand the boundary to the delivery worker.  Returns
        ``{"rows": ..., "boundary_of": [sub ids notified]}``."""
        dl = Deadline.after(deadline)
        with self._lock:
            if self._closed:
                raise RuntimeError("standing-query engine is closed")
            self._adopt(table)
            ndf, keys, ts_ns, seq = table.prepare(df)
            groups = [gk for gk in self._feeds
                      if table.name in gk[1:]]
            # validate EVERY group first (commit-after-success: the
            # admit_batch discipline), then advance the watermarks
            cands: List[Tuple[dict, Dict[tuple, tuple]]] = []
            for gk in groups:
                wm = self._feeds[gk]
                sides = []
                if gk[0] == "r":
                    sides.append(_SIDE_RIGHT)
                else:
                    if gk[2] == table.name:
                        sides.append(_SIDE_RIGHT)
                    if gk[1] == table.name:
                        sides.append(_SIDE_LEFT)
                for side in sides:
                    cand: Dict[tuple, tuple] = {}
                    for i, k in enumerate(keys):
                        key = (int(ts_ns[i]), float(seq[i]), side)
                        prev = cand.get(k, wm.get(k))
                        if prev is not None and key < prev:
                            raise LateTickError(
                                f"{table.name}/{k!r}", key[0], key[1],
                                side, prev)
                        cand[k] = key
                    cands.append((wm, cand))
            for wm, cand in cands:
                wm.update(cand)
            base = table.rows_total()
            table.commit(ndf)
            subs = [s for s in self._by_table.get(table.name, ())
                    if s.live]
            self._enqueued += 1
            # unbounded queue: put_nowait never raises Full, so the
            # enqueue cannot stall other users of the engine lock
            self._work.put_nowait(("push", table, ndf, keys, ts_ns, seq,
                                   base, dl))
        return {"rows": len(ndf), "boundary_of": [s.id for s in subs]}

    def flush(self, timeout: Optional[float] = None) -> None:
        """Block until the delivery worker has drained every boundary
        enqueued so far."""
        with self._lock:
            self._drained.wait_for(
                lambda: self._processed >= self._enqueued or self._closed,
                timeout=timeout)

    # -- lifecycle ------------------------------------------------------

    def _cancel(self, sub: Subscription) -> None:
        with self._lock:
            if sub._cancelled:
                return
            sub._cancelled = True
            self._release_member(sub)

    def _release_member(self, sub: Subscription) -> None:  # guarded-by: self._lock
        member, plane = sub._member, sub._plane
        sub._member = None
        if member is None or plane is None:
            return
        cohort = plane.cohort
        g = member._group
        if g is not None:
            g.release(member.slot)
            member._group, member.slot = None, None
            cohort._resident -= 1
        cohort._members.pop(member.name, None)
        cohort._lru.pop(member.name, None)
        plane.members -= 1

    def close(self) -> None:
        """Stop the delivery worker and the serving planes.  Standing
        results already accumulated stay readable; adopted tables are
        released back to direct :meth:`StreamTable.append` use."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            planes = list(self._planes.values())
            for t in self._tables.values():
                with t._lock:
                    if t._engine is self:
                        t._engine = None
            self._drained.notify_all()
        self._work.put(None)
        self._worker.join(timeout=30)
        for p in planes:
            p.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()

    # -- serving planes -------------------------------------------------

    def _plane_for(self, plan: qsplit.StandingPlan) -> Optional[_Plane]:  # guarded-by: self._lock
        if plan.emas:
            key = ("ema", tuple(e.col for e in plan.emas),
                   plan.emas[0].alpha)
            mk = dict(value_cols=[e.col for e in plan.emas],
                      skip_nulls=True, max_lookback=0,
                      ema_alpha=plan.emas[0].alpha)
        elif plan.join is not None:
            js = plan.join
            vcols = [c for c in js.right.value_cols]
            if not vcols:
                return None
            key = ("join", tuple(vcols), js.skip_nulls, js.max_lookback)
            mk = dict(value_cols=vcols, skip_nulls=js.skip_nulls,
                      max_lookback=js.max_lookback, ema_alpha=None)
        else:
            return None
        plane = self._planes.get(key)
        if plane is None:
            plane = self._planes[key] = _Plane(key, **mk)
        return plane

    def _ensure_member(self, sub: Subscription,
                       keys: List[tuple]) -> None:  # guarded-by: self._lock
        """Admit any unseen series keys into the subscription's plane
        member (created on first contact — an empty stream has no
        member, so registration against empty tables is free)."""
        plane = sub._plane
        if plane is None:
            return
        seen: set = getattr(sub, "_series_seen", None)
        if seen is None:
            seen = sub._series_seen = set()
        fresh = []
        for k in keys:
            if k not in seen:
                seen.add(k)
                fresh.append(k)
        if not fresh:
            return
        if sub._member is None:
            sub._member = plane.cohort.add_stream(f"sub{sub.id}", fresh)
            plane.members += 1
        else:
            sub._member.add_series(fresh)
        plane.warm(sub._member)

    # -- catch-up -------------------------------------------------------

    def _catchup(self, sub: Subscription) -> None:  # guarded-by: self._lock
        """Register-time replay: everything the tables already hold
        becomes the subscription's boundary-0 state — the plane carries
        seeded bitwise (history replayed per series in the SAME
        (ts, seq) stable order the batch layout sorts), the
        accumulators holding the history rows in arrival order."""
        plan = sub.plan
        for t in plan.tables:
            sub._cursors[t.name] = t.rows_total()
        if sub.mode == "remainder":
            pinned = self._pin_snapshots(plan.tables)
            frame = _run_batch(plan.root, pinned)
            self._notify(sub, Notification("catchup", 0, frame.df))
            return
        if sub.mode == "stateless":
            df = plan.table.snapshot_df()
            if len(df):
                sub._acc.append({"base": df})
            self._notify(sub, Notification(
                "catchup", 0, _suffix_df(plan, self._as_tsdf(df, plan))))
            return
        if plan.join is not None:
            self._catchup_join(sub)
            return
        self._catchup_ema(sub)

    def _as_tsdf(self, df: pd.DataFrame, plan: qsplit.StandingPlan):
        from tempo_tpu.frame import TSDF

        t = plan.table
        return TSDF(df, t.ts_col, t.partitionCols, t.sequence_col or None)

    def _catchup_ema(self, sub: Subscription) -> None:  # guarded-by: self._lock
        t = sub.plan.table
        df = t.snapshot_df()
        sub._plane = self._plane_for(sub.plan)
        if not len(df):
            self._notify(sub, Notification("catchup", 0, df))
            return
        _, keys, ts_ns, seq = t.prepare(df)
        # per-series (ts, seq) stable order: the exact order the batch
        # layout packs, and an always-admissible replay order
        perm = np.lexsort((seq, ts_ns))
        self._ensure_member(sub, [keys[i] for i in perm])
        emas = self._dispatch_ema(sub, df, keys, ts_ns, seq, perm,
                                  Deadline.after(None))
        base = df.copy()
        for e in sub.plan.emas:
            base[f"EMA_{e.col}"] = emas[e.col]
        sub._acc.append({"base": base})
        self._notify(sub, Notification(
            "catchup", 0, _suffix_df(sub.plan, self._as_tsdf(base, sub.plan))))

    def _dispatch_ema(self, sub: Subscription, df, keys, ts_ns, seq,
                      perm, dl) -> Dict[str, np.ndarray]:
        """Push ``df``'s rows (in ``perm`` order) through the
        subscription's plane member and return per-ROW (original
        order) float64 EMA columns from the carry emissions."""
        t = sub.plan.table
        cols = [e.col for e in sub.plan.emas]
        colvals = {c: df[c].to_numpy() for c in cols}
        out = {c: np.empty(len(df), np.float64) for c in cols}
        member = sub._member
        ex = sub._plane.executor
        has_seq = t.sequence_col is not None
        for lo in range(0, len(perm), _REPLAY_CHUNK):
            chunk = perm[lo:lo + _REPLAY_CHUNK]
            ticks = [("right", member, keys[i], int(ts_ns[i]),
                      {c: float(colvals[c][i]) for c in cols},
                      (float(seq[i]) if has_seq else None))
                     for i in chunk]
            tickets = ex.submit_many(ticks, deadline=dl)
            for i, tk in zip(chunk, tickets):
                res = tk.result(timeout=dl.remaining() if dl else None)
                for c in cols:
                    # exact f32 -> f64 widening: bitwise the batch
                    # kernel's unpack .astype(np.float64)
                    out[c][i] = np.float64(
                        np.float32(res[f"{c}_ema"]))
        return out

    def _catchup_join(self, sub: Subscription) -> None:  # guarded-by: self._lock
        js = sub.plan.join
        sub._plane = self._plane_for(sub.plan)
        ldf = js.left.snapshot_df()
        rdf = js.right.snapshot_df()
        _, lkeys, lts, _ = js.left.prepare(ldf)
        _, rkeys, rts, _ = js.right.prepare(rdf)
        pcols = js.left.partitionCols
        rvcols = [c for c in js.right.columns if c not in pcols]
        nrv = len(rvcols)
        valid = np.column_stack(
            [(~pd.isna(rdf[c])).to_numpy() for c in rvcols]) \
            if len(rdf) and nrv else np.zeros((len(rdf), nrv), bool)
        # merged-stream order: (ts, side[right first], within-side pos)
        nl, nr = len(ldf), len(rdf)
        ts_all = np.concatenate([rts, lts])
        side = np.concatenate([np.zeros(nr, np.int8),
                               np.ones(nl, np.int8)])
        pos = np.concatenate([np.arange(nr), np.arange(nl)])
        order = np.lexsort((pos, side, ts_all))
        row_idx = np.full(nl, -1, np.int64)
        col_idx = np.full((nrv, nl), -1, np.int64)
        for j in order:
            if side[j] == 0:
                ridx = int(pos[j])
                st = self._jseries(sub, rkeys[ridx], nrv, js.max_lookback)
                st.on_right(ridx, tuple(valid[ridx]))
            else:
                lidx = int(pos[j])
                st = self._jseries(sub, lkeys[lidx], nrv, js.max_lookback)
                row, cols_m = st.on_left(nrv)
                row_idx[lidx] = row
                for ci in range(nrv):
                    col_idx[ci, lidx] = cols_m[ci]
        sub._rrows = nr
        if nl:
            sub._acc.append({"left": ldf, "row_idx": row_idx,
                             "col_idx": col_idx})
        res = sub._join_result(sub._acc)
        self._notify(sub, Notification(
            "catchup", 0, res.df if hasattr(res, "df") else res))

    def _jseries(self, sub: Subscription, key, nrv, max_lookback) -> _JoinSeries:
        st = sub._jstate.get(key)
        if st is None:
            st = sub._jstate[key] = _JoinSeries(nrv, max_lookback)
        return st

    def _pin_snapshots(self, tables) -> Dict[str, pd.DataFrame]:
        """One consistent snapshot per table, taken under the engine
        lock so a multi-table remainder never sees a torn boundary."""
        with self._lock:
            return {t.name: t.snapshot_df() for t in tables}

    # -- delivery worker ------------------------------------------------

    def _run(self) -> None:
        """The delivery loop: one work item per admitted push (or one
        per coalesced run under ``TEMPO_TPU_STANDING_PUSH_PERIOD``),
        fanned out to every live subscription on the pushed table —
        submits first, awaits after, so concurrent subscriptions
        coalesce into batched cohort dispatches."""
        while True:
            item = self._work.get()
            if item is None:
                with self._lock:
                    self._drained.notify_all()
                return
            items = [item]
            if self.push_period > 0:
                dl = Deadline.after(self.push_period)
                while True:
                    try:
                        nxt = self._work.get(timeout=dl.remaining())
                    except queue.Empty:
                        break
                    if nxt is None:
                        self._work.put(None)
                        break
                    items.append(nxt)
            for it in items:
                try:
                    self._deliver(it)
                finally:
                    with self._lock:
                        self._processed += 1
                        self._drained.notify_all()

    def _deliver(self, item) -> None:
        _, table, ndf, keys, ts_ns, seq, base, dl = item
        with self._lock:
            # a subscription registered (or resumed) AFTER this push
            # committed already holds these rows from its catch-up
            # snapshot — its cursor sits past `base`; delivering the
            # delta again would duplicate the rows in the accumulator
            # and overshoot the cursor past rows_total
            subs = [s for s in self._by_table.get(table.name, ())
                    if s.live and s._cursors.get(table.name, 0) <= base]
            submitted = []
            for sub in subs:
                try:
                    submitted.append(
                        (sub, self._submit_sub(sub, table, ndf, keys,
                                               ts_ns, seq, base, dl)))
                except Exception as e:  # noqa: BLE001 - per subscriber
                    self._fail(sub, e)
        for sub, pending in submitted:
            try:
                self._finish_sub(sub, table, ndf, pending, dl)
            except Exception as e:  # noqa: BLE001 - per subscriber
                with self._lock:
                    self._fail(sub, e)

    def _submit_sub(self, sub, table, ndf, keys, ts_ns, seq, base, dl):  # guarded-by: self._lock
        """Phase 1 (under the lock): update host carries, enqueue the
        subscription's plane ticks.  Returns what phase 2 awaits."""
        plan = sub.plan
        if sub.mode == "remainder":
            return ("remainder",)
        if sub.mode == "stateless":
            return ("stateless",)
        if plan.join is not None:
            return self._submit_join(sub, table, ndf, keys, ts_ns,
                                     base, dl)
        # EMA: one tick per pushed row, in arrival order (admission
        # guarantees per-series (ts, seq) monotone arrival = the batch
        # layout's stable order)
        self._ensure_member(sub, keys)
        cols = [e.col for e in plan.emas]
        has_seq = table.sequence_col is not None
        vals = {c: ndf[c].to_numpy() for c in cols}
        ticks = [("right", sub._member, keys[i], int(ts_ns[i]),
                  {c: float(vals[c][i]) for c in cols},
                  (float(seq[i]) if has_seq else None))
                 for i in range(len(ndf))]
        tickets = sub._plane.executor.submit_many(ticks, deadline=dl)
        return ("ema", tickets)

    def _submit_join(self, sub, table, ndf, keys, ts_ns, base, dl):  # guarded-by: self._lock
        js = sub.plan.join
        pcols = js.left.partitionCols
        rvcols = [c for c in js.right.columns if c not in pcols]
        nrv = len(rvcols)
        if table is js.right:
            valid = np.column_stack(
                [(~pd.isna(ndf[c])).to_numpy() for c in rvcols]) \
                if len(ndf) and nrv else np.zeros((len(ndf), nrv), bool)
            for i, k in enumerate(keys):
                st = self._jseries(sub, k, nrv, js.max_lookback)
                st.on_right(base + i, tuple(valid[i]))
            sub._rrows = base + len(ndf)
            tickets = []
            if sub._plane is not None and js.right.value_cols:
                self._ensure_member(sub, keys)
                vals = {c: ndf[c].to_numpy()
                        for c in js.right.value_cols}
                ticks = [("right", sub._member, keys[i], int(ts_ns[i]),
                          {c: float(vals[c][i])
                           for c in js.right.value_cols}, None)
                         for i in range(len(ndf))]
                tickets = sub._plane.executor.submit_many(ticks,
                                                          deadline=dl)
            return ("join_right", tickets)
        row_idx = np.full(len(ndf), -1, np.int64)
        col_idx = np.full((nrv, len(ndf)), -1, np.int64)
        for i, k in enumerate(keys):
            st = self._jseries(sub, k, nrv, js.max_lookback)
            row, cols_m = st.on_left(nrv)
            row_idx[i] = row
            for ci in range(nrv):
                col_idx[ci, i] = cols_m[ci]
        rec = {"left": ndf, "row_idx": row_idx, "col_idx": col_idx}
        tickets = []
        if (sub._plane is not None and sub._member is not None
                and all(k in sub._series_seen for k in keys)):
            ticks = [("left", sub._member, keys[i], int(ts_ns[i]),
                      None, None) for i in range(len(ndf))]
            tickets = sub._plane.executor.submit_many(ticks, deadline=dl)
        return ("join_left", tickets, rec)

    def _finish_sub(self, sub, table, ndf, pending, dl) -> None:
        """Phase 2 (outside the lock): await the plane tickets,
        assemble the delta (from the EXACT rows this boundary pushed —
        carried in the work item, never re-derived from a racing
        snapshot), append the accumulator and notify."""
        kind = pending[0]
        plan = sub.plan
        if kind == "remainder":
            with self._lock:
                sub.boundaries += 1
                self._bump_cursor(sub, table, len(ndf))
                due = sub.boundaries % self.remainder_every == 0
                bno = sub.boundaries
                tables = plan.tables
            if due:
                pinned = self._pin_snapshots(tables)
                frame = _run_batch(plan.root, pinned)
                self._notify(sub, Notification("refresh", bno, frame.df))
            return
        if kind == "stateless":
            with self._lock:
                sub._acc.append({"base": ndf})
                sub.boundaries += 1
                bno = sub.boundaries
                self._bump_cursor(sub, table, len(ndf))
            self._notify(sub, Notification(
                "delta", bno, _suffix_df(plan, self._as_tsdf(ndf, plan))))
            return
        if kind == "ema":
            tickets = pending[1]
            cols = [e.col for e in plan.emas]
            emas = {c: np.empty(len(ndf), np.float64) for c in cols}
            for i, tk in enumerate(tickets):
                res = tk.result(timeout=dl.remaining() if dl else None)
                for c in cols:
                    emas[c][i] = np.float64(np.float32(res[f"{c}_ema"]))
            base = ndf.copy()
            for e in plan.emas:
                base[f"EMA_{e.col}"] = emas[e.col]
            with self._lock:
                sub._acc.append({"base": base})
                sub.boundaries += 1
                bno = sub.boundaries
                self._bump_cursor(sub, table, len(ndf))
            self._notify(sub, Notification(
                "delta", bno, _suffix_df(plan, self._as_tsdf(base, plan))))
            return
        # join sides: await the plane's merged-stream step (machinery
        # + quarantine semantics); the exact-dtype assembly rides the
        # host carries recorded in phase 1
        tickets = pending[1]
        for tk in tickets:
            tk.result(timeout=dl.remaining() if dl else None)
        if kind == "join_right":
            with self._lock:
                sub.boundaries += 1
                self._bump_cursor(sub, table, len(ndf))
            return
        rec = pending[2]
        with self._lock:
            sub._acc.append(rec)
            sub.boundaries += 1
            bno = sub.boundaries
            self._bump_cursor(sub, table, len(ndf))
        delta = sub._join_result([rec])
        self._notify(sub, Notification(
            "delta", bno, delta.df if hasattr(delta, "df") else delta))

    def _bump_cursor(self, sub, table, rows: int) -> None:  # guarded-by: self._lock
        sub._cursors[table.name] = sub._cursors.get(table.name, 0) + rows

    def _fail(self, sub, exc: BaseException) -> None:  # guarded-by: self._lock
        if sub._err is None:
            sub._err = exc
        self._notify(sub, Notification("error", sub.boundaries, None,
                                       error=exc))
        self._release_member(sub)

    def _notify(self, sub, note: Notification) -> None:
        """Bounded, per-subscriber delivery: a full queue drops the
        OLDEST notification (counted) — one slow consumer never stalls
        the fleet, and ``result()`` stays exact regardless."""
        if sub._cancelled:
            return
        while True:
            try:
                sub._q.put_nowait(note)
                return
            except queue.Full:
                try:
                    sub._q.get_nowait()
                    sub.dropped += 1
                except queue.Empty:
                    continue


# ----------------------------------------------------------------------
# Snapshot / resume: kind="standing_state"
# ----------------------------------------------------------------------

def snapshot_subscription(sub: Subscription, path: str) -> str:
    """Persist one standing subscription as a CRC'd
    ``kind="standing_state"`` artifact: per-table replay cursors plus —
    for EMA subscriptions — the serving plane's slot carries and
    watermark rows, bit-for-bit (the cohort spill recipe).  Resuming
    and pushing the tail is byte-identical to the uninterrupted run."""
    from tempo_tpu import checkpoint as ckpt

    eng = sub.engine
    eng.flush()
    with eng._lock:
        if sub._err is not None:
            raise sub._err
        arrays: Dict[str, np.ndarray] = {
            "cursor_rows": np.asarray(
                [sub._cursors.get(t.name, 0) for t in sub.plan.tables],
                np.int64)}
        meta = {
            "plan_signature": sub.plan.signature,
            "mode": sub.mode,
            "boundaries": int(sub.boundaries),
            "tables": [t.name for t in sub.plan.tables],
            "series_repr": ([repr(s) for s in sub._member.series]
                            if sub._member is not None else []),
        }
        member = sub._member
        if member is not None and member._group is not None:
            g, slot = member._group, member.slot
            g._host()
            for n, a in g.state.items():
                arrays[f"s.{n}"] = np.ascontiguousarray(a[slot])
            arrays["wm_ts"] = np.ascontiguousarray(g.wm_ts[slot])
            arrays["wm_seq"] = np.ascontiguousarray(g.wm_seq[slot])
            arrays["wm_side"] = np.ascontiguousarray(g.wm_side[slot])
            meta["bucket"] = int(g.bucket)
        ckpt.save_state(arrays, path, meta, kind="standing_state")
    return path


def resume_subscription(engine: StandingQueryEngine, query,
                        path: str) -> Subscription:
    """Re-register ``query`` from a ``kind="standing_state"`` artifact:
    the canonical plan signature must match the artifact's (refused by
    name otherwise), the accumulators are rebuilt from each table's
    snapshot prefix at the saved cursors, the plane carries install
    bit-for-bit, and any rows the tables gained past the cursors replay
    as a catch-up gap.  Subsequent pushes are byte-identical to the
    never-killed subscription."""
    from tempo_tpu import checkpoint as ckpt

    arrays, meta = ckpt.load_state(path, kind="standing_state")
    root = qsplit.canonicalize(engine._as_root(query))
    plan = qsplit.split(root)
    if plan.signature != meta.get("plan_signature"):
        raise ckpt.CheckpointError(
            f"standing-state artifact {path!r} was saved for plan "
            f"signature {meta.get('plan_signature')!r} but the "
            f"registered query canonicalizes to {plan.signature!r}: "
            f"refusing to resume a DIFFERENT standing query from it")
    cursors = {name: int(r) for name, r in
               zip(meta.get("tables", ()),
                   np.asarray(arrays["cursor_rows"]))}
    with engine._lock:
        if engine._closed:
            raise RuntimeError("standing-query engine is closed")
        sub = Subscription(engine, next(engine._ids), plan,
                           engine.queue_depth)
        for t in plan.tables:
            engine._adopt(t)
            if cursors.get(t.name, 0) > t.rows_total():
                raise ckpt.CheckpointError(
                    f"standing-state artifact {path!r} holds a cursor "
                    f"of {cursors[t.name]} rows for table {t.name!r} "
                    f"but the table only has {t.rows_total()}: the "
                    f"artifact outlived this table's data — resume "
                    f"against the original tables")
        engine._seed_feeds(plan)
        engine._resume_state(sub, arrays, meta, cursors)
        engine._subs[sub.id] = sub
        for t in plan.tables:
            engine._by_table.setdefault(t.name, []).append(sub)
    return sub


def _install_slot(plane: _Plane, member, arrays) -> None:
    g, slot = member._group, member.slot
    g._host()
    for n in g.state:
        g.state[n][slot] = arrays[f"s.{n}"]
    g.wm_ts[slot] = np.asarray(arrays["wm_ts"], np.int64)
    g.wm_seq[slot] = np.asarray(arrays["wm_seq"], np.float64)
    g.wm_side[slot] = np.asarray(arrays["wm_side"], np.int8)


def _resume_state(self, sub: Subscription, arrays, meta,
                  cursors: Dict[str, int]) -> None:  # guarded-by: self._lock
    """Rebuild a resumed subscription's accumulators from the table
    prefixes at the saved cursors and install the plane carries."""
    from tempo_tpu import checkpoint as ckpt

    plan = sub.plan
    for t in plan.tables:
        sub._cursors[t.name] = cursors.get(t.name, 0)
    if sub.mode == "remainder":
        sub.boundaries = int(meta.get("boundaries", 0))
        self._replay_gap(sub)
        return
    if sub.mode == "stateless":
        t = plan.table
        pre = t.prefix_df(sub._cursors[t.name])
        if len(pre):
            sub._acc.append({"base": pre})
        sub.boundaries = int(meta.get("boundaries", 0))
        self._replay_gap(sub)
        return
    if plan.join is not None:
        # host carries are cheap to rebuild exactly: replay the saved
        # prefix through the merged-stream walk (no device state)
        js = plan.join
        lcur = sub._cursors[js.left.name]
        rcur = sub._cursors[js.right.name]
        sub._plane = self._plane_for(plan)
        self._seed_join_prefix(sub, js.left.prefix_df(lcur),
                               js.right.prefix_df(rcur))
        sub.boundaries = int(meta.get("boundaries", 0))
        self._replay_gap(sub)
        return
    # EMA: accumulator from the prefix (batch kernel — same bits), the
    # carry installed from the artifact (same bits as the live slot)
    t = plan.table
    pre = t.prefix_df(sub._cursors[t.name])
    sub._plane = self._plane_for(plan)
    if len(pre):
        _, keys, ts_ns, seq = t.prepare(pre)
        prefix_series = list(dict.fromkeys(
            keys[i] for i in np.lexsort((seq, ts_ns))))
        # the live member admitted series in push ARRIVAL order, and
        # the slot carries are laid out in that order — rebuild from
        # the artifact's saved series list (any permutation of the
        # prefix's series set is legitimate; a different SET is not)
        saved = meta.get("series_repr") or []
        if saved:
            by_repr = {repr(k): k for k in prefix_series}
            if sorted(saved) != sorted(by_repr):
                raise ckpt.CheckpointError(
                    f"standing-state artifact holds carries for series "
                    f"{sorted(saved)} but the table prefix yields "
                    f"{sorted(by_repr)}: refusing to install "
                    f"FOREIGN carries")
            order = [by_repr[r] for r in saved]
        else:
            order = prefix_series
        sub._series_seen = set(order)
        sub._member = sub._plane.cohort.add_stream(f"sub{sub.id}", order)
        sub._plane.members += 1
        if "wm_ts" in arrays:
            _install_slot(sub._plane, sub._member, arrays)
        sub._plane.warm(sub._member)
        base = pre.copy()
        for c, e in self._batch_ema_cols(plan, pre).items():
            base[c] = e
        sub._acc.append({"base": base})
    sub.boundaries = int(meta.get("boundaries", 0))
    self._replay_gap(sub)


def _batch_ema_cols(self, plan: qsplit.StandingPlan,
                    df: pd.DataFrame) -> Dict[str, np.ndarray]:
    """Per-row (original order) EMA columns via the batch kernel —
    bitwise the carry emissions (ema_scan is the shared kernel)."""
    from tempo_tpu.frame import TSDF

    t = plan.table
    out: Dict[str, np.ndarray] = {}
    tsdf = TSDF(df[t.columns], t.ts_col, t.partitionCols,
                t.sequence_col or None)
    inv = np.empty(len(df), np.int64)
    inv[tsdf.layout.order] = np.arange(len(df))
    for e in plan.emas:
        res = qsplit.eval_ema_stream(tsdf, e.col, e.alpha)
        out[f"EMA_{e.col}"] = res.df[f"EMA_{e.col}"].to_numpy()[inv]
    return out


def _seed_join_prefix(self, sub: Subscription, ldf: pd.DataFrame,
                      rdf: pd.DataFrame) -> None:  # guarded-by: self._lock
    js = sub.plan.join
    _, lkeys, lts, _ = js.left.prepare(ldf)
    _, rkeys, rts, _ = js.right.prepare(rdf)
    pcols = js.left.partitionCols
    rvcols = [c for c in js.right.columns if c not in pcols]
    nrv = len(rvcols)
    valid = np.column_stack(
        [(~pd.isna(rdf[c])).to_numpy() for c in rvcols]) \
        if len(rdf) and nrv else np.zeros((len(rdf), nrv), bool)
    nl, nr = len(ldf), len(rdf)
    ts_all = np.concatenate([rts, lts])
    side = np.concatenate([np.zeros(nr, np.int8), np.ones(nl, np.int8)])
    pos = np.concatenate([np.arange(nr), np.arange(nl)])
    order = np.lexsort((pos, side, ts_all))
    row_idx = np.full(nl, -1, np.int64)
    col_idx = np.full((nrv, nl), -1, np.int64)
    for j in order:
        if side[j] == 0:
            ridx = int(pos[j])
            st = self._jseries(sub, rkeys[ridx], nrv, js.max_lookback)
            st.on_right(ridx, tuple(valid[ridx]))
        else:
            lidx = int(pos[j])
            st = self._jseries(sub, lkeys[lidx], nrv, js.max_lookback)
            row, cols_m = st.on_left(nrv)
            row_idx[lidx] = row
            for ci in range(nrv):
                col_idx[ci, lidx] = cols_m[ci]
    sub._rrows = nr
    if nl:
        sub._acc.append({"left": ldf, "row_idx": row_idx,
                         "col_idx": col_idx})


def _replay_gap(self, sub: Subscription) -> None:  # guarded-by: self._lock
    """Rows the tables gained past the saved cursors (pushes the
    engine admitted after the snapshot, or before resume) replay as
    one catch-up boundary per table — the resumed subscription lands
    exactly at the tables' current edge."""
    for t in sub.plan.tables:
        lo = sub._cursors.get(t.name, 0)
        hi = t.rows_total()
        if hi <= lo:
            continue
        gap = t.snapshot_df().iloc[lo:hi].reset_index(drop=True)
        _, keys, ts_ns, seq = t.prepare(gap)
        pending = self._submit_sub(sub, t, gap, keys, ts_ns, seq, lo,
                                   None)
        self._finish_sub(sub, t, gap, pending, None)


# bind the resume helpers as engine methods (they live at module level
# to keep the class body focused on the live path)
StandingQueryEngine._resume_state = _resume_state
StandingQueryEngine._batch_ema_cols = _batch_ema_cols
StandingQueryEngine._seed_join_prefix = _seed_join_prefix
StandingQueryEngine._replay_gap = _replay_gap
