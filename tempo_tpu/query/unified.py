"""The unified history+live scan: one source over everything ever
written.

A :class:`StreamTable` is a named, watermarked event table: optional
Parquet history in the transactional store (``tempo_tpu/store``) plus
a live host tail of admitted pushes, in arrival order.  Its plan-facing
face is the ``unified_scan`` IR node (payload:
:class:`UnifiedSource`), which materializes history ∪ tail as ONE
``TSDF`` under the table's single watermark — so a registered query
(method chain or SQL) answers over all data ever seen, bitwise equal
to a batch run over the concatenated frames.  The kappa-architecture
answer to maintaining separate batch and speed codepaths in the
client.

Ordering contract: rows are admitted per series against the same
merged-stream watermark rule the serving plane enforces
(``serve.stream.admit_batch`` — one admission rule, so the standing
incremental path and the batch twin cannot drift on what "late"
means).  ``sync_to_store`` persists the tail as a new clustered store
generation WITHOUT re-sorting (empty ``sort_cols``), so arrival order
— and therefore the packed layouts' first-appearance key
factorization — survives the round trip, and a live ``store.compact``
mid-subscription republishes the same rows in the same order:
unified-scan results are bitwise stable across compaction.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pandas as pd

from tempo_tpu import packing

__all__ = ["StreamTable", "UnifiedSource"]


def _seq_sort_key(seq_vals: np.ndarray) -> np.ndarray:
    """NULLS FIRST realized as -inf, the serving plane's convention."""
    s = np.asarray(seq_vals, np.float64)
    return np.where(np.isnan(s), -np.inf, s)


class StreamTable:
    """One live event table: schema + watermark + host tail, with
    optional store-backed history.

    ``columns`` fixes the schema order (history and every pushed frame
    are re-projected onto it).  ``value_cols`` names the float metric
    columns the incremental operators stream; everything else is
    structural (``ts_col``, ``partition_cols``, ``sequence_col``).
    Pushes normally arrive through
    :meth:`~tempo_tpu.query.standing.StandingQueryEngine.push` (which
    fans them out to subscribers); :meth:`append` is the direct,
    engine-less form for batch-only use, and is refused while an
    engine owns the table.  Thread-safe: all mutable state is guarded
    by the table lock."""

    def __init__(self, name: str, ts_col: str,
                 partition_cols: Sequence[str],
                 value_cols: Sequence[str], *,
                 sequence_col: Optional[str] = None,
                 store=None, columns: Optional[Sequence[str]] = None):
        self.name = str(name)
        self.ts_col = str(ts_col)
        self.partitionCols = [str(c) for c in partition_cols]
        self.value_cols = [str(c) for c in value_cols]
        self.sequence_col = str(sequence_col) if sequence_col else None
        self.store = store
        if columns is None:
            columns = ([self.ts_col] + self.partitionCols
                       + self.value_cols
                       + ([self.sequence_col] if self.sequence_col
                          else []))
        self.columns = [str(c) for c in columns]
        for c in ([self.ts_col] + self.partitionCols + self.value_cols
                  + ([self.sequence_col] if self.sequence_col else [])):
            if c not in self.columns:
                raise ValueError(
                    f"StreamTable {self.name!r}: declared column "
                    f"{c!r} is missing from the schema {self.columns}")
        self._lock = threading.RLock()
        self.version = 0          # guarded-by: self._lock
        self._tail: List[pd.DataFrame] = []   # guarded-by: self._lock
        self.tail_rows = 0        # guarded-by: self._lock
        self._history = None      # guarded-by: self._lock
        self._history_gen = None  # guarded-by: self._lock
        #: the adopting StandingQueryEngine, if any — while set,
        #: direct append() is refused (it would bypass the engine's
        #: watermarks and corrupt the per-boundary base row counts the
        #: join carries index against); released on engine close
        self._engine = None       # guarded-by: self._lock

    # -- admission ------------------------------------------------------

    def _normalize(self, df: pd.DataFrame) -> pd.DataFrame:
        missing = [c for c in self.columns if c not in df.columns]
        if missing:
            raise ValueError(
                f"push to table {self.name!r} is missing columns "
                f"{missing} (schema: {self.columns})")
        return df[self.columns].reset_index(drop=True)

    def _row_keys(self, df: pd.DataFrame) -> List[tuple]:
        cols = [df[c].to_numpy() for c in self.partitionCols]
        n = len(df)
        return [tuple(c[i] for c in cols) for i in range(n)]

    def prepare(self, df: pd.DataFrame):
        """Normalize one pushed frame: ``(frame, keys, ts_ns, seq)``
        with per-row series-key tuples, int64-ns timestamps and the
        NULLS-FIRST seq plane — the shared currency of admission and
        member dispatch.  Does NOT append."""
        df = self._normalize(df)
        ts_ns = packing.series_to_ns(df[self.ts_col])
        if self.sequence_col:
            seq = _seq_sort_key(
                pd.to_numeric(df[self.sequence_col]).to_numpy(np.float64))
        else:
            seq = np.full(len(df), -np.inf, np.float64)
        return df, self._row_keys(df), ts_ns, seq

    def commit(self, df: pd.DataFrame) -> None:
        """Append one admitted (already watermark-validated) frame to
        the live tail."""
        with self._lock:
            if len(df):
                self._tail.append(df)
                self.tail_rows += len(df)
            self.version += 1

    def append(self, df: pd.DataFrame) -> int:
        """Direct, engine-less append (no subscriber fanout, no
        watermark check beyond schema) — batch-only ingestion.  Refused
        once a standing-query engine has adopted the table: a direct
        append would slip rows past the engine's watermarks and shift
        the snapshot row indices its join carries point at — route live
        data through ``engine.push(table, df)`` instead."""
        with self._lock:
            if self._engine is not None:
                raise RuntimeError(
                    f"StreamTable {self.name!r} is adopted by a "
                    f"standing-query engine: direct append() would "
                    f"bypass its watermarks and subscriber carries — "
                    f"push through StandingQueryEngine.push(table, df)")
        df, _, _, _ = self.prepare(df)
        self.commit(df)
        return len(df)

    # -- the unified snapshot ------------------------------------------

    def _history_df(self) -> Optional[pd.DataFrame]:  # guarded-by: self._lock
        if self.store is None:
            return None
        cur = self.store.current(self.name)
        if cur is None:
            return None
        gen = cur[0]
        if self._history is None or self._history_gen != gen:
            self._history = self._normalize(self.store.read(self.name))
            self._history_gen = gen
        return self._history

    def snapshot_df(self) -> pd.DataFrame:
        """History ∪ tail in arrival order, projected to the schema."""
        with self._lock:
            parts = []
            hist = self._history_df()
            if hist is not None and len(hist):
                parts.append(hist)
            parts.extend(self._tail)
            if not parts:
                return pd.DataFrame({c: pd.Series([], dtype="float64")
                                     for c in self.columns})
            if len(parts) == 1:
                return parts[0].copy()
            return pd.concat(parts, ignore_index=True)

    def state_token(self) -> tuple:
        """What a compiled plan over this table is keyed by: version
        counter + committed store generation + tail length."""
        with self._lock:
            gen = None
            if self.store is not None:
                cur = self.store.current(self.name)
                gen = cur[0] if cur is not None else None
            return (self.name, self.version, gen, self.tail_rows)

    def rows_total(self) -> int:
        with self._lock:
            hist = self._history_df()
            return (len(hist) if hist is not None else 0) + self.tail_rows

    def prefix_df(self, rows: int) -> pd.DataFrame:
        """The first ``rows`` rows of the unified snapshot (resume
        replay cursor)."""
        return self.snapshot_df().iloc[:rows].reset_index(drop=True)

    # -- store sync -----------------------------------------------------

    def sync_to_store(self) -> Optional[dict]:
        """Persist the unified snapshot as a new store generation and
        truncate the live tail.  Rows are written with EMPTY sort_cols
        — arrival order is the table's bitwise identity (it drives the
        packed layouts' key factorization), so the store must preserve
        it verbatim; a later ``store.compact`` keeps it too (compaction
        re-clusters by the commit's recorded sort_cols, also empty)."""
        if self.store is None:
            raise ValueError(
                f"StreamTable {self.name!r} has no store to sync to")
        with self._lock:
            df = self.snapshot_df()
            stats = self.store.write_table(
                self.name, df, [],
                source_fp=f"standing:{self.name}:v{self.version}:"
                          f"rows{len(df)}")
            self._tail = []
            self.tail_rows = 0
            self._history = None
            self._history_gen = None
            self.version += 1
            return stats

    # -- plan integration ----------------------------------------------

    def frame(self):
        """A lazy frame over this table's ``unified_scan`` node — use
        it exactly like a planned TSDF (method chains, SQL ``tables=``
        entries, ``register``)."""
        from tempo_tpu.plan import ir, lazy

        return lazy.wrap(ir.Node("unified_scan",
                                 payload=UnifiedSource(self)))

    def __repr__(self) -> str:
        with self._lock:
            rows, ver = self.rows_total(), self.version
        return f"StreamTable({self.name!r}, rows={rows}, v{ver})"


class UnifiedSource:
    """Payload of a ``unified_scan`` plan node: the TSDF-shaped view
    of one :class:`StreamTable` snapshot.  Duck-types the source-frame
    surface the optimizer touches (``df`` / ``ts_col`` /
    ``partitionCols`` / ``sequence_col``) and pins one snapshot per
    table version so a single plan execution never sees a torn
    read."""

    def __init__(self, table: StreamTable):
        self.table = table
        self._pin: Optional[Tuple[tuple, pd.DataFrame]] = None

    @property
    def ts_col(self) -> str:
        return self.table.ts_col

    @property
    def partitionCols(self) -> List[str]:
        return self.table.partitionCols

    @property
    def sequence_col(self) -> Optional[str]:
        return self.table.sequence_col

    @property
    def columns(self) -> List[str]:
        return self.table.columns

    @property
    def df(self) -> pd.DataFrame:
        token = self.table.state_token()
        if self._pin is None or self._pin[0] != token:
            self._pin = (token, self.table.snapshot_df())
        return self._pin[1]

    def materialize(self):
        from tempo_tpu.frame import TSDF

        return TSDF(self.df, self.table.ts_col,
                    self.table.partitionCols,
                    self.table.sequence_col or None)

    def _unified_state(self) -> tuple:
        """The ``plan.ir._frame_state`` entry for unified sources."""
        return ("unified",) + self.table.state_token() + (
            tuple(self.table.columns), self.table.ts_col,
            tuple(self.table.partitionCols),
            self.table.sequence_col or "")

    def __repr__(self) -> str:
        return f"UnifiedSource({self.table!r})"
