"""The standing-query split pass: one registered plan, two programs.

``canonicalize`` rewrites every host-side ``EMA(exact=True)`` node
into the ``ema_stream`` IR op, whose batch kernel is
``ops/rolling.ema_scan`` — the sequential (one multiply-add per
element) twin of ``ema_exact`` with an explicit carry.  The sequential
form is **split-invariant bitwise** (feeding the carry across any
batch boundary reproduces the unsplit run bit-for-bit), which is the
contract the serving plane's EMA carry resumes; ``ema_exact``'s
``associative_scan`` bracketing — and therefore its f32 rounding —
depends on the total length, so it cannot be resumed mid-stream.  The
canonical plan IS the registered query: ``explain()`` renders the
rewrite, and the standing results are bitwise what re-running this
canonical plan over the concatenated history produces.

``split`` then classifies the canonical plan against the incremental
surface:

* **stateless** — row-local ops only (``select`` / ``sql_project`` /
  ``sql_filter``) over one ``unified_scan``: each push's delta is the
  suffix applied to the new rows, no carry at all;
* **delta** — a run of ``ema_stream`` nodes (one shared alpha — the
  serving config carries a single EMA coefficient) or one bottom
  ``asof_join`` between two stream tables, plus a row-local suffix:
  the incremental program reuses the serve-plane carries through the
  cohort executor, AOT-compiled and shape-bucketed so steady state is
  zero-recompile;
* **remainder** — everything else (centred/trailing window stats,
  resample, interpolate, mesh chains, seq-bearing join right sides,
  EMA above a join...): the full canonical plan re-runs over the
  unified scan on a periodic cadence — correct by construction, paid
  as a batch job.  ``StandingPlan.reason`` names what forced the
  fallback.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

import numpy as np

from tempo_tpu.plan import ir

#: Ops whose output rows depend only on their own input row — applying
#: them to a delta frame is bitwise applying them to the same rows of
#: the concatenated history (the SQL parity gate pins planned==eager
#: for all three, so the delta path evaluates them eagerly with zero
#: compiles).
ROW_LOCAL_OPS = ("select", "sql_project", "sql_filter")

__all__ = ["canonicalize", "split", "StandingPlan", "EmaSpec",
           "JoinSpec", "eval_ema_stream", "ROW_LOCAL_OPS"]


@dataclasses.dataclass
class EmaSpec:
    col: str
    alpha: float


@dataclasses.dataclass
class JoinSpec:
    left: object                  # StreamTable
    right: object                 # StreamTable
    right_prefix: str
    skip_nulls: bool
    max_lookback: int


@dataclasses.dataclass
class StandingPlan:
    """The split decision for one registered query."""

    root: ir.Node                 # canonical plan (the registered query)
    mode: str                     # "stateless" | "delta" | "remainder"
    tables: List[object]          # every StreamTable the plan scans
    table: Optional[object] = None       # delta/stateless: driving table
    join: Optional[JoinSpec] = None      # delta join spec
    emas: List[EmaSpec] = dataclasses.field(default_factory=list)
    suffix: List[ir.Node] = dataclasses.field(default_factory=list)
    reason: str = ""              # why the remainder path, when it is

    @property
    def signature(self) -> str:
        return ir.signature(self.root)


def _on_mesh_below(node: ir.Node) -> bool:
    return any(n.op in ("on_mesh", "dist_source") for n in node.walk())


def canonicalize(root: ir.Node) -> ir.Node:
    """Rewrite host-side ``EMA(exact=True)`` nodes to ``ema_stream``
    (see module docstring).  Returns a fresh DAG; recorded nodes are
    never mutated (the caller's lazy frame stays replayable as-is)."""
    memo = {}

    def rec(n: ir.Node) -> ir.Node:
        got = memo.get(id(n))
        if got is not None:
            return got
        ins = tuple(rec(c) for c in n.inputs)
        if (n.op == "ema" and n.param("exact") is True
                and not _on_mesh_below(n)):
            out = ir.Node("ema_stream", params=dict(
                colName=n.param("colName"),
                exp_factor=float(n.param("exp_factor", 0.2))),
                inputs=ins)
        elif any(a is not b for a, b in zip(ins, n.inputs)):
            out = ir.Node(n.op, params=dict(n.params), inputs=ins,
                          payload=n.payload, objs=n.objs)
        else:
            out = n
        memo[id(n)] = out
        return out

    return rec(root)


def _table_of(node: ir.Node):
    if node.op == "unified_scan":
        return node.payload.table
    return None


def split(root: ir.Node) -> StandingPlan:
    """Classify one canonical plan (see module docstring)."""
    tables = [n.payload.table for n in root.walk()
              if n.op == "unified_scan"]

    def remainder(reason: str) -> StandingPlan:
        return StandingPlan(root=root, mode="remainder", tables=tables,
                            reason=reason)

    if not tables:
        return remainder("plan scans no StreamTable (no unified_scan "
                         "source)")

    suffix: List[ir.Node] = []
    n = root
    while n.op in ROW_LOCAL_OPS:
        suffix.append(n)
        n = n.inputs[0]
    suffix.reverse()              # application order, bottom-up

    emas: List[EmaSpec] = []
    while n.op == "ema_stream":
        emas.append(EmaSpec(col=str(n.param("colName")),
                            alpha=float(n.param("exp_factor", 0.2))))
        n = n.inputs[0]
    emas.reverse()

    if n.op == "unified_scan":
        table = n.payload.table
        if not emas:
            return StandingPlan(root=root, mode="stateless",
                                tables=tables, table=table,
                                suffix=suffix)
        cols = [e.col for e in emas]
        bad = [c for c in cols if c not in table.value_cols]
        if bad:
            return remainder(f"EMA over non-streamed column(s) {bad} "
                             f"(table {table.name!r} streams "
                             f"{table.value_cols})")
        if len(set(cols)) != len(cols):
            return remainder(f"repeated EMA column(s) in {cols}: the "
                             f"serving carry holds one EMA per column")
        alphas = {e.alpha for e in emas}
        if len(alphas) != 1:
            return remainder(f"mixed EMA alphas {sorted(alphas)}: the "
                             f"serving config carries a single "
                             f"coefficient")
        return StandingPlan(root=root, mode="delta", tables=tables,
                            table=table, emas=emas, suffix=suffix)

    if n.op == "asof_join" and not emas:
        left_n, right_n = n.inputs[0], n.inputs[1]
        left, right = _table_of(left_n), _table_of(right_n)
        if left is None or right is None:
            return remainder("asof_join over a non-StreamTable side")
        if n.param("tsPartitionVal") is not None:
            return remainder("tsPartitionVal (skew-bracketed join) is "
                             "not an incremental carry")
        if n.param("sql_join_opt"):
            return remainder("sql_join_opt (broadcast inner join) "
                             "changes row semantics; batch remainder")
        if n.param("left_prefix"):
            return remainder("left_prefix renames the left side; "
                             "batch remainder")
        if left is right:
            return remainder(
                "self-join over one stream table: each push's rows "
                "enter BOTH merged sides at once, so per-push arrival "
                "order and the batch merged order diverge; batch "
                "remainder")
        if left.sequence_col:
            return remainder(
                f"left table {left.name!r} carries a sequence column: "
                f"the batch join orders left rows NULLS-FIRST "
                f"regardless of their sequence values, so an "
                f"incremental carry honoring them would diverge "
                f"bitwise; batch remainder")
        if right.sequence_col:
            return remainder(
                f"right table {right.name!r} carries a sequence "
                f"column: the prefixed right seq output column needs "
                f"the merged-stream per-column carry; batch remainder")
        if left.partitionCols != right.partitionCols:
            return remainder("asof_join sides disagree on partition "
                             "columns")
        return StandingPlan(
            root=root, mode="delta", tables=tables, table=left,
            join=JoinSpec(
                left=left, right=right,
                right_prefix=str(n.param("right_prefix") or "right"),
                skip_nulls=bool(n.param("skipNulls", True)),
                max_lookback=int(n.param("maxLookback", 0) or 0)),
            suffix=suffix)

    return remainder(f"op {n.op!r} has no incremental carry")


# ----------------------------------------------------------------------
# The ema_stream batch kernel (plan/executor.py dispatches here)
# ----------------------------------------------------------------------

def eval_ema_stream(tsdf, col: str, alpha: float):
    """Batch evaluation of one ``ema_stream`` node: the sequential
    split-invariant EMA (``ops/rolling.ema_scan``) over the packed
    layout, assembled exactly like ``rolling.ema`` (layout row order,
    ``EMA_<col>`` widened to float64)."""
    import jax.numpy as jnp

    from tempo_tpu import packing
    from tempo_tpu.frame import TSDF
    from tempo_tpu.ops import rolling as ops_rolling

    if not len(tsdf.df):
        out = tsdf.df.copy()
        out["EMA_" + col] = np.array([], np.float64)
        return TSDF(out, tsdf.ts_col, tsdf.partitionCols,
                    tsdf.sequence_col or None)
    layout = tsdf.layout
    v, m = tsdf.packed_numeric(col)
    # compute at f32: the serving plane's carry IS f32 (state.py pins
    # the ema_y plane), and the standing==batch bitwise contract is
    # only meaningful with both sides at the same precision
    ys, _ = ops_rolling.ema_scan(jnp.asarray(np.asarray(v, np.float32)),
                                 jnp.asarray(m), np.float32(alpha))
    out = tsdf.df.iloc[layout.order].reset_index(drop=True)
    out["EMA_" + col] = packing.unpack_column(
        np.asarray(ys), layout).astype(np.float64)
    return TSDF(out, tsdf.ts_col, tsdf.partitionCols,
                tsdf.sequence_col or None)
