"""Continuous queries: standing plans over live streams.

Register a planned method chain or SQL statement as a **standing
query** over :class:`~tempo_tpu.query.unified.StreamTable` streams:
every admitted push fans out to subscribers as an incremental delta,
and the accumulated standing result is bitwise identical to re-running
the registered batch query over the concatenated history at every push
boundary.  See :mod:`tempo_tpu.query.standing` for the engine,
:mod:`tempo_tpu.query.split` for the incremental/remainder split pass,
and :mod:`tempo_tpu.query.unified` for the history+live unified scan.
"""

# NOTE: the split PASS lives in the `split` submodule; it is not
# re-exported here because the bare name would shadow the submodule
# attribute on the package (plan/executor dispatches through
# `tempo_tpu.query.split`).  Use `query.split.split(root)` /
# `query.split.canonicalize(root)` directly.
from tempo_tpu.query.split import EmaSpec, JoinSpec, StandingPlan
from tempo_tpu.query.standing import (Notification, StandingQueryEngine,
                                      Subscription, resume_subscription,
                                      snapshot_subscription)
from tempo_tpu.query.unified import StreamTable, UnifiedSource

__all__ = [
    "StreamTable", "UnifiedSource",
    "StandingQueryEngine", "Subscription", "Notification",
    "snapshot_subscription", "resume_subscription",
    "StandingPlan", "EmaSpec", "JoinSpec",
]
