"""DistributedTSDF: the device mesh wired into the frame-level API.

In the reference every op is distributed *by construction* because
``Window.partitionBy``/shuffle is the execution substrate
(/root/reference/python/tempo/tsdf.py:121,571).  This module gives
tempo-tpu the same property: ``TSDF.on_mesh(...)`` packs the frame once
into mesh-sharded ``jax.Array``s and returns a :class:`DistributedTSDF`
whose op methods (``asofJoin`` / ``withRangeStats`` / ``EMA`` /
``resample``) run as shard_map programs over the mesh — data parallel
over the ``series`` axis, sequence parallel with halo exchange over the
``time`` axis — with results staying device-resident across chained
ops.  ``collect()`` materialises back to a host :class:`TSDF` with ONE
stacked device->host transfer.

This is also the single-chip device-residency mechanism: on a 1-device
mesh a chain of N ops performs exactly one pack and one unpack
(``_PACK_EVENTS`` / ``_FETCH_EVENTS`` count them for the tests), where
the host frame path would re-pack per op.

Design notes:

* Shard boundaries on the ``time`` axis are positional (each packed row
  is ascending reals then ``TS_PAD`` pads), and lookback windows read
  their history through a trailing neighbor halo
  (:mod:`tempo_tpu.parallel.halo`).  For the AS-OF join this mirrors
  the reference's ``tsPartitionVal`` contract exactly: a match further
  back than the halo yields a null plus a *deferred audit* warning (the
  reference's missing-lookback warning, tsdf.py:150-159) — audits are
  device scalars fetched at ``collect()`` so chains stay sync-free.
* Timestamps compute in int64 ns on device.  The joined right
  timestamp column is carried through the value-gather path as three
  21-bit chunk planes (each exact in float32) and recomposed to exact
  int64 ns at collect.
* Non-numeric columns stay on host and re-join the frame at collect
  (they are untouched by the device ops, like Spark columns that no
  expression references).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tempo_tpu import packing
from tempo_tpu.freq import (
    freq_to_seconds, validateFuncExists, floor, ceiling, average,
    min_func, max_func,
)
from tempo_tpu.ops import asof as asof_ops
from tempo_tpu.ops import rolling as rk
from tempo_tpu.ops.sortmerge import use_sort_kernels as _use_sort_kernels
from tempo_tpu.parallel import halo as ph
from tempo_tpu.parallel.halo import shard_map
from tempo_tpu.parallel.mesh import make_mesh

logger = logging.getLogger(__name__)

# transfer-count instrumentation: a chain of N ops must do 1 pack + 1
# fetch (tests assert this; the host frame path re-packs per op)
_PACK_EVENTS = 0
_FETCH_EVENTS = 0


@dataclasses.dataclass(frozen=True)
class DistCol:
    """One device-resident column: values + validity, with
    materialisation hints."""

    values: jax.Array          # [K_dev, L] compute dtype
    valid: jax.Array           # [K_dev, L] bool
    int64: bool = False        # cast to int64 at collect (counts)
    # (target ts column, bit shift): this col is one 21-bit chunk of an
    # int64-ns timestamp — three such planes recompose the ts EXACTLY
    # at collect even when the compute dtype is float32 (2^21 < 2^24)
    ts_chunk: Optional[Tuple[str, int]] = None
    # (flat host values [n_right_rows], right starts [K_r+1], perm
    # [K_dev] left->right series map): ``values`` holds matched right
    # ROW indices (f32-exact below 2^24) and collect() gathers the
    # host-resident (non-numeric) data — device never sees object dtypes
    host_gather: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None


def _spec(mesh: Mesh, series_axis, time_axis: Optional[str],
          ndim: int = 2) -> P:
    lead = [None] * (ndim - 2)
    return P(*(lead + [series_axis, time_axis]))


def _ns(mesh: Mesh, spec: P) -> NamedSharding:
    """NamedSharding for a stage-boundary declaration: every chained
    shard_map program below jits with explicit ``in_shardings`` /
    ``out_shardings`` built from its own shard specs, so stage N's
    output layout IS stage N+1's input layout by construction — a
    mis-laid operand raises at dispatch instead of compiling an
    implicit reshard (the zero-undeclared-collectives contract of the
    mesh chain, checked compiled-side by the stage-sharding-match rule
    in tools/analysis/compiled)."""
    return NamedSharding(mesh, spec)


def stream_mesh(n_devices: Optional[int] = None,
                stream_axis: str = "streams") -> Mesh:
    """A 1-D mesh whose single axis is the cohort STREAM axis — the
    fleet-serving layout (serve/cohort.py): scale-out is
    stream-parallel, so the whole device budget goes to one axis and
    every cohort state array shards its leading [S] dim across it."""
    devs = jax.devices()
    n = len(devs) if n_devices is None else int(n_devices)
    return Mesh(np.asarray(devs[:n]).reshape(n), (stream_axis,))


def stream_shardings(mesh: Mesh, stream_axis: str, tree):
    """Same-structure tree of ``NamedSharding(mesh, P(stream_axis))``
    for every leaf of ``tree`` (avals or arrays): axis 0 — the cohort
    stream axis — sharded, everything else replicated per shard.  The
    cohort step programs jit with this as BOTH ``in_shardings`` and
    ``out_shardings`` (:func:`serve.state.cohort_push_jitted`), the
    PR 10 pre-partitioned handoff: the compiled loop's output layout
    is its own input layout, so the steady state never implies a
    reshard and the compiled HLO carries zero collectives
    (``profiling.collective_counts_from_compiled`` — asserted by the
    ``serve.cohort_push`` compiled contract and the fleet bench)."""
    sh = _ns(mesh, P(stream_axis))
    return jax.tree_util.tree_map(lambda _: sh, tree)


class DistributedTSDF:
    """A TSDF whose packed cache is a sharded ``jax.Array`` on a device
    mesh and whose ops run distributed (SURVEY.md §2.3)."""

    def __init__(self, mesh: Mesh, series_axis: str,
                 time_axis: Optional[str], ts, mask,
                 cols: Dict[str, DistCol], layout, ts_col: str,
                 partition_cols: List[str], ts_dtype, source_df,
                 host_cols: Dict[str, str], halo_fraction: float,
                 audits: Optional[List[Tuple[str, jax.Array]]] = None,
                 resampled: bool = False, seq=None, seq_col: str = "",
                 resample_freq: Optional[str] = None):
        self.mesh = mesh
        self.series_axis = series_axis
        self.time_axis = time_axis
        self.ts = ts                      # [K_dev, L] int64 ns, TS_PAD pads
        self.mask = mask                  # [K_dev, L] bool (real rows)
        self.cols = cols
        self.layout = layout
        self.ts_col = ts_col
        self.partitionCols = list(partition_cols)
        self._ts_dtype = ts_dtype
        self._source_df = source_df
        self.host_cols = dict(host_cols)   # output name -> source column
        self.halo_fraction = halo_fraction
        self.audits = list(audits or [])
        self.resampled = resampled
        self.seq = seq                    # [K_dev, L] sort key or None
        self.seq_col = seq_col
        self._resample_freq = resample_freq

    # ------------------------------------------------------------------
    # Construction
    # ------------------------------------------------------------------

    @property
    def n_time(self) -> int:
        return self.mesh.shape[self.time_axis] if self.time_axis else 1

    @property
    def n_series_shards(self) -> int:
        # a series-LOCAL re-laid frame (reshard_frame) shards its K axis
        # jointly over ('series', 'time'): the axis name is a tuple and
        # the shard count is the product
        if isinstance(self.series_axis, tuple):
            return int(np.prod([self.mesh.shape[a]
                                for a in self.series_axis]))
        return self.mesh.shape[self.series_axis]

    @property
    def L(self) -> int:
        return int(self.ts.shape[1])

    @property
    def K_dev(self) -> int:
        return int(self.ts.shape[0])

    def _sharding(self, ndim: int = 2) -> NamedSharding:
        return NamedSharding(
            self.mesh, _spec(self.mesh, self.series_axis, self.time_axis, ndim)
        )

    @classmethod
    def from_tsdf(cls, tsdf, mesh: Optional[Mesh] = None,
                  series_axis: str = "series",
                  time_axis: Optional[str] = None,
                  halo_fraction: float = 0.5) -> "DistributedTSDF":
        """Pack + shard a host TSDF onto the mesh (the ingest boundary —
        the analog of Spark's shuffle-on-partition-cols).  ONE
        host->device transfer for the whole frame."""
        global _PACK_EVENTS
        mesh = mesh if mesh is not None else make_mesh()
        if time_axis is not None and time_axis not in mesh.axis_names:
            raise ValueError(f"mesh has no axis named {time_axis!r}")
        n_s = mesh.shape[series_axis]
        n_t = mesh.shape[time_axis] if time_axis else 1

        layout = tsdf.layout
        K_dev, L, n_s, n_t = _mesh_packed_geometry(
            layout, mesh, series_axis, time_axis)

        dt = packing.compute_dtype()
        ts_p = packing.pack_column(layout.ts_ns, layout, L, fill=packing.TS_PAD)
        mask_p = packing.row_mask(layout, L)
        ts_p = _pad_k(ts_p, K_dev, packing.TS_PAD)
        mask_p = _pad_k(mask_p, K_dev, False)

        cols: Dict[str, DistCol] = {}
        host_cols: Dict[str, str] = {}
        structural = {tsdf.ts_col, *tsdf.partitionCols}
        seq_p = None
        if tsdf.sequence_col:
            structural.add(tsdf.sequence_col)
            # the sequence column is both an output column (it rides the
            # host row-identity path like any structural col) and a
            # device-resident join sort key.  A null RIGHT sequence
            # sorts FIRST (-inf in the float total order) per Spark's
            # ASC NULLS FIRST (tsdf.py:117-121), matching the host merge
            # path (join.py); values beyond 2^24 lose exactness under
            # the f32 policy.
            host_cols[tsdf.sequence_col] = tsdf.sequence_col
            sv, sm_ = tsdf.numeric_flat(tsdf.sequence_col)
            sv = np.where(sm_, sv, -np.inf).astype(dt)
            seq_p = _pad_k(
                packing.pack_column(sv, layout, L, fill=np.inf),
                K_dev, np.inf,
            )
        for c in tsdf.df.columns:
            if c in structural:
                continue
            dtype = tsdf.df[c].dtype
            if pd.api.types.is_numeric_dtype(dtype) and not \
                    pd.api.types.is_bool_dtype(dtype):
                vals, valid = tsdf.numeric_flat(c)
                if pd.api.types.is_integer_dtype(dtype) and valid.any() \
                        and np.abs(vals[valid]).max() >= 2.0 ** 53:
                    # integers beyond float64's exact range (2^53) can't
                    # ride the float compute planes without corruption —
                    # they stay host-resident (exact row-identity /
                    # join-index gather), like non-numeric columns
                    host_cols[c] = c
                    continue
                pv = packing.pack_column(vals.astype(dt), layout, L, fill=np.nan)
                pm = packing.pack_column(valid, layout, L, fill=False)
                cols[c] = DistCol(_pad_k(pv, K_dev, np.nan),
                                  _pad_k(pm, K_dev, False))
            else:
                host_cols[c] = c

        sharding = NamedSharding(mesh, _spec(mesh, series_axis, time_axis))
        put = _put_global(sharding)
        ts_d = put(ts_p)
        mask_d = put(mask_p)
        cols_d = {
            c: DistCol(put(col.values), put(col.valid))
            for c, col in cols.items()
        }
        seq_d = put(seq_p) if seq_p is not None else None
        _PACK_EVENTS += 1
        return cls(mesh, series_axis, time_axis, ts_d, mask_d, cols_d,
                   layout, tsdf.ts_col, tsdf.partitionCols,
                   tsdf.ts_dtype(), tsdf.df, host_cols, halo_fraction,
                   seq=seq_d, seq_col=tsdf.sequence_col or "")

    def _plan_record(self, op: str, others=(), params=None, objs=None):
        """Record a deferred plan node over this (already packed) mesh
        frame instead of executing (``TEMPO_TPU_PLAN=1``); the lazy
        wrapper's ``collect()`` optimizes + executes through the plan
        executable cache (tempo_tpu/plan/)."""
        from tempo_tpu.plan import lazy as plan_lazy

        return plan_lazy.record(self, op, others, params, objs)

    def explain(self, cost: bool = False) -> str:
        """Render this frame's query plan (bare mesh source when
        eager; the lazy wrappers show recorded chains + optimizer
        rewrites)."""
        from tempo_tpu.plan import ir, render

        text = render.explain_text(ir.Node("dist_source", payload=self),
                                   cost=cost)
        print(text)
        return text

    def _with(self, **kw) -> "DistributedTSDF":
        base = dict(
            mesh=self.mesh, series_axis=self.series_axis,
            time_axis=self.time_axis, ts=self.ts, mask=self.mask,
            cols=self.cols, layout=self.layout, ts_col=self.ts_col,
            partition_cols=self.partitionCols, ts_dtype=self._ts_dtype,
            source_df=self._source_df, host_cols=self.host_cols,
            halo_fraction=self.halo_fraction, audits=self.audits,
            resampled=self.resampled, seq=self.seq, seq_col=self.seq_col,
            resample_freq=self._resample_freq,
        )
        base.update(kw)
        return DistributedTSDF(**base)

    def numeric_columns(self) -> List[str]:
        return [c for c, col in self.cols.items()
                if col.ts_chunk is None and col.host_gather is None]

    def _window_rowbounds(self, window_secs: float) -> Optional[Tuple[int, int]]:
        """Static (max rows back, max tie rows ahead) any rangeBetween
        (-window_secs, 0) frame spans, from the host layout.  Cached per
        window size; O(n) numpy per series.

        Returns None when the layout's timestamps cannot vouch for the
        device timestamps — resampled frames (device ts are bucket
        floors, layout still holds raw ts) and ingest-assembled frames
        (layout carries offsets only, ts_ns is empty) — so callers fall
        back to the data-independent exact kernels."""
        lay = self.layout
        if (self.resampled or lay.n_rows == 0
                or int(lay.starts[-1]) != lay.n_rows):
            return None
        return packing.layout_rowbounds(lay, window_secs)

    def _halo(self, L: int) -> int:
        shard = L // self.n_time
        return max(1, min(shard, int(shard * self.halo_fraction)))

    def _range_engine_choice(self, window_secs: float):
        """``(engine, rowbounds, sort_kernels)`` — the three-way
        range-stats engine decision for this frame's shard shape, shared
        by the eager :meth:`withRangeStats`, the plan optimizer's
        plan-time hoist (via :func:`plan_range_engine_choice`), and the
        fused-chain executor (plan/fused.py).  On TPU, row-boundable
        windows run gather-free as shifted masked accumulations
        (ops/sortmerge.py); bounds come from the host layout once per
        window size."""
        sort_kernels = _use_sort_kernels()
        if not sort_kernels:
            return "shifted", None, sort_kernels
        rb = self._window_rowbounds(window_secs)
        # per-device shard element count bounds the unrolled form's
        # HBM footprint (ops/rolling.py:shifted_row_budget); on the
        # exact strategy the kernel computes over series-local FULL
        # rows (the a2a layout switch), so the shard is K/devices
        # by the full L.  Same three-way pick as the host frame
        # (ops/rolling.pick_range_engine): shifted / streaming VMEM
        # sweep / prefix+RMQ fallback.
        shard_k = self.K_dev // (self.n_series_shards
                                 * max(self.n_time, 1))
        engine, rowbounds = _pick_range_engine_for_shard(shard_k, self.L,
                                                         rb)
        return engine, rowbounds, sort_kernels

    # ------------------------------------------------------------------
    # withRangeStats (tsdf.py:673-721)
    # ------------------------------------------------------------------

    def withRangeStats(self, colsToSummarize=None,
                       rangeBackWindowSecs: int = 1000,
                       strategy: str = "exact") -> "DistributedTSDF":
        """Distributed rolling range stats.  On a time-sharded mesh:

        * ``strategy="exact"`` (default) — switch to a series-local
          layout with one all_to_all each way and compute the exact
          Spark rangeBetween semantics regardless of window size.
        * ``strategy="halo"`` — stay time-sharded and read the lookback
          through a trailing neighbor-halo exchange (O(halo) comm
          instead of O(L)); windows longer than the halo truncate, and
          a deferred audit (collect-time warning) counts affected rows
          — the reference's own tsPartitionVal trade-off
          (tsdf.py:164-190).
        """
        if strategy not in ("exact", "halo"):
            raise ValueError("strategy must be 'exact' or 'halo'")
        from tempo_tpu import plan

        if plan.recording():
            return self._plan_record("range_stats", params=dict(
                colsToSummarize=tuple(colsToSummarize)
                if colsToSummarize else None,
                rangeBackWindowSecs=rangeBackWindowSecs,
                strategy=strategy))
        if strategy == "exact" and self.n_time > 1:
            # exact stats on a time-sharded mesh: ONE explicit
            # whole-frame reshard to the series-local layout
            # (reshard_frame — the same program the planner's
            # plan-placed reshard nodes run), the SAME local stats
            # program every series-local frame runs, and one switch
            # back.  The former in-kernel all_to_all sandwich
            # (_range_stats_a2a_packed) compiled the collectives INTO
            # the stats program, and XLA's FMA-contraction decisions
            # around the cancellation-sensitive var/stddev math
            # drifted in the last ulp vs the series-local program —
            # which would have broken the plan optimizer's
            # reshard-elimination bitwise contract (planned chains
            # elide the interior switches and so MUST run the
            # series-local program).
            local = reshard_frame(self, RESHARD_SERIES_LOCAL)
            out = local.withRangeStats(
                colsToSummarize=colsToSummarize,
                rangeBackWindowSecs=rangeBackWindowSecs,
                strategy=strategy)
            return reshard_frame(out, RESHARD_TIME_SHARDED)
        cols = colsToSummarize or self.numeric_columns()
        w = float(rangeBackWindowSecs)
        new_cols = dict(self.cols)
        audits = list(self.audits)
        if strategy == "exact":
            engine, rowbounds, sort_kernels = self._range_engine_choice(w)
        else:
            engine, rowbounds, sort_kernels = \
                "shifted", None, _use_sort_kernels()
        if cols and (strategy == "exact" or self.n_time <= 1):
            # (a single-shard "halo" strategy has no halo to exchange —
            # it runs the local path exactly like the seed did)
            # multi-column payload packing: ONE shard_map program over
            # the [C, K, L] column stack — the timestamp planes stream
            # once per kernel pack instead of once per column, and the
            # per-op dispatch cost stops scaling with C.  Per-column
            # results are bitwise-identical to the per-column programs
            # (_range_stats_block_packed).
            xs = jnp.stack([self.cols[c].values for c in cols])
            vs = jnp.stack([self.cols[c].valid for c in cols])
            stats, rb_clipped = _range_stats_local_packed(
                self.mesh, self.series_axis, w, rowbounds,
                sort_kernels, engine,
            )(self.ts, xs, vs)
            for ci, c in enumerate(cols):
                if strategy == "exact" and rowbounds is not None:
                    # deferred truncation audit of the shifted-window
                    # form: the host-derived row bounds must cover
                    # every frame (they do by construction — this
                    # catches bound-derivation bugs and device/layout
                    # ts divergence)
                    audits.append((
                        f"withRangeStats({c}): %d rows had window "
                        f"frames extending past the static row bounds "
                        f"{rowbounds}; this is a tempo-tpu bug — "
                        f"please report it", rb_clipped[ci],
                    ))
                for stat in packing.RANGE_STATS:
                    new_cols[f"{stat}_{c}"] = DistCol(
                        stats[stat][ci], self.mask,
                        int64=(stat == "count"),
                    )
            return self._with(cols=new_cols, audits=audits)
        for c in cols:
            col = self.cols[c]
            halo = self._halo(self.L)
            stats, clipped = _range_stats_halo(
                self.mesh, self.series_axis, self.time_axis, w, halo,
            )(self.ts, col.values, col.valid)
            audits.append((
                f"withRangeStats({c}): %d rows had windows truncated "
                f"at the time-shard halo ({halo} rows); increase the "
                f"halo_fraction or shard count", clipped,
            ))
            for stat in packing.RANGE_STATS:
                new_cols[f"{stat}_{c}"] = DistCol(
                    stats[stat], self.mask, int64=(stat == "count"),
                )
        return self._with(cols=new_cols, audits=audits)

    rangeStats = withRangeStats

    # ------------------------------------------------------------------
    # EMA (tsdf.py:615-635; exact scan form)
    # ------------------------------------------------------------------

    def EMA(self, colName: str, window: int = 30, exp_factor: float = 0.2,
            exact: bool = False,
            inclusive_window: bool = False) -> "DistributedTSDF":
        """Distributed EMA.  Defaults mirror ``TSDF.EMA`` (truncated-lag
        reference parity, tsdf.py:615-635) so the same call gives the
        same numbers on or off the mesh.  The exact infinite-horizon
        scan composes across time shards (associative carry stitch); the
        truncated-lag approximation does not, so time-sharded meshes
        require ``exact=True``."""
        from tempo_tpu import plan

        if plan.recording():
            return self._plan_record("ema", params=dict(
                colName=colName, window=window, exp_factor=exp_factor,
                exact=exact, inclusive_window=inclusive_window))
        col = self.cols[colName]
        if self.n_time > 1:
            if not exact:
                raise ValueError(
                    "truncated-lag EMA does not cross time shards; use "
                    "exact=True (or a series-only mesh)"
                )
            y = ph.ema_time_sharded(self.mesh, col.values, col.valid,
                                    float(exp_factor),
                                    time_axis=self.time_axis,
                                    series_axis=self.series_axis)
        else:
            n_taps = int(window) + (1 if inclusive_window else 0)
            y = _ema_local(self.mesh, self.series_axis, float(exp_factor),
                           bool(exact), n_taps)(col.values, col.valid)
        new_cols = dict(self.cols)
        new_cols["EMA_" + colName] = DistCol(y, self.mask)
        return self._with(cols=new_cols)

    # ------------------------------------------------------------------
    # asofJoin (tsdf.py:463-560, fast path)
    # ------------------------------------------------------------------

    def asofJoin(self, right: "DistributedTSDF",
                 left_prefix: Optional[str] = None,
                 right_prefix: str = "right",
                 tsPartitionVal: Optional[int] = None,
                 fraction: float = 0.5,
                 skipNulls: bool = True,
                 sql_join_opt: bool = False,
                 suppress_null_warning: bool = False,
                 maxLookback: int = 0) -> "DistributedTSDF":
        """Distributed AS-OF join.  The right frame is aligned to the
        left's series-id space with one device gather (the
        co-partitioning shuffle analog), then joined shard-locally with
        a trailing halo on time-sharded meshes.

        Right-side non-numeric (host-resident) columns join by carrying
        the matched right *row index* as a value plane (exact in f32 up
        to 2^24 rows/series) and gathering the strings host-side at
        ``collect()`` — the device never touches object data.

        Sequence-number tie-break runs device-resident when the RIGHT
        frame was built with a ``sequence_col`` — only the right's
        sequence orders the merge, mirroring the reference (left rows
        carry NULL in it and sort first on ties, tsdf.py:117-121).
        ``maxLookback`` > 0 caps the fill at the trailing maxLookback+1
        merged (left+right) rows, Scala's rowsBetween window on the
        union stream (asofJoin.scala:64-88), computed device-side via
        the windowed argmax ladder.

        ``tsPartitionVal``/``fraction``/``sql_join_opt`` are accepted
        for migration compatibility and ignored: they tune Spark's skew
        brackets and broadcast-range fast path (tsdf.py:463-509), both
        of which this join replaces — the packed layout is skew-free by
        construction and the merge join is already shuffle-free."""
        from tempo_tpu import plan

        if plan.recording():
            return self._plan_record("asof_join", (right,), dict(
                left_prefix=left_prefix, right_prefix=right_prefix,
                tsPartitionVal=tsPartitionVal, fraction=fraction,
                skipNulls=skipNulls, sql_join_opt=sql_join_opt,
                suppress_null_warning=suppress_null_warning,
                maxLookback=maxLookback))
        if tsPartitionVal is not None:
            logger.info(
                "asofJoin: tsPartitionVal ignored on the mesh — the "
                "packed layout needs no skew brackets"
            )
        if right.mesh is not self.mesh and right.mesh != self.mesh:
            raise ValueError("both frames must live on the same mesh")
        if self.partitionCols != right.partitionCols:
            raise ValueError(
                "left and right dataframe partition columns should have same name in same order"
            )

        # host-side key-space alignment (K-sized metadata only)
        perm, ok = _key_perm(self.layout.key_frame, right.layout.key_frame,
                             self.partitionCols, self.K_dev)
        align2 = _align_fn(self.mesh, self.series_axis, self.time_axis)

        # every device-resident right column joins — plain numerics,
        # ts-chunk planes from earlier joins, and host-gather index
        # planes from earlier joins (chained a.asofJoin(b.asofJoin(c))
        # must not lose the inner join's columns)
        r_recs = list(right.cols.items())
        h_names = [c for c in right.host_cols
                   if right._source_df is not None]
        r_ts_al = align2(right.ts, perm, ok, packing.TS_PAD)

        dt = packing.compute_dtype()
        sharding_r = right._sharding(2)
        # value stack layout (offsets named below):
        #   [0, n)              right col values (all kinds)
        #   [n, n+3)            right ts as three 21-bit ns chunks (f32-exact)
        #   skipNulls=True:
        #     [n+3, n+3+H)      host-col row-index planes (validity = the
        #                       host col's non-null mask -> per-col ffill)
        #   skipNulls=False:
        #     [n+3, 2n+3)       per-col validity planes (to recover nulls)
        #     [2n+3, 2n+3+H)    host-col row-index planes (validity = mask)
        #     [2n+3+H, 2n+3+2H) host-col non-null planes
        planes = [col.values for _, col in r_recs]
        valid_planes = [col.valid for _, col in r_recs]
        chunk_mask = jnp.int64((1 << 21) - 1)
        ts_chunks = [
            ((right.ts >> shift) & chunk_mask).astype(dt)
            for shift in (42, 21, 0)
        ]
        planes.extend(ts_chunks)

        host_flat: Dict[str, np.ndarray] = {}
        h_notna_dev = []
        if h_names:
            ridx_plane = jnp.broadcast_to(
                jnp.arange(right.L, dtype=dt), (right.K_dev, right.L)
            )
            for c in h_names:
                src = right.host_cols[c]
                flat = right._source_df[src].to_numpy()[right.layout.order]
                host_flat[c] = flat
                pm = packing.pack_column(
                    ~pd.isna(flat), right.layout, right.L, fill=False
                )
                h_notna_dev.append(jax.device_put(
                    _pad_k(pm, right.K_dev, False), sharding_r
                ))
        if skipNulls:
            if h_names:
                planes.extend([ridx_plane] * len(h_names))
            vstack = jnp.stack(valid_planes + [right.mask] * 3
                               + h_notna_dev)
        else:
            planes.extend(v.astype(dt) for v in valid_planes)
            if h_names:
                planes.extend([ridx_plane] * len(h_names))
                planes.extend(v.astype(dt) for v in h_notna_dev)
            vstack = jnp.stack([right.mask] * len(planes))
        pstack = jnp.stack(planes)

        # pstack/vstack are freshly-stacked temporaries and the output
        # shape matches when the packed K agrees — donate their HBM to
        # the aligned copies (align2's operands are frame-owned: never
        # donated).  The layouts must also agree: a series-LOCAL left
        # frame (plan-placed reshard) aligning a time-sharded right
        # stack has different per-device buffer shapes, so XLA could
        # not apply the alias and would silently keep both live.
        align3 = _align3_fn(self.mesh, self.series_axis, self.time_axis,
                            donate=(right.K_dev == self.K_dev
                                    and right.series_axis
                                    == self.series_axis
                                    and right.time_axis
                                    == self.time_axis))
        pstack = align3(pstack, perm, ok, np.nan)
        vstack = align3(vstack, perm, ok, False)

        sort_kernels = _use_sort_kernels()
        # per-shard engine note (round 6): the a2a layout switch hands
        # each device FULL series rows, so the shard-local merge width
        # is the full merged width — past the single-program ceiling
        # (resilience.max_merged_lanes) the sortmerge dispatch inside
        # the shard kernels routes to the XLA bitonic network
        # (ops/pallas_merge.py:asof_merge_values_bitonic, O(log Lc)
        # stages — the lax.sort ladder's unrolled network OOM-killed
        # the compiler at ~205K lanes), governed by the same
        # TEMPO_TPU_JOIN_ENGINE knob as the host join.  The host-built
        # lane-chunked layout cannot cross shard_map, so chunked stays
        # a host-path engine.
        from tempo_tpu import resilience as _resilience

        _merged_full = int(self.L) + int(right.L)
        _limit = _resilience.max_merged_lanes()
        if 0 < _limit < _merged_full:
            logger.info(
                "asofJoin(mesh): merged width %d exceeds the "
                "single-program limit %d — shard-local joins use the "
                "XLA bitonic oversize engine", _merged_full, _limit,
            )
        # sequence-number tie-break (tsdf.py:117-121): the reference
        # sorts the merged stream by (combined_ts, RIGHT's sequence col
        # ASC NULLS FIRST, rec_ind).  Left rows carry NULL in the
        # right's seq column; a tied-ts NON-null-seq right row sorts
        # after them (invisible to them), while a tied-ts NULL-seq right
        # row (packed as -inf, from_tsdf) ties on seq and wins via
        # rec_ind — visible to the tied left rows.  The left frame's own
        # sequence never orders the merge.
        ml = int(maxLookback or 0)
        # resampled (bucket-head) frames keep real-looking ts at masked
        # lane rows; maxLookback must count real rows only, so those
        # lanes are sort-compacted to the lane tail inside the kernel —
        # on the right (carrying every value plane along) and, since
        # round 4, on the LEFT too (outputs route back through the
        # recorded source-lane plane, _uncompact_left).  The mask
        # planes are only consulted when a compaction is active.
        compact = bool(ml and right.resampled)
        compact_left = bool(ml and self.resampled)
        r_mask_al = (align2(right.mask, perm, ok, False) if compact
                     else r_ts_al < packing.TS_REAL_MAX)
        has_seq = right.seq is not None
        # stage donation applies only when the join outputs (left lane
        # width) can alias the aligned right stacks (right lane width)
        _donate_join = int(self.L) == int(right.L)
        if has_seq:
            # left rows ride the kernel-synthesized seq fill
            # (finfo.min in _merge_sides — above the -inf null-seq
            # encoding, below any real seq, so the order is
            # right-null < left < right-non-null on ts ties) — no
            # constant plane to shard or transpose
            r_seq_al = align2(right.seq, perm, ok, np.inf)
            if self.n_time > 1:
                vals, found = _asof_a2a_seq(self.mesh, self.series_axis,
                                            self.time_axis, ml,
                                            compact_left,
                                            donate=_donate_join)(
                    self.ts, self.mask, r_ts_al, r_seq_al, vstack, pstack
                )
            else:
                vals, found = _asof_local_seq(self.mesh, self.series_axis,
                                              ml, compact_left,
                                              donate=_donate_join)(
                    self.ts, self.mask, r_ts_al, r_seq_al, vstack, pstack
                )
        elif self.n_time > 1:
            # joins are *global* per series (unbounded lookback), so the
            # time-sharded layout switches to series-local full rows
            # with one all_to_all each way (reshard.py pattern), joins
            # exactly, and switches back — no halo approximation
            vals, found = _asof_a2a(self.mesh, self.series_axis,
                                    self.time_axis, sort_kernels, ml,
                                    compact, compact_left,
                                    donate=_donate_join)(
                self.ts, self.mask, r_ts_al, r_mask_al, vstack, pstack
            )
        else:
            vals, found = _asof_local(self.mesh, self.series_axis,
                                      sort_kernels, ml, compact,
                                      compact_left,
                                      donate=_donate_join)(
                self.ts, self.mask, r_ts_al, r_mask_al, vstack, pstack
            )
        audits = list(self.audits)

        rename = (lambda c: f"{left_prefix}_{c}") if left_prefix else (lambda c: c)
        new_cols = {rename(c): col for c, col in self.cols.items()}
        new_host = {rename(c): src for c, src in self.host_cols.items()}
        n = len(r_recs)
        H = len(h_names)
        hidx_off = (n + 3) if skipNulls else (2 * n + 3)
        for i, (c, rcol) in enumerate(r_recs):
            if skipNulls:
                v, f = vals[i], found[i]
            else:
                v = vals[i]
                f = found[i] & (vals[n + 3 + i] > 0.5)
            if rcol.ts_chunk is not None:
                # a joined-timestamp chunk from an earlier join: re-target
                # its recompose name under this join's prefix
                target, shift = rcol.ts_chunk
                nt = f"{right_prefix}_{target}"
                j = {42: 0, 21: 1, 0: 2}[shift]
                new_cols[f"__{nt}__c{j}"] = DistCol(v, f, ts_chunk=(nt, shift))
            elif rcol.host_gather is not None:
                # an earlier join's host-col index plane: compose this
                # join's series permutation into its gather map
                fv, st, pm = rcol.host_gather
                pm2 = pm[np.clip(perm, 0, max(len(pm) - 1, 0))]
                new_cols[f"{right_prefix}_{c}"] = DistCol(
                    v, f, host_gather=(fv, st, pm2)
                )
            else:
                new_cols[f"{right_prefix}_{c}"] = DistCol(
                    jnp.where(f, v, jnp.nan), f, int64=rcol.int64
                )
        rts_name = f"{right_prefix}_{right.ts_col}"
        for j, shift in enumerate((42, 21, 0)):
            new_cols[f"__{rts_name}__c{j}"] = DistCol(
                vals[n + j], found[n + j], ts_chunk=(rts_name, shift)
            )
        for i, c in enumerate(h_names):
            if skipNulls:
                v, f = vals[hidx_off + i], found[hidx_off + i]
            else:
                v = vals[hidx_off + i]
                f = found[hidx_off + i] & (vals[hidx_off + H + i] > 0.5)
            new_cols[f"{right_prefix}_{c}"] = DistCol(
                v, f, host_gather=(
                    host_flat[c], right.layout.starts, perm,
                ),
            )
        # the left ts column itself is the frame's time axis (renamed
        # when left_prefix is set, tsdf.py:529-531).  The join result
        # has no sequence column (the host path returns a TSDF without
        # one, join.py:285) — chained joins must not re-apply the
        # tie-break, and the left seq stays available as a data column
        # via host_cols.
        return self._with(cols=new_cols, audits=audits,
                          host_cols=new_host, ts_col=rename(self.ts_col),
                          seq=None, seq_col="")

    # ------------------------------------------------------------------
    # resample (resample.py:38-117), device-resident representation
    # ------------------------------------------------------------------

    def resample(self, freq: str, func: str,
                 metricCols=None) -> "DistributedTSDF":
        """Distributed downsample.  The result keeps the packed [K, L]
        shape as a *bucket-head view*: each row's ts becomes its bucket
        start, only the first row of each bucket is valid, and column
        values hold the bucket aggregate at head rows.  ``collect()``
        compacts the view; chained device ops (EMA, range stats) treat
        it like any masked frame.  On a time-sharded mesh the rows are
        switched to a series-local layout with one all_to_all each way
        (the reshard analog of the reference's groupBy shuffle).
        """
        from tempo_tpu import plan

        if plan.recording():
            return self._plan_record("resample", params=dict(
                freq=freq, func=func,
                metricCols=tuple(metricCols) if metricCols else None))
        validateFuncExists(func)
        if self.n_time > 1:
            # time-sharded mesh: explicit whole-frame reshard + the
            # series-local kernel + switch back (see withRangeStats —
            # the mean aggregates are accumulation-sensitive, so the
            # plan-placed reshard elimination requires the eager path
            # to run the SAME series-local program)
            local = reshard_frame(self, RESHARD_SERIES_LOCAL)
            out = local.resample(freq, func, metricCols=metricCols)
            return reshard_frame(out, RESHARD_TIME_SHARDED)
        step = freq_to_seconds(freq) * packing.NS_PER_S
        cols = metricCols or self.numeric_columns()
        fkey = {floor: 0, ceiling: 1, average: 2, min_func: 3, max_func: 4}[
            _canon_func(func)
        ]

        kernel = _resample_fn(self.mesh, self.series_axis, self.time_axis,
                              int(step), fkey, len(cols),
                              _use_sort_kernels())
        vals = jnp.stack([self.cols[c].values for c in cols])
        valids = jnp.stack([self.cols[c].valid for c in cols])
        new_ts, head, out_vals, out_valid = kernel(self.ts, self.mask,
                                                   vals, valids)
        new_cols = {
            c: DistCol(out_vals[i], out_valid[i]) for i, c in enumerate(cols)
        }
        return self._with(ts=new_ts, mask=head, cols=new_cols,
                          resampled=True, seq=None, seq_col="",
                          resample_freq=freq)

    def calc_bars(self, freq: str, func=None, metricCols=None,
                  fill=None) -> "DistributedTSDF":
        """OHLC bars (tsdf.py:813-826) device-resident.  The reference
        runs four resamples and joins them on key+ts; here the four
        resample results land on identical bucket grids (bucket heads
        depend only on ts and freq), so their columns combine by name
        with no join.  Each resample still runs its own kernel — on a
        time-sharded mesh that is four a2a round-trips where a fused
        four-aggregate kernel would need one; fuse if bars become hot.

        ``fill=True`` upsamples the merged bars to each series' dense
        bucket grid with zero-filled numerics (resample.py:102-116) —
        realised as the device interpolate's zero fill over the merged
        bucket-head view (round 4; the four grids are identical, so
        fill-then-merge and merge-then-fill commute)."""
        from tempo_tpu import plan

        if plan.recording():
            return self._plan_record("calc_bars", params=dict(
                freq=freq, func=func,
                metricCols=tuple(metricCols) if metricCols else None,
                fill=fill))
        with plan.suspended():
            # eager-only op whose body chains recorded methods
            # (resample/interpolate): those must not re-enter planning
            mc = metricCols or self.numeric_columns()
            new_cols: Dict[str, DistCol] = {}
            base = None
            for prefix, f in (("open", "floor"), ("low", "min"),
                              ("high", "max"), ("close", "ceil")):
                r = self.resample(freq, f, metricCols=mc)
                base = r
                for c in mc:
                    new_cols[f"{prefix}_{c}"] = r.cols[c]
            # host column order parity: prefixed metrics sorted by name
            # (resample.py:calc_bars sorts the non-partition columns)
            new_cols = {c: new_cols[c] for c in sorted(new_cols)}
            bars = base._with(cols=new_cols)
            if fill:
                bars = bars.interpolate(method="zero")
            return bars

    # ------------------------------------------------------------------
    # withGroupedStats (tsdf.py:723-759) / vwap (TSDF.scala:378-401)
    # ------------------------------------------------------------------

    def withGroupedStats(self, metricCols=None,  # plan-ok: eager-only
                         freq: str = None) -> "DistributedTSDF":
        """Distributed tumbling-window grouped statistics: six
        aggregates per metric column per epoch-aligned bucket, emitted
        as a bucket-head view (one valid row per bucket, ts = bucket
        start — the reference's groupBy output shape)."""
        step = freq_to_seconds(freq) * packing.NS_PER_S
        cols = metricCols or self.numeric_columns()
        kernel = _bucket_stats_fn(self.mesh, self.series_axis,
                                  self.time_axis, int(step), len(cols),
                                  _use_sort_kernels())
        vals = jnp.stack([self.cols[c].values for c in cols])
        valids = jnp.stack([self.cols[c].valid for c in cols])
        new_ts, head, stats = kernel(self.ts, self.mask, vals, valids)
        new_cols = {}
        for i, c in enumerate(cols):
            for j, stat in enumerate(("mean", "count", "min", "max",
                                      "sum", "stddev")):
                new_cols[f"{stat}_{c}"] = DistCol(
                    stats[j, i], head, int64=(stat == "count")
                )
        return self._with(ts=new_ts, mask=head, cols=new_cols,
                          resampled=True, seq=None, seq_col="",
                          resample_freq=freq)

    def vwap(self, frequency: str = "m", volume_col: str = "volume",  # plan-ok: eager-only
             price_col: str = "price") -> "DistributedTSDF":
        """Distributed VWAP (Scala spec): per (series, truncated-ts)
        bucket — dllr_value = sum(price*volume), total volume,
        max price, vwap = dllr_value / volume."""
        from tempo_tpu.freq import UNIT_SECONDS
        from tempo_tpu.rolling import _VWAP_TRUNC

        if frequency not in _VWAP_TRUNC:
            raise ValueError("vwap frequency must be one of 'm', 'H', 'D'")
        step = UNIT_SECONDS[_VWAP_TRUNC[frequency]] * packing.NS_PER_S
        price = self.cols[price_col]
        vol = self.cols[volume_col]
        both = price.valid & vol.valid
        vals = jnp.stack([
            jnp.where(both, price.values * vol.values, 0.0),
            vol.values, price.values,
        ])
        valids = jnp.stack([both, vol.valid, price.valid])
        kernel = _bucket_stats_fn(self.mesh, self.series_axis,
                                  self.time_axis, int(step), 3,
                                  _use_sort_kernels())
        new_ts, head, stats = kernel(self.ts, self.mask, vals, valids)
        dllr = stats[4, 0]     # sum of price*volume
        vsum = stats[4, 1]     # sum of volume
        pmax = stats[3, 2]     # max price
        new_cols = {
            "dllr_value": DistCol(dllr, head),
            volume_col: DistCol(vsum, head),
            "max_" + price_col: DistCol(pmax, head),
            "vwap": DistCol(dllr / vsum, head),
        }
        bucket_freq = {"m": "1 minute", "H": "1 hour", "D": "1 day"}[frequency]
        return self._with(ts=new_ts, mask=head, cols=new_cols,
                          resampled=True, seq=None, seq_col="",
                          resample_freq=bucket_freq)

    # ------------------------------------------------------------------
    # interpolate (interpol.py; tsdf.py:778-811)
    # ------------------------------------------------------------------

    def interpolate(self, freq: str = None, func: str = None,
                    method: str = None, target_cols=None,
                    show_interpolated: bool = False) -> "DistributedTSDF":
        """Distributed resample + gap fill.  Aggregates to ``freq``
        buckets (device resample), then generates each series' dense
        bucket grid [min_bucket, max_bucket] and fills missing values
        with ``method`` (zero / null / ffill / bfill / linear) — the
        prev/next scaffolds are two gather-free merge joins of the grid
        against the bucket heads (ops/sortmerge.py), with linear weights
        computed on exact f32 bucket indices.

        The result is a NEW dense frame (series-sharded; a time-sharded
        input is regathered series-local first).  ``show_interpolated``
        adds the reference's ``is_ts_interpolated`` /
        ``is_interpolated_<col>`` flag columns (interpol.py:330-364).
        """
        from tempo_tpu import plan

        if plan.recording():
            return self._plan_record("interpolate", params=dict(
                freq=freq, func=func, method=method,
                target_cols=tuple(target_cols) if target_cols else None,
                show_interpolated=show_interpolated))
        if method not in ("zero", "null", "ffill", "bfill", "linear"):
            raise ValueError(
                f"Please select from one of the following fill options: "
                f"['zero', 'null', 'bfill', 'ffill', 'linear']: got {method}"
            )
        if self.n_time > 1:
            # the result is a NEW dense series-local frame even on a
            # time-sharded mesh — reshard the inputs once (explicit
            # program, same as the planner's reshard node), no switch
            # back; the linear-fill lerp is FMA-sensitive, so the
            # series-local kernel must be the one program both eager
            # and planned chains run
            return reshard_frame(self, RESHARD_SERIES_LOCAL).interpolate(
                freq=freq, func=func, method=method,
                target_cols=target_cols,
                show_interpolated=show_interpolated)
        if self.resampled:
            freq = freq or self._resample_freq
            if freq != self._resample_freq:
                raise ValueError(
                    f"interpolate freq {freq!r} must match the resample "
                    f"freq {self._resample_freq!r} on a resampled frame"
                )
        if freq is None:
            raise ValueError("interpolate requires freq")
        cols = target_cols or self.numeric_columns()
        if not self.resampled:
            validateFuncExists(func)
        res = self if self.resampled else self.resample(
            freq, func, metricCols=cols
        )
        step = int(freq_to_seconds(freq) * packing.NS_PER_S)

        # static grid bound: bucket span from the host layout when it
        # can vouch for the device ts, else one tiny [K] device fetch
        lay = self.layout
        if lay.n_rows > 0 and int(lay.starts[-1]) == lay.n_rows:
            spans = []
            for k in range(lay.n_series):
                s = lay.ts_ns[lay.starts[k]: lay.starts[k + 1]]
                if len(s):
                    spans.append(int(s[-1] - s[0]))
            span = max(spans, default=0)
        else:
            first = jnp.min(jnp.where(res.mask, res.ts, packing.TS_PAD),
                            axis=1)
            last = jnp.max(jnp.where(res.mask, res.ts, -1), axis=1)
            span = int(np.asarray(jnp.max(
                jnp.where(last >= 0, last - first, 0)
            )))
        G = span // step + 2
        G = max(8, -(-G // 8) * 8)

        mkey = ("zero", "null", "ffill", "bfill", "linear").index(method)
        kernel = _interp_fn(self.mesh, res.series_axis, res.time_axis,
                            step, G, mkey, len(cols),
                            bool(show_interpolated))
        vals = jnp.stack([res.cols[c].values for c in cols])
        valids = jnp.stack([res.cols[c].valid for c in cols])
        out = kernel(res.ts, res.mask, vals, valids)
        grid_ts, grid_mask, out_vals, out_valid = out[:4]
        new_cols = {
            c: DistCol(out_vals[i], out_valid[i]) for i, c in enumerate(cols)
        }
        if show_interpolated:
            ts_interp, col_interp = out[4], out[5]
            new_cols["is_ts_interpolated"] = DistCol(
                ts_interp.astype(vals.dtype), grid_mask, int64=True
            )
            for i, c in enumerate(cols):
                new_cols[f"is_interpolated_{c}"] = DistCol(
                    col_interp[i].astype(vals.dtype), grid_mask, int64=True
                )
        # interpolated frames are dense series-local grids: the time
        # axis (if any) was consumed by the regather inside the kernel,
        # and on a time-sharded mesh the outputs are JOINTLY sharded
        # over ('series', 'time') — record that as the frame's series
        # axis so downstream stages (whose jits now declare explicit
        # in_shardings) see the true layout instead of compiling an
        # implicit reshard against a stale P(series, None) claim
        out_series_axis = ((res.series_axis, res.time_axis)
                           if res.time_axis is not None
                           else res.series_axis)
        return self._with(ts=grid_ts, mask=grid_mask, cols=new_cols,
                          series_axis=out_series_axis,
                          time_axis=None, resampled=True,
                          seq=None, seq_col="", resample_freq=freq)

    # ------------------------------------------------------------------
    # describe (tsdf.py:384-431) / autocorr (tsdf.py:192-316)
    # ------------------------------------------------------------------

    def describe(self) -> pd.DataFrame:
        """Distributed describe: numeric columns reduce device-resident
        (XLA partitions the sharded sums/mins/maxes and inserts the
        cross-shard collectives; only [C, 5] scalars leave the device);
        host-resident columns (strings, huge ints) and the table
        assembly share the host implementation (describe.py)."""
        from tempo_tpu.describe import (
            assemble_table, classify_granularity, col_describe_series,
        )

        names = self.numeric_columns()
        secs = self.ts / packing.NS_PER_S
        vals = (jnp.stack([self.cols[c].values for c in names]) if names
                else jnp.zeros((0,) + self.ts.shape,
                               packing.compute_dtype()))
        valids = (jnp.stack([self.cols[c].valid for c in names]) if names
                  else jnp.zeros((0,) + self.ts.shape, bool))
        r = {k: np.asarray(v) for k, v in _describe_reduce()(
            self.ts, self.mask, secs, vals, valids).items()}

        n = int(r["n_rows"])
        gran = classify_granularity(r["has_frac"], r["sub_min"],
                                    r["sub_hr"], r["sub_day"])
        unique_ts = (len(self.layout.key_frame)
                     if self.partitionCols else 1)
        fmt = lambda x: None if x is None or (isinstance(x, float)
                                              and np.isnan(x)) else str(x)

        def reduced_stats(cnt, s1, s2, mn, mx):
            cnt = int(cnt)
            if cnt == 0:
                return {"count": "0", "mean": None, "stddev": None,
                        "min": None, "max": None}
            mean = s1 / cnt
            var = (s2 - s1 ** 2 / cnt) / max(cnt - 1, 1)
            return {
                "count": str(cnt),
                "mean": fmt(float(mean)),
                "stddev": fmt(float(np.sqrt(max(var, 0.0))))
                if cnt > 1 else None,
                "min": fmt(float(mn)),
                "max": fmt(float(mx)),
            }

        host_names = [c for c in self.host_cols
                      if self._source_df is not None
                      and not self.resampled]
        stat_cols = list(self.partitionCols) + names + host_names \
            + [self.ts_col + "_dbl"]
        stats = {}
        missing = {}
        kf = self.layout.key_frame
        lengths = self.layout.lengths
        for c in self.partitionCols:
            sv = kf[c].dropna().astype(str)
            na_rows = int(lengths[kf[c].isna().to_numpy()].sum()) \
                if len(kf) else 0
            stats[c] = {"count": str(n - na_rows), "mean": None,
                        "stddev": None,
                        "min": fmt(sv.min()) if len(sv) else None,
                        "max": fmt(sv.max()) if len(sv) else None}
            missing[c] = 100.0 * na_rows / max(n, 1)
        for i, c in enumerate(names):
            stats[c] = reduced_stats(r["count"][i], r["sum"][i],
                                     r["sumsq"][i], r["min"][i],
                                     r["max"][i])
            missing[c] = 100.0 * (n - int(r["count"][i])) / max(n, 1)
        for c in host_names:
            s = pd.Series(
                self._source_df[self.host_cols[c]].to_numpy()
                [self.layout.order]
            )
            stats[c] = col_describe_series(s)
            missing[c] = 100.0 * float(s.isna().sum()) / max(n, 1)
        stats[self.ts_col + "_dbl"] = reduced_stats(
            n, r["ts_sum"], r["ts_sumsq"], r["ts_min"], r["ts_max"]
        )
        missing[self.ts_col + "_dbl"] = 0.0

        min_ts = packing.ns_to_original(np.int64(r["min_ts"]),
                                        self._ts_dtype)
        max_ts = packing.ns_to_original(np.int64(r["max_ts"]),
                                        self._ts_dtype)
        if np.issubdtype(np.asarray(min_ts).dtype, np.datetime64):
            min_ts, max_ts = pd.Timestamp(min_ts), pd.Timestamp(max_ts)
        return assemble_table(stat_cols, stats, missing, unique_ts,
                              min_ts, max_ts, gran)

    def autocorr(self, col: str, lag: int = 1) -> pd.DataFrame:
        """Distributed lag-k autocorrelation per series (reference
        tsdf.py:192-316 semantics via the host kernel's pair rule).
        Returns a bare DataFrame (host parity); only [K] scalars leave
        the device.  Bucket-head views (resampled frames) compact their
        scattered valid rows with one stable lane sort first, so the
        physical lag pairing sees consecutive observations."""
        dcol = self.cols[col]
        if self.n_time > 1:
            # positions must be series-contiguous for the lag pairing
            fwd = _to_series_local_fn(self.mesh, self.series_axis,
                                      self.time_axis, 3)
            v, ok, mask = fwd(dcol.values, dcol.valid, self.mask)
        else:
            v, ok, mask = dcol.values, dcol.valid, self.mask
        ac, cnt, lengths = _autocorr_fn(int(lag), bool(self.resampled))(
            v, ok, mask
        )
        K = self.layout.n_series
        ac_h = _to_host(ac).astype(np.float64)[:K]
        cnt_h = _to_host(cnt)[:K]
        len_h = _to_host(lengths)[:K]
        # a series only yields a row when the numerator join is non-empty
        # (reference tsdf.py:248-253 inner joins drop pairless series)
        present = (len_h > lag) & (cnt_h > lag)
        out = self.layout.key_frame.copy()
        if not self.partitionCols:
            out = pd.DataFrame({"_dummy_group_col": ["dummy"]})
        out[f"autocorr_lag_{lag}"] = ac_h
        return out[present].reset_index(drop=True)

    def fourier_transform(self, timestep: float, valueCol: str):
        """Fourier transform, device-resident (round 4; the reference
        ships every group's rows to Python workers over Arrow —
        applyInPandas, tsdf.py:865-899 — and earlier rounds mirrored
        that with a collect()).  Each series' exact n-point DFT runs as
        one batched Bluestein program at the frame's lane width
        (ops/fft.py:bluestein_dft; time-sharded meshes switch to
        series-local rows around it), and ``freq`` is the fftfreq grid
        of each series' true length.  Output column surface matches the
        host path: partition/ts/[seq] + value + freq/ft_real/ft_imag
        (spectral.py:104-112).

        Bucket-head (resampled) views keep the host fallback — their
        real rows are not front-packed, which the batched DFT
        requires."""
        from tempo_tpu import plan

        if plan.recording():
            return self._plan_record("fourier", params=dict(
                timestep=timestep, valueCol=valueCol))
        matches = [c for c in self.cols if c.lower() == valueCol.lower()
                   and self.cols[c].ts_chunk is None
                   and self.cols[c].host_gather is None]
        if self.resampled or not matches:
            # bucket-head views (rows not front-packed) and columns
            # without a plain device plane (host-resident ints/strings,
            # join-produced gather/ts-chunk columns) keep the
            # collect-based path — spectral.py resolves any frame
            # column, including raising the reference's error for a
            # truly absent one
            logger.warning(
                "fourier_transform(%r): materialization barrier — the "
                "mesh chain silently collects to host here (%s) and "
                "re-packs afterwards; under TEMPO_TPU_PLAN=1 explain() "
                "marks this barrier in the plan", valueCol,
                "bucket-head (resampled) view" if self.resampled
                else "no plain device plane for the column")
            with plan.suspended():
                host = self.collect().fourier_transform(timestep, valueCol)
                s_ax, t_ax = self.series_axis, self.time_axis
                if isinstance(s_ax, tuple):
                    # joint series-LOCAL frames (reshard_frame /
                    # interpolate output) re-pack onto the plain series
                    # axis: from_tsdf packs fresh from the host, so
                    # there is no layout to preserve — and it cannot
                    # look a tuple axis up in mesh.shape
                    s_ax, t_ax = s_ax[0], None
                return host.on_mesh(self.mesh, series_axis=s_ax,
                                    time_axis=t_ax)
        if self.n_time > 1:
            # explicit reshard sandwich (see withRangeStats): the
            # Bluestein DFT's accumulations must run the same
            # series-local program eager and planned
            local = reshard_frame(self, RESHARD_SERIES_LOCAL)
            out = local.fourier_transform(timestep, valueCol)
            return reshard_frame(out, RESHARD_TIME_SHARDED)
        vc = matches[0]
        col = self.cols[vc]
        freq, ftr, fti = _fourier_fn(self.mesh, self.series_axis,
                                     self.time_axis, float(timestep))(
            col.values, self.mask
        )
        new_cols = {
            vc: col,
            "freq": DistCol(freq, self.mask),
            "ft_real": DistCol(ftr, self.mask),
            "ft_imag": DistCol(fti, self.mask),
        }
        keep_host = {c: src for c, src in self.host_cols.items()
                     if c == self.seq_col}
        return self._with(cols=new_cols, host_cols=keep_host)

    def withLookbackFeatures(self, featureCols, lookbackWindowSize: int,
                             exactSize: bool = True,
                             featureColName: str = "features"):
        """Lookback feature tensors via the host frame path.  The
        reference materialises these as array-of-array columns through
        a shuffle (collect_list, tsdf.py:637-671) — inherently a
        row-materialisation op — so the distributed form collects once
        and runs the device shifted-stack path; the dense device-side
        form is :meth:`lookback_tensor`."""
        from tempo_tpu import plan

        if plan.recording():
            return self._plan_record("lookback_features", params=dict(
                featureCols=tuple(featureCols),
                lookbackWindowSize=lookbackWindowSize,
                exactSize=exactSize, featureColName=featureColName))
        logger.warning(
            "withLookbackFeatures: materialization barrier — the mesh "
            "chain silently collects to host here (collect_list "
            "semantics materialise rows); use lookback_tensor for the "
            "device-resident dense form, or TEMPO_TPU_PLAN=1 explain() "
            "to see the barrier in the plan")
        with plan.suspended():
            return self.collect().withLookbackFeatures(
                featureCols, lookbackWindowSize, exactSize, featureColName
            )

    def lookback_tensor(self, featureCols, lookbackWindowSize: int):
        """Dense ``([K, L, w, F] values, [K, L, w, F] validity)``
        lookback tensor as DEVICE arrays, series-sharded — the
        TPU-native model-feeding form of ``withLookbackFeatures``
        (round 4; host analog ``tempo_tpu.rolling.lookback_tensor``),
        with no object-array materialisation and no host round trip.
        Window axis is oldest-first (row t's slot j holds observation
        t - w + j), zero-padded with the mask False where no
        observation exists.  On a time-sharded mesh the rows switch to
        a series-local layout first (the shifts cross shard
        boundaries), so the result is sharded over all devices along
        the series axis.

        Plain numeric device columns only (join-index/ts-chunk planes
        hold row positions, not values), and not on bucket-head
        (resampled) views — their real rows are interspersed with
        masked lanes, so a physical-slot window would not be the w
        previous observations; collect() + ``withLookbackFeatures``
        compacts first."""
        if self.resampled:
            raise ValueError(
                "lookback_tensor on a resampled (bucket-head) view "
                "would window over physical lane slots, not the "
                "previous w buckets; collect() and use "
                "withLookbackFeatures (which compacts rows first)"
            )
        cols = list(featureCols)
        eligible = set(self.numeric_columns())
        bad = [c for c in cols if c not in eligible]
        if bad:
            raise ValueError(
                f"lookback_tensor needs plain numeric device columns; "
                f"{bad} are missing or host/join-resident "
                f"(available: {sorted(eligible)})"
            )
        vals = jnp.stack([self.cols[c].values for c in cols])
        valids = jnp.stack([self.cols[c].valid for c in cols])
        return _lookback_tensor_fn(
            self.mesh, self.series_axis, self.time_axis,
            int(lookbackWindowSize), len(cols)
        )(vals, valids)

    # ------------------------------------------------------------------
    # Materialisation
    # ------------------------------------------------------------------

    def collect(self):  # plan-ok: eager-only
        """ONE stacked device->host transfer -> host TSDF."""
        global _FETCH_EVENTS
        from tempo_tpu.frame import TSDF

        names = list(self.cols)
        # single stacked fetch: float cols as one [C, K, L] f64 block
        if names:
            stacked = _to_host(
                jnp.stack([self.cols[c].values.astype(jnp.float64)
                           for c in names]
                          + [self.cols[c].valid.astype(jnp.float64)
                             for c in names])
            )
            val_block = stacked[: len(names)]
            ok_block = stacked[len(names):] > 0.5
        ts_h = _to_host(self.ts)
        mask_h = _to_host(self.mask)
        _FETCH_EVENTS += 1

        for msg, count in self.audits:
            n = int(_to_host(count))
            if n > 0:
                logger.warning(msg, n) if "%d" in msg else logger.warning(msg)
        K = self.layout.n_series
        mask_h = mask_h[:K]
        ts_h = ts_h[:K]

        lengths = mask_h.sum(axis=1).astype(np.int64)
        key_ids = np.repeat(np.arange(K, dtype=np.int64), lengths)
        flat = lambda a: a[:K][mask_h]

        out = {}
        kf = self.layout.key_frame
        for c in self.partitionCols:
            out[c] = kf[c].to_numpy()[key_ids]
        out[self.ts_col] = packing.ns_to_original(flat(ts_h), self._ts_dtype)
        ts_parts: Dict[str, dict] = {}
        for i, c in enumerate(names):
            col = self.cols[c]
            v = flat(val_block[i])
            okv = flat(ok_block[i])
            if col.ts_chunk is not None:
                target, shift = col.ts_chunk
                part = ts_parts.setdefault(target, {"ns": 0, "ok": okv})
                part["ns"] = part["ns"] + (
                    np.round(np.where(okv, v, 0.0)).astype(np.int64) << shift
                )
            elif col.host_gather is not None:
                flat_vals, r_starts, perm = col.host_gather
                ridx = np.round(np.where(okv, v, 0.0)).astype(np.int64)
                pos = r_starts[perm[key_ids]] + ridx
                pos = np.clip(pos, 0, max(len(flat_vals) - 1, 0))
                if len(flat_vals) and np.issubdtype(flat_vals.dtype,
                                                    np.integer):
                    # integer host col (e.g. a joined sequence column):
                    # keep int exactness — values near 2^63 must not
                    # round through float64; unmatched rows are NA
                    # (Spark nullable int join output)
                    g = flat_vals[pos].astype(np.int64)
                    arr = pd.array(g, dtype="Int64")
                    arr[~okv] = pd.NA
                    out[c] = arr
                    continue
                if len(flat_vals) and np.issubdtype(flat_vals.dtype,
                                                    np.number):
                    out[c] = np.where(okv,
                                      flat_vals[pos].astype(np.float64),
                                      np.nan)
                    continue
                gathered = (flat_vals[pos] if len(flat_vals)
                            else np.full(len(pos), None, object))
                res = np.empty(len(pos), dtype=object)
                res[:] = gathered
                res[~okv] = None
                out[c] = res
            elif col.int64:
                out[c] = np.where(okv, v, 0).astype(np.int64)
            else:
                out[c] = np.where(okv, v, np.nan)
        for target, part in ts_parts.items():
            tsv = packing.ns_to_original(part["ns"], self._ts_dtype)
            if np.issubdtype(np.asarray(tsv).dtype, np.datetime64):
                tsv = np.where(part["ok"], tsv, np.datetime64("NaT"))
            out[target] = tsv
        if not self.resampled:
            # host-resident (non-numeric) columns rejoin by row identity
            for c, src in self.host_cols.items():
                out[c] = self._source_df[src].to_numpy()[self.layout.order]
        return TSDF(pd.DataFrame(out), self.ts_col, self.partitionCols)

    def to_pandas(self) -> pd.DataFrame:
        return self.collect().df

    def count(self) -> int:
        return int(np.asarray(jnp.sum(self.mask)))

    def show(self, n: int = 20, truncate: bool = True) -> None:
        """Materialise and display (host TSDF.show semantics)."""
        self.collect().show(n, truncate)

    def __repr__(self) -> str:
        axes = dict(self.mesh.shape)
        return (
            f"DistributedTSDF(mesh={axes}, series={self.layout.n_series}, "
            f"packed=[{self.K_dev}, {self.L}], "
            f"cols={self.numeric_columns()}, host_cols={list(self.host_cols)}, "
            f"ts_col={self.ts_col!r}, partition_cols={self.partitionCols})"
        )


def _mesh_packed_geometry(layout, mesh, series_axis: str,
                          time_axis: Optional[str]):
    """``(K_dev, L, n_series_shards, n_time)`` — the packed geometry
    :meth:`DistributedTSDF.from_tsdf` will realise for this layout on
    this mesh.  The series dim is a multiple of every mesh axis so
    layout-switching collectives (the all_to_all resample path) stay
    legal.  Shared with the plan optimizer's engine hoist, which must
    reason about shard shapes BEFORE the frame is packed."""
    n_s = mesh.shape[series_axis]
    n_t = mesh.shape[time_axis] if time_axis else 1
    k_mult = n_s * n_t
    K_dev = max(1, -(-layout.n_series // k_mult)) * k_mult
    L = packing.pad_length(int(layout.lengths.max(initial=0)),
                           multiple=8 * n_t)
    return K_dev, L, n_s, n_t


def _pick_range_engine_for_shard(shard_k: int, L: int, rb):
    """The shifted/stream/windowed pick for one shard shape + static
    row bounds (None = unboundable -> the data-independent windowed
    form).  One function so the realized-frame pick
    (:meth:`DistributedTSDF._range_engine_choice`) and the pre-packing
    plan-time pick (:func:`plan_range_engine_choice`) can never
    diverge — a hoisted hint that disagreed with the run-time pick
    would silently change which kernel (and which float rounding) a
    planned chain runs."""
    from tempo_tpu.ops import pallas_stats as _ps
    from tempo_tpu.ops import pallas_window as _pw

    f32 = packing.compute_dtype() == np.float32
    pallas_ok = f32 and _ps.pallas_block_feasible(max(shard_k, 1), L)
    stream_ok = f32 and _pw.stream_block_feasible(max(shard_k, 1), L)
    engine = "shifted"
    rowbounds = None
    if rb is not None:
        engine = rk.pick_range_engine(max(shard_k, 1) * L, rb[0], rb[1],
                                      pallas_ok, stream_ok)
        if engine != "windowed":
            rowbounds = rb
    return engine, rowbounds


def plan_range_engine_choice(layout, mesh, series_axis: str,
                             time_axis: Optional[str],
                             window_secs: float):
    """``(engine, rowbounds, sort_kernels)`` a frame packed from
    ``layout`` onto ``mesh`` will choose in
    :meth:`DistributedTSDF._range_engine_choice` — computed WITHOUT
    packing, for the plan optimizer's plan-time hoist."""
    sort_kernels = _use_sort_kernels()
    if not sort_kernels:
        return "shifted", None, sort_kernels
    K_dev, L, n_s, n_t = _mesh_packed_geometry(layout, mesh,
                                               series_axis, time_axis)
    rb = (packing.layout_rowbounds(layout, window_secs)
          if layout.n_rows > 0 and int(layout.starts[-1]) == layout.n_rows
          else None)
    shard_k = K_dev // (n_s * max(n_t, 1))
    engine, rowbounds = _pick_range_engine_for_shard(shard_k, L, rb)
    return engine, rowbounds, sort_kernels


def _put_global(sharding):
    """Host->device placement that works across processes.  Ingest is
    replicated-host (every process packed the same frame, the standard
    multi-controller SPMD pattern), so each device's shard is a slice
    of the local array — ``make_array_from_callback`` places exactly
    those slices.  Multi-process ``device_put`` would work too but
    value-checks the array across processes with an equality that
    fails on NaN payloads (jax multihost_utils.assert_equal; NaN !=
    NaN), which every packed value plane contains."""
    if jax.process_count() > 1:
        def put(arr):
            return jax.make_array_from_callback(
                arr.shape, sharding, lambda idx: arr[idx]
            )

        return put
    return lambda arr: jax.device_put(arr, sharding)


def _to_host(arr) -> np.ndarray:
    """Device->host fetch that also works across processes: a
    multi-controller frame's arrays are not fully addressable (each
    host owns its mesh slice), so ``np.asarray`` would raise —
    ``process_allgather`` rebuilds the global value on every host
    instead (DCN), which is exactly collect()'s dense contract.
    Single-process arrays take the plain fetch."""
    if isinstance(arr, jax.Array) and not arr.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(arr,
                                                            tiled=True))
    return np.asarray(arr)


def _pad_k(arr: np.ndarray, K_dev: int, fill) -> np.ndarray:
    K = arr.shape[0]
    if K == K_dev:
        return arr
    pad = np.full((K_dev - K,) + arr.shape[1:], fill, dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def _canon_func(func: str) -> str:
    from tempo_tpu.freq import CLOSEST_LEAD, MEAN_LEAD, MIN_LEAD, MAX_LEAD

    return {CLOSEST_LEAD: floor, MEAN_LEAD: average, MIN_LEAD: min_func,
            MAX_LEAD: max_func}.get(func, func)


def _key_perm(left_kf: pd.DataFrame, right_kf: pd.DataFrame,
              pcols: List[str], K_dev: int):
    """For each left series id, the right series id with the same
    partition-key tuple (-1 when absent).  Host numpy (K-sized metadata
    consumed both by the jitted align fns and collect-time gathers)."""
    if not pcols:
        perm = np.zeros(K_dev, np.int32)
        ok = np.zeros(K_dev, bool)
        ok[0] = len(right_kf.index) > 0
        return perm, ok
    rk_idx = right_kf.reset_index().rename(columns={"index": "__rid__"})
    merged = left_kf.merge(rk_idx, on=pcols, how="left")
    rid = merged["__rid__"].to_numpy()
    ok = ~pd.isna(rid)
    perm = np.where(ok, rid, 0).astype(np.int32)
    perm = np.concatenate([perm, np.zeros(K_dev - len(perm), np.int32)])
    okp = np.concatenate([ok, np.zeros(K_dev - len(ok), bool)])
    return perm, okp


# ----------------------------------------------------------------------
# Cached shard_map program builders (compile once per mesh/params/shape)
# ----------------------------------------------------------------------

@functools.lru_cache(maxsize=256)
def _range_stats_halo(mesh, series_axis, time_axis, window_secs, halo):
    def fn(ts, x, valid):
        secs = ts // packing.NS_PER_S
        return ph.range_stats_time_sharded(
            mesh, secs, x, valid, window_secs, halo,
            time_axis=time_axis, series_axis=series_axis,
        )

    return fn


def _range_stats_block_packed(ts, xs, valids, w, rowbounds,
                              engine="shifted"):
    """Shard-local range stats over a multi-column stack:
    ``xs``/``valids`` are [C, K, L] planes sharing the shard's
    timestamp plane, reduced with the key planes read ONCE per kernel
    pack instead of once per column
    (ops/rolling.range_stats_streaming_packed /
    sortmerge.range_stats_shifted_packed); shifted gather-free form
    when static row bounds are known (TPU), the streaming VMEM sweep
    for wider bounded frames (``engine="stream"``), else bounds +
    prefix/RMQ form.  Per-column results are bitwise-identical to C
    single-column calls — the packed kernels trace the identical
    per-column op sequence and the fallbacks ARE the single-column
    paths — which is what keeps the eager chain, the planner replay,
    and the fused single program (plan/fused.py) in exact agreement.
    Returns (stats dict of [C, ...] planes, clipped [C] int64) —
    clipped is the window kernels' truncation audit (zero by
    construction for the exact form)."""
    from tempo_tpu.ops import sortmerge as sm

    C = xs.shape[0]
    secs = ts // packing.NS_PER_S
    if rowbounds is not None:
        behind, ahead = rowbounds
        # per-series int32 rebase for the VMEM kernel.  _window_rowbounds
        # guarantees span + window < 2^31 host-side, so the window casts
        # exactly (no narrowing clamp — one would silently shrink
        # frames) and the INT32_MAX pad clamp keeps >= window of
        # headroom above every real key (the truncation audit's
        # pad-immunity condition)
        rb = jnp.minimum(secs - secs[:, :1], 2**31 - 1).astype(jnp.int32)
        w32 = jnp.asarray(w).astype(jnp.int32)
        if engine == "stream":
            stats = rk.range_stats_streaming_packed(
                rb, xs, valids, w32,
                max_behind=int(behind), max_ahead=int(ahead))
        else:
            stats = sm.range_stats_shifted_packed(
                rb, xs, valids, w32,
                max_behind=int(behind), max_ahead=int(ahead))
        clipped = jnp.sum(stats.pop("clipped"),
                          axis=(1, 2)).astype(jnp.int64)
        return stats, clipped
    # window operand: over integer seconds ANY width folds to an exact
    # integer compare (rk.range_window_width) — the bare jnp.asarray(w)
    # this replaces minted weak-f64 bound arithmetic under the f32
    # policy (caught by the compiled no-f64-leak contract,
    # tools/analyze.py --compiled)
    start, end = rk.range_window_bounds(secs,
                                        rk.range_window_width(secs, w))
    per = [rk.windowed_stats(xs[c], valids[c], start, end)
           for c in range(C)]
    stats = {k: jnp.stack([p[k] for p in per]) for k in per[0]}
    return stats, jnp.zeros((C,), jnp.int64)


@functools.lru_cache(maxsize=256)
def _range_stats_local_packed(mesh, series_axis, window_secs,
                              rowbounds=None, sort_kernels=False,
                              engine="shifted"):
    """Series-sharded range stats over the whole column stack: ONE
    shard_map program computes every summarized column ([C, K, L]
    stacks) — C-1 fewer dispatches and the timestamp planes stream
    once.  Replaces the former per-column ``_range_stats_local`` (a
    width-1 stack reproduces it exactly)."""
    sp = _spec(mesh, series_axis, None)
    sp3 = _spec(mesh, series_axis, None, ndim=3)
    w = window_secs

    def kernel(ts, xs, valids):
        stats, clipped = _range_stats_block_packed(ts, xs, valids, w,
                                                   rowbounds, engine)
        return stats, jax.lax.psum(clipped, series_axis)

    stats_spec = {k: sp3 for k in packing.RANGE_STATS}
    # the [C, K, L] value stack is a fresh jnp.stack at every call site
    # (withRangeStats packs frame columns per call) and each f32 stats
    # plane matches its shape/dtype — donate it so the packed stats
    # reuse the stack's HBM instead of doubling the stage's working
    # set.  The bool validity stack has no bool-shaped output and the
    # ts plane is frame-owned: neither is donatable.
    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(sp, sp3, sp3),
                             out_specs=(stats_spec, P())),
                   in_shardings=(_ns(mesh, sp), _ns(mesh, sp3),
                                 _ns(mesh, sp3)),
                   out_shardings=({k: _ns(mesh, sp3)
                                   for k in packing.RANGE_STATS},
                                  _ns(mesh, P())),
                   donate_argnums=(1,))


@functools.lru_cache(maxsize=256)
def _ema_local(mesh, series_axis, alpha, exact, window):
    sp = _spec(mesh, series_axis, None)

    def kernel(x, valid):
        if exact:
            from tempo_tpu.ops import pallas_kernels as pk

            return pk.ema_scan(x, valid, alpha)
        return rk.ema_compat(x, valid, window, alpha)

    # no donation: the EMA's value operand is the frame-OWNED column
    # plane (the result frame shares it via ``_with``), unlike the
    # join/stats stages whose operands are per-call stacks
    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(sp, sp),
                             out_specs=sp),
                   in_shardings=(_ns(mesh, sp), _ns(mesh, sp)),
                   out_shardings=_ns(mesh, sp))


def _compact_right_lanes(r_ts, r_mask, vstack, pstack):
    """Stable per-row sort pushing non-existent (masked-out) right rows
    to the lane tail as TS_PAD, restoring the ascending packed
    invariant that bucket-head (resample) views lack.  Needed only when
    maxLookback counts merged-stream rows: a masked lane row with a
    real-looking ts would consume a window slot Spark's stream never
    contains.  One multi-operand lax.sort carrying every plane."""
    nv = int(vstack.shape[0])
    key = jnp.where(r_mask, r_ts, packing.TS_PAD)
    ops = jax.lax.sort(
        (key,) + tuple(vstack[i] for i in range(nv))
        + tuple(pstack[i] for i in range(int(pstack.shape[0]))),
        dimension=-1, num_keys=1, is_stable=True,
    )
    return ops[0], jnp.stack(ops[1: 1 + nv]), jnp.stack(ops[1 + nv:])


def _compact_left_rows(l_ts, l_mask):
    """Stable sort pushing masked-out LEFT rows to the lane tail as
    TS_PAD (they would otherwise consume maxLookback merged-stream
    window slots Spark's stream never contains — the left-side mirror
    of ``_compact_right_lanes``).  Returns the compacted keys and the
    original-lane plane whose inverse routes outputs back."""
    K, L = l_ts.shape
    iota = jnp.broadcast_to(jnp.arange(L, dtype=jnp.int32), (K, L))
    key = jnp.where(l_mask, l_ts, packing.TS_PAD)
    return jax.lax.sort((key, iota), dimension=-1, num_keys=1,
                        is_stable=True)


def _uncompact_left(src, vals, found):
    """Route [C, K, L] join outputs back to the original left lanes:
    sorting on the carried source-lane plane inverts the compaction
    permutation."""
    C = int(vals.shape[0])
    ops = (src,) + tuple(vals[c] for c in range(C)) \
        + tuple(found[c] for c in range(C))
    routed = jax.lax.sort(ops, dimension=-1, num_keys=1, is_stable=True)
    vals2 = jnp.stack(routed[1:1 + C]) if C else vals
    found2 = jnp.stack(routed[1 + C:]) if C else found
    return vals2, found2


def _asof_planes(l_ts, r_ts, r_valids, r_values, sort_kernels,
                 max_lookback=0):
    """Per-plane AS-OF fill: on TPU the sort-and-scan join (no gathers,
    ops/sortmerge.py timings); elsewhere searchsorted + index gathers.
    ``max_lookback`` > 0 caps the merged-stream fill (Scala
    asofJoin.scala:64-88)."""
    from tempo_tpu.ops import sortmerge as sm

    if sort_kernels:
        vals, found, _ = sm.asof_merge_values(
            l_ts, r_ts, r_valids, r_values, max_lookback=max_lookback
        )
        return vals, found
    if max_lookback:
        _, col_idx = asof_ops.asof_indices_merge(
            l_ts, None, r_ts, None, r_valids,
            n_cols=int(r_values.shape[0]), max_lookback=int(max_lookback),
        )
    else:
        _, col_idx = asof_ops.asof_indices_searchsorted(
            l_ts, r_ts, r_valids, n_cols=int(r_values.shape[0])
        )
    found = col_idx >= 0
    vals = jnp.take_along_axis(r_values, jnp.maximum(col_idx, 0), axis=-1)
    return jnp.where(found, vals, jnp.nan), found


@functools.lru_cache(maxsize=256)
def _asof_local(mesh, series_axis, sort_kernels=False, max_lookback=0,
                compact=False, compact_left=False, donate=True):
    sp2 = _spec(mesh, series_axis, None)
    sp3 = _spec(mesh, series_axis, None, ndim=3)

    def kernel(l_ts, l_mask, r_ts, r_mask, r_valids, r_values):
        if compact:
            r_ts, r_valids, r_values = _compact_right_lanes(
                r_ts, r_mask, r_valids, r_values
            )
        if compact_left:
            l_ts, src = _compact_left_rows(l_ts, l_mask)
        vals, found = _asof_planes(l_ts, r_ts, r_valids, r_values,
                                   sort_kernels, max_lookback)
        if compact_left:
            vals, found = _uncompact_left(src, vals, found)
        return vals, found

    # whole-chain donation: the aligned validity/plane stacks are
    # per-call temporaries (built by asofJoin, already donated once
    # through _align3_fn) whose shapes/dtypes exactly match the
    # ``found``/``vals`` outputs — each join stage reuses its consumed
    # stage-N-1 buffers instead of doubling the chain's working set
    # (verified compiled-side by the donation-applied contract rule).
    # ``donate=False`` when the left/right lane widths differ: the
    # outputs are left-width [P, K, Ll] and XLA could never alias a
    # [P, K, Lr] stack onto them (it would warn and keep both live).
    return jax.jit(shard_map(kernel, mesh=mesh,
                             in_specs=(sp2, sp2, sp2, sp2, sp3, sp3),
                             out_specs=(sp3, sp3)),
                   in_shardings=(_ns(mesh, sp2),) * 4
                   + (_ns(mesh, sp3),) * 2,
                   out_shardings=(_ns(mesh, sp3), _ns(mesh, sp3)),
                   donate_argnums=(4, 5) if donate else ())


@functools.lru_cache(maxsize=256)
def _asof_local_seq(mesh, series_axis, max_lookback=0,
                    compact_left=False, donate=True):
    """AS-OF with sequence tie-break: the merge join is the only exact
    form (reference union-sort semantics, tsdf.py:117-121), so it runs
    on every backend.  (A resampled RIGHT frame never has a sequence
    column — resample drops it — so only the left compaction exists
    here.)"""
    from tempo_tpu.ops import sortmerge as sm

    sp2 = _spec(mesh, series_axis, None)
    sp3 = _spec(mesh, series_axis, None, ndim=3)

    def kernel(l_ts, l_mask, r_ts, r_seq, r_valids, r_values):
        if compact_left:
            l_ts, src = _compact_left_rows(l_ts, l_mask)
        vals, found, _ = sm.asof_merge_values(
            l_ts, r_ts, r_valids, r_values, r_seq=r_seq,
            max_lookback=max_lookback,
        )
        if compact_left:
            vals, found = _uncompact_left(src, vals, found)
        return vals, found

    return jax.jit(shard_map(kernel, mesh=mesh,
                             in_specs=(sp2, sp2, sp2, sp2, sp3, sp3),
                             out_specs=(sp3, sp3)),
                   in_shardings=(_ns(mesh, sp2),) * 4
                   + (_ns(mesh, sp3),) * 2,
                   out_shardings=(_ns(mesh, sp3), _ns(mesh, sp3)),
                   donate_argnums=(4, 5) if donate else ())


@functools.lru_cache(maxsize=256)
def _asof_a2a_seq(mesh, series_axis, time_axis, max_lookback=0,
                  compact_left=False, donate=True):
    from tempo_tpu.ops import sortmerge as sm

    sp2 = _spec(mesh, series_axis, time_axis)
    sp3 = _spec(mesh, series_axis, time_axis, 3)

    def kernel(l_ts, l_mask, r_ts, r_seq, r_valids, r_values):
        fwd = lambda a: jax.lax.all_to_all(
            a, time_axis, split_axis=a.ndim - 2, concat_axis=a.ndim - 1,
            tiled=True)
        rev = lambda a: jax.lax.all_to_all(
            a, time_axis, split_axis=a.ndim - 1, concat_axis=a.ndim - 2,
            tiled=True)
        l_full = fwd(l_ts)
        if compact_left:
            l_full, src = _compact_left_rows(l_full, fwd(l_mask))
        vals, found, _ = sm.asof_merge_values(
            l_full, fwd(r_ts), fwd(r_valids), fwd(r_values),
            r_seq=fwd(r_seq), max_lookback=max_lookback,
        )
        if compact_left:
            vals, found = _uncompact_left(src, vals, found)
        return rev(vals), rev(found)

    return jax.jit(shard_map(kernel, mesh=mesh,
                             in_specs=(sp2, sp2, sp2, sp2, sp3, sp3),
                             out_specs=(sp3, sp3)),
                   in_shardings=(_ns(mesh, sp2),) * 4
                   + (_ns(mesh, sp3),) * 2,
                   out_shardings=(_ns(mesh, sp3), _ns(mesh, sp3)),
                   donate_argnums=(4, 5) if donate else ())


@functools.lru_cache(maxsize=256)
def _asof_a2a(mesh, series_axis, time_axis, sort_kernels=False,
              max_lookback=0, compact=False, compact_left=False,
              donate=True):
    """Exact AS-OF join on a time-sharded mesh: switch both sides to a
    series-local layout (full rows per device, one ``all_to_all`` per
    array), join locally, switch the [n_cols, K, Ll] results back."""
    sp2 = _spec(mesh, series_axis, time_axis)
    sp3 = _spec(mesh, series_axis, time_axis, 3)

    def kernel(l_ts, l_mask, r_ts, r_mask, r_valids, r_values):
        fwd = lambda a: jax.lax.all_to_all(
            a, time_axis, split_axis=a.ndim - 2, concat_axis=a.ndim - 1,
            tiled=True)
        rev = lambda a: jax.lax.all_to_all(
            a, time_axis, split_axis=a.ndim - 1, concat_axis=a.ndim - 2,
            tiled=True)
        l_full, r_full = fwd(l_ts), fwd(r_ts)
        rv_full, rx_full = fwd(r_valids), fwd(r_values)
        if compact:
            r_full, rv_full, rx_full = _compact_right_lanes(
                r_full, fwd(r_mask), rv_full, rx_full
            )
        if compact_left:
            l_full, src = _compact_left_rows(l_full, fwd(l_mask))
        vals, found = _asof_planes(l_full, r_full, rv_full, rx_full,
                                   sort_kernels, max_lookback)
        if compact_left:
            vals, found = _uncompact_left(src, vals, found)
        return rev(vals), rev(found)

    return jax.jit(shard_map(kernel, mesh=mesh,
                             in_specs=(sp2, sp2, sp2, sp2, sp3, sp3),
                             out_specs=(sp3, sp3)),
                   in_shardings=(_ns(mesh, sp2),) * 4
                   + (_ns(mesh, sp3),) * 2,
                   out_shardings=(_ns(mesh, sp3), _ns(mesh, sp3)),
                   donate_argnums=(4, 5) if donate else ())


@functools.lru_cache(maxsize=256)
def _align_fn(mesh, series_axis, time_axis):
    """Gather a right-frame [K_r, L] array into the left key order along
    the sharded series axis (XLA plans the cross-device movement)."""
    sharding = NamedSharding(mesh, _spec(mesh, series_axis, time_axis))

    def fn(arr, perm, ok, fill):
        g = jnp.take(arr, jnp.clip(perm, 0, arr.shape[0] - 1), axis=0)
        return jnp.where(ok[:, None], g, jnp.asarray(fill, arr.dtype))

    return jax.jit(fn, out_shardings=sharding, static_argnums=(3,))


@functools.lru_cache(maxsize=256)
def _align3_fn(mesh, series_axis, time_axis, donate=False):
    """``donate=True`` (caller asserts the left/right packed K match,
    so input and output shapes are equal) donates the plane stack: the
    aligned copy reuses the pre-alignment stack's HBM instead of
    doubling the join's biggest transient.  The donation-applied
    compiled contract (plan/contracts.py) verifies the input-output
    alias on the compiled executable."""
    sharding = NamedSharding(mesh, _spec(mesh, series_axis, time_axis, 3))

    def fn(arr, perm, ok, fill):
        g = jnp.take(arr, jnp.clip(perm, 0, arr.shape[1] - 1), axis=1)
        return jnp.where(ok[None, :, None], g, jnp.asarray(fill, arr.dtype))

    return jax.jit(fn, out_shardings=sharding, static_argnums=(3,),
                   donate_argnums=(0,) if donate else ())


@functools.lru_cache(maxsize=256)
def _to_series_local_fn(mesh, series_axis, time_axis, n_arrays):
    """[K, L] arrays -> series-local full rows (each device owns
    K/(ns*nt) whole series), via one all_to_all per array.  Keyed on
    arity so the jitted callable is built (and compiled) once."""
    sp_in = _spec(mesh, series_axis, time_axis)
    sp_out = P((series_axis, time_axis), None)

    def kernel(*arrays):
        a2a = lambda a: jax.lax.all_to_all(
            a, time_axis, split_axis=a.ndim - 2, concat_axis=a.ndim - 1,
            tiled=True)
        return tuple(a2a(a) for a in arrays)

    return jax.jit(shard_map(
        kernel, mesh=mesh, in_specs=(sp_in,) * n_arrays,
        out_specs=(sp_out,) * n_arrays,
    ))


# ----------------------------------------------------------------------
# Plan-placed resharding: the executor of the planner's first-class
# ``reshard`` IR node (tempo_tpu/plan/optimizer.py)
# ----------------------------------------------------------------------

#: targets of :func:`reshard_frame`: ``series_local`` re-lays a
#: time-sharded frame so every device owns whole series (K sharded
#: jointly over ('series', 'time'), rows unsplit) — the layout every
#: per-series kernel wants; ``time_sharded`` is the inverse.
RESHARD_SERIES_LOCAL = "series_local"
RESHARD_TIME_SHARDED = "time_sharded"


def reshard_frame(d: "DistributedTSDF", target: str) -> "DistributedTSDF":
    """Explicit whole-frame layout switch — ONE jitted shard_map
    program moving every device plane with ``lax.all_to_all`` (the
    reshard.py collectives, fused across the frame's planes), instead
    of each downstream op paying its own per-op all_to_all pair.  The
    global logical [K, L] arrays are bit-identical before and after
    (the collective moves bytes, computes nothing), which is what lets
    the plan optimizer place/eliminate these nodes without breaking
    the planned==eager bitwise contract.  A no-op when the frame is
    already in the target layout.

    Deliberately WHOLE-frame: untouched columns cross the wire too.
    A partial relayout (move only the consulted planes) would leave
    the frame mixed-layout, breaking the uniform-sharding invariant
    every stage's explicit ``in_shardings`` now declares; the
    planner's dead-column pruning is the sanctioned way to shrink the
    moved set (it drops dead columns BEFORE packing, so they never
    reach the reshard)."""
    if target == RESHARD_SERIES_LOCAL:
        if d.time_axis is None:
            return d
        s_ax, t_ax = d.series_axis, d.time_axis
        new_series, new_time = (s_ax, t_ax), None
    elif target == RESHARD_TIME_SHARDED:
        if d.time_axis is not None or not (
                isinstance(d.series_axis, tuple)
                and len(d.series_axis) == 2):
            return d
        s_ax, t_ax = d.series_axis
        new_series, new_time = s_ax, t_ax
    else:
        raise ValueError(f"unknown reshard target {target!r}")
    names = list(d.cols)
    fn = _relayout_fn(d.mesh, s_ax, t_ax,
                      forward=(target == RESHARD_SERIES_LOCAL),
                      with_cols=bool(names), has_seq=d.seq is not None)
    ops = [d.ts, d.mask]
    if names:
        ops.append(jnp.stack([d.cols[c].values for c in names]))
        ops.append(jnp.stack([d.cols[c].valid for c in names]))
    if d.seq is not None:
        ops.append(d.seq)
    outs = list(fn(*ops))
    ts2, mask2 = outs[0], outs[1]
    i = 2
    new_cols = dict(d.cols)
    if names:
        vals2, valids2 = outs[2], outs[3]
        i = 4
        new_cols = {
            c: dataclasses.replace(col, values=vals2[j], valid=valids2[j])
            for j, (c, col) in enumerate(d.cols.items())
        }
    seq2 = outs[i] if d.seq is not None else None
    return d._with(ts=ts2, mask=mask2, cols=new_cols, seq=seq2,
                   series_axis=new_series, time_axis=new_time)


def relayout_comm_bytes(K_dev: int, L: int, n_cols: int, n_shards: int,
                        has_seq: bool = False) -> int:
    """Modeled per-shard all_to_all bytes of one :func:`reshard_frame`
    call: every plane's per-shard element count (K*L / total shards)
    times its itemsize — int64 ts + bool mask + n_cols x (compute
    dtype value + bool validity) [+ seq].  The explain() annotation
    and the reshard.plan_node compiled contract both read this model;
    ``profiling.comm_bytes_from_compiled`` is the measured side."""
    val_itemsize = np.dtype(packing.compute_dtype()).itemsize
    elems = (K_dev * L) // max(n_shards, 1)
    per_elem = 8 + 1 + n_cols * (val_itemsize + 1)
    if has_seq:
        per_elem += val_itemsize
    return int(elems * per_elem)


@functools.lru_cache(maxsize=256)
def _relayout_fn(mesh, series_axis, time_axis, forward=True,
                 with_cols=True, has_seq=False):
    """The jitted relayout program: P(series, time) <-> the joint
    P((series, time), None) series-local layout, every plane in one
    program (ts/mask [K, L]; value/validity stacks [C, K, L]; optional
    seq plane).  No donation: the input and output PER-DEVICE buffer
    shapes differ by construction (that is the point of a layout
    switch), so XLA could never apply an alias."""
    joint = (series_axis, time_axis)
    if forward:
        sp2_in, sp2_out = P(series_axis, time_axis), P(joint, None)
        sp3_in = P(None, series_axis, time_axis)
        sp3_out = P(None, joint, None)
    else:
        sp2_in, sp2_out = P(joint, None), P(series_axis, time_axis)
        sp3_in = P(None, joint, None)
        sp3_out = P(None, series_axis, time_axis)

    def kernel(*ops):
        if forward:
            a2a = lambda a: jax.lax.all_to_all(
                a, time_axis, split_axis=a.ndim - 2,
                concat_axis=a.ndim - 1, tiled=True)
        else:
            a2a = lambda a: jax.lax.all_to_all(
                a, time_axis, split_axis=a.ndim - 1,
                concat_axis=a.ndim - 2, tiled=True)
        return tuple(a2a(a) for a in ops)

    in_specs = [sp2_in, sp2_in]
    out_specs = [sp2_out, sp2_out]
    if with_cols:
        in_specs += [sp3_in, sp3_in]
        out_specs += [sp3_out, sp3_out]
    if has_seq:
        in_specs.append(sp2_in)
        out_specs.append(sp2_out)
    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=tuple(in_specs),
                             out_specs=tuple(out_specs)),
                   in_shardings=tuple(_ns(mesh, s) for s in in_specs),
                   out_shardings=tuple(_ns(mesh, s) for s in out_specs))


@functools.lru_cache(maxsize=8)
def _describe_reduce():
    """Jitted global reductions for describe(); cached so repeated
    describe() calls retrace nothing."""

    @jax.jit
    def reduce_cols(ts, mask, secs, vals, valids):
        out = {}
        out["min_ts"] = jnp.min(jnp.where(mask, ts, packing.TS_PAD))
        out["max_ts"] = jnp.max(jnp.where(mask, ts, jnp.int64(-2 ** 62)))
        out["n_rows"] = jnp.sum(mask)
        s = jnp.where(mask, secs, 0.0)
        out["has_frac"] = jnp.any(mask & (s - jnp.floor(s) > 0))
        out["sub_min"] = jnp.any(mask & (jnp.mod(s, 60) != 0))
        out["sub_hr"] = jnp.any(mask & (jnp.mod(s, 3600) != 0))
        out["sub_day"] = jnp.any(mask & (jnp.mod(s, 86400) != 0))
        ok = valids & mask[None]
        v = jnp.where(ok, vals, 0.0)
        out["count"] = jnp.sum(ok, axis=(1, 2))
        out["sum"] = jnp.sum(v, axis=(1, 2))
        out["sumsq"] = jnp.sum(v * v, axis=(1, 2))
        out["min"] = jnp.min(jnp.where(ok, vals, jnp.inf), axis=(1, 2))
        out["max"] = jnp.max(jnp.where(ok, vals, -jnp.inf), axis=(1, 2))
        # seconds view of the ts column (tsdf.py:393-400)
        out["ts_sum"] = jnp.sum(jnp.where(mask, secs, 0.0))
        out["ts_sumsq"] = jnp.sum(jnp.where(mask, secs * secs, 0.0))
        out["ts_min"] = jnp.min(jnp.where(mask, secs, jnp.inf))
        out["ts_max"] = jnp.max(jnp.where(mask, secs, -jnp.inf))
        return out

    return reduce_cols


@functools.lru_cache(maxsize=64)
def _autocorr_fn(lag, compact):
    """Jitted per-series lag-k autocorrelation; ``compact`` stable-sorts
    scattered valid rows (bucket-head views) to the front first so the
    physical lag pairing matches the host path's compacted layout."""

    @jax.jit
    def per_series(v, ok, mask):
        ok = ok & mask
        if compact:
            # stable sort by (invalid, position): valid rows keep order
            # at the front; the frame's row set becomes the valid rows
            key = (~ok).astype(jnp.int32)
            _, v, ok = jax.lax.sort(
                (key, v, ok), dimension=-1, num_keys=1, is_stable=True
            )
            mask2 = ok
        else:
            mask2 = mask
        Lh = v.shape[-1]
        cnt = jnp.sum(ok, axis=-1)
        mean = jnp.sum(jnp.where(ok, v, 0.0), axis=-1) \
            / jnp.maximum(cnt, 1)
        sub = jnp.where(ok, v - mean[:, None], 0.0)
        denom = jnp.sum(sub * sub, axis=-1)
        lengths = jnp.sum(mask2, axis=-1)
        if lag >= Lh:
            return jnp.full(denom.shape, jnp.nan), cnt, lengths
        left = sub[:, :-lag]
        right = sub[:, lag:]
        pos = jnp.arange(Lh - lag)
        keep = (
            (pos[None, :] + 1 <= cnt[:, None] - lag)
            & (pos[None, :] + lag < lengths[:, None])
            & ok[:, :-lag] & ok[:, lag:]
        )
        num = jnp.sum(jnp.where(keep, left * right, 0.0), axis=-1)
        any_pair = jnp.any(keep, axis=-1)
        ac = jnp.where(any_pair, num, jnp.nan) / denom
        return ac, cnt, lengths

    return per_series


def _bucket_heads(ts, mask, step_ns):
    """Shared tumbling-bucket scaffolding: absolute bucket key ``b``,
    bucket-head mask, and per-row [start, end) row bounds of the row's
    bucket (used by resample, grouped stats, and vwap).

    The searchsorted bounds run over ``b_all`` (every row's bucket,
    masked rows included) and NOT the TS_PAD-masked ``b``: a masked row
    *between* two real rows of one bucket (any bucket-head view — e.g.
    a chained resample) would make ``b`` non-monotone, and the TPU
    sort-based searchsorted silently returns garbage on unsorted keys
    (round-4 fix; the masked rows inside a range are harmless — their
    validity planes are False).  ``head`` compares each real row's
    bucket against the previous REAL row's bucket (a cummax carry —
    buckets are monotone over the sorted ts), not the physically
    previous row: comparing against a masked neighbour flagged every
    real row after a gap as a head, duplicating buckets in chained
    resamples (round-4 fix)."""
    step = jnp.int64(step_ns)
    b_all = (ts // step) * step
    b = jnp.where(mask, b_all, packing.TS_PAD)
    neg = jnp.int64(-(2**62))
    last_real = rk.wu.cummax(jnp.where(mask, b_all, neg))
    prev_real = jnp.concatenate(
        [jnp.full_like(b[:, :1], neg), last_real[:, :-1]], axis=-1
    )
    head = mask & (b_all != prev_real)
    start = rk.wu.searchsorted_batched(b_all, b_all,
                                       side="left").astype(jnp.int32)
    end = rk.wu.searchsorted_batched(b_all, b_all + step,
                                     side="left").astype(jnp.int32)
    # per-row rebased i32 bucket id for the VMEM segmented-reduction
    # kernel (rk.bucket_stats); pads clamp to i32-max and form their
    # own trailing bucket, masked downstream like the bound form
    rel = (b_all - b_all[:, :1]) // step
    bid = jnp.minimum(rel, 2**31 - 1).astype(jnp.int32)
    return b, head, start, end, bid


@functools.lru_cache(maxsize=256)
def _bucket_stats_fn(mesh, series_axis, time_axis, step_ns, n_cols,
                     sort_kernels=False):
    """Six aggregates per epoch-aligned tumbling bucket, emitted at
    bucket-head rows (withGroupedStats tsdf.py:723-759 / vwap
    aggregation).  Time-sharded meshes switch to a series-local layout
    around the bucket reduction, like _resample_fn."""
    n_t = mesh.shape[time_axis] if time_axis else 1
    sp2 = _spec(mesh, series_axis, time_axis)
    sp3 = _spec(mesh, series_axis, time_axis, 3)

    def local(ts, mask, vals, valids):
        b, head, start, end, bid = _bucket_heads(ts, mask, step_ns)
        # packed passes share the bucket-id plane across the column
        # stack (bucket_pack_budget-sized groups); bitwise-identical to
        # the per-column loop it replaced
        stats = rk.bucket_stats_multi(bid, vals, valids, start, end)
        new_ts = jnp.where(mask, b, packing.TS_PAD)
        # [6, n_cols, K, L]
        return new_ts, head, jnp.stack([
            stats["mean"], stats["count"], stats["min"], stats["max"],
            stats["sum"], stats["stddev"],
        ])

    def kernel(ts, mask, vals, valids):
        if n_t > 1:
            a2a_in = lambda a: jax.lax.all_to_all(
                a, time_axis, split_axis=a.ndim - 2, concat_axis=a.ndim - 1,
                tiled=True)
            a2a_out = lambda a: jax.lax.all_to_all(
                a, time_axis, split_axis=a.ndim - 1, concat_axis=a.ndim - 2,
                tiled=True)
            ts, mask, vals, valids = (a2a_in(a) for a in
                                      (ts, mask, vals, valids))
            new_ts, head, stats = local(ts, mask, vals, valids)
            return a2a_out(new_ts), a2a_out(head), a2a_out(stats)
        return local(ts, mask, vals, valids)

    sp_stats = _spec(mesh, series_axis, time_axis, 4)
    return jax.jit(shard_map(kernel, mesh=mesh,
                             in_specs=(sp2, sp2, sp3, sp3),
                             out_specs=(sp2, sp2, sp_stats)))


@functools.lru_cache(maxsize=256)
def _interp_fn(mesh, series_axis, time_axis, step_ns, G, mkey, n_cols,
               flags):
    """Dense-grid gap fill (interpol.py semantics): generate each
    series' bucket grid and fill via prev/next merge joins.

    Inputs are a bucket-head resample view [K, L]; outputs are dense
    [K, G] grids, series-sharded (``P(series, None)``) — on a
    time-sharded mesh the inputs regather series-local first (the grid
    length G has no relation to the input shard width)."""
    from tempo_tpu.ops import sortmerge as sm

    n_t = mesh.shape[time_axis] if time_axis else 1
    # interpolate() reshards time-sharded frames through reshard_frame
    # BEFORE building this kernel, so only series-local (or degenerate
    # size-1 time axis) frames reach here
    assert n_t == 1, "interpolate kernels are series-local by contract"
    sp2_in = _spec(mesh, series_axis, time_axis)
    sp3_in = _spec(mesh, series_axis, time_axis, 3)
    sp2_out = _spec(mesh, series_axis, None)
    sp3_out = _spec(mesh, series_axis, None, 3)

    def kernel(ts, head, vals, valids):
        step = jnp.int64(step_ns)
        dt = vals.dtype

        ts_j = jnp.where(head, ts, packing.TS_PAD)
        first_b = jnp.min(ts_j, axis=1, keepdims=True)         # [K, 1]
        last_b = jnp.max(jnp.where(head, ts, jnp.int64(-1)), axis=1,
                         keepdims=True)
        # the merge joins below receive ``ts`` (sorted), NOT the
        # TS_PAD-masked ``ts_j``: a bucket-head view has interior
        # head=False rows, and masking them to TS_PAD breaks the
        # ascending-per-row contract of the TPU merge kernels (silent
        # wrong results; round-4 fix).  Non-head rows are excluded by
        # their validity planes instead — identical semantics, the
        # per-column fill skips invalid rows.
        has_any = last_b >= 0
        gridj = jnp.arange(G, dtype=jnp.int64)[None, :]        # [1, G]
        grid_ts = jnp.where(
            has_any, first_b + gridj * step, packing.TS_PAD
        )
        grid_mask = has_any & (grid_ts <= last_b)
        grid_ts = jnp.where(grid_mask, grid_ts, packing.TS_PAD)

        # per-col planes: value + exact bucket index; plus one row plane
        bidx = jnp.where(head, (ts - jnp.where(has_any, first_b, 0))
                         // step, -1).astype(dt)
        planes = jnp.concatenate([
            vals,
            jnp.broadcast_to(bidx, (n_cols,) + bidx.shape),
            bidx[None],
        ])
        pvalid = jnp.concatenate([
            valids, valids, head[None],
        ])
        prev_v, prev_f, _ = sm.asof_merge_values(
            grid_ts, ts, pvalid, planes
        )
        neg = lambda a: -a[..., ::-1]
        flip = lambda a: a[..., ::-1]
        next_v_r, next_f_r, _ = sm.asof_merge_values(
            neg(grid_ts), neg(ts), flip(pvalid), flip(planes)
        )
        next_v = flip(next_v_r)
        next_f = flip(next_f_r)

        gj = gridj.astype(dt)
        out_vals = []
        out_valid = []
        col_interp = []
        for i in range(n_cols):
            pv, pf = prev_v[i], prev_f[i]
            pi = prev_v[n_cols + i]
            nv, nf = next_v[i], next_f[i]
            ni = next_v[n_cols + i]
            exact = pf & (pi == gj)
            if mkey == 0:        # zero
                filled = jnp.where(exact, pv, 0.0)
                ok = grid_mask
            elif mkey == 1:      # null
                filled = jnp.where(exact, pv, jnp.nan)
                ok = grid_mask & exact
            elif mkey == 2:      # ffill
                filled = jnp.where(pf, pv, jnp.nan)
                ok = grid_mask & pf
            elif mkey == 3:      # bfill
                filled = jnp.where(nf, nv, jnp.nan)
                ok = grid_mask & nf
            else:                # linear
                both = pf & nf & (ni > pi)
                w = jnp.where(both, (gj - pi) / jnp.maximum(ni - pi, 1), 0.0)
                lerp = pv + (nv - pv) * w
                filled = jnp.where(exact, pv,
                                   jnp.where(both, lerp, jnp.nan))
                ok = grid_mask & (exact | both)
            out_vals.append(jnp.where(grid_mask, filled, jnp.nan))
            out_valid.append(ok)
            col_interp.append(grid_mask & ~exact)

        row_pi = prev_v[2 * n_cols]
        row_pf = prev_f[2 * n_cols]
        ts_interp = grid_mask & ~(row_pf & (row_pi == gj))
        out = (grid_ts, grid_mask, jnp.stack(out_vals),
               jnp.stack(out_valid))
        if flags:
            out = out + (ts_interp, jnp.stack(col_interp))
        return out

    out_specs = (sp2_out, sp2_out, sp3_out, sp3_out)
    if flags:
        out_specs = out_specs + (sp2_out, sp3_out)
    return jax.jit(shard_map(kernel, mesh=mesh,
                             in_specs=(sp2_in, sp2_in, sp3_in, sp3_in),
                             out_specs=out_specs))


@functools.lru_cache(maxsize=256)
def _lookback_tensor_fn(mesh, series_axis, time_axis, w, n_cols):
    """[F, K, L] planes -> ([K, L, w, F] values, mask) shifted stacks
    (rolling.lookback_tensor semantics: slot j = observation t-w+j,
    zero/False where absent).  Time-sharded meshes regather
    series-local rows first — the output stays series-local over all
    devices, like the interpolate grid outputs."""
    n_t = mesh.shape[time_axis] if time_axis else 1
    sp_in = _spec(mesh, series_axis, time_axis, 3)
    if n_t > 1:
        sp_out = P((series_axis, time_axis), None, None, None)
    else:
        sp_out = P(series_axis, None, None, None)

    def kernel(vals, valids):
        from tempo_tpu.rolling import lookback_stack

        if n_t > 1:
            a2a = lambda a: jax.lax.all_to_all(
                a, time_axis, split_axis=a.ndim - 2, concat_axis=a.ndim - 1,
                tiled=True)
            vals, valids = a2a(vals), a2a(valids)
        return lookback_stack(vals.transpose(1, 2, 0),
                              valids.transpose(1, 2, 0), w)

    return jax.jit(shard_map(kernel, mesh=mesh, in_specs=(sp_in, sp_in),
                             out_specs=(sp_out, sp_out)))


@functools.lru_cache(maxsize=256)
def _fourier_fn(mesh, series_axis, time_axis, timestep):
    """Per-series exact-length DFT planes (freq, ft_real, ft_imag) on
    front-packed [K, L] rows; one Bluestein program at the lane width
    serves every length mix (ops/fft.py).  Time-sharded meshes switch
    to series-local full rows around the transform."""
    from tempo_tpu.ops import fft as fft_ops

    n_t = mesh.shape[time_axis] if time_axis else 1
    # fourier_transform() reshards time-sharded frames through
    # reshard_frame BEFORE building this kernel
    assert n_t == 1, "fourier kernels are series-local by contract"
    sp2 = _spec(mesh, series_axis, time_axis)

    def local(vals, mask):
        L = vals.shape[-1]
        n = jnp.sum(mask, axis=-1)                       # [K]
        x = jnp.where(mask, vals, 0.0).astype(vals.dtype)
        # the Bluestein bucket must be a power of two (its internal
        # convolution length is 2*bucket); the frame's lane width is
        # only 8-aligned — zero-pad up and slice back
        B2 = 1 << max(int(L) - 1, 1).bit_length()
        if B2 != L:
            x = jnp.pad(x, ((0, 0), (0, B2 - L)))
        re, im = fft_ops.bluestein_dft(x, jnp.maximum(n, 1), B2)
        re, im = re[:, :L], im[:, :L]
        j = jnp.arange(L)[None, :]
        n_ = jnp.maximum(n[:, None], 1)
        # np.fft.fftfreq order: [0 .. (n-1)//2, -(n//2) .. -1] / (n d)
        jj = jnp.where(j <= (n_ - 1) // 2, j, j - n_)
        freq = jj.astype(vals.dtype) / (
            n_.astype(vals.dtype) * vals.dtype.type(timestep)
        )
        ok = j < n[:, None]
        nan = vals.dtype.type(jnp.nan)
        return (jnp.where(ok, freq, nan),
                jnp.where(ok, re.astype(vals.dtype), nan),
                jnp.where(ok, im.astype(vals.dtype), nan))

    return jax.jit(shard_map(local, mesh=mesh, in_specs=(sp2, sp2),
                             out_specs=(sp2, sp2, sp2)))


@functools.lru_cache(maxsize=256)
def _resample_fn(mesh, series_axis, time_axis, step_ns, fkey, n_cols,
                 sort_kernels=False):
    """Bucket-head resample kernel.  On a time-sharded mesh the blocks
    all_to_all to a series-local layout (full rows per device), compute,
    and switch back — the reference's groupBy shuffle as two ICI
    collectives (reshard.py pattern)."""
    n_t = mesh.shape[time_axis] if time_axis else 1
    # resample() reshards time-sharded frames through reshard_frame
    # BEFORE building this kernel (dist.resample)
    assert n_t == 1, "resample kernels are series-local by contract"
    sp2 = _spec(mesh, series_axis, time_axis)
    sp3 = _spec(mesh, series_axis, time_axis, 3)

    def local(ts, mask, vals, valids):
        b, head, start, end, bid = _bucket_heads(ts, mask, step_ns)

        if fkey == 1:
            # ceil reads each bucket's last REAL row: a bucket-head
            # view can end its physical [start, end) run on a masked
            # row, so the gather index comes from a segmented
            # last-real-lane scan, not end-1 itself (round-4 fix;
            # identical to end-1 on dense frames)
            from tempo_tpu.ops.sortmerge import _ffill_scan_seg

            K_l, L_l = mask.shape
            lane = jnp.broadcast_to(
                jnp.arange(L_l, dtype=jnp.int32), (K_l, L_l)
            )
            fence = jnp.concatenate(
                [jnp.ones((K_l, 1), jnp.bool_),
                 bid[:, 1:] != bid[:, :-1]], axis=-1
            )
            _, has_real, last_lane = _ffill_scan_seg(fence, mask, lane)
            last_phys = jnp.maximum(end - 1, 0)
            idx = jnp.take_along_axis(last_lane, last_phys, axis=-1)
            has = jnp.take_along_axis(has_real, last_phys, axis=-1)
            last = jnp.maximum(idx, 0)

        if fkey >= 2:              # mean/min/max: one packed reduction
            stats = rk.bucket_stats_multi(bid, vals, valids, start, end)
            key = {2: "mean", 3: "min", 4: "max"}[fkey]
        outs = []
        oks = []
        for i in range(n_cols):
            x, v = vals[i], valids[i]
            if fkey == 0:          # floor: first record of the bucket
                outs.append(x)
                oks.append(head & v)
            elif fkey == 1:        # ceil: last record of the bucket
                outs.append(jnp.take_along_axis(x, last, axis=-1))
                oks.append(head & has
                           & jnp.take_along_axis(v, last, axis=-1))
            else:
                outs.append(stats[key][i])
                oks.append(head & (stats["count"][i] > 0))
        new_ts = jnp.where(mask, b, packing.TS_PAD)
        return new_ts, head, jnp.stack(outs), jnp.stack(oks)

    return jax.jit(shard_map(local, mesh=mesh,
                             in_specs=(sp2, sp2, sp3, sp3),
                             out_specs=(sp2, sp2, sp3, sp3)))
