"""Spectral ops: Fourier transform and autocorrelation.

* ``fourier_transform`` (reference tsdf.py:828-902): the reference ships
  each series to a Python worker via ``applyInPandas`` and runs scipy's
  fft.  Here it is a *batched* ``jnp.fft.fft`` on the packed layout -
  series are grouped by length (XLA FFTs are static-shape) and each
  length group is one device call, replacing per-group Arrow IPC with
  on-device batch FFT.
* ``autocorr`` (reference tsdf.py:192-316): the reference's
  row_number + self-join-shifted-by-lag dance collapses to a masked
  shifted dot product on the packed arrays.  Exact parity quirks kept:
  the pair range is bounded by the *non-null count* (grouping_col1 at
  tsdf.py:229), while row numbers run over all rows, and null products
  drop out of the numerator.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np
import pandas as pd

import jax.numpy as jnp

from tempo_tpu import packing


# On TPU the complex-typed FFT path is unavailable (no c64/c128
# materialisation on the axon backend), so for moderate lengths we run
# the DFT as two real matmuls on the MXU: X = x @ (cos - i sin)(2pi jk/L).
# O(L^2) flops but the systolic array makes it faster than shipping the
# batch to the host up to a few-thousand-point series.
_MXU_DFT_MAX_LEN = 2048


def _batched_fft(batch: np.ndarray):
    """[B, L] real -> (real, imag) of the DFT along the last axis."""
    import jax

    if jax.default_backend() == "cpu":
        tran = np.asarray(jnp.fft.fft(jnp.asarray(batch), axis=-1))
        return tran.real, tran.imag
    L = batch.shape[-1]
    if L <= _MXU_DFT_MAX_LEN:
        j = np.arange(L)
        angle = 2.0 * np.pi * np.outer(j, j) / L
        cos_m = jnp.asarray(np.cos(angle), jnp.float32)
        sin_m = jnp.asarray(np.sin(angle), jnp.float32)
        import jax.lax as lax

        xb = jnp.asarray(batch, jnp.float32)
        re = np.asarray(jnp.matmul(xb, cos_m, precision=lax.Precision.HIGHEST))
        im = np.asarray(-jnp.matmul(xb, sin_m, precision=lax.Precision.HIGHEST))
        return re, im
    tran = np.fft.fft(batch, axis=-1)  # host fallback for very long series
    return tran.real, tran.imag


def fourier_transform(tsdf, timestep: float, valueCol: str):
    from tempo_tpu.frame import TSDF

    # validation parity (tsdf.py:853) - resolve case-insensitively like
    # Spark's analyzer, then use the frame's actual column name
    matches = [c for c in tsdf.df.columns if c.lower() == valueCol.lower()]
    if not matches:
        raise ValueError(f"Column {valueCol} not found in Dataframe")
    valueCol = matches[0]

    layout = tsdf.layout
    sorted_df = tsdf.df.iloc[layout.order].reset_index(drop=True)
    vals = pd.to_numeric(sorted_df[valueCol], errors="coerce").to_numpy(np.float64)

    lengths = layout.lengths
    ft_real = np.empty(layout.n_rows)
    ft_imag = np.empty(layout.n_rows)
    freq = np.empty(layout.n_rows)

    # batch series of equal length into single device calls
    for L in np.unique(lengths):
        if L == 0:
            continue
        keys = np.flatnonzero(lengths == L)
        rows = (layout.starts[keys][:, None] + np.arange(L)[None, :])  # [B, L]
        re, im = _batched_fft(vals[rows])
        ft_real[rows] = re
        ft_imag[rows] = im
        freq[rows] = np.fft.fftfreq(int(L), d=timestep)[None, :]

    select_cols = tsdf.partitionCols + [tsdf.ts_col]
    if tsdf.sequence_col:
        select_cols.append(tsdf.sequence_col)
    out = sorted_df[select_cols + [valueCol]].copy()
    out["freq"] = freq
    out["ft_real"] = ft_real
    out["ft_imag"] = ft_imag
    return TSDF(out, tsdf.ts_col, tsdf.partitionCols, tsdf.sequence_col or None)


def autocorr(tsdf, col: str, lag: int = 1) -> pd.DataFrame:
    """Returns a bare DataFrame of partition cols + autocorr_lag_<lag>
    (reference returns a DataFrame, not a TSDF)."""
    layout = tsdf.layout
    L = tsdf.packed_len()
    v, ok = tsdf.packed_numeric(col)
    v = jnp.asarray(v)
    ok = jnp.asarray(ok)
    lengths = jnp.asarray(layout.lengths)

    cnt = jnp.sum(ok, axis=-1)
    mean = jnp.sum(jnp.where(ok, v, 0.0), axis=-1) / jnp.maximum(cnt, 1)
    sub = jnp.where(ok, v - mean[:, None], jnp.nan)
    denom = jnp.nansum(jnp.where(ok, sub * sub, jnp.nan), axis=-1)

    if lag >= L:
        num = jnp.full_like(denom, jnp.nan)
        any_pair = jnp.zeros(denom.shape, bool)
    else:
        left = sub[:, :-lag]          # row r   (0-based pos)
        right = sub[:, lag:]          # row r+lag
        pos = jnp.arange(L - lag)
        # pair kept when row (pos+1) <= non-null count - lag, the row
        # exists, and both values are non-null (tsdf.py:228-251)
        keep = (
            (pos[None, :] + 1 <= cnt[:, None] - lag)
            & (pos[None, :] + lag < lengths[:, None])
            & ok[:, :-lag]
            & ok[:, lag:]
        )
        num = jnp.sum(jnp.where(keep, left * right, 0.0), axis=-1)
        any_pair = jnp.any(keep, axis=-1)

    # a series only yields a row when the numerator join is non-empty
    # (reference tsdf.py:248-253 inner joins drop pairless series)
    present = np.asarray((lengths > lag) & (cnt > lag))
    ac = np.asarray(jnp.where(any_pair, num, jnp.nan) / denom).astype(np.float64)

    out = tsdf.layout.key_frame.copy()
    if not tsdf.partitionCols:
        out = pd.DataFrame({"_dummy_group_col": ["dummy"]})
    out[f"autocorr_lag_{lag}"] = ac
    return out[present].reset_index(drop=True)
