"""Spectral ops: Fourier transform and autocorrelation.

* ``fourier_transform`` (reference tsdf.py:828-902): the reference ships
  each series to a Python worker via ``applyInPandas`` and runs scipy's
  fft.  Here it is a *batched* ``jnp.fft.fft`` on the packed layout -
  series are grouped by length (XLA FFTs are static-shape) and each
  length group is one device call, replacing per-group Arrow IPC with
  on-device batch FFT.
* ``autocorr`` (reference tsdf.py:192-316): the reference's
  row_number + self-join-shifted-by-lag dance collapses to a masked
  shifted dot product on the packed arrays.  Exact parity quirks kept:
  the pair range is bounded by the *non-null count* (grouping_col1 at
  tsdf.py:229), while row numbers run over all rows, and null products
  drop out of the numerator.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np
import pandas as pd

import jax.numpy as jnp

from tempo_tpu import packing


def _device_fft_by_bucket(vals, layout, ft_real, ft_imag):
    """Batched exact DFTs on device, grouped by *power-of-two length
    bucket* (not exact length): every series whose length falls in
    (B/2, B] rides the same compiled Bluestein program of bucket B, so
    a Zipfian key distribution costs O(log max_len) compilations
    instead of O(#distinct lengths) — VERDICT r1 weak #5.  Lengths
    above the old 2048 DFT ceiling run through the four-step MXU
    factorisation inside tempo_tpu.ops.fft."""
    import jax

    from tempo_tpu.ops import fft as fft_ops

    dt = np.float32 if jax.default_backend() == "tpu" else np.float64
    lengths = layout.lengths
    # pow2 bucket per series (min 8)
    buckets = np.maximum(8, 2 ** np.ceil(
        np.log2(np.maximum(lengths, 1))).astype(np.int64))
    for B in np.unique(buckets):
        keys = np.flatnonzero(buckets == B)
        keys = keys[lengths[keys] > 0]
        if keys.size == 0:
            continue
        ns = lengths[keys].astype(np.int64)
        pos = np.arange(int(B))[None, :]
        idx = layout.starts[keys][:, None] + np.minimum(pos, ns[:, None] - 1)
        xs = np.where(pos < ns[:, None], vals[idx], 0.0).astype(dt)
        re, im = fft_ops.bluestein_dft(jnp.asarray(xs), jnp.asarray(ns),
                                       int(B))
        re, im = np.asarray(re, np.float64), np.asarray(im, np.float64)
        out_rows = layout.starts[keys][:, None] + pos
        keep = pos < ns[:, None]
        ft_real[out_rows[keep]] = re[keep]
        ft_imag[out_rows[keep]] = im[keep]


def fourier_transform(tsdf, timestep: float, valueCol: str):
    from tempo_tpu.frame import TSDF

    # validation parity (tsdf.py:853) - resolve case-insensitively like
    # Spark's analyzer, then use the frame's actual column name
    matches = [c for c in tsdf.df.columns if c.lower() == valueCol.lower()]
    if not matches:
        raise ValueError(f"Column {valueCol} not found in Dataframe")
    valueCol = matches[0]

    import jax

    layout = tsdf.layout
    sorted_df = tsdf.df.iloc[layout.order].reset_index(drop=True)
    vals = pd.to_numeric(sorted_df[valueCol], errors="coerce").to_numpy(np.float64)

    lengths = layout.lengths
    ft_real = np.empty(layout.n_rows)
    ft_imag = np.empty(layout.n_rows)
    freq = np.empty(layout.n_rows)

    if jax.default_backend() == "cpu":
        # the host IS the compute device here: numpy's FFT with zero
        # XLA compilations, grouped by exact length
        for L in np.unique(lengths):
            if L == 0:
                continue
            keys = np.flatnonzero(lengths == L)
            rows = layout.starts[keys][:, None] + np.arange(L)[None, :]
            tran = np.fft.fft(vals[rows], axis=-1)
            ft_real[rows] = tran.real
            ft_imag[rows] = tran.imag
    else:
        _device_fft_by_bucket(vals, layout, ft_real, ft_imag)
    for L in np.unique(lengths):
        if L == 0:
            continue
        keys = np.flatnonzero(lengths == L)
        rows = layout.starts[keys][:, None] + np.arange(L)[None, :]
        freq[rows] = np.fft.fftfreq(int(L), d=timestep)[None, :]

    select_cols = tsdf.partitionCols + [tsdf.ts_col]
    if tsdf.sequence_col:
        select_cols.append(tsdf.sequence_col)
    out = sorted_df[select_cols + [valueCol]].copy()
    out["freq"] = freq
    out["ft_real"] = ft_real
    out["ft_imag"] = ft_imag
    return TSDF(out, tsdf.ts_col, tsdf.partitionCols, tsdf.sequence_col or None)


def autocorr(tsdf, col: str, lag: int = 1) -> pd.DataFrame:
    """Returns a bare DataFrame of partition cols + autocorr_lag_<lag>
    (reference returns a DataFrame, not a TSDF)."""
    layout = tsdf.layout
    L = tsdf.packed_len()
    v, ok = tsdf.packed_numeric(col)
    v = jnp.asarray(v)
    ok = jnp.asarray(ok)
    lengths = jnp.asarray(layout.lengths)

    cnt = jnp.sum(ok, axis=-1)
    mean = jnp.sum(jnp.where(ok, v, 0.0), axis=-1) / jnp.maximum(cnt, 1)
    sub = jnp.where(ok, v - mean[:, None], jnp.nan)
    denom = jnp.nansum(jnp.where(ok, sub * sub, jnp.nan), axis=-1)

    if lag >= L:
        num = jnp.full_like(denom, jnp.nan)
        any_pair = jnp.zeros(denom.shape, bool)
    else:
        left = sub[:, :-lag]          # row r   (0-based pos)
        right = sub[:, lag:]          # row r+lag
        pos = jnp.arange(L - lag)
        # pair kept when row (pos+1) <= non-null count - lag, the row
        # exists, and both values are non-null (tsdf.py:228-251)
        keep = (
            (pos[None, :] + 1 <= cnt[:, None] - lag)
            & (pos[None, :] + lag < lengths[:, None])
            & ok[:, :-lag]
            & ok[:, lag:]
        )
        num = jnp.sum(jnp.where(keep, left * right, 0.0), axis=-1)
        any_pair = jnp.any(keep, axis=-1)

    # a series only yields a row when the numerator join is non-empty
    # (reference tsdf.py:248-253 inner joins drop pairless series)
    present = np.asarray((lengths > lag) & (cnt > lag))
    ac = np.asarray(jnp.where(any_pair, num, jnp.nan) / denom).astype(np.float64)

    out = tsdf.layout.key_frame.copy()
    if not tsdf.partitionCols:
        out = pd.DataFrame({"_dummy_group_col": ["dummy"]})
    out[f"autocorr_lag_{lag}"] = ac
    return out[present].reset_index(drop=True)
