"""tempo-tpu: a TPU-native time-series analytics framework.

From-scratch rebuild of the capabilities of dbl-tempo
(/root/reference, the Databricks Labs TSDF library) on JAX/XLA:
series are packed, time-sorted columnar arrays sharded over a device
mesh; ops are jitted/vmapped kernels (searchsorted AS-OF merges,
prefix-scan rolling stats, segment-reduce resampling, associative-scan
EMA, batched FFT) instead of Spark Window expressions.

Public surface mirrors the reference: ``TSDF`` plus ``display``
(python/tempo/__init__.py:1-2).
"""

import os as _os

from tempo_tpu import config as _config

# capture the platform the user asked for BEFORE importing jax: device
# plugins may rewrite JAX_PLATFORMS during jax import, which would
# silently retarget e.g. an explicitly requested CPU run
_requested_platform = _config.env_external("JAX_PLATFORMS")

import jax

# int64-nanosecond timestamps and float64 golden-parity accumulations
# require 64-bit mode; TPU fast paths opt into f32/bf16 explicitly.
jax.config.update("jax_enable_x64", True)

# Enforce the platform the user named in the environment: device
# plugins may prepend themselves to jax_platforms during import (e.g.
# 'cpu' -> 'axon,cpu'), silently retargeting an explicitly requested
# CPU run.  An env var set at process start is an explicit user choice;
# code that wants a different platform can still call
# jax.config.update("jax_platforms", ...) after importing tempo_tpu.
if _requested_platform and jax.config.jax_platforms != _requested_platform:
    jax.config.update("jax_platforms", _requested_platform)

# Persistent compilation cache: TSDF kernels are compiled per packed
# shape and some (notably windowed range stats) take tens of seconds of
# XLA time; caching makes every process after the first start warm.
# Opt out with TEMPO_TPU_CACHE_DIR="" or pre-set jax_compilation_cache_dir.
if jax.config.jax_compilation_cache_dir is None:
    _cache_dir = _config.get(
        "TEMPO_TPU_CACHE_DIR",
        _os.path.join(_os.path.expanduser("~"), ".cache", "tempo_tpu", "jax"),
    )
    if _cache_dir:
        jax.config.update("jax_compilation_cache_dir", _cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

from tempo_tpu.frame import TSDF  # noqa: E402
from tempo_tpu.utils import display  # noqa: E402

__version__ = "0.1.0"
__all__ = ["TSDF", "DistributedTSDF", "display"]


def __getattr__(name):  # PEP 562: keep the mesh/shard_map stack lazy —
    # host-only users never pay for it (frame.on_mesh imports it lazily
    # for the same reason)
    if name == "DistributedTSDF":
        from tempo_tpu.dist import DistributedTSDF

        return DistributedTSDF
    raise AttributeError(f"module 'tempo_tpu' has no attribute {name!r}")
