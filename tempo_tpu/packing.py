"""Ragged->padded packing: the foundational layout transform of tempo-tpu.

The reference (dbl-tempo) represents a collection of time series as a lazy
Spark DataFrame partitioned by key columns (``Window.partitionBy`` /
``groupBy``); Spark's shuffle dynamically routes rows of one key to one
task (see /root/reference/python/tempo/tsdf.py:121,571).  XLA wants static
shapes, so tempo-tpu instead *packs* the ragged per-key row groups into
dense ``[num_series, padded_len]`` arrays with validity masks.  Every
kernel in ``tempo_tpu.ops`` consumes this layout and is ``vmap``-ed over
the leading (series) axis, which is also the axis we shard across a TPU
mesh (see ``tempo_tpu.parallel``).

Time is canonicalised to int64 nanoseconds (``ts_ns``); a float64 seconds
view is derived where the reference semantics are defined in seconds
(range windows cast timestamps to long seconds, tsdf.py:567; skew
bracketing casts to double seconds, tsdf.py:169-178).  We document the
divergence: int64 ns is exact where Spark's double cast is not.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np
import pandas as pd

from tempo_tpu import native

NS_PER_S = 1_000_000_000

# Sentinel used in padded slots of the time axis: larger than any real
# timestamp so sorted-order based kernels (searchsorted, merges) naturally
# ignore padding.  We keep headroom so small arithmetic offsets cannot
# overflow int64.
TS_PAD = np.int64(2**62)

# Any ts at or above this is a sentinel, not data (real ns timestamps
# stay far below 2^61 ≈ year 2043 in ns); window/halo kernels use it to
# tell real rows from padding with headroom on both sides.
TS_REAL_MAX = np.int64(2**61)

# Canonical name/order of the per-column aggregates withRangeStats
# emits (`<stat>_<col>`, Spark's six plus the derived zscore).  The
# stats kernels (ops/sortmerge, ops/pallas_window), the frame/mesh
# unpack loops, and the planner's schema inference + fused program
# (tempo_tpu/plan) must all agree on this tuple — define it once.
RANGE_STATS = ("mean", "count", "min", "max", "sum", "stddev", "zscore")


def compute_dtype() -> np.dtype:
    """Floating dtype for on-device metric math.

    TPU has no native f64 — emulation is ~25x slower than f32 (measured
    5.4s vs ms-scale for a 1M-row withRangeStats) — so the TPU backend
    computes in float32 (kernels mean-centre accumulations to keep f32
    benign) and frame-level outputs are cast back to float64 at the host
    boundary.  CPU (the golden-parity test platform) keeps full float64.
    Override with TEMPO_TPU_COMPUTE_DTYPE=float64|float32.
    """
    from tempo_tpu import config

    env = config.get("TEMPO_TPU_COMPUTE_DTYPE")
    if env:
        return np.dtype(env)
    import jax

    return np.dtype(np.float32 if jax.default_backend() == "tpu" else np.float64)


def rebase_seconds(ts_sec: np.ndarray, pad_mask: Optional[np.ndarray] = None):
    """Per-series rebase of a [K, L] seconds axis to small offsets.

    64-bit integer compares are also emulated on TPU, so range-window
    kernels take int32 seconds-from-series-start instead of absolute
    unix seconds when every span allows it.  Padded slots (``pad_mask``
    True) clamp to INT32_MAX so sorted-order kernels keep ignoring them.
    Returns (rebased int32 [K, L], ok) — ok False means some span
    overflows int32 and the caller must stay on int64.
    """
    if ts_sec.size == 0:
        return ts_sec.astype(np.int32), True
    first = ts_sec[:, :1]
    span = ts_sec - first
    if pad_mask is not None:
        span = np.where(pad_mask, 0, span)
    if span.max(initial=0) >= 2**31 - 2:
        return ts_sec.astype(np.int64), False
    out = span.astype(np.int32)
    if pad_mask is not None:
        out = np.where(pad_mask, np.int32(2**31 - 1), out)
    return out, True


def series_to_ns(values: "pd.Series | np.ndarray") -> np.ndarray:
    """Convert a timestamp-like column to canonical int64 nanoseconds.

    datetime64 -> ns since epoch; integers -> value interpreted as seconds
    (matching Spark's ``cast("double")`` of numeric ts cols, which yields
    the raw value in 'seconds' units for windowing math); floats -> seconds
    scaled to ns.
    """
    if isinstance(values, pd.Series) and isinstance(
        values.dtype, pd.DatetimeTZDtype
    ):
        # tz-aware columns canonicalise through UTC (Spark stores
        # session-local timestamps as UTC micros the same way)
        values = values.dt.tz_convert("UTC").dt.tz_localize(None)
    arr = values.to_numpy() if isinstance(values, pd.Series) else np.asarray(values)
    if np.issubdtype(arr.dtype, np.datetime64):
        return arr.astype("datetime64[ns]").astype(np.int64)
    if np.issubdtype(arr.dtype, np.integer):
        return arr.astype(np.int64) * NS_PER_S
    if np.issubdtype(arr.dtype, np.floating):
        return np.round(arr * NS_PER_S).astype(np.int64)
    raise TypeError(f"Unsupported timestamp dtype: {arr.dtype}")


def ns_to_original(ns: np.ndarray, like_dtype):
    """Map canonical ns back to the dtype the user supplied."""
    if isinstance(like_dtype, pd.DatetimeTZDtype):
        utc = pd.Series(ns.astype("datetime64[ns]")).dt.tz_localize("UTC")
        return utc.dt.tz_convert(like_dtype.tz).to_numpy()
    if np.issubdtype(like_dtype, np.datetime64):
        return ns.astype("datetime64[ns]")
    if np.issubdtype(like_dtype, np.integer):
        return (ns // NS_PER_S).astype(like_dtype)
    if np.issubdtype(like_dtype, np.floating):
        return (ns / NS_PER_S).astype(like_dtype)
    raise TypeError(f"Unsupported timestamp dtype: {like_dtype}")


def encode_keys(
    df: pd.DataFrame, partition_cols: List[str]
) -> Tuple[np.ndarray, pd.DataFrame]:
    """Factorize the partition-key tuple into dense int32 series ids.

    Equivalent role to Spark's hash-shuffle routing on partition columns
    (tsdf.py:121): decides which logical series each row belongs to.
    Returns (key_ids [n_rows], key_frame [n_series x partition_cols]).
    Key order is order of first appearance (stable), so round-trips keep
    a deterministic layout.
    """
    if not partition_cols:
        key_ids = np.zeros(len(df), dtype=np.int64)
        key_frame = pd.DataFrame(index=[0])
        return key_ids, key_frame
    if len(partition_cols) == 1:
        codes, uniques = pd.factorize(df[partition_cols[0]], use_na_sentinel=False)
        key_frame = pd.DataFrame({partition_cols[0]: uniques})
        return codes.astype(np.int64), key_frame
    # tuple-key factorization via a MultiIndex
    mi = pd.MultiIndex.from_frame(df[partition_cols])
    codes, uniques = pd.factorize(mi, use_na_sentinel=False)
    key_frame = pd.DataFrame(
        [list(t) for t in uniques], columns=partition_cols
    )
    return codes.astype(np.int64), key_frame


def encode_keys_joint(
    df_left: pd.DataFrame, df_right: pd.DataFrame, partition_cols: List[str]
) -> Tuple[np.ndarray, np.ndarray, pd.DataFrame]:
    """Factorize partition keys over the *union* of two frames so both
    sides share one series-id space - the packed analog of Spark
    co-partitioning both join inputs on the same keys (tsdf.py:121)."""
    nl = len(df_left)
    if not partition_cols:
        return (
            np.zeros(nl, dtype=np.int64),
            np.zeros(len(df_right), dtype=np.int64),
            pd.DataFrame(index=[0]),
        )
    both = pd.concat(
        [df_left[partition_cols], df_right[partition_cols]], ignore_index=True
    )
    codes, key_frame = encode_keys(both, partition_cols)
    return codes[:nl], codes[nl:], key_frame


@dataclasses.dataclass
class FlatLayout:
    """Sorted flat (row-major) layout of a series collection.

    Rows are globally sorted by (key_id, ts_ns, seq) - the total order the
    reference only *promises* (tsdf.py:37-39 'ordering is promised, not
    enforced') but that every windowed op implicitly requires.  We enforce
    it once at ingest so kernels can assume sortedness.
    """

    key_ids: np.ndarray       # int64 [n]
    ts_ns: np.ndarray         # int64 [n]
    order: np.ndarray         # int64 [n]  (positions into the user's df)
    starts: np.ndarray        # int64 [K+1] row offsets per series
    key_frame: pd.DataFrame   # [K x partition_cols]

    @property
    def n_rows(self) -> int:
        return int(self.ts_ns.shape[0])

    @property
    def n_series(self) -> int:
        return int(self.starts.shape[0] - 1)

    @property
    def lengths(self) -> np.ndarray:
        return self.starts[1:] - self.starts[:-1]


def build_flat_layout(
    df: pd.DataFrame,
    ts_col: str,
    partition_cols: List[str],
    sequence_col: Optional[str] = None,
) -> FlatLayout:
    key_ids, key_frame = encode_keys(df, partition_cols)
    ts_ns = series_to_ns(df[ts_col])
    # keep integer sequence columns exact: int64 ids above 2^53 must not
    # round through float64 before the tie-break sort
    seq = pd.to_numeric(df[sequence_col]).to_numpy() if sequence_col else None
    n_series = len(key_frame)
    order, starts = _sort_layout(key_ids, ts_ns, seq, n_series)
    return FlatLayout(
        key_ids=take(key_ids, order),
        ts_ns=take(ts_ns, order),
        order=order,
        starts=starts,
        key_frame=key_frame,
    )


def _sort_layout(
    key_ids: np.ndarray,
    ts_ns: np.ndarray,
    seq: Optional[np.ndarray],
    n_series: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """(order, starts) of the (key, ts, seq) total order; dispatches to
    the C++ engine (tempo_tpu/native) when built, numpy otherwise."""
    native_ok = native.available()
    if native_ok and seq is not None:
        dt = np.asarray(seq).dtype
        if np.issubdtype(dt, np.unsignedinteger):
            # uint64 ids above 2^63 would wrap negative through the C
            # ABI's int64; keep those on the exact numpy path
            native_ok = seq.size == 0 or int(seq.max()) <= np.iinfo(np.int64).max
    if native_ok:
        return native.sort_layout(key_ids, ts_ns, seq, n_series)
    if seq is not None:
        order = np.lexsort((seq, ts_ns, key_ids))
    else:
        order = np.lexsort((ts_ns, key_ids))
    counts = np.bincount(key_ids, minlength=n_series)
    starts = np.zeros(n_series + 1, dtype=np.int64)
    np.cumsum(counts, out=starts[1:])
    return order, starts


def take(values: np.ndarray, order: np.ndarray) -> np.ndarray:
    """``values[order]`` through the multithreaded native gather when the
    engine is built and the dtype has a fixed itemsize."""
    if values.dtype != object and native.available():
        return native.take(values, order)
    return values[order]


def build_layout_from_codes(
    key_ids: np.ndarray,
    ts_ns: np.ndarray,
    seq: Optional[np.ndarray],
    n_series: int,
) -> FlatLayout:
    """Like :func:`build_flat_layout` but with externally-assigned series
    ids (joint join encodings, skew bracket composition)."""
    order, starts = _sort_layout(key_ids, ts_ns, seq, n_series)
    return FlatLayout(
        key_ids=take(key_ids, order),
        ts_ns=take(ts_ns, order),
        order=order,
        starts=starts,
        key_frame=pd.DataFrame(index=range(n_series)),
    )


def pad_length(max_len: int, multiple: int = 8) -> int:
    """Pad series length to a lane-friendly multiple (TPU sublane=8)."""
    if max_len <= 0:
        return multiple
    return int(-(-max_len // multiple) * multiple)


def pack_column(
    values: np.ndarray,
    layout: FlatLayout,
    padded_len: Optional[int] = None,
    fill=0,
) -> np.ndarray:
    """Scatter a flat (already key/ts-sorted) column into [K, L] dense form."""
    if padded_len is None:
        padded_len = pad_length(int(layout.lengths.max(initial=0)))
    if values.dtype != object and native.available():
        return native.pack(values, layout.starts, int(padded_len), fill)
    K = layout.n_series
    out = np.full((K, padded_len), fill, dtype=values.dtype)
    pos = np.arange(layout.n_rows, dtype=np.int64) - layout.starts[layout.key_ids]
    out[layout.key_ids, pos] = values
    return out


def unpack_column(packed: np.ndarray, layout: FlatLayout) -> np.ndarray:
    """Gather [K, L] padded form back into the sorted flat layout."""
    if packed.dtype != object and native.available():
        return native.unpack(packed, layout.starts)
    pos = np.arange(layout.n_rows, dtype=np.int64) - layout.starts[layout.key_ids]
    return packed[layout.key_ids, pos]


def row_mask(layout: FlatLayout, padded_len: int) -> np.ndarray:
    """Boolean [K, L] mask of real (non-padding) rows."""
    return np.arange(padded_len)[None, :] < layout.lengths[:, None]


def layout_rowbounds(layout: "FlatLayout", window_secs: float):
    """Static (max rows back, max tie rows ahead) any
    rangeBetween(-window_secs, 0) frame spans over this layout, or
    None when a per-series seconds span + window would overflow the
    int32 rebased keys the shifted/VMEM kernels compare (the pads
    clamp to INT32_MAX and the truncation audit's pad-immunity needs
    >= window of headroom above every real key).  Cached per (layout,
    window) — chained frames sharing a layout reuse the bounds.
    Shared by the host frame auto-pick (rolling.with_range_stats) and
    the mesh path (dist._window_rowbounds)."""
    cache = layout.__dict__.setdefault("_rowbound_cache", {})
    key = float(window_secs)
    if key not in cache:
        secs = layout.ts_ns // NS_PER_S
        w = np.int64(window_secs)
        behind = 0
        ahead = 0
        span_i32 = True
        for k in range(layout.n_series):
            s = secs[layout.starts[k]: layout.starts[k + 1]]
            if len(s) == 0:
                continue
            idx = np.arange(len(s))
            behind = max(
                behind,
                int((idx - np.searchsorted(s, s - w, side="left")).max()),
            )
            ahead = max(
                ahead,
                int((np.searchsorted(s, s, side="right") - 1 - idx).max()),
            )
            if int(s[-1] - s[0]) + int(w) >= 2**31 - 2:
                span_i32 = False
        cache[key] = (behind, ahead) if span_i32 else None
    return cache[key]


SID_PAD = np.int32(2**31 - 1)


@dataclasses.dataclass
class BinPackLayout:
    """Assignment of series to shared lane rows (bin packing).

    The [K, max_len] one-series-per-row layout wastes its lanes on
    Zipf-skewed key distributions (a real NBBO day is ~96% padding —
    round-2 verdict); the reference handles the same skew by dynamic
    Spark partitioning + tsPartitionVal brackets (tsdf.py:164-190).
    Here short series share lane rows back-to-back: ``row[s]`` is the
    lane row of series ``s`` and ``l_off[s]``/``r_off[s]`` its starting
    lane on the left/right side.  Within a row, series sit in ascending
    series-id order and pads only at the tail (sid = SID_PAD), the
    layout the segmented merge kernels require
    (ops/pallas_merge.py, ops/sortmerge.py:asof_merge_values_binpacked).
    """

    row: np.ndarray     # [S] int32 lane row per series
    l_off: np.ndarray   # [S] int32 starting lane, left side
    r_off: np.ndarray   # [S] int32 starting lane, right side
    n_rows: int
    l_width: int
    r_width: int

    def occupancy(self, l_lengths, r_lengths) -> float:
        return float(
            (np.sum(l_lengths) + np.sum(r_lengths))
            / (self.n_rows * (self.l_width + self.r_width))
        )


def bin_pack_series(
    l_lengths: np.ndarray,
    r_lengths: np.ndarray,
    l_width: int,
    r_width: int,
) -> BinPackLayout:
    """First-fit-decreasing packing of series into lane rows with two
    capacities (left and right side must both fit).  Series keep
    ascending id order *within* each row by a final per-row reorder.
    """
    l_lengths = np.asarray(l_lengths, np.int64)
    r_lengths = np.asarray(r_lengths, np.int64)
    S = len(l_lengths)
    if np.any(l_lengths > l_width) or np.any(r_lengths > r_width):
        raise ValueError("a series exceeds the lane-row width")
    sev = np.maximum(
        l_lengths / max(l_width, 1), r_lengths / max(r_width, 1)
    )
    order = np.argsort(-sev, kind="stable")
    l_rem: list = []
    r_rem: list = []
    row = np.zeros(S, np.int32)
    for s in order:
        placed = False
        for b in range(len(l_rem)):
            if l_rem[b] >= l_lengths[s] and r_rem[b] >= r_lengths[s]:
                row[s] = b
                l_rem[b] -= l_lengths[s]
                r_rem[b] -= r_lengths[s]
                placed = True
                break
        if not placed:
            row[s] = len(l_rem)
            l_rem.append(l_width - int(l_lengths[s]))
            r_rem.append(r_width - int(r_lengths[s]))
    # lay series out in ascending id order within each row (the
    # non-decreasing-sid contract of the segmented kernels)
    l_off = np.zeros(S, np.int32)
    r_off = np.zeros(S, np.int32)
    l_cur = np.zeros(len(l_rem), np.int64)
    r_cur = np.zeros(len(l_rem), np.int64)
    for s in range(S):
        b = row[s]
        l_off[s] = l_cur[b]
        r_off[s] = r_cur[b]
        l_cur[b] += l_lengths[s]
        r_cur[b] += r_lengths[s]
    return BinPackLayout(row=row, l_off=l_off, r_off=r_off,
                         n_rows=len(l_rem), l_width=int(l_width),
                         r_width=int(r_width))


def binpack_rows(
    src: np.ndarray,
    lengths: np.ndarray,
    row: np.ndarray,
    off: np.ndarray,
    n_rows: int,
    width: int,
    fill,
    dtype=None,
) -> np.ndarray:
    """Scatter per-series leading segments of ``src [S, Lsrc]`` into the
    bin-packed [n_rows, width] grid."""
    out = np.full((n_rows, width), fill, dtype=dtype or src.dtype)
    for s in range(len(lengths)):
        n = int(lengths[s])
        out[row[s], off[s]: off[s] + n] = src[s, :n]
    return out


def binpack_dest(starts: np.ndarray, row: np.ndarray, off: np.ndarray,
                 width: int) -> np.ndarray:
    """Flat destination slot of every row of a flat per-series-sorted
    column in the bin-packed [n_rows, width] grid — computed once and
    reused for every plane (one vectorised scatter per plane instead of
    a Python per-series loop)."""
    n = int(starts[-1])
    key_ids = np.repeat(np.arange(len(row), dtype=np.int64),
                        np.diff(starts))
    pos = np.arange(n, dtype=np.int64) - starts[key_ids]
    return row[key_ids].astype(np.int64) * width + off[key_ids] + pos


def binpack_scatter(flat: np.ndarray, dest: np.ndarray, n_rows: int,
                    width: int, fill, dtype=None) -> np.ndarray:
    """One fancy-index scatter of a flat column into the bin-packed
    grid (``dest`` from :func:`binpack_dest`)."""
    out = np.full(n_rows * width, fill, dtype=dtype or flat.dtype)
    out[dest] = flat
    return out.reshape(n_rows, width)


def binpack_rows_flat(
    flat: np.ndarray,
    starts: np.ndarray,
    row: np.ndarray,
    off: np.ndarray,
    n_rows: int,
    width: int,
    fill,
    dtype=None,
) -> np.ndarray:
    """Scatter a flat per-series-sorted column (``starts`` offsets, the
    FlatLayout form) into the bin-packed [n_rows, width] grid."""
    dest = binpack_dest(starts, row, off, width)
    return binpack_scatter(flat, dest, n_rows, width, fill, dtype)


def binpack_sid(
    lengths: np.ndarray, row: np.ndarray, off: np.ndarray,
    n_rows: int, width: int,
) -> np.ndarray:
    """The series-id plane of a bin-packed grid (SID_PAD at pad slots)."""
    out = np.full((n_rows, width), SID_PAD, np.int32)
    for s in range(len(lengths)):
        n = int(lengths[s])
        out[row[s], off[s]: off[s] + n] = s
    return out


# ----------------------------------------------------------------------
# Lane-chunked AS-OF layout (the streaming merge kernel's host planner)
# ----------------------------------------------------------------------

@dataclasses.dataclass
class AsofChunkPlan:
    """Merge-path split of packed AS-OF sides into VMEM-sized chunks.

    The streaming merge kernel (ops/pallas_merge.py chunked form) grids
    over the merged-lane axis: chunk ``c`` of a lane row holds merged
    rows [c*S, (c+1)*S) of that row's (ts [, seq], side) total order —
    the exact split points are per-row data, so the host computes them
    once (numpy searchsorted over the already-sorted packed sides, the
    same cost class as the packing itself) and scatters both sides into
    a ``[K, n_chunks * Cm]`` chunk-major layout, ``Cm = 2 * S`` lanes
    per chunk: ``[left rows (<= S, ascending) | reversed right rows
    (<= S)]`` — a bitonic sequence per chunk, like the single-plan
    layout per full row.  Greedy packing guarantees every chunk before
    a non-empty one is full, so a real slot's global merged position is
    ``c * S + lane`` (what the maxLookback horizon counts).

    ``l_dest``/``r_dest`` are lane destinations inside [K, n_chunks*Cm]
    (-1 at padding); ``l_out`` the destination inside the kernel's
    [K, n_chunks*S] output; ``r_pos`` each right row's global merged
    position (the psrc planes of the maxLookback form);
    ``chunk_pad_sid`` the per-(row, chunk) series id given to pad
    lanes so the segmented fill flows into the chunk tail and the
    cross-chunk carry can be read at the last lane (SID_PAD when the
    chunk is empty)."""

    n_chunks: int
    chunk_rows: int                 # S = real merged rows per full chunk
    merged_lanes: int               # Cm = 2 * S (power of two)
    l_dest: np.ndarray              # [K, Ll] int64, -1 pads
    r_dest: np.ndarray              # [K, Lr] int64, -1 pads
    l_out: np.ndarray               # [K, Ll] int64, -1 pads
    r_pos: np.ndarray               # [K, Lr] int64, -1 pads
    chunk_pad_sid: Optional[np.ndarray]   # [K, n_chunks] int32 or None


def _seq_merge_sides_np(l_seq, r_seq, K, Ll, Lr):
    """Numpy mirror of the kernels' ``_seq_sides`` synthesis: the None
    side rides the promoted dtype's minimum (above the -inf null-seq
    encoding, below any real value — Spark ASC NULLS FIRST + rec_ind)."""
    sdt = (l_seq if l_seq is not None else r_seq).dtype
    neg = (np.finfo(sdt).min if np.issubdtype(sdt, np.floating)
           else np.iinfo(sdt).min)
    ls = l_seq if l_seq is not None else np.full((K, Ll), neg, sdt)
    rs = r_seq if r_seq is not None else np.full((K, Lr), neg, sdt)
    pdt = np.promote_types(ls.dtype, rs.dtype)
    return ls.astype(pdt), rs.astype(pdt)


def asof_chunk_plan(
    l_ts: np.ndarray,               # [K, Ll] int64 ns, TS_PAD padded
    r_ts: np.ndarray,               # [K, Lr] int64 ns
    merged_lanes: int,              # Cm (power of two); S = Cm // 2
    l_sid: Optional[np.ndarray] = None,
    r_sid: Optional[np.ndarray] = None,
    l_seq: Optional[np.ndarray] = None,
    r_seq: Optional[np.ndarray] = None,
) -> AsofChunkPlan:
    """Split packed AS-OF sides along each row's merged stream.

    REQUIRES the packed-layout invariant (real rows lead, ascending in
    (sid?, ts, seq); TS_PAD tails).  The merged order replicated here —
    lexicographic (sid?, ts, seq, side) with right rows before left on
    full ties, stable within a side — must match the kernels' key-plane
    order exactly or chunk boundaries would disagree with the fill."""
    K, Ll = l_ts.shape
    Lr = r_ts.shape[1]
    Cm = int(merged_lanes)
    if Cm < 2 or Cm & (Cm - 1):
        raise ValueError(f"merged_lanes must be a power of two, got {Cm}")
    S = Cm // 2
    segmented = l_sid is not None
    if l_seq is not None or r_seq is not None:
        l_seq, r_seq = _seq_merge_sides_np(
            np.asarray(l_seq) if l_seq is not None else None,
            np.asarray(r_seq) if r_seq is not None else None, K, Ll, Lr)

    l_real = np.asarray(l_ts) < TS_REAL_MAX
    r_real = np.asarray(r_ts) < TS_REAL_MAX
    l_counts = l_real.sum(axis=1)
    r_counts = r_real.sum(axis=1)
    n_chunks = max(int(-(-int((l_counts + r_counts).max(initial=0)) // S)),
                   1)

    l_dest = np.full((K, Ll), -1, np.int64)
    r_dest = np.full((K, Lr), -1, np.int64)
    l_out = np.full((K, Ll), -1, np.int64)
    r_pos = np.full((K, Lr), -1, np.int64)
    pad_sid = (np.full((K, n_chunks), -1, np.int64) if segmented else None)

    for k in range(K):
        nl, nr = int(l_counts[k]), int(r_counts[k])
        n = nl + nr
        if n == 0:
            continue
        ts = np.concatenate([l_ts[k, :nl], r_ts[k, :nr]])
        side = np.concatenate([np.ones(nl, np.int8), np.zeros(nr, np.int8)])
        lex = [side]
        if l_seq is not None:
            lex.append(np.concatenate([l_seq[k, :nl], r_seq[k, :nr]]))
        lex.append(ts)
        if segmented:
            lex.append(np.concatenate([l_sid[k, :nl], r_sid[k, :nr]]))
        order = np.lexsort(tuple(lex))
        mpos = np.empty(n, np.int64)
        mpos[order] = np.arange(n, dtype=np.int64)
        l_mpos, r_mpos = mpos[:nl], mpos[nl:]

        lc = l_mpos // S
        rc = r_mpos // S
        # within-chunk per-side rank: both sides' mpos are ascending
        # (each side was sorted and the merge is stable), so the first
        # same-side row of a chunk is one searchsorted away
        l_rank = np.arange(nl) - np.searchsorted(l_mpos, lc * S)
        r_rank = np.arange(nr) - np.searchsorted(r_mpos, rc * S)
        l_dest[k, :nl] = lc * Cm + l_rank
        # the right part sits reversed at the chunk tail (the bitonic
        # [ascending | descending] precondition): ascending rank j
        # lands at offset S + (S - 1 - j)
        r_dest[k, :nr] = rc * Cm + (2 * S - 1 - r_rank)
        l_out[k, :nl] = lc * S + l_rank
        r_pos[k, :nr] = r_mpos
        if segmented:
            sid_sorted = np.concatenate(
                [l_sid[k, :nl], r_sid[k, :nr]])[order]
            np.maximum.at(pad_sid[k], np.arange(n, dtype=np.int64) // S,
                          sid_sorted.astype(np.int64))

    if segmented:
        pad_sid = np.where(pad_sid < 0, np.int64(SID_PAD),
                           pad_sid).astype(np.int32)
    return AsofChunkPlan(
        n_chunks=n_chunks, chunk_rows=S, merged_lanes=Cm,
        l_dest=l_dest, r_dest=r_dest, l_out=l_out, r_pos=r_pos,
        chunk_pad_sid=pad_sid,
    )


def chunk_scatter(src: np.ndarray, dest: np.ndarray, width: int, fill,
                  dtype=None) -> np.ndarray:
    """Scatter per-row source lanes into the [K, width] chunked layout
    (``dest`` from :func:`asof_chunk_plan`, -1 entries dropped)."""
    K = src.shape[0]
    out = np.full((K, width), fill, dtype=dtype or src.dtype)
    rows = np.broadcast_to(np.arange(K)[:, None], dest.shape)
    m = dest >= 0
    out[rows[m], dest[m]] = src[m]
    return out


def chunk_gather(plane: np.ndarray, dest: np.ndarray, fill,
                 dtype=None) -> np.ndarray:
    """Inverse of :func:`chunk_scatter` for kernel outputs: read each
    real lane's chunked destination back into the packed [K, L] form."""
    K = dest.shape[0]
    out = np.full(dest.shape, fill, dtype=dtype or plane.dtype)
    rows = np.broadcast_to(np.arange(K)[:, None], dest.shape)
    m = dest >= 0
    out[m] = plane[rows[m], dest[m]]
    return out


def unpack_ragged(
    packed: np.ndarray, lengths: np.ndarray
) -> Tuple[np.ndarray, np.ndarray]:
    """Flatten a [K, L] array with per-series valid ``lengths`` into a flat
    array plus the key_id of each row.  Used to materialise op outputs whose
    per-series row counts differ from the input (resample, interpolate)."""
    K, L = packed.shape[0], packed.shape[1]
    mask = np.arange(L)[None, :] < lengths[:, None]
    key_ids = np.repeat(np.arange(K, dtype=np.int64), lengths.astype(np.int64))
    return packed[mask], key_ids
