"""Storage-plane chaos: the kill/corrupt campaign behind bench config
17 at smoke sizes, the legacy-writer overwrite data-loss fix (never
delete the old table before its replacement exists), and the hardened
``io.writer.read`` path (corrupt row groups named, quarantined,
never an opaque traceback)."""

import os

import numpy as np
import pandas as pd
import pytest

from tempo_tpu.frame import TSDF
from tempo_tpu.io import writer
from tempo_tpu.io.ingest import CorruptRowGroupError
from tempo_tpu.testing import chaos, faults

pytestmark = pytest.mark.chaos


def mk_tsdf(n=400, seed=3, n_keys=4):
    rng = np.random.default_rng(seed)
    df = pd.DataFrame({
        "symbol": rng.choice([f"s{k}" for k in range(n_keys)], n),
        "event_ts": pd.to_datetime(
            np.sort(rng.integers(0, 10 ** 6, n)) * 1_000_000_000),
        "px": rng.standard_normal(n),
    })
    return df, TSDF(df, ts_col="event_ts", partition_cols=["symbol"])


def read_px(name, base_dir):
    return (writer.read(name, base_dir=base_dir).df
            .sort_values(["symbol", "event_ts"], kind="stable")
            .px.to_numpy())


# ----------------------------------------------------------------------
# The legacy (delta-format) overwrite: staged sibling + atomic swap
# ----------------------------------------------------------------------

class TestDeltaOverwriteSurvivesKills:
    """Satellite proof of the data-loss fix: the seed-era write()
    rmtree'd the live table before writing its replacement — a kill in
    the window lost BOTH tables.  Now a kill at every point of the
    staged swap leaves the old table readable."""

    def _seed_table(self, tmp_path):
        df1, t1 = mk_tsdf(seed=1)
        writer.write(t1, "tab", base_dir=str(tmp_path), format="delta")
        old = read_px("tab", str(tmp_path))
        _, t2 = mk_tsdf(seed=2)
        return old, t2

    def test_kill_mid_build_keeps_old_table(self, tmp_path):
        old, t2 = self._seed_table(tmp_path)
        with pytest.raises(faults.SimulatedKill):
            with faults.FaultInjector().kill_on_call(
                    writer, "_write_delta", call_no=1):
                writer.write(t2, "tab", base_dir=str(tmp_path),
                             format="delta")
        np.testing.assert_array_equal(read_px("tab", str(tmp_path)),
                                      old)
        # no staging residue poisons the NEXT write
        writer.write(t2, "tab", base_dir=str(tmp_path), format="delta")

    def test_kill_mid_fsync_keeps_old_table(self, tmp_path):
        old, t2 = self._seed_table(tmp_path)
        with pytest.raises(faults.SimulatedKill):
            with faults.FaultInjector().kill_on_call(
                    writer, "_fsync_tree", call_no=1):
                writer.write(t2, "tab", base_dir=str(tmp_path),
                             format="delta")
        np.testing.assert_array_equal(read_px("tab", str(tmp_path)),
                                      old)

    def test_kill_between_swap_renames_reads_bak(self, tmp_path):
        # the worst window: old table already moved to .bak, staged
        # table not yet live — read() finds the .bak survivor
        old, t2 = self._seed_table(tmp_path)
        with pytest.raises(faults.SimulatedKill):
            with faults.FaultInjector().kill_on_call(
                    writer.os, "replace", call_no=2):
                writer.write(t2, "tab", base_dir=str(tmp_path),
                             format="delta")
        assert not os.path.isdir(os.path.join(str(tmp_path), "tab"))
        assert os.path.isdir(os.path.join(str(tmp_path), "tab.bak"))
        np.testing.assert_array_equal(read_px("tab", str(tmp_path)),
                                      old)
        # the re-issued write completes and clears the .bak
        writer.write(t2, "tab", base_dir=str(tmp_path), format="delta")
        assert not os.path.isdir(os.path.join(str(tmp_path), "tab.bak"))


# ----------------------------------------------------------------------
# writer.read through the hardened ingest path
# ----------------------------------------------------------------------

def _corrupt_one_committed_segment(tmp_path):
    from tempo_tpu.store import engine as se

    df, tsdf = mk_tsdf(n=600)
    writer.write(tsdf, "tab", base_dir=str(tmp_path))
    store = se.Store(str(tmp_path))
    gen_dir = store.dataset_path("tab")
    segs = sorted(p for p in os.listdir(gen_dir)
                  if p.endswith(".parquet"))
    # writer.write clusters with the default segment size -> force a
    # multi-segment table first if needed
    if len(segs) < 2:
        store.write_table("tab", store.read("tab"),
                          ["symbol", "event_time"],
                          source_fp="resegment", segment_rows=150)
        gen_dir = store.dataset_path("tab")
        segs = sorted(p for p in os.listdir(gen_dir)
                      if p.endswith(".parquet"))
    assert len(segs) >= 2
    rec = faults.corrupt_parquet_row_group(
        os.path.join(gen_dir, segs[0]))
    return df, rec


def test_read_names_corrupt_row_group(tmp_path):
    _, rec = _corrupt_one_committed_segment(tmp_path)
    with pytest.raises(CorruptRowGroupError) as ei:
        writer.read("tab", base_dir=str(tmp_path))
    msg = str(ei.value)
    assert os.path.basename(rec["file"]) in msg
    assert f"[rg {rec['row_group']}]" in msg
    assert ei.value.ranges          # exact ranges ride the exception


def test_read_quarantine_reads_around_corruption(tmp_path):
    df, rec = _corrupt_one_committed_segment(tmp_path)
    out = writer.read("tab", base_dir=str(tmp_path),
                      on_corrupt="quarantine")
    # every surviving row is bitwise one of the source rows, and
    # exactly the quarantined row-group's rows are missing
    assert len(out.df) == len(df) - rec["rows"]
    merged = out.df.merge(
        df.drop_duplicates(), on=["symbol", "event_ts", "px"],
        how="left", indicator=True)
    assert (merged["_merge"] == "both").all()


def test_store_errors_classify_for_retry_policy(tmp_path):
    from tempo_tpu import resilience
    from tempo_tpu.resilience import FailureKind
    from tempo_tpu.store import engine as se

    _, tsdf = mk_tsdf()
    writer.write(tsdf, "tab", base_dir=str(tmp_path))
    cpath = os.path.join(str(tmp_path), "tab", se.CURRENT_NAME)
    blob = open(cpath, "rb").read()
    open(cpath, "wb").write(blob[: len(blob) // 2])
    with pytest.raises(se.StoreCommitError) as ei:
        writer.read("tab", base_dir=str(tmp_path))
    # a torn commit/pointer is NEVER transient: retrying re-reads the
    # same bad bytes
    assert resilience.classify(ei.value) is \
        FailureKind.CORRUPTED_ARTIFACT


# ----------------------------------------------------------------------
# The campaign smoke (bench config 17's body at tiny sizes)
# ----------------------------------------------------------------------

def test_store_campaign_smoke(tmp_path):
    rep = chaos.run_store_campaign(
        str(tmp_path), rows=4_000, n_keys=6, seed=31,
        segment_rows=600, n_streams=10, resident_budget=3,
        events_per_stream=6)
    wr = rep["write_resume"]
    assert wr["segments_rewritten_committed"] == 0
    assert wr["pointer_swing_resume_segment_writes"] == 0
    assert "bitwise" in wr["value_audit"]
    assert all(rep["refusals_by_name"].values())
    assert rep["legacy_overwrite"]["old_table_lost"] is False
    assert rep["compaction"]["killed_mid_merge"] is True
    assert "bitwise" in rep["compaction"]["reader_on_old_generation"]
    cs = rep["cohort_spill"]
    assert cs["spills"] >= 1 and cs["restores"] >= 1
    assert "bitwise" in cs["value_audit"]
    assert rep["no_silent_restores"] is True
