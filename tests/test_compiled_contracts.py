"""Fixture tests for the compiled-contract analyzer tier
(tools/analysis/compiled/, ``python tools/analyze.py --compiled``):
every rule fires on a deliberately broken compiled artifact (an f64
literal in a jitted body, a dropped donation, a stage-boundary
sharding mismatch, an unmodeled collective, a host callback), passes a
known-good twin, and is silenced by a ``# lint-ok: <rule>: <reason>``
marker at the builder's ``@register`` site — mirroring the AST tier's
fixture pattern one level up the stack (test_analysis.py).  The live
gate at the bottom keeps the production-program registry
(tempo_tpu/plan/contracts.py) analyzer-clean at HEAD."""

import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # direct invocation outside pytest rootdir
    sys.path.insert(0, str(REPO))

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, NamedSharding  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import tempo_tpu  # noqa: E402,F401  (x64 + platform config)
from tempo_tpu import profiling  # noqa: E402
from tempo_tpu.plan import contracts  # noqa: E402
from tempo_tpu.plan.contracts import (  # noqa: E402
    Chain,
    CompiledProgram,
    Contract,
    Link,
)
from tools.analysis.compiled import COMPILED_RULES  # noqa: E402
from tools.analysis.compiled.core import (  # noqa: E402
    BUILD_ERROR_CODE,
    run_compiled,
)
from tools.analysis.compiled.rules import (  # noqa: E402
    CollectiveInventoryRule,
    DonationAppliedRule,
    NoF64LeakRule,
    NoHostTransferRule,
    RecompileCoverageRule,
    StageShardingMatchRule,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh")


def _compile(fn, *args, **jit_kw):
    return jax.jit(fn, **jit_kw).lower(*args).compile()


def _program(fn, *args, name="fixture", contract=None, **jit_kw):
    return CompiledProgram(name, _compile(fn, *args, **jit_kw),
                           contract or Contract())


def _mesh():
    return Mesh(np.asarray(jax.devices()[:8]), ("d",))


def _codes(findings, exit_code, rule):
    """Assert exactly this one rule family fired, with its bit."""
    assert exit_code == rule.code, (exit_code, [f.render() for f in findings])
    assert findings and all(f.rule == rule.name for f in findings)


# ----------------------------------------------------------------------
# no-f64-leak (exit 1)
# ----------------------------------------------------------------------

def test_f64_leak_fires_on_f64_literal_array():
    """The broken fixture of the acceptance list: a non-scalar f64
    literal in a jitted body (the weak-float class that broke 22
    interpret tests) must fail with exit bit 1."""
    p = _program(lambda x: x + jnp.asarray([1.0, 2.0], jnp.float64).sum(),
                 np.ones(2, np.float32))
    findings, code = run_compiled([NoF64LeakRule()], [p], [], {})
    _codes(findings, code, NoF64LeakRule())
    assert "f64" in findings[0].message


def test_f64_leak_passes_f32_program():
    """An f32-only artifact passes — weak python scalars stay in the
    operand dtype (the rule also tolerates folded scalar ``f64[]``
    constants by regex design; only f64 ARRAYS mean real f64 compute)."""
    p = _program(lambda x: x * 2.0 + 1.0, np.ones(4, np.float32))
    findings, code = run_compiled([NoF64LeakRule()], [p], [], {})
    assert findings == [] and code == 0


def test_f64_leak_allow_f64_contract():
    """Golden/f64-policy programs declare allow_f64 and are exempt."""
    p = _program(lambda x: x + jnp.asarray([1.0], jnp.float64).sum(),
                 np.ones(2, np.float32),
                 contract=Contract(allow_f64=True))
    findings, code = run_compiled([NoF64LeakRule()], [p], [], {})
    assert findings == [] and code == 0


# ----------------------------------------------------------------------
# no-host-transfer (exit 2)
# ----------------------------------------------------------------------

def _callback_fn(x):
    y = jax.pure_callback(lambda a: np.asarray(a),
                          jax.ShapeDtypeStruct(x.shape, x.dtype), x)
    return y + 1


def test_host_transfer_fires_on_python_callback():
    p = _program(_callback_fn, np.ones(4, np.float32))
    findings, code = run_compiled([NoHostTransferRule()], [p], [], {})
    _codes(findings, code, NoHostTransferRule())
    assert "host-transfer" in findings[0].message


def test_host_transfer_pass_and_declared_barrier():
    clean = _program(lambda x: x + 1, np.ones(4, np.float32))
    findings, code = run_compiled([NoHostTransferRule()], [clean], [], {})
    assert findings == [] and code == 0
    declared = _program(
        _callback_fn, np.ones(4, np.float32),
        contract=Contract(host_transfer_ok="fourier host fallback "
                                           "(materialization barrier)"))
    findings, code = run_compiled([NoHostTransferRule()], [declared],
                                  [], {})
    assert findings == [] and code == 0


# ----------------------------------------------------------------------
# collective-inventory (exit 4)
# ----------------------------------------------------------------------

def _gather_program(contract):
    from tempo_tpu.parallel.halo import shard_map

    mesh = _mesh()
    fn = shard_map(lambda x: jax.lax.all_gather(x, "d", tiled=True),
                   mesh=mesh, in_specs=(P("d"),), out_specs=P(None))
    x = jax.device_put(np.ones((8, 16), np.float32),
                       NamedSharding(mesh, P("d")))
    c = _compile(fn, x)
    return CompiledProgram("fixture.gather", c, contract), c


def test_collective_unmodeled_kind_fires():
    """The acceptance list's unmodeled collective: an all-gather the
    contract neither models nor declares incidental, exit bit 4."""
    p, _ = _gather_program(Contract())
    findings, code = run_compiled([CollectiveInventoryRule()], [p], [], {})
    _codes(findings, code, CollectiveInventoryRule())
    assert "UNMODELED" in findings[0].message


def test_collective_model_match_passes_and_bounds_fire():
    p, c = _gather_program(Contract())
    measured = profiling.comm_bytes_from_compiled(c)["all-gather"]
    assert measured > 0

    exact = CompiledProgram(
        "fixture.gather", c, Contract(collectives={"all-gather": measured}))
    findings, code = run_compiled([CollectiveInventoryRule()], [exact],
                                  [], {})
    assert findings == [] and code == 0

    # modeled at half the real bytes: measured = 2x model > 1.25x tol
    low = CompiledProgram(
        "fixture.gather", c,
        Contract(collectives={"all-gather": measured // 2}))
    findings, code = run_compiled([CollectiveInventoryRule()], [low], [], {})
    _codes(findings, code, CollectiveInventoryRule())
    assert "outside" in findings[0].message

    # a per-kind tolerance override in the contract widens the bound
    wide = CompiledProgram(
        "fixture.gather", c,
        Contract(collectives={"all-gather": measured // 2},
                 tolerances={"all-gather": 4.0}))
    findings, code = run_compiled([CollectiveInventoryRule()], [wide],
                                  [], {})
    assert findings == [] and code == 0


def test_collective_declared_kind_absent_fires():
    """A modeled kind missing from the HLO means the comm the model
    budgets for no longer happens — also a finding."""
    _, c = _gather_program(Contract())
    measured = profiling.comm_bytes_from_compiled(c)["all-gather"]
    p = CompiledProgram(
        "fixture.gather", c,
        Contract(collectives={"all-to-all": 1024,
                              "all-gather": measured}))
    findings, code = run_compiled([CollectiveInventoryRule()], [p], [], {})
    _codes(findings, code, CollectiveInventoryRule())
    assert "ABSENT" in findings[0].message


def test_collective_incidental_ceiling():
    p, c = _gather_program(Contract())
    measured = profiling.comm_bytes_from_compiled(c)["all-gather"]
    under = CompiledProgram(
        "fixture.gather", c,
        Contract(incidental={"all-gather": measured}))
    findings, code = run_compiled([CollectiveInventoryRule()], [under],
                                  [], {})
    assert findings == [] and code == 0
    over = CompiledProgram(
        "fixture.gather", c,
        Contract(incidental={"all-gather": measured - 1}))
    findings, code = run_compiled([CollectiveInventoryRule()], [over],
                                  [], {})
    _codes(findings, code, CollectiveInventoryRule())
    assert "ceiling" in findings[0].message


# ----------------------------------------------------------------------
# donation-applied (exit 8)
# ----------------------------------------------------------------------

def test_donation_dropped_fires():
    """The acceptance list's dropped donation: the contract declares
    donate_argnums the executable does not alias, exit bit 8."""
    p = _program(lambda x: x + 1, np.ones((8, 8), np.float32),
                 name="fixture.donate",
                 contract=Contract(donate_argnums=(0,)))
    findings, code = run_compiled([DonationAppliedRule()], [p], [], {})
    _codes(findings, code, DonationAppliedRule())
    assert "NOT" in findings[0].message


def test_donation_applied_passes():
    p = _program(lambda x: x + 1, np.ones((8, 8), np.float32),
                 name="fixture.donate",
                 contract=Contract(donate_argnums=(0,)),
                 donate_argnums=(0,))
    findings, code = run_compiled([DonationAppliedRule()], [p], [], {})
    assert findings == [] and code == 0


def test_donation_undeclared_alias_fires():
    """The drift's other direction: the jit donates but the contract
    does not know — both must read one source of truth."""
    p = _program(lambda x: x + 1, np.ones((8, 8), np.float32),
                 name="fixture.donate", donate_argnums=(0,))
    findings, code = run_compiled([DonationAppliedRule()], [p], [], {})
    _codes(findings, code, DonationAppliedRule())
    assert "not declare" in findings[0].message


# ----------------------------------------------------------------------
# stage-sharding-match (exit 16)
# ----------------------------------------------------------------------

def _stage(fn, x, out_spec, mesh, name):
    sharding = NamedSharding(mesh, out_spec)
    c = _compile(fn, x, out_shardings=sharding)
    return CompiledProgram(name, c, Contract())


def _sharded_input(mesh, spec, shape=(8, 16)):
    return jax.device_put(np.ones(shape, np.float32),
                          NamedSharding(mesh, spec))


def test_stage_sharding_match_passes():
    mesh = _mesh()
    x = _sharded_input(mesh, P("d"))
    prod = _stage(lambda a: a * 2, x, P("d"), mesh, "stage.a")
    cons = _stage(lambda a: a + 1, x, P("d"), mesh, "stage.b")
    chain = Chain("fixture.chain", (Link("stage.a", 0, "stage.b", 0),))
    findings, code = run_compiled([StageShardingMatchRule()],
                                  [prod, cons], [chain], {})
    assert findings == [] and code == 0


def test_stage_sharding_mismatch_fires():
    """The acceptance list's stage-boundary sharding mismatch: the
    producer writes P('d') rows, the consumer expects replicated —
    chaining would insert an implicit reshard; exit bit 16."""
    mesh = _mesh()
    x_sh = _sharded_input(mesh, P("d"))
    x_rep = _sharded_input(mesh, P(None))
    prod = _stage(lambda a: a * 2, x_sh, P("d"), mesh, "stage.a")
    cons = _stage(lambda a: a + 1, x_rep, P(None), mesh, "stage.b")
    chain = Chain("fixture.chain", (Link("stage.a", 0, "stage.b", 0),))
    findings, code = run_compiled([StageShardingMatchRule()],
                                  [prod, cons], [chain], {})
    _codes(findings, code, StageShardingMatchRule())
    assert "mismatch" in findings[0].message


def test_stage_sharding_sharded_dropped_axis_fires():
    """drop_leading axes must be unsharded: host-slicing a sharded
    leading axis changes device ownership in flight."""
    mesh = _mesh()
    x = _sharded_input(mesh, P("d", None))
    prod = _stage(lambda a: a * 2, x, P("d", None), mesh, "stage.a")
    y = _sharded_input(mesh, P(None), shape=(16,))
    cons = _stage(lambda a: a + 1, y, P(None), mesh, "stage.b")
    chain = Chain("fixture.chain",
                  (Link("stage.a", 0, "stage.b", 0, drop_leading=1),))
    findings, code = run_compiled([StageShardingMatchRule()],
                                  [prod, cons], [chain], {})
    _codes(findings, code, StageShardingMatchRule())
    assert "SHARDED" in findings[0].message


def test_stage_sharding_finding_suppressible_at_chain_site(tmp_path):
    """Chains carry the declaring builder's source site, so a known
    stage-boundary mismatch can be waived with the standard marker
    while a reshard change lands."""
    mesh = _mesh()
    x_sh = _sharded_input(mesh, P("d"))
    x_rep = _sharded_input(mesh, P(None))
    prod = _stage(lambda a: a * 2, x_sh, P("d"), mesh, "stage.a")
    cons = _stage(lambda a: a + 1, x_rep, P(None), mesh, "stage.b")
    chain = Chain("fixture.chain", (Link("stage.a", 0, "stage.b", 0),))
    src = tmp_path / "builders.py"
    src.write_text(
        "# lint-ok: stage-sharding-match: reshard lands next round\n"
        "@register('fixture.chain')\n"
        "def _build():\n"
        "    ...\n")
    chain.source_file, chain.source_line = str(src), 3
    findings, code = run_compiled([StageShardingMatchRule()],
                                  [prod, cons], [chain], {})
    assert findings == [] and code == 0


def test_stage_sharding_bad_link_indices_fire():
    mesh = _mesh()
    x = _sharded_input(mesh, P("d"))
    prod = _stage(lambda a: a * 2, x, P("d"), mesh, "stage.a")
    cons = _stage(lambda a: a + 1, x, P("d"), mesh, "stage.b")
    chain = Chain("fixture.chain", (
        Link("stage.a", 3, "stage.b", 0),
        Link("stage.a", 0, "stage.gone", 0),
    ))
    findings, code = run_compiled([StageShardingMatchRule()],
                                  [prod, cons], [chain], {})
    assert code == StageShardingMatchRule().code
    msgs = " | ".join(f.message for f in findings)
    assert "out of range" in msgs and "did not build" in msgs


# ----------------------------------------------------------------------
# recompile-coverage (exit 32)
# ----------------------------------------------------------------------

class _FakeFrame:
    def _plan_record(self, op, others=(), params=None, objs=None):
        return self

    def covered(self, colName, window):
        return self._plan_record("covered", (),
                                 dict(colName=colName, window=window))

    def leaky(self, colName, window):
        # 'window' feeds the computation but NOT the plan node: two
        # calls differing only in window share a plan signature
        return self._plan_record("leaky", (), dict(colName=colName))

    def waived(self, colName, window):  # lint-ok: recompile-coverage: fixture
        return self._plan_record("waived", (), dict(colName=colName))


def test_recompile_coverage_fires_on_unrecorded_param():
    rule = RecompileCoverageRule()
    found = rule._check_method("TSDF", _FakeFrame, "leaky")
    assert found is not None and "window" in found.message
    assert rule.code == 32


def test_recompile_coverage_passes_recorded_params():
    rule = RecompileCoverageRule()
    assert rule._check_method("TSDF", _FakeFrame, "covered") is None


def test_recompile_coverage_suppressible_at_method_def():
    """Registry-level findings anchor to the planned METHOD's def
    line, so the standard same-site marker suppresses them."""
    rule = RecompileCoverageRule()
    assert rule._check_method("TSDF", _FakeFrame, "waived") is None


def test_recompile_coverage_live_registry_clean():
    """Every PLANNED_METHODS op method at HEAD records all its
    parameters — cache hits can never replay a stale executable."""
    rule = RecompileCoverageRule()
    found = rule.check_registry(REPO)
    assert found == [], "\n".join(f.render() for f in found)


# ----------------------------------------------------------------------
# engine: suppression, build-error, exit-bit OR
# ----------------------------------------------------------------------

def test_lint_ok_at_register_site_suppresses(tmp_path):
    """A ``# lint-ok: <rule>: <reason>`` comment at the builder's
    @register site silences that rule for that program — the AST
    tier's convention, anchored where the program is declared."""
    p = _program(lambda x: x + jnp.asarray([1.0], jnp.float64).sum(),
                 np.ones(2, np.float32), name="fixture.suppressed")
    src = tmp_path / "builders.py"
    src.write_text(
        "# lint-ok: no-f64-leak: golden-parity artifact, f64 by design\n"
        "@register('fixture.suppressed')\n"
        "def _build():\n"
        "    ...\n")
    p.source_file, p.source_line = str(src), 3
    findings, code = run_compiled([NoF64LeakRule()], [p], [], {})
    assert findings == [] and code == 0


def test_build_error_exit_bit():
    """A registry entry that fails to build reports as build-error
    (exit 64) instead of crashing the run."""
    findings, code = run_compiled(
        list(COMPILED_RULES), [], [],
        {"fixture.broken": "ValueError: boom"})
    assert code == BUILD_ERROR_CODE
    assert findings[0].rule == "build-error"
    assert "boom" in findings[0].message


def test_build_all_collects_builder_exceptions(monkeypatch):
    monkeypatch.setenv("TEMPO_TPU_COMPUTE_DTYPE", "float32")
    monkeypatch.setenv("TEMPO_TPU_SORT_KERNELS", "1")

    @contracts.register("fixture.raises")
    def _build():
        raise ValueError("shape mismatch")

    try:
        programs, chains, skipped, errors = contracts.build_all(
            only=["fixture.raises"])
        assert programs == [] and chains == []
        assert "ValueError: shape mismatch" in errors["fixture.raises"]
    finally:
        contracts._BUILDERS.pop("fixture.raises")
        contracts._BUILDER_META.pop("fixture.raises")


def test_exit_bits_or_across_rules():
    """Distinct power-of-two bits OR, mirroring the AST tier."""
    p = _program(lambda x: x + jnp.asarray([1.0], jnp.float64).sum(),
                 np.ones(2, np.float32), name="fixture.both",
                 contract=Contract(donate_argnums=(0,)))
    findings, code = run_compiled(
        [NoF64LeakRule(), DonationAppliedRule()], [p], [], {})
    assert code == NoF64LeakRule().code | DonationAppliedRule().code
    assert {f.rule for f in findings} == {"no-f64-leak",
                                          "donation-applied"}


def test_rule_bits_are_distinct_powers_of_two():
    codes = [r.code for r in COMPILED_RULES] + [BUILD_ERROR_CODE]
    assert len(set(codes)) == len(codes)
    for c in codes:
        assert c > 0 and (c & (c - 1)) == 0


# ----------------------------------------------------------------------
# live gate: the production registry is analyzer-clean at HEAD
# ----------------------------------------------------------------------

def test_compiled_tier_clean_at_head():
    """``python tools/analyze.py --compiled`` over the full
    production-program registry exits 0 — the compiled twin of the
    AST tier's analyzer-clean-at-HEAD gate.  Subprocess: the tier
    pins TEMPO_TPU_COMPUTE_DTYPE/SORT_KERNELS before jax wakes up,
    which an in-process check cannot re-arrange."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "analyze.py"), "--compiled"],
        capture_output=True, text=True, timeout=580)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "compiled contracts clean" in proc.stderr


def test_env_precondition_failure_is_usage_error():
    """A misconfigured environment (the f64 golden-parity knob left
    exported) exits 2 with a message — not a traceback whose exit 1
    reads as the no-f64-leak bit to CI."""
    import os

    env = dict(os.environ, TEMPO_TPU_COMPUTE_DTYPE="float64")
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "analyze.py"), "--compiled"],
        capture_output=True, text=True, timeout=300, env=env)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "compiled tier cannot run" in proc.stderr


def test_unknown_compiled_rule_is_usage_error_not_build_error():
    """A typo'd --rule under --compiled exits 2 (argparse's usage
    status), NOT the build-error bit 64 — the documented bit table
    must stay honest for CI scripts keying off it."""
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "analyze.py"),
         "--compiled", "--rule", "no-such-rule"],
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 2, proc.stdout + proc.stderr
    assert "unknown compiled rule" in proc.stderr


def test_contract_docs_rule_table_agrees():
    """BUILDING.md's compiled-rule table names every rule with its
    exit bit (the three-way style of the env-knobs rule)."""
    text = (REPO / "BUILDING.md").read_text()
    for rule in COMPILED_RULES:
        assert rule.name in text, f"BUILDING.md missing {rule.name}"
    assert "build-error" in text
