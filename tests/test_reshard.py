"""Resharding between series- and time-parallel layouts (8-dev CPU mesh)."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import pytest

from tempo_tpu.parallel import make_mesh
from tempo_tpu.parallel import (
    reshard,
    all_to_all_series_to_time,
    all_to_all_time_to_series,
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"series": 4, "time": 2})


def _arr(K=8, L=16):
    return jnp.asarray(
        np.arange(K * L, dtype=np.float32).reshape(K, L)
    )


def test_declarative_reshard_preserves_values(mesh):
    x = jax.device_put(_arr(), NamedSharding(mesh, P("series", "time")))
    y = reshard(x, mesh, P(None, "time"))
    assert y.sharding.spec == P(None, "time")
    np.testing.assert_array_equal(np.asarray(y), np.asarray(x))


def test_all_to_all_round_trip(mesh):
    x = jax.device_put(_arr(), NamedSharding(mesh, P("series", "time")))
    full_rows = all_to_all_series_to_time(x, mesh)
    # every device now holds complete rows for its series block
    assert full_rows.shape == x.shape
    np.testing.assert_array_equal(np.asarray(full_rows), np.asarray(x))
    shard_shapes = {s.data.shape for s in full_rows.addressable_shards}
    assert shard_shapes == {(1, 16)}   # K/(4*2) x full L

    back = all_to_all_time_to_series(full_rows, mesh)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))
    shard_shapes = {s.data.shape for s in back.addressable_shards}
    assert shard_shapes == {(2, 8)}    # K/4 x L/2


def test_time_layout_feeds_series_op(mesh):
    """A time-sharded stage can hand full rows to a per-series reduction
    without a host round-trip."""
    x = jax.device_put(_arr(), NamedSharding(mesh, P("series", "time")))
    rows = all_to_all_series_to_time(x, mesh)
    per_series_sum = jnp.sum(rows, axis=1)   # needs whole rows
    np.testing.assert_allclose(
        np.asarray(per_series_sum), np.asarray(x).sum(axis=1), rtol=1e-6
    )
