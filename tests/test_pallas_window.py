"""Streaming sliding-window engine: interpret-mode property tests.

Pins the ops/pallas_window.py kernels (streaming fori-loop form AND the
statically-unrolled twin) against

* the XLA shifted form (itself oracle-tested in test_rolling /
  test_pallas_stats), across window sizes spanning every auto-pick
  crossover: tiny (shifted regime), at the unroll ceiling, and far
  beyond it (streaming-only regime);
* a brute-force per-row numpy float64 oracle, including range windows
  whose bounds land BETWEEN timestamps, ragged series tails (i32-max
  clamped pads), NaN-masked rows, and tie runs;
* each other (the two forms must agree exactly — same math, different
  loop structure).

Also covers the three-way auto-pick (ops/rolling.pick_range_engine)
and the streaming dispatcher's CPU fallback.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from tempo_tpu.ops import pallas_window as pw
from tempo_tpu.ops import rolling as rk
from tempo_tpu.ops import sortmerge as sm

KEYS = ("mean", "count", "min", "max", "sum", "stddev", "zscore",
        "clipped")


def _case(seed, K=4, L=256, span=600, pads=True, invalids=True):
    rng = np.random.default_rng(seed)
    secs = np.sort(rng.integers(0, span, (K, L)), axis=-1).astype(np.int64)
    x = rng.standard_normal((K, L)).astype(np.float32)
    valid = (rng.random((K, L)) > 0.25) if invalids else np.ones((K, L), bool)
    if invalids and K > 1:
        valid[1] = False                      # a fully-null series
    if pads:
        cut = rng.integers(L // 2, L, K)
        for k in range(K):
            secs[k, cut[k]:] = 2**31 - 1
            valid[k, cut[k]:] = False
    return secs.astype(np.int32), x, valid


def _assert_close(got, want, err=""):
    for k in KEYS:
        np.testing.assert_allclose(
            np.asarray(got[k], dtype=np.float64),
            np.asarray(want[k], dtype=np.float64),
            rtol=2e-5, atol=2e-5, equal_nan=True, err_msg=f"{err}:{k}",
        )


# window sizes spanning the shifted (<= shifted_row_budget), unrolled
# (<= UNROLL_MAX_W) and streaming-only (beyond) regimes; `span` tunes
# the resulting row extents
@pytest.mark.parametrize("seed,span,W,behind,ahead", [
    (0, 600, 25, 24, 12),        # shifted regime, ties + pads
    (1, 40, 25, 64, 32),         # heavy ties, at the unroll ceiling
    (2, 600, 120, 100, 8),       # past the unroll ceiling
    (3, 200, 180, 250, 16),      # streaming-only: W ~ L
])
def test_stream_matches_xla_shifted(seed, span, W, behind, ahead):
    secs, x, valid = _case(seed, span=span)
    args = (jnp.asarray(secs), jnp.asarray(x), jnp.asarray(valid),
            jnp.asarray(np.int32(W)))
    want = sm._range_stats_shifted_xla(
        *args, max_behind=behind, max_ahead=ahead)
    got = pw.range_stats_stream(
        *args, max_behind=behind, max_ahead=ahead, interpret=True)
    _assert_close(got, want, f"stream W={W}")
    if behind + ahead <= pw.UNROLL_MAX_W:
        got_u = pw.range_stats_unrolled(
            *args, max_behind=behind, max_ahead=ahead, interpret=True)
        _assert_close(got_u, want, f"unrolled W={W}")
        # the two forms are the same math: exact agreement
        for k in KEYS:
            np.testing.assert_array_equal(
                np.asarray(got[k]), np.asarray(got_u[k]), err_msg=k)


def test_numpy_oracle_window_between_timestamps():
    """Keys stride 5, window 7: every frame boundary lands strictly
    between timestamps; brute-force f64 oracle per row."""
    K, L = 3, 128
    rng = np.random.default_rng(9)
    secs = (np.arange(L, dtype=np.int64) * 5)[None].repeat(K, 0)
    x = rng.standard_normal((K, L)).astype(np.float32)
    valid = rng.random((K, L)) > 0.2
    W = 7
    got = pw.range_stats_stream(
        jnp.asarray(secs.astype(np.int32)), jnp.asarray(x),
        jnp.asarray(valid), jnp.asarray(np.int32(W)),
        max_behind=4, max_ahead=2, interpret=True)
    x64 = x.astype(np.float64)
    for k in range(K):
        for i in range(L):
            lo, hi = secs[k, i] - W, secs[k, i]
            inw = (secs[k] >= lo) & (secs[k] <= hi) & valid[k]
            win = x64[k, inw]
            assert float(got["count"][k, i]) == len(win), (k, i)
            if len(win):
                np.testing.assert_allclose(
                    float(got["min"][k, i]), win.min(), rtol=1e-5)
                np.testing.assert_allclose(
                    float(got["max"][k, i]), win.max(), rtol=1e-5)
                np.testing.assert_allclose(
                    float(got["mean"][k, i]), win.mean(),
                    rtol=1e-4, atol=1e-5)
            else:
                assert np.isnan(float(got["mean"][k, i]))


def test_rows_mode_matches_bruteforce():
    K, L = 3, 128
    rng = np.random.default_rng(11)
    x = rng.standard_normal((K, L)).astype(np.float32)
    valid = rng.random((K, L)) > 0.25
    rb, ra = 6, 3
    got = pw.rows_stats_stream(jnp.asarray(x), jnp.asarray(valid),
                               rb, ra, interpret=True)
    x64 = x.astype(np.float64)
    for k in range(K):
        for i in range(L):
            s, e = max(0, i - rb), min(L, i + ra + 1)
            win = x64[k, s:e][valid[k, s:e]]
            assert float(got["count"][k, i]) == len(win), (k, i)
            if len(win) > 1:
                np.testing.assert_allclose(
                    float(got["stddev"][k, i]), win.std(ddof=1),
                    rtol=1e-4, atol=1e-5)
    assert float(np.asarray(got["clipped"]).sum()) == 0


def test_scale_folds_into_kernel():
    secs, x, valid = _case(5)
    args = (jnp.asarray(secs), jnp.asarray(x), jnp.asarray(valid),
            jnp.asarray(np.int32(30)))
    want = sm._range_stats_shifted_xla(
        args[0], jnp.asarray(x * np.float32(2.5)), args[2], args[3],
        max_behind=20, max_ahead=8)
    for fn in (pw.range_stats_stream, pw.range_stats_unrolled):
        got = fn(*args, max_behind=20, max_ahead=8, scale=2.5,
                 interpret=True)
        _assert_close(got, want, fn.__name__)


def test_clipped_audit_parity_when_truncating():
    secs, x, valid = _case(6)
    args = (jnp.asarray(secs), jnp.asarray(x), jnp.asarray(valid),
            jnp.asarray(np.int32(50)))
    want = sm._range_stats_shifted_xla(*args, max_behind=3, max_ahead=0)
    assert float(np.asarray(want["clipped"]).sum()) > 0
    for fn in (pw.range_stats_stream, pw.range_stats_unrolled):
        got = fn(*args, max_behind=3, max_ahead=0, interpret=True)
        np.testing.assert_allclose(
            np.asarray(got["clipped"]), np.asarray(want["clipped"]),
            err_msg=fn.__name__)


def test_pick_range_engine_three_way(monkeypatch):
    monkeypatch.delenv("TEMPO_TPU_WINDOW_ENGINE", raising=False)
    n = 1024 * 8192
    # small extent -> shifted; past the budget with a feasible stream
    # block -> stream; past the stream ceiling (or no stream) -> windowed
    assert rk.pick_range_engine(n, 10, 2, True, True) == "shifted"
    assert rk.pick_range_engine(n, 500, 8, True, True) == "stream"
    assert rk.pick_range_engine(n, 500, 8, True, False) == "windowed"
    big = pw._stream_max_rows() + 1
    assert rk.pick_range_engine(n, big, 0, True, True) == "windowed"
    monkeypatch.setenv("TEMPO_TPU_WINDOW_ENGINE", "stream")
    assert rk.pick_range_engine(n, 10, 2, True, True) == "stream"
    monkeypatch.setenv("TEMPO_TPU_WINDOW_ENGINE", "legacy")
    # legacy only redirects the shifted path's kernel choice
    assert rk.pick_range_engine(n, 10, 2, True, True) == "shifted"


def test_streaming_dispatcher_cpu_fallback():
    """Off-TPU the dispatcher must produce the same numbers through the
    windowed form, including a zero clipped plane."""
    secs, x, valid = _case(7, pads=False)
    W, behind, ahead = 40, 40, 16
    want = sm._range_stats_shifted_xla(
        jnp.asarray(secs), jnp.asarray(x), jnp.asarray(valid),
        jnp.asarray(np.int32(W)), max_behind=behind, max_ahead=ahead)
    got = rk.range_stats_streaming(
        jnp.asarray(secs), jnp.asarray(x), jnp.asarray(valid),
        jnp.asarray(np.int32(W)), behind, ahead)
    for k in KEYS:
        if k == "clipped":
            assert float(np.asarray(got[k]).sum()) == 0
            continue
        np.testing.assert_allclose(
            np.asarray(got[k], np.float64),
            np.asarray(want[k], np.float64),
            rtol=2e-4, atol=2e-4, equal_nan=True, err_msg=k)
