"""Streaming sliding-window engine: interpret-mode property tests.

Pins the ops/pallas_window.py kernels (streaming fori-loop form AND the
statically-unrolled twin) against

* the XLA shifted form (itself oracle-tested in test_rolling /
  test_pallas_stats), across window sizes spanning every auto-pick
  crossover: tiny (shifted regime), at the unroll ceiling, and far
  beyond it (streaming-only regime);
* a brute-force per-row numpy float64 oracle, including range windows
  whose bounds land BETWEEN timestamps, ragged series tails (i32-max
  clamped pads), NaN-masked rows, and tie runs;
* each other (the two forms must agree exactly — same math, different
  loop structure).

Also covers the three-way auto-pick (ops/rolling.pick_range_engine)
and the streaming dispatcher's CPU fallback.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from tempo_tpu.ops import pallas_window as pw
from tempo_tpu.ops import rolling as rk
from tempo_tpu.ops import sortmerge as sm

KEYS = ("mean", "count", "min", "max", "sum", "stddev", "zscore",
        "clipped")


def _case(seed, K=4, L=256, span=600, pads=True, invalids=True):
    rng = np.random.default_rng(seed)
    secs = np.sort(rng.integers(0, span, (K, L)), axis=-1).astype(np.int64)
    x = rng.standard_normal((K, L)).astype(np.float32)
    valid = (rng.random((K, L)) > 0.25) if invalids else np.ones((K, L), bool)
    if invalids and K > 1:
        valid[1] = False                      # a fully-null series
    if pads:
        cut = rng.integers(L // 2, L, K)
        for k in range(K):
            secs[k, cut[k]:] = 2**31 - 1
            valid[k, cut[k]:] = False
    return secs.astype(np.int32), x, valid


def _assert_close(got, want, err=""):
    for k in KEYS:
        np.testing.assert_allclose(
            np.asarray(got[k], dtype=np.float64),
            np.asarray(want[k], dtype=np.float64),
            rtol=2e-5, atol=2e-5, equal_nan=True, err_msg=f"{err}:{k}",
        )


# window sizes spanning the shifted (<= shifted_row_budget), unrolled
# (<= UNROLL_MAX_W) and streaming-only (beyond) regimes; `span` tunes
# the resulting row extents
@pytest.mark.parametrize("seed,span,W,behind,ahead", [
    (0, 600, 25, 24, 12),        # shifted regime, ties + pads
    (1, 40, 25, 64, 32),         # heavy ties, at the unroll ceiling
    (2, 600, 120, 100, 8),       # past the unroll ceiling
    (3, 200, 180, 250, 16),      # streaming-only: W ~ L
])
def test_stream_matches_xla_shifted(seed, span, W, behind, ahead):
    secs, x, valid = _case(seed, span=span)
    args = (jnp.asarray(secs), jnp.asarray(x), jnp.asarray(valid),
            jnp.asarray(np.int32(W)))
    want = sm._range_stats_shifted_xla(
        *args, max_behind=behind, max_ahead=ahead)
    got = pw.range_stats_stream(
        *args, max_behind=behind, max_ahead=ahead, interpret=True)
    _assert_close(got, want, f"stream W={W}")
    if behind + ahead <= pw.UNROLL_MAX_W:
        got_u = pw.range_stats_unrolled(
            *args, max_behind=behind, max_ahead=ahead, interpret=True)
        _assert_close(got_u, want, f"unrolled W={W}")
        # the two forms are the same math: exact agreement
        for k in KEYS:
            np.testing.assert_array_equal(
                np.asarray(got[k]), np.asarray(got_u[k]), err_msg=k)


def test_numpy_oracle_window_between_timestamps():
    """Keys stride 5, window 7: every frame boundary lands strictly
    between timestamps; brute-force f64 oracle per row."""
    K, L = 3, 128
    rng = np.random.default_rng(9)
    secs = (np.arange(L, dtype=np.int64) * 5)[None].repeat(K, 0)
    x = rng.standard_normal((K, L)).astype(np.float32)
    valid = rng.random((K, L)) > 0.2
    W = 7
    got = pw.range_stats_stream(
        jnp.asarray(secs.astype(np.int32)), jnp.asarray(x),
        jnp.asarray(valid), jnp.asarray(np.int32(W)),
        max_behind=4, max_ahead=2, interpret=True)
    x64 = x.astype(np.float64)
    for k in range(K):
        for i in range(L):
            lo, hi = secs[k, i] - W, secs[k, i]
            inw = (secs[k] >= lo) & (secs[k] <= hi) & valid[k]
            win = x64[k, inw]
            assert float(got["count"][k, i]) == len(win), (k, i)
            if len(win):
                np.testing.assert_allclose(
                    float(got["min"][k, i]), win.min(), rtol=1e-5)
                np.testing.assert_allclose(
                    float(got["max"][k, i]), win.max(), rtol=1e-5)
                np.testing.assert_allclose(
                    float(got["mean"][k, i]), win.mean(),
                    rtol=1e-4, atol=1e-5)
            else:
                assert np.isnan(float(got["mean"][k, i]))


def test_rows_mode_matches_bruteforce():
    K, L = 3, 128
    rng = np.random.default_rng(11)
    x = rng.standard_normal((K, L)).astype(np.float32)
    valid = rng.random((K, L)) > 0.25
    rb, ra = 6, 3
    got = pw.rows_stats_stream(jnp.asarray(x), jnp.asarray(valid),
                               rb, ra, interpret=True)
    x64 = x.astype(np.float64)
    for k in range(K):
        for i in range(L):
            s, e = max(0, i - rb), min(L, i + ra + 1)
            win = x64[k, s:e][valid[k, s:e]]
            assert float(got["count"][k, i]) == len(win), (k, i)
            if len(win) > 1:
                np.testing.assert_allclose(
                    float(got["stddev"][k, i]), win.std(ddof=1),
                    rtol=1e-4, atol=1e-5)
    assert float(np.asarray(got["clipped"]).sum()) == 0


def test_scale_folds_into_kernel():
    secs, x, valid = _case(5)
    args = (jnp.asarray(secs), jnp.asarray(x), jnp.asarray(valid),
            jnp.asarray(np.int32(30)))
    want = sm._range_stats_shifted_xla(
        args[0], jnp.asarray(x * np.float32(2.5)), args[2], args[3],
        max_behind=20, max_ahead=8)
    for fn in (pw.range_stats_stream, pw.range_stats_unrolled):
        got = fn(*args, max_behind=20, max_ahead=8, scale=2.5,
                 interpret=True)
        _assert_close(got, want, fn.__name__)


def test_clipped_audit_parity_when_truncating():
    secs, x, valid = _case(6)
    args = (jnp.asarray(secs), jnp.asarray(x), jnp.asarray(valid),
            jnp.asarray(np.int32(50)))
    want = sm._range_stats_shifted_xla(*args, max_behind=3, max_ahead=0)
    assert float(np.asarray(want["clipped"]).sum()) > 0
    for fn in (pw.range_stats_stream, pw.range_stats_unrolled):
        got = fn(*args, max_behind=3, max_ahead=0, interpret=True)
        np.testing.assert_allclose(
            np.asarray(got["clipped"]), np.asarray(want["clipped"]),
            err_msg=fn.__name__)


def test_pick_range_engine_three_way(monkeypatch):
    monkeypatch.delenv("TEMPO_TPU_WINDOW_ENGINE", raising=False)
    n = 1024 * 8192
    # small extent -> shifted; past the budget with a feasible stream
    # block -> stream; past the stream ceiling (or no stream) -> windowed
    assert rk.pick_range_engine(n, 10, 2, True, True) == "shifted"
    assert rk.pick_range_engine(n, 500, 8, True, True) == "stream"
    assert rk.pick_range_engine(n, 500, 8, True, False) == "windowed"
    big = pw._stream_max_rows() + 1
    assert rk.pick_range_engine(n, big, 0, True, True) == "windowed"
    monkeypatch.setenv("TEMPO_TPU_WINDOW_ENGINE", "stream")
    assert rk.pick_range_engine(n, 10, 2, True, True) == "stream"
    monkeypatch.setenv("TEMPO_TPU_WINDOW_ENGINE", "legacy")
    # legacy only redirects the shifted path's kernel choice
    assert rk.pick_range_engine(n, 10, 2, True, True) == "shifted"


# ----------------------------------------------------------------------
# Multi-column payload packing + explicit DMA ring: the bitwise-identity
# matrix (ISSUE 6).  Per-column results of the packed kernels and the
# ring-pipelined forms must equal the single-column/BlockSpec forms
# EXACTLY — same math, different data movement.
# ----------------------------------------------------------------------

def _packed_case(seed, C=3, K=4, L=256, span=600):
    rng = np.random.default_rng(seed)
    secs, _, _ = _case(seed, K=K, L=L, span=span)
    xs = rng.standard_normal((C, K, L)).astype(np.float32)
    valids = rng.random((C, K, L)) > 0.25
    valids[0, -1] = False                      # a fully-null column row
    xs[1, 0, ::7] = np.nan                     # NaN runs ride one column
    for c in range(C):                         # pads per column
        valids[c, :, L - 32:] = False
    return (jnp.asarray(secs), jnp.asarray(xs), jnp.asarray(valids))


@pytest.mark.parametrize("seed,span,W,behind,ahead", [
    (0, 600, 25, 24, 12),        # ties + ragged pads, unrolled regime
    (2, 40, 25, 40, 16),         # heavy tie runs
    (3, 600, 120, 100, 8),       # streaming-only width
])
def test_packed_matches_single_column_bitwise(seed, span, W, behind,
                                              ahead):
    secs, xs, valids = _packed_case(seed, span=span)
    w = jnp.asarray(np.int32(W))
    scales = np.asarray([1.0, 2.5, 0.5], np.float32)
    packed = pw.range_stats_stream_packed(
        secs, xs, valids, w, max_behind=behind, max_ahead=ahead,
        scales=scales, interpret=True)
    for c in range(xs.shape[0]):
        single = pw.range_stats_stream(
            secs, xs[c], valids[c], w, max_behind=behind,
            max_ahead=ahead, scale=float(scales[c]), interpret=True)
        for k in KEYS:
            np.testing.assert_array_equal(
                np.asarray(packed[k][c]), np.asarray(single[k]),
                err_msg=f"stream packed c={c}:{k}")
    if behind + ahead <= pw.UNROLL_MAX_W:
        packed_u = pw.range_stats_unrolled_packed(
            secs, xs, valids, w, max_behind=behind, max_ahead=ahead,
            scales=scales, interpret=True)
        for c in range(xs.shape[0]):
            single_u = pw.range_stats_unrolled(
                secs, xs[c], valids[c], w, max_behind=behind,
                max_ahead=ahead, scale=float(scales[c]), interpret=True)
            for k in KEYS:
                np.testing.assert_array_equal(
                    np.asarray(packed_u[k][c]), np.asarray(single_u[k]),
                    err_msg=f"unrolled packed c={c}:{k}")


def test_width1_packed_stack_matches_single_column():
    """[1, K, L] stacks — a single summarized column, or the leftover
    of a C % pack_cols_budget split (packed_column_dispatch emits both)
    — must run: the dispatch squeezes to the rank-2 single-column form
    and restacks (code-review r5: the rank-2 spec path crashed at trace
    time on width-1 stacks).  Results bitwise-equal, both kernel
    forms."""
    secs, xs, valids = _packed_case(17)
    w = jnp.asarray(np.int32(30))
    kw = dict(max_behind=25, max_ahead=8, interpret=True)
    single = pw.range_stats_stream(secs, xs[0], valids[0], w, scale=2.5,
                                   **kw)
    packed = pw.range_stats_stream_packed(secs, xs[:1], valids[:1], w,
                                          scales=2.5, **kw)
    single_u = pw.range_stats_unrolled(secs, xs[0], valids[0], w,
                                       scale=2.5, **kw)
    packed_u = pw.range_stats_unrolled_packed(secs, xs[:1], valids[:1],
                                              w, scales=2.5, **kw)
    for k in KEYS:
        assert packed[k].shape == (1,) + single[k].shape
        np.testing.assert_array_equal(
            np.asarray(packed[k][0]), np.asarray(single[k]),
            err_msg=f"stream:{k}")
        np.testing.assert_array_equal(
            np.asarray(packed_u[k][0]), np.asarray(single_u[k]),
            err_msg=f"unrolled:{k}")


@pytest.mark.parametrize("depth", [3, 4])
def test_dma_ring_matches_blockspec_bitwise(monkeypatch, depth):
    """TEMPO_TPU_DMA_BUFFERS > 2 streams the slabs through the explicit
    make_async_copy ring — outputs must be IDENTICAL to the implicit
    BlockSpec pipeline, single-column and packed, range and rows mode."""
    secs, xs, valids = _packed_case(depth)
    w = jnp.asarray(np.int32(40))
    kw = dict(max_behind=30, max_ahead=10, interpret=True)
    monkeypatch.delenv("TEMPO_TPU_DMA_BUFFERS", raising=False)
    base = pw.range_stats_stream(secs, xs[0], valids[0], w, **kw)
    base_p = pw.range_stats_stream_packed(secs, xs, valids, w, **kw)
    base_r = pw.rows_stats_stream(xs[0], valids[0], 6, 3, interpret=True)
    monkeypatch.setenv("TEMPO_TPU_DMA_BUFFERS", str(depth))
    ring = pw.range_stats_stream(secs, xs[0], valids[0], w, **kw)
    ring_p = pw.range_stats_stream_packed(secs, xs, valids, w, **kw)
    ring_r = pw.rows_stats_stream(xs[0], valids[0], 6, 3, interpret=True)
    for k in KEYS:
        np.testing.assert_array_equal(
            np.asarray(ring[k]), np.asarray(base[k]), err_msg=k)
        np.testing.assert_array_equal(
            np.asarray(ring_p[k]), np.asarray(base_p[k]), err_msg=k)
        np.testing.assert_array_equal(
            np.asarray(ring_r[k]), np.asarray(base_r[k]), err_msg=k)


def test_packed_dispatcher_groups_and_falls_back():
    """ops/rolling.range_stats_streaming_packed must agree with the
    packed/single kernels on any backend (on CPU it loops the
    single-column dispatcher — still bitwise per column)."""
    secs, xs, valids = _packed_case(11)
    w = jnp.asarray(np.int32(30))
    got = rk.range_stats_streaming_packed(secs, xs, valids, w, 25, 8)
    for c in range(xs.shape[0]):
        want = rk.range_stats_streaming(secs, xs[c], valids[c], w,
                                        25, 8)
        for k in KEYS:
            np.testing.assert_array_equal(
                np.asarray(got[k][c]), np.asarray(want[k]),
                err_msg=f"c={c}:{k}")


def test_packed_shifted_dispatcher_bitwise():
    secs, xs, valids = _packed_case(12)
    w = jnp.asarray(np.int32(30))
    got = sm.range_stats_shifted_packed(secs, xs, valids, w,
                                        max_behind=20, max_ahead=8)
    for c in range(xs.shape[0]):
        want = dict(sm.range_stats_shifted(secs, xs[c], valids[c], w,
                                           max_behind=20, max_ahead=8))
        for k in KEYS:
            np.testing.assert_array_equal(
                np.asarray(got[k][c]), np.asarray(want[k]),
                err_msg=f"c={c}:{k}")


def test_pack_cols_budget_respects_cap_and_vmem(monkeypatch):
    monkeypatch.delenv("TEMPO_TPU_PACK_COLS", raising=False)
    monkeypatch.delenv("TEMPO_TPU_DMA_BUFFERS", raising=False)
    assert pw.pack_cols_budget(1024, 8192, 16) == 8   # default cap
    assert pw.pack_cols_budget(1024, 8192, 3) == 3
    monkeypatch.setenv("TEMPO_TPU_PACK_COLS", "2")
    assert pw.pack_cols_budget(1024, 8192, 16) == 2
    monkeypatch.delenv("TEMPO_TPU_PACK_COLS", raising=False)
    # a lane extent no [8, L] block survives: budget degrades to 1
    assert pw.pack_cols_budget(8, 8 * 1024 * 1024, 8) == 1


def test_stream_clipped_audit_packed_parity():
    """Truncating bounds must produce the SAME per-column clipped
    counts through the packed kernel as per-column calls."""
    secs, xs, valids = _packed_case(13)
    w = jnp.asarray(np.int32(50))
    packed = pw.range_stats_stream_packed(
        secs, xs, valids, w, max_behind=3, max_ahead=0, interpret=True)
    total = 0.0
    for c in range(xs.shape[0]):
        single = pw.range_stats_stream(
            secs, xs[c], valids[c], w, max_behind=3, max_ahead=0,
            interpret=True)
        np.testing.assert_array_equal(
            np.asarray(packed["clipped"][c]),
            np.asarray(single["clipped"]), err_msg=f"c={c}")
        total += float(np.asarray(single["clipped"]).sum())
    assert total > 0  # the fixture really truncates


def test_streaming_dispatcher_cpu_fallback():
    """Off-TPU the dispatcher must produce the same numbers through the
    windowed form, including a zero clipped plane."""
    secs, x, valid = _case(7, pads=False)
    W, behind, ahead = 40, 40, 16
    want = sm._range_stats_shifted_xla(
        jnp.asarray(secs), jnp.asarray(x), jnp.asarray(valid),
        jnp.asarray(np.int32(W)), max_behind=behind, max_ahead=ahead)
    got = rk.range_stats_streaming(
        jnp.asarray(secs), jnp.asarray(x), jnp.asarray(valid),
        jnp.asarray(np.int32(W)), behind, ahead)
    for k in KEYS:
        if k == "clipped":
            assert float(np.asarray(got[k]).sum()) == 0
            continue
        np.testing.assert_allclose(
            np.asarray(got[k], np.float64),
            np.asarray(want[k], np.float64),
            rtol=2e-4, atol=2e-4, equal_nan=True, err_msg=k)
