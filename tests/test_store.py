"""The transactional storage engine (tempo_tpu/store/): generation
lifecycle, crash-consistent resume, refusal-by-name semantics,
compaction, retention, and the write→ingest clustering contract."""

import json
import os

import numpy as np
import pandas as pd
import pytest

from tempo_tpu import resilience
from tempo_tpu.frame import TSDF
from tempo_tpu.io import writer
from tempo_tpu.resilience import FailureKind
from tempo_tpu.store.compact import compact as run_compact
from tempo_tpu.store import engine as se
from tempo_tpu.testing import faults


def mk_df(n=600, seed=0, n_keys=4):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "symbol": rng.choice([f"s{k}" for k in range(n_keys)], n),
        "event_ts": pd.to_datetime(
            np.sort(rng.integers(0, 10 ** 6, n)) * 1_000_000_000),
        "px": rng.standard_normal(n),
    })


def sorted_twin(df, cols=("symbol",)):
    return df.sort_values(list(cols), kind="stable").reset_index(
        drop=True)


@pytest.fixture
def store(tmp_path):
    return se.Store(str(tmp_path / "wh"))


# ----------------------------------------------------------------------
# Generation lifecycle
# ----------------------------------------------------------------------

def test_write_read_roundtrip_bitwise(store):
    df = mk_df()
    stats = store.write_table("t", df, ["symbol"], source_fp="a",
                              segment_rows=100)
    assert stats["generation"] == "gen_00000001"
    assert stats["segments"] == 6
    pd.testing.assert_frame_equal(store.read("t", verify=True),
                                  sorted_twin(df))


def test_generation_dir_is_plain_parquet_dataset(store):
    import pyarrow.dataset as pads

    df = mk_df()
    store.write_table("t", df, ["symbol"], source_fp="a",
                      segment_rows=100)
    ds = pads.dataset(store.dataset_path("t"), format="parquet")
    got = ds.to_table().to_pandas()
    pd.testing.assert_frame_equal(got, sorted_twin(df))


def test_overwrite_is_new_generation_old_survives(store):
    df1, df2 = mk_df(seed=1), mk_df(seed=2)
    store.write_table("t", df1, ["symbol"], source_fp="a")
    p1 = store.dataset_path("t")
    store.write_table("t", df2, ["symbol"], source_fp="b")
    assert store.current("t")[0] == "gen_00000002"
    # a live reader holding generation 1's path stays bitwise-correct
    pd.testing.assert_frame_equal(se.read_dataset_df(p1),
                                  sorted_twin(df1))
    pd.testing.assert_frame_equal(store.read("t"), sorted_twin(df2))


def test_retention_prunes_beyond_keep(store):
    for i in range(4):
        store.write_table("t", mk_df(seed=i), ["symbol"],
                          source_fp=f"v{i}", keep_generations=2)
    gens = store.generations("t")
    assert gens == ["gen_00000003", "gen_00000004"]


def test_verbatim_reissue_is_idempotent(store):
    df = mk_df()
    store.write_table("t", df, ["symbol"], source_fp="a",
                      segment_rows=100)
    with faults.FaultInjector().flaky(se, "_write_segment",
                                      failures=0) as fi:
        stats = store.write_table("t", df, ["symbol"], source_fp="a",
                                  segment_rows=100)
    assert stats["resumed"] and stats["segments_reused"] == 6
    assert not fi.records          # zero segment writes
    assert store.current("t")[0] == "gen_00000001"


# ----------------------------------------------------------------------
# Kill / resume
# ----------------------------------------------------------------------

def test_killed_write_resumes_zero_committed_rewrites(store):
    df1, df2 = mk_df(seed=1), mk_df(seed=2)
    store.write_table("t", df1, ["symbol"], source_fp="a",
                      segment_rows=100)
    with pytest.raises(faults.SimulatedKill):
        with faults.FaultInjector().kill_on_call(
                se, "_write_segment", call_no=3):
            store.write_table("t", df2, ["symbol"], source_fp="b",
                              segment_rows=100)
    # readers still see the OLD generation, bitwise
    pd.testing.assert_frame_equal(store.read("t", verify=True),
                                  sorted_twin(df1))
    with faults.FaultInjector().flaky(se, "_write_segment",
                                      failures=0) as fi:
        stats = store.write_table("t", df2, ["symbol"], source_fp="b",
                                  segment_rows=100)
    assert stats["resumed"] and stats["segments_reused"] == 2
    assert stats["segments_rewritten"] == 0
    assert len(fi.records) == 4    # only the uncommitted tail
    pd.testing.assert_frame_equal(store.read("t", verify=True),
                                  sorted_twin(df2))


def test_kill_between_segment_and_sidecar(store):
    # sidecar-last: a segment whose sidecar never landed is
    # uncommitted residue, rewritten without complaint
    df = mk_df()
    with pytest.raises(faults.SimulatedKill):
        with faults.FaultInjector().kill_on_call(
                se, "_write_seg_manifest", call_no=2):
            store.write_table("t", df, ["symbol"], source_fp="a",
                              segment_rows=100)
    stats = store.write_table("t", df, ["symbol"], source_fp="a",
                              segment_rows=100)
    assert stats["resumed"] and stats["segments_reused"] == 1
    pd.testing.assert_frame_equal(store.read("t", verify=True),
                                  sorted_twin(df))


def test_kill_between_commit_and_pointer_swing(store):
    df1, df2 = mk_df(seed=1), mk_df(seed=2)
    store.write_table("t", df1, ["symbol"], source_fp="a",
                      segment_rows=100)
    with pytest.raises(faults.SimulatedKill):
        with faults.FaultInjector().kill_on_call(
                se, "_swing_pointer", call_no=1):
            store.write_table("t", df2, ["symbol"], source_fp="b",
                              segment_rows=100)
    pd.testing.assert_frame_equal(store.read("t"), sorted_twin(df1))
    with faults.FaultInjector().flaky(se, "_write_segment",
                                      failures=0) as fi:
        stats = store.write_table("t", df2, ["symbol"], source_fp="b",
                                  segment_rows=100)
    assert not fi.records          # everything durable: just swing
    assert stats["segments_reused"] == stats["segments"]
    pd.testing.assert_frame_equal(store.read("t", verify=True),
                                  sorted_twin(df2))


def test_unsigned_staging_residue_is_discarded(store):
    df = mk_df()
    store.write_table("t", df, ["symbol"], source_fp="a")
    residue = os.path.join(store.table_path("t"), "gen_00000002")
    os.makedirs(residue)
    open(os.path.join(residue, "seg_00000.parquet.tmp"), "wb").close()
    stats = store.write_table("t", mk_df(seed=9), ["symbol"],
                              source_fp="b")
    # the residue dir was rmtree'd and the slot reused for a FRESH write
    assert stats["generation"] == "gen_00000002"
    assert not stats["resumed"]
    assert not os.path.exists(
        os.path.join(residue, "seg_00000.parquet.tmp"))
    pd.testing.assert_frame_equal(store.read("t", verify=True),
                                  sorted_twin(mk_df(seed=9)))


def test_resume_pins_staged_segment_rows(store):
    # the resumed write must continue the STAGED chunking even when
    # today's knob says otherwise — chunk boundaries line up exactly
    df = mk_df()
    with pytest.raises(faults.SimulatedKill):
        with faults.FaultInjector().kill_on_call(
                se, "_write_segment", call_no=2):
            store.write_table("t", df, ["symbol"], source_fp="a",
                              segment_rows=100)
    stats = store.write_table("t", df, ["symbol"], source_fp="a",
                              segment_rows=250)
    assert stats["segments"] == 6  # 600/100, not 600/250
    pd.testing.assert_frame_equal(store.read("t", verify=True),
                                  sorted_twin(df))


# ----------------------------------------------------------------------
# Refusal by name + resilience classification
# ----------------------------------------------------------------------

def kill_staged(store, df, fp, call_no=2):
    with pytest.raises(faults.SimulatedKill):
        with faults.FaultInjector().kill_on_call(
                se, "_write_segment", call_no=call_no):
            store.write_table("t", df, ["symbol"], source_fp=fp,
                              segment_rows=100)


def test_foreign_staged_write_refused_by_name(store):
    store.write_table("t", mk_df(seed=1), ["symbol"], source_fp="a")
    kill_staged(store, mk_df(seed=2), "b")
    with pytest.raises(se.StoreError, match="DIFFERENT write"):
        store.write_table("t", mk_df(seed=3), ["symbol"],
                          source_fp="c", segment_rows=100)
    # the named escape hatch works, then the new write lands
    assert store.discard_staging("t")
    store.write_table("t", mk_df(seed=3), ["symbol"], source_fp="c")


def test_foreign_refusal_is_permanent_not_corruption(store):
    store.write_table("t", mk_df(seed=1), ["symbol"], source_fp="a")
    kill_staged(store, mk_df(seed=2), "b")
    with pytest.raises(se.StoreError) as ei:
        store.write_table("t", mk_df(seed=3), ["symbol"],
                          source_fp="c", segment_rows=100)
    assert resilience.classify(ei.value) is FailureKind.PERMANENT


def test_torn_commit_record_refused_never_transient(store):
    store.write_table("t", mk_df(), ["symbol"], source_fp="a")
    gen = store.current("t")[0]
    cpath = os.path.join(store.table_path("t"), gen, se.COMMIT_NAME)
    blob = open(cpath, "rb").read()
    open(cpath, "wb").write(blob[: len(blob) // 2])
    with pytest.raises(se.StoreCommitError, match="crc32"):
        store.read("t")
    with pytest.raises(se.StoreCommitError) as ei:
        store.read("t")
    assert resilience.classify(ei.value) is \
        FailureKind.CORRUPTED_ARTIFACT


def test_torn_pointer_refused_by_name(store):
    store.write_table("t", mk_df(), ["symbol"], source_fp="a")
    cur = os.path.join(store.table_path("t"), se.CURRENT_NAME)
    open(cur, "w").write("{not json")
    with pytest.raises(se.StoreCommitError, match="store pointer"):
        store.read("t")


def test_dangling_pointer_refused_by_name(store):
    store.write_table("t", mk_df(), ["symbol"], source_fp="a")
    cur = os.path.join(store.table_path("t"), se.CURRENT_NAME)
    open(cur, "w").write(json.dumps(
        {"generation": "gen_99999999", "commit_crc": 1}))
    with pytest.raises(se.StoreCommitError):
        store.read("t")


def test_corrupt_segment_fails_verify_by_name(store):
    store.write_table("t", mk_df(), ["symbol"], source_fp="a",
                      segment_rows=100)
    gen = store.current("t")[0]
    seg = os.path.join(store.table_path("t"), gen, se._seg_name(2))
    faults.flip_byte(seg, offset=os.path.getsize(seg) // 2)
    with pytest.raises(se.StoreCommitError, match="seg_00002"):
        store.verify("t")


def test_broken_sidecar_chain_refused(store):
    kill_staged(store, mk_df(), "a", call_no=3)
    gen_dir = os.path.join(store.table_path("t"), "gen_00000001")
    man_path = os.path.join(gen_dir, se._seg_manifest_name(1))
    man = json.load(open(man_path))
    man["prev_manifest_crc"] = 12345
    json.dump(man, open(man_path, "w"))
    with pytest.raises(se.StoreCommitError, match="chain broken"):
        store.write_table("t", mk_df(), ["symbol"], source_fp="a",
                          segment_rows=100)


def test_newer_format_version_refused(store):
    store.write_table("t", mk_df(), ["symbol"], source_fp="a")
    gen = store.current("t")[0]
    cpath = os.path.join(store.table_path("t"), gen, se.COMMIT_NAME)
    commit = json.load(open(cpath))
    commit["format_version"] = se.FORMAT_VERSION + 1
    json.dump(commit, open(cpath, "w"))
    # pointer CRC now mismatches too, but version refusal must win
    # when the CRC is patched to match
    cur_path = os.path.join(store.table_path("t"), se.CURRENT_NAME)
    cur = json.load(open(cur_path))
    from tempo_tpu import checkpoint as ckpt
    cur["commit_crc"] = ckpt.file_crc(cpath)
    json.dump(cur, open(cur_path, "w"))
    with pytest.raises(se.StoreError, match="format_version"):
        store.read("t")


# ----------------------------------------------------------------------
# Compaction
# ----------------------------------------------------------------------

def test_compaction_merges_and_stays_bitwise(store, tmp_path):
    df = mk_df()
    store.write_table("t", df, ["symbol"], source_fp="a",
                      segment_rows=100)
    stats = run_compact("t", base_dir=str(tmp_path / "wh"))
    assert stats["segments"] == 1
    assert stats["compacted_from"] == "gen_00000001"
    pd.testing.assert_frame_equal(store.read("t", verify=True),
                                  sorted_twin(df))


def test_compaction_noop_below_min_segments(store, tmp_path):
    store.write_table("t", mk_df(), ["symbol"], source_fp="a")
    assert run_compact("t", base_dir=str(tmp_path / "wh"),
                                 min_segments=2) is None


def test_compaction_kill_leaves_generation_n(store, tmp_path):
    df = mk_df()
    store.write_table("t", df, ["symbol"], source_fp="a",
                      segment_rows=100)
    with pytest.raises(faults.SimulatedKill):
        with faults.FaultInjector().kill_on_call(
                se, "_write_segment", call_no=1):
            run_compact("t", base_dir=str(tmp_path / "wh"))
    assert store.current("t")[0] == "gen_00000001"   # exactly N
    pd.testing.assert_frame_equal(store.read("t", verify=True),
                                  sorted_twin(df))
    stats = run_compact("t", base_dir=str(tmp_path / "wh"))
    assert stats["generation"] == "gen_00000002"     # exactly N+1
    pd.testing.assert_frame_equal(store.read("t", verify=True),
                                  sorted_twin(df))


def test_compaction_refuses_corrupt_source(store, tmp_path):
    store.write_table("t", mk_df(), ["symbol"], source_fp="a",
                      segment_rows=100)
    gen = store.current("t")[0]
    seg = os.path.join(store.table_path("t"), gen, se._seg_name(0))
    faults.flip_byte(seg, offset=os.path.getsize(seg) // 2)
    # never launder corruption into a clean-looking generation
    with pytest.raises(se.StoreCommitError):
        run_compact("t", base_dir=str(tmp_path / "wh"))


# ----------------------------------------------------------------------
# The write -> ingest clustering contract (layout pinned)
# ----------------------------------------------------------------------

def test_clustered_layout_row_group_stats_are_selective(tmp_path):
    """The (series, time) clustering contract: segment key ranges are
    sorted and non-overlapping, sidecar key_min/key_max match the
    parquet column statistics, and any single key maps to a strict
    subset of segments — the selectivity the census pass reads back.
    Layout drift (an unsorted write, a dropped sidecar stat) fails
    here loudly."""
    import pyarrow.parquet as pq

    store = se.Store(str(tmp_path / "wh"))
    df = mk_df(n=800, n_keys=8)
    store.write_table("t", df, ["symbol"], source_fp="a",
                      segment_rows=100)
    gen_dir = store.dataset_path("t")
    _, commit = store.current("t")
    segs = commit["segments"]
    assert len(segs) == 8
    # sidecar ranges are sorted and consistent with parquet stats
    for i, s in enumerate(segs):
        meta = pq.ParquetFile(
            os.path.join(gen_dir, s["file"])).metadata
        col_idx = [meta.schema.column(j).name
                   for j in range(meta.num_columns)].index("symbol")
        stats = meta.row_group(0).column(col_idx).statistics
        assert stats.min == s["key_min"] and stats.max == s["key_max"]
        if i:
            assert segs[i - 1]["key_max"] <= s["key_min"]
    # selectivity: one key's range covers a strict subset of segments
    key = sorted(df.symbol.unique())[0]
    touching = [s for s in segs
                if s["key_min"] <= key <= s["key_max"]]
    assert 0 < len(touching) < len(segs)


def test_written_table_ingests_via_from_parquet(tmp_path):
    from tempo_tpu.io.ingest import from_parquet

    df = mk_df(n=400)
    tsdf = TSDF(df, ts_col="event_ts", partition_cols=["symbol"])
    path = writer.write(tsdf, "t", base_dir=str(tmp_path))
    out = from_parquet(path, ts_col="event_ts",
                       partition_cols=["symbol"]).to_pandas()
    exp = sorted_twin(df, ("symbol", "event_ts"))
    got = out[exp.columns.tolist()].sort_values(
        ["symbol", "event_ts"], kind="stable").reset_index(drop=True)
    pd.testing.assert_frame_equal(got, exp)


def test_ingest_refuses_torn_store_state_before_streaming(tmp_path):
    from tempo_tpu.io.ingest import from_parquet

    tsdf = TSDF(mk_df(), ts_col="event_ts", partition_cols=["symbol"])
    path = writer.write(tsdf, "t", base_dir=str(tmp_path))
    open(os.path.join(path, se.CURRENT_NAME), "w").write("{torn")
    with pytest.raises(se.StoreCommitError, match="store pointer"):
        from_parquet(path, ts_col="event_ts",
                     partition_cols=["symbol"])


# ----------------------------------------------------------------------
# write_back: frames, distributed frames, query results
# ----------------------------------------------------------------------

def test_write_back_tsdf_and_dataframe(tmp_path):
    from tempo_tpu.store import write_back

    df = mk_df()
    tsdf = TSDF(df, ts_col="event_ts", partition_cols=["symbol"])
    stats = write_back(tsdf, "frames", base_dir=str(tmp_path / "wh"))
    assert stats["rows"] == len(df)
    stats2 = write_back(df, "results", base_dir=str(tmp_path / "wh"),
                        ts_col="event_ts",
                        partition_cols=["symbol"])
    assert stats2["rows"] == len(df)
    store = se.Store(str(tmp_path / "wh"))
    a = store.read("frames").drop(
        columns=["event_dt", "event_time"])
    b = store.read("results").drop(
        columns=["event_dt", "event_time"])
    pd.testing.assert_frame_equal(a, b)


def test_write_back_is_content_addressed_idempotent(tmp_path):
    from tempo_tpu.store import write_back

    df = mk_df()
    tsdf = TSDF(df, ts_col="event_ts", partition_cols=["symbol"])
    write_back(tsdf, "t", base_dir=str(tmp_path / "wh"))
    # the SAME content re-written is a no-op (source fingerprint is
    # content-derived, not identity-derived)
    tsdf2 = TSDF(df.copy(), ts_col="event_ts",
                 partition_cols=["symbol"])
    stats = write_back(tsdf2, "t", base_dir=str(tmp_path / "wh"))
    assert stats["resumed"] and stats["segments_rewritten"] == 0
