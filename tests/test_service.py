"""The multi-tenant query service (tempo_tpu/service/, round 11):
shared single-flight executable cache, admission control, fair
scheduling, and failure isolation.
"""

import queue as queue_mod
import threading
import time

import numpy as np
import pandas as pd
import pytest

from tempo_tpu import TSDF, profiling
from tempo_tpu.plan import cache as plan_cache
from tempo_tpu.plan import executor as plan_executor
from tempo_tpu.service import (AdmissionError, QueryService, lazy_frame,
                               project_footprint)
from tempo_tpu.testing.faults import FaultInjector, InjectedFault


@pytest.fixture(autouse=True)
def _clean_cache():
    plan_cache.CACHE.clear()
    yield
    plan_cache.CACHE.clear()


def _frame(cols, K=4, L=64, seed=0):
    rng = np.random.default_rng(seed)
    secs = np.cumsum(rng.integers(1, 3, size=(K, L)), axis=-1)
    data = {"sym": np.repeat(np.arange(K), L),
            "event_ts": secs.ravel().astype(np.int64)}
    for c in cols:
        data[c] = rng.standard_normal(K * L)
    return TSDF(pd.DataFrame(data), "event_ts", ["sym"])


def _query(left, right):
    return (lazy_frame(left).asofJoin(right)
            .withRangeStats(colsToSummarize=["x"],
                            rangeBackWindowSecs=10))


# ----------------------------------------------------------------------
# PlanCache: single-flight + per-signature / per-tenant counters
# ----------------------------------------------------------------------

def test_single_flight_builds_once_under_contention():
    cache = plan_cache.PlanCache()
    built = []
    gate = threading.Event()

    def build():
        gate.wait(5)
        time.sleep(0.02)                 # widen the race window
        built.append(object())
        return built[-1]

    results = []

    def worker():
        results.append(cache.get_or_build(("sig", "k"), build))

    threads = [threading.Thread(target=worker) for _ in range(8)]
    for t in threads:
        t.start()
    gate.set()
    for t in threads:
        t.join()
    assert len(built) == 1
    assert all(r is built[0] for r in results)
    st = cache.stats()
    assert st["builds"] == 1 and st["misses"] == 1
    assert st["hits"] == 7


def test_single_flight_failed_build_releases_the_claim():
    cache = plan_cache.PlanCache()
    calls = []

    def flaky_build():
        calls.append(1)
        if len(calls) == 1:
            raise RuntimeError("poisoned build")
        return "exe"

    with pytest.raises(RuntimeError, match="poisoned build"):
        cache.get_or_build(("sig",), flaky_build)
    # the claim is released: the next caller retries as the builder
    assert cache.get_or_build(("sig",), flaky_build) == "exe"
    assert len(calls) == 2


def test_insert_failure_releases_single_flight_claim(monkeypatch):
    """insert() raising (malformed cache-size env var) must release
    the build claim — otherwise every waiter on that key hangs."""
    cache = plan_cache.PlanCache()
    monkeypatch.setenv("TEMPO_TPU_PLAN_CACHE_SIZE", "not-a-number")
    with pytest.raises(ValueError):
        cache.get_or_build(("sig",), lambda: "exe")
    monkeypatch.setenv("TEMPO_TPU_PLAN_CACHE_SIZE", "8")
    assert cache.get_or_build(("sig",), lambda: "exe2") == "exe2"


def test_per_signature_and_per_tenant_counters():
    cache = plan_cache.PlanCache()
    with plan_cache.tenant_scope("alice"):
        cache.get_or_build(("sigA",), lambda: "a")
        cache.get_or_build(("sigA",), lambda: "a")
    with plan_cache.tenant_scope("bob"):
        cache.get_or_build(("sigA",), lambda: "a")
        cache.get_or_build(("sigB",), lambda: "b")
    st = cache.stats()
    assert st["by_signature"]["sigA"]["builds"] == 1
    assert st["by_signature"]["sigA"]["hits"] == 2
    assert st["by_signature"]["sigB"]["builds"] == 1
    assert st["by_tenant"]["alice"] == {"hits": 1, "misses": 1,
                                        "builds": 1}
    assert st["by_tenant"]["bob"] == {"hits": 1, "misses": 1,
                                      "builds": 1}


def test_plan_cache_stats_exposes_breakdowns():
    st = profiling.plan_cache_stats()
    assert "by_signature" in st and "by_tenant" in st


# ----------------------------------------------------------------------
# QueryService basics
# ----------------------------------------------------------------------

def test_concurrent_tenants_share_one_build():
    left, right = _frame(["x"], seed=1), _frame(["v"], seed=2)
    with QueryService(workers=4) as svc:
        tickets = [svc.submit(f"t{i % 4}", _query(left, right))
                   for i in range(12)]
        results = [t.result(timeout=120) for t in tickets]
        st = svc.stats()
    pc = st["plan_cache"]
    assert pc["builds"] == 1, pc
    assert pc["hits"] == 11
    assert st["starvation_ratio"] == 1.0
    ref = results[0].df
    for r in results[1:]:
        pd.testing.assert_frame_equal(ref, r.df, check_exact=True)


def test_submit_after_close_raises():
    left, right = _frame(["x"], seed=1), _frame(["v"], seed=2)
    svc = QueryService(workers=1)
    svc.close()
    with pytest.raises(RuntimeError, match="closed"):
        svc.submit("t0", _query(left, right))


def test_submit_rejects_non_lazy_queries():
    svc = QueryService(workers=1)
    try:
        with pytest.raises(TypeError, match="lazy chain"):
            svc.submit("t0", _frame(["x"]))
    finally:
        svc.close()


# ----------------------------------------------------------------------
# Admission control
# ----------------------------------------------------------------------

def test_footprint_projection_scales_with_shape():
    left, right = _frame(["x"], seed=1), _frame(["v"], seed=2)
    small = project_footprint(_query(left, right).plan)
    big_l = _frame(["x"], L=512, seed=1)
    big_r = _frame(["v"], L=512, seed=2)
    big = project_footprint(_query(big_l, big_r).plan)
    assert small.hbm_bytes > 0 and small.vmem_bytes > 0
    assert big.hbm_bytes > small.hbm_bytes
    assert big.vmem_bytes >= small.vmem_bytes


def test_host_frame_footprint_counts_real_columns():
    """A bare host frame's HBM projection must scale with its actual
    value-column count, not the 2-plane fallback — a wide frame
    projected at 2 planes lets admission over-admit."""
    from tempo_tpu import packing

    wide = _frame([f"c{i}" for i in range(12)], seed=1)
    narrow = _frame(["x"], seed=1)
    fp_wide = project_footprint(lazy_frame(wide).plan)
    fp_narrow = project_footprint(lazy_frame(narrow).plan)
    assert fp_wide.hbm_bytes > fp_narrow.hbm_bytes
    L = packing.pad_length(64)
    # ts i64 + (value f32 + validity bool) per value column
    assert fp_narrow.hbm_bytes == 4 * L * (8 + 5 * 1)
    assert fp_wide.hbm_bytes == 4 * L * (8 + 5 * 12)
    # intermediates derive from the same model: an op node over the
    # wide host source projects its real plane count, not the 2-plane
    # fallback (the source leaf makes the whole chain derivable)
    from tempo_tpu.plan import optimizer

    stats_node = (lazy_frame(wide)
                  .withRangeStats(colsToSummarize=["c0"],
                                  rangeBackWindowSecs=10).plan)
    assert optimizer._device_plane_count(stats_node) is not None
    assert optimizer._device_plane_count(stats_node) > 12


def test_over_vmem_query_is_rejected_named_not_queued():
    left, right = _frame(["x"], seed=1), _frame(["v"], seed=2)
    with QueryService(workers=1, vmem_budget=64) as svc:
        t0 = time.perf_counter()
        with pytest.raises(AdmissionError, match="VMEM"):
            svc.submit("t0", _query(left, right))
        assert time.perf_counter() - t0 < 5      # immediate, not queued
        st = svc.stats()
    assert st["tenants"]["t0"]["rejected"] == 1
    assert st["tenants"]["t0"]["completed"] == 0


def test_over_total_hbm_query_is_rejected():
    left, right = _frame(["x"], seed=1), _frame(["v"], seed=2)
    with QueryService(workers=1, hbm_budget=128) as svc:
        with pytest.raises(AdmissionError, match="TOTAL"):
            svc.submit("t0", _query(left, right))


def test_queued_query_runs_after_budget_frees():
    left, right = _frame(["x"], seed=1), _frame(["v"], seed=2)
    fp = project_footprint(_query(left, right).plan)
    # budget admits exactly ONE query at a time; three must still all
    # complete, serialized by admission (release -> re-check)
    with QueryService(workers=2,
                      hbm_budget=int(fp.hbm_bytes * 1.5)) as svc:
        tickets = [svc.submit("t0", _query(left, right))
                   for _ in range(3)]
        results = [t.result(timeout=120) for t in tickets]
        st = svc.stats()
    assert st["tenants"]["t0"]["completed"] == 3
    assert st["hbm_in_use"] == 0
    ref = results[0].df
    for r in results[1:]:
        pd.testing.assert_frame_equal(ref, r.df, check_exact=True)


# ----------------------------------------------------------------------
# Fairness + backpressure
# ----------------------------------------------------------------------

def _blocked_executor(monkeypatch):
    """Patch plan execution to wait on a gate — lets tests stack the
    queues deterministically before any dispatch completes."""
    gate = threading.Event()
    original = plan_executor.execute

    def gated(root):
        gate.wait(30)
        return original(root)

    monkeypatch.setattr(plan_executor, "execute", gated)
    return gate


def test_tenant_quota_backpressure(monkeypatch):
    left, right = _frame(["x"], seed=1), _frame(["v"], seed=2)
    gate = _blocked_executor(monkeypatch)
    svc = QueryService(workers=1, tenant_quota=2)
    try:
        t1 = svc.submit("t0", _query(left, right))
        # wait until the worker has POPPED t1 and sits blocked inside
        # execution — from here the queue can only grow
        deadline = time.perf_counter() + 10
        while t1.t_start is None:
            assert time.perf_counter() < deadline, "worker never started"
            time.sleep(0.005)
        tickets = [t1,
                   svc.submit("t0", _query(left, right)),
                   svc.submit("t0", _query(left, right))]  # at quota
        with pytest.raises(queue_mod.Full, match="quota"):
            svc.submit("t0", _query(left, right), timeout=0.05)
        gate.set()
        for t in tickets:
            t.result(timeout=120)
    finally:
        gate.set()
        svc.close()


def test_quota_blocked_submitter_survives_queue_drain(monkeypatch):
    """A submitter blocked at quota must append into the LIVE deque
    after waking: if the scheduler pruned the tenant's drained deque
    while the submitter slept, the woken append would land in an
    orphaned deque the picker never scans — a silently lost query whose
    ticket blocks forever."""
    left, right = _frame(["x"], seed=1), _frame(["v"], seed=2)
    gate = _blocked_executor(monkeypatch)
    svc = QueryService(workers=1, tenant_quota=1)
    try:
        t1 = svc.submit("t0", _query(left, right))
        deadline = time.perf_counter() + 10
        while t1.t_start is None:        # t1 popped; queue is empty
            assert time.perf_counter() < deadline
            time.sleep(0.005)
        t2 = svc.submit("t0", _query(left, right))   # queue at quota
        slot = []

        def blocked_submit():
            slot.append(svc.submit("t0", _query(left, right)))

        th = threading.Thread(target=blocked_submit)
        th.start()
        time.sleep(0.2)                  # t3's submitter is in wait()
        assert not slot                  # …still blocked at quota
        gate.set()                       # t1 completes; t2 dispatches,
        th.join(30)                      # draining the deque; t3 wakes
        assert not th.is_alive()
        assert slot, "blocked submitter never returned"
        for t in (t1, t2, slot[0]):
            t.result(timeout=60)
        st = svc.stats()
    finally:
        gate.set()
        svc.close()
    assert st["tenants"]["t0"]["completed"] == 3


def test_reservation_clock_starts_at_head_not_at_submit(monkeypatch):
    """A query that aged behind its OWN tenant's earlier queries must
    not freeze service-wide dispatch the instant it reaches the head:
    the reservation clock starts when it first fails ``fits_now()`` as
    head, not at submit."""
    small_l, small_r = _frame(["x"], L=64, seed=1), _frame(["v"], L=64,
                                                           seed=2)
    big_l, big_r = _frame(["x"], L=256, seed=3), _frame(["v"], L=256,
                                                        seed=4)
    fp_small = project_footprint(_query(small_l, small_r).plan)
    fp_big = project_footprint(_query(big_l, big_r).plan)
    # geometry: big alone fits; big + one small does not; two smalls do
    budget = fp_big.hbm_bytes + fp_small.hbm_bytes // 2
    assert 2 * fp_small.hbm_bytes <= budget
    sem = threading.Semaphore(0)
    original = plan_executor.execute

    def gated(root):
        assert sem.acquire(timeout=60)
        return original(root)

    monkeypatch.setattr(plan_executor, "execute", gated)
    svc = QueryService(workers=2, hbm_budget=budget, reserve_after_s=2.0)
    try:
        s1 = svc.submit("busy", _query(small_l, small_r))
        s2 = svc.submit("busy", _query(small_l, small_r))
        deadline = time.perf_counter() + 10
        while s1.t_start is None or s2.t_start is None:
            assert time.perf_counter() < deadline
            time.sleep(0.005)
        # big queues behind nothing dispatchable and AGES past
        # reserve_after_s before any picker ever sees it as a
        # failing head
        big = svc.submit("busy", _query(big_l, big_r))
        time.sleep(2.5)
        sem.release()                    # one small drains its budget
        deadline = time.perf_counter() + 10
        while not (s1.done() or s2.done()):
            assert time.perf_counter() < deadline
            time.sleep(0.005)
        # big's head-check now fails fits_now with t_submit 2.5 s old:
        # a submit-based clock would reserve instantly and freeze this
        # fitting query; the head-based clock dispatches it promptly
        other = svc.submit("other", _query(small_l, small_r))
        deadline = time.perf_counter() + 1.5    # well under 2.0 s
        while other.t_start is None:
            assert time.perf_counter() < deadline, \
                "fitting query frozen by a never-head-starved reservation"
            time.sleep(0.005)
        sem.release(8)                   # drain everything
        for t in (s1, s2, big, other):
            t.result(timeout=120)
    finally:
        sem.release(16)
        svc.close()


def test_close_timeout_is_a_shared_deadline(monkeypatch):
    """close(timeout) bounds the WHOLE drain: with W gated workers the
    call must return in ~timeout, not W x timeout."""
    left, right = _frame(["x"], seed=1), _frame(["v"], seed=2)
    gate = _blocked_executor(monkeypatch)
    svc = QueryService(workers=4)
    tickets = [svc.submit("t0", _query(left, right)) for _ in range(4)]
    t0 = time.perf_counter()
    svc.close(timeout=1.0)
    elapsed = time.perf_counter() - t0
    assert elapsed < 2.5, elapsed        # per-worker joins would be ~4 s
    gate.set()
    for t in tickets:                    # daemon workers still drain
        t.result(timeout=120)


def test_explicit_zero_budget_admits_nothing():
    left, right = _frame(["x"], seed=1), _frame(["v"], seed=2)
    with QueryService(workers=1, hbm_budget=0) as svc:
        with pytest.raises(AdmissionError):
            svc.submit("t0", _query(left, right))


def test_new_tenant_joins_at_token_floor(monkeypatch):
    """A tenant first seen after hours of service must NOT get
    absolute priority until token parity: newcomers join at the floor
    of the live token counts, so dispatch interleaves instead of
    draining the newcomer's whole backlog first."""
    left, right = _frame(["x"], seed=1), _frame(["v"], seed=2)
    gate = threading.Event()
    gate.set()
    original = plan_executor.execute

    def gated(root):
        gate.wait(30)
        return original(root)

    monkeypatch.setattr(plan_executor, "execute", gated)
    svc = QueryService(workers=1)
    try:
        for _ in range(4):                    # veteran earns 4 tokens
            svc.submit("vet", _query(left, right)).result(timeout=120)
        gate.clear()                          # block the worker…
        hold = svc.submit("vet", _query(left, right))
        deadline = time.perf_counter() + 10
        while hold.t_start is None:           # …mid-dispatch
            assert time.perf_counter() < deadline
            time.sleep(0.005)
        new = [svc.submit("newbie", _query(left, right))
               for _ in range(3)]
        vet = [svc.submit("vet", _query(left, right))
               for _ in range(3)]
        gate.set()
        for t in new + vet + [hold]:
            t.result(timeout=120)
        # floor join: newbie starts at vet's token count, so vet's
        # queued work interleaves — its first follow-up starts before
        # newbie's backlog fully drains (tokens from 0 would run all
        # three newbie queries first)
        assert min(t.t_start for t in vet) < max(t.t_start for t in new)
    finally:
        gate.set()
        svc.close()


def test_starved_large_query_reserves_budget(monkeypatch):
    """A large admitted query must not be starved by smaller queries
    re-consuming every freed HBM byte: past ``reserve_after_s`` the
    scheduler reserves — nothing smaller dispatches until the starved
    head fits."""
    small_l, small_r = _frame(["x"], L=64, seed=1), _frame(["v"], L=64,
                                                           seed=2)
    big_l, big_r = _frame(["x"], L=256, seed=3), _frame(["v"], L=256,
                                                        seed=4)
    fp_small = project_footprint(_query(small_l, small_r).plan)
    fp_big = project_footprint(_query(big_l, big_r).plan)
    assert fp_big.hbm_bytes > fp_small.hbm_bytes
    gate = _blocked_executor(monkeypatch)
    # budget: big alone fits; big + small does not; small + small does
    budget = fp_big.hbm_bytes + fp_small.hbm_bytes // 2
    svc = QueryService(workers=2, hbm_budget=budget, reserve_after_s=0.0)
    try:
        s1 = svc.submit("flood", _query(small_l, small_r))
        deadline = time.perf_counter() + 10
        while s1.t_start is None:         # worker holds fp_small
            assert time.perf_counter() < deadline
            time.sleep(0.005)
        big = svc.submit("big", _query(big_l, big_r))     # cannot fit
        s2 = svc.submit("flood", _query(small_l, small_r))  # would fit
        time.sleep(0.3)
        # reservation active: s2 fits the free share but must NOT run
        # ahead of the starved big query
        assert s2.t_start is None and big.t_start is None
        gate.set()
        big.result(timeout=120)
        s2.result(timeout=120)
        assert big.t_start < s2.t_start
    finally:
        gate.set()
        svc.close()


def test_fair_scheduler_interleaves_tenants(monkeypatch):
    """A flooding tenant must not starve a light one: with the worker
    gated, 'heavy' enqueues 5 queries before 'light' enqueues 1 — the
    token accounting dispatches light's query second, not sixth."""
    left, right = _frame(["x"], seed=1), _frame(["v"], seed=2)
    gate = _blocked_executor(monkeypatch)
    svc = QueryService(workers=1, tenant_quota=16)
    try:
        heavy = [svc.submit("heavy", _query(left, right))
                 for _ in range(5)]
        light = svc.submit("light", _query(left, right))
        gate.set()
        for t in heavy + [light]:
            t.result(timeout=120)
        starts = sorted(t.t_start for t in heavy)
        # light started before heavy's 3rd dispatch (fair interleave,
        # not FIFO behind the flood)
        assert light.t_start < starts[2], (light.t_start, starts)
        st = svc.stats()
    finally:
        gate.set()
        svc.close()
    assert st["tenants"]["light"]["completed"] == 1
    assert st["tenants"]["heavy"]["completed"] == 5


# ----------------------------------------------------------------------
# Failure isolation (chaos)
# ----------------------------------------------------------------------

@pytest.mark.chaos
def test_poisoned_query_fails_its_ticket_not_the_scheduler():
    left, right = _frame(["x"], seed=1), _frame(["v"], seed=2)
    with QueryService(workers=2) as svc:
        with FaultInjector() as fi:
            fi.flaky(plan_executor, "execute", failures=1)
            poisoned = svc.submit("evil", _query(left, right))
            with pytest.raises(InjectedFault):
                poisoned.result(timeout=120)
            # the scheduler survives: later queries (any tenant) run
            ok = svc.submit("good", _query(left, right))
            assert isinstance(ok.result(timeout=120), object)
        st = svc.stats()
    assert st["tenants"]["evil"]["failed"] == 1
    assert st["tenants"]["good"]["completed"] == 1
    assert st["hbm_in_use"] == 0         # the poisoned query released


@pytest.mark.chaos
def test_poisoned_build_does_not_wedge_single_flight_waiters():
    """Two tenants race the same signature; the first build dies.  The
    waiter must retry as the builder and succeed — nobody hangs."""
    left, right = _frame(["x"], seed=1), _frame(["v"], seed=2)
    with FaultInjector() as fi:
        fi.flaky(plan_executor.Executable, "run", failures=1)
        with QueryService(workers=2) as svc:
            tickets = [svc.submit(f"t{i}", _query(left, right))
                       for i in range(4)]
            outcomes = []
            for t in tickets:
                try:
                    t.result(timeout=120)
                    outcomes.append("ok")
                except InjectedFault:
                    outcomes.append("fault")
    assert outcomes.count("fault") == 1
    assert outcomes.count("ok") == 3
